"""Dense-equivalent control for the MoE bench: a Llama with the SAME
active FLOPs per token as the moe preset (top-2 of 8 experts at F=2048 ≡
dense F=4096), same d/L/heads/vocab/seq, benched with the same recipe.

The gap between this number and the moe preset's active-param MFU is the
structural cost of MoE on this chip (dispatch movements + grouped-GEMM
rate); BASELINE.md tracks its decomposition round over round.

Run: python examples/mixtral/dense_equiv.py [--batch 44]
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=44)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--steps", type=int, default=8)
    args = p.parse_args()

    import jax

    from tony_tpu.models import llama
    from tony_tpu.parallel import MeshSpec
    from tony_tpu.train import (
        OptimizerConfig, Throughput, make_train_step, sharded_init,
    )
    from tony_tpu.train.metrics import detect_peak_flops, flops_per_token_for_batch

    cfg = llama.LlamaConfig(
        vocab_size=32_000, d_model=1024, n_layers=8, n_heads=8, n_kv_heads=4,
        d_ff=4096, max_seq=args.seq, remat=True, remat_policy="flash",
        ce_chunk=512,
    )
    mesh = MeshSpec.auto(len(jax.devices())).build()
    opt = OptimizerConfig(warmup_steps=10, total_steps=1000).build()
    state = sharded_init(
        lambda: llama.init(jax.random.PRNGKey(0), cfg), llama.sharding_rules(cfg),
        mesh, opt,
    )
    step_fn = make_train_step(functools.partial(llama.loss_fn, cfg=cfg, mesh=mesh), opt)
    batch = llama.synthetic_batch(jax.random.PRNGKey(1), args.batch, args.seq, cfg)

    for _ in range(2):
        state, m = step_fn(state, batch)
        float(m["loss"])

    meter = Throughput(
        tokens_per_step=args.batch * args.seq,
        flops_per_token=flops_per_token_for_batch(cfg, batch, args.seq),
        n_chips=1,
        peak_flops=detect_peak_flops(),
    )
    meter.start()
    for _ in range(args.steps):
        state, m = step_fn(state, batch)
        float(m["loss"])
        meter.step()
    r = meter.report()
    print(json.dumps({"dense_equiv_mfu": r["mfu"], **{k: round(v, 2) for k, v in r.items()}}))


if __name__ == "__main__":
    main()
