"""Probe 2: does per-row DMA gather hide under the fused MoE tile GEMMs?

Arms (loop-in-jit as gather_probe.py):
  gemm        — xs already in expert order, pipelined BlockSpec input, the
                3-GEMM SwiGLU tile body (ops/moe_gemm._fwd_kernel shape)
  gather_gemm — same body, but rows arrive via in-kernel per-row DMA from
                x in HBM (double-buffered across tiles)
  xla_total   — xs = x[idx] (XLA gather) THEN the gemm kernel — i.e. the
                current production forward

If gather_gemm ≈ gemm, the descriptor issue overlaps MXU work and the
in-kernel gather removes the XLA gather for free. If gather_gemm ≈
gemm + standalone-gather, the scalar issue serializes and the lever is dead.

Run: python examples/mixtral/gather_gemm_probe.py
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

TILE = 256
ITERS = 16


def _swiglu_body(x, wg, wu, wd, o_dtype):
    g = jnp.dot(x, wg, preferred_element_type=jnp.float32)
    u = jnp.dot(x, wu, preferred_element_type=jnp.float32)
    h = (g * jax.nn.sigmoid(g) * u).astype(x.dtype)
    return jnp.dot(h, wd, preferred_element_type=jnp.float32).astype(o_dtype)


def gemm_plain(xs, wg, wu, wd, tile=TILE):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PN, D = xs.shape
    F = wg.shape[1]

    def kern(xs_ref, wg_ref, wu_ref, wd_ref, o_ref):
        o_ref[...] = _swiglu_body(
            xs_ref[...], wg_ref[...], wu_ref[...], wd_ref[...], o_ref.dtype
        )

    return pl.pallas_call(
        kern,
        grid=(PN // tile,),
        in_specs=[
            pl.BlockSpec((tile, D), lambda m: (m, 0)),
            pl.BlockSpec((D, F), lambda m: (0, 0)),
            pl.BlockSpec((D, F), lambda m: (0, 0)),
            pl.BlockSpec((F, D), lambda m: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile, D), lambda m: (m, 0)),
        out_shape=jax.ShapeDtypeStruct((PN, D), xs.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",), vmem_limit_bytes=100 * 1024 * 1024
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * PN * D * F * 3,
            bytes_accessed=2 * PN * D * 2,
            transcendentals=PN * F,
        ),
    )(xs, wg, wu, wd)


def gemm_gathered(x, idx, wg, wu, wd, tile=TILE, order="issue_first", unroll=False):
    """order="issue_first": tile m+1's DMA issue loop runs BEFORE tile m's
    wait+compute (the scalar core delays every compute by the issue time).
    order="compute_first": wait(m) → compute(m) → issue(m+1) — the scalar
    core issues while the MXU chews on tile m. unroll: python-range loops
    (straight-line scalar code) instead of fori_loop."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PN = idx.shape[0]
    BT, D = x.shape
    F = wg.shape[1]
    x3 = x.reshape(BT, 8, D // 8)

    def kern(idx_ref, x_hbm, wg_ref, wu_ref, wd_ref, o_ref, buf, sem):
        m = pl.program_id(0)
        nm = pl.num_programs(0)

        def row_copy(t, slot, r):
            return pltpu.make_async_copy(
                x_hbm.at[idx_ref[t * tile + r]], buf.at[slot, r], sem.at[slot]
            )

        def start(t, slot):
            if unroll:
                for r in range(tile):
                    row_copy(t, slot, r).start()
            else:
                def row(r, _):
                    row_copy(t, slot, r).start()
                    return 0

                jax.lax.fori_loop(0, tile, row, 0)

        def wait_all(t, slot):
            if unroll:
                for r in range(tile):
                    row_copy(t, slot, r).wait()
            else:
                def row(r, _):
                    row_copy(t, slot, r).wait()
                    return 0

                jax.lax.fori_loop(0, tile, row, 0)

        @pl.when(m == 0)
        def _warm():
            start(0, 0)

        slot = m % 2
        if order == "issue_first":
            @pl.when(m + 1 < nm)
            def _next():
                start(m + 1, (m + 1) % 2)

            wait_all(m, slot)
            x_t = buf[slot].reshape(tile, D)
            o_ref[...] = _swiglu_body(
                x_t, wg_ref[...], wu_ref[...], wd_ref[...], o_ref.dtype
            )
        else:
            wait_all(m, slot)
            x_t = buf[slot].reshape(tile, D)
            o_ref[...] = _swiglu_body(
                x_t, wg_ref[...], wu_ref[...], wd_ref[...], o_ref.dtype
            )

            @pl.when(m + 1 < nm)
            def _next():
                start(m + 1, (m + 1) % 2)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(PN // tile,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((D, F), lambda m, idx: (0, 0)),
            pl.BlockSpec((D, F), lambda m, idx: (0, 0)),
            pl.BlockSpec((F, D), lambda m, idx: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile, D), lambda m, idx: (m, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, tile, 8, D // 8), jnp.bfloat16),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((PN, D), x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",), vmem_limit_bytes=100 * 1024 * 1024
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * PN * D * F * 3,
            bytes_accessed=2 * PN * D * 2,
            transcendentals=PN * F,
        ),
    )(idx, x3, wg, wu, wd)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--bt", type=int, default=65536)
    p.add_argument("--d", type=int, default=1024)
    p.add_argument("--f", type=int, default=2048)
    p.add_argument("--pn", type=int, default=133120)
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args()

    x = jax.random.normal(jax.random.PRNGKey(0), (args.bt, args.d), jnp.bfloat16)
    idx = jax.random.randint(jax.random.PRNGKey(1), (args.pn,), 0, args.bt, jnp.int32)
    wg = jax.random.normal(jax.random.PRNGKey(2), (args.d, args.f), jnp.bfloat16) * 0.02
    wu = jax.random.normal(jax.random.PRNGKey(3), (args.d, args.f), jnp.bfloat16) * 0.02
    wd = jax.random.normal(jax.random.PRNGKey(4), (args.f, args.d), jnp.bfloat16) * 0.02

    a = jax.jit(lambda x, i: gemm_plain(x[i], wg, wu, wd))(x, idx)
    for nm, kw in [
        ("issue_first", {}),
        ("compute_first", {"order": "compute_first"}),
        ("cf_unroll", {"order": "compute_first", "unroll": True}),
    ]:
        b = jax.jit(lambda x, i, kw=kw: gemm_gathered(x, i, wg, wu, wd, **kw))(x, idx)
        ok = np.allclose(np.asarray(a), np.asarray(b))
        print(f"parity {nm}: {'OK' if ok else 'MISMATCH'}")

    xs_pn = jax.jit(lambda x, i: x[i])(x, idx)  # PN-row input for the gemm arm

    def make_loop(arm):
        @jax.jit
        def loop(x, xs_pn, idx):
            def body(i, carry):
                x, xs_pn, acc = carry
                if arm == "gemm":
                    ys = gemm_plain(xs_pn, wg, wu, wd)
                elif arm == "gather_gemm":
                    ys = gemm_gathered(x, idx, wg, wu, wd)
                elif arm == "gg_compute_first":
                    ys = gemm_gathered(x, idx, wg, wu, wd, order="compute_first")
                elif arm == "gg_cf_unroll":
                    ys = gemm_gathered(
                        x, idx, wg, wu, wd, order="compute_first", unroll=True
                    )
                elif arm == "xla_total":
                    ys = gemm_plain(x[idx], wg, wu, wd)
                else:
                    ys = None
                if ys is not None:
                    acc = acc + ys.astype(jnp.float32).sum()
                x = jnp.where(jnp.isnan(acc), jnp.bfloat16(0), x)
                xs_pn = jnp.where(jnp.isnan(acc), jnp.bfloat16(0), xs_pn)
                return (x, xs_pn, acc)

            x, xs_pn, acc = jax.lax.fori_loop(
                0, ITERS, body, (x, xs_pn, x[0, 0].astype(jnp.float32))
            )
            return acc

        return loop

    results = {}
    for arm in [
        "control", "gemm", "gather_gemm", "gg_compute_first", "gg_cf_unroll",
        "xla_total",
    ]:
        loop = make_loop(arm)
        loop(x, xs_pn, idx).block_until_ready()
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            loop(x, xs_pn, idx).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        results[arm] = best / ITERS * 1e3
        print(f"{arm:16s}: {results[arm]:7.3f} ms/iter")

    ctl = results["control"]
    for arm in ["gemm", "gather_gemm", "gg_compute_first", "gg_cf_unroll", "xla_total"]:
        print(f"{arm:16s}: net {results[arm] - ctl:7.3f} ms")


if __name__ == "__main__":
    main()
