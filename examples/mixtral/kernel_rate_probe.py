"""Probe 3: fused grouped-GEMM kernel rate vs the dense-equivalent SwiGLU.

Same total FLOPs both arms (top-2@F=2048 over N=K·B·T rows ≡ dense F=4096
over B·T rows). If the kernel arm is materially slower, the MoE gap sits
in the kernel's MXU rate (tile size / pipelining); if they tie, the gap is
the dispatch/combine movements around it. fwd and fwd+bwd arms.

Run: python examples/mixtral/kernel_rate_probe.py [--bt 90112]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--bt", type=int, default=90112)    # b44 × 2048
    p.add_argument("--d", type=int, default=1024)
    p.add_argument("--f", type=int, default=2048)
    p.add_argument("--e", type=int, default=8)
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args()

    from tony_tpu.ops import moe_gemm

    BT, D, F, E, K = args.bt, args.d, args.f, args.e, args.k
    N = BT * K
    tile = moe_gemm.TILE_M
    PN = (-(-N // tile) + E) * tile
    per_group = (PN // tile // E) * tile
    group_sizes = jnp.full((E,), per_group, jnp.int32)
    nt = PN // tile
    tg = moe_gemm.tile_group_map(group_sizes, nt, tile)

    key = jax.random.PRNGKey(0)
    xs = jax.random.normal(key, (PN, D), jnp.bfloat16)
    xd = jax.random.normal(key, (BT, D), jnp.bfloat16)
    wg = jax.random.normal(jax.random.PRNGKey(1), (E, D, F), jnp.bfloat16) * 0.02
    wu = jax.random.normal(jax.random.PRNGKey(2), (E, D, F), jnp.bfloat16) * 0.02
    wd = jax.random.normal(jax.random.PRNGKey(3), (E, F, D), jnp.bfloat16) * 0.02
    wg2 = jax.random.normal(jax.random.PRNGKey(4), (D, 2 * F), jnp.bfloat16) * 0.02
    wu2 = jax.random.normal(jax.random.PRNGKey(5), (D, 2 * F), jnp.bfloat16) * 0.02
    wd2 = jax.random.normal(jax.random.PRNGKey(6), (2 * F, D), jnp.bfloat16) * 0.02

    flops_fwd = 2 * N * D * F * 3  # identical for the dense arm (2F over BT rows)

    def kernel_fwd(xs, w1, w2, w3):
        return moe_gemm.moe_swiglu_grouped(xs, w1, w2, w3, tg, tile)

    def dense_fwd(xd, w1, w2, w3):
        g = jnp.dot(xd, w1, preferred_element_type=jnp.float32)
        u = jnp.dot(xd, w2, preferred_element_type=jnp.float32)
        h = (jax.nn.silu(g) * u).astype(xd.dtype)
        return jnp.dot(h, w3, preferred_element_type=jnp.float32).astype(xd.dtype)

    def arm(fn, x0, ws, grad):
        if grad:
            # random fixed cotangent: grad-of-sum (dy = ones) lets XLA
            # algebraically collapse matmul(ones, W) in the transparent arm;
            # and differentiate w.r.t. the weights too, else the dW GEMMs
            # dead-code away in the transparent arm only
            dy = jax.random.normal(jax.random.PRNGKey(9), x0.shape, x0.dtype)

            def body_fn(x):
                out, vjp = jax.vjp(fn, x, *ws)
                dx, *_ = vjp(dy[: out.shape[0]].astype(out.dtype))
                return dx
        else:
            def body_fn(x):
                return fn(x, *ws)

        @jax.jit
        def loop(x):
            def body(i, carry):
                x, acc = carry
                out = body_fn(x)
                acc = acc + out.astype(jnp.float32).sum()
                x = jnp.where(jnp.isnan(acc), jnp.bfloat16(0), x)
                return (x, acc)

            x, acc = jax.lax.fori_loop(
                0, args.iters, body, (x, x[0, 0].astype(jnp.float32))
            )
            return acc

        loop(x0).block_until_ready()
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            loop(x0).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best / args.iters

    for name, fn, x0, ws in [
        ("kernel", kernel_fwd, xs, (wg, wu, wd)),
        ("dense", dense_fwd, xd, (wg2, wu2, wd2)),
    ]:
        t_f = arm(fn, x0, ws, False)
        t_b = arm(fn, x0, ws, True)
        print(
            f"{name:6s}: fwd {t_f * 1e3:7.2f} ms ({flops_fwd / t_f / 1e12:6.1f} TF/s)"
            f"   fwd+bwd {t_b * 1e3:7.2f} ms ({3 * flops_fwd / t_b / 1e12:6.1f} TF/s)"
        )


if __name__ == "__main__":
    main()
