"""Mixtral MoE pretraining under `tony submit` (BASELINE.json config #5):
expert-parallel over the `expert` mesh axis (--expert_axis N)."""
import sys

from tony_tpu.models import mixtral
from tony_tpu.train.loop import parse_loop_args, run_lm_training


def main() -> int:
    loop, extra = parse_loop_args()
    cfg = mixtral.config_from_dict(extra["preset"])
    run_lm_training(mixtral, cfg, loop)
    return 0


if __name__ == "__main__":
    sys.exit(main())
