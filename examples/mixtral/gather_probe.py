"""Probe: per-row DMA gather (Pallas) vs XLA row gather at MoE bench shape.

Decides whether the fused grouped-GEMM kernel (ops/moe_gemm.py) can gather
token rows in-kernel via scalar-prefetched indices + per-row async DMA —
killing the materialized [PN, D] dispatch gather and its remat replay —
without the per-descriptor DMA issue cost eating the win (BASELINE.md r3:
the queued "in-kernel gather/combine" lever).

Arms (loop-in-jit, ITERS serialized iterations per jit call, input scaled
by (1+1e-9) each iteration to defeat CSE; whole output reduced so nothing
dead-codes):
  xla      — xs = x[idx] (the current _dispatch_gather forward)
  pallas   — per-row DMA straight into the pipelined output block
  pallas2  — per-row DMA into a double-buffered VMEM scratch (tile m+1's
             rows issued while tile m copies out) — the shape the fused
             kernel would use, where compute hides the issue latency
  control  — the loop scaffolding alone (subtract from the arms)

Run on the chip: python examples/mixtral/gather_probe.py
"""

from __future__ import annotations

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

TILE = 256
ITERS = 16


def pallas_gather_direct(x, idx, tile=TILE):
    """Rows are DMA'd one by one straight into the pipelined output block.

    HBM slices must align to the (8, 128) bf16 tiling, so a row is viewed
    as an [8, D//8] tile: x arrives [BT, 8, D//8] (free reshape in HBM)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PN = idx.shape[0]
    BT, D = x.shape
    x3 = x.reshape(BT, 8, D // 8)

    def kern(idx_ref, x_hbm, o_ref, sem):
        m = pl.program_id(0)

        def start(r, _):
            pltpu.make_async_copy(
                x_hbm.at[idx_ref[m * tile + r]], o_ref.at[r], sem
            ).start()
            return 0

        jax.lax.fori_loop(0, tile, start, 0)

        def wait(r, _):
            pltpu.make_async_copy(
                x_hbm.at[idx_ref[m * tile + r]], o_ref.at[r], sem
            ).wait()
            return 0

        jax.lax.fori_loop(0, tile, wait, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(PN // tile,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((tile, 8, D // 8), lambda m, idx: (m, 0, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
    )
    out = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((PN, 8, D // 8), x.dtype),
        compiler_params=pltpu.CompilerParams(dimension_semantics=("arbitrary",)),
        cost_estimate=pl.CostEstimate(
            flops=0, bytes_accessed=2 * PN * D * x.dtype.itemsize, transcendentals=0
        ),
    )(idx, x3)
    return out.reshape(PN, D)


def pallas_gather_pipelined(x, idx, tile=TILE):
    """Double-buffered: tile m+1's row DMAs issue while tile m copies out.

    Also answers whether the (tile, 8, D//8) → (tile, D) in-VMEM reshape
    the fused kernel needs is cheap (the copy-out does exactly that)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PN = idx.shape[0]
    BT, D = x.shape
    x3 = x.reshape(BT, 8, D // 8)

    def kern(idx_ref, x_hbm, o_ref, buf, sem):
        m = pl.program_id(0)
        nm = pl.num_programs(0)

        def start(t, slot):
            def row(r, _):
                pltpu.make_async_copy(
                    x_hbm.at[idx_ref[t * tile + r]], buf.at[slot, r], sem.at[slot]
                ).start()
                return 0

            jax.lax.fori_loop(0, tile, row, 0)

        @pl.when(m == 0)
        def _warm():
            start(0, 0)

        @pl.when(m + 1 < nm)
        def _next():
            start(m + 1, (m + 1) % 2)

        slot = m % 2

        def wait(r, _):
            pltpu.make_async_copy(
                x_hbm.at[idx_ref[m * tile + r]], buf.at[slot, r], sem.at[slot]
            ).wait()
            return 0

        jax.lax.fori_loop(0, tile, wait, 0)
        o_ref[...] = buf[slot].reshape(tile, D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(PN // tile,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((tile, D), lambda m, idx: (m, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, tile, 8, D // 8), jnp.bfloat16),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((PN, D), x.dtype),
        compiler_params=pltpu.CompilerParams(dimension_semantics=("arbitrary",)),
        cost_estimate=pl.CostEstimate(
            flops=0, bytes_accessed=2 * PN * D * x.dtype.itemsize, transcendentals=0
        ),
    )(idx, x3)


def make_loop(arm):
    @jax.jit
    def loop(x, idx):
        def body(i, carry):
            x, acc = carry
            if arm == "xla":
                xs = x[idx]
            elif arm == "xla_tiled":
                # gather (8, D//8) slabs instead of flat rows — does XLA's
                # gather run faster on tile-aligned slices?
                xs = x.reshape(x.shape[0], 8, x.shape[1] // 8)[idx].reshape(
                    idx.shape[0], x.shape[1]
                )
            elif arm == "pallas":
                xs = pallas_gather_direct(x, idx)
            elif arm == "pallas2":
                xs = pallas_gather_pipelined(x, idx)
            else:
                xs = None
            if xs is not None:
                acc = acc + xs.astype(jnp.float32).sum()
            # true serialization: x depends on acc (isnan can't be folded,
            # and the select defeats CSE across iterations) — note a plain
            # x * (1+eps) folds away in bf16 and CSE collapses the loop
            x = jnp.where(jnp.isnan(acc), jnp.bfloat16(0), x)
            return (x, acc)

        # acc starts data-dependent so the control arm's chain can't fold
        x, acc = jax.lax.fori_loop(0, ITERS, body, (x, x[0, 0].astype(jnp.float32)))
        return acc

    return loop


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--bt", type=int, default=65536)
    p.add_argument("--d", type=int, default=1024)
    p.add_argument("--pn", type=int, default=133120)
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args()

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (args.bt, args.d), jnp.bfloat16)
    idx = jax.random.randint(jax.random.PRNGKey(1), (args.pn,), 0, args.bt, jnp.int32)

    # correctness first (tiny shapes would hide alignment bugs; use real ones)
    ref = np.asarray(x)[np.asarray(idx)]
    for name, fn in [("pallas", pallas_gather_direct), ("pallas2", pallas_gather_pipelined)]:
        got = np.asarray(jax.jit(fn)(x, idx))
        ok = np.array_equal(got, ref)
        print(f"{name} correctness: {'OK' if ok else 'MISMATCH'}")
        if not ok:
            bad = np.argwhere(~(got == ref).all(axis=1))[:5]
            print("  first bad rows:", bad.ravel())

    results = {}
    for arm in ["control", "xla", "xla_tiled", "pallas", "pallas2"]:
        loop = make_loop(arm)
        loop(x, idx).block_until_ready()  # compile
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            loop(x, idx).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        per_iter = best / ITERS * 1e3
        results[arm] = per_iter
        print(f"{arm:8s}: {per_iter:7.3f} ms/iter (best of {args.reps})")

    ctl = results["control"]
    for arm in ["xla", "xla_tiled", "pallas", "pallas2"]:
        net = results[arm] - ctl
        gb = 2 * args.pn * args.d * 2 / 1e9
        print(f"{arm:8s}: net {net:7.3f} ms  ({gb / (net / 1e3):6.1f} GB/s effective)")


if __name__ == "__main__":
    main()
