"""Llama pretraining under `tony submit` (BASELINE.json config #4).

    tony submit --conf_file examples/llama/tony.json \
        --executes "python examples/llama/pretrain.py --preset llama3-8b --model_axis 4"
"""
import sys

from tony_tpu.models import llama
from tony_tpu.train.loop import parse_loop_args, run_lm_training


def main() -> int:
    loop, extra = parse_loop_args()
    cfg = llama.config_from_dict(extra["preset"])
    run_lm_training(llama, cfg, loop)
    return 0


if __name__ == "__main__":
    sys.exit(main())
