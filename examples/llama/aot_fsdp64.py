"""AOT compile plan: the Llama-3-8B train step on a 64-chip FSDP mesh.

No hardware needed: 64 virtual CPU devices stand in for a v5e-64, every
argument is abstract (ShapeDtypeStruct + sharding), and the result is the
compiler's own accounting of the step — per-chip HBM for parameters,
optimizer state, activations (with the remat policy applied), and the
collectives XLA inserted for the fsdp axis. This is the memory plan a real
v5e-64 deployment starts from (BASELINE.json north star).

    python examples/llama/aot_fsdp64.py [--fsdp 64] [--batch 64] [--seq 8192]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--fsdp", type=int, default=64)
    p.add_argument("--batch", type=int, default=64, help="global batch (sequences)")
    p.add_argument("--seq", type=int, default=8192)
    p.add_argument("--remat-policy", default="flash")
    args = p.parse_args()

    import jax

    if len(jax.devices()) < args.fsdp:
        import jax.extend.backend as _jeb

        jax.config.update("jax_platforms", "cpu")
        _jeb.clear_backends()
        jax.config.update("jax_num_cpu_devices", args.fsdp)

    import dataclasses
    import functools
    import os

    # must precede the tony_tpu imports: ops/attention.py latches the
    # interpret flag at import time
    os.environ.setdefault("TONY_PALLAS_INTERPRET", "1")

    from tony_tpu.models import llama
    from tony_tpu.parallel import MeshSpec
    from tony_tpu.train import OptimizerConfig, TrainState, make_train_step
    from tony_tpu.train.trainer import sharded_init  # noqa: F401  (docs pointer)

    # compile the REAL kernel graph, not the CPU fallback: the reference
    # attention path would count O(T²) score buffers the TPU flash kernel
    # never materializes (its working set is VMEM tiles, invisible to HBM
    # accounting — matching the chip)
    cfg = dataclasses.replace(
        llama.LLAMA3_8B, max_seq=args.seq, remat=True,
        remat_policy=args.remat_policy, ce_chunk=1024, attn_impl="flash",
    )
    mesh = MeshSpec(fsdp=args.fsdp).build(jax.devices()[: args.fsdp])
    rules = llama.sharding_rules(cfg)
    opt = OptimizerConfig(warmup_steps=100, total_steps=10_000).build()

    # fully-abstract state: nothing is materialized anywhere
    def make_state():
        params = llama.init(jax.random.PRNGKey(0), cfg)
        return TrainState(params=params, opt_state=opt.init(params),
                          step=jax.numpy.zeros((), jax.numpy.int32))

    abs_state = jax.eval_shape(make_state)
    shard_tree = rules.sharding_tree(abs_state, mesh)
    abs_state = jax.tree.map(
        lambda st, sh: jax.ShapeDtypeStruct(st.shape, st.dtype, sharding=sh),
        abs_state, shard_tree,
    )
    abs_batch = {
        "tokens": jax.ShapeDtypeStruct(
            (args.batch, args.seq + 1), jax.numpy.int32,
            sharding=rules.sharding_tree(
                {"tokens": jax.ShapeDtypeStruct((args.batch, args.seq + 1), jax.numpy.int32)},
                mesh,
            )["tokens"],
        )
    }

    step = make_train_step(functools.partial(llama.loss_fn, cfg=cfg, mesh=mesh), opt)
    t0 = time.perf_counter()
    lowered = step.lower(abs_state, abs_batch)
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0

    mem = compiled.memory_analysis()

    # Analytic per-chip activation plan for remat_policy="flash" (what the
    # TPU actually holds; the CPU compiler's temp accounting is not
    # representative — interpret-mode kernel callbacks pin buffers and CPU
    # layouts differ):
    #   pinned per layer = flash o [b,T,H·Dh] bf16 + lse [b,T,H,8] f32,
    #   + residual stream x per layer boundary (scan carry is remat-pinned
    #   per layer input), + CE chunk logits f32, over b = batch/fsdp chips.
    b_local = max(args.batch // args.fsdp, 1)
    D, H, L = cfg.d_model, cfg.n_heads, cfg.n_layers
    per_layer = (
        b_local * args.seq * D * 2            # flash o (bf16)
        + b_local * args.seq * H * 8 * 4      # lse lanes (f32)
        + b_local * args.seq * D * 2          # block input (remat pin, bf16)
    )
    ce_chunk_bytes = b_local * cfg.ce_chunk * cfg.vocab_size * 4
    acts_gib = (L * per_layer + ce_chunk_bytes) / 2**30
    params_gib = cfg.num_params() * 2 / args.fsdp / 2**30          # bf16
    opt_gib = cfg.num_params() * 2 * 2 / args.fsdp / 2**30         # adam mu+nu bf16
    grads_gib = params_gib                                          # bf16 grads
    plan = {
        "params_gib": round(params_gib, 2),
        "opt_state_gib": round(opt_gib, 2),
        "grads_gib": round(grads_gib, 2),
        "activations_gib": round(acts_gib, 2),
        "total_gib": round(params_gib + opt_gib + grads_gib + acts_gib, 2),
    }
    out = {
        "metric": "llama3_8b_fsdp64_aot_compile",
        "params_b": round(cfg.num_params() / 1e9, 3),
        "mesh": {k: v for k, v in mesh.shape.items() if v > 1},
        "global_batch": args.batch,
        "seq": args.seq,
        "remat_policy": args.remat_policy,
        "compile_s": round(compile_s, 1),
        # faithful from the compiled artifact: sharded param+opt bytes/chip
        "compiled_argument_gib": round(
            getattr(mem, "argument_size_in_bytes", 0) / 2**30, 2
        ) if mem is not None else None,
        "per_chip_hbm_plan": plan,
        "fits_v5e_16gib": plan["total_gib"] < 16.0,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
