"""Sample from a (checkpointed or randomly initialized) Llama.

    python examples/llama/generate.py --preset tiny --max_new_tokens 32
    python examples/llama/generate.py --preset llama-1b \
        --checkpoint_dir /path/to/ckpt --prompt "1 2 3 4" --temperature 0.7
"""

import argparse
import sys

import jax
import jax.numpy as jnp

from tony_tpu.models import generate, llama


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="tiny")
    p.add_argument("--checkpoint_dir", default="")
    p.add_argument("--prompt", default="", help="space-separated token ids")
    p.add_argument("--max_new_tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    cfg = llama.PRESETS[args.preset]
    if args.checkpoint_dir:
        # training checkpoints hold the full TrainState (params/opt/step):
        # restore against an ABSTRACT template (eval_shape — no multi-GB
        # random init just to throw it away) and keep only the params
        from tony_tpu.train.checkpoint import CheckpointManager
        from tony_tpu.train.trainer import OptimizerConfig, TrainState

        opt = OptimizerConfig(warmup_steps=0, total_steps=1).build()
        template = jax.eval_shape(
            lambda: TrainState.create(llama.init(jax.random.PRNGKey(0), cfg), opt)
        )
        mgr = CheckpointManager(args.checkpoint_dir)
        params = mgr.restore(template).params
        print(f"[generate] restored checkpoint step {mgr.latest_step()}", file=sys.stderr)
    else:
        params = llama.init(jax.random.PRNGKey(args.seed), cfg)

    ids = [int(t) for t in args.prompt.split()] if args.prompt else [0, 1, 2, 3]
    prompt = jnp.asarray([ids], jnp.int32)
    out = generate.generate(
        params, prompt, cfg,
        max_new_tokens=args.max_new_tokens,
        temperature=args.temperature, top_k=args.top_k,
        key=jax.random.PRNGKey(args.seed),
    )
    print(" ".join(str(int(t)) for t in out[0]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
