"""Measured per-layer MFU at the 8B geometry on ONE real chip.

Full 8B training cannot fit a single v5e, but one transformer layer at the
exact 8B geometry (d4096 / F14336 / H32 / KV8 / Dh128) at the AOT fsdp=64
plan's per-chip shape (batch 1 × seq 8192) can. The fsdp=64 HBM plan
(examples/llama/aot_fsdp64.py, BASELINE.md) assumes 8B matches the 0.87B
bench proxy's efficiency — this measures that assumption directly: R
applications of the layer (fwd+bwd, flash remat, shared weights) inside one
jit, one scalar fetch (the axon dispatch floor swamps per-call timing).

    python examples/llama/layer8b_mfu.py [--reps 8] [--seq 8192] [--batch 1]
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import sys
import time

import jax
import jax.numpy as jnp


def main() -> int:
    from tony_tpu.models import llama
    from tony_tpu.ops import attention as attn_ops
    from tony_tpu.ops import layers as L
    from tony_tpu.train.metrics import detect_peak_flops, transformer_flops_per_token

    p = argparse.ArgumentParser()
    p.add_argument("--reps", type=int, default=8)
    p.add_argument("--seq", type=int, default=8192)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--steps", type=int, default=6)
    args = p.parse_args()

    cfg = dataclasses.replace(
        llama.LLAMA3_8B, n_layers=1, max_seq=args.seq,
        remat=True, remat_policy="flash", attn_impl="auto",
    )
    D = cfg.d_model
    key = jax.random.PRNGKey(0)
    lp = {k: v[0] for k, v in llama.init(key, cfg)["layers"].items()}
    x = jax.random.normal(jax.random.fold_in(key, 1), (args.batch, args.seq, D), jnp.bfloat16)
    cos, sin = L.rope_frequencies(cfg.head_dim, args.seq, cfg.rope_theta, cfg.rope_scaling)

    block = attn_ops.remat_block(
        functools.partial(llama._block, cos=cos, sin=sin, cfg=cfg, mesh=None),
        cfg.remat, cfg.remat_policy,
    )

    def loss(lp, x):
        def body(h, _):
            h, _ = block(h, lp)
            return h, None
        h, _ = jax.lax.scan(body, x, length=args.reps)
        return (h.astype(jnp.float32) ** 2).mean()

    step = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))

    t0 = time.perf_counter()
    out = step(lp, x)
    float(out[0])
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(args.steps):
        out = step(lp, x)
        float(out[0])  # hard host sync per step (axon async dispatch)
    dt = (time.perf_counter() - t0) / args.steps

    # per-layer training-FLOP basis: the shared 6N + causal-attention
    # formula, with N = this ONE layer's params (no embed/head)
    layer_params = sum(v.size for v in lp.values())
    fpt = transformer_flops_per_token(layer_params, 1, D, args.seq, training=True)
    tokens = args.batch * args.seq * args.reps
    mfu = fpt * tokens / dt / detect_peak_flops()
    print(json.dumps({
        "metric": "llama8b_layer_train_mfu_1chip",
        "value": round(mfu, 4),
        "unit": "mfu",
        "layer_params": layer_params,
        "batch": args.batch, "seq": args.seq, "reps": args.reps,
        "step_ms": round(dt * 1000, 2),
        "warmup_s": round(compile_s, 1),
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
