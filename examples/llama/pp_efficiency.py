"""1F1B schedule-efficiency measurement (VERDICT r2 #2c).

Runs the SAME global-batch train computation two ways on a virtual CPU
mesh and compares wall-clock:

- flat: data-parallel value_and_grad over a data=N mesh;
- 1F1B: pp_value_and_grad over a stage=S × data=N/S mesh, M microbatches.

On a virtual mesh every "device" shares the host's cores, so wall-clock
measures TOTAL EXECUTED WORK, not parallel latency — which is exactly the
right probe for the question "does the cond-gated schedule still execute
redundant work?": an ungated SPMD schedule executes
S×(M+2S−1)/M useful-equivalents of the loss head per step; the gated one
executes M + bubbles. The analytic schedule efficiency (tick utilization,
what a real S-deep pipeline's wall-clock follows) is M/(M+2S−1) and is
printed alongside.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/llama/pp_efficiency.py [--stages 4] [--micro 8]
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import sys
import time

import jax
import jax.numpy as jnp


def main() -> int:
    from tony_tpu.models import llama
    from tony_tpu.parallel import MeshSpec

    p = argparse.ArgumentParser()
    p.add_argument("--stages", type=int, default=4)
    p.add_argument("--micro", type=int, default=8)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--steps", type=int, default=5)
    args = p.parse_args()

    n_dev = len(jax.devices())
    S, M = args.stages, args.micro
    cfg = dataclasses.replace(
        llama.LLAMA_TINY, d_model=128, n_layers=2 * S, n_heads=4, n_kv_heads=4,
        d_ff=256, vocab_size=4096, max_seq=args.seq, remat=False, ce_chunk=64,
    )
    key = jax.random.PRNGKey(0)
    params = llama.init(key, cfg)
    batch = llama.synthetic_batch(key, args.batch, args.seq, cfg)

    def timeit(fn, *a):
        out = fn(*a)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out = fn(*a)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / args.steps

    mesh_flat = MeshSpec(data=n_dev).build()
    flat = jax.jit(
        jax.value_and_grad(
            lambda p: llama.loss_fn(p, batch, cfg, mesh_flat)[0]
        )
    )
    t_flat = timeit(flat, params)

    mesh_pp = MeshSpec(stage=S, data=n_dev // S).build()
    pp = jax.jit(
        functools.partial(
            llama.pp_value_and_grad, cfg=cfg, mesh=mesh_pp, num_microbatches=M
        )
    )
    t_pp = timeit(pp, params, batch)

    analytic = M / (M + 2 * S - 1)
    print(json.dumps({
        "metric": "pp_1f1b_total_work_ratio",
        "value": round(t_pp / t_flat, 3),
        "unit": "x_flat_wallclock_virtual_mesh",
        "stages": S, "microbatches": M, "devices": n_dev,
        "flat_step_ms": round(t_flat * 1000, 1),
        "pp_step_ms": round(t_pp * 1000, 1),
        "analytic_tick_utilization": round(analytic, 3),
        "note": "virtual CPU mesh: wall-clock ~ total executed work; an "
                "ungated schedule would multiply the head cost by ~S and "
                "bubble compute by 2S-1 ticks",
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
