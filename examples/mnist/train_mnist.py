"""MNIST-scale training under `tony submit` (BASELINE.json config #1; the
tony-examples/mnist analog). Runs standalone or as a gang task."""
import functools
import sys

import jax

from tony_tpu.models import mlp
from tony_tpu.runtime import init_distributed
from tony_tpu.train import OptimizerConfig, TrainState, make_train_step


def main() -> int:
    init_distributed()
    cfg = mlp.MLPConfig()
    opt = OptimizerConfig(learning_rate=1e-3, warmup_steps=0, total_steps=200).build()
    state = TrainState.create(mlp.init(jax.random.PRNGKey(0), cfg), opt)
    step = make_train_step(functools.partial(mlp.loss_fn, cfg=cfg), opt)
    key = jax.random.PRNGKey(1)
    for i in range(200):
        batch = mlp.synthetic_batch(jax.random.fold_in(key, i), 64, cfg)
        state, m = step(state, batch)
        if (i + 1) % 50 == 0:
            print(f"step {i+1} loss={float(m['loss']):.4f} acc={float(m['accuracy']):.3f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
