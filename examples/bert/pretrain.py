"""BERT MLM pretraining (BASELINE.json config #2 analog)."""
import functools
import sys

import jax

from tony_tpu.models import bert
from tony_tpu.parallel import MeshSpec
from tony_tpu.runtime import init_distributed
from tony_tpu.train import OptimizerConfig, make_train_step, sharded_init
from tony_tpu.train.loop import parse_loop_args


def main() -> int:
    init_distributed()
    loop, extra = parse_loop_args()
    cfg = bert.config_from_dict(extra["preset"])
    mesh = MeshSpec.auto(model=loop.model_axis).build()
    opt = OptimizerConfig(learning_rate=loop.learning_rate, warmup_steps=loop.warmup_steps,
                          total_steps=loop.steps).build()
    state = sharded_init(lambda: bert.init(jax.random.PRNGKey(0), cfg),
                         bert.sharding_rules(cfg), mesh, opt)
    step = make_train_step(functools.partial(bert.loss_fn, cfg=cfg, mesh=mesh), opt)
    key = jax.random.PRNGKey(1)
    for i in range(loop.steps):
        batch = bert.synthetic_batch(jax.random.fold_in(key, i), loop.batch_size, loop.seq_len, cfg)
        state, m = step(state, batch)
        if (i + 1) % loop.log_every == 0:
            print(f"step {i+1} loss={float(m['loss']):.4f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
