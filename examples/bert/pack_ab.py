"""Packed-vs-padded BERT MLM throughput A/B (BASELINE config #2 follow-up).

Real MLM corpora have variable-length documents; the padded recipe gives
every document its own 512-token row and pays full attention+FFN cost on
the padding. Packing (data.pack_sequences) lays multiple documents per row
with segment-confined attention and per-segment positions, so the same
document stream needs fewer rows. Both arms run in ONE process on the same
synthetic length distribution; the metric is REAL (non-pad) content tokens
per second.

    python examples/bert/pack_ab.py [--steps 8] [--rows 384]
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def doc_stream(rng: np.random.Generator, n_docs: int, lo: int = 48, hi: int = 512):
    """Uniform[lo, hi] doc lengths — mean ~280 of a 512 row (a 1.8× pack)."""
    return [
        rng.integers(1, 30_000, size=rng.integers(lo, hi + 1)).astype(np.int32)
        for _ in range(n_docs)
    ]


def masked_positions(rng, seg: np.ndarray, m: int):
    """Sample m mask positions per row from REAL (non-pad) positions
    (with replacement — static shapes; fine for a throughput A/B)."""
    B, T = seg.shape
    pos = np.zeros((B, m), np.int32)
    for b in range(B):
        real = np.flatnonzero(seg[b] != 0)
        pos[b] = rng.choice(real, size=m, replace=True)
    return np.sort(pos, axis=1)


def run_arm(name, tokens, seg, cfg, steps, mask_frac=0.15):
    from tony_tpu.train import OptimizerConfig, make_train_step, sharded_init
    from tony_tpu.models import bert
    from tony_tpu.parallel import MeshSpec

    rng = np.random.default_rng(1)
    B, T = tokens.shape
    m = max(1, round(T * mask_frac))
    batch = {
        "tokens": jnp.asarray(tokens),
        "segment_ids": jnp.asarray(seg),
        "masked_pos": jnp.asarray(masked_positions(rng, seg, m)),
    }
    batch["masked_targets"] = jnp.take_along_axis(
        batch["tokens"], batch["masked_pos"], axis=1
    )
    mesh = MeshSpec.auto(len(jax.devices())).build()
    opt = OptimizerConfig(warmup_steps=10, total_steps=1000).build()
    state = sharded_init(
        lambda: bert.init(jax.random.PRNGKey(0), cfg), bert.sharding_rules(cfg), mesh, opt
    )
    step_fn = make_train_step(functools.partial(bert.loss_fn, cfg=cfg, mesh=mesh), opt)

    for _ in range(2):
        state, metrics = step_fn(state, batch)
        float(metrics["loss"])
    real_tokens = int((seg != 0).sum())
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
        float(metrics["loss"])  # hard host sync (axon async dispatch)
    dt = (time.perf_counter() - t0) / steps
    out = {
        "arm": name, "rows": B, "seq": T, "real_tokens_per_batch": real_tokens,
        "step_ms": round(dt * 1000, 2),
        "content_tokens_per_sec": round(real_tokens / dt, 1),
    }
    print(json.dumps(out))
    return out


def main() -> int:
    from tony_tpu.data.dataset import pack_sequences
    from tony_tpu.models import bert

    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--rows", type=int, default=384, help="PADDED-arm row count")
    p.add_argument("--seq", type=int, default=512)
    args = p.parse_args()

    cfg = dataclasses.replace(bert.BERT_BASE, remat=True, attn_impl="auto")
    rng = np.random.default_rng(0)
    docs = doc_stream(rng, args.rows)

    # padded arm: one doc per row
    T = args.seq
    tok_pad = np.zeros((args.rows, T), np.int32)
    seg_pad = np.zeros((args.rows, T), np.int32)
    for i, d in enumerate(docs):
        tok_pad[i, : len(d)] = d[:T]
        seg_pad[i, : len(d)] = 1
    padded = run_arm("padded", tok_pad, seg_pad, cfg, args.steps)

    # packed arm: same docs, first-fit packed; pad row count to a multiple
    # of 8 for clean sharding
    tok_pk, seg_pk = pack_sequences(docs, T)
    keep = (len(tok_pk) // 8) * 8 or len(tok_pk)
    packed = run_arm("packed", tok_pk[:keep], seg_pk[:keep], cfg, args.steps)

    speedup = packed["content_tokens_per_sec"] / max(padded["content_tokens_per_sec"], 1)
    print(json.dumps({
        "metric": "bert_pack_speedup", "value": round(speedup, 3), "unit": "x",
        "padded_tok_s": padded["content_tokens_per_sec"],
        "packed_tok_s": packed["content_tokens_per_sec"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
