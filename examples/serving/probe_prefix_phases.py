"""Per-step timeline of the shared-prefix workload (paged-vs-dense probe).

    python examples/serving/probe_prefix_phases.py --kv paged

Replicates bench_decode's shared-prefix workload (16 requests, 1024-token
common prefix + 32 unique, 16 new tokens each) but times EVERY engine
step individually and labels it with what the engine did (staged / admitted
/ running / prefix-hit delta), so the end-to-end gap between dense and
paged decomposes into named phases instead of one opaque total.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import jax
import numpy as np

from tony_tpu.models import llama
from tony_tpu.models.serving import ContinuousBatcher


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--kv", default="paged", choices=["dense", "paged"])
    p.add_argument("--slots", type=int, default=16)
    p.add_argument("--prompt-len", type=int, default=1056)
    p.add_argument("--shared-prefix", type=int, default=1024)
    p.add_argument("--new-tokens", type=int, default=16)
    p.add_argument("--max-len", type=int, default=2048)
    p.add_argument("--page-len", type=int, default=256)
    p.add_argument("--passes", type=int, default=1,
                   help=">1: drain the workload N-1 times first (compiles + "
                        "prefix registration), then per-step-time the last")
    args = p.parse_args()

    cfg = dataclasses.replace(llama.LLAMA_1B, max_seq=args.max_len)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    eng = ContinuousBatcher(
        params, cfg, num_slots=args.slots, max_len=args.max_len,
        kv=args.kv, page_len=args.page_len,
    )
    rng = np.random.default_rng(0)
    shared = rng.integers(0, cfg.vocab_size, args.shared_prefix).tolist()

    def submit_all():
        for _ in range(args.slots):
            tail = args.prompt_len - len(shared)
            eng.submit(shared + rng.integers(0, cfg.vocab_size, tail).tolist(),
                       max_new_tokens=args.new_tokens)

    for _ in range(max(args.passes, 1) - 1):
        submit_all()
        while eng.step():
            pass
        jax.block_until_ready(eng.tokens)
    submit_all()
    tok0 = sum(len(v) for v in eng.done.values())  # exclude warm-pass output

    t_start = time.perf_counter()
    i = 0
    rows = []
    while True:
        before = dict(
            pending=len(eng.pending), staged=len(eng._staged),
            running=len(eng.running),
            hits=getattr(eng, "prefix_hit_tokens", 0),
        )
        t0 = time.perf_counter()
        more = eng.step()
        jax.block_until_ready(eng.tokens)
        dt = time.perf_counter() - t0
        rows.append(dict(
            step=i, ms=round(dt * 1000, 1), **{f"pre_{k}": v for k, v in before.items()},
            post_pending=len(eng.pending), post_staged=len(eng._staged),
            post_running=len(eng.running),
            post_hits=getattr(eng, "prefix_hit_tokens", 0),
        ))
        i += 1
        if not more:
            break
    total = time.perf_counter() - t_start
    for r in rows:
        print(json.dumps(r), file=sys.stderr)
    n_tok = sum(len(v) for v in eng.done.values()) - tok0
    print(json.dumps(dict(
        metric="prefix_phase_probe", kv=args.kv, total_s=round(total, 2),
        steps=len(rows), tokens=n_tok,
        step_ms=[r["ms"] for r in rows],
    )))
    return 0


if __name__ == "__main__":
    sys.exit(main())
