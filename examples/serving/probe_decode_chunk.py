"""Warmed per-chunk timing of the jitted decode for dense vs paged.

    python examples/serving/probe_decode_chunk.py --ctx 1056 --max-len 2048

Builds both engines at identical slot state (every slot length = --ctx),
compiles the decode-chunk program once, then times N warmed calls each —
no admission, no prefill, no compile in the timed region. This is the
cleanest per-chunk paged-vs-dense number the engine can produce; the
bench_decode end-to-end figure layers admission + compile on top.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import jax
import numpy as np

from tony_tpu.models import llama
from tony_tpu.models.serving import ContinuousBatcher


def steady_state(eng, ctx: int, budget: int) -> None:
    rng = np.random.default_rng(0)
    for _ in range(eng.S):
        eng.submit(rng.integers(0, eng.cfg.vocab_size, ctx).tolist(),
                   max_new_tokens=budget)
    eng.step()  # admit + first chunk (compiles here)
    jax.block_until_ready(eng.tokens)


def time_chunks(eng, n_calls: int) -> list[float]:
    out = []
    for _ in range(n_calls):
        t0 = time.perf_counter()
        eng.step()
        jax.block_until_ready(eng.tokens)
        out.append((time.perf_counter() - t0) * 1000)
    return out


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--slots", type=int, default=16)
    p.add_argument("--ctx", type=int, default=1056)
    p.add_argument("--max-len", type=int, default=2048)
    p.add_argument("--page-len", type=int, default=256)
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument("--calls", type=int, default=10)
    p.add_argument("--attn", default="auto", choices=["auto", "ragged", "bucketed"])
    args = p.parse_args()

    cfg = dataclasses.replace(llama.LLAMA_1B, max_seq=args.max_len)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    # decode budget: enough chunks for warm + measured calls
    budget = (args.calls + 3) * args.chunk

    res = {}
    for kv in ("dense", "paged"):
        eng = ContinuousBatcher(
            params, cfg, num_slots=args.slots, max_len=args.max_len,
            kv=kv, page_len=args.page_len, decode_chunk=args.chunk,
            attn=args.attn,
        )
        steady_state(eng, args.ctx, budget)
        time_chunks(eng, 2)  # settle
        ms = time_chunks(eng, args.calls)
        res[kv] = dict(
            attn=eng.attn, ms_per_chunk=[round(m, 1) for m in ms],
            median=round(sorted(ms)[len(ms) // 2], 1),
        )
        print(f"[probe] {kv}: median {res[kv]['median']} ms/chunk "
              f"({res[kv]['ms_per_chunk']})", file=sys.stderr)

    print(json.dumps(dict(
        metric="decode_chunk_warmed_ms", slots=args.slots, ctx=args.ctx,
        max_len=args.max_len, chunk=args.chunk,
        dense=res["dense"], paged=res["paged"],
        paged_over_dense=round(res["paged"]["median"] / res["dense"]["median"], 3),
    )))
    return 0


if __name__ == "__main__":
    sys.exit(main())
