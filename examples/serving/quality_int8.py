"""int8 weight-only quality gate: logit error + top-1 agreement vs bf16.

A 643 tok/s int8 serving number without a quality bound is half a result
(VERDICT r3 #7): this measures, on the SAME weights, the serving forward's
logits bf16-vs-int8 — mean/max |Δlogit|, top-1 agreement across positions,
and KL(bf16‖int8) — on the 1B model end-to-end and on the 8B GEOMETRY as a
single-layer gate (full 8B bf16 cannot coexist with int8 on one v5e's HBM;
the per-layer error bounds what each of the 32 layers contributes).

    python examples/serving/quality_int8.py --preset llama-1b --batch 4 --seq 512
    python examples/serving/quality_int8.py --geometry 8b --batch 2 --seq 256

Prints one JSON line per config; BASELINE.md records the table.
"""

import argparse
import dataclasses
import json
import sys

import jax
import jax.numpy as jnp

from tony_tpu.models import llama
from tony_tpu.models.generate import _forward_with_cache, init_cache
from tony_tpu.ops.quant import quantize_tree


def logits_of(params, tokens, cfg):
    cache = init_cache(cfg, tokens.shape[0], tokens.shape[1])
    logits, _ = jax.jit(_forward_with_cache, static_argnames=("cfg",))(
        params, tokens, cache, cfg
    )
    return logits.astype(jnp.float32)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="llama-1b")
    p.add_argument("--geometry", default="", choices=["", "8b"],
                   help="'8b': single-layer gate at the 8B dims instead of a preset")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    if args.geometry == "8b":
        cfg = dataclasses.replace(llama.LLAMA3_8B, n_layers=1, max_seq=args.seq)
        label = "8b_geometry_1layer"
    else:
        cfg = dataclasses.replace(llama.PRESETS[args.preset], max_seq=args.seq)
        label = args.preset
    key = jax.random.PRNGKey(args.seed)
    params = llama.init(key, cfg)
    tokens = jax.random.randint(
        jax.random.fold_in(key, 1), (args.batch, args.seq), 0, cfg.vocab_size
    )

    ref = logits_of(params, tokens, cfg)
    qparams, before, after = quantize_tree(params)
    got = logits_of(qparams, tokens, cfg)

    d = jnp.abs(got - ref)
    ref_scale = jnp.abs(ref).mean()
    top1 = (jnp.argmax(got, -1) == jnp.argmax(ref, -1)).mean()
    logp_ref = jax.nn.log_softmax(ref, -1)
    logp_got = jax.nn.log_softmax(got, -1)
    kl = (jnp.exp(logp_ref) * (logp_ref - logp_got)).sum(-1).mean()
    print(json.dumps({
        "metric": f"int8_quality_{label}",
        "value": round(float(top1), 4),
        "unit": "top1_agreement",
        "mean_abs_dlogit": round(float(d.mean()), 4),
        "max_abs_dlogit": round(float(d.max()), 3),
        "mean_abs_logit_bf16": round(float(ref_scale), 3),
        "kl_bf16_to_int8": round(float(kl), 5),
        "weights_gb": [round(before / 1e9, 2), round(after / 1e9, 2)],
        "batch": args.batch, "seq": args.seq,
        "device": jax.devices()[0].device_kind,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
