"""Continuous-batching decode throughput (serving path, single chip).

    python examples/serving/bench_decode.py --slots 8 --new-tokens 64 [--int8]

Prints one JSON line with decode tokens/sec (all slots active, steady
state) for the ~0.9B bench Llama. Weight-only int8 (ops/quant.py) halves
the HBM bytes per token-step, which is what bounds batch-decode on TPU.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import jax
import numpy as np

from tony_tpu.models import llama
from tony_tpu.models.serving import ContinuousBatcher


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--new-tokens", type=int, default=64)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--int8", action="store_true")
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help=">0: prefill long prompts in exact chunks of this "
                        "many tokens (one per engine step) — kills the "
                        "power-of-two padding waste on long prompts (a 1056 "
                        "prompt pads to 2048 unchunked) and bounds admission "
                        "stalls; short prompts are unaffected")
    p.add_argument("--preset", default="bench-1b")
    p.add_argument("--model", default="llama", choices=["llama", "mixtral"])
    p.add_argument("--host-init", action="store_true",
                   help="init + quantize on the host CPU, then ship to the "
                        "chip — required for models whose bf16 weights don't "
                        "fit HBM before quantization (llama3-8b on one v5e)")
    p.add_argument("--attn", default="auto", choices=["auto", "ragged", "bucketed"])
    p.add_argument("--kv", default="dense", choices=["dense", "paged"],
                   help="paged: block-paged KV pool + shared-prefix reuse")
    p.add_argument("--page-len", type=int, default=256)
    p.add_argument("--num-pages", type=int, default=0,
                   help="page pool size (0 = dense-equivalent)")
    p.add_argument("--shared-prefix", type=int, default=0,
                   help=">0: every request shares a prompt prefix of this "
                        "many tokens (prefix-cache workload)")
    p.add_argument("--long-slot", action="store_true",
                   help="pre-occupy slot 0 with a near-max_len request: with "
                        "attn=ragged the other slots' tokens/s should barely "
                        "move (per-slot cache reads); with bucketed the long "
                        "slot drags every slot to the max bucket")
    p.add_argument("--mixed-lengths", default="",
                   help="comma list of prompt lengths, e.g. 96,224,480,992: "
                        "requests cycle through them (--prompt-len ignored). "
                        "The capacity workload: a paged pool sized well below "
                        "slots*max_len serves short requests in slots a dense "
                        "cache would hold whole-max_len slabs for")
    p.add_argument("--requests", type=int, default=0,
                   help="total requests to drain (0 = --slots). >slots "
                        "exercises continuous admission through retirements")
    p.add_argument("--passes", type=int, default=1,
                   help=">1: run the whole workload N times through one "
                        "engine and time only the LAST pass. Pass 1 compiles "
                        "every jit variant the workload touches (prefill "
                        "buckets, decode chunks, retirement flushes) — with "
                        "--passes 1 those compiles land INSIDE the measured "
                        "window and read as engine slowness (the r5 probe "
                        "measured warmed paged decode at 0.999x dense while "
                        "single-pass end-to-ends showed paged -17%: all "
                        "compile). Use 2 for steady-state numbers.")
    args = p.parse_args()

    if args.model == "mixtral":
        from tony_tpu.models import mixtral

        # the moe bench geometry (~0.49B total / 0.17B active), serving shape
        cfg = mixtral.MixtralConfig(
            vocab_size=32_000, d_model=1024, n_layers=8, n_heads=8, n_kv_heads=4,
            d_ff=2048, max_seq=args.max_len, num_experts=8, top_k=2,
        )
        params_init = lambda: mixtral.init(jax.random.PRNGKey(0), cfg)
    else:
        cfg = (
            dataclasses.replace(llama.LLAMA_1B, max_seq=args.max_len)
            if args.preset == "bench-1b" else llama.PRESETS[args.preset]
        )
        params_init = lambda: llama.init(jax.random.PRNGKey(0), cfg)
    if args.model == "mixtral" and (args.int8 or args.host_init):
        sys.exit("int8/host-init quantization is dense-family only (the MoE "
                 "decode path einsums stacked expert weights directly)")
    if args.host_init:
        from tony_tpu.ops import quant

        cpu = jax.devices("cpu")[0]
        t0 = time.perf_counter()
        with jax.default_device(cpu):
            params = params_init()
            params, before, after = quant.quantize_tree(params)
            jax.block_until_ready(params)
        print(f"[bench] host init+quant: {before / 1e9:.2f} GB -> "
              f"{after / 1e9:.2f} GB in {time.perf_counter() - t0:.0f}s",
              file=sys.stderr)
        t0 = time.perf_counter()
        params = jax.device_put(params, jax.devices()[0])
        jax.block_until_ready(params)
        print(f"[bench] weights to chip in {time.perf_counter() - t0:.0f}s",
              file=sys.stderr)
    else:
        params = params_init()
        if args.int8:
            from tony_tpu.ops import quant

            params, before, after = quant.quantize_tree(params)
            print(f"[bench] int8: {before / 1e9:.2f} GB -> {after / 1e9:.2f} GB",
                  file=sys.stderr)

    eng = ContinuousBatcher(
        params, cfg, num_slots=args.slots, max_len=args.max_len,
        decode_chunk=args.chunk, prefill_chunk=args.prefill_chunk,
        attn=args.attn, kv=args.kv,
        page_len=args.page_len,
        num_pages=args.num_pages if args.num_pages > 0 else None,
    )
    rng = np.random.default_rng(0)
    shared = []
    if args.shared_prefix > 0:
        # the shared prefix is PART of the prompt (prompts stay at
        # --prompt-len); at least one token per request stays unique so the
        # last-token logits differ per request
        n_shared = min(args.shared_prefix, args.prompt_len - 1)
        if n_shared < args.shared_prefix:
            print(f"[bench] shared prefix capped at {n_shared} "
                  f"(prompt-len {args.prompt_len})", file=sys.stderr)
        if args.kv == "paged" and n_shared < args.page_len:
            print(f"[bench] WARNING: shared prefix {n_shared} < page-len "
                  f"{args.page_len}: no full page to share — zero prefix hits",
                  file=sys.stderr)
        shared = rng.integers(0, cfg.vocab_size, n_shared).tolist()

    mixed = [int(x) for x in args.mixed_lengths.split(",") if x.strip()]

    def submit_workload():
        n_short = args.requests if args.requests > 0 else args.slots
        if args.long_slot:
            # one near-max-length resident request; its decode budget
            # outlasts the short requests so it stays active throughout
            long_prompt_len = args.max_len - args.new_tokens - 1
            eng.submit(rng.integers(0, cfg.vocab_size, long_prompt_len).tolist(),
                       max_new_tokens=args.new_tokens)
            n_short -= 1
        for i in range(n_short):
            plen = mixed[i % len(mixed)] if mixed else args.prompt_len
            tail = max(plen - len(shared), 1)
            prompt = shared + rng.integers(0, cfg.vocab_size, tail).tolist()
            eng.submit(prompt, max_new_tokens=args.new_tokens)

    def produced():
        return sum(len(r.out) for r in eng.running.values()) + sum(
            len(v) for v in eng.done.values()
        )

    # warm passes: drain the full workload passes-1 times so every jit
    # variant it touches is compiled before the timed pass (tails stay
    # random per pass; only the shared prefix repeats, so a paged engine's
    # prefix cache is WARM across passes — that is the serving regime the
    # cache exists for, and prefix_hit_tokens in the output says how much
    # it contributed)
    for _ in range(max(args.passes, 1) - 1):
        submit_workload()
        while eng.step():
            pass
        jax.block_until_ready(eng.tokens)
    hits0 = eng.prefix_hit_tokens if args.kv == "paged" else 0

    submit_workload()
    if args.passes <= 1:
        eng.step()  # single-pass mode: one admission+chunk step of warmup

    tok0 = produced()
    t0 = time.perf_counter()
    while eng.step():
        pass
    jax.block_until_ready(eng.tokens)
    dt = time.perf_counter() - t0
    n_tokens = produced() - tok0
    if n_tokens <= 0:
        sys.exit(f"nothing left to measure after warmup: raise --new-tokens "
                 f"above {1 + eng.decode_chunk} or lower --chunk")

    out = {
        "metric": f"{args.model}_decode_tokens_per_sec_1chip",
        "attn": eng.attn,
        "kv": args.kv,
        "long_slot": bool(args.long_slot),
        **(
            {
                "pages_total": eng.num_pages - 1,
                "prefix_hit_tokens": eng.prefix_hit_tokens - hits0,
            }
            if args.kv == "paged" else {}
        ),
        "passes": args.passes,
        "value": round(n_tokens / dt, 1),
        "unit": "tokens/sec/chip",
        "slots": args.slots,
        "decode_chunk": args.chunk,
        "model_params": cfg.num_params(),
        "int8": bool(args.int8 or args.host_init),  # host-init always quantizes
        "ms_per_token_step": round(1000 * dt / (n_tokens / args.slots), 2),
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
