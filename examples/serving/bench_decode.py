"""Continuous-batching decode throughput (serving path, single chip).

    python examples/serving/bench_decode.py --slots 8 --new-tokens 64 [--int8]

Prints one JSON line with decode tokens/sec (all slots active, steady
state) for the ~0.9B bench Llama. Weight-only int8 (ops/quant.py) halves
the HBM bytes per token-step, which is what bounds batch-decode on TPU.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import jax
import numpy as np

from tony_tpu.models import llama
from tony_tpu.models.serving import ContinuousBatcher


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--new-tokens", type=int, default=64)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--int8", action="store_true")
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument("--preset", default="bench-1b")
    p.add_argument("--host-init", action="store_true",
                   help="init + quantize on the host CPU, then ship to the "
                        "chip — required for models whose bf16 weights don't "
                        "fit HBM before quantization (llama3-8b on one v5e)")
    args = p.parse_args()

    cfg = (
        dataclasses.replace(llama.LLAMA_1B, max_seq=args.max_len)
        if args.preset == "bench-1b" else llama.PRESETS[args.preset]
    )
    if args.host_init:
        from tony_tpu.ops import quant

        cpu = jax.devices("cpu")[0]
        t0 = time.perf_counter()
        with jax.default_device(cpu):
            params = llama.init(jax.random.PRNGKey(0), cfg)
            params, before, after = quant.quantize_tree(params)
            jax.block_until_ready(params)
        print(f"[bench] host init+quant: {before / 1e9:.2f} GB -> "
              f"{after / 1e9:.2f} GB in {time.perf_counter() - t0:.0f}s",
              file=sys.stderr)
        t0 = time.perf_counter()
        params = jax.device_put(params, jax.devices()[0])
        jax.block_until_ready(params)
        print(f"[bench] weights to chip in {time.perf_counter() - t0:.0f}s",
              file=sys.stderr)
    else:
        params = llama.init(jax.random.PRNGKey(0), cfg)
        if args.int8:
            from tony_tpu.ops import quant

            params, before, after = quant.quantize_tree(params)
            print(f"[bench] int8: {before / 1e9:.2f} GB -> {after / 1e9:.2f} GB",
                  file=sys.stderr)

    eng = ContinuousBatcher(
        params, cfg, num_slots=args.slots, max_len=args.max_len,
        decode_chunk=args.chunk,
    )
    rng = np.random.default_rng(0)
    for _ in range(args.slots):
        prompt = rng.integers(0, cfg.vocab_size, args.prompt_len).tolist()
        eng.submit(prompt, max_new_tokens=args.new_tokens)

    # admission (prefills) + decode-chunk compile warmup
    eng.step()

    def produced():
        return sum(len(r.out) for r in eng.running.values()) + sum(
            len(v) for v in eng.done.values()
        )

    tok0 = produced()
    t0 = time.perf_counter()
    while eng.step():
        pass
    jax.block_until_ready(eng.tokens)
    dt = time.perf_counter() - t0
    n_tokens = produced() - tok0
    if n_tokens <= 0:
        sys.exit(f"nothing left to measure after warmup: raise --new-tokens "
                 f"above {1 + eng.decode_chunk} or lower --chunk")

    out = {
        "metric": "llama_decode_tokens_per_sec_1chip",
        "value": round(n_tokens / dt, 1),
        "unit": "tokens/sec/chip",
        "slots": args.slots,
        "decode_chunk": args.chunk,
        "model_params": cfg.num_params(),
        "int8": bool(args.int8 or args.host_init),  # host-init always quantizes
        "ms_per_token_step": round(1000 * dt / (n_tokens / args.slots), 2),
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
