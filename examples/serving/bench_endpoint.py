"""Fixed-occupancy serve-parity A/B (VERDICT r4 #7).

    python examples/serving/bench_endpoint.py --slots 16 --window 45

Measures the HTTP layer's overhead with occupancy and ambient drift
cancelled out, in ONE process/session:

  A.  bare engine, closed loop — a small pending backlog keeps all S
      slots fed; every retirement is refilled before the next step, so
      occupancy is pinned at S.
  B.  HTTP endpoint — EngineServer + ThreadingHTTPServer driven by S
      closed-loop blocking clients, each resubmitting the instant its
      response lands; occupancy pinned at S again.
  A'. bare engine repeated, so ambient drift across the session shows up
      as A vs A' disagreement instead of polluting the B/A ratio.

All three phases decode the same ~0.9B bench Llama with identical slot
count, prompt length, and token budget. Tokens are counted over a timed
steady-state window (after a warmup). The headline is
endpoint / mean(engine, engine2): at equal occupancy this ratio IS the
HTTP layer's overhead (queues + handler threads + JSON + socket writes).

The round-4 session could not produce this number (drifting ambient +
open-loop clients conflated occupancy with overhead; BASELINE.md r4 serve
table) — this driver is the fixed-occupancy design the verdict asked for.
"""

from __future__ import annotations

import argparse
import dataclasses
import http.client
import json
import sys
import threading
import time

import jax
import numpy as np

from tony_tpu.models import llama
from tony_tpu.models.serving import ContinuousBatcher
from tony_tpu.models.serving_http import EngineServer, _Handler
from tony_tpu.cluster.executor import pick_free_port


def _build(cfg, args):
    params = llama.init(jax.random.PRNGKey(0), cfg)
    return ContinuousBatcher(
        params, cfg, num_slots=args.slots, max_len=args.max_len,
        decode_chunk=args.chunk, attn=args.attn, kv=args.kv,
    )


def _prompts(cfg, args, seed=0):
    rng = np.random.default_rng(seed)

    def make():
        return rng.integers(0, cfg.vocab_size, args.prompt_len).tolist()

    return make


def run_engine_phase(cfg, args) -> float:
    """Closed-loop direct drive; returns steady-state tokens/sec."""
    eng = _build(cfg, args)
    make = _prompts(cfg, args)
    backlog = 4  # refill margin: retirements are replaced before admission starves

    def top_up():
        in_flight = len(eng.pending) + len(eng._staged) + len(eng.running)
        for _ in range(max(args.slots + backlog - in_flight, 0)):
            eng.submit(make(), max_new_tokens=args.new_tokens)

    def produced():
        return sum(len(r.out) for r in eng.running.values()) + sum(
            len(v) for v in eng.done.values()
        )

    top_up()
    eng.step()  # prefill + decode-chunk compile warmup
    t_end_warm = time.perf_counter() + args.warmup
    while time.perf_counter() < t_end_warm:
        top_up()
        eng.step()
    # done{} only ever grows in this loop; snapshot-delta excludes warmup
    tok0, t0 = produced(), time.perf_counter()
    t_end = t0 + args.window
    while time.perf_counter() < t_end:
        top_up()
        eng.step()
    jax.block_until_ready(eng.tokens)
    dt = time.perf_counter() - t0
    return (produced() - tok0) / dt


def run_endpoint_phase(cfg, args) -> tuple[float, float]:
    """S closed-loop HTTP clients; returns (generated tok/s, delivered tok/s)."""
    from http.server import ThreadingHTTPServer

    eng = _build(cfg, args)
    srv = EngineServer(eng).start()
    handler = type("H", (_Handler,), {"server_ref": srv, "tokenizer": None})
    port = pick_free_port()
    httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    stop = threading.Event()
    errors: list[str] = []

    def client(seed: int) -> None:
        make = _prompts(cfg, args, seed)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
        body_tmpl = {"max_tokens": args.new_tokens, "stream": False}
        while not stop.is_set():
            body = json.dumps({**body_tmpl, "prompt_tokens": make()})
            try:
                conn.request("POST", "/v1/completions", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                data = resp.read()
                if resp.status != 200:
                    errors.append(f"{resp.status}: {data[:120]!r}")
                    return
            except OSError as e:  # server going down at phase end
                if not stop.is_set():
                    errors.append(repr(e))
                return
        conn.close()

    n_clients = args.clients or args.slots
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n_clients)]
    for t in threads:
        t.start()
    time.sleep(args.warmup + 5.0)  # compile + ramp to full occupancy
    if errors:
        sys.exit(f"endpoint clients failed during warmup: {errors[:3]}")
    s0, t0 = srv.stats(), time.perf_counter()
    time.sleep(args.window)
    s1, t1 = srv.stats(), time.perf_counter()
    stop.set()
    for t in threads:
        t.join(timeout=30)
    srv.stop(timeout_s=60)
    httpd.shutdown()
    if errors:
        sys.exit(f"endpoint clients failed mid-window: {errors[:3]}")
    dt = t1 - t0
    gen = (s1["tokens_out"] - s0["tokens_out"]) / dt
    deliv = (s1["tokens_delivered"] - s0["tokens_delivered"]) / dt
    # occupancy sanity: the ratio is only meaningful if the window ran full
    if s1["slots_active"] < args.slots - 2:
        print(f"[bench] WARNING: only {s1['slots_active']}/{args.slots} slots "
              f"active at window end", file=sys.stderr)
    return gen, deliv


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--slots", type=int, default=16)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--new-tokens", type=int, default=64)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument("--attn", default="auto", choices=["auto", "ragged", "bucketed"])
    p.add_argument("--kv", default="dense", choices=["dense", "paged"])
    p.add_argument("--warmup", type=float, default=10.0)
    p.add_argument("--window", type=float, default=45.0)
    p.add_argument("--clients", type=int, default=0,
                   help="closed-loop client count (0 = --slots). slots+2 "
                        "probes whether the resubmission roundtrip gap "
                        "(the only occupancy difference vs phase A) matters")
    p.add_argument("--preset", default="bench-1b", choices=["bench-1b", "tiny"],
                   help="tiny: 4-layer toy model (mechanics smoke on CPU)")
    args = p.parse_args()

    if args.preset == "tiny":
        cfg = llama.LlamaConfig(
            vocab_size=256, d_model=128, n_layers=4, n_heads=4, n_kv_heads=2,
            d_ff=256, max_seq=args.max_len,
        )
    else:
        cfg = dataclasses.replace(llama.LLAMA_1B, max_seq=args.max_len)

    print("[bench] phase A: bare engine, closed loop", file=sys.stderr)
    eng1 = run_engine_phase(cfg, args)
    print(f"[bench]   engine: {eng1:.1f} tok/s", file=sys.stderr)
    print("[bench] phase B: HTTP endpoint, closed-loop clients", file=sys.stderr)
    ep_gen, ep_deliv = run_endpoint_phase(cfg, args)
    print(f"[bench]   endpoint: {ep_gen:.1f} generated, "
          f"{ep_deliv:.1f} delivered tok/s", file=sys.stderr)
    print("[bench] phase A': bare engine again (ambient check)", file=sys.stderr)
    eng2 = run_engine_phase(cfg, args)
    print(f"[bench]   engine: {eng2:.1f} tok/s", file=sys.stderr)

    mean_eng = (eng1 + eng2) / 2
    out = {
        "metric": "serve_endpoint_vs_engine_fixed_occupancy",
        "engine_tok_s": round(eng1, 1),
        "engine2_tok_s": round(eng2, 1),
        "endpoint_tok_s": round(ep_gen, 1),
        "endpoint_delivered_tok_s": round(ep_deliv, 1),
        "value": round(ep_gen / mean_eng, 4),
        "unit": "endpoint/engine throughput ratio at equal occupancy",
        "ambient_drift": round(abs(eng1 - eng2) / mean_eng, 4),
        "slots": args.slots,
        "clients": args.clients or args.slots,
        "window_s": args.window,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
