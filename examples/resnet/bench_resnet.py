"""ResNet-50 training throughput (BASELINE config #3).

    python examples/resnet/bench_resnet.py --batch 512 --steps 10

Prints one JSON line with images/sec/chip and model-flops utilization
(ResNet-50 fwd ~4.1 GFLOP @ 224^2; training ~3x).
"""

import argparse
import json
import sys

import jax
import optax

from tony_tpu.models import resnet
from tony_tpu.train.metrics import detect_peak_flops
from tony_tpu.train.trainer import Throughput

FWD_GFLOP_PER_IMAGE = 4.1


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=512)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--preset", default="resnet50")
    args = p.parse_args()

    cfg = resnet.PRESETS[args.preset]
    key = jax.random.PRNGKey(0)
    params, bn_state = resnet.init(key, cfg)
    batch = resnet.synthetic_batch(key, args.batch, cfg)
    batch["bn_state"] = bn_state
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        def lf(p):
            return resnet.loss_fn(p, batch, cfg)

        (loss, aux), grads = jax.value_and_grad(lf, has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, aux["bn_state"]

    for _ in range(max(args.warmup, 2)):  # step 2 hits the donated-buffer recompile
        params, opt_state, loss, batch["bn_state"] = step(params, opt_state, batch)
        float(loss)  # per-step host sync (honest timing on async backends)

    # the shared meter — same timing/MFU methodology as bench.py, with
    # "tokens" = images and flops/token = training flops per image
    meter = Throughput(
        tokens_per_step=args.batch,
        flops_per_token=int(3 * FWD_GFLOP_PER_IMAGE * 1e9),
        n_chips=1,
        peak_flops=detect_peak_flops(),
    )
    meter.start()
    for _ in range(args.steps):
        params, opt_state, loss, batch["bn_state"] = step(params, opt_state, batch)
        float(loss)
        meter.step()
    r = meter.report()
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_1chip",
        "value": round(r["tokens_per_sec"], 1),
        "unit": "images/sec/chip",
        "step_time_ms": round(r["step_time_ms"], 1),
        "batch": args.batch,
        "mfu": round(r["mfu"], 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
