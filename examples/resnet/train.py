"""ResNet image-classification training (BASELINE.json config #3 analog)."""
import functools
import sys

import jax

from tony_tpu.models import resnet
from tony_tpu.runtime import init_distributed
from tony_tpu.train import OptimizerConfig, TrainState, make_train_step
from tony_tpu.train.loop import parse_loop_args


def main() -> int:
    init_distributed()
    loop, extra = parse_loop_args()
    cfg = resnet.config_from_dict(extra["preset"])
    opt = OptimizerConfig(learning_rate=loop.learning_rate, warmup_steps=loop.warmup_steps,
                          total_steps=loop.steps).build()
    params, bn_state = resnet.init(jax.random.PRNGKey(0), cfg)
    state = TrainState.create(params, opt)
    step = make_train_step(functools.partial(resnet.loss_fn, cfg=cfg), opt)
    key = jax.random.PRNGKey(1)
    for i in range(loop.steps):
        batch = resnet.synthetic_batch(jax.random.fold_in(key, i), loop.batch_size, cfg)
        batch["bn_state"] = bn_state
        state, m = step(state, batch)
        bn_state = m.pop("bn_state", bn_state)
        if (i + 1) % loop.log_every == 0:
            print(f"step {i+1} loss={float(m['loss']):.4f} acc={float(m['accuracy']):.3f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
