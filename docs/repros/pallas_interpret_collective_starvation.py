"""Repro: Pallas TPU-interpret collective kernels wedge at n-of-n devices.

tony-tpu's remote-DMA ring attention kernel (tony_tpu/ops/ring.py) under
shard_map over ALL virtual CPU devices deadlocks in interpret mode when the
mesh occupies every device in the process and per-shard work spans multiple
tiles; the IDENTICAL program over n of 2n devices completes. Observed on
single-core hosts (nproc=1) with jax 0.8.x — the interpret emulation
appears to starve for executor threads when every device in the process is
simultaneously parked inside one collective kernel.

    python pallas_interpret_collective_starvation.py 8 16   # passes
    timeout 300 python pallas_interpret_collective_starvation.py 8 8  # wedges

Because of this, the 8-way ring parity test runs in a subprocess with spare
devices (tests/test_ring_pallas.py::
test_pallas_ring_backward_eight_devices_multi_tile) — this file is the
linked standalone demonstration that the wedge tracks the device/mesh
ratio, not the kernel protocol (which passes every parity test at 4-of-8
and 8-of-16, race detection on).
"""

import functools
import os
import sys

MESH_N = int(sys.argv[1]) if len(sys.argv) > 1 else 8
DEVICES = int(sys.argv[2]) if len(sys.argv) > 2 else 2 * MESH_N

# force the CPU platform + virtual device count BEFORE the backend
# initializes (robust against site hooks that pre-import jax)
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={DEVICES}"
    ).strip()

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu.ops.ring import ring_attention_pallas


def main() -> None:
    from jax.experimental.pallas import tpu as pltpu

    devs = jax.devices()
    print(f"devices={len(devs)} mesh={MESH_N} "
          f"({'n-of-n: expect WEDGE' if len(devs) == MESH_N else 'spare devices: expect OK'})",
          flush=True)
    mesh = Mesh(np.array(devs[:MESH_N]), ("context",))
    B, H, Hkv, D = 1, 4, 2, 64
    T = MESH_N * 256  # 256-row shards → multiple tiles per device
    ks = [jax.random.fold_in(jax.random.PRNGKey(3), i) for i in range(3)]
    q = jax.random.normal(ks[0], (B, H, T, D), jnp.float32) * 0.5
    k = jax.random.normal(ks[1], (B, Hkv, T, D), jnp.float32) * 0.5
    v = jax.random.normal(ks[2], (B, Hkv, T, D), jnp.float32) * 0.5
    spec = P(None, None, "context", None)
    ring = jax.jit(
        jax.shard_map(
            functools.partial(
                ring_attention_pallas, axis_name="context", causal=True,
                interpret=pltpu.InterpretParams(detect_races=True),
            ),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={"context"}, check_vma=False,
        )
    )
    out = ring(q, k, v)
    jax.block_until_ready(out)
    print("OK", float(jnp.abs(out).sum()), flush=True)


if __name__ == "__main__":
    main()
