// tonyio: native data-plane loader for tokenized training shards.
//
// The reference delegated its data plane to the user's ML framework
// (tf.data / torch DataLoader inside the user process — SURVEY.md §2.4);
// here the framework owns it: a C++ loader that mmaps token shards, samples
// fixed-length sequences (shuffled, sharded across data-parallel workers),
// and fills pinned host batch buffers from a background prefetch thread so
// the TPU step never waits on the host.
//
// Shard format ("TONYTOK1"): 8-byte magic, u32 dtype (0=u16, 1=i32),
// u64 token count, then the flat token stream. Written by
// tony_tpu/data/dataset.py, which also carries the Python fallback reader.
//
// C ABI (ctypes-friendly): every function returns 0 on success or a negative
// errno-style code; the loader handle is an opaque pointer.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'T', 'O', 'N', 'Y', 'T', 'O', 'K', '1'};
constexpr int kErrIO = -1;
constexpr int kErrFormat = -2;
constexpr int kErrArg = -3;
constexpr int kErrStopped = -4;

struct Shard {
  void* map = nullptr;
  size_t map_len = 0;
  const uint8_t* tokens = nullptr;  // past the header
  uint64_t count = 0;               // number of tokens
  uint32_t dtype = 0;               // 0=u16, 1=i32

  int64_t token_at(uint64_t i) const {
    if (dtype == 0) {
      uint16_t v;
      std::memcpy(&v, tokens + i * 2, 2);
      return v;
    }
    int32_t v;
    std::memcpy(&v, tokens + i * 4, 4);
    return v;
  }
};

struct Batch {
  std::vector<int32_t> data;  // [batch, seq+1] int32 (inputs+shifted targets)
  uint64_t index = 0;
};

struct Loader {
  std::vector<Shard> shards;
  uint64_t total_tokens = 0;
  // sampling plan
  uint32_t batch = 0, seq = 0;
  uint32_t shard_id = 0, num_shards = 1;  // data-parallel split
  uint64_t seed = 0;
  uint64_t num_windows = 0;  // usable (seq+1)-token windows across shards
  // prefetch machinery
  std::deque<Batch> ready;
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::vector<std::thread> workers;
  std::atomic<uint64_t> next_index{0};
  uint64_t next_consume = 0;  // guarded by mu: index the consumer must get next
  std::atomic<bool> stop{false};
  uint32_t prefetch_depth = 4;

  ~Loader() {
    stop.store(true);
    cv_space.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    for (auto& s : shards)
      if (s.map) munmap(s.map, s.map_len);
  }

  // Map a global window id -> (shard, offset) and copy seq+1 tokens.
  void fill_sequence(uint64_t window, int32_t* out) const {
    const uint64_t stride = seq + 1;
    uint64_t w = window;
    for (const auto& s : shards) {
      const uint64_t here = s.count / stride;
      if (w < here) {
        const uint64_t base = w * stride;
        for (uint64_t i = 0; i < stride; ++i) out[i] = (int32_t)s.token_at(base + i);
        return;
      }
      w -= here;
    }
    std::memset(out, 0, stride * sizeof(int32_t));  // unreachable when window < num_windows
  }

  // Deterministic shuffle: batch b draws windows via a splitmix-style hash of
  // (seed, epoch, slot) — no epoch-wide permutation array, O(1) memory.
  static uint64_t mix(uint64_t x) {
    x += 0x9e3779b97f4a7c15ull;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
    return x ^ (x >> 31);
  }

  Batch make_batch(uint64_t index) const {
    Batch b;
    b.index = index;
    b.data.resize((size_t)batch * (seq + 1));
    // GLOBAL-ORDER draw (elastic-replay contract, mirrored in the Python
    // fallback): the stream is one global sequence of samples keyed by
    // (seed, global slot); shard k of K owns rows [k*batch, (k+1)*batch)
    // of each global batch of G = batch*num_shards rows. Resharding K->K'
    // replays exactly as long as G is held constant, because the slots a
    // resumed run consumes are the same regardless of how they re-split.
    const uint64_t gbatch = (uint64_t)batch * num_shards;
    for (uint32_t i = 0; i < batch; ++i) {
      const uint64_t g = index * gbatch + (uint64_t)shard_id * batch + i;
      const uint64_t epoch = g / num_windows;
      const uint64_t pos = g % num_windows;
      // epoch goes through its own mix round so (epoch, pos) keys can't
      // alias linearly across epochs for any num_windows
      const uint64_t r = mix(mix(seed ^ mix(epoch)) ^ pos);
      fill_sequence(r % num_windows, b.data.data() + (size_t)i * (seq + 1));
    }
    return b;
  }

  void worker_loop() {
    while (!stop.load()) {
      const uint64_t idx = next_index.fetch_add(1);
      Batch b = make_batch(idx);
      std::unique_lock<std::mutex> lk(mu);
      // The batch the consumer is waiting on is always admitted even when
      // the deque is at depth — otherwise a full deque of later indices
      // would deadlock against the in-order consumer below.
      cv_space.wait(lk, [&] {
        return stop.load() || ready.size() < prefetch_depth || b.index == next_consume;
      });
      if (stop.load()) return;
      // keep batches ordered by index; the consumer pops strictly in order
      auto it = ready.begin();
      while (it != ready.end() && it->index < b.index) ++it;
      ready.insert(it, std::move(b));
      cv_ready.notify_all();
    }
  }
};

int map_shard(const char* path, Shard* out) {
  const int fd = open(path, O_RDONLY);
  if (fd < 0) return kErrIO;
  struct stat st;
  if (fstat(fd, &st) != 0 || (size_t)st.st_size < 20) {
    close(fd);
    return kErrFormat;
  }
  void* m = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (m == MAP_FAILED) return kErrIO;
  const uint8_t* p = (const uint8_t*)m;
  if (std::memcmp(p, kMagic, 8) != 0) {
    munmap(m, st.st_size);
    return kErrFormat;
  }
  Shard s;
  s.map = m;
  s.map_len = st.st_size;
  std::memcpy(&s.dtype, p + 8, 4);
  std::memcpy(&s.count, p + 12, 8);
  s.tokens = p + 20;
  // divide instead of multiply: `count * width` can wrap for a corrupt
  // header, which would pass the size check and read past the mapping
  const uint64_t width = (s.dtype == 0 ? 2 : 4);
  if (s.dtype > 1 || s.count > (uint64_t)(s.map_len - 20) / width) {
    munmap(m, st.st_size);
    return kErrFormat;
  }
  madvise(m, st.st_size, MADV_WILLNEED);
  *out = s;
  return 0;
}

}  // namespace

extern "C" {

// paths: NUL-separated, double-NUL-terminated list of shard files.
// start_index: first GLOBAL batch index to produce — the draw is a pure
// function of (seed, global slot), so resuming a run at step K with
// start_index=K replays the exact uninterrupted stream (no repeated, no
// skipped samples), even across a shard-count change as long as the global
// batch (batch * num_shards) is held constant.
int tony_loader_open_at(const char* paths, uint32_t batch, uint32_t seq,
                        uint32_t shard_id, uint32_t num_shards, uint64_t seed,
                        uint32_t prefetch_depth, uint32_t num_threads,
                        uint64_t start_index, void** out) {
  if (!paths || !out || batch == 0 || seq == 0 || num_shards == 0 || shard_id >= num_shards)
    return kErrArg;
  auto ld = new Loader();
  ld->next_index = start_index;
  ld->next_consume = start_index;
  ld->batch = batch;
  ld->seq = seq;
  ld->shard_id = shard_id;
  ld->num_shards = num_shards;
  ld->seed = seed;
  ld->prefetch_depth = prefetch_depth ? prefetch_depth : 4;
  for (const char* p = paths; *p;) {
    Shard s;
    const int rc = map_shard(p, &s);
    if (rc != 0) {
      delete ld;
      return rc;
    }
    ld->shards.push_back(s);
    ld->total_tokens += s.count;
    ld->num_windows += s.count / (seq + 1);
    p += std::strlen(p) + 1;
  }
  if (ld->num_windows < 1) {
    delete ld;
    return kErrFormat;  // not enough data for a single window
  }
  const uint32_t n = num_threads ? num_threads : 2;
  for (uint32_t i = 0; i < n; ++i) ld->workers.emplace_back([ld] { ld->worker_loop(); });
  *out = ld;
  return 0;
}

int tony_loader_open(const char* paths, uint32_t batch, uint32_t seq,
                     uint32_t shard_id, uint32_t num_shards, uint64_t seed,
                     uint32_t prefetch_depth, uint32_t num_threads, void** out) {
  return tony_loader_open_at(paths, batch, seq, shard_id, num_shards, seed,
                             prefetch_depth, num_threads, 0, out);
}

// Blocks until the next *sequential* batch is ready; copies [batch, seq+1]
// int32 into out. Strict index order keeps the stream deterministic (and
// identical to the single-threaded Python fallback) regardless of how many
// prefetch threads race on production.
int tony_loader_next(void* handle, int32_t* out, uint64_t* out_index) {
  if (!handle || !out) return kErrArg;
  auto ld = (Loader*)handle;
  std::unique_lock<std::mutex> lk(ld->mu);
  ld->cv_ready.wait(lk, [&] {
    return ld->stop.load() ||
           (!ld->ready.empty() && ld->ready.front().index == ld->next_consume);
  });
  if (ld->stop.load()) return kErrStopped;
  Batch b = std::move(ld->ready.front());
  ld->ready.pop_front();
  ld->next_consume = b.index + 1;
  lk.unlock();
  ld->cv_space.notify_all();
  std::memcpy(out, b.data.data(), b.data.size() * sizeof(int32_t));
  if (out_index) *out_index = b.index;
  return 0;
}

uint64_t tony_loader_total_tokens(void* handle) {
  return handle ? ((Loader*)handle)->total_tokens : 0;
}

uint64_t tony_loader_num_windows(void* handle) {
  return handle ? ((Loader*)handle)->num_windows : 0;
}

void tony_loader_close(void* handle) {
  delete (Loader*)handle;
}

}  // extern "C"
