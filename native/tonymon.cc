// tonymon: low-overhead host metrics sampling for executor heartbeats.
//
// The reference sampled per-container CPU/mem (and forked nvidia-smi for GPU)
// from the Java executor (SURVEY.md §2.1 "GPU metrics"); the TPU rebuild keeps
// device metrics on the PJRT side (Python) and does the host side natively:
// one syscall-cheap snapshot of /proc counters per heartbeat, delta-ed in C++
// so the Python thread never parses /proc under the GIL.

#include <cstdint>
#include <cstdio>
#include <cstring>

#include <unistd.h>

namespace {

struct CpuTimes {
  uint64_t total = 0, idle = 0;
};

bool read_cpu(CpuTimes* out) {
  FILE* f = std::fopen("/proc/stat", "re");
  if (!f) return false;
  char line[512];
  bool ok = false;
  if (std::fgets(line, sizeof line, f)) {
    uint64_t v[8] = {0};
    if (std::sscanf(line, "cpu %lu %lu %lu %lu %lu %lu %lu %lu", &v[0], &v[1],
                    &v[2], &v[3], &v[4], &v[5], &v[6], &v[7]) >= 4) {
      out->idle = v[3] + v[4];  // idle + iowait
      out->total = 0;
      for (uint64_t x : v) out->total += x;
      ok = true;
    }
  }
  std::fclose(f);
  return ok;
}

uint64_t meminfo_kb(const char* key) {
  FILE* f = std::fopen("/proc/meminfo", "re");
  if (!f) return 0;
  char line[256];
  uint64_t val = 0;
  const size_t klen = std::strlen(key);
  while (std::fgets(line, sizeof line, f)) {
    if (std::strncmp(line, key, klen) == 0 && line[klen] == ':') {
      std::sscanf(line + klen + 1, "%lu", &val);
      break;
    }
  }
  std::fclose(f);
  return val;
}

uint64_t self_rss_kb() {
  FILE* f = std::fopen("/proc/self/statm", "re");
  if (!f) return 0;
  uint64_t size = 0, rss = 0;
  const int n = std::fscanf(f, "%lu %lu", &size, &rss);
  std::fclose(f);
  return n == 2 ? rss * (uint64_t)(sysconf(_SC_PAGESIZE) / 1024) : 0;
}

CpuTimes g_last;  // per-process sampler state (one executor per process)

}  // namespace

extern "C" {

// Fills out[0..4] = {cpu_util_pct, mem_used_pct, mem_total_mb, rss_mb, ncpus}.
// cpu_util is the delta since the previous call (first call returns 0).
int tony_mon_sample(double* out) {
  if (!out) return -3;
  CpuTimes now;
  if (!read_cpu(&now)) return -1;
  double util = 0.0;
  if (g_last.total && now.total > g_last.total) {
    const double dt = (double)(now.total - g_last.total);
    const double di = (double)(now.idle - g_last.idle);
    util = 100.0 * (1.0 - di / dt);
  }
  g_last = now;
  const uint64_t total_kb = meminfo_kb("MemTotal");
  const uint64_t avail_kb = meminfo_kb("MemAvailable");
  out[0] = util;
  out[1] = total_kb ? 100.0 * (1.0 - (double)avail_kb / (double)total_kb) : 0.0;
  out[2] = (double)total_kb / 1024.0;
  out[3] = (double)self_rss_kb() / 1024.0;
  out[4] = (double)sysconf(_SC_NPROCESSORS_ONLN);
  return 0;
}

}  // extern "C"
