"""Model family tests: forward shapes, training convergence, and sharded-vs-
single-device numerical parity (the guarantee that the parallelism rules are
semantics-preserving)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import bert, llama, mixtral, mlp, resnet
from tony_tpu.parallel import MeshSpec
from tony_tpu.train import OptimizerConfig, TrainState, make_train_step, sharded_init

KEY = jax.random.PRNGKey(0)


def quick_opt(lr=1e-2):
    return OptimizerConfig(learning_rate=lr, warmup_steps=0, total_steps=20, weight_decay=0.0).build()


class TestLlama:
    cfg = llama.LLAMA_TINY

    def test_forward_shape_dtype(self):
        params = llama.init(KEY, self.cfg)
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = llama.forward(params, tokens, self.cfg)
        assert logits.shape == (2, 16, self.cfg.vocab_size)
        assert logits.dtype == jnp.bfloat16

    def test_param_count_formula(self):
        params = llama.init(KEY, self.cfg)
        actual = sum(p.size for p in jax.tree.leaves(params))
        assert actual == self.cfg.num_params()

    @pytest.mark.slow  # ~17 s flash-kernel remat grad comparison
    def test_flash_remat_policy_grads_match_full(self):
        # remat_policy="flash" pins the named flash-kernel outputs; grads must
        # equal plain full remat (kernels run via the Pallas interpreter on CPU)
        import dataclasses as dc
        import numpy as np

        base = dc.replace(self.cfg, remat=True, attn_impl="flash", max_seq=128)
        params = llama.init(KEY, base)
        batch = llama.synthetic_batch(KEY, 2, 128, base)

        def loss_with(policy):
            cfg = dc.replace(base, remat_policy=policy)
            return jax.grad(lambda p: llama.loss_fn(p, batch, cfg)[0])(params)

        g_full, g_flash = loss_with("full"), loss_with("flash")
        for a, b in zip(jax.tree.leaves(g_full), jax.tree.leaves(g_flash)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=2e-2, atol=1e-4
            )

    def test_loss_decreases(self):
        params = llama.init(KEY, self.cfg)
        opt = quick_opt()
        state = TrainState.create(params, opt)
        step = make_train_step(functools.partial(llama.loss_fn, cfg=self.cfg), opt)
        batch = llama.synthetic_batch(KEY, 4, 32, self.cfg)
        losses = []
        for _ in range(5):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] - 0.5, losses

    def test_sharded_loss_matches_single_device(self):
        params = llama.init(KEY, self.cfg)
        batch = llama.synthetic_batch(KEY, 4, 32, self.cfg)
        want, _ = llama.loss_fn(params, batch, self.cfg)

        for spec in (MeshSpec(data=2, fsdp=2, model=2), MeshSpec(context=4, model=2)):
            mesh = spec.build()
            sharded = jax.device_put(
                params, llama.sharding_rules(self.cfg).sharding_tree(params, mesh)
            )
            got, _ = jax.jit(functools.partial(llama.loss_fn, cfg=self.cfg, mesh=mesh))(
                sharded, batch
            )
            assert abs(float(got) - float(want)) < 0.05, (spec, float(got), float(want))

    def test_ulysses_cp_matches_single_device(self):
        import dataclasses as dc

        cfg = dc.replace(self.cfg, cp_impl="ulysses")
        params = llama.init(KEY, cfg)
        batch = llama.synthetic_batch(KEY, 4, 32, cfg)
        want, _ = llama.loss_fn(params, batch, self.cfg)
        mesh = MeshSpec(context=4, model=2).build()
        sharded = jax.device_put(
            params, llama.sharding_rules(cfg).sharding_tree(params, mesh)
        )
        got, _ = jax.jit(functools.partial(llama.loss_fn, cfg=cfg, mesh=mesh))(
            sharded, batch
        )
        assert abs(float(got) - float(want)) < 0.05

    def test_ulysses_cp_narrow_kv_gqa(self):
        # n_kv_heads % context == 0: KV must stay at Hkv width through the
        # all-to-all (no repeat_kv fallback) and still match single-device
        import dataclasses as dc

        cfg = dc.replace(self.cfg, cp_impl="ulysses", n_heads=4, n_kv_heads=2)
        params = llama.init(KEY, cfg)
        batch = llama.synthetic_batch(KEY, 4, 32, cfg)
        want, _ = llama.loss_fn(params, batch, dc.replace(cfg, cp_impl="xla"))
        mesh = MeshSpec(context=2, data=4).build()
        sharded = jax.device_put(
            params, llama.sharding_rules(cfg).sharding_tree(params, mesh)
        )
        got, _ = jax.jit(functools.partial(llama.loss_fn, cfg=cfg, mesh=mesh))(
            sharded, batch
        )
        assert abs(float(got) - float(want)) < 0.05

    def test_ulysses_cp_head_divisibility_validated(self):
        import dataclasses as dc

        cfg = dc.replace(self.cfg, cp_impl="ulysses", n_heads=3, n_kv_heads=3)
        mesh = MeshSpec(context=2, data=4).build()
        with pytest.raises(ValueError, match="divisible"):
            llama._attention(
                jnp.zeros((1, 3, 8, 4)), jnp.zeros((1, 3, 8, 4)),
                jnp.zeros((1, 3, 8, 4)), cfg, mesh,
            )

    def test_pipeline_loss_matches_flat(self):
        # llama's own PP path: stage-split layer stack + GPipe microbatches
        # must reproduce the flat scan's loss AND gradients
        params = llama.init(KEY, self.cfg)
        batch = llama.synthetic_batch(KEY, 4, 32, self.cfg)
        mesh = MeshSpec(stage=2, data=4).build()

        want, gw = jax.value_and_grad(
            lambda p: llama.loss_fn(p, batch, self.cfg)[0]
        )(params)
        got, gg = jax.jit(jax.value_and_grad(
            lambda p: llama.pp_loss_fn(p, batch, self.cfg, mesh, num_microbatches=2)[0]
        ))(params)
        assert abs(float(got) - float(want)) < 0.05
        for a, b in zip(jax.tree.leaves(gg), jax.tree.leaves(gw)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=5e-2, rtol=5e-2,
            )

    @pytest.mark.slow
    def test_grad_accumulation_matches_full_batch(self):
        cfg = self.cfg
        params = llama.init(KEY, cfg)
        opt = quick_opt(lr=1e-3)
        batch = llama.synthetic_batch(KEY, 8, 16, cfg)
        # independent buffer copies: train_step donates its input state
        s1 = TrainState.create(jax.tree.map(jnp.copy, params), opt)
        s2 = TrainState.create(jax.tree.map(jnp.copy, params), opt)
        step1 = make_train_step(functools.partial(llama.loss_fn, cfg=cfg), opt, accum_steps=1)
        step4 = make_train_step(functools.partial(llama.loss_fn, cfg=cfg), opt, accum_steps=4)
        s1, m1 = step1(s1, batch)
        s2, m4 = step4(s2, batch)
        # same data → same mean loss and near-identical updated params
        assert abs(float(m1["loss"]) - float(m4["loss"])) < 0.02
        diffs = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))),
            s1.params, s2.params,
        )
        assert max(jax.tree.leaves(diffs)) < 0.02


class TestMixtral:
    cfg = mixtral.MIXTRAL_TINY

    def test_forward_and_aux(self):
        params = mixtral.init(KEY, self.cfg)
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits, aux = mixtral.forward(params, tokens, self.cfg)
        assert logits.shape == (2, 16, self.cfg.vocab_size)
        assert {"moe_balance_loss", "moe_z_loss", "moe_dropped_frac"} <= set(aux)

    def test_param_count_formula(self):
        params = mixtral.init(KEY, self.cfg)
        actual = sum(p.size for p in jax.tree.leaves(params))
        assert actual == self.cfg.num_params()
        assert self.cfg.active_params() < self.cfg.num_params()

    def test_expert_parallel_matches_single_device(self):
        params = mixtral.init(KEY, self.cfg)
        batch = mixtral.synthetic_batch(KEY, 4, 32, self.cfg)
        want, _ = mixtral.loss_fn(params, batch, self.cfg)
        mesh = MeshSpec(data=2, expert=4).build()
        sharded = jax.device_put(
            params, mixtral.sharding_rules(self.cfg).sharding_tree(params, mesh)
        )
        got, _ = jax.jit(functools.partial(mixtral.loss_fn, cfg=self.cfg, mesh=mesh))(
            sharded, batch
        )
        assert abs(float(got) - float(want)) < 0.05

    @pytest.mark.slow
    def test_train_step(self):
        opt = quick_opt()
        mesh = MeshSpec(data=2, expert=4).build()
        state = sharded_init(
            lambda: mixtral.init(KEY, self.cfg), mixtral.sharding_rules(self.cfg), mesh, opt
        )
        step = make_train_step(functools.partial(mixtral.loss_fn, cfg=self.cfg, mesh=mesh), opt)
        batch = mixtral.synthetic_batch(KEY, 4, 32, self.cfg)
        state, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))


class TestBert:
    cfg = bert.BERT_TINY

    @pytest.mark.slow
    def test_mlm_loss_and_convergence(self):
        params = bert.init(KEY, self.cfg)
        opt = quick_opt()
        state = TrainState.create(params, opt)
        step = make_train_step(functools.partial(bert.loss_fn, cfg=self.cfg), opt)
        batch = bert.synthetic_batch(KEY, 4, 32, self.cfg)
        losses = []
        for _ in range(5):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_gathered_head_matches_dense(self):
        # the gathered-positions MLM loss must equal the full-logits loss
        # over the same mask
        import jax.numpy as jnp

        params = bert.init(KEY, self.cfg)
        batch = bert.synthetic_batch(KEY, 4, 32, self.cfg)
        dense = {
            "tokens": batch["tokens"],
            "targets": jnp.full_like(batch["tokens"], -100)
            .at[jnp.arange(4)[:, None], batch["masked_pos"]]
            .set(batch["masked_targets"]),
        }
        got, _ = bert.loss_fn(params, batch, self.cfg)
        want, _ = bert.loss_fn(params, dense, self.cfg)
        assert abs(float(got) - float(want)) < 1e-3

    def test_sharded_matches(self):
        params = bert.init(KEY, self.cfg)
        batch = bert.synthetic_batch(KEY, 4, 32, self.cfg)
        want, _ = bert.loss_fn(params, batch, self.cfg)
        mesh = MeshSpec(data=2, fsdp=2, model=2).build()
        sharded = jax.device_put(params, bert.sharding_rules(self.cfg).sharding_tree(params, mesh))
        got, _ = jax.jit(functools.partial(bert.loss_fn, cfg=self.cfg, mesh=mesh))(sharded, batch)
        assert abs(float(got) - float(want)) < 0.05

    def test_packed_matches_separate_rows(self):
        """A packed two-doc row (segment confinement + restarting positions)
        must reproduce the per-position MLM loss of the same docs in their
        own rows — proves no cross-document attention leakage in the
        bidirectional encoder."""
        import jax.numpy as jnp

        params = bert.init(KEY, self.cfg)
        T = 32
        t1 = jax.random.randint(KEY, (1, 20), 0, self.cfg.vocab_size, jnp.int32)
        t2 = jax.random.randint(jax.random.PRNGKey(7), (1, 12), 0, self.cfg.vocab_size, jnp.int32)
        packed_tok = jnp.concatenate([t1, t2], axis=1)                   # [1, 32]
        packed_seg = jnp.concatenate(
            [jnp.full((1, 20), 1, jnp.int32), jnp.full((1, 12), 2, jnp.int32)], axis=1
        )
        # mask two positions in each doc
        pos = jnp.array([[3, 11, 22, 27]], jnp.int32)                    # 22,27 → doc2 pos 2,7
        batch_packed = {
            "tokens": packed_tok, "segment_ids": packed_seg,
            "masked_pos": pos,
            "masked_targets": jnp.take_along_axis(packed_tok, pos, axis=1),
        }
        got, m = bert.loss_fn(params, batch_packed, self.cfg)

        def solo(tok, mask_pos):
            b = {
                "tokens": tok,
                "masked_pos": mask_pos,
                "masked_targets": jnp.take_along_axis(tok, mask_pos, axis=1),
            }
            return bert.loss_fn(params, b, self.cfg)[0]

        want = 0.5 * (
            float(solo(t1, jnp.array([[3, 11]], jnp.int32)))
            + float(solo(t2, jnp.array([[2, 7]], jnp.int32)))
        )
        assert abs(float(got) - want) < 2e-3, (float(got), want)


class TestResNet:
    def test_stem_s2d_matches_plain_conv(self):
        import jax.numpy as jnp

        img = jax.random.normal(KEY, (2, 64, 64, 3), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (7, 7, 3, 16), jnp.float32) * 0.1
        from tony_tpu.models import resnet as R

        np.testing.assert_allclose(
            np.asarray(R._stem_conv_s2d(img, w)),
            np.asarray(R._conv(img, w, 2)),
            atol=1e-4, rtol=1e-4,
        )

    cfg = resnet.RESNET_TINY

    def test_forward_and_bn_state(self):
        params, state = resnet.init(KEY, self.cfg)
        batch = resnet.synthetic_batch(KEY, 4, self.cfg)
        logits, new_state = resnet.forward(params, state, batch["image"], self.cfg)
        assert logits.shape == (4, self.cfg.num_classes)
        # running stats moved off init values
        stem = new_state["stem"]["bn"]
        assert float(jnp.abs(stem["mean"]).sum()) > 0

    @pytest.mark.slow
    def test_loss_decreases(self):
        params, bn_state = resnet.init(KEY, self.cfg)
        opt = quick_opt()
        state = TrainState.create(params, opt)
        batch = resnet.synthetic_batch(KEY, 8, self.cfg)
        batch["bn_state"] = bn_state
        step = make_train_step(functools.partial(resnet.loss_fn, cfg=self.cfg), opt)
        losses = []
        for _ in range(4):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]


class TestMLP:
    cfg = mlp.MLPConfig(input_dim=16, hidden_dim=32, num_classes=4)

    def test_memorizes_small_batch(self):
        params = mlp.init(KEY, self.cfg)
        opt = quick_opt(lr=5e-2)
        state = TrainState.create(params, opt)
        step = make_train_step(functools.partial(mlp.loss_fn, cfg=self.cfg), opt)
        batch = mlp.synthetic_batch(KEY, 16, self.cfg)
        for _ in range(30):
            state, m = step(state, batch)
        assert float(m["accuracy"]) > 0.9


class TestFullScaleConfigsSymbolic:
    """BASELINE configs at REAL scale, validated symbolically (eval_shape —
    no memory): param counts, sharding-rule coverage, and the train-step
    output structure for Llama-3-8B and Mixtral-8x7B."""

    def test_llama3_8b_structure(self):
        cfg = llama.LLAMA3_8B
        shapes = jax.eval_shape(lambda: llama.init(jax.random.PRNGKey(0), cfg))
        n = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
        assert n == cfg.num_params()
        assert 7.9e9 < n < 8.1e9, n  # the 8B config really is 8B
        # every LARGE leaf must be actually sharded (a replicated 8B matmul
        # would silently blow per-chip HBM on a slice) — spec_for defaults
        # to replicate, so check for a non-empty PartitionSpec explicitly
        from tony_tpu.parallel.sharding import path_str

        rules = llama.sharding_rules(cfg)
        flat = jax.tree_util.tree_flatten_with_path(shapes)[0]
        for path, leaf in flat:
            if int(np.prod(leaf.shape)) < 1 << 20:
                continue  # norms etc. may replicate
            spec = rules.spec_for(path_str(path))  # same renderer production uses
            assert any(ax is not None for ax in spec), (path, spec)

    def test_mixtral_8x7b_structure(self):
        cfg = mixtral.MIXTRAL_8X7B
        shapes = jax.eval_shape(lambda: mixtral.init(jax.random.PRNGKey(0), cfg))
        n = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
        assert n == cfg.num_params()
        assert 44e9 < n < 49e9, n          # 8x7B ≈ 46.7B total
        assert 11e9 < cfg.active_params() < 14e9  # ~12.9B active (top-2)


class TestSequencePacking:
    """Packed batches (segment_ids) must train exactly like the equivalent
    unpacked batch: same per-token loss mass, segment-confined attention,
    restarting RoPE positions, masked boundary targets."""

    def test_segment_positions(self):
        seg = jnp.array([[1, 1, 1, 2, 2, 3, 0, 0]])
        pos = llama.segment_positions(seg)
        assert pos.tolist() == [[0, 1, 2, 0, 1, 0, 0, 1]]

    def test_pack_sequences_first_fit(self):
        from tony_tpu.data.dataset import pack_sequences

        toks, segs = pack_sequences([[1, 2, 3], [4, 5], [6, 7, 8, 9, 10, 11, 12]], 6)
        assert toks.shape == segs.shape and toks.shape[1] == 6
        # 3+2 pack into one row; the 7-long splits into 6 + 1 (joins row 1)
        assert segs.max() >= 2
        # padding is segment 0 and only trails
        for r in range(segs.shape[0]):
            nz = np.nonzero(segs[r])[0]
            assert (segs[r, : nz.max() + 1] != 0).all()

    def test_packed_loss_equals_unpacked(self):
        # two sequences run separately (unpacked, padded rows) must produce
        # the same summed token-NLL as the same two packed into one row
        import dataclasses as dc

        cfg = dc.replace(llama.LLAMA_TINY, max_seq=64, remat=False)
        params = llama.init(KEY, cfg)
        a = jax.random.randint(jax.random.fold_in(KEY, 1), (33,), 0, cfg.vocab_size)
        b = jax.random.randint(jax.random.fold_in(KEY, 2), (32,), 0, cfg.vocab_size)

        def solo_nll(seq):
            tokens = seq[None, :]
            loss, m = llama.loss_fn(params, {"tokens": tokens}, cfg)
            return float(loss) * float(m["tokens"])

        packed_tokens = jnp.concatenate([a, b])[None, :]          # 65 = 64+1 tokens
        seg = jnp.concatenate([jnp.full((33,), 1), jnp.full((32,), 2)])[None, :]
        loss_p, m_p = llama.loss_fn(
            params, {"tokens": packed_tokens, "segment_ids": seg}, cfg
        )
        packed_mass = float(loss_p) * float(m_p["tokens"])
        want_mass = solo_nll(a) + solo_nll(b)
        # token counts: solo gives (33-1)+(32-1); packed masks the boundary → 63
        assert int(m_p["tokens"]) == 63
        np.testing.assert_allclose(packed_mass, want_mass, rtol=5e-3)

    @pytest.mark.slow  # ~21 s packed flash-kernel parity
    def test_packed_flash_matches_reference_impl(self):
        import dataclasses as dc

        base = dc.replace(llama.LLAMA_TINY, max_seq=256, remat=False)
        params = llama.init(KEY, base)
        from tony_tpu.data.dataset import pack_sequences

        rng = np.random.default_rng(0)
        seqs = [rng.integers(0, base.vocab_size, size=n) for n in (100, 70, 120, 50)]
        toks, segs = pack_sequences(seqs, 257)
        batch = {"tokens": jnp.asarray(toks), "segment_ids": jnp.asarray(segs)}

        l_ref, _ = llama.loss_fn(params, batch, dc.replace(base, attn_impl="reference"))
        l_flash, _ = llama.loss_fn(params, batch, dc.replace(base, attn_impl="flash"))
        np.testing.assert_allclose(float(l_ref), float(l_flash), rtol=2e-3)

        g_ref = jax.grad(lambda p: llama.loss_fn(p, batch, dc.replace(base, attn_impl="reference"))[0])(params)
        g_flash = jax.grad(lambda p: llama.loss_fn(p, batch, dc.replace(base, attn_impl="flash"))[0])(params)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_flash)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=2e-2, atol=2e-4
            )


class TestMixtralPacking:
    def test_packed_loss_equals_unpacked(self):
        # same invariant as the llama packing test, through the MoE model:
        # packed token-NLL mass == the sequences run separately
        import dataclasses as dc

        from tony_tpu.models import mixtral

        cfg = dc.replace(mixtral.MIXTRAL_TINY, max_seq=64, remat=False)
        params = mixtral.init(KEY, cfg)
        a = jax.random.randint(jax.random.fold_in(KEY, 1), (33,), 0, cfg.vocab_size)
        b = jax.random.randint(jax.random.fold_in(KEY, 2), (32,), 0, cfg.vocab_size)

        def solo_mass(seq):
            _, m = mixtral.loss_fn(params, {"tokens": seq[None, :]}, cfg)
            return float(m["ce_loss"]) * float(m["tokens"])

        seg = jnp.concatenate([jnp.full((33,), 1), jnp.full((32,), 2)])[None, :]
        packed = jnp.concatenate([a, b])[None, :]
        _, m_p = mixtral.loss_fn(params, {"tokens": packed, "segment_ids": seg}, cfg)
        np.testing.assert_allclose(
            float(m_p["ce_loss"]) * float(m_p["tokens"]),
            solo_mass(a) + solo_mass(b),
            rtol=5e-3,
        )
        assert int(m_p["tokens"]) == 63
