"""Scheduler flight recorder: decision provenance, telemetry, `tony explain`.

PR 15 (docs/scheduling.md "Explaining decisions"). Layers under test:

- recorder units: ring bounding, deny coalescing, causal chains, telemetry
  window aggregation, the cluster-series JSONL carrier;
- policy provenance: every binding rule in the vocabulary produced by a
  scenario that actually binds on it, and the hard neutrality contract —
  attaching a recorder NEVER changes a decision;
- the chain property: a seeded simulation's every app folds its record
  chain into a legal transition sequence that reaches its terminal state
  (no decision gaps);
- sim-vs-live parity: the same seeded arrival mix through `tony sim` and a
  live PoolService emits the same decision stream;
- pool integration: `pool_status` blocked_reason, the enriched allocate
  wait answer, `pool_explain` over real RPC, `tony explain` CLI, the
  no-rect placement record, telemetry flush → history-store
  ``cluster_series`` → portal capacity dashboard.
"""

import json
import os
import threading
import urllib.request

import pytest

from tony_tpu.cluster.policy import AppView, PreemptionPolicy, make_policy
from tony_tpu.cluster.pool import PoolService
from tony_tpu.cluster.recorder import (
    DENY_RULES,
    FlightRecorder,
    QueueTelemetry,
    read_window_lines,
    window_line,
)
from tony_tpu.cluster.sim import PoolSimulator, SimJob, generate_jobs

from tests.test_pool import SECRET, register_cpu_node

pytestmark = pytest.mark.sched

GB = 1024**3


def make_pool(**kw):
    return PoolService(heartbeat_interval_ms=100, max_missed_heartbeats=3,
                       secret=SECRET, **kw)


# ---------------------------------------------------------------------------
# FlightRecorder units
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_notes_and_latest(self):
        rec = FlightRecorder(clock=lambda: 100.0)
        rec.begin_pass()
        rec.note("admit", "a1", "prod", "fits-free")
        rec.note("deny", "a2", "dev", "no-capacity", ask=[1, 0, 0])
        assert rec.latest("a1").action == "admit"
        assert rec.blocked_reason("a1") is None
        assert rec.blocked_reason("a2") == "no-capacity"
        assert rec.latest("a2").unix_ms == 100_000

    def test_deny_coalescing(self):
        rec = FlightRecorder()
        for i in range(50):
            rec.begin_pass()
            rec.note("deny", "a1", "prod", "share-deficit", used=i)
        assert len(rec.records) == 1
        r = rec.latest("a1")
        assert r.count == 50 and r.pass_id == 50 and r.detail == {"used": 49}
        # a different rule breaks the run: new record
        rec.note("deny", "a1", "prod", "budget-exhausted")
        assert len(rec.records) == 2
        assert rec.blocked_reason("a1") == "budget-exhausted"
        # an action between two identical denies also breaks the run
        rec.note("admit", "a1", "prod", "fits-free")
        rec.note("deny", "a1", "prod", "budget-exhausted")
        assert len(rec.records) == 4

    def test_ring_bounded_and_latest_pruned(self):
        rec = FlightRecorder(capacity=16)
        for i in range(100):
            rec.note("admit", f"a{i}", "q", "fits-free")
        assert len(rec.records) == 16
        assert rec.latest("a0") is None          # rotated out
        assert rec.latest("a99") is not None

    def test_explain_chain_includes_funded_actions(self):
        rec = FlightRecorder()
        rec.note("deny", "head", "prod", "share-deficit")
        rec.note("shrink", "victim", "dev", "partial-reclaim", for_app="head", workers=2)
        rec.note("evict", "victim2", "dev", "share-reclaim", for_app="head")
        rec.note("admit", "head", "prod", "share-reclaim")
        chain = [r.app_id for r in rec.explain("head")]
        assert chain == ["head", "victim", "victim2", "head"]
        # the victim's chain names the head its shed capacity funded
        vchain = rec.explain("victim")
        assert [(r.action, r.for_app) for r in vchain] == [("shrink", "head")]

    def test_queue_counters(self):
        rec = FlightRecorder()
        rec.note("admit", "a", "prod", "fits-free")
        rec.note("deny", "b", "prod", "no-capacity")
        rec.note("deny", "b", "prod", "no-capacity")   # coalesced, still counted
        assert rec.counters("prod") == {"admit": 1, "deny": 2}

    def test_on_note_hook(self):
        seen = []
        rec = FlightRecorder(on_note=seen.append)
        rec.note("deny", "a", "q", "no-capacity")
        assert [r.rule for r in seen] == ["no-capacity"]


# ---------------------------------------------------------------------------
# QueueTelemetry units
# ---------------------------------------------------------------------------
class TestQueueTelemetry:
    def test_window_aggregation_and_counter_deltas(self):
        now = [0.0]
        t = QueueTelemetry(window_ms=1_000, clock=lambda: now[0])
        counters = {"prod": {"admit": 3, "deny": 10}}
        t.sample({"prod": {"used": 2, "share_capacity": 4, "demand": 6,
                           "waiting": 3, "wait_age_s": 5.0}}, counters)
        now[0] = 0.5
        counters = {"prod": {"admit": 5, "deny": 12, "evict": 1}}
        t.sample({"prod": {"used": 4, "share_capacity": 4, "demand": 2,
                           "waiting": 1, "wait_age_s": 9.0}}, counters)
        assert t.drain_finalized() == []          # window still open
        now[0] = 1.2                              # crosses the 1s boundary
        t.sample({"prod": {"used": 0, "share_capacity": 4, "demand": 0,
                           "waiting": 0, "wait_age_s": 0.0}}, counters)
        (w,) = t.drain_finalized()
        assert w["queue"] == "prod" and w["samples"] == 2
        m = w["metrics"]
        assert m["used_avg"] == 3.0 and m["used_max"] == 4
        assert m["utilization_avg"] == 0.75
        assert m["demand_max"] == 6 and m["waiting_max"] == 3
        assert m["wait_age_max_s"] == 9.0
        # deltas against the window-start counters
        assert m["admissions"] == 2 and m["denials"] == 2 and m["evictions"] == 1

    def test_boundary_gap_events_attribute_to_next_window(self):
        """Events between one window's last sample and the next window's
        first sample must count in the NEXT window, not vanish."""
        now = [0.0]
        t = QueueTelemetry(window_ms=1_000, clock=lambda: now[0])
        q = {"q": {"used": 0, "share_capacity": 1, "demand": 0,
                   "waiting": 0, "wait_age_s": 0.0}}
        t.sample(q, {"q": {"deny": 1}})
        now[0] = 1.5  # crosses the boundary; 9 denials landed in the gap
        t.sample(q, {"q": {"deny": 10}})
        (w1,) = t.drain_finalized()
        assert w1["metrics"]["denials"] == 0  # none seen inside window 1
        now[0] = 2.5
        t.sample(q, {"q": {"deny": 10}})
        (w2,) = t.drain_finalized()
        assert w2["metrics"]["denials"] == 9  # the gap burst, not dropped

    def test_flush_force_finalizes(self):
        now = [0.0]
        t = QueueTelemetry(window_ms=60_000, clock=lambda: now[0])
        t.sample({"q": {"used": 1, "share_capacity": 2, "demand": 0,
                        "waiting": 0, "wait_age_s": 0.0}})
        (w,) = t.flush(now_ms=500)
        assert w["window_end_ms"] == 500 and w["metrics"]["used_avg"] == 1.0
        assert t.flush() == []

    def test_window_lines_torn_tail_tolerant(self, tmp_path):
        p = tmp_path / "series.jsonl"
        now = [0.0]
        t = QueueTelemetry(window_ms=1_000, clock=lambda: now[0])
        t.sample({"q": {"used": 1, "share_capacity": 2, "demand": 0,
                        "waiting": 0, "wait_age_s": 0.0}})
        windows = t.flush(now_ms=900)
        with open(p, "w") as f:
            for w in windows:
                f.write(window_line("pool", w) + "\n")
            f.write('{"queue": "q", "metr')     # torn mid-append
        got = list(read_window_lines(p))
        assert len(got) == 1 and got[0]["source"] == "pool"
        assert list(read_window_lines(tmp_path / "missing.jsonl")) == []


# ---------------------------------------------------------------------------
# Policy provenance: every binding rule from a scenario that binds on it
# ---------------------------------------------------------------------------
def view(app_id, queue, *, mem=1, admitted=False, prio=0, seq=0, wait=0.0,
         admitted_at=0.0, unit=0, slack=0, shrink_pending=False):
    d = (mem * GB, 1, 0)
    return AppView(
        app_id=app_id, queue=queue, priority=prio, seq=seq, demand=d,
        held=d if admitted else (0, 0, 0), admitted=admitted,
        wait_since=wait, admitted_at=admitted_at,
        elastic_unit=(unit * GB, 0, 0) if unit else (0, 0, 0),
        elastic_slack=slack, shrink_pending=shrink_pending,
    )


def run_pass(views, *, totals=(4 * GB, 64, 0), clock_now=1000.0, **policy_kw):
    rec = FlightRecorder(clock=lambda: clock_now)
    pol = PreemptionPolicy(
        policy_kw.pop("queues", {"prod": 0.5, "dev": 0.5}),
        clock=lambda: clock_now, sink=rec, **policy_kw)
    decision = pol.schedule(views, totals)
    return decision, rec


class TestPolicyProvenance:
    def test_fits_free_admit(self):
        d, rec = run_pass([view("a", "prod")])
        assert d.admit == ["a"]
        assert rec.latest("a").rule == "fits-free"

    def test_pool_empty(self):
        d, rec = run_pass([view("a", "prod")], totals=(0, 0, 0))
        assert d.empty() and rec.blocked_reason("a") == "pool-empty"

    def test_no_capacity(self):
        d, rec = run_pass([view("big", "prod", mem=3, admitted=True),
                           view("a", "prod", mem=2, seq=1)])
        assert d.empty() and rec.blocked_reason("a") == "no-capacity"
        assert rec.latest("a").detail["ask"][0] == 2 * GB

    def test_share_deficit(self):
        # prod at its 2 GB share; its next app over-share while dev waits
        d, rec = run_pass([
            view("p1", "prod", mem=2, admitted=True),
            view("p2", "prod", mem=1, seq=1),
            view("d1", "dev", mem=3, seq=2),
        ])
        assert d.empty()
        assert rec.blocked_reason("p2") == "share-deficit"
        assert rec.latest("p2").detail["share_capacity"] == 2 * GB
        # the dev head is blocked by capacity, not its share
        assert rec.blocked_reason("d1") == "no-capacity"

    def test_priority_preemption_records(self):
        d, rec = run_pass(
            [view("low", "prod", mem=3, admitted=True, prio=0),
             view("high", "prod", mem=3, prio=9, seq=1),
             view("filler", "dev", mem=1, admitted=True, seq=2)],
            preemption=True)
        assert d.admit == ["high"] and [e.app_id for e in d.evict] == ["low"]
        chain = rec.explain("low")
        ev = next(r for r in chain if r.action == "evict")
        assert ev.rule == "priority-preemption" and ev.for_app == "high"
        assert ev.detail["head_priority"] == 9
        # the victim re-queued inside the same pass and was denied again:
        # its LATEST record says why it now waits
        assert rec.latest("low").action == "deny"
        ad = rec.latest("high")
        assert ad.rule == "priority-preemption" and ad.detail["evicted"] == ["low"]

    def test_share_reclaim_shrink_records(self):
        d, rec = run_pass(
            [view("borrower", "dev", mem=4, admitted=True, unit=1, slack=3),
             view("head", "prod", mem=2, seq=1)],
            preemption=True)
        assert d.admit == ["head"]
        assert [(s.app_id, s.workers) for s in d.shrink] == [("borrower", 2)]
        sh = rec.latest("borrower")
        assert sh.action == "shrink" and sh.rule == "partial-reclaim"
        assert sh.for_app == "head" and sh.detail["workers"] == 2
        assert rec.latest("head").rule == "share-reclaim"
        assert rec.latest("head").detail["shrunk"] == ["borrower"]

    def test_grace_pending(self):
        d, rec = run_pass(
            [view("borrower", "dev", mem=4, admitted=True),
             view("head", "prod", mem=2, seq=1, wait=999.0)],
            preemption=True, grace_ms=30_000, clock_now=1000.0)
        assert d.empty()
        r = rec.latest("head")
        assert r.rule == "grace-pending"
        assert r.detail["grace_ms"] == 30_000 and r.detail["waited_ms"] == 1000

    def test_min_runtime_shield(self):
        d, rec = run_pass(
            [view("borrower", "dev", mem=4, admitted=True, admitted_at=999.5),
             view("head", "prod", mem=2, seq=1)],
            preemption=True, min_runtime_ms=60_000, clock_now=1000.0)
        assert d.empty()
        r = rec.latest("head")
        assert r.rule == "min-runtime-shield"
        assert r.detail["protected_victims"] >= 1

    def test_drain_pending(self):
        d, rec = run_pass(
            [view("borrower", "dev", mem=4, admitted=True, shrink_pending=True),
             view("head", "prod", mem=2, seq=1)],
            preemption=True)
        assert d.empty()
        assert rec.blocked_reason("head") == "drain-pending"

    def test_budget_exhausted(self):
        clock = [1000.0]
        rec = FlightRecorder(clock=lambda: clock[0])
        pol = PreemptionPolicy({"prod": 0.5, "dev": 0.5}, preemption=True,
                               eviction_budget=1, budget_window_ms=60_000,
                               clock=lambda: clock[0], sink=rec)
        totals = (4 * GB, 64, 0)
        # first reclaim spends prod's 1-disruption budget...
        first = [view("b1", "dev", mem=4, admitted=True),
                 view("h1", "prod", mem=2, seq=1)]
        assert pol.schedule(first, totals).admit == ["h1"]
        # ...the second, inside the same window, is denied on the budget
        second = [view("b2", "dev", mem=4, admitted=True, seq=2),
                  view("h2", "prod", mem=2, seq=3)]
        assert pol.schedule(second, totals).empty()
        r = rec.latest("h2")
        assert r.rule == "budget-exhausted" and r.detail["budget"] == 1

    def test_no_eligible_victims(self):
        # dev holds capacity but sits exactly AT its share: nothing to reclaim
        d, rec = run_pass(
            [view("d1", "dev", mem=2, admitted=True),
             view("p1", "prod", mem=1, admitted=True),
             view("head", "prod", mem=2, seq=2)],
            preemption=True)
        assert d.empty()
        assert rec.blocked_reason("head") in ("no-eligible-victims", "share-deficit")

    def test_rules_stay_in_vocabulary(self):
        # every deny rule any scenario above produced is a documented one
        for impl_rule in DENY_RULES:
            assert isinstance(impl_rule, str)


class TestProvenanceNeutrality:
    """The hard contract: recording never changes a decision."""

    @pytest.mark.parametrize("mix", ["bursty", "elastic", "priority"])
    def test_sim_trace_identical_with_and_without_recorder(self, mix):
        queues = {"prod": 0.6, "dev": 0.4}
        traces = {}
        for record in (False, True):
            sim = PoolSimulator(
                queues, (8 * GB, 256, 0), seed=7, policy_impl="indexed",
                record_trace=True, record_decisions=record,
                preemption=True, grace_ms=2_000, drain_ms=5_000,
                min_runtime_ms=3_000)
            rep = sim.run(generate_jobs(mix, 300, queues, 7))
            assert rep.ok(), rep.violations
            traces[record] = sim.trace
        assert traces[False] == traces[True]


# ---------------------------------------------------------------------------
# chain property: terminal state reachable from the record chain, no gaps
# ---------------------------------------------------------------------------
class TestChainProperty:
    @pytest.mark.parametrize("mix,seed", [("priority", 3), ("elastic", 11),
                                          ("bursty", 5)])
    def test_every_completed_app_chain_folds_to_admitted(self, mix, seed):
        queues = {"prod": 0.6, "dev": 0.4}
        sim = PoolSimulator(
            queues, (8 * GB, 256, 0), seed=seed, policy_impl="indexed",
            record_decisions=True, preemption=True, grace_ms=1_000,
            drain_ms=4_000, min_runtime_ms=2_000)
        # an unbounded ring for the property: the fold must see whole chains
        sim.recorder = FlightRecorder(capacity=1_000_000,
                                      clock=lambda: sim.now)
        sim.policy.sink = sim.recorder
        rep = sim.run(generate_jobs(mix, 400, queues, seed))
        assert rep.ok(), rep.violations
        rec = sim.recorder
        assert rec.records and rec.records[0].seq == 1  # nothing rotated out
        for st in sim._jobs.values():
            app_id = st.view.app_id
            subject = [r for r in rec.records if r.app_id == app_id]
            # no decision gaps: every app's life is fully explained —
            # strictly legal transitions from "waiting", ending "admitted"
            # (every job completed, and completion happens while admitted)
            assert subject, f"{app_id} completed with no decision records"
            state = "waiting"
            for r in subject:
                if r.action == "admit":
                    assert state == "waiting", (
                        f"{app_id}: admit while {state} (seq {r.seq})")
                    state = "admitted"
                elif r.action == "evict":
                    assert state == "admitted", (
                        f"{app_id}: evict while {state} (seq {r.seq})")
                    state = "waiting"
                elif r.action == "shrink":
                    assert state == "admitted", (
                        f"{app_id}: shrink while {state} (seq {r.seq})")
                elif r.action == "deny":
                    assert state == "waiting", (
                        f"{app_id}: denied while {state} (seq {r.seq})")
            assert st.done_at is not None
            assert state == "admitted", (
                f"{app_id} completed but its chain folds to {state}")


# ---------------------------------------------------------------------------
# sim-vs-live record parity on a seeded arrival mix
# ---------------------------------------------------------------------------
class TestSimLiveParity:
    def test_same_arrival_mix_same_decision_stream(self):
        queues = {"prod": 0.6, "dev": 0.4}
        rng_jobs = generate_jobs("batch", 40, queues, seed=13)
        # arrivals only: effectively-infinite work, so capacity never frees
        # and every decision is arrival-driven (time-independent policy:
        # no grace/min-runtime/budget)
        jobs = [
            SimJob(app_id=j.app_id, queue=j.queue, arrival_s=float(i),
                   work_s=10_000_000.0, demand=j.demand, priority=j.priority)
            for i, j in enumerate(rng_jobs)
        ]
        sim = PoolSimulator(queues, (8 * GB, 256, 0), seed=13,
                            policy_impl="indexed", record_decisions=True,
                            preemption=False)
        sim.run(jobs, horizon_s=50_000.0)  # starvation report is expected
        sim_stream = [
            (r.action, r.app_id, r.rule)
            for r in sim.recorder.records if r.action != "deny"
        ]
        sim_denied = {(r.app_id, r.rule)
                      for r in sim.recorder.records if r.action == "deny"}

        svc = make_pool(queues=queues, preemption=False)
        try:
            register_cpu_node(svc, "n0", memory=8 * GB, vcores=256)
            for j in jobs:
                svc.register_app(
                    j.app_id, queue=j.queue, priority=j.priority,
                    memory_bytes=j.demand[0], vcores=j.demand[1])
            live_stream = [
                (r.action, r.app_id, r.rule)
                for r in svc.recorder.records if r.action != "deny"
            ]
            live_denied = {(r.app_id, r.rule)
                           for r in svc.recorder.records if r.action == "deny"}
        finally:
            svc.stop()
        assert sim_stream == live_stream
        assert sim_denied == live_denied
        # the streams decided something (the mix overloads an 8 GB pool)
        assert any(a == "admit" for a, _, _ in sim_stream)
        assert sim_denied


# ---------------------------------------------------------------------------
# pool integration
# ---------------------------------------------------------------------------
class TestPoolIntegration:
    def test_blocked_reason_in_status_and_allocate_answer(self):
        svc = make_pool()
        try:
            register_cpu_node(svc, "n0")  # 4 GB
            svc.register_app("app1", memory_bytes=3 * GB, vcores=1)
            svc.allocate("app1", "worker", 0, 3 * GB, 1, 0)
            svc.register_app("app2", memory_bytes=3 * GB, vcores=1)
            svc.register_app("app3", memory_bytes=3 * GB, vcores=1)
            wait = svc.allocate("app2", "worker", 0, 3 * GB, 1, 0)
            assert wait["blocked_reason"] == "no-capacity"
            assert "blocked: no-capacity" in wait["reason"]
            st = svc.pool_status()
            waiting = st["queues"]["default"]["waiting"]
            assert waiting[0]["blocked_reason"] == "no-capacity"
            assert waiting[1]["blocked_reason"] == "behind-queue-head"
        finally:
            svc.stop()

    def test_no_rect_placement_record(self):
        svc = make_pool()
        try:
            # two 4 GB hosts; app1 pins 3 GB on each → 2 GB free TOTAL but
            # only 1 GB per host: app2 (2 GB demand) is admitted yet
            # unplaceable on any single node
            register_cpu_node(svc, "n0")
            register_cpu_node(svc, "n1")
            svc.register_app("app1", memory_bytes=6 * GB, vcores=2)
            svc.allocate("app1", "worker", 0, 3 * GB, 1, 0)
            svc.allocate("app1", "worker", 1, 3 * GB, 1, 0)
            svc.register_app("app2", memory_bytes=2 * GB, vcores=1)
            got = svc.allocate("app2", "worker", 0, 2 * GB, 1, 0)
            assert got.get("wait") is True
            r = svc.recorder.latest("app2")
            assert r.action == "deny" and r.rule == "no-rect-placement"
            assert r.detail["task"] == "worker:0"
            ex = svc.pool_explain(app_id="app2")
            assert ex["app"]["admitted"] is True
            assert any(rr["rule"] == "no-rect-placement" for rr in ex["records"])
        finally:
            svc.stop()

    def test_recorder_disabled_pool(self):
        svc = make_pool(recorder_enabled=False)
        try:
            register_cpu_node(svc, "n0")
            svc.register_app("app1", memory_bytes=3 * GB, vcores=1)
            assert svc.pool_explain() == {"enabled": False}
            st = svc.pool_status()  # blocked_reason degrades to None/behind
            assert st["queues"]["default"]["waiting"] == []
        finally:
            svc.stop()

    def test_telemetry_windows_flush_to_series_file(self, tmp_path):
        from tony_tpu.histserver.ingest import sweep_cluster_series
        from tony_tpu.histserver.store import HistoryStore

        series = tmp_path / "pool_series.jsonl"
        svc = make_pool(queues={"prod": 0.5, "dev": 0.5},
                        recorder_series_file=str(series))
        try:
            register_cpu_node(svc, "n0")
            # prod admits first (registration order); dev then waits
            svc.register_app("app1", queue="prod", memory_bytes=3 * GB, vcores=1)
            svc.allocate("app1", "worker", 0, 3 * GB, 1, 0)
            svc.register_app("app2", queue="dev", memory_bytes=3 * GB, vcores=1)
            assert svc.allocate("app2", "worker", 0, 3 * GB, 1, 0).get("wait")
            # deterministic clock for the telemetry windows
            now = [0.0]
            svc._telemetry = QueueTelemetry(window_ms=1_000, clock=lambda: now[0])
            # sampling drains finalized windows under the lock; WRITING them
            # happens outside it — the liveness tick's two-phase shape
            for t in (0.0, 0.6, 1.3):  # 1.3 crosses the boundary → finalize
                now[0] = t
                with svc._lock:
                    drained = svc._sample_telemetry_locked()
                svc._write_series(drained)
        finally:
            svc.stop()  # flushes the open windows too
        windows = list(read_window_lines(series))
        assert {w["queue"] for w in windows} >= {"prod", "dev"}
        dev = next(w for w in windows if w["queue"] == "dev"
                   and w["window_end_ms"] == 1000)
        assert dev["metrics"]["waiting_max"] == 1.0
        assert dev["metrics"]["demand_max"] == 3 * GB
        prod = next(w for w in windows if w["queue"] == "prod"
                    and w["window_end_ms"] == 1000)
        assert prod["metrics"]["used_max"] == 3 * GB
        assert prod["metrics"]["utilization_avg"] == 1.5  # borrowing over share

        # → history store: idempotent rows, query shape, retention
        store = HistoryStore(str(tmp_path / "hist.sqlite"))
        try:
            counts = sweep_cluster_series(store, [str(series)])
            assert counts["files"] == 1 and counts["rows"] > 0
            again = sweep_cluster_series(store, [str(series)])
            assert again["rows"] == counts["rows"]  # REPLACE converged
            pts = store.cluster_series("waiting_max", queue="dev")
            assert [p["value"] for p in pts][:1] == [1.0]
            # source = the series file's stem, so two pools feeding one
            # store through different files keep distinct row keys
            assert ("pool_series", "prod") in store.cluster_queues()
            purged = store.purge_cluster_older_than(10_000_000)
            assert purged == counts["rows"]
        finally:
            store.close()

    def test_gauges_exported(self):
        from tony_tpu.obs import metrics as obs_metrics

        svc = make_pool(queues={"prod": 0.5, "dev": 0.5})
        try:
            register_cpu_node(svc, "n0")
            svc.register_app("app1", queue="prod", memory_bytes=3 * GB, vcores=1)
            svc.allocate("app1", "worker", 0, 3 * GB, 1, 0)
            with svc._lock:
                svc._sample_telemetry_locked()
            text = obs_metrics.REGISTRY.render()
            assert 'tony_pool_queue_used{queue="prod"}' in text
            assert 'tony_pool_queue_share_capacity{queue="dev"}' in text
        finally:
            svc.stop()


# ---------------------------------------------------------------------------
# the e2e: a real pool under pressure + `tony explain` over real RPC
# ---------------------------------------------------------------------------
class TestExplainE2E:
    @pytest.fixture()
    def pressured_pool(self, monkeypatch):
        svc = make_pool(queues={"prod": 0.5, "dev": 0.5}, preemption=True)
        svc.start()
        register_cpu_node(svc, "n0")
        # the elastic borrower fills the pool from 'dev' (idle-pool borrowing)
        svc.register_app("borrower", queue="dev", memory_bytes=4 * GB, vcores=4,
                         elastic_unit=[GB, 1, 0], elastic_slack=3)
        svc.allocate("borrower", "worker", 0, 4 * GB, 4, 0)
        monkeypatch.setenv("TONY_POOL_SECRET", SECRET)
        yield svc
        svc.stop()

    def run_cli(self, capsys, *args):
        from tony_tpu.cli.explain import main as explain_main

        rc = explain_main(list(args))
        out = capsys.readouterr().out
        return rc, out

    def test_explain_names_binding_rules_for_queued_and_shrink_victim(
            self, pressured_pool, capsys):
        svc = pressured_pool
        host, port = svc.address
        pool_arg = f"{host}:{port}"
        # under-share head arrives: the policy shrinks the borrower for it
        svc.register_app("head", queue="prod", memory_bytes=2 * GB, vcores=2)
        st = svc.pool_status()
        assert st["queues"]["dev"]["admitted"][0]["draining"] is True

        # the shrink victim's chain names partial-reclaim and who it funded
        rc, out = self.run_cli(capsys, "borrower", "--pool", pool_arg)
        assert rc == 0
        assert "partial-reclaim" in out and "for head" in out
        assert "shrink" in out

        # a queued app blocked behind the in-flight shrink
        svc.register_app("queued", queue="dev", memory_bytes=3 * GB, vcores=1)
        got = svc.allocate("queued", "worker", 0, 3 * GB, 1, 0)
        assert got.get("wait") is True
        rc, out = self.run_cli(capsys, "queued", "--pool", pool_arg)
        assert rc == 0
        assert "WAITING in 'dev'" in out
        assert "blocked:" in out and "deny" in out

        # the queue view lists waiters with their rules
        rc, out = self.run_cli(capsys, "--queue", "dev", "--pool", pool_arg)
        assert rc == 0
        assert "queued" in out and "counters:" in out

        # records the CLI rendered match the recorder's own state (the RPC
        # is a faithful view, not a re-derivation)
        ex = svc.pool_explain(app_id="borrower")
        assert any(r["rule"] == "partial-reclaim" and r["for_app"] == "head"
                   for r in ex["records"])

    def test_explain_records_match_journal_stream(self, tmp_path, capsys):
        """The recorder's admit/evict facts line up with what the journal
        persisted — provenance describes the same history the recovery
        stream records."""
        from tony_tpu.cluster.journal import iter_journal

        jpath = tmp_path / "pool_journal.jsonl"
        svc = make_pool(queues={"prod": 0.5, "dev": 0.5}, preemption=True,
                        journal_path=str(jpath))
        try:
            register_cpu_node(svc, "n0")
            svc.register_app("low", queue="prod", priority=0,
                             memory_bytes=4 * GB, vcores=1)
            svc.allocate("low", "worker", 0, 4 * GB, 1, 0)
            svc.register_app("high", queue="prod", priority=9,
                             memory_bytes=4 * GB, vcores=1)
            # priority preemption: high evicts low
            assert any(
                r.action == "evict" and r.rule == "priority-preemption"
                for r in svc.recorder.explain("low"))
            journaled = {
                rec["app_id"]: rec["admitted"]
                for rec in iter_journal(str(jpath)) if rec.get("t") == "app"
            }
            # last-wins journal rows agree with the recorder's latest facts
            assert journaled["low"] is False and journaled["high"] is True
        finally:
            svc.stop()

    def test_cli_errors(self, capsys):
        from tony_tpu.cli.explain import main as explain_main

        assert explain_main([]) == 2                       # no target
        assert explain_main(["a", "--queue", "q"]) == 2    # both targets
        rc = explain_main(["app", "--pool", "127.0.0.1:1"])
        assert rc == 1                                     # unreachable pool

    def test_sim_explain_flag_conflicts(self, capsys):
        from tony_tpu.cli.sim import main as sim_main

        # --explain needs the instrumented (indexed) policy...
        assert sim_main(["--jobs", "5", "--policy", "reference",
                         "--explain", "x"]) == 2
        # ...and is rejected loudly with --parity rather than ignored
        assert sim_main(["--jobs", "5", "--parity", "--explain", "x"]) == 2


# ---------------------------------------------------------------------------
# cbench: the scheduler lane runs with the recorder ON
# ---------------------------------------------------------------------------
class TestCbenchRecorderLane:
    @pytest.mark.slow
    def test_scaled_lane_reports_recorder_on(self):
        from tony_tpu.cluster.cbench import CbenchSizes, bench_scheduler

        sizes = CbenchSizes(seed=0).scaled(0.01)
        result = bench_scheduler(sizes, passes=2)
        assert result["sched_recorder"] == "on"
        assert result["sched_decisions_per_sec"] > 0
        # the reference lane stays uninstrumented
        ref = bench_scheduler(sizes, passes=2, policy_impl="reference")
        assert ref["sched_recorder"] == "off"

    def test_recorder_does_not_change_bench_decisions(self):
        from tony_tpu.cluster.cbench import CbenchSizes, _scheduler_world
        from dataclasses import replace as _replace

        sizes = CbenchSizes(seed=0).scaled(0.01)
        policy, template, totals = _scheduler_world(sizes)
        bare = policy.schedule([_replace(v) for v in template], totals)
        policy._charges.clear()
        policy.sink = FlightRecorder()
        recorded = policy.schedule([_replace(v) for v in template], totals)
        assert bare.admit == recorded.admit
        assert [(e.app_id, e.for_app) for e in bare.evict] == [
            (e.app_id, e.for_app) for e in recorded.evict]


# ---------------------------------------------------------------------------
# portal: /pool blocked reasons + /history capacity dashboard
# ---------------------------------------------------------------------------
class TestPortalSurfaces:
    def _get(self, port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
            return resp.read().decode()

    def test_pool_page_and_history_capacity_dashboard(self, tmp_path, monkeypatch):
        from tony_tpu.histserver.store import HistoryStore
        from tony_tpu.portal import server as portal_server

        monkeypatch.setenv("TONY_POOL_SECRET", SECRET)
        svc = make_pool(queues={"prod": 0.5, "dev": 0.5})
        svc.start()
        try:
            register_cpu_node(svc, "n0")
            svc.register_app("app1", queue="prod", memory_bytes=4 * GB, vcores=1)
            svc.allocate("app1", "worker", 0, 4 * GB, 1, 0)
            svc.register_app("app2", queue="dev", memory_bytes=2 * GB, vcores=1)
            svc.allocate("app2", "worker", 0, 2 * GB, 1, 0)
            # a few telemetry samples so /pool has sparkline material
            now = [0.0]
            svc._telemetry = QueueTelemetry(window_ms=1_000, clock=lambda: now[0])
            for t in (0.0, 0.3, 0.6):
                now[0] = t
                with svc._lock:
                    svc._sample_telemetry_locked()

            db = tmp_path / "history.sqlite"
            store = HistoryStore(str(db))
            store.put_cluster_windows("pool", [
                {"queue": "prod", "window_start_ms": s, "window_end_ms": s + 1000,
                 "metrics": {"utilization_avg": 0.5 + s / 10_000,
                             "demand_avg": 1.0, "waiting_avg": 1.0}}
                for s in (0, 1000, 2000)
            ])
            store.close()

            host, port = svc.address
            httpd = portal_server.serve(
                str(tmp_path / "history"), 0, staging_root=str(tmp_path),
                pool=f"{host}:{port}", history_db=str(db))
            thread = threading.Thread(target=httpd.serve_forever, daemon=True)
            thread.start()
            try:
                pport = httpd.server_address[1]
                pool_page = self._get(pport, "/pool")
                assert "blocked: no-capacity" in pool_page
                assert "queue telemetry" in pool_page
                assert "recent scheduling decisions" in pool_page
                hist_page = self._get(pport, "/history")
                assert "cluster capacity" in hist_page
                assert "pool/prod" in hist_page
                api = json.loads(self._get(
                    pport, "/api/history/cluster/utilization_avg"))
                assert len(api) == 3 and api[0]["queue"] == "prod"
            finally:
                httpd.shutdown()
                httpd.server_close()
                thread.join()
        finally:
            svc.stop()
