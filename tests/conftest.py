"""Test harness configuration.

Multi-chip behavior is tested the way the reference tests multi-node behavior
(SURVEY.md §4): no real cluster — an in-process fake resource manager, local
subprocesses as "containers", and a virtual device mesh. Here the mesh is
8 virtual CPU devices via --xla_force_host_platform_device_count, set BEFORE
jax is first imported.
"""

import os
import sys

# Force the CPU platform with 8 virtual devices. The axon sitecustomize may
# have imported jax at interpreter startup (registering the one-chip TPU
# plugin), so setting env vars here is not enough — override via jax.config
# before any backend initializes. Both env and config are set so subprocesses
# spawned by E2E tests (AM/executors) inherit the CPU platform too.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
# Run Pallas TPU kernels through the interpreter on CPU so kernel numerics
# (incl. the flash-attention backward) are covered without a chip.
os.environ["TONY_PALLAS_INTERPRET"] = "1"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Repo root on sys.path so `import tony_tpu` works without install.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture()
def tmp_tony_root(tmp_path, monkeypatch):
    """Isolated staging/history root per test."""
    root = tmp_path / ".tony"
    root.mkdir()
    monkeypatch.setenv("TONY_ROOT", str(root))
    return root
