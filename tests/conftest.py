"""Test harness configuration.

Multi-chip behavior is tested the way the reference tests multi-node behavior
(SURVEY.md §4): no real cluster — an in-process fake resource manager, local
subprocesses as "containers", and a virtual device mesh. Here the mesh is
8 virtual CPU devices via --xla_force_host_platform_device_count, set BEFORE
jax is first imported.
"""

import os
import sys

# Must happen before any jax import anywhere in the test session.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Repo root on sys.path so `import tony_tpu` works without install.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture()
def tmp_tony_root(tmp_path, monkeypatch):
    """Isolated staging/history root per test."""
    root = tmp_path / ".tony"
    root.mkdir()
    monkeypatch.setenv("TONY_ROOT", str(root))
    return root
