"""The r11 step-path overhaul: overlapped input pipeline, kernel autotuner,
goodput input_wait attribution, bench-gate movement/provenance warnings, and
the size-1-axis collective guard.

Headline contracts:
- the overlapped pipeline feeds a BIT-IDENTICAL batch sequence to the
  synchronous path (loss-trajectory parity over a seeded run, both loader
  and synthetic sources);
- a producer failure propagates to the step loop's thread and teardown is
  clean mid-run;
- the autotuner cache round-trips to disk and the kernel entry points pick
  winners up (with stale entries degrading to the shipped defaults);
- `tony bench --gate` warns on a gate round whose headline metric didn't
  move vs the prior round, and on perf records without profile provenance.
"""

import functools
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.obs import goodput as obs_goodput
from tony_tpu.ops import tune
from tony_tpu.train.input_pipeline import InputPipeline, InputPipelineError


# ---------------------------------------------------------------------------
# pipeline unit contracts
# ---------------------------------------------------------------------------
class TestInputPipeline:
    def test_feeds_every_step_in_order_once(self):
        calls = []

        def make(step):
            calls.append(step)
            return step * 10

        with InputPipeline(make, 3, 9, depth=2) as p:
            assert p.overlapped
            got = [p.next(s) for s in range(3, 9)]
        assert got == [30, 40, 50, 60, 70, 80]
        assert calls == list(range(3, 9))

    def test_sync_mode_is_inline(self):
        p = InputPipeline(lambda s: s, 0, 4, depth=0)
        assert not p.overlapped
        assert [p.next(s) for s in range(4)] == [0, 1, 2, 3]
        p.close()

    def test_exhaustion_raises_stopiteration(self):
        with InputPipeline(lambda s: s, 0, 2, depth=2) as p:
            p.next(0), p.next(1)
            with pytest.raises(StopIteration):
                p.next(2)

    def test_out_of_order_request_rejected(self):
        with InputPipeline(lambda s: s, 0, 5, depth=1) as p:
            p.next(0)
            with pytest.raises(ValueError, match="out-of-order"):
                p.next(2)

    def test_producer_exception_propagates_with_cause(self):
        def bad(step):
            if step == 2:
                raise ValueError("shard went away")
            return step

        with InputPipeline(bad, 0, 6, depth=2) as p:
            assert p.next(0) == 0 and p.next(1) == 1
            with pytest.raises(InputPipelineError) as ei:
                p.next(2)
            assert isinstance(ei.value.__cause__, ValueError)

    def test_producer_error_survives_a_full_queue_backlog(self):
        """Review-caught hang: with the queue full of ready batches and a
        slow consumer, the error must wait out the backlog — a bounded put
        that drops it would leave next() parked forever once the buffered
        batches drain."""
        def bad(step):
            if step == 2:
                raise ValueError("boom after the backlog filled")
            return step

        p = InputPipeline(bad, 0, 10, depth=2)
        time.sleep(0.3)  # producer fills the 2-deep queue, then fails
        assert p.next(0) == 0 and p.next(1) == 1  # drain the backlog
        with pytest.raises(InputPipelineError):
            p.next(2)
        p.close()

    def test_close_is_idempotent_and_joins_even_when_producer_parked(self):
        # depth 1 with a never-consuming caller: the producer is parked on a
        # full queue; close() must still unblock + join it promptly
        p = InputPipeline(lambda s: bytes(1024), 0, 1000, depth=1)
        time.sleep(0.05)  # let the producer fill the queue and park
        t0 = time.perf_counter()
        p.close()
        p.close()
        assert time.perf_counter() - t0 < 2.0
        assert not p._thread.is_alive()

    def test_close_mid_run_after_partial_consumption(self):
        with InputPipeline(lambda s: s, 0, 100, depth=3) as p:
            for s in range(5):
                p.next(s)
        assert not p._thread.is_alive()

    def test_wait_metric_and_span_on_slow_producer(self):
        spans = []

        class _Span:
            def __init__(self):
                self.start_ms = 0.0
                self.attrs = {}

            def set(self, **kw):
                self.attrs.update(kw)
                return self

        class _Ctx:
            def __init__(self, rec):
                self.rec = rec

            def __enter__(self):
                return self.rec

            def __exit__(self, *exc):
                return False

        class _Tracer:
            def span(self, name, **attrs):
                sp = _Span()
                spans.append((name, sp))
                return _Ctx(sp)

        def slow(step):
            time.sleep(0.03)
            return step

        p = InputPipeline(slow, 0, 3, depth=1, tracer=_Tracer(), span_min_ms=5.0)
        for s in range(3):
            p.next(s)
        p.close()
        assert p.wait_s_total > 0
        assert spans and all(n == "train.input_wait" for n, _ in spans)

    def test_sub_floor_waits_emit_no_span(self):
        spans = []

        class _Tracer:
            def span(self, name, **attrs):  # pragma: no cover — must not run
                spans.append(name)
                raise AssertionError("span for a sub-floor wait")

        p = InputPipeline(lambda s: s, 0, 3, depth=2, tracer=_Tracer(),
                          span_min_ms=10_000.0)
        for s in range(3):
            p.next(s)
        p.close()
        assert spans == []


# ---------------------------------------------------------------------------
# loop-level parity: overlapped ≡ synchronous, bit-identical
# ---------------------------------------------------------------------------
class TestLoopParity:
    def _run(self, tmp_path, tag, depth, steps=4, **extra):
        from tony_tpu.models import llama
        from tony_tpu.train.loop import LoopConfig, run_lm_training

        return run_lm_training(
            llama, llama.LLAMA_TINY,
            LoopConfig(steps=steps, batch_size=2, seq_len=64, log_every=100,
                       warmup_steps=0, prefetch_depth=depth, **extra),
        )

    @pytest.mark.slow
    def test_synthetic_loss_trajectory_is_bit_identical(self, tmp_path):
        sync = self._run(tmp_path, "sync", depth=0)
        overlapped = self._run(tmp_path, "pre", depth=2)
        assert overlapped["step"] == sync["step"]
        assert overlapped["loss"] == sync["loss"], (sync, overlapped)

    @pytest.mark.slow
    def test_loader_loss_trajectory_is_bit_identical(self, tmp_path):
        from tony_tpu.data import write_token_shard

        rng = np.random.default_rng(7)
        data = tmp_path / "data"
        data.mkdir()
        write_token_shard(data / "s0.tonytok",
                          rng.integers(0, 256, 30_000, dtype=np.int32))
        sync = self._run(tmp_path, "sync", depth=0, data_dir=str(data))
        overlapped = self._run(tmp_path, "pre", depth=3, data_dir=str(data))
        assert overlapped["loss"] == sync["loss"], (sync, overlapped)

    def test_loader_failure_mid_run_tears_down_cleanly(self, tmp_path, monkeypatch):
        """A shard that dies mid-run surfaces as the pipeline error on the
        step loop's thread and the finally-block teardown leaves no live
        producer thread behind."""
        from tony_tpu.data import write_token_shard
        from tony_tpu.data.native import TokenLoader

        rng = np.random.default_rng(8)
        data = tmp_path / "data"
        data.mkdir()
        write_token_shard(data / "s0.tonytok",
                          rng.integers(0, 256, 30_000, dtype=np.int32))
        real_next = TokenLoader.next
        state = {"n": 0}

        def dying_next(self):
            state["n"] += 1
            if state["n"] > 2:
                raise OSError("mmap torn under us")
            return real_next(self)

        monkeypatch.setattr(TokenLoader, "next", dying_next)
        before = {t.name for t in threading.enumerate()}
        with pytest.raises(InputPipelineError):
            self._run(tmp_path, "die", depth=2, steps=6, data_dir=str(data))
        for _ in range(50):
            leaked = {t.name for t in threading.enumerate()} - before
            if not any("input-pipeline" in n for n in leaked):
                break
            time.sleep(0.05)
        assert not any("input-pipeline" in n for n in leaked), leaked


# ---------------------------------------------------------------------------
# autotuner: cache round-trip + kernel consult
# ---------------------------------------------------------------------------
class TestTuneCache:
    def test_miss_then_hit_and_persistence_roundtrip(self, tmp_path):
        path = str(tmp_path / "tune.json")
        c = tune.TuneCache(path)
        assert c.get("flash_fwd", (1, 2, 1, 256, 256, 64), "bfloat16", kind="v5e") is None
        c.put("flash_fwd", (1, 2, 1, 256, 256, 64), "bfloat16",
              {"block_q": 128, "block_k": 256}, ms=3.5, kind="v5e")
        c.save()
        # a FRESH object (new process analog) reads the same winner back
        c2 = tune.TuneCache(path)
        assert c2.get("flash_fwd", (1, 2, 1, 256, 256, 64), "bfloat16",
                      kind="v5e") == {"block_q": 128, "block_k": 256}
        # different device kind / shape / dtype are misses
        assert c2.get("flash_fwd", (1, 2, 1, 256, 256, 64), "bfloat16", kind="v4") is None
        assert c2.get("flash_fwd", (1, 2, 1, 512, 512, 64), "bfloat16", kind="v5e") is None
        assert c2.get("flash_fwd", (1, 2, 1, 256, 256, 64), "float32", kind="v5e") is None

    def test_save_merges_with_concurrent_writers(self, tmp_path):
        path = str(tmp_path / "tune.json")
        a, b = tune.TuneCache(path), tune.TuneCache(path)
        a.put("moe_gemm", (8, 64, 128), "bfloat16", {"tile": 64}, kind="v5e")
        a.save()
        b.put("int8_matmul", (128, 256, 256), "bfloat16",
              {"block_m": 128, "block_n": 128, "block_k": 256}, kind="v5e")
        b.save()
        c = tune.TuneCache(path)
        assert c.get("moe_gemm", (8, 64, 128), "bfloat16", kind="v5e")
        assert c.get("int8_matmul", (128, 256, 256), "bfloat16", kind="v5e")

    def test_corrupt_cache_is_cold_not_fatal(self, tmp_path):
        path = tmp_path / "tune.json"
        path.write_text("{torn")
        c = tune.TuneCache(str(path))
        assert c.get("flash_fwd", (1,), "bfloat16", kind="x") is None
        c.put("flash_fwd", (1,), "bfloat16", {"block_q": 8, "block_k": 128}, kind="x")
        c.save()
        assert tune.TuneCache(str(path)).get("flash_fwd", (1,), "bfloat16", kind="x")

    def test_lookup_honors_disable_env(self, tmp_path, monkeypatch):
        path = str(tmp_path / "tune.json")
        monkeypatch.setenv(tune.ENV_CACHE, path)
        c = tune.TuneCache(path)
        c.put("flash_fwd", (9,), "bfloat16", {"block_q": 8, "block_k": 128})
        c.save()
        assert tune.lookup("flash_fwd", (9,), "bfloat16") is not None
        monkeypatch.setenv(tune.ENV_DISABLE, "1")
        assert tune.lookup("flash_fwd", (9,), "bfloat16") is None

    def test_persist_winners_takes_lowest_ms_per_key(self, tmp_path):
        cache = tune.TuneCache(str(tmp_path / "t.json"))
        rows = [
            {"op": "flash_fwd", "shape": (1, 2, 1, 256, 256, 64),
             "dtype": "bfloat16", "params": {"block_q": 256, "block_k": 256}, "ms": 9.0},
            {"op": "flash_fwd", "shape": (1, 2, 1, 256, 256, 64),
             "dtype": "bfloat16", "params": {"block_q": 128, "block_k": 128}, "ms": 4.0},
            {"op": "flash_fwd", "shape": (1, 2, 1, 256, 256, 64),
             "dtype": "bfloat16", "params": {"block_q": 512, "block_k": 512},
             "ms": None, "error": "OOM"},
        ]
        tune.persist_winners(rows, cache)
        got = cache.get("flash_fwd", (1, 2, 1, 256, 256, 64), "bfloat16")
        assert got == {"block_q": 128, "block_k": 128}


class TestKernelConsult:
    def test_flash_entry_points_pick_the_tuned_blocks_up(self, tmp_path, monkeypatch):
        from tony_tpu.ops import attention as A

        path = str(tmp_path / "tune.json")
        monkeypatch.setenv(tune.ENV_CACHE, path)
        q = jnp.zeros((1, 2, 256, 64), jnp.bfloat16)
        shape = (1, 2, 1, 256, 256, 64)
        # cold cache → module defaults
        assert A._tuned_blocks("flash_fwd", q, 1, 256) == A._block_sizes(256, 256)
        c = tune.TuneCache(path)
        c.put("flash_fwd", shape, "bfloat16", {"block_q": 128, "block_k": 128})
        c.put("flash_bwd", shape, "bfloat16", {"block_q": 64, "block_k": 256})
        c.save()
        assert A._tuned_blocks("flash_fwd", q, 1, 256) == (128, 128)
        # fwd and bwd are tuned independently
        assert A._tuned_blocks("flash_bwd", q, 1, 256) == (64, 256)

    def test_explicit_env_override_beats_the_cache(self, tmp_path, monkeypatch):
        """Review-caught precedence: TONY_FLASH_BQ/BK (and TONY_MOE_TILE)
        are the operator's explicit debugging lever — a tune-cache hit must
        not silently win over them."""
        from tony_tpu.ops import attention as A
        from tony_tpu.ops import moe_gemm

        path = str(tmp_path / "tune.json")
        monkeypatch.setenv(tune.ENV_CACHE, path)
        c = tune.TuneCache(path)
        c.put("flash_fwd", (1, 2, 1, 256, 256, 64), "bfloat16",
              {"block_q": 128, "block_k": 128})
        c.put("moe_gemm", (8, 64, 128), "bfloat16", {"tile": 64})
        c.save()
        q = jnp.zeros((1, 2, 256, 64), jnp.bfloat16)
        assert A._tuned_blocks("flash_fwd", q, 1, 256) == (128, 128)
        monkeypatch.setenv("TONY_FLASH_BQ", "256")
        assert A._tuned_blocks("flash_fwd", q, 1, 256) == A._block_sizes(256, 256)
        assert moe_gemm.tuned_tile(8, 64, 128, "bfloat16") == 64
        monkeypatch.setenv("TONY_MOE_TILE", str(moe_gemm.TILE_M))
        assert moe_gemm.tuned_tile(8, 64, 128, "bfloat16") == moe_gemm.TILE_M

    def test_stale_entry_degrades_to_default_not_lowering_failure(
            self, tmp_path, monkeypatch):
        from tony_tpu.ops import attention as A

        path = str(tmp_path / "tune.json")
        monkeypatch.setenv(tune.ENV_CACHE, path)
        q = jnp.zeros((1, 2, 256, 64), jnp.bfloat16)
        shape = (1, 2, 1, 256, 256, 64)
        c = tune.TuneCache(path)
        # 192 does not divide 256; 100 is not lane-aligned — both invalid
        c.put("flash_fwd", shape, "bfloat16", {"block_q": 192, "block_k": 100})
        c.save()
        assert A._tuned_blocks("flash_fwd", q, 1, 256) == A._block_sizes(256, 256)

    def test_tuned_flash_matches_reference_numerics(self, tmp_path, monkeypatch):
        """A cache winner actually changes the kernel grid AND the math
        stays right (interpret mode on CPU)."""
        from tony_tpu.ops import attention as A

        path = str(tmp_path / "tune.json")
        monkeypatch.setenv(tune.ENV_CACHE, path)
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (1, 2, 256, 64), jnp.float32) * 0.5
        k = jax.random.normal(ks[1], (1, 1, 256, 64), jnp.float32) * 0.5
        v = jax.random.normal(ks[2], (1, 1, 256, 64), jnp.float32) * 0.5
        c = tune.TuneCache(path)
        c.put("flash_fwd", (1, 2, 1, 256, 256, 64), "float32",
              {"block_q": 128, "block_k": 128})
        c.save()
        assert A._tuned_blocks("flash_fwd", q, 1, 256) == (128, 128)
        got = A.flash_attention(q, k, v, causal=True)
        want = A.attention_reference(
            q, A.repeat_kv(k, 2), A.repeat_kv(v, 2), causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-3, rtol=2e-3)

    def test_int8_corrupt_cache_entry_degrades_not_crashes(self, tmp_path, monkeypatch):
        """Review-caught: a zero/misaligned tuned block must fall back to
        the shipped defaults, not ZeroDivisionError at trace time."""
        from tony_tpu.ops import quant

        path = str(tmp_path / "tune.json")
        monkeypatch.setenv(tune.ENV_CACHE, path)
        x = jnp.ones((128, 256), jnp.float32)
        qt = quant.quantize_int8(np.ones((256, 256), np.float32))
        c = tune.TuneCache(path)
        c.put("int8_matmul", (128, 256, 256), "float32",
              {"block_m": 0, "block_n": -128, "block_k": 100})
        c.save()
        out = quant.int8_matmul(x, qt)          # must not raise
        want = quant.int8_matmul_ref(x, qt)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-2, rtol=1e-2)

    def test_moe_tuned_tile_validates_entries(self, tmp_path, monkeypatch):
        from tony_tpu.ops import moe_gemm

        path = str(tmp_path / "tune.json")
        monkeypatch.setenv(tune.ENV_CACHE, path)
        assert moe_gemm.tuned_tile(8, 64, 128, "bfloat16") == moe_gemm.TILE_M
        c = tune.TuneCache(path)
        c.put("moe_gemm", (8, 64, 128), "bfloat16", {"tile": 64})
        c.save()
        assert moe_gemm.tuned_tile(8, 64, 128, "bfloat16") == 64
        c.put("moe_gemm", (8, 64, 128), "bfloat16", {"tile": 60})  # not 8-aligned
        c.save()
        assert moe_gemm.tuned_tile(8, 64, 128, "bfloat16") == moe_gemm.TILE_M

    def test_sweep_flash_measures_and_persists_on_this_backend(self, tmp_path, monkeypatch):
        """The whole tony tune flow, CPU interpret mode: sweep a tiny
        geometry, persist, and see the kernel entry point consult it."""
        from tony_tpu.ops import attention as A

        path = str(tmp_path / "tune.json")
        monkeypatch.setenv(tune.ENV_CACHE, path)
        rows = tune.sweep_flash(1, 2, 1, 256, 64, dtype="float32", steps=1)
        measured = [r for r in rows if r.get("ms") is not None]
        assert {r["op"] for r in measured} == {"flash_fwd", "flash_bwd"}
        tune.persist_winners(rows)
        q = jnp.zeros((1, 2, 256, 64), jnp.float32)
        bq, bk = A._tuned_blocks("flash_fwd", q, 1, 256)
        best = min((r for r in measured if r["op"] == "flash_fwd"),
                   key=lambda r: r["ms"])
        assert (bq, bk) == (best["params"]["block_q"], best["params"]["block_k"])

    @pytest.mark.slow
    def test_tune_cli_dry_run_and_persist(self, tmp_path, capsys):
        from tony_tpu.cli.tune import main as tune_main

        cache = str(tmp_path / "tune.json")
        rc = tune_main(["--flash", "1,2,1,256,64", "--dtype", "float32",
                        "--steps", "1", "--dry-run"])
        assert rc == 0
        assert not os.path.exists(cache)
        rc = tune_main(["--flash", "1,2,1,256,64", "--dtype", "float32",
                        "--steps", "1", "--cache", cache])
        assert rc == 0
        data = json.loads(open(cache).read())
        assert any("flash_fwd" in k for k in data["entries"])

    def test_tune_cli_usage_errors(self, capsys):
        from tony_tpu.cli.tune import main as tune_main

        assert tune_main([]) == 2                       # nothing to sweep
        assert tune_main(["--flash", "1,2"]) == 2       # bad dims

    def test_tune_cli_registered_in_tony_main(self, capsys):
        from tony_tpu.cli.main import main as tony_main

        assert tony_main([]) == 0
        assert "tune" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# goodput: the input_wait phase
# ---------------------------------------------------------------------------
class TestGoodputInputWait:
    def test_input_wait_spans_claim_their_phase_exactly(self):
        from tony_tpu.cluster.events import Event, EventType

        def ev(t, ts, **payload):
            return Event(EventType(t), payload, ts)

        events = [
            ev("APPLICATION_INITED", 1000),
            ev("TASK_REGISTERED", 1100, task="worker:0"),
            ev("GANG_COMPLETE", 1200, tasks=1),
            ev("TASK_FINISHED", 9000, task="worker:0", exit_code=0),
            ev("APPLICATION_FINISHED", 9500, status="SUCCEEDED"),
        ]
        spans = [
            {"name": "train.input_wait", "start_ms": 3000, "end_ms": 3400},
            {"name": "train.input_wait", "start_ms": 5000, "end_ms": 5100},
        ]
        led = obs_goodput.build_ledger("a", events, spans)
        assert led.phases_ms["input_wait"] == 500
        assert sum(led.phases_ms.values()) == led.wall_ms  # exact partition
        # the waits came OUT of productive, not out of thin air
        assert led.phases_ms["productive"] == 9000 - 1200 - 500

    def test_input_wait_is_a_known_phase(self):
        assert "input_wait" in obs_goodput.PHASE_ORDER


# ---------------------------------------------------------------------------
# collectives: the size-1-axis transfer guard
# ---------------------------------------------------------------------------
class TestStopTransferIfSingle:
    def _shardmapped(self, n):
        from jax.sharding import Mesh, PartitionSpec as P

        from tony_tpu.compat import shard_map
        from tony_tpu.parallel import collectives

        mesh = Mesh(np.array(jax.devices()[:n]).reshape(n), ("ring",))

        def body(x):
            return collectives.stop_transfer_if_single(
                collectives.rotate, "ring", x)

        return shard_map(
            body, mesh=mesh, in_specs=(P("ring"),), out_specs=P("ring"),
            axis_names={"ring"}, check_vma=False,
        )

    def test_size_one_axis_is_identity_with_no_collective(self):
        f = self._shardmapped(1)
        x = jnp.arange(8.0)
        np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(x))
        assert "ppermute" not in str(jax.make_jaxpr(f)(x))

    def test_multi_shard_axis_still_transfers(self):
        from jax.sharding import Mesh, PartitionSpec as P

        from tony_tpu.compat import shard_map
        from tony_tpu.parallel import collectives

        f = self._shardmapped(4)
        x = jnp.arange(8.0)
        assert "ppermute" in str(jax.make_jaxpr(f)(x))
        # guarded == unguarded rotate
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("ring",))
        direct = shard_map(
            lambda x: collectives.rotate(x, "ring"),
            mesh=mesh, in_specs=(P("ring"),), out_specs=P("ring"),
            axis_names={"ring"}, check_vma=False,
        )
        np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(direct(x)))

    def test_ring_attention_single_shard_has_no_ppermute(self):
        """The call-site payoff: a context axis collapsed to one shard (the
        1-chip bench, an elastic shrink) runs ring attention with zero
        collective launches."""
        from jax.sharding import PartitionSpec as P

        from tony_tpu.compat import shard_map
        from tony_tpu.parallel import MeshSpec
        from tony_tpu.parallel.context import ring_attention

        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (1, 2, 32, 16)) for kk in ks)
        mesh = MeshSpec(context=1).build(devices=jax.devices()[:1])
        spec = P(None, None, "context", None)
        ring = shard_map(
            functools.partial(ring_attention, axis_name="context", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={"context"}, check_vma=False,
        )
        assert "ppermute" not in str(jax.make_jaxpr(ring)(q, k, v))
        from tony_tpu.ops.attention import attention_reference

        got = jax.jit(ring)(q, k, v)
        want = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# bench provenance: movement + profile warnings in the gate
# ---------------------------------------------------------------------------
class TestGateMovementWarnings:
    def _rec(self, n, value, **extra):
        # warmup_s varies per round so two flat rounds are distinct records
        # (the gate's self-comparison guard drops content-identical peers)
        return (f"BENCH_r{n:02d}.json", {
            "n": n, "rc": 0,
            "parsed": {"metric": "m_mfu", "value": value, "unit": "mfu",
                       "vs_baseline": round(value / 0.45, 4),
                       "warmup_s": 10.0 + n, **extra},
        })

    def test_unmoved_headline_warns(self):
        from tony_tpu.histserver import gate

        traj = [self._rec(1, 0.4906), self._rec(2, 0.4906)]
        res = gate.evaluate(traj[-1][1], traj)
        assert res.passed  # warn, not fail
        moves = [c for c in res.checks if c.metric == "movement"]
        assert moves and "gate-without-movement" in moves[0].note
        assert moves[0].reference_from == "BENCH_r01.json"

    def test_content_identical_copied_round_still_warns(self):
        """Review-caught: a BENCH_r06 checked in as a byte-identical copy
        of r05 is THE no-movement offense — the peers self-comparison
        guard drops it by content, so the check must detect duplicates
        explicitly."""
        from tony_tpu.histserver import gate

        r5 = self._rec(5, 0.4906)
        r6 = ("BENCH_r06.json", {"n": 6, "rc": 0,
                                 "parsed": dict(r5[1]["parsed"])})
        res = gate.evaluate(r6[1], [self._rec(4, 0.4883), r5, r6])
        moves = [c for c in res.checks if c.metric == "movement"]
        assert moves and "content-identical" in moves[0].note
        assert moves[0].reference_from == "BENCH_r05.json"

    def test_moved_headline_is_quiet(self):
        from tony_tpu.histserver import gate

        traj = [self._rec(1, 0.4906), self._rec(2, 0.5301)]
        res = gate.evaluate(traj[-1][1], traj)
        assert res.passed
        assert not [c for c in res.checks if c.metric == "movement"]

    def test_perf_record_without_profile_reference_warns(self):
        from tony_tpu.histserver import gate

        traj = [self._rec(1, 0.49)]
        cur = self._rec(2, 0.52, kernel_smoke="8/8")[1]
        res = gate.evaluate(cur, traj)
        assert res.passed
        notes = [c for c in res.checks if c.metric == "provenance"]
        assert notes and "profile" in notes[0].note

    def test_profile_reference_satisfies_provenance(self):
        from tony_tpu.histserver import gate

        traj = [self._rec(1, 0.49)]
        cur = self._rec(2, 0.52, kernel_smoke="8/8",
                        profile={"before": "profiles/a", "after": "profiles/b"})[1]
        res = gate.evaluate(cur, traj)
        assert not [c for c in res.checks if c.metric == "provenance"]
