"""Control-plane benchmark suite (`tony cbench`, docs/performance.md
"Control-plane scalability").

Tier-1 runs scaled-down rounds of the five microbenchmarks asserting the
same invariants the checked-in full-scale ``CBENCH_r<N>.json`` records were
produced under — plus the deterministic contracts behind the fixes the
baseline round forced: the heartbeat handler never serializes on the session
lock, journal compaction keeps replay O(live state) with crash-safe snapshot
semantics, the sweep's unchanged-job fast path, and the portal's O(changed)
scrape cache. Full-scale sizes run behind ``-m slow``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

import pytest

from tony_tpu import constants
from tony_tpu.cluster import cbench
from tony_tpu.cluster.cbench import CbenchSizes, write_pool_history
from tony_tpu.cluster.journal import Journal, JournalError, iter_journal, read_journal

pytestmark = [pytest.mark.cbench]

#: tier-1 scale: seconds, not minutes — same invariants as the full rounds
TINY = CbenchSizes(
    apps=150, queues=4, executors=24, heartbeat_seconds=0.4,
    journal_records=600, journal_live_apps=6, history_jobs=25,
    portal_ams=4, seed=7,
)


# ---------------------------------------------------------------- scheduler
class TestSchedulerBench:
    def test_seeded_world_reproduces(self):
        _, views_a, totals_a = cbench._scheduler_world(TINY)
        _, views_b, totals_b = cbench._scheduler_world(TINY)
        assert totals_a == totals_b
        assert [(v.app_id, v.queue, v.demand, v.admitted) for v in views_a] \
            == [(v.app_id, v.queue, v.demand, v.admitted) for v in views_b]

    def test_bench_scheduler_invariants(self):
        got = cbench.bench_scheduler(TINY, passes=6)
        assert got["sched_decisions_per_sec"] > 0
        assert 0 < got["sched_decision_p50_ms"] <= got["sched_decision_p99_ms"]
        # the seeded world leaves real work on the table: a pass admits some
        assert got["sched_admitted_per_pass"] > 0
        # provenance: the record names which implementation it measured
        assert got["sched_policy"] == "indexed"
        # steady-state sub-bench (r14): 100 delta-fed passes over a
        # persistent WorldIndex — present, positive, and consistent
        assert got["sched_incremental_p50_ms"] > 0
        assert got["sched_incremental_passes_per_sec"] > 0

    def test_bench_scheduler_reference_impl(self):
        """The kill-switch spelling runs the reference pass (and has no
        steady-state sub-bench — there is no persistent world to measure)."""
        got = cbench.bench_scheduler(TINY, passes=2, policy_impl="reference")
        assert got["sched_policy"] == "reference"
        assert "sched_incremental_p50_ms" not in got

    def test_cold_pass_decisions_match_reference(self):
        """The benchmark world itself is a parity fixture: both
        implementations admit the same apps from the same seeded world."""
        a = cbench.bench_scheduler(TINY, passes=2)
        b = cbench.bench_scheduler(TINY, passes=2, policy_impl="reference")
        assert a["sched_admitted_per_pass"] == b["sched_admitted_per_pass"]


# ------------------------------------------------------- heartbeat fan-in
class TestHeartbeatFanIn:
    def test_bench_heartbeats_smoke(self, tmp_path):
        got = cbench.bench_heartbeats(TINY, str(tmp_path), threads=2)
        assert got["heartbeats_per_sec"] > 0
        assert 0 < got["heartbeat_p50_ms"] <= got["heartbeat_p99_ms"]
        assert got["heartbeat_churn_p99_ms"] > 0

    def test_handler_does_not_serialize_on_the_session_lock(self, tmp_path):
        """The epoch-lock/session-lock decoupling, asserted deterministically
        (acceptance: handler p99 unaffected by monitor-loop activity): with
        the session lock HELD — a monitor-loop snapshot in progress — a
        heartbeat must still answer, because the beat lands in the lock-free
        ledger. Pre-decoupling this call blocked until the lock released."""
        from tony_tpu.cluster.rpc import RpcClient

        sizes = CbenchSizes(executors=4, seed=1)
        am = cbench._bench_am(sizes, str(tmp_path))
        try:
            host, port = am.rpc.address
            cli = RpcClient(host, port, secret=am.secret, timeout_s=5.0)
            try:
                # first beat flips REGISTERED→RUNNING (the one lock touch)
                assert cli.call("task_executor_heartbeat",
                                job_name="worker", index=1, attempt=0)["ack"]
                with am.session.lock:
                    t0 = time.perf_counter()
                    resp = cli.call("task_executor_heartbeat",
                                    job_name="worker", index=1, attempt=0)
                    held_latency = time.perf_counter() - t0
                assert resp["ack"]
                assert held_latency < 2.0
                # the ledger's beat is visible to lock-holding readers
                infos = {f"{t['name']}:{t['index']}": t for t in am.session.task_infos()}
                assert infos["worker:1"]["last_heartbeat_ms"] > 0
            finally:
                cli.close()
        finally:
            am.rpc.stop()

    def test_stale_epoch_still_fenced(self, tmp_path):
        """The single-acquisition rewrite must keep the epoch fence: a beat
        from a killed gang epoch is rejected, never recorded."""
        from tony_tpu.cluster.rpc import RpcClient

        am = cbench._bench_am(CbenchSizes(executors=2, seed=1), str(tmp_path))
        try:
            host, port = am.rpc.address
            cli = RpcClient(host, port, secret=am.secret, timeout_s=5.0)
            try:
                got = cli.call("task_executor_heartbeat",
                               job_name="worker", index=0, attempt=99)
                assert got == {"ack": False, "stale": True}
            finally:
                cli.close()
        finally:
            am.rpc.stop()


# ------------------------------------------------- journal reader/compaction
class TestIterJournal:
    def test_streams_the_same_records_read_journal_returns(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        for i in range(50):
            j.append("rec", i=i)
        j.close()
        streamed = list(iter_journal(path))
        assert streamed == read_journal(path)
        assert [r["i"] for r in streamed] == list(range(50))

    def test_torn_tail_dropped_corrupt_middle_raises(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        with open(path, "w") as f:
            f.write('{"t": "a"}\n{"t": "b"}\n{"t": "c", "x"')  # torn mid-append
        assert [r["t"] for r in iter_journal(path)] == ["a", "b"]
        with open(path, "w") as f:
            f.write('{"t": "a"}\ngarbage\n{"t": "c"}\n')
        with pytest.raises(JournalError, match="corrupt"):
            list(iter_journal(path))

    def test_missing_and_empty_raise(self, tmp_path):
        with pytest.raises(JournalError, match="missing"):
            list(iter_journal(str(tmp_path / "nope.jsonl")))
        path = str(tmp_path / "empty.jsonl")
        Journal(path).close()
        with pytest.raises(JournalError, match="empty"):
            list(iter_journal(path))


class TestJournalCompaction:
    def test_compact_rotates_to_one_snapshot_record(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        for i in range(100):
            j.append("old", i=i)
        assert j.appends_since_compact == 100
        assert j.compact([{"t": "live", "n": 1}])
        assert j.appends_since_compact == 0
        j.append("tail", i=0)
        j.close()
        records = read_journal(path)
        assert [r["t"] for r in records] == ["snapshot", "tail"]
        assert records[0]["records"] == [{"t": "live", "n": 1}]
        with open(path) as f:
            assert sum(1 for line in f if line.strip()) == 2

    def test_torn_snapshot_append_falls_back_to_pre_snapshot_tail(self, tmp_path):
        """A SIGKILL tearing the snapshot append itself (phase 1 of compact)
        must recover from the intact pre-snapshot history — loud, never a
        half-applied snapshot."""
        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        for i in range(5):
            j.append("old", i=i)
        j.close()
        snapshot_line = json.dumps(
            {"t": "snapshot", "records": [{"t": "live"}]}, sort_keys=True)
        with open(path, "a") as f:
            f.write(snapshot_line[: len(snapshot_line) // 2])  # torn mid-write
        records = read_journal(path)
        assert [r["t"] for r in records] == ["old"] * 5

    def test_stale_snapshot_is_refused_by_the_append_token(self, tmp_path):
        """The AM's optimistic-concurrency contract: a snapshot built before
        an append landed must NOT be written — the interleaved record would
        sort before it and be discarded by the replay barrier (a takeover
        would silently lose the transition)."""
        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        j.append("old", i=0)
        token = j.total_appends
        recs = [{"t": "live", "snapshot_of": 1}]  # built "now"...
        j.append("raced", i=1)  # ...but an RPC handler appended meanwhile
        assert j.compact(recs, expected_total=token) is False
        assert [r["t"] for r in read_journal(path)] == ["old", "raced"]
        # with a fresh token the same snapshot goes through
        assert j.compact(recs, expected_total=j.total_appends) is True
        j.close()
        assert [r["t"] for r in read_journal(path)] == ["snapshot"]

    def test_concurrent_appends_never_tear_the_journal(self, tmp_path):
        """Appends racing compactions: every surviving record parses, the
        stream stays valid, and every record appended AFTER the last
        snapshot survives verbatim (the compaction lock's contract)."""
        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        stop = threading.Event()
        appended: list[int] = []

        def writer() -> None:
            i = 0
            while not stop.is_set():
                j.append("rec", i=i)
                appended.append(i)
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            for gen in range(5):
                time.sleep(0.02)
                assert j.compact([{"t": "gen", "n": gen}])
        finally:
            stop.set()
            t.join()
        j.close()
        records = read_journal(path)  # parses end to end: nothing torn
        last_snap = max(i for i, r in enumerate(records) if r["t"] == "snapshot")
        tail = [r["i"] for r in records[last_snap + 1:]]
        assert tail == sorted(tail)
        assert set(tail) <= set(appended)


# -------------------------------------------- pool journal replay benchmark
class TestJournalReplayBench:
    def test_write_pool_history_is_seeded_and_replayable(self, tmp_path):
        from tony_tpu.cluster.pool import PoolService

        path = str(tmp_path / "pool.jsonl")
        write_pool_history(path, records=300, live_apps=5, seed=3)
        svc = PoolService(journal_path=path, port=0)
        try:
            assert {a for a in svc._apps} >= {f"live_{i:05d}" for i in range(5)}
            running = [r for r in svc._containers.values() if r["state"] == "RUNNING"]
            assert len(running) == 5
        finally:
            svc.stop()

    def test_compacted_history_replays_to_the_same_live_state(self, tmp_path):
        from tony_tpu.cluster.pool import PoolService

        plain, compacted = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
        write_pool_history(plain, records=800, live_apps=6, seed=3)
        write_pool_history(compacted, records=800, live_apps=6, seed=3,
                           compact_every=100)
        states = []
        for path in (plain, compacted):
            svc = PoolService(journal_path=path, port=0)
            try:
                # FULL state, not a field subset: any drift between the
                # generator's _PoolShadow vocabulary and the real
                # _snapshot_records_locked/_recover_from_journal_locked pair
                # must fail here, not silently skew the benchmark workload
                apps = {
                    a.app_id: (a.queue, a.priority, a.seq, a.admitted,
                               a.preempted, a.demand_memory, a.demand_vcores,
                               a.demand_chips, list(a.elastic_unit),
                               a.elastic_slack)
                    for a in svc._apps.values()
                }
                conts = {c: {k: v for k, v in rec.items()}
                         for c, rec in svc._containers.items()}
                exits = {k: dict(v) for k, v in svc._app_exits.items()}
                states.append((apps, conts, exits))
            finally:
                svc.stop()
        assert states[0] == states[1]

    def test_replay_is_o_live_state(self, tmp_path):
        """Acceptance: a long history with a fixed live set replays within a
        small constant factor of a short one — asserted on the compacted
        file's RECORD COUNT (deterministic) and, loosely, on wall time."""
        from tony_tpu.cluster.pool import PoolService

        long_p, short_p = str(tmp_path / "long.jsonl"), str(tmp_path / "short.jsonl")
        write_pool_history(long_p, records=6_000, live_apps=20, seed=5,
                           compact_every=300)
        write_pool_history(short_p, records=600, live_apps=20, seed=5)

        def lines(p: str) -> int:
            with open(p) as f:
                return sum(1 for line in f if line.strip())

        assert lines(long_p) <= lines(short_p)  # 10x the history, smaller file

        def replay_s(p: str) -> float:
            t0 = time.perf_counter()
            svc = PoolService(journal_path=p, port=0)
            dt = time.perf_counter() - t0
            live = len([a for a in svc._apps if a.startswith("live_")])
            svc.stop()
            assert live == 20
            return dt

        t_long, t_short = replay_s(long_p), replay_s(short_p)
        assert t_long < t_short * 8 + 0.25  # constant factor, noise-padded

    @pytest.mark.slow
    def test_replay_is_o_live_state_full_scale(self, tmp_path):
        """The acceptance sizes verbatim: 100k records / 200 live apps vs a
        1k-record history with the same live set."""
        from tony_tpu.cluster.pool import PoolService

        long_p, short_p = str(tmp_path / "long.jsonl"), str(tmp_path / "short.jsonl")
        write_pool_history(long_p, records=100_000, live_apps=200, seed=5,
                           compact_every=5_000)
        write_pool_history(short_p, records=1_000, live_apps=200, seed=5)

        def replay_s(p: str) -> float:
            t0 = time.perf_counter()
            svc = PoolService(journal_path=p, port=0)
            dt = time.perf_counter() - t0
            assert len([a for a in svc._apps if a.startswith("live_")]) == 200
            svc.stop()
            return dt

        assert replay_s(long_p) < replay_s(short_p) * 8 + 0.5


# ------------------------------------------------------------ history sweep
class TestHistorySweepBench:
    def test_sweep_then_resweep_converges(self, tmp_path):
        from tony_tpu.histserver.ingest import sweep
        from tony_tpu.histserver.store import HistoryStore

        staging = str(tmp_path / "staging")
        os.makedirs(staging)
        cbench.make_history_fixtures(staging, 12, seed=2)
        store = HistoryStore(str(tmp_path / "h.sqlite"))
        try:
            first = sweep(store, [staging])
            assert first["ingested"] == 12 and not first["errors"]
            second = sweep(store, [staging])
            assert second["unchanged"] == 12 and second["ingested"] == 0
            # a changed .jhist re-ingests (the fast path keys on mtime)
            hist = []
            for dirpath, _, files in os.walk(os.path.join(staging, "history")):
                hist += [os.path.join(dirpath, f) for f in files if f.endswith(".jhist")]
            os.utime(hist[0], ns=(time.time_ns(), time.time_ns()))
            third = sweep(store, [staging])
            assert third["ingested"] == 1 and third["unchanged"] == 11
        finally:
            store.close()

    def test_bench_history_sweep_smoke(self, tmp_path):
        got = cbench.bench_history_sweep(TINY, str(tmp_path))
        assert got["sweep_jobs_per_sec"] > 0
        assert got["resweep_ms"] > 0


# ------------------------------------------------------------ portal scrape
def _portal_world(tmp_path, ams: int, stubs: int = 2):
    """``ams`` running apps whose am_info points at ``stubs`` live stub
    servers that count their get_metrics calls."""
    from tony_tpu.cluster.rpc import RpcServer

    staging = str(tmp_path / "staging")
    inter = os.path.join(staging, "history", constants.HISTORY_INTERMEDIATE_DIR)
    os.makedirs(inter)
    calls = [0] * stubs
    servers = []
    for s in range(stubs):
        srv = RpcServer(port=0, secret="t")

        def get_metrics(slot=s):
            calls[slot] += 1
            return {"identity": "am", "metrics": [], "tasks": {}}

        srv.register("get_metrics", get_metrics)
        srv.start()
        servers.append(srv)
    for i in range(ams):
        app = f"app_{i:03d}"
        host, port = servers[i % stubs].address
        os.makedirs(os.path.join(staging, app))
        with open(os.path.join(staging, app, constants.AM_INFO_FILE), "w") as f:
            json.dump({"host": host, "port": port, "secret": "t"}, f)
        with open(os.path.join(inter, app + constants.HISTORY_SUFFIX), "w") as f:
            f.write("")
    return staging, servers, calls


class TestPortalScrapeCache:
    def _scrape(self, httpd) -> str:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/metrics"
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.read().decode()

    def test_default_ttl_zero_scrapes_every_time(self, tmp_path):
        from tony_tpu.portal.server import serve

        staging, servers, calls = _portal_world(tmp_path, ams=4)
        httpd = serve(os.path.join(staging, "history"), 0, staging_root=staging)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            self._scrape(httpd)
            first = sum(calls)
            self._scrape(httpd)
            assert sum(calls) == first * 2  # no cache at default config
        finally:
            httpd.shutdown()
            httpd.server_close()
            t.join()
            for srv in servers:
                srv.stop()

    def test_ttl_serves_cached_groups_with_age_label(self, tmp_path):
        from tony_tpu.portal.server import serve

        staging, servers, calls = _portal_world(tmp_path, ams=4)
        httpd = serve(os.path.join(staging, "history"), 0, staging_root=staging,
                      scrape_ttl_ms=60_000)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            self._scrape(httpd)
            knocked = sum(calls)
            assert knocked == 4
            body = self._scrape(httpd)
            assert sum(calls) == knocked  # O(changed): nothing moved, no knocks
            assert "tony_portal_scrape_age_seconds" in body
            assert 'app="app_000"' in body  # cached groups still exported
        finally:
            httpd.shutdown()
            httpd.server_close()
            t.join()
            for srv in servers:
                srv.stop()

    def test_moved_am_info_invalidates_its_entry_only(self, tmp_path):
        from tony_tpu.portal.server import serve

        staging, servers, calls = _portal_world(tmp_path, ams=4, stubs=2)
        httpd = serve(os.path.join(staging, "history"), 0, staging_root=staging,
                      scrape_ttl_ms=60_000)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            self._scrape(httpd)
            before = list(calls)
            # a takeover republishes app_000's am_info (content length moves
            # too, so the (mtime, size) key changes even on coarse clocks)
            host, port = servers[0].address
            with open(os.path.join(staging, "app_000", constants.AM_INFO_FILE), "w") as f:
                json.dump({"host": host, "port": port, "secret": "t",
                           "pid": 12345}, f)
            self._scrape(httpd)
            # app_000 lives on stub 0: exactly one extra knock, and stub 1's
            # apps were all served from cache
            assert calls[0] == before[0] + 1
            assert calls[1] == before[1]
        finally:
            httpd.shutdown()
            httpd.server_close()
            t.join()
            for srv in servers:
                srv.stop()

    def test_finished_app_drops_its_cache_entry_and_age_series(self, tmp_path):
        """An app leaving the RUNNING list must not pin its cached groups OR
        its scrape-age gauge series forever (unbounded label cardinality on
        a long-lived portal)."""
        from tony_tpu.portal.server import serve

        staging, servers, _calls = _portal_world(tmp_path, ams=2)
        httpd = serve(os.path.join(staging, "history"), 0, staging_root=staging,
                      scrape_ttl_ms=60_000)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            self._scrape(httpd)
            # app_001 finalizes: its intermediate .jhist is gone
            os.remove(os.path.join(staging, "history",
                                   constants.HISTORY_INTERMEDIATE_DIR,
                                   "app_001" + constants.HISTORY_SUFFIX))
            body = self._scrape(httpd)
            assert 'tony_portal_scrape_age_seconds{app="app_001"}' not in body
            assert 'app="app_000"' in body  # the live app is unaffected
        finally:
            httpd.shutdown()
            httpd.server_close()
            t.join()
            for srv in servers:
                srv.stop()

    def test_bench_portal_scrape_smoke(self, tmp_path):
        got = cbench.bench_portal_scrape(TINY, str(tmp_path), stub_servers=2,
                                         scrapes=2)
        assert got["portal_scrape_ms"] > 0
        assert got["portal_ams_per_sec"] > 0


# ------------------------------------------------------------- CLI + record
class TestCbenchCli:
    def test_cli_emits_a_gateable_record(self, tmp_path):
        from tony_tpu.cli.cbench import main
        from tony_tpu.histserver import gate

        record = str(tmp_path / "CBENCH_r99.json")
        rc = main([
            "--apps", "60", "--queues", "3", "--executors", "6",
            "--heartbeat-seconds", "0.2", "--records", "200",
            "--live-apps", "3", "--jobs", "6", "--ams", "3",
            "--workdir", str(tmp_path / "work"),
            "--bench-record", record, "--round", "99", "--baseline", "1.0",
        ])
        assert rc == 0
        with open(record) as f:
            rec = json.load(f)
        assert gate.validate_record(rec, wrapper=True) == []
        parsed = gate.parsed_of(rec)
        assert parsed["metric"] == "control_plane_ops_per_sec"
        assert isinstance(parsed["sizes"], dict)
        for key in cbench.HEADLINE_COMPONENTS:
            assert parsed[key] > 0


# ------------------------------------------------------------- scale probe
class TestScaleProbe:
    @pytest.mark.slow
    def test_probe_at_100k_apps_names_the_next_wall(self, tmp_path):
        """ROADMAP item 4 stretch, full probe scale: 100k apps / 10k
        executors through the indexed scheduler. The probe must name a
        single dominating phase (the next wall) and report finite scaling
        exponents — and write NO CBENCH round (probe sizes are not the
        headline's provenance)."""
        before = sorted(tmp_path.glob("CBENCH_*.json"))
        got = cbench.bench_scale_probe(
            str(tmp_path), apps=100_000, executors=10_000,
            heartbeat_seconds=1.0, log=lambda m: None)
        assert got["probe_apps"] == 100_000
        assert got["probe_executors"] == 10_000
        assert got["next_wall"] in (
            "sched_cold_pass", "world_index_rebuild", "heartbeat_full_sweep")
        assert got["next_wall_seconds"] > 0
        for key in ("probe_sched_cold_p50_s", "probe_world_index_rebuild_s",
                    "probe_heartbeat_sweep_s", "probe_cold_scaling_exponent",
                    "probe_incremental_scaling_exponent"):
            assert isinstance(got[key], float), key
        # the indexed scheduler's incremental path is the whole point: it
        # must stay far below linear scaling at 10x
        assert got["probe_incremental_scaling_exponent"] < 1.0
        assert sorted(tmp_path.glob("CBENCH_*.json")) == before

    def test_probe_smoke_and_cli_flag(self, tmp_path, capsys):
        """Tier-1 sized probe: same code path, tiny sizes, via the CLI flag
        (which also must not write a bench record)."""
        from tony_tpu.cli.cbench import main

        out = tmp_path / "probe.json"
        rc = main(["--scale-probe", "--apps", "400", "--executors", "8",
                   "--heartbeat-seconds", "0.2",
                   "--workdir", str(tmp_path / "work"), "--out", str(out)])
        assert rc == 0
        capsys.readouterr()
        with open(out) as f:
            got = json.load(f)
        assert got["probe_apps"] == 400
        assert "next_wall" in got and "next_wall_seconds" in got
        assert not list(tmp_path.glob("CBENCH_*.json"))
