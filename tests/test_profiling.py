"""Profiler sidecar (SURVEY.md §5.1 rebuild): window state machine + env wiring."""

import os

from tony_tpu import constants
from tony_tpu.config import TonyConfig, keys
from tony_tpu.train import profiling
from tony_tpu.train.profiling import StepProfiler


class _FakeJaxProfiler:
    def __init__(self):
        self.calls = []

    def start_trace(self, d):
        self.calls.append(("start", d))

    def stop_trace(self):
        self.calls.append(("stop", None))


class TestStepProfiler:
    def test_disabled_without_env(self):
        p = StepProfiler(env={})
        assert not p.enabled
        p.step(0); p.step(100)  # must be a no-op (would import jax otherwise)
        assert not p.active

    def test_window_state_machine(self, tmp_path, monkeypatch):
        import jax

        fake = _FakeJaxProfiler()
        monkeypatch.setattr(jax, "profiler", fake)
        p = StepProfiler(env={
            profiling.ENV_PROFILE_DIR: str(tmp_path / "trace"),
            profiling.ENV_PROFILE_START_STEP: "2",
            profiling.ENV_PROFILE_NUM_STEPS: "3",
        })
        for step in range(8):
            p.step(step)
        assert fake.calls == [("start", str(tmp_path / "trace")), ("stop", None)]
        assert p.done
        p.step(20)  # one window only
        assert len(fake.calls) == 2

    def test_stop_flushes_open_window(self, tmp_path, monkeypatch):
        import jax

        fake = _FakeJaxProfiler()
        monkeypatch.setattr(jax, "profiler", fake)
        p = StepProfiler(env={profiling.ENV_PROFILE_DIR: str(tmp_path),
                              profiling.ENV_PROFILE_START_STEP: "0"})
        p.step(0)
        assert p.active
        p.stop()
        p.stop()  # idempotent
        assert fake.calls.count(("stop", None)) == 1

    def test_short_run_artifact_is_terminated_and_readable(self, tmp_path):
        """Training that finishes before start_step + num_steps used to leave
        the trace unterminated; the train-loop `finally` now stops it — with
        the REAL jax profiler, the capture directory must hold a complete,
        readable artifact after stop()."""
        import gzip

        import jax.numpy as jnp

        trace_dir = tmp_path / "trace"
        p = StepProfiler(env={
            profiling.ENV_PROFILE_DIR: str(trace_dir),
            profiling.ENV_PROFILE_START_STEP: "0",
            profiling.ENV_PROFILE_NUM_STEPS: "1000",  # run ends long before
        })
        p.step(0)
        assert p.active
        (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
        p.stop()  # what the loop's finally does
        assert p.done and not p.active
        artifacts = [
            os.path.join(root, f)
            for root, _, files in os.walk(trace_dir)
            for f in files
        ]
        xplanes = [a for a in artifacts if a.endswith(".xplane.pb")]
        assert xplanes and os.path.getsize(xplanes[0]) > 0, artifacts
        for gz in (a for a in artifacts if a.endswith(".trace.json.gz")):
            with gzip.open(gz) as f:  # terminated, not torn: gzip readable
                assert f.read(16)


class TestExecutorEnvWiring:
    def test_profile_env_injected(self, monkeypatch, tmp_path):
        """build_child_env exports the profile contract when enabled."""
        from tony_tpu.cluster.executor import TaskExecutor

        staging = tmp_path / "stage"
        staging.mkdir()
        cfg = TonyConfig({
            "tony.worker.instances": "1",
            keys.TASK_PROFILE: "true",
            keys.TASK_PROFILE_START_STEP: "7",
        })
        cfg.freeze()
        cfg.write_final(str(staging))
        env = {
            constants.ENV_APP_ID: "app",
            constants.ENV_STAGING_DIR: str(staging),
            constants.ENV_JOB_NAME: "worker",
            constants.ENV_TASK_INDEX: "0",
            constants.ENV_AM_PORT: "1",
        }
        ex = TaskExecutor(env=env)
        child_env = ex.build_child_env({"worker": ["h:1"]}, {})
        assert child_env[profiling.ENV_PROFILE_DIR].endswith(os.path.join("profile", "worker_0"))
        assert child_env[profiling.ENV_PROFILE_START_STEP] == "7"

    def test_introspection_env_injected(self, tmp_path):
        """The on-demand + logging contracts ride the same env channel: the
        control-file poll throttle and the structured-log sink/level."""
        from tony_tpu.cluster.executor import TaskExecutor

        staging = tmp_path / "stage"
        staging.mkdir()
        cfg = TonyConfig({
            "tony.worker.instances": "1",
            keys.PROFILE_POLL_INTERVAL_MS: "250",
            keys.LOG_LEVEL: "debug",
        })
        cfg.freeze()
        cfg.write_final(str(staging))
        env = {
            constants.ENV_APP_ID: "app",
            constants.ENV_STAGING_DIR: str(staging),
            constants.ENV_JOB_NAME: "worker",
            constants.ENV_TASK_INDEX: "0",
            constants.ENV_AM_PORT: "1",
        }
        ex = TaskExecutor(env=env)
        child_env = ex.build_child_env({"worker": ["h:1"]}, {})
        assert child_env[profiling.ENV_PROFILE_POLL_MS] == "250"
        assert child_env[constants.ENV_LOG_DIR] == os.path.join(str(staging), "logs")
        assert child_env[constants.ENV_LOG_LEVEL] == "debug"

    def test_log_level_off_skips_child_contract(self, tmp_path):
        from tony_tpu.cluster.executor import TaskExecutor

        staging = tmp_path / "stage"
        staging.mkdir()
        cfg = TonyConfig({
            "tony.worker.instances": "1",
            keys.LOG_LEVEL: "off",
        })
        cfg.freeze()
        cfg.write_final(str(staging))
        env = {
            constants.ENV_APP_ID: "app",
            constants.ENV_STAGING_DIR: str(staging),
            constants.ENV_JOB_NAME: "worker",
            constants.ENV_TASK_INDEX: "0",
            constants.ENV_AM_PORT: "1",
        }
        ex = TaskExecutor(env=env)
        child_env = ex.build_child_env({"worker": ["h:1"]}, {})
        assert constants.ENV_LOG_DIR not in child_env
