"""Trace-driven capacity planning (cluster/replay.py, `tony sim
--from-history`, portal /pool/whatif): reconstruct recorded history into a
workload, gate a no-override replay on reproducing the recorded decision
sequence exactly, and answer what-ifs with counterfactual reports.

The fidelity headline drives a REAL PoolService through a multi-queue
admit/shrink/evict episode and replays its journal; the rest of the suite
covers the exit-code contract, override/sweep directionality, torn/partial
inputs (byte-chopped journal, mid-sweep history DB), and the portal page.
"""

from __future__ import annotations

import json
import threading
import urllib.request

import pytest

from tests.test_pool import register_cpu_node
from tony_tpu.cli.sim import main as sim_main
from tony_tpu.cluster.pool import PoolService
from tony_tpu.cluster.replay import (
    ReplayError,
    parse_override,
    parse_sweep,
    reconstruct,
    run_whatif,
)

pytestmark = [pytest.mark.replay]

GB = 1024**3
T0 = 1_700_000_000.0  # fixed wall-clock origin for synthesized journals


# ---------------------------------------------------------------------------
# synthesized journals (hand-written history with known shape)
# ---------------------------------------------------------------------------
def _app_row(app_id, queue, seq, admitted, preempted, demand_gb, wait_unix,
             admitted_unix, unit=(0, 0, 0), slack=0):
    return {
        "t": "app", "app_id": app_id, "queue": queue, "priority": 0,
        "seq": seq, "admitted": admitted, "preempted": preempted,
        "demand_memory": demand_gb * GB, "demand_vcores": 1,
        "demand_chips": 0, "wait_unix": wait_unix,
        "admitted_unix": admitted_unix, "elastic_unit": list(unit),
        "elastic_slack": slack,
    }


def _congested_journal(path):
    """Two 4 GiB prod hogs fill an 8 GiB pool for ~120s; four 2 GiB dev
    jobs queue behind them and only run after the hogs leave. The recorded
    admit order is hand-written (the real policy would preempt), so this
    fixture doubles as the fidelity-divergence case; its point is the
    counterfactual: more dev share → less dev wait."""
    recs = [
        {"t": "config", "queues": {"prod": 0.6, "dev": 0.4},
         "preemption": True, "grace_ms": 0, "drain_ms": 5000,
         "min_runtime_ms": 0, "budget": 0, "budget_window_ms": 60_000,
         "unix": T0},
        {"t": "capacity", "totals": [8 * GB, 256, 0], "unix": T0},
        _app_row("p1", "prod", 0, False, False, 4, T0, 0.0),
        _app_row("p1", "prod", 0, True, False, 4, T0, T0),
        _app_row("p2", "prod", 1, False, False, 4, T0 + 1, 0.0),
        _app_row("p2", "prod", 1, True, False, 4, T0 + 1, T0 + 1),
    ]
    recs += [_app_row(f"d{i}", "dev", 2 + i, False, False, 2, T0 + 5 + i, 0.0)
             for i in range(4)]
    recs += [
        {"t": "app_removed", "app_id": "p1", "unix": T0 + 120},
        {"t": "app_removed", "app_id": "p2", "unix": T0 + 121},
    ]
    recs += [_app_row(f"d{i}", "dev", 2 + i, True, False, 2, T0 + 5 + i,
                      T0 + 121) for i in range(4)]
    recs += [{"t": "app_removed", "app_id": f"d{i}", "unix": T0 + 131}
             for i in range(4)]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return path


def _calm_journal(path):
    """Two non-contending jobs: a journal whose recorded sequence the
    policy reproduces trivially (the exit-0 fidelity fixture)."""
    recs = [
        {"t": "config", "queues": {"prod": 0.6, "dev": 0.4},
         "preemption": True, "grace_ms": 0, "drain_ms": 5000,
         "min_runtime_ms": 0, "budget": 0, "budget_window_ms": 60_000,
         "unix": T0},
        {"t": "capacity", "totals": [8 * GB, 256, 0], "unix": T0},
        _app_row("p1", "prod", 0, False, False, 4, T0, 0.0),
        _app_row("p1", "prod", 0, True, False, 4, T0, T0),
        _app_row("d1", "dev", 1, False, False, 2, T0 + 5, 0.0),
        _app_row("d1", "dev", 1, True, False, 2, T0 + 5, T0 + 5),
        {"t": "app_removed", "app_id": "d1", "unix": T0 + 30},
        {"t": "app_removed", "app_id": "p1", "unix": T0 + 60},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return path


# ---------------------------------------------------------------------------
# the fidelity headline: a REAL pool's journal replays exactly
# ---------------------------------------------------------------------------
class TestFidelityAgainstLivePool:
    def test_recorded_multi_queue_run_replays_exactly(self, tmp_path):
        """Drive a real PoolService through admits, an elastic shrink, a
        whole-gang evict, and a post-release re-admit; the no-override
        replay must reproduce that admit/evict/shrink sequence exactly
        (ROADMAP item 4's fidelity gate). Event spacing (1.5s) stays above
        the sim's 1 Hz revisit tick so virtual decision instants cannot
        alias."""
        import time

        journal = tmp_path / "pool.jsonl"
        svc = PoolService(
            port=0, preemption=True, preemption_drain_ms=2000,
            queues={"prod": 0.6, "dev": 0.4}, journal_path=str(journal))
        try:
            register_cpu_node(svc, "n0", memory=8 * GB, vcores=64)
            # dev1: elastic, 6 GiB (over dev's 3.2 GiB share — admitted
            # work-conserving while the pool is empty), may shed to 2 GiB
            svc.register_app("dev1", queue="dev", memory_bytes=6 * GB,
                             vcores=6, elastic_unit=[GB, 1, 0],
                             elastic_slack=4)
            time.sleep(1.5)
            # prod1's 4 GiB is within prod's 4.8 GiB share cap (reclaim
            # never funds past the cap): share-reclaim shrinks dev1 by two
            # workers instead of evicting it, and admits prod1 same pass
            svc.register_app("prod1", queue="prod", memory_bytes=4 * GB, vcores=4)
            time.sleep(1.5)
            svc.release_all("prod1")
            time.sleep(1.0)
            svc.register_app("dev2", queue="dev", memory_bytes=3 * GB, vcores=3)
            time.sleep(1.5)
            # prod2 needs 4 GiB with 1 GiB free: whole-gang-evicting dev2
            # (3 GiB, no containers running → instant requeue) covers it
            svc.register_app("prod2", queue="prod", memory_bytes=4 * GB, vcores=4)
            time.sleep(1.5)
            svc.release_all("prod2")
            time.sleep(1.5)
            for app in ("dev2", "dev1"):
                svc.release_all(app)
        finally:
            svc.rpc.stop()

        trace = reconstruct(str(journal))
        assert trace.kind == "journal"
        assert not trace.incomplete, trace.notes
        assert trace.queues == {"prod": 0.6, "dev": 0.4}
        assert trace.totals[0] == 8 * GB
        assert trace.knobs["drain_ms"] == 2000
        actions = [e.action for e in trace.recorded]
        # the episode must actually exercise all three decision kinds, or
        # the gate gates nothing
        assert actions.count("admit") >= 4, trace.recorded
        assert "shrink" in actions, trace.recorded
        assert "evict" in actions, trace.recorded

        report = run_whatif(trace)
        fid = report["fidelity"]
        assert fid["applicable"]
        assert fid["ok"], fid["detail"]
        assert fid["recorded_len"] == len(trace.recorded)

        # the CLI contract on the same journal: 0 = fidelity OK
        assert sim_main(["--from-history", str(journal)]) == 0


# ---------------------------------------------------------------------------
# exit-code contract (satellite: mirrors the lint / bench-gate CLIs)
# ---------------------------------------------------------------------------
class TestExitCodeContract:
    def test_exit_0_when_counterfactual_report_produced(self, tmp_path, capsys):
        journal = _congested_journal(tmp_path / "j.jsonl")
        rc = sim_main(["--from-history", str(journal),
                       "--override", "share.dev=0.5", "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["overrides"] == {"share.dev": 0.5}
        assert "delta" in report

    def test_exit_0_on_exact_fidelity(self, tmp_path):
        assert sim_main(["--from-history",
                         str(_calm_journal(tmp_path / "j.jsonl"))]) == 0

    def test_exit_1_on_fidelity_divergence(self, tmp_path, capsys):
        journal = _congested_journal(tmp_path / "j.jsonl")
        assert sim_main(["--from-history", str(journal)]) == 1
        out = capsys.readouterr().out
        assert "DIVERGED" in out
        # the loud report names the first divergent decision and shows the
        # replay's causal chain (pool_explain vocabulary)
        assert "decision #" in out
        assert "replay chain" in out

    def test_exit_2_on_missing_and_garbage_input(self, tmp_path, capsys):
        assert sim_main(["--from-history", str(tmp_path / "nope.jsonl")]) == 2
        garbage = tmp_path / "garbage.bin"
        garbage.write_bytes(b"\x00\xffnot a journal\n" * 4)
        assert sim_main(["--from-history", str(garbage)]) == 2
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert sim_main(["--from-history", str(empty)]) == 2
        capsys.readouterr()

    def test_exit_2_on_bad_override_and_sweep_specs(self, tmp_path, capsys):
        journal = _calm_journal(tmp_path / "j.jsonl")
        assert sim_main(["--from-history", str(journal),
                         "--override", "bogus=1"]) == 2
        assert sim_main(["--from-history", str(journal),
                         "--sweep", "share.dev=broken"]) == 2
        assert sim_main(["--from-history", str(journal),
                         "--override", "share.nosuch=0.5"]) == 2
        capsys.readouterr()

    def test_override_flags_require_from_history(self, capsys):
        assert sim_main(["--override", "share.dev=0.5"]) == 2
        capsys.readouterr()


# ---------------------------------------------------------------------------
# counterfactuals: the whole point
# ---------------------------------------------------------------------------
class TestCounterfactuals:
    def test_share_sweep_deltas_are_directional(self, tmp_path, capsys):
        """More dev share → monotonically non-increasing dev queue wait:
        the acceptance criterion's direction check, read from the CLI's
        --json output."""
        journal = _congested_journal(tmp_path / "j.jsonl")
        rc = sim_main(["--from-history", str(journal),
                       "--sweep", "share.dev=0.1:0.5:0.2", "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        rows = report["sweep"]["rows"]
        assert [r["value"] for r in rows] == [0.1, 0.3, 0.5]
        p99 = [r["metrics"]["queue_wait"]["dev"]["wait_p99_s"] for r in rows]
        assert all(a >= b - 1e-9 for a, b in zip(p99, p99[1:])), p99
        assert p99[0] > p99[-1], "sweep must actually move the dev wait"
        # and the grid table renders in text mode too
        assert sim_main(["--from-history", str(journal),
                         "--sweep", "share.dev=0.1:0.5:0.2"]) == 0
        assert "sweep over share.dev" in capsys.readouterr().out

    def test_single_override_reports_delta_and_notes_renormalization(
            self, tmp_path):
        trace = reconstruct(str(_congested_journal(tmp_path / "j.jsonl")))
        report = run_whatif(trace, {"share.dev": 0.5})
        # 0.5 + prod 0.6 oversubscribes: prod is rescaled, loudly
        assert any("rescaled" in n for n in report["config_notes"])
        assert report["delta"]["queue_wait"]["dev"]["wait_p99_s_delta"] < 0
        # decision records explaining the variant ride the report
        assert any(r["action"] in ("admit", "evict", "shrink")
                   for r in report["variant_decisions"])

    def test_capacity_and_knob_overrides_parse(self):
        assert parse_override("memory-gb=16") == ("memory-gb", 16.0)
        assert parse_override("drain-ms=10000") == ("drain-ms", 10000.0)
        assert parse_override("preemption=0") == ("preemption", 0.0)
        with pytest.raises(ReplayError):
            parse_override("share=0.5")  # share needs a queue
        key, vals = parse_sweep("drain-ms=0:10000:5000")
        assert key == "drain-ms" and vals == [0.0, 5000.0, 10000.0]
        with pytest.raises(ReplayError):
            parse_sweep("share.dev=0.5:0.1:0.1")  # hi < lo
        with pytest.raises(ReplayError):
            parse_sweep("share.dev=0:1:0.001")  # > 64 grid points

    def test_more_capacity_reduces_waits(self, tmp_path):
        trace = reconstruct(str(_congested_journal(tmp_path / "j.jsonl")))
        report = run_whatif(trace, {"memory-gb": 16})
        d = report["delta"]["queue_wait"]["dev"]
        assert d["wait_p99_s_delta"] <= 0
        assert report["variant"]["queue_wait"]["dev"]["wait_p99_s"] == 0.0


# ---------------------------------------------------------------------------
# torn / partial inputs (satellite: journal.py's torn-tail discipline)
# ---------------------------------------------------------------------------
class TestTornAndPartialInputs:
    def test_byte_chopped_journal_is_usable_and_flagged(self, tmp_path):
        journal = _congested_journal(tmp_path / "j.jsonl")
        raw = journal.read_bytes()
        # chop mid-record somewhere past the first few app rows: the torn
        # final line is dropped (journal discipline) and the apps left
        # mid-flight surface as an explicit incomplete flag
        chopped = tmp_path / "chopped.jsonl"
        chopped.write_bytes(raw[: int(len(raw) * 0.6)])
        trace = reconstruct(str(chopped))
        assert trace.incomplete
        assert any("mid-flight" in n or "truncated" in n for n in trace.notes)
        assert trace.jobs, "truncated-but-USABLE: the surviving apps replay"
        report = run_whatif(trace, {"share.dev": 0.5})
        assert "delta" in report
        assert report["trace"]["incomplete"] is True

    def test_midfile_garbage_truncates_with_note_never_crashes(self, tmp_path):
        journal = _calm_journal(tmp_path / "j.jsonl")
        lines = journal.read_text().splitlines(keepends=True)
        # corrupt a MIDDLE line (not the tail): iter_journal raises
        # JournalError lazily; reconstruction must degrade, not die
        lines[3] = "{this is not json\n"
        bad = tmp_path / "bad.jsonl"
        bad.write_text("".join(lines))
        trace = reconstruct(str(bad))
        assert trace.incomplete
        assert any("truncated mid-stream" in n for n in trace.notes)
        assert trace.jobs

    def test_mid_sweep_history_db_yields_incomplete_trace(self, tmp_path):
        from tony_tpu.histserver.store import HistoryStore

        store = HistoryStore(str(tmp_path / "hist.sqlite"))
        windows = [
            {"queue": "prod", "window_start_ms": i * 60_000,
             "window_end_ms": (i + 1) * 60_000,
             "metrics": {"admissions": 2, "used_avg": 4.0 * GB,
                         "share_capacity": 5.0 * GB}}
            for i in range(3)
        ]
        # dev ingested only one window: a sweep caught mid-flight
        windows.append(
            {"queue": "dev", "window_start_ms": 0, "window_end_ms": 60_000,
             "metrics": {"admissions": 1, "used_avg": 2.0 * GB,
                         "share_capacity": 3.0 * GB}})
        store.put_cluster_windows("pool", windows)
        exported = store.cluster_trace("pool")
        assert len(exported) == 4
        store.close()
        trace = reconstruct(str(tmp_path / "hist.sqlite"))
        assert trace.kind == "history-db"
        assert trace.approximate
        assert trace.incomplete  # window coverage differs across queues
        assert any("coverage differs" in n for n in trace.notes)
        assert len(trace.jobs) == 7  # 3*2 prod + 1 dev
        # the fidelity gate does not apply to synthesized workloads — and
        # an approximate replay still reports, exit 0
        report = run_whatif(trace)
        assert report["fidelity"]["applicable"] is False
        assert sim_main(["--from-history", str(tmp_path / "hist.sqlite")]) == 0

    def test_empty_history_db_is_exit_2(self, tmp_path, capsys):
        from tony_tpu.histserver.store import HistoryStore

        HistoryStore(str(tmp_path / "hist.sqlite")).close()
        assert sim_main(["--from-history", str(tmp_path / "hist.sqlite")]) == 2
        assert "no cluster_series rows" in capsys.readouterr().err

    def test_series_file_reconstructs_with_torn_line_skipped(self, tmp_path):
        from tony_tpu.cluster.recorder import window_line

        series = tmp_path / "cluster.series.jsonl"
        lines = [
            window_line("pool", {
                "queue": "prod", "window_start_ms": i * 60_000,
                "window_end_ms": (i + 1) * 60_000,
                "metrics": {"admissions": 1, "used_avg": 4.0 * GB,
                            "share_capacity": 5.0 * GB}})
            for i in range(2)
        ]
        series.write_text("\n".join(lines) + "\n" + lines[0][: len(lines[0]) // 2])
        trace = reconstruct(str(series))
        assert trace.kind == "series"
        assert trace.approximate
        assert len(trace.jobs) == 2


# ---------------------------------------------------------------------------
# journal reconstruction details
# ---------------------------------------------------------------------------
class TestReconstruction:
    def test_missing_config_and_capacity_fall_back_loudly(self, tmp_path):
        """Pre-upgrade journals (no config/capacity records) still replay:
        equal shares, inferred totals, and notes saying exactly that."""
        journal = tmp_path / "old.jsonl"
        recs = [
            _app_row("a1", "q1", 0, False, False, 4, T0, 0.0),
            _app_row("a1", "q1", 0, True, False, 4, T0, T0),
            _app_row("a2", "q2", 1, False, False, 2, T0 + 2, 0.0),
            _app_row("a2", "q2", 1, True, False, 2, T0 + 2, T0 + 2),
            {"t": "app_removed", "app_id": "a1", "unix": T0 + 30},
            {"t": "app_removed", "app_id": "a2", "unix": T0 + 30},
        ]
        journal.write_text("".join(json.dumps(r) + "\n" for r in recs))
        trace = reconstruct(str(journal))
        assert trace.queues == {"q1": 0.5, "q2": 0.5}
        assert trace.totals[0] >= 6 * GB  # peak concurrent admitted demand
        assert any("inferred EQUAL" in n for n in trace.notes)
        assert any("totals inferred" in n for n in trace.notes)

    def test_no_app_records_is_replay_error(self, tmp_path):
        journal = tmp_path / "cfg-only.jsonl"
        journal.write_text(json.dumps(
            {"t": "config", "queues": {"q": 1.0}, "unix": T0}) + "\n")
        with pytest.raises(ReplayError, match="no app records"):
            reconstruct(str(journal))

    def test_compacted_journal_reconstructs_from_snapshot(self, tmp_path):
        """A compacted journal (snapshot barrier + embedded records) folds
        like the pool's own recovery: surviving state replays, and a note
        says pre-snapshot runtimes are folded away."""
        inner = [
            {"t": "config", "queues": {"prod": 1.0}, "preemption": True,
             "grace_ms": 0, "drain_ms": 5000, "min_runtime_ms": 0,
             "budget": 0, "budget_window_ms": 60_000, "unix": T0},
            {"t": "capacity", "totals": [8 * GB, 64, 0], "unix": T0},
            _app_row("a1", "prod", 0, True, False, 4, T0, T0 + 1),
        ]
        recs = [
            {"t": "snapshot", "records": inner},
            {"t": "app_removed", "app_id": "a1", "unix": T0 + 40},
        ]
        journal = (tmp_path / "compacted.jsonl")
        journal.write_text("".join(json.dumps(r) + "\n" for r in recs))
        trace = reconstruct(str(journal))
        assert [j.app_id for j in trace.jobs] == ["a1"]
        assert trace.jobs[0].work_s == pytest.approx(39.0, abs=0.1)
        assert any("compacted" in n for n in trace.notes)

    def test_evict_and_elastic_contract_survive_reconstruction(self, tmp_path):
        journal = tmp_path / "j.jsonl"
        recs = [
            {"t": "config", "queues": {"prod": 0.5, "dev": 0.5},
             "preemption": True, "grace_ms": 0, "drain_ms": 5000,
             "min_runtime_ms": 0, "budget": 0, "budget_window_ms": 60_000,
             "unix": T0},
            {"t": "capacity", "totals": [8 * GB, 64, 0], "unix": T0},
            _app_row("e1", "dev", 0, False, False, 6, T0, 0.0,
                     unit=(GB, 1, 0), slack=4),
            _app_row("e1", "dev", 0, True, False, 6, T0, T0,
                     unit=(GB, 1, 0), slack=4),
            # policy shrink: app row shows reduced demand, drain names it
            _app_row("e1", "dev", 0, True, False, 3, T0, T0,
                     unit=(GB, 1, 0), slack=1),
            {"t": "drain", "app_id": "e1", "req_id": "r1", "mode": "shrink",
             "workers": 3, "target_primary": 3 * GB, "origin": "sched",
             "for_app": "p1", "deadline_unix": T0 + 20, "t0_unix": T0 + 10},
            # later evicted whole for another head
            _app_row("e1", "dev", 0, False, True, 3, T0 + 30, 0.0,
                     unit=(GB, 1, 0), slack=1),
            {"t": "app_removed", "app_id": "e1", "unix": T0 + 60},
        ]
        journal.write_text("".join(json.dumps(r) + "\n" for r in recs))
        trace = reconstruct(str(journal))
        job = trace.jobs[0]
        # ORIGINAL demand and slack (elementwise max over history), not the
        # shrunken remnant — the replay re-decides the shrink itself
        assert job.demand[0] == 6 * GB
        assert job.elastic_slack == 4
        assert job.elastic_unit == (GB, 1, 0)
        keys = [e.key() for e in trace.recorded]
        assert ("admit", "e1") in keys
        assert ("shrink", "e1", 3) in keys
        assert ("evict", "e1") in keys


# ---------------------------------------------------------------------------
# portal /pool/whatif (acceptance: deltas visible on the page too)
# ---------------------------------------------------------------------------
class TestPortalWhatif:
    def _portal(self, tmp_path, journal):
        from tony_tpu.portal.server import serve

        root = tmp_path / "history"
        root.mkdir(exist_ok=True)
        srv = serve(str(root), port=0, pool_journal=str(journal))
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, srv.server_address[1]

    def test_whatif_page_renders_overlay_and_sweep(self, tmp_path):
        journal = _congested_journal(tmp_path / "j.jsonl")
        srv, port = self._portal(tmp_path, journal)
        try:
            api = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/pool/whatif"
                "?override=share.dev=0.5&sweep=share.dev=0.1:0.5:0.2"))
            # directional: more dev share → less dev wait, on the portal too
            assert api["delta"]["queue_wait"]["dev"]["wait_p99_s_delta"] < 0
            p99 = [r["metrics"]["queue_wait"]["dev"]["wait_p99_s"]
                   for r in api["sweep"]["rows"]]
            assert all(a >= b - 1e-9 for a, b in zip(p99, p99[1:])), p99
            page = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/pool/whatif"
                "?override=share.dev=0.5&sweep=share.dev=0.1:0.5:0.2"
            ).read().decode()
            assert "counterfactual" in page
            assert "sweep over share.dev" in page
            # deltas link back to the decision records that explain them
            assert "decision records behind" in page
            assert "baseline" in page and "share.dev" in page
        finally:
            srv.shutdown()

    def test_whatif_without_journal_explains_instead_of_500(self, tmp_path):
        srv, port = self._portal(tmp_path, "")
        try:
            page = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/pool/whatif").read().decode()
            assert "no --pool-journal" in page
            api = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/pool/whatif"))
            assert "error" in api
        finally:
            srv.shutdown()

    def test_whatif_bad_override_is_a_rendered_error(self, tmp_path):
        journal = _calm_journal(tmp_path / "j.jsonl")
        srv, port = self._portal(tmp_path, journal)
        try:
            page = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/pool/whatif?override=bogus=1"
            ).read().decode()
            assert "replay failed" in page
            assert "unknown" in page
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# instruments (metrics-discipline: registered + documented)
# ---------------------------------------------------------------------------
class TestInstruments:
    def test_replay_runs_counter_moves_by_outcome(self, tmp_path):
        from tony_tpu.obs import metrics as obs_metrics

        def counter_value(name, **labels):
            snap = obs_metrics.REGISTRY.snapshot()
            for fam in snap:
                if fam["name"] == name:
                    for s in fam["samples"]:
                        if all(s["labels"].get(k) == v
                               for k, v in labels.items()):
                            return s["value"]
            return 0.0

        before = counter_value("tony_sim_replay_runs_total",
                               outcome="counterfactual")
        trace = reconstruct(str(_calm_journal(tmp_path / "j.jsonl")))
        run_whatif(trace, {"drain-ms": 1000})
        after = counter_value("tony_sim_replay_runs_total",
                              outcome="counterfactual")
        assert after == before + 1
