"""Pallas remote-DMA ring attention vs the XLA ring implementation.

Runs the kernel in TPU-interpret mode (emulated RDMA/semaphores, race
detection on) inside shard_map over a 4-device ``context`` axis on the
virtual CPU mesh — the kernel-level analog of how the reference tests
multi-node logic without a cluster (SURVEY.md §4).
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from tony_tpu.compat import (
    cpu_devices_configurable,
    shard_map,
    tpu_interpret_supported,
)
from tony_tpu.ops.attention import attention_reference, repeat_kv
from tony_tpu.parallel.context import ring_attention

# The whole suite leans on two newer-jax features: the TPU Pallas
# interpreter (pltpu.InterpretParams — emulated RDMA/semaphores the generic
# interpret=True path can't provide) and, for the subprocess cases,
# re-sizing the virtual CPU mesh via the jax_num_cpu_devices config option.
# On builds missing either, skip cleanly instead of 14 AttributeErrors.
pytestmark = pytest.mark.skipif(
    not (tpu_interpret_supported() and cpu_devices_configurable()),
    reason="jax build lacks pltpu.InterpretParams and/or jax_num_cpu_devices",
)


def _interpret_params():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.InterpretParams(detect_races=True)


def _mk_qkv(B=1, H=4, Hkv=2, T=256, D=64, seed=3):
    ks = [jax.random.fold_in(jax.random.PRNGKey(seed), i) for i in range(3)]
    q = jax.random.normal(ks[0], (B, H, T, D), jnp.float32) * 0.5
    k = jax.random.normal(ks[1], (B, Hkv, T, D), jnp.float32) * 0.5
    v = jax.random.normal(ks[2], (B, Hkv, T, D), jnp.float32) * 0.5
    return q, k, v


def _shard_ring(fn, mesh):
    spec = P(None, None, "context", None)
    return jax.jit(
        shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={"context"}, check_vma=False,
        )
    )


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_ring_matches_reference(causal):
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    q, k, v = _mk_qkv()
    ring = _shard_ring(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=causal,
            interpret=_interpret_params(),
        ),
        mesh,
    )
    out = ring(q, k, v)
    want = attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_pallas_ring_matches_xla_ring():
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    q, k, v = _mk_qkv(seed=5)
    pallas_ring = _shard_ring(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=True,
            interpret=_interpret_params(),
        ),
        mesh,
    )
    xla_ring = _shard_ring(
        functools.partial(ring_attention, axis_name="context", causal=True), mesh
    )
    np.testing.assert_allclose(
        np.asarray(pallas_ring(q, k, v)),
        np.asarray(xla_ring(q, repeat_kv(k, 2), repeat_kv(v, 2))),
        atol=2e-5, rtol=2e-5,
    )


def test_pallas_ring_multi_tile():
    # Tl=512 per device → bq=bk=256, num_qb=num_kb=2: exercises the kb loop,
    # the per-tile causal skip, and acc/m/l staging across multiple q blocks
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    q, k, v = _mk_qkv(H=2, Hkv=1, T=2048, seed=11)
    ring = _shard_ring(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=True,
            interpret=_interpret_params(),
        ),
        mesh,
    )
    out = ring(q, k, v)
    want = attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_pallas_ring_eight_devices():
    # n=8: seven ring rotations → the per-neighbor ready/parity handshake
    # cycles both slots repeatedly (race detection is on in interpret mode)
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:8]), ("context",))
    q, k, v = _mk_qkv(H=2, Hkv=1, T=512, seed=13)
    ring = _shard_ring(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=True,
            interpret=_interpret_params(),
        ),
        mesh,
    )
    out = ring(q, k, v)
    want = attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_pallas_ring_backward():
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    q, k, v = _mk_qkv(seed=7)
    w = jnp.arange(64, dtype=jnp.float32) / 64.0

    def make_loss(attn):
        def body(q, k, v):
            return jax.lax.psum((attn(q, k, v) * w).sum(), "context")

        spec = P(None, None, "context", None)
        inner = shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=P(),
            axis_names={"context"}, check_vma=False,
        )
        return jax.jit(jax.grad(inner, argnums=(0, 1, 2)))

    g_pallas = make_loss(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=True,
            interpret=_interpret_params(),
        )
    )(q, k, v)

    def loss_ref(q, k, v):
        return (attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True) * w).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), g_pallas, g_ref):
        assert a.shape == b.shape, f"{name}: {a.shape} vs {b.shape}"
        scale = float(jnp.max(jnp.abs(b))) + 1e-9
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 2e-4, f"{name} rel err {err}"


def test_llama_train_step_with_pallas_cp():
    # model-level wiring: tiny llama with cp_impl="pallas" over a real
    # context axis, full train step (forward + custom-VJP backward)
    from tony_tpu.models import llama
    from tony_tpu.parallel import MeshSpec
    from tony_tpu.train import OptimizerConfig, make_train_step, sharded_init

    cfg = dataclasses.replace(
        llama.LLAMA_TINY, d_model=128, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=256, max_seq=128, cp_impl="pallas", remat=False,
    )
    mesh = MeshSpec(context=2, data=4).build()
    opt = OptimizerConfig(warmup_steps=0, total_steps=4).build()
    key = jax.random.PRNGKey(0)
    state = sharded_init(lambda: llama.init(key, cfg), llama.sharding_rules(cfg), mesh, opt)
    step = make_train_step(functools.partial(llama.loss_fn, cfg=cfg, mesh=mesh), opt)
    batch = llama.synthetic_batch(key, 8, 128, cfg)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_cp_impl_validation():
    from tony_tpu.models import llama

    cfg = dataclasses.replace(llama.LLAMA_TINY, cp_impl="ring")
    with pytest.raises(ValueError, match="cp_impl"):
        llama._attention(
            jnp.zeros((1, 4, 8, 16)), jnp.zeros((1, 2, 8, 16)),
            jnp.zeros((1, 2, 8, 16)), cfg, None,
        )


_EIGHT_DEV_BWD_PROBE = r"""
import sys
sys.path.insert(0, "__REPO__")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.extend.backend as _jeb
_jeb.clear_backends()
jax.config.update("jax_num_cpu_devices", 16)
import jax.numpy as jnp, numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.pallas import tpu as pltpu
from tony_tpu.compat import shard_map
from tony_tpu.ops.ring import ring_attention_pallas
from tony_tpu.ops.attention import attention_reference, repeat_kv

mesh = Mesh(np.array(jax.devices()[:8]), ("context",))
key = jax.random.PRNGKey(11)
B, H, Hkv, T, D = 1, 2, 1, 8 * 512, 64
q = jax.random.normal(jax.random.fold_in(key, 0), (B, H, T, D), jnp.float32) * 0.5
k = jax.random.normal(jax.random.fold_in(key, 1), (B, Hkv, T, D), jnp.float32) * 0.5
v = jax.random.normal(jax.random.fold_in(key, 2), (B, Hkv, T, D), jnp.float32) * 0.5
w = jnp.arange(D, dtype=jnp.float32) / D
spec = P(None, None, "context", None)

def body(q, k, v):
    out = ring_attention_pallas(
        q, k, v, axis_name="context", causal=True,
        interpret=pltpu.InterpretParams(detect_races=True),
    )
    return jax.lax.psum((out * w).sum(), "context")

inner = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=P(),
                      axis_names={"context"}, check_vma=False)
g_pallas = jax.jit(jax.grad(inner, argnums=(0, 1, 2)))(q, k, v)

def loss_ref(q, k, v):
    return (attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True) * w).sum()

g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
for name, a, b in zip("dq dk dv".split(), g_pallas, g_ref):
    scale = float(jnp.max(jnp.abs(b))) + 1e-9
    err = float(jnp.max(jnp.abs(a - b))) / scale
    assert err < 5e-4, f"{name} rel err {err}"
print("EIGHT_DEV_BWD_OK")
"""


def test_pallas_ring_backward_eight_devices_multi_tile():
    # 8-way ring backward with multiple (bq=bk=256) tiles per shard: the
    # riding dk/dv accumulators cross 7 rotations + the final delivery hop.
    # Runs in a SUBPROCESS with SPARE virtual devices (16 for an 8-mesh):
    # the interpret emulation starves for executor threads — and wedges —
    # when a collective kernel with large tiles occupies every device in
    # the process (8-of-16 passes in ~17 s, 8-of-8 deadlocks; same for the
    # FORWARD kernel at n-of-n with 256-row tiles, so this is an emulation
    # artifact, not a kernel-protocol property). Standalone demonstration:
    # docs/repros/pallas_interpret_collective_starvation.py (run it at
    # 8-of-16 to see the pass, 8-of-8 under timeout to see the wedge).
    import os
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # a clean jax env: the probe does its own backend/device setup, and the
    # conftest's XLA_FLAGS/interpret env wedges the emulation at this scale
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "TONY_PALLAS_INTERPRET")
    }
    out = subprocess.run(
        [_sys.executable, "-c", _EIGHT_DEV_BWD_PROBE.replace("__REPO__", repo)],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout}\nstderr:\n{out.stderr[-2000:]}"
    assert "EIGHT_DEV_BWD_OK" in out.stdout


def test_pallas_ring_backward_noncausal():
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    q, k, v = _mk_qkv(seed=13)
    w = jnp.arange(64, dtype=jnp.float32) / 64.0
    spec = P(None, None, "context", None)

    def body(q, k, v):
        out = ring_attention_pallas(
            q, k, v, axis_name="context", causal=False, interpret=_interpret_params()
        )
        return jax.lax.psum((out * w).sum(), "context")

    inner = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=P(),
        axis_names={"context"}, check_vma=False,
    )
    g_pallas = jax.jit(jax.grad(inner, argnums=(0, 1, 2)))(q, k, v)

    def loss_ref(q, k, v):
        return (attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=False) * w).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), g_pallas, g_ref):
        scale = float(jnp.max(jnp.abs(b))) + 1e-9
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 2e-4, f"{name} rel err {err}"


def _mk_seg(B, T, seed=5):
    # two or three segments per row + trailing pad (id 0), block sizes
    # chosen so boundaries never align with shard edges
    key = jax.random.PRNGKey(seed)
    cuts = sorted(
        int(x) for x in jax.random.randint(key, (2,), T // 5, 4 * T // 5)
    )
    seg = np.ones((B, T), np.int32)
    seg[:, cuts[0]:] = 2
    seg[:, cuts[1]:] = 3
    seg[:, -T // 8:] = 0
    return jnp.asarray(seg)


@pytest.mark.parametrize("n_dev", [4])
def test_pallas_ring_packed_matches_reference(n_dev):
    """CP × packing: segment-confined ring fwd+bwd on 4 devices (r2 VERDICT
    #4 — the long-context features now compose with the long-context
    parallelism built for them)."""
    from tony_tpu.ops.ring import ring_attention_pallas_seg

    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("context",))
    B, H, Hkv, T, D = 1, 4, 2, 256, 64
    q, k, v = _mk_qkv(B, H, Hkv, T, D)
    seg = _mk_seg(B, T)

    spec = P(None, None, "context", None)
    ring = jax.jit(
        shard_map(
            functools.partial(
                ring_attention_pallas_seg, axis_name="context", causal=True,
                interpret=_interpret_params(),
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec, P(None, "context")),
            out_specs=spec,
            axis_names={"context"},
            check_vma=False,
        )
    )
    out = ring(q, k, v, seg)
    want = attention_reference(
        q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True, segment_ids=seg
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)

    # gradients: packed ring backward vs autodiff through the reference
    w = jax.random.normal(jax.random.PRNGKey(9), out.shape, jnp.float32)

    def loss_ring(q, k, v):
        return (ring(q, k, v, seg) * w).sum()

    def loss_ref(q, k, v):
        return (attention_reference(
            q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True, segment_ids=seg
        ) * w).sum()

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), gr, gf):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5,
            err_msg=f"{name} mismatch (packed ring)",
        )


def test_pallas_ring_swa_matches_reference():
    """CP × sliding window: banded ring fwd+bwd, window smaller than a
    shard so whole below-band shards exercise the skip path."""
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    B, H, Hkv, T, D = 1, 4, 2, 256, 64
    window = 48  # < per-device 64: below-band shard skipping engages
    q, k, v = _mk_qkv(B, H, Hkv, T, D)
    ring = _shard_ring(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=True,
            interpret=_interpret_params(), window=window,
        ),
        mesh,
    )
    out = ring(q, k, v)
    want = attention_reference(
        q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True, window=window
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)

    w = jax.random.normal(jax.random.PRNGKey(11), out.shape, jnp.float32)
    gr = jax.grad(lambda *a: (ring(*a) * w).sum(), argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(
        lambda q, k, v: (attention_reference(
            q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True, window=window
        ) * w).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for name, a, b in zip("dq dk dv".split(), gr, gf):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5,
            err_msg=f"{name} mismatch (swa ring)",
        )


def test_pallas_ring_short_shard_blocks():
    """Per-device sequences below 256 pick an adaptive block size instead
    of hard-erroring (r2 weak #6)."""
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    q, k, v = _mk_qkv(T=160)  # per-device 40 → block 40
    ring = _shard_ring(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=True,
            interpret=_interpret_params(),
        ),
        mesh,
    )
    out = ring(q, k, v)
    want = attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


_EIGHT_DEV_FEATURES_PROBE = r"""
import sys
sys.path.insert(0, "__REPO__")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.extend.backend as _jeb
_jeb.clear_backends()
jax.config.update("jax_num_cpu_devices", 16)
import functools
import jax.numpy as jnp, numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.pallas import tpu as pltpu
from tony_tpu.compat import shard_map
from tony_tpu.ops.ring import ring_attention_pallas, ring_attention_pallas_seg
from tony_tpu.ops.attention import attention_reference, repeat_kv

mesh = Mesh(np.array(jax.devices()[:8]), ("context",))
key = jax.random.PRNGKey(17)
B, H, Hkv, T, D = 1, 2, 1, 8 * 64, 64
q = jax.random.normal(jax.random.fold_in(key, 0), (B, H, T, D), jnp.float32) * 0.5
k = jax.random.normal(jax.random.fold_in(key, 1), (B, Hkv, T, D), jnp.float32) * 0.5
v = jax.random.normal(jax.random.fold_in(key, 2), (B, Hkv, T, D), jnp.float32) * 0.5
seg = np.ones((B, T), np.int32); seg[:, T//3:] = 2; seg[:, 3*T//4:] = 3; seg[:, -T//8:] = 0
seg = jnp.asarray(seg)
w = jnp.arange(D, dtype=jnp.float32) / D
spec = P(None, None, "context", None)
ip = pltpu.InterpretParams(detect_races=True)

# packed, n=8, fwd+bwd
def body_seg(q, k, v, s):
    out = ring_attention_pallas_seg(q, k, v, s, axis_name="context", causal=True, interpret=ip)
    return jax.lax.psum((out * w).sum(), "context")

inner = shard_map(body_seg, mesh=mesh, in_specs=(spec, spec, spec, P(None, "context")),
                      out_specs=P(), axis_names={"context"}, check_vma=False)
g_pallas = jax.jit(jax.grad(inner, argnums=(0, 1, 2)))(q, k, v, seg)
g_ref = jax.grad(
    lambda q, k, v: (attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2),
                                         causal=True, segment_ids=seg) * w).sum(),
    argnums=(0, 1, 2))(q, k, v)
for name, a, b in zip("dq dk dv".split(), g_pallas, g_ref):
    scale = float(jnp.max(jnp.abs(b))) + 1e-9
    err = float(jnp.max(jnp.abs(a - b))) / scale
    assert err < 1e-4, f"packed {name} rel err {err}"

# swa (window < shard), n=8, fwd+bwd
window = 48
def body_swa(q, k, v):
    out = ring_attention_pallas(q, k, v, axis_name="context", causal=True,
                                interpret=ip, window=window)
    return jax.lax.psum((out * w).sum(), "context")

inner2 = shard_map(body_swa, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=P(), axis_names={"context"}, check_vma=False)
g2 = jax.jit(jax.grad(inner2, argnums=(0, 1, 2)))(q, k, v)
g2_ref = jax.grad(
    lambda q, k, v: (attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2),
                                         causal=True, window=window) * w).sum(),
    argnums=(0, 1, 2))(q, k, v)
for name, a, b in zip("dq dk dv".split(), g2, g2_ref):
    scale = float(jnp.max(jnp.abs(b))) + 1e-9
    err = float(jnp.max(jnp.abs(a - b))) / scale
    assert err < 1e-4, f"swa {name} rel err {err}"
print("EIGHT_DEV_FEATURES_OK")
"""


def test_pallas_ring_packed_swa_eight_devices():
    """(packed, SWA) × n=8, fwd+bwd — same spare-device subprocess recipe
    as the plain n=8 backward (see that test's docstring for why)."""
    import os
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "TONY_PALLAS_INTERPRET")
    }
    out = subprocess.run(
        [_sys.executable, "-c", _EIGHT_DEV_FEATURES_PROBE.replace("__REPO__", repo)],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout}\nstderr:\n{out.stderr[-2000:]}"
    assert "EIGHT_DEV_FEATURES_OK" in out.stdout
