"""Pallas remote-DMA ring attention vs the XLA ring implementation.

Runs the kernel in TPU-interpret mode (emulated RDMA/semaphores, race
detection on) inside shard_map over a 4-device ``context`` axis on the
virtual CPU mesh — the kernel-level analog of how the reference tests
multi-node logic without a cluster (SURVEY.md §4).
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from tony_tpu.ops.attention import attention_reference, repeat_kv
from tony_tpu.parallel.context import ring_attention


def _interpret_params():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.InterpretParams(detect_races=True)


def _mk_qkv(B=1, H=4, Hkv=2, T=256, D=64, seed=3):
    ks = [jax.random.fold_in(jax.random.PRNGKey(seed), i) for i in range(3)]
    q = jax.random.normal(ks[0], (B, H, T, D), jnp.float32) * 0.5
    k = jax.random.normal(ks[1], (B, Hkv, T, D), jnp.float32) * 0.5
    v = jax.random.normal(ks[2], (B, Hkv, T, D), jnp.float32) * 0.5
    return q, k, v


def _shard_ring(fn, mesh):
    spec = P(None, None, "context", None)
    return jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={"context"}, check_vma=False,
        )
    )


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_ring_matches_reference(causal):
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    q, k, v = _mk_qkv()
    ring = _shard_ring(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=causal,
            interpret=_interpret_params(),
        ),
        mesh,
    )
    out = ring(q, k, v)
    want = attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_pallas_ring_matches_xla_ring():
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    q, k, v = _mk_qkv(seed=5)
    pallas_ring = _shard_ring(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=True,
            interpret=_interpret_params(),
        ),
        mesh,
    )
    xla_ring = _shard_ring(
        functools.partial(ring_attention, axis_name="context", causal=True), mesh
    )
    np.testing.assert_allclose(
        np.asarray(pallas_ring(q, k, v)),
        np.asarray(xla_ring(q, repeat_kv(k, 2), repeat_kv(v, 2))),
        atol=2e-5, rtol=2e-5,
    )


def test_pallas_ring_multi_tile():
    # Tl=512 per device → bq=bk=256, num_qb=num_kb=2: exercises the kb loop,
    # the per-tile causal skip, and acc/m/l staging across multiple q blocks
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    q, k, v = _mk_qkv(H=2, Hkv=1, T=2048, seed=11)
    ring = _shard_ring(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=True,
            interpret=_interpret_params(),
        ),
        mesh,
    )
    out = ring(q, k, v)
    want = attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_pallas_ring_eight_devices():
    # n=8: seven ring rotations → the per-neighbor ready/parity handshake
    # cycles both slots repeatedly (race detection is on in interpret mode)
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:8]), ("context",))
    q, k, v = _mk_qkv(H=2, Hkv=1, T=512, seed=13)
    ring = _shard_ring(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=True,
            interpret=_interpret_params(),
        ),
        mesh,
    )
    out = ring(q, k, v)
    want = attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_pallas_ring_backward():
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    q, k, v = _mk_qkv(seed=7)
    w = jnp.arange(64, dtype=jnp.float32) / 64.0

    def make_loss(attn):
        def body(q, k, v):
            return jax.lax.psum((attn(q, k, v) * w).sum(), "context")

        spec = P(None, None, "context", None)
        inner = jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=P(),
            axis_names={"context"}, check_vma=False,
        )
        return jax.jit(jax.grad(inner, argnums=(0, 1, 2)))

    g_pallas = make_loss(
        functools.partial(
            ring_attention_pallas, axis_name="context", causal=True,
            interpret=_interpret_params(),
        )
    )(q, k, v)

    def loss_ref(q, k, v):
        return (attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True) * w).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), g_pallas, g_ref):
        assert a.shape == b.shape, f"{name}: {a.shape} vs {b.shape}"
        scale = float(jnp.max(jnp.abs(b))) + 1e-9
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 2e-4, f"{name} rel err {err}"


def test_llama_train_step_with_pallas_cp():
    # model-level wiring: tiny llama with cp_impl="pallas" over a real
    # context axis, full train step (forward + custom-VJP backward)
    from tony_tpu.models import llama
    from tony_tpu.parallel import MeshSpec
    from tony_tpu.train import OptimizerConfig, make_train_step, sharded_init

    cfg = dataclasses.replace(
        llama.LLAMA_TINY, d_model=128, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=256, max_seq=128, cp_impl="pallas", remat=False,
    )
    mesh = MeshSpec(context=2, data=4).build()
    opt = OptimizerConfig(warmup_steps=0, total_steps=4).build()
    key = jax.random.PRNGKey(0)
    state = sharded_init(lambda: llama.init(key, cfg), llama.sharding_rules(cfg), mesh, opt)
    step = make_train_step(functools.partial(llama.loss_fn, cfg=cfg, mesh=mesh), opt)
    batch = llama.synthetic_batch(key, 8, 128, cfg)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_cp_impl_validation():
    from tony_tpu.models import llama

    cfg = dataclasses.replace(llama.LLAMA_TINY, cp_impl="ring")
    with pytest.raises(ValueError, match="cp_impl"):
        llama._attention(
            jnp.zeros((1, 4, 8, 16)), jnp.zeros((1, 2, 8, 16)),
            jnp.zeros((1, 2, 8, 16)), cfg, None,
        )


_EIGHT_DEV_BWD_PROBE = r"""
import sys
sys.path.insert(0, "__REPO__")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.extend.backend as _jeb
_jeb.clear_backends()
jax.config.update("jax_num_cpu_devices", 16)
import jax.numpy as jnp, numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.pallas import tpu as pltpu
from tony_tpu.ops.ring import ring_attention_pallas
from tony_tpu.ops.attention import attention_reference, repeat_kv

mesh = Mesh(np.array(jax.devices()[:8]), ("context",))
key = jax.random.PRNGKey(11)
B, H, Hkv, T, D = 1, 2, 1, 8 * 512, 64
q = jax.random.normal(jax.random.fold_in(key, 0), (B, H, T, D), jnp.float32) * 0.5
k = jax.random.normal(jax.random.fold_in(key, 1), (B, Hkv, T, D), jnp.float32) * 0.5
v = jax.random.normal(jax.random.fold_in(key, 2), (B, Hkv, T, D), jnp.float32) * 0.5
w = jnp.arange(D, dtype=jnp.float32) / D
spec = P(None, None, "context", None)

def body(q, k, v):
    out = ring_attention_pallas(
        q, k, v, axis_name="context", causal=True,
        interpret=pltpu.InterpretParams(detect_races=True),
    )
    return jax.lax.psum((out * w).sum(), "context")

inner = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=P(),
                      axis_names={"context"}, check_vma=False)
g_pallas = jax.jit(jax.grad(inner, argnums=(0, 1, 2)))(q, k, v)

def loss_ref(q, k, v):
    return (attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True) * w).sum()

g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
for name, a, b in zip("dq dk dv".split(), g_pallas, g_ref):
    scale = float(jnp.max(jnp.abs(b))) + 1e-9
    err = float(jnp.max(jnp.abs(a - b))) / scale
    assert err < 5e-4, f"{name} rel err {err}"
print("EIGHT_DEV_BWD_OK")
"""


def test_pallas_ring_backward_eight_devices_multi_tile():
    # 8-way ring backward with multiple (bq=bk=256) tiles per shard: the
    # riding dk/dv accumulators cross 7 rotations + the final delivery hop.
    # Runs in a SUBPROCESS with SPARE virtual devices (16 for an 8-mesh):
    # the interpret emulation starves for executor threads — and wedges —
    # when a collective kernel with large tiles occupies every device in
    # the process (8-of-16 passes in ~17 s, 8-of-8 deadlocks; same for the
    # FORWARD kernel at n-of-n with 256-row tiles, so this is an emulation
    # artifact, not a kernel-protocol property).
    import os
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # a clean jax env: the probe does its own backend/device setup, and the
    # conftest's XLA_FLAGS/interpret env wedges the emulation at this scale
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "TONY_PALLAS_INTERPRET")
    }
    out = subprocess.run(
        [_sys.executable, "-c", _EIGHT_DEV_BWD_PROBE.replace("__REPO__", repo)],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout}\nstderr:\n{out.stderr[-2000:]}"
    assert "EIGHT_DEV_BWD_OK" in out.stdout


def test_pallas_ring_backward_noncausal():
    from tony_tpu.ops.ring import ring_attention_pallas

    mesh = Mesh(np.array(jax.devices()[:4]), ("context",))
    q, k, v = _mk_qkv(seed=13)
    w = jnp.arange(64, dtype=jnp.float32) / 64.0
    spec = P(None, None, "context", None)

    def body(q, k, v):
        out = ring_attention_pallas(
            q, k, v, axis_name="context", causal=False, interpret=_interpret_params()
        )
        return jax.lax.psum((out * w).sum(), "context")

    inner = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=P(),
        axis_names={"context"}, check_vma=False,
    )
    g_pallas = jax.jit(jax.grad(inner, argnums=(0, 1, 2)))(q, k, v)

    def loss_ref(q, k, v):
        return (attention_reference(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=False) * w).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), g_pallas, g_ref):
        scale = float(jnp.max(jnp.abs(b))) + 1e-9
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 2e-4, f"{name} rel err {err}"
