"""int8 weight-only quantization: error bounds + kernel/reference parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.ops import quant


class TestQuantizeInt8:
    def test_roundtrip_error_bound(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (128, 64), jnp.float32)
        qt = quant.quantize_int8(w)
        deq = np.asarray(quant.dequantize(qt, jnp.float32))
        # absmax int8: per-channel max error <= scale/2 ~ absmax/254
        err = np.abs(deq - np.asarray(w))
        bound = np.asarray(qt.scale) * 0.5 + 1e-7
        assert (err <= bound[None, :]).all()

    def test_scale_per_output_channel(self):
        w = jnp.stack([jnp.ones(16), 100 * jnp.ones(16)], axis=1)  # [16, 2]
        qt = quant.quantize_int8(w)
        assert qt.scale.shape == (2,)
        assert float(qt.scale[1]) > float(qt.scale[0]) * 50

    def test_int8_values_in_range(self):
        w = jax.random.normal(jax.random.PRNGKey(1), (64, 32)) * 10
        qt = quant.quantize_int8(w)
        assert qt.q.dtype == jnp.int8
        assert int(jnp.max(jnp.abs(qt.q.astype(jnp.int32)))) <= 127


class TestInt8Matmul:
    def test_reference_close_to_float(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (8, 128), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(3), (128, 64), jnp.float32) * 0.1
        qt = quant.quantize_int8(w)
        got = np.asarray(quant.int8_matmul_ref(x, qt))
        want = np.asarray(x @ w)
        # int8 quant error accumulates over K=128; ~1% relative is expected
        np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)

    def test_kernel_matches_reference(self):
        # interpreter mode on CPU (conftest sets TONY_PALLAS_INTERPRET=1);
        # the kernel streams x through bf16 so tolerance covers bf16 rounding
        # accumulated over K=512 (outputs are O(sqrt(K)) ≈ 22)
        x = jax.random.normal(jax.random.PRNGKey(4), (256, 512), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(5), (512, 256), jnp.float32)
        qt = quant.quantize_int8(w)
        got = np.asarray(quant.int8_matmul(x, qt))
        want = np.asarray(quant.int8_matmul_ref(x, qt))
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=0.3)

    def test_kernel_fallback_on_awkward_shapes(self):
        # M=300 > block_m=256 and not divisible → XLA reference path (exact)
        x = jax.random.normal(jax.random.PRNGKey(6), (300, 512), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(7), (512, 256), jnp.float32)
        qt = quant.quantize_int8(w)
        got = np.asarray(quant.int8_matmul(x, qt))
        want = np.asarray(quant.int8_matmul_ref(x, qt))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_batched_leading_dims(self):
        x = jax.random.normal(jax.random.PRNGKey(8), (2, 4, 64), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(9), (64, 32), jnp.float32)
        qt = quant.quantize_int8(w)
        out = quant.int8_matmul(x, qt)
        assert out.shape == (2, 4, 32)


class TestQuantizeTree:
    def test_llama_params_shrink_near_half(self):
        import dataclasses

        from tony_tpu.models import llama

        cfg = dataclasses.replace(
            llama.LLAMA_TINY, d_model=128, d_ff=256, vocab_size=512
        )
        params = llama.init(jax.random.PRNGKey(0), cfg)
        qtree, before, after = quant.quantize_tree(params, min_size=1 << 12)
        assert after < before * 0.65  # big mats bf16 → int8 (~half), norms stay
        # stacked-layer 3-D leaves quantize per layer; norms stay float
        assert isinstance(qtree["layers"]["wq"], quant.QTensor)
        assert isinstance(qtree["lm_head"], quant.QTensor)
        assert not isinstance(qtree["layers"]["attn_norm"], quant.QTensor)
        # per-layer scales: [L, N]
        assert qtree["layers"]["wq"].scale.ndim == 2

    def test_stacked_norm_weights_never_quantize(self):
        # 8B-scale norm shape [L, D] is 2-D and large but K=L is tiny — it
        # must stay float or the layer scan and rms_norm break
        tree = {
            "attn_norm": jnp.ones((32, 4096), jnp.bfloat16),   # stacked norms
            "mlp_norm": jnp.ones((80, 8192), jnp.bfloat16),    # 70B-scale: L >= 64
            "w": jnp.ones((32, 4096, 4096), jnp.bfloat16),     # stacked matmuls
        }
        qtree, _, _ = quant.quantize_tree(tree, min_size=1 << 10)
        assert not isinstance(qtree["attn_norm"], quant.QTensor)
        assert not isinstance(qtree["mlp_norm"], quant.QTensor)
        assert isinstance(qtree["w"], quant.QTensor)

    def test_nested_norm_dicts_stay_float(self):
        # BERT-style layout: the telling name is an INNER path segment
        tree = {"layers": {
            "attn_norm": {"w": jnp.ones((64, 768), jnp.bfloat16)},
            "wq": jnp.ones((64, 768, 768), jnp.bfloat16),
        }}
        qtree, _, _ = quant.quantize_tree(tree, min_size=1 << 10)
        assert not isinstance(qtree["layers"]["attn_norm"]["w"], quant.QTensor)
        assert isinstance(qtree["layers"]["wq"], quant.QTensor)

    @pytest.mark.slow  # ~14 s layer-stacked quant roundtrip
    def test_stacked_dequant_roundtrip(self):
        w = jax.random.normal(jax.random.PRNGKey(10), (3, 32, 16), jnp.float32)
        qt = quant.quantize_int8(w)
        assert qt.scale.shape == (3, 16)
        deq = np.asarray(quant.dequantize(qt, jnp.float32))
        err = np.abs(deq - np.asarray(w))
        bound = np.asarray(qt.scale)[:, None, :] * 0.5 + 1e-7
        assert (err <= bound).all()
