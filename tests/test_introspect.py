"""Live-introspection suite: structured logging (sink, bridge, merge,
follow, free disabled path), the on-demand profile plane (coordinator,
courier, runtime-armed StepProfiler, AM handlers, typed already-profiling
error), `tony logs` / `tony top` CLI surfaces, portal scrape-failure
degradation, and the headline e2e — a live fixture gang profiled, log-tailed
and `top`ped mid-run with no resubmit.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time

import pytest

from tony_tpu import constants
from tony_tpu.obs import introspect as obs_introspect
from tony_tpu.obs import logging as obs_log
from tony_tpu.obs import trace as obs_trace
from tony_tpu.obs.introspect import (
    AlreadyProfilingError,
    ProfileCoordinator,
    ProfileCourier,
    build_top_rows,
)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


@pytest.fixture(autouse=True)
def _logger_isolation():
    """Each test starts and ends with no process-global logger installed."""
    obs_log.shutdown()
    yield
    obs_log.shutdown()


# ---------------------------------------------------------------- logging
@pytest.mark.obs
class TestJsonLogger:
    def test_records_carry_identity_epoch_and_fields(self, tmp_path):
        obs_log.init_logging("worker:0", str(tmp_path), epoch=2)
        obs_log.info("hello", step=7)
        obs_log.warning("uh oh")
        obs_log.shutdown()
        recs = obs_log.read_records(str(tmp_path))
        assert [r["level"] for r in recs] == ["info", "warning"]
        assert recs[0]["identity"] == "worker:0"
        assert recs[0]["epoch"] == 2
        assert recs[0]["step"] == 7
        assert recs[0]["msg"] == "hello"
        assert recs[1]["ts_ms"] >= recs[0]["ts_ms"]

    def test_echo_matches_print_behavior(self, tmp_path, capsys):
        obs_log.init_logging("am", str(tmp_path))
        obs_log.info("[tony] to stdout")
        obs_log.error("[tony] to stderr")
        out = capsys.readouterr()
        assert out.out == "[tony] to stdout\n"
        assert out.err == "[tony] to stderr\n"

    def test_echo_only_fallback_without_logger(self, capsys):
        assert obs_log.get() is None
        obs_log.info("still visible")
        assert capsys.readouterr().out == "still visible\n"

    def test_below_level_builds_nothing(self, tmp_path, monkeypatch, capsys):
        """The acceptance contract: at the default info level, debug() must
        allocate no record, write nothing, and echo nothing — mirroring the
        disabled-tracing zero-allocation assert of PR 3."""
        obs_log.init_logging("worker:0", str(tmp_path))

        def boom(*a, **kw):
            raise AssertionError("record built on the sub-level fast path")

        monkeypatch.setattr(obs_log.JsonLogger, "_emit", boom)
        obs_log.debug("invisible", huge_field="x" * 1000)
        assert capsys.readouterr().out == ""
        # same for the no-logger default path
        obs_log.shutdown()
        obs_log.debug("also invisible")
        assert capsys.readouterr().out == ""

    def test_span_correlation_when_tracing(self, tmp_path):
        obs_log.init_logging("worker:0", str(tmp_path / "logs"))
        tr = obs_trace.init_tracing("app-x", "worker:0", str(tmp_path / "trace"))
        try:
            with tr.span("outer") as sp:
                obs_log.info("inside the span")
                span_id = sp.span_id
        finally:
            obs_trace.shutdown()
        rec = obs_log.read_records(str(tmp_path / "logs"))[0]
        assert rec["span"] == span_id

    def test_reserved_fields_never_shadowed(self, tmp_path):
        obs_log.init_logging("real-identity", str(tmp_path))
        obs_log.info("msg", identity="spoof", ts_ms=0)
        obs_log.shutdown()
        rec = obs_log.read_records(str(tmp_path))[0]
        assert rec["identity"] == "real-identity"
        assert rec["ts_ms"] > 0

    def test_stdlib_bridge_forwards_into_sink(self, tmp_path, capsys):
        import logging as stdlib_logging

        obs_log.init_logging("am", str(tmp_path))
        stdlib_logging.getLogger("third.party").warning("from stdlib")
        obs_log.shutdown()
        recs = [r for r in obs_log.read_records(str(tmp_path))
                if r.get("logger") == "third.party"]
        assert recs and recs[0]["msg"] == "from stdlib"
        assert "from stdlib" not in capsys.readouterr().out  # bridge never echoes

    def test_read_records_merges_files_in_timestamp_order(self, tmp_path):
        for ident, ts in [("am", 3.0), ("worker_0", 1.0), ("worker_0_train", 2.0)]:
            with open(tmp_path / f"{ident}{obs_log.LOG_SUFFIX}", "w") as f:
                f.write(json.dumps({"ts_ms": ts, "msg": ident, "identity": ident}) + "\n")
            # torn tail line is tolerated
            with open(tmp_path / f"{ident}{obs_log.LOG_SUFFIX}", "a") as f:
                f.write('{"torn": ')
        recs = obs_log.read_records(str(tmp_path))
        assert [r["msg"] for r in recs] == ["worker_0", "worker_0_train", "am"]

    def test_follower_is_incremental_and_discovers_new_files(self, tmp_path):
        follower = obs_log.LogFollower(str(tmp_path))
        assert follower.poll() == []
        obs_log.init_logging("am", str(tmp_path))
        obs_log.info("first")
        assert [r["msg"] for r in follower.poll()] == ["first"]
        assert follower.poll() == []
        obs_log.init_logging("worker:0", str(tmp_path))  # a new file appears
        obs_log.info("second")
        assert [r["msg"] for r in follower.poll()] == ["second"]

    def test_format_record(self):
        line = obs_log.format_record(
            {"ts_ms": 0.0, "level": "info", "identity": "worker:0",
             "msg": "hi", "step": 3}
        )
        assert "[worker:0]" in line and "INFO" in line and "hi" in line
        assert "step=3" in line

    def test_echo_threshold_independent_of_sink_level(self, tmp_path, capsys):
        """tony.log.level governs only the JSONL sink: a level=error job
        still prints its submit/monitor lines exactly like the print calls
        the helpers replaced, and a level=debug job does not spam the
        console with sink-only debug records."""
        obs_log.init_logging("client", str(tmp_path), level=obs_log.ERROR)
        obs_log.info("[tony] task worker:0 → RUNNING")
        assert capsys.readouterr().out == "[tony] task worker:0 → RUNNING\n"
        assert obs_log.read_records(str(tmp_path)) == []  # below sink level
        obs_log.init_logging("child", str(tmp_path), level=obs_log.DEBUG)
        obs_log.debug("sink only")
        assert capsys.readouterr().out == ""
        assert [r["msg"] for r in obs_log.read_records(str(tmp_path))] == ["sink only"]

    def test_sink_io_failure_never_raises(self, tmp_path, monkeypatch):
        lg = obs_log.init_logging("am", str(tmp_path))

        def full_disk(_):
            raise OSError(28, "No space left on device")

        monkeypatch.setattr(lg._file, "write", full_disk)
        obs_log.info("must not propagate")  # ENOSPC is swallowed

    def test_resolve_log_dir_honors_frozen_config_override(self, tmp_path):
        from tony_tpu.config import TonyConfig, keys

        app_dir = tmp_path / "app9"
        app_dir.mkdir()
        assert obs_log.resolve_log_dir(str(tmp_path), "app9") == str(app_dir / "logs")
        cfg = TonyConfig({"tony.worker.instances": "1",
                          keys.LOG_DIR: "/shared/logs"})
        cfg.freeze()
        cfg.write_final(str(app_dir))
        assert obs_log.resolve_log_dir(str(tmp_path), "app9") == "/shared/logs"

    def test_init_from_env_contract(self, tmp_path):
        env = {
            constants.ENV_LOG_DIR: str(tmp_path),
            constants.ENV_LOG_LEVEL: "warning",
            constants.ENV_JOB_NAME: "worker",
            constants.ENV_TASK_INDEX: "1",
            "TONY_RESTART_ATTEMPT": "3",
        }
        lg = obs_log.init_from_env(env)
        assert lg is not None
        assert lg.identity == "worker:1:train"
        assert lg.level == obs_log.WARNING
        assert lg.epoch == 3
        # a co-scheduled non-training child labels itself by role — a serve
        # engine's records must not masquerade as a training process
        assert obs_log.init_from_env(env, role="serve").identity == "worker:1:serve"
        assert obs_log.init_from_env({}) is None

    def test_tail_records_bounds_work_per_file(self, tmp_path):
        """The portal pages read only file tails: a huge aggregate costs a
        bounded read, and the newest `limit` records still come out merged
        in timestamp order."""
        with open(tmp_path / f"big{obs_log.LOG_SUFFIX}", "w") as f:
            for i in range(2000):
                f.write(json.dumps({"ts_ms": float(i), "msg": f"b{i}",
                                    "identity": "big"}) + "\n")
        with open(tmp_path / f"small{obs_log.LOG_SUFFIX}", "w") as f:
            f.write(json.dumps({"ts_ms": 1998.5, "msg": "s", "identity": "small"}) + "\n")
        recs = obs_log.tail_records(str(tmp_path), limit=3)
        assert [r["msg"] for r in recs] == ["b1998", "s", "b1999"]
        # a tail seek landing mid-line drops the partial, keeps the rest
        recs = obs_log.tail_records(str(tmp_path), limit=5,
                                    max_bytes_per_file=100)
        assert recs and all(r["msg"] for r in recs)


# ------------------------------------------------- coordinator and courier
@pytest.mark.obs
class TestProfileCoordinator:
    def test_lifecycle_and_typed_concurrency_error(self):
        c = ProfileCoordinator()
        with pytest.raises(RuntimeError):
            c.start([], 3, False)  # no tasks → refuse
        r = c.start(["worker:0", "worker:1"], 3, False)
        with pytest.raises(AlreadyProfilingError):
            c.start(["worker:0"], 3, False)
        assert c.pending_for("worker:0")["req_id"] == r["req_id"]
        assert c.pending_for("worker:9") is None
        assert c.report("worker:0", r["req_id"], "captured", dir="/a") == (True, False)
        assert c.pending_for("worker:0") is None  # terminal → no redelivery
        assert c.report("worker:1", r["req_id"], "error", error="boom") == (True, True)
        st = c.status()
        assert st["complete"]
        assert st["tasks"]["worker:0"]["status"] == "captured"
        assert st["tasks"]["worker:1"]["error"] == "boom"
        # complete → a new request is allowed
        c.start(["worker:0"], 1, True)

    def test_report_rejects_unknown_request_and_task(self):
        c = ProfileCoordinator()
        r = c.start(["worker:0"], 2, False)
        assert c.report("worker:0", "bogus", "captured") == (False, False)
        assert c.report("worker:7", r["req_id"], "captured") == (False, False)
        assert c.report("worker:0", r["req_id"], "weird-status") == (False, False)

    def test_stale_unreported_request_expires_instead_of_wedging(self):
        """A target whose child never runs a StepProfiler (raw shell
        command, serve replica) never reports; the single slot must not be
        bricked for the job's lifetime — the next start past the TTL fails
        the ghost request and proceeds."""
        c = ProfileCoordinator(stale_after_s=0.01)
        r1 = c.start(["worker:0"], 2, False)
        time.sleep(0.02)
        r2 = c.start(["worker:0"], 2, False)  # expired → allowed
        assert r2["req_id"] != r1["req_id"]
        st = c.status(r2["req_id"])
        assert st is not None and not st["complete"]
        # inside the TTL it still refuses, and says when the slot frees up
        c2 = ProfileCoordinator(stale_after_s=60)
        c2.start(["w:0"], 1, False)
        with pytest.raises(AlreadyProfilingError, match="expire"):
            c2.start(["w:0"], 1, False)

    def test_abort_fails_outstanding_tasks(self):
        c = ProfileCoordinator()
        r = c.start(["worker:0", "worker:1"], 2, False)
        c.report("worker:0", r["req_id"], "captured")
        c.abort("gang restarted")
        st = c.status()
        assert st["complete"]
        assert st["tasks"]["worker:0"]["status"] == "captured"  # kept
        assert st["tasks"]["worker:1"]["status"] == "error"
        assert "gang restarted" in st["tasks"]["worker:1"]["error"]
        c.start(["worker:0"], 1, False)  # unblocked


class _FakeJaxProfiler:
    def __init__(self):
        self.calls = []

    def start_trace(self, d):
        self.calls.append(("start", d))
        with open(os.path.join(d, "t.xplane.pb"), "w") as f:
            f.write("x")

    def stop_trace(self):
        self.calls.append(("stop", None))

    def save_device_memory_profile(self, path):
        with open(path, "w") as f:
            f.write("mem")


@pytest.mark.obs
class TestOnDemandCapturePlane:
    """Courier ↔ StepProfiler relay over the real control/done files."""

    def _profiler(self, tmp_path, monkeypatch):
        import jax

        from tony_tpu.train import profiling

        fake = _FakeJaxProfiler()
        monkeypatch.setattr(jax, "profiler", fake)
        metrics_path = os.path.join(str(tmp_path), "worker_0.json")
        p = profiling.StepProfiler(env={
            constants.ENV_TRAIN_METRICS_FILE: metrics_path,
            profiling.ENV_PROFILE_POLL_MS: "1",
        })
        return p, fake, metrics_path

    def test_full_relay_round_trip(self, tmp_path, monkeypatch):
        p, fake, metrics_path = self._profiler(tmp_path, monkeypatch)
        reports = []
        courier = ProfileCourier(str(tmp_path), "worker", 0,
                                 lambda **kw: reports.append(kw))
        courier.handle({"req_id": "r1", "num_steps": 2, "memory": True},
                       metrics_path)
        assert reports[0]["status"] == "delivered"
        time.sleep(0.005)
        p.step(10)  # arms at this boundary
        assert p._request is not None
        p.step(11)
        p.step(12)  # 10+2 reached → finalize
        assert p._request is None
        courier.handle(None, metrics_path)  # sees the done record
        final = reports[-1]
        assert final["status"] == "captured"
        assert final["summary"]["steps_captured"] == 2
        assert len(final["summary"]["step_times_ms"]) == 2
        assert not final["summary"].get("truncated")
        assert "t.xplane.pb" in final["artifacts"]
        assert "memory.prof" in final["artifacts"]
        assert os.path.isdir(final["dir"])
        assert fake.calls[0][0] == "start" and fake.calls[1][0] == "stop"
        # redelivery of the same req_id is a no-op (idempotent)
        courier.handle({"req_id": "r1", "num_steps": 2}, metrics_path)
        assert [r["status"] for r in reports] == ["delivered", "captured"]

    def test_stop_finalizes_truncated_capture(self, tmp_path, monkeypatch):
        p, fake, metrics_path = self._profiler(tmp_path, monkeypatch)
        out_dir = os.path.join(str(tmp_path), "prof")
        obs_introspect.write_json_atomic(
            metrics_path + obs_introspect.CONTROL_SUFFIX,
            {"req_id": "r2", "num_steps": 1000, "dir": out_dir},
        )
        time.sleep(0.005)
        p.step(0)
        p.step(1)
        assert p._request is not None
        p.stop()  # training ended inside the window (the loop's finally)
        assert p._request is None
        done = obs_introspect.read_json(
            metrics_path + obs_introspect.DONE_SUFFIX
        )
        assert done["ok"] and done["truncated"]
        assert done["steps_captured"] == 1
        assert ("stop", None) in fake.calls  # the trace was terminated

    def test_capture_failure_reports_error_not_crash(self, tmp_path, monkeypatch):
        import jax

        from tony_tpu.train import profiling

        class Exploding:
            def start_trace(self, d):
                raise RuntimeError("no backend")

        monkeypatch.setattr(jax, "profiler", Exploding())
        metrics_path = os.path.join(str(tmp_path), "w.json")
        p = profiling.StepProfiler(env={
            constants.ENV_TRAIN_METRICS_FILE: metrics_path,
            profiling.ENV_PROFILE_POLL_MS: "1",
        })
        obs_introspect.write_json_atomic(
            metrics_path + obs_introspect.CONTROL_SUFFIX,
            {"req_id": "r3", "num_steps": 2},
        )
        time.sleep(0.005)
        p.step(0)  # must not raise
        done = obs_introspect.read_json(metrics_path + obs_introspect.DONE_SUFFIX)
        assert done["req_id"] == "r3" and not done["ok"]
        assert "no backend" in done["error"]

    def test_unarmed_hot_path_does_no_control_io(self, monkeypatch):
        """Profiling not armed (no tony container): step() touches no files
        and allocates no capture state — the acceptance's free-path clause."""
        from tony_tpu.train import profiling

        def boom(*a, **kw):
            raise AssertionError("control-file I/O on the unarmed fast path")

        monkeypatch.setattr(obs_introspect, "read_json", boom)
        p = profiling.StepProfiler(env={})
        for step in range(100):
            p.step(step)
        assert p._request is None and not p.active

    def test_poll_is_time_throttled(self, tmp_path, monkeypatch):
        from tony_tpu.train import profiling

        calls = []
        monkeypatch.setattr(obs_introspect, "read_json",
                            lambda path: calls.append(path))
        metrics_path = os.path.join(str(tmp_path), "w.json")
        p = profiling.StepProfiler(env={
            constants.ENV_TRAIN_METRICS_FILE: metrics_path,
            profiling.ENV_PROFILE_POLL_MS: "60000",
        })
        for step in range(50):
            p.step(step)
        assert len(calls) == 1  # one stat per poll window, not per step


# --------------------------------------------------------- AM RPC handlers
@pytest.mark.obs
class TestAmProfileHandlers:
    def _am(self, tmp_path):
        from tony_tpu.cluster.appmaster import ApplicationMaster
        from tony_tpu.config import TonyConfig

        cfg = TonyConfig({"tony.worker.instances": "2"})
        cfg.freeze()
        staging = tmp_path / "stage"
        staging.mkdir()
        return ApplicationMaster(cfg, "app_prof", str(staging))

    def test_handlers_and_heartbeat_piggyback(self, tmp_path):
        from tony_tpu.cluster.session import TaskStatus

        am = self._am(tmp_path)
        try:
            with pytest.raises(RuntimeError):
                am.start_profile()  # nothing RUNNING yet
            for t in am.session.all_tasks():
                t.status = TaskStatus.RUNNING
            r = am.start_profile(steps=4)
            assert sorted(r["tasks"]) == ["worker:0", "worker:1"]
            hb = am.task_executor_heartbeat("worker", 0)
            assert hb["profile"] == {"req_id": r["req_id"], "num_steps": 4,
                                     "memory": False}
            with pytest.raises(AlreadyProfilingError):
                am.start_profile()
            # stale-epoch reports are fenced like every executor RPC
            stale = am.report_profile_status(
                "worker", 0, r["req_id"], "captured", attempt=7)
            assert stale == {"ack": False, "stale": True}
            am.report_profile_status("worker", 0, r["req_id"], "captured",
                                     dir="/d", artifacts=["a.pb"])
            am.report_profile_status("worker", 1, r["req_id"], "captured")
            st = am.get_profile_status()["profile"]
            assert st["complete"]
            assert "profile" not in am.task_executor_heartbeat("worker", 0)
            am.start_profile(steps=1)  # slot free again
        finally:
            am.events.stop()
            am.rm.shutdown()

    def test_gang_restart_aborts_inflight_capture(self, tmp_path):
        from tony_tpu.cluster.session import TaskStatus

        am = self._am(tmp_path)
        try:
            for t in am.session.all_tasks():
                t.status = TaskStatus.RUNNING
            r = am.start_profile(steps=2)
            am._restart_gang_spanned("test restart", None)
            st = am.get_profile_status(r["req_id"])["profile"]
            assert st["complete"]
            assert all(e["status"] == "error" for e in st["tasks"].values())
        finally:
            am.events.stop()
            am.rm.shutdown()


# --------------------------------------------------------------- tony top
@pytest.mark.obs
class TestTopSynthesis:
    def test_rows_from_infos_and_obs_snapshots(self):
        infos = [{
            "name": "worker", "index": 0, "status": "RUNNING",
            "last_heartbeat_ms": 1_000_000.0,
            "metrics": {"train": {"step": 40, "loss": 2.5,
                                  "tokens_per_sec": 1234.5, "mfu": 0.41}},
        }]
        obs = {"worker:0": [
            {"name": "tony_train_step_seconds", "type": "histogram",
             "samples": [{"labels": {}, "counts": [5, 0], "sum": 2.5, "count": 5}]},
            {"name": "tony_serve_queue_depth", "type": "gauge",
             "samples": [{"labels": {}, "value": 3.0}]},
            {"name": "tony_serve_ttft_seconds", "type": "histogram",
             "samples": [{"labels": {}, "counts": [4], "sum": 0.8, "count": 4}]},
        ]}
        rows = build_top_rows(infos, obs, now_ms=1_000_500.0)
        r = rows[0]
        assert r["task"] == "worker:0" and r["state"] == "RUNNING"
        assert r["step"] == 40 and r["tokens_per_s"] == 1234.5
        assert r["steps_per_s"] == pytest.approx(2.0)  # 5 samples / 2.5s
        assert r["queue_depth"] == 3.0
        assert r["ttft_s"] == pytest.approx(0.2)
        assert r["hb_age_s"] == pytest.approx(0.5)

    def test_step_rate_is_live_between_frames(self):
        """With the previous frame's stats the rate is the snapshot delta —
        a job that slows down shows the slowdown instead of its lifetime
        average — and a stalled job reads 0."""
        from tony_tpu.obs.introspect import step_stats_by_task

        def snap(count, total):
            return {"worker:0": [
                {"name": "tony_train_step_seconds", "type": "histogram",
                 "samples": [{"labels": {}, "count": count, "sum": total}]},
            ]}

        infos = [{"name": "worker", "index": 0, "status": "RUNNING",
                  "metrics": {"train": {"step": 1}}}]
        # an hour at 5 step/s, then 2 more frames' steps at 0.5 step/s
        prev = step_stats_by_task(infos, snap(18000, 3600.0))
        rows = build_top_rows(infos, snap(18002, 3604.0), prev_step_stats=prev)
        assert rows[0]["steps_per_s"] == pytest.approx(0.5)
        # no new observations since the last frame → live rate 0, not avg
        prev = step_stats_by_task(infos, snap(18002, 3604.0))
        rows = build_top_rows(infos, snap(18002, 3604.0), prev_step_stats=prev)
        assert rows[0]["steps_per_s"] == 0.0
        # child restarted (histogram reset): fall back to lifetime average
        rows = build_top_rows(infos, snap(10, 5.0), prev_step_stats=prev)
        assert rows[0]["steps_per_s"] == pytest.approx(2.0)

    def test_render_contains_live_columns(self):
        from tony_tpu.cli.introspect import render_top

        rows = build_top_rows(
            [{"name": "worker", "index": 0, "status": "RUNNING",
              "metrics": {"train": {"step": 7}}}], {}
        )
        frame = render_top({"app_id": "app_x", "state": "RUNNING",
                            "restart_attempt": 1}, rows)
        assert "app_x" in frame and "attempt 1" in frame
        assert "STEP/S" in frame and "HB AGE" in frame
        assert re.search(r"worker:0\s+RUNNING\s+7", frame)


# ----------------------------------------------------------- tony profile
@pytest.mark.obs
class TestProfileCli:
    def test_finalized_job_exits_promptly_not_full_timeout(self, tmp_path, capsys):
        """A job that finalizes mid-capture must not make `tony profile`
        spin out its whole --timeout retrying a dead AM: the poll loop
        consults am_status.json exactly like `tony logs` / `tony top`."""
        from tony_tpu.cli.introspect import main_profile
        from tony_tpu.cluster.rpc import RpcServer

        app_dir = tmp_path / "app1"
        app_dir.mkdir()
        srv = RpcServer()
        srv.register("start_profile", lambda steps=None, memory=False: {
            "req_id": "r1", "num_steps": 2, "tasks": ["worker:0"]})
        srv.start()
        host, port = srv.address
        (app_dir / "am_info.json").write_text(json.dumps(
            {"host": host, "port": port, "secret": ""}))
        (app_dir / "am_status.json").write_text(json.dumps({"status": "SUCCEEDED"}))
        srv_stopper = threading.Timer(0.2, srv.stop)  # AM dies after accepting
        srv_stopper.start()
        t0 = time.monotonic()
        rc = main_profile(["app1", "--staging", str(tmp_path), "--timeout", "30"])
        srv_stopper.join()
        assert rc == 1
        assert time.monotonic() - t0 < 10, "spun toward --timeout instead"
        assert "finalized" in capsys.readouterr().err


# -------------------------------------------------------------- tony logs
@pytest.mark.obs
class TestLogsCli:
    def _write_logs(self, log_dir):
        os.makedirs(log_dir, exist_ok=True)
        rows = [
            ("am", 1.0, "info", "gang complete"),
            ("worker_0", 2.0, "info", "child launched"),
            ("worker_0_train", 3.0, "debug", "step 1"),
            ("worker_0_train", 4.0, "error", "loss went NaN"),
            ("worker_1", 5.0, "info", "child launched"),
        ]
        for ident, ts, level, msg in rows:
            with open(os.path.join(log_dir, ident + obs_log.LOG_SUFFIX), "a") as f:
                identity = ident.replace("_0", ":0").replace("_1", ":1").replace(":0_train", ":0:train")
                f.write(json.dumps({"ts_ms": ts, "level": level,
                                    "identity": identity, "msg": msg}) + "\n")

    def test_merge_order_and_filters(self, tmp_path, capsys):
        from tony_tpu.cli.introspect import main_logs

        log_dir = os.path.join(str(tmp_path), "app1", "logs")
        self._write_logs(log_dir)
        assert main_logs(["app1", "--staging", str(tmp_path)]) == 0
        out = capsys.readouterr().out.splitlines()
        msgs = [line.split(None, 3)[-1] for line in out]
        assert msgs == ["gang complete", "child launched", "step 1",
                        "loss went NaN", "child launched"]  # ts order
        # --task matches the executor AND its training child
        assert main_logs(["app1", "--staging", str(tmp_path),
                          "--task", "worker:0"]) == 0
        out = capsys.readouterr().out
        assert "gang complete" not in out and "step 1" in out
        # --grep and --level
        assert main_logs(["app1", "--staging", str(tmp_path),
                          "--grep", "NaN"]) == 0
        assert "loss went NaN" in capsys.readouterr().out
        assert main_logs(["app1", "--staging", str(tmp_path),
                          "--level", "error"]) == 0
        out = capsys.readouterr().out
        assert "loss went NaN" in out and "gang complete" not in out

    def test_no_records_is_an_error(self, tmp_path, capsys):
        from tony_tpu.cli.introspect import main_logs

        assert main_logs(["ghost", "--staging", str(tmp_path)]) == 1
        # -f on a nonexistent app must error out, not spin forever waiting
        # for an am_status.json that can never appear
        assert main_logs(["ghost", "--staging", str(tmp_path), "-f"]) == 1

    def test_follow_exits_when_job_finalizes(self, tmp_path, capsys):
        from tony_tpu.cli.introspect import main_logs

        app_dir = os.path.join(str(tmp_path), "app2")
        self._write_logs(os.path.join(app_dir, "logs"))
        with open(os.path.join(app_dir, "am_status.json"), "w") as f:
            json.dump({"status": "SUCCEEDED"}, f)
        t0 = time.monotonic()
        rc = main_logs(["app2", "--staging", str(tmp_path), "-f"])
        assert rc == 0
        assert time.monotonic() - t0 < 10
        assert "loss went NaN" in capsys.readouterr().out
        # documented contract: -f exits 0 when the job finalizes, even when
        # no record passed the filters (an over-narrow --grep is not a
        # job failure)
        rc = main_logs(["app2", "--staging", str(tmp_path), "-f",
                        "--grep", "no-such-pattern-anywhere"])
        assert rc == 0
        assert capsys.readouterr().out == ""


# ----------------------------------------------------- portal degradation
@pytest.mark.obs
class TestPortalScrapeDegradation:
    def test_dead_am_is_skipped_and_counted(self, tmp_path):
        from tony_tpu.portal.server import PortalHandler, _SCRAPE_FAILURES

        history = tmp_path / "history" / constants.HISTORY_INTERMEDIATE_DIR
        history.mkdir(parents=True)
        (history / ("app_dead" + constants.HISTORY_SUFFIX)).write_text("")
        staging = tmp_path / "app_dead"
        staging.mkdir()
        # am_info.json pointing at a port nothing listens on
        (staging / constants.AM_INFO_FILE).write_text(json.dumps(
            {"host": "127.0.0.1", "port": 1, "secret": "s"}
        ))
        handler = PortalHandler.__new__(PortalHandler)  # no socket plumbing
        handler.history_root = str(tmp_path / "history")
        handler.staging_root = str(tmp_path)
        before = _SCRAPE_FAILURES.value(app="app_dead")
        text = handler._metrics_text()
        assert _SCRAPE_FAILURES.value(app="app_dead") == before + 1
        # the exposition survived AND carries the failure counter
        assert 'tony_portal_scrape_failures_total{app="app_dead"}' in text


FAST = {
    "tony.am.monitor-interval-ms": "50",
    "tony.task.heartbeat-interval-ms": "100",
    "tony.task.metrics-interval-ms": "200",
    "tony.am.gang-timeout-ms": "60000",
    "tony.profile.poll-interval-ms": "50",
}


# ------------------------------------------------------------ headline e2e
@pytest.mark.obs
@pytest.mark.e2e
class TestLiveIntrospectionEndToEnd:
    """The acceptance path: a running fixture gang is profiled on demand
    (per-task confirmations + artifacts, no resubmit), its merged logs are
    streamed with `tony logs -f` (AM + executor + training child, timestamp
    order), and `tony top` renders a live snapshot with a step rate — while
    a second concurrent start_profile gets the typed error."""

    def test_profile_logs_top_against_live_gang(self, tmp_tony_root, capsys):
        from tony_tpu.cli.introspect import main_profile, main_top
        from tony_tpu.cli.trace import load_spans
        from tony_tpu.cluster.client import Client
        from tony_tpu.cluster.rpc import RpcError
        from tony_tpu.cluster.session import JobStatus
        from tony_tpu.config import TonyConfig, keys

        cfg = TonyConfig({
            **FAST,
            keys.STAGING_ROOT: str(tmp_tony_root),
            "tony.worker.instances": "2",
            keys.EXECUTES:
                f"{sys.executable} {os.path.join(FIXTURES, 'introspect_child.py')}",
            keys.TRACE_ENABLED: "true",
        })
        client = Client(cfg)
        handle = client.submit()
        logs_proc = None
        try:
            rpc = handle.rpc(timeout_s=30)
            assert rpc is not None, "AM never advertised"
            deadline = time.time() + 45
            while time.time() < deadline:
                infos = rpc.call("get_task_infos")
                if infos and all(
                    t["status"] == "RUNNING" and (t.get("metrics") or {}).get("train")
                    for t in infos
                ):
                    break
                time.sleep(0.2)
            else:
                pytest.fail(f"gang never went live: {rpc.call('get_task_infos')}")

            # -------- tony profile mid-run (blocks until both report) ----
            result: dict = {}
            th = threading.Thread(target=lambda: result.update(rc=main_profile(
                [handle.app_id, "--steps", "3", "--staging", str(tmp_tony_root),
                 "--timeout", "60"]
            )))
            th.start()
            # a second start_profile while the first is in flight → typed error
            while time.time() < deadline:
                if rpc.call("get_profile_status")["profile"] is not None:
                    break
                time.sleep(0.05)
            with pytest.raises(RpcError, match="AlreadyProfilingError"):
                rpc.call("start_profile", steps=3)
            th.join(90)
            assert not th.is_alive(), "tony profile never returned"
            assert result.get("rc") == 0, "tony profile reported failure"

            status = rpc.call("get_profile_status")["profile"]
            assert status["complete"]
            assert sorted(status["tasks"]) == ["worker:0", "worker:1"]
            for tid, entry in status["tasks"].items():
                assert entry["status"] == "captured", (tid, entry)
                assert entry["artifacts"], f"{tid} captured no artifacts"
                assert entry["summary"]["steps_captured"] >= 3
                # artifacts really exist under <staging>/profile/<identity>/
                for rel in entry["artifacts"]:
                    assert os.path.exists(os.path.join(entry["dir"], rel))
                assert entry["dir"].startswith(
                    os.path.join(handle.staging_dir, "profile")
                )
            profile_out = capsys.readouterr().out
            assert "captured" in profile_out
            assert "mean" in profile_out  # step-time summary printed

            # -------- tony top: live snapshot with a step rate -----------
            assert main_top([handle.app_id, "--staging", str(tmp_tony_root),
                             "--once"]) == 0
            frame = capsys.readouterr().out
            assert re.search(r"worker:0\s+RUNNING", frame)
            assert re.search(r"worker:1\s+RUNNING", frame)
            # live step rate from the piggybacked step-time histogram
            m = re.search(r"worker:0\s+RUNNING\s+\d+\s+\S+\s+\S+\s+(\d+\.\d+)", frame)
            assert m, f"no step rate in frame:\n{frame}"
            assert float(m.group(1)) > 0

            # -------- tony logs -f: stream during the run ----------------
            repo_root = os.path.dirname(os.path.dirname(FIXTURES))
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
            logs_proc = subprocess.Popen(
                [sys.executable, "-m", "tony_tpu.cli.main", "logs",
                 handle.app_id, "-f", "--staging", str(tmp_tony_root)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            )
            time.sleep(1.0)  # let the follower stream a first batch mid-run

            # -------- wind the job down ----------------------------------
            with open(os.path.join(handle.staging_dir, "stop"), "w"):
                pass
            final = client.monitor_application(handle, quiet=True)
            assert final == JobStatus.SUCCEEDED, handle.final_status()

            out, _ = logs_proc.communicate(timeout=60)
            text = out.decode()
            assert "[am]" in text                  # AM records
            assert "[worker:0]" in text            # executor records
            assert "[worker:0:train]" in text      # training-child records
            assert "[worker:1:train]" in text

            # merged (non-follow) view is strictly timestamp-ordered across
            # AM + executors + children
            records = obs_log.read_records(
                os.path.join(handle.staging_dir, "logs"))
            idents = {r["identity"] for r in records}
            assert {"am", "worker:0", "worker:0:train", "worker:1:train"} <= idents
            ts = [r["ts_ms"] for r in records]
            assert ts == sorted(ts)

            # -------- capture spans visible in tony trace ----------------
            spans = load_spans(os.path.join(handle.staging_dir, "trace"))
            captures = [s for s in spans if s["name"] == "profile.capture"]
            assert {s["identity"] for s in captures} == {
                "worker:0:train", "worker:1:train"
            }
            assert all(s["end_ms"] >= s["start_ms"] for s in captures)
        finally:
            if logs_proc is not None and logs_proc.poll() is None:
                logs_proc.kill()
            try:
                with open(os.path.join(handle.staging_dir, "stop"), "w"):
                    pass
            except OSError:
                pass
            obs_trace.shutdown()  # the in-process client installed a tracer
