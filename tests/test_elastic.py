"""Elastic-training suite (docs/fault-tolerance.md "Elastic training").

Covers the whole resize stack bottom-up: the shrink-on-preempt planner, the
``@step+N`` chaos gate, the exactly-once data-replay primitives
(``global_slots`` / ``ConsumptionCursor``), cross-topology checkpoint restore
(4-way → 2-way → 1-way on CPU devices), the AM's typed ``InvalidResizeError``
and hot-spare bookkeeping, ``tony top``'s resized-away row handling, the
``tony resize`` CLI — and the headline chaos E2E: a 4-worker training gang
preempted mid-run shrinks to 2, resumes from checkpoint on the smaller mesh,
and every global sample slot is consumed exactly once across the resize.
"""

import json
import os
import re
import time

import numpy as np
import pytest

from tony_tpu import constants
from tony_tpu.chaos import FaultSchedule
from tony_tpu.chaos.context import ChaosContext
from tony_tpu.cluster.scheduler import plan_preempt_shrink
from tony_tpu.config import TonyConfig, keys
from tony_tpu.data.dataset import ConsumptionCursor, global_slots

from tests.test_e2e import FAST, fixture_cmd

pytestmark = pytest.mark.elastic


# ---------------------------------------------------------------------------
# shrink-on-preempt planner: divisor targets only, floor-bounded
# ---------------------------------------------------------------------------
class TestPlanPreemptShrink:
    def test_divisor_targets_from_four(self):
        # losing 1..3 of 4 lands on the largest DIVISOR the survivors can form
        assert plan_preempt_shrink(4, 4, 1, 1) == 2  # 3 survive → 2 (never 3)
        assert plan_preempt_shrink(4, 4, 2, 1) == 2
        assert plan_preempt_shrink(4, 4, 3, 1) == 1

    def test_floor_bounds_the_shrink(self):
        assert plan_preempt_shrink(4, 4, 2, 2) == 2
        # 1 survivor < floor 2: shrinking cannot help → re-queue at full size
        assert plan_preempt_shrink(4, 4, 3, 2) is None

    def test_disabled_and_degenerate_cases(self):
        assert plan_preempt_shrink(4, 4, 1, 0) is None  # floor 0 = elasticity off
        assert plan_preempt_shrink(4, 4, 0, 1) is None  # nothing actually lost
        assert plan_preempt_shrink(4, 4, 4, 1) is None  # nobody survived

    def test_non_power_of_two_configured_count(self):
        assert plan_preempt_shrink(6, 6, 1, 1) == 3
        assert plan_preempt_shrink(6, 6, 3, 1) == 3
        assert plan_preempt_shrink(6, 6, 4, 1) == 2
        assert plan_preempt_shrink(8, 8, 3, 1) == 4


# ---------------------------------------------------------------------------
# @step+N chaos gate: grammar + progress-fed arming
# ---------------------------------------------------------------------------
class TestStepGatedFaults:
    def test_parse_step_gate(self):
        (f,) = FaultSchedule.parse("preempt:worker:3@step+4").faults
        assert f.step_gate == 4 and f.delay_ms == 0 and f.trigger is None
        assert f.target == ("worker", 3)

    def test_step_gate_is_am_decided_faults_only(self):
        # container faults + am-crash: the AM is the only process fed steps
        with pytest.raises(ValueError, match="AM-decided faults only"):
            FaultSchedule.parse("rpc-drop:p=1@step+2")

    def test_bad_step_gates_rejected(self):
        with pytest.raises(ValueError, match="non-integer step gate"):
            FaultSchedule.parse("preempt@step+soon")
        with pytest.raises(ValueError, match=">= 1"):
            FaultSchedule.parse("preempt@step+0")

    def test_gate_stays_closed_until_progress(self):
        ctx = ChaosContext(FaultSchedule.parse("preempt:worker:1@step+4"), "am")
        assert ctx.take("preempt") is None
        ctx.set_progress(3)
        assert ctx.take("preempt") is None
        ctx.set_progress(4)
        # identity "am" is not the target, so route through take_spec-style
        # matching: use an untargeted spec for the firing half
        ctx2 = ChaosContext(FaultSchedule.parse("preempt@step+4"), "am")
        assert ctx2.take("preempt") is None
        ctx2.set_progress(4)
        assert ctx2.take("preempt") is not None

    def test_progress_is_monotonic(self):
        # a gang restart resets the reported step; an opened gate stays open
        ctx = ChaosContext(FaultSchedule.parse("preempt@step+4"), "am")
        ctx.set_progress(5)
        ctx.set_progress(0)  # restarted gang reports from scratch
        assert ctx._progress_step == 5
        assert ctx.take("preempt") is not None


# ---------------------------------------------------------------------------
# exactly-once replay primitives
# ---------------------------------------------------------------------------
class TestGlobalSlots:
    def test_contiguous_rank_slices(self):
        assert list(global_slots(0, 8, 0, 4)) == [0, 1]
        assert list(global_slots(0, 8, 3, 4)) == [6, 7]
        assert list(global_slots(5, 8, 1, 2)) == [44, 45, 46, 47]

    def test_rejects_bad_shapes(self):
        with pytest.raises(ValueError, match="out of range"):
            global_slots(0, 8, 4, 4)
        with pytest.raises(ValueError, match="must divide"):
            global_slots(0, 8, 0, 3)

    def test_exactly_once_across_any_resize_history(self):
        # the elastic guarantee as a property: ANY world-size history over
        # global batches [0, T) with a constant G covers range(T*G) exactly
        G, history = 8, [(0, 3, 4), (3, 5, 2), (5, 9, 8), (9, 12, 1)]
        seen: list[int] = []
        for start, stop, world in history:
            for t in range(start, stop):
                for k in range(world):
                    seen.extend(global_slots(t, G, k, world))
        assert sorted(seen) == list(range(12 * G))
        assert len(seen) == len(set(seen))  # no slot consumed twice


class TestConsumptionCursor:
    def test_roundtrip_per_step_files(self, tmp_path):
        c = ConsumptionCursor(global_batch_index=6, global_batch_size=8, seed=3, world_size=4)
        path = c.save(tmp_path)
        assert path.name == "cursor-6.json"
        assert ConsumptionCursor.load(tmp_path, 6) == c
        # other steps' cursors are independent files
        ConsumptionCursor(global_batch_index=8, global_batch_size=8, seed=3, world_size=2).save(tmp_path)
        assert ConsumptionCursor.load(tmp_path, 6).world_size == 4

    def test_missing_or_garbage_cursor_is_none(self, tmp_path):
        assert ConsumptionCursor.load(tmp_path, 2) is None
        (tmp_path / "cursor-2.json").write_text("not json")
        assert ConsumptionCursor.load(tmp_path, 2) is None

    def test_validate_resume_accepts_world_size_change(self):
        c = ConsumptionCursor(global_batch_index=4, global_batch_size=8, seed=3, world_size=4)
        c.validate_resume(8, 3, 4)  # world size changed 4→2 is exactly what's allowed

    def test_validate_resume_rejects_stream_changes(self):
        c = ConsumptionCursor(global_batch_index=4, global_batch_size=8, seed=3, world_size=4)
        with pytest.raises(ValueError, match="global batch changed"):
            c.validate_resume(16, 3, 4)
        with pytest.raises(ValueError, match="seed changed"):
            c.validate_resume(8, 5, 4)
        with pytest.raises(ValueError, match="disagrees"):
            c.validate_resume(8, 3, 2)


# ---------------------------------------------------------------------------
# cross-topology checkpoint restore: {data: 4} → {data: 2} → {data: 1}
# ---------------------------------------------------------------------------
class TestCrossMeshRestore:
    @staticmethod
    def _state(n_dev, fill=None):
        """A training-shaped state (sharded params + optimizer moments +
        replicated step) on a {data: n_dev} mesh carved from the CPU devices."""
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
        sharded = NamedSharding(mesh, P("data"))
        replicated = NamedSharding(mesh, P())
        w = jnp.arange(32, dtype=jnp.float32).reshape(8, 4) if fill is None else jnp.full((8, 4), fill)
        params = {"w": jax.device_put(w, sharded)}
        opt_state = jax.device_put(optax.adam(1e-3).init(params), replicated)
        return {
            "params": params,
            "opt": opt_state,
            "step": jax.device_put(jnp.int32(7), replicated),
        }

    def test_four_way_checkpoint_restores_onto_two_and_one_way(self, tmp_path):
        import jax

        from tony_tpu.train.checkpoint import CheckpointManager

        d = str(tmp_path / "ckpt")
        mgr = CheckpointManager(d, use_async=False)
        src = self._state(4)
        mgr.save(2, src, force=True)
        mgr.wait()
        mgr.close()
        expect_w = np.arange(32, dtype=np.float32).reshape(8, 4)
        expect_opt = jax.device_get(jax.tree.leaves(src["opt"]))
        for m in (2, 1):
            target = self._state(m, fill=0.0)
            mgr2 = CheckpointManager(d, use_async=False)
            restored = mgr2.restore(target)
            mgr2.close()
            # parameter equality, target sharding imposed, step carried over
            np.testing.assert_array_equal(jax.device_get(restored["params"]["w"]), expect_w)
            assert restored["params"]["w"].sharding.num_devices == m
            assert int(restored["step"]) == 7
            # optimizer-state integrity: every moment leaf restored exactly
            got_opt = jax.device_get(jax.tree.leaves(restored["opt"]))
            assert len(got_opt) == len(expect_opt)
            for a, b in zip(expect_opt, got_opt):
                np.testing.assert_array_equal(a, b)

    def test_restore_or_init_resume_path_reshards(self, tmp_path):
        # the gang-restart entry point (what the resized worker actually
        # calls) applies the same target-sharding-wins contract
        import jax

        from tony_tpu.train.checkpoint import CheckpointManager, restore_or_init

        d = str(tmp_path / "ckpt")
        mgr = CheckpointManager(d, use_async=False)
        mgr.save(4, self._state(4), force=True)
        mgr.wait()
        mgr.close()
        state, mgr2, step = restore_or_init(d, lambda: self._state(2, fill=0.0), use_async=False)
        try:
            assert step == 4
            assert state["params"]["w"].sharding.num_devices == 2
            np.testing.assert_array_equal(
                jax.device_get(state["params"]["w"]),
                np.arange(32, dtype=np.float32).reshape(8, 4),
            )
        finally:
            mgr2.close()


# ---------------------------------------------------------------------------
# AM-level units: typed InvalidResizeError + hot-spare bookkeeping
# ---------------------------------------------------------------------------
@pytest.fixture()
def quiet_am(tmp_path):
    from tony_tpu.cluster.appmaster import ApplicationMaster

    cfg = TonyConfig({
        "tony.worker.instances": "4",
        keys.ELASTIC_MIN_WORKERS: "2",
        keys.ELASTIC_MAX_WORKERS: "8",
    })
    am = ApplicationMaster(cfg, "app_elastic_unit", str(tmp_path / "stage"))
    yield am
    am.rpc.stop()
    am.events.stop()
    am.rm.shutdown()


class TestInvalidResize:
    def test_typed_rejections(self, quiet_am):
        from tony_tpu.cluster.appmaster import InvalidResizeError

        with pytest.raises(InvalidResizeError, match="unknown job type"):
            quiet_am.resize_jobtype("nope", 2)
        with pytest.raises(InvalidResizeError, match=">= 1"):
            quiet_am.resize_jobtype("worker", 0)
        with pytest.raises(InvalidResizeError, match="below tony.elastic.min-workers"):
            quiet_am.resize_jobtype("worker", 1)
        with pytest.raises(InvalidResizeError, match="above tony.elastic.max-workers"):
            quiet_am.resize_jobtype("worker", 16)

    def test_conflicting_pending_resize_rejected(self, quiet_am):
        from tony_tpu.cluster.appmaster import InvalidResizeError

        assert quiet_am.resize_jobtype("worker", 8) == {"ack": True, "current": 4}
        with pytest.raises(InvalidResizeError, match="already pending"):
            quiet_am.resize_jobtype("worker", 6)
        # re-asking for the SAME pending target is not a conflict
        assert quiet_am.resize_jobtype("worker", 8)["ack"]

    def test_noop_clears_pending(self, quiet_am):
        # asking for the CURRENT size is the explicit abort of a pending
        # resize — and the cancellation is reported, not silent
        quiet_am.resize_jobtype("worker", 8)
        r = quiet_am.resize_jobtype("worker", 4)
        assert r["noop"] and r["cancelled_pending"] == 8
        assert quiet_am._pending_resize == {}
        assert "cancelled_pending" not in quiet_am.resize_jobtype("worker", 4)

    def test_typed_error_crosses_the_rpc_frame(self, quiet_am):
        from tony_tpu.cluster.rpc import APPLICATION_RPC_METHODS, RpcClient, RpcError

        quiet_am.rpc.register_object(quiet_am, APPLICATION_RPC_METHODS)
        quiet_am.rpc.start()
        host, port = quiet_am.rpc.address
        cli = RpcClient(host, port, secret=quiet_am.secret)
        try:
            with pytest.raises(RpcError, match="InvalidResizeError.*unknown job type"):
                cli.call("resize_jobtype", job_name="ghost", instances=2)
        finally:
            cli.close()


class TestSpareBookkeeping:
    def test_unknown_spare_is_stale(self, quiet_am):
        assert quiet_am.register_spare("spare-9", "h", 1) == {"ack": False, "stale": True}
        assert quiet_am.poll_spare_assignment("spare-9") == {"stale": True}

    def test_registered_spare_parks_until_promoted(self, quiet_am):
        from tony_tpu.cluster.resources import Container, Resources

        c = Container(id="c_sp", host="h", resources=Resources(), job_type="worker", task_index=-1)
        quiet_am._spares["spare-1"] = {"container": c, "ready": False, "assignment": None}
        assert quiet_am.register_spare("spare-1", "h", 1)["ack"]
        assert quiet_am._spares["spare-1"]["ready"]
        assert quiet_am.poll_spare_assignment("spare-1") == {"assignment": None}
        quiet_am._containers.clear()  # _bind_spare registers it as a gang container
        quiet_am._bind_spare("spare-1", "worker", 1)
        got = quiet_am.poll_spare_assignment("spare-1")["assignment"]
        assert got == {"job_name": "worker", "index": 1, "attempt": 0}
        assert c.job_type == "worker" and c.task_index == 1
        assert quiet_am._by_task[("worker", 1)] is c

    def test_parked_spare_death_is_reaped(self, quiet_am):
        from tony_tpu.cluster.resources import Container, Resources

        released = []
        quiet_am.rm.release = released.append
        c = Container(id="c_dead", host="h", resources=Resources(), job_type="worker", task_index=-1)
        quiet_am._spares["spare-2"] = {"container": c, "ready": True, "assignment": None}
        quiet_am._reap_dead_spare("c_dead", 137)
        assert "spare-2" not in quiet_am._spares
        assert released == [c]

    def test_promoted_spare_is_not_reaped_as_spare(self, quiet_am):
        from tony_tpu.cluster.resources import Container, Resources

        c = Container(id="c_prom", host="h", resources=Resources(), job_type="worker", task_index=0)
        quiet_am._spares["spare-3"] = {"container": c, "ready": True, "assignment": {"job_name": "worker", "index": 0, "attempt": 0}}
        quiet_am._reap_dead_spare("c_prom", 1)  # promoted: ordinary gang container
        assert "spare-3" in quiet_am._spares


# ---------------------------------------------------------------------------
# tony top / portal: rows removed by a shrink
# ---------------------------------------------------------------------------
class TestTopRowsResizedAway:
    @staticmethod
    def _info(name, index, status):
        return {"name": name, "index": index, "status": status,
                "metrics": {}, "last_heartbeat_ms": time.time() * 1000}

    def test_terminal_rows_beyond_instance_count_are_dropped(self):
        from tony_tpu.obs.introspect import build_top_rows

        infos = [
            self._info("worker", 0, "RUNNING"),
            self._info("worker", 1, "RUNNING"),
            self._info("worker", 2, "KILLED"),   # removed by the 4→2 shrink
            self._info("worker", 3, "FAILED"),
        ]
        rows = build_top_rows(infos, {}, instances={"worker": 2})
        assert [r["task"] for r in rows] == ["worker:0", "worker:1"]

    def test_in_teardown_rows_show_resized_away(self):
        from tony_tpu.obs.introspect import build_top_rows

        infos = [self._info("worker", 0, "RUNNING"), self._info("worker", 2, "RUNNING")]
        rows = build_top_rows(infos, {}, instances={"worker": 2})
        assert rows[1]["state"] == "resized-away"

    def test_without_instance_counts_nothing_is_dropped(self):
        from tony_tpu.obs.introspect import build_top_rows

        infos = [self._info("worker", 3, "FAILED")]
        assert len(build_top_rows(infos, {})) == 1


# ---------------------------------------------------------------------------
# tony resize CLI against a staged fake AM
# ---------------------------------------------------------------------------
class TestResizeCLI:
    @staticmethod
    def _stage_am(tmp_path, handler):
        from tony_tpu.cluster.rpc import RpcServer

        srv = RpcServer(secret="s3")
        srv.register("resize_jobtype", handler)
        srv.start()
        host, port = srv.address
        app_dir = tmp_path / "app_cli"
        app_dir.mkdir()
        (app_dir / constants.AM_INFO_FILE).write_text(
            json.dumps({"host": host, "port": port, "secret": "s3"}))
        return srv

    def test_accepted_resize(self, tmp_path, capsys):
        from tony_tpu.cli.elastic import main_resize

        srv = self._stage_am(tmp_path, lambda job_name, instances: {"ack": True, "current": 4})
        try:
            rc = main_resize(["app_cli", "worker", "2", "--staging", str(tmp_path)])
        finally:
            srv.stop()
        out = capsys.readouterr().out
        assert rc == 0 and "worker: 4 → 2 accepted" in out

    def test_noop_resize(self, tmp_path, capsys):
        from tony_tpu.cli.elastic import main_resize

        srv = self._stage_am(
            tmp_path, lambda job_name, instances: {"ack": True, "current": 2, "noop": True})
        try:
            rc = main_resize(["app_cli", "worker", "2", "--staging", str(tmp_path)])
        finally:
            srv.stop()
        assert rc == 0 and "nothing to do" in capsys.readouterr().out

    def test_typed_rejection_exits_2(self, tmp_path, capsys):
        from tony_tpu.cli.elastic import main_resize
        from tony_tpu.cluster.appmaster import InvalidResizeError

        def reject(job_name, instances):
            raise InvalidResizeError(f"target {instances} below tony.elastic.min-workers=2")

        srv = self._stage_am(tmp_path, reject)
        try:
            rc = main_resize(["app_cli", "worker", "1", "--staging", str(tmp_path)])
        finally:
            srv.stop()
        err = capsys.readouterr().err
        assert rc == 2 and "rejected" in err and "min-workers" in err

    def test_no_am_exits_1(self, tmp_path, capsys):
        from tony_tpu.cli.elastic import main_resize

        rc = main_resize(["app_gone", "worker", "2", "--staging", str(tmp_path)])
        assert rc == 1
        assert "no running AM" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# E2E: hot-spare promotion covers a grow without fresh allocation
# ---------------------------------------------------------------------------
@pytest.mark.e2e
class TestSparePromotionE2E:
    @pytest.mark.slow
    def test_grow_promotes_a_parked_spare(self, tmp_tony_root):
        from tony_tpu.cluster import history
        from tony_tpu.cluster.client import Client
        from tony_tpu.cluster.session import JobStatus

        cfg = TonyConfig({
            **FAST,
            keys.STAGING_ROOT: str(tmp_tony_root),
            "tony.worker.instances": "1",
            keys.ELASTIC_SPARES: "1",
            keys.EXECUTES: fixture_cmd("forever.py"),
        })
        client = Client(cfg)
        handle = client.submit()
        jhist = os.path.join(
            str(tmp_tony_root), "history",
            constants.HISTORY_INTERMEDIATE_DIR, handle.app_id + constants.HISTORY_SUFFIX)
        try:
            rpc = handle.rpc(timeout_s=30)
            assert rpc is not None

            def _wait(fn, timeout_s=60):
                deadline = time.time() + timeout_s
                while time.time() < deadline:
                    got = fn()
                    if got:
                        return got
                    time.sleep(0.1)
                return None

            # the spare parks (SPARE_READY streams to the in-flight .jhist)
            def spare_ready():
                try:
                    with open(jhist) as f:
                        return "SPARE_READY" in f.read()
                except OSError:
                    return False

            assert _wait(spare_ready), "hot spare never registered"
            assert rpc.call("resize_jobtype", job_name="worker", instances=2)["ack"]

            def two_running():
                infos = rpc.call("get_task_infos")
                return infos if (
                    len(infos) == 2 and all(t["status"] == "RUNNING" for t in infos)
                ) else None

            assert _wait(two_running, timeout_s=90), "grow to 2 never converged"
        finally:
            Client.kill(handle)
        final = client.monitor_application(handle, quiet=True)
        assert final == JobStatus.KILLED
        events = history.read_events(os.path.join(str(tmp_tony_root), "history"), handle.app_id)
        promoted = [e for e in events if e.type.value == "SPARE_PROMOTED"]
        # the grow consumed the parked spare instead of allocating fresh
        assert promoted and promoted[0].payload["task"] == "worker:1"
        resized = [e for e in events if e.type.value == "GANG_RESIZED"]
        assert resized and resized[0].payload["trigger"] == "rpc"


# ---------------------------------------------------------------------------
# E2E headline: preempt K workers mid-run → shrink 4→2 → resume → exactly-once
# ---------------------------------------------------------------------------
@pytest.mark.e2e
@pytest.mark.chaos
class TestElasticShrinkHeadlineE2E:
    STEPS = 24  # attempt 0 gets a 10x budget; post-shrink attempts train to 24
    GLOBAL_BATCH = 4
    SEQ = 64

    def test_preempted_gang_shrinks_resumes_and_replays_exactly_once(
            self, tmp_tony_root, tmp_path, capsys):
        from tony_tpu.cli.chaos import main as chaos_main
        from tony_tpu.data import TokenLoader, write_token_shard

        data = tmp_path / "data"
        data.mkdir()
        write_token_shard(
            data / "s0.tonytok", (np.arange(120_000) % 251).astype(np.int32))
        shared = tmp_path / "shared"

        spec = "preempt:worker:2@step+4;preempt:worker:3@step+4"
        rc = chaos_main([
            "--spec", spec,
            "--seed", "17",
            "--executes", f"{fixture_cmd('elastic_chaos_train.py')} {data} {shared} {self.STEPS}",
            "--workers", "4",
            "--expect-resume",
            "--expect-resize", "worker=2",
            "--conf", f"{keys.STAGING_ROOT}={tmp_tony_root}",
            "--conf", f"{keys.TASK_RESTART_ON_FAILURE}=true",
            # @step+N gates arm off the executor-pushed train metrics; the
            # default 5s push cadence would let attempt 0 run far past the gate
            "--conf", f"{keys.TASK_METRICS_INTERVAL_MS}=200",
            "--conf", f"{keys.ELASTIC_SHRINK_ON_PREEMPT}=true",
            "--conf", f"{keys.ELASTIC_MIN_WORKERS}=1",
        ] + [f"--conf={k}={v}" for k, v in FAST.items()])
        captured = capsys.readouterr()
        out = captured.out
        # tony chaos verdict: SUCCESS + no orphans + gang-complete once per
        # epoch + .jhist finalized + a checkpoint resume + the 4→2 landing
        assert rc == 0, out + captured.err
        assert "invariants: OK" in out
        assert "job finished: SUCCEEDED" in out
        assert "gang epochs: 2" in out, out  # ONE resize restart, no thrash

        app_id = re.search(r"submitted (\S+) under schedule", out).group(1)
        staging = os.path.join(str(tmp_tony_root), app_id)

        # the shrunken gang really ran at 2: attempt-1 logs exist for exactly
        # workers 0 and 1, and the fixture reports world=2
        logs = os.path.join(staging, "logs")
        r1 = sorted(d for d in os.listdir(logs) if d.endswith("_r1"))
        assert r1 == ["worker_0_r1", "worker_1_r1"], r1
        with open(os.path.join(logs, "worker_0_r1", "stdout.log")) as f:
            resumed_out = f.read()
        assert f"elastic-chaos attempt 1: rank=0 step={self.STEPS} world=2" in resumed_out, resumed_out
        resumed = re.search(r"resumed from checkpoint step (\d+)", resumed_out)
        assert resumed, resumed_out
        resume_step = int(resumed.group(1))
        assert 0 < resume_step < self.STEPS
        # the cursor gate ran on the resized resume (stream provenance held)
        assert "data cursor validated" in resumed_out, resumed_out
        assert "written at world size 4, now 2" in resumed_out, resumed_out

        # data determinism across the resize. The committed stream is steps
        # [0, resume) at world 4 + [resume, STEPS) at world 2; every rank
        # recorded a content hash per local batch it actually drew.
        records = []
        for fn in os.listdir(shared):
            if not fn.startswith("consumed-"):
                continue
            with open(shared / fn) as f:
                for line in f:
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        pass  # a SIGKILLed writer may leave one torn tail line
        committed = [
            r for r in records
            if (r["attempt"] == 0 and r["world"] == 4 and r["t"] < resume_step)
            or (r["attempt"] == 1 and r["world"] == 2 and resume_step <= r["t"] < self.STEPS)
        ]
        # (a) slot accounting: recomputing each record's global slots with
        # the loader's repartition rule covers every slot exactly once
        consumed: list[int] = []
        for r in committed:
            consumed.extend(global_slots(r["t"], self.GLOBAL_BATCH, r["rank"], r["world"]))
        assert len(consumed) == len(set(consumed)), "a sample slot was double-consumed"
        assert sorted(consumed) == list(range(self.STEPS * self.GLOBAL_BATCH)), \
            "a sample slot was dropped across the resize"
        # (b) content equality: what the resized gang actually drew IS the
        # uninterrupted stream — an unsharded reference draw over the same
        # (seed, global batch) produces byte-identical rank slices
        import hashlib

        ref = TokenLoader(
            [data / "s0.tonytok"], self.GLOBAL_BATCH, self.SEQ,
            shard_id=0, num_shards=1, seed=0)
        try:
            ref_hashes: dict[tuple[int, int, int], str] = {}
            for t in range(self.STEPS):
                batch = ref.next()
                for world in (4, 2):
                    b = self.GLOBAL_BATCH // world
                    for k in range(world):
                        rows = np.ascontiguousarray(batch[k * b:(k + 1) * b])
                        ref_hashes[(t, world, k)] = hashlib.sha1(rows.tobytes()).hexdigest()
        finally:
            ref.close()
        for r in committed:
            assert r["sha1"] == ref_hashes[(r["t"], r["world"], r["rank"])], r

        # the final consumption cursor records the post-resize world
        final_cursor = ConsumptionCursor.load(shared / "ckpt", self.STEPS)
        assert final_cursor is not None
        assert final_cursor.world_size == 2
        assert final_cursor.global_batch_size == self.GLOBAL_BATCH
