"""Multi-host pool service tests: RM daemon + NodeAgent protocol.

The reference's RM/NM machine boundary (SURVEY.md §2.1 AM → NMClient, §3.1
process boundary #2), tested the reference's way (SURVEY.md §4): real daemons
on loopback — the pool service in-process, ≥2 host agents as separate OS
processes — driving the real client → AM → executor spine.
"""

import os
import re
import signal
import subprocess
import sys
import time

import pytest

from tony_tpu import constants
from tony_tpu.config import TonyConfig, keys
from tony_tpu.cluster.client import Client
from tony_tpu.cluster.pool import PoolService, RemoteResourceManager, _rect_from
from tony_tpu.cluster.resources import AllocationError, Resources
from tony_tpu.cluster.session import JobStatus

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")

FAST = {
    keys.AM_MONITOR_INTERVAL_MS: "50",
    keys.TASK_HEARTBEAT_INTERVAL_MS: "100",
    keys.AM_GANG_TIMEOUT_MS: "30000",
}

SECRET = "pool-test-secret"


def fixture_cmd(name: str) -> str:
    return f"{sys.executable} {os.path.join(FIXTURES, name)}"


# ---------------------------------------------------------------------------
# Unit: per-node rectangle carving
# ---------------------------------------------------------------------------
class TestRectFrom:
    def test_exact_block(self):
        free = {(0, 0), (0, 1), (1, 0), (1, 1)}
        assert set(_rect_from(free, 4)) == free

    def test_subrect_prefers_square(self):
        free = {(r, c) for r in range(2) for c in range(4)}
        got = _rect_from(free, 4)
        rows = {r for r, _ in got}
        cols = {c for _, c in got}
        assert len(rows) == 2 and len(cols) == 2  # 2x2, not 1x4

    def test_fragmented_no_rect(self):
        # 3 free chips in an L: no contiguous 1x3/3x1
        assert _rect_from({(0, 0), (0, 1), (1, 1)}, 3) is None

    def test_zero_and_too_big(self):
        assert _rect_from(set(), 0) == ()
        assert _rect_from({(0, 0)}, 2) is None


# ---------------------------------------------------------------------------
# Unit: pool service model (no RPC, direct method calls)
# ---------------------------------------------------------------------------
@pytest.fixture()
def pool():
    svc = PoolService(heartbeat_interval_ms=100, max_missed_heartbeats=3, secret=SECRET)
    yield svc
    svc.stop()


def register_cpu_node(svc, name, memory=4 * 1024**3, vcores=8):
    svc.register_node(name=name, host="127.0.0.1", port=1, memory_bytes=memory, vcores=vcores)


class TestPoolModel:
    def test_allocate_spreads_by_memory(self, pool):
        register_cpu_node(pool, "n0")
        register_cpu_node(pool, "n1")
        a = pool.allocate("app", "worker", 0, 3 * 1024**3, 1, 0)
        b = pool.allocate("app", "worker", 1, 3 * 1024**3, 1, 0)
        assert {a["node"], b["node"]} == {"n0", "n1"}
        # transient shortage (capacity busy, ask feasible) now WAITS instead
        # of failing — AllocationError is reserved for never-fits asks
        got = pool.allocate("app", "worker", 2, 3 * 1024**3, 1, 0)
        assert got.get("wait") is True
        with pytest.raises(AllocationError, match="memory"):
            pool.allocate("app", "worker", 2, 5 * 1024**3, 1, 0)  # > any host

    def test_chips_from_one_node_only(self, pool):
        pool.register_node(
            name="t0", host="h", port=1, memory_bytes=8 * 1024**3, vcores=8,
            slice_id=0, slice_spec="v5e-8", chips=[[0, 0], [0, 1], [1, 0], [1, 1]],
        )
        pool.register_node(
            name="t1", host="h", port=1, memory_bytes=8 * 1024**3, vcores=8,
            slice_id=0, slice_spec="v5e-8", chips=[[0, 2], [0, 3], [1, 2], [1, 3]],
        )
        got = pool.allocate("app", "worker", 0, 1024, 1, 4)
        assert got["node"] in ("t0", "t1") and len(got["chips"]) == 4
        with pytest.raises(AllocationError, match="per-host"):
            pool.allocate("app", "worker", 1, 1024, 1, 8)  # larger than any host

    def test_gang_packs_into_one_slice(self, pool):
        for s in (0, 1):
            for h in (0, 1):
                pool.register_node(
                    name=f"s{s}h{h}", host="h", port=1, memory_bytes=8 * 1024**3,
                    vcores=8, slice_id=s, slice_spec="v5e-8",
                    chips=[[r, 2 * h + c] for r in (0, 1) for c in (0, 1)],
                )
        a = pool.allocate("app", "worker", 0, 1024, 1, 4)
        b = pool.allocate("app", "worker", 1, 1024, 1, 4)
        assert a["slice_id"] == b["slice_id"]  # same slice → ICI, not DCN
        assert a["node"] != b["node"]

    def test_chip_collision_rejected(self, pool):
        pool.register_node(
            name="t0", host="h", port=1, memory_bytes=1024, vcores=8,
            slice_id=0, slice_spec="v5e-8", chips=[[0, 0], [0, 1]],
        )
        with pytest.raises(ValueError, match="collide"):
            pool.register_node(
                name="t1", host="h", port=1, memory_bytes=1024, vcores=8,
                slice_id=0, slice_spec="v5e-8", chips=[[0, 1], [0, 2]],
            )

    def test_exit_frees_resources(self, pool):
        register_cpu_node(pool, "n0")
        got = pool.allocate("app", "worker", 0, 3 * 1024**3, 1, 0)
        pool.node_heartbeat("n0", exited={got["id"]: 0})
        assert pool.poll_exited("app") == {got["id"]: 0}
        assert pool.poll_exited("app") == {}  # drained
        pool.allocate("app", "worker", 1, 3 * 1024**3, 1, 0)  # memory was freed

    def test_dead_node_containers_lost(self, pool):
        register_cpu_node(pool, "n0")
        got = pool.allocate("app", "worker", 0, 1024, 1, 0)
        node = pool._nodes["n0"]
        node.last_heartbeat -= 10  # way past 3×100ms
        pool._monitor.start()
        deadline = time.time() + 5
        exited = {}
        while time.time() < deadline and not exited:
            exited = pool.poll_exited("app")
            time.sleep(0.02)
        assert exited == {got["id"]: constants.EXIT_NODE_LOST}
        assert not node.alive
        # a dead node takes no new work; a KNOWN node may come back
        # (re-register), so the ask waits rather than failing the job
        assert pool.allocate("app", "worker", 1, 1024, 1, 0).get("wait") is True
        # and a late heartbeat from it is told to re-register
        assert pool.node_heartbeat("n0") == {"unknown_node": True}

    def test_all_nodes_dead_wait_is_bounded(self, pool):
        """Agents that stay gone past one liveness budget are permanently
        dead: the ask must escalate to AllocationError, not queue forever
        (ADVICE r4: unbounded AllocationPending retry)."""
        register_cpu_node(pool, "n0")
        pool._nodes["n0"].alive = False
        # within the budget: wait (the agent may re-register)
        assert pool.allocate("app", "w", 0, 1024, 1, 0).get("wait") is True
        # past the budget (backdate the first all-dead observation)
        pool._all_dead_since -= 10
        with pytest.raises(AllocationError, match="permanently"):
            pool.allocate("app", "w", 0, 1024, 1, 0)
        # a node coming back clears the escalation clock AT REGISTRATION
        # (not only on the allocate path): a stale timestamp from this
        # outage must not insta-fail a future brief blip
        register_cpu_node(pool, "n0")
        assert pool._all_dead_since is None
        assert "id" in pool.allocate("app", "w", 0, 1024, 1, 0)


# ---------------------------------------------------------------------------
# Unit: incremental journal compaction (tony.pool.journal.compact-every)
# ---------------------------------------------------------------------------
class TestPoolJournalCompaction:
    """Snapshot+rotate compaction (docs/performance.md "Control-plane
    scalability"): replay of a compacted journal must be EQUIVALENT to the
    state the writer held — proven property-style over seeded op histories —
    while the on-disk file stays O(live state)."""

    def _drive(self, svc, seed, ops=120):
        """Seeded register/allocate/exit/release churn through the REAL pool
        methods (every one journals through _jlog_locked, so compaction
        triggers on the production path). Biased to leave live state."""
        import random

        rng = random.Random(seed)
        svc.register_node("n0", "127.0.0.1", 1,
                          memory_bytes=1 << 40, vcores=4096)
        live = {}
        for i in range(ops):
            r = rng.random()
            if r < 0.6 or not live:
                app = f"app_{i}"
                svc.register_app(app, queue="default",
                                 priority=rng.randrange(3),
                                 memory_bytes=1 << 20, vcores=1)
                got = svc.allocate(app, "worker", 0,
                                   memory_bytes=1 << 20, vcores=1)
                if "id" in got:
                    live[app] = got["id"]
            elif r < 0.85:
                app, cid = rng.choice(sorted(live.items()))
                svc.node_heartbeat("n0", exited={cid: 0})
                if rng.random() < 0.5:
                    svc.poll_exited(app)  # some exits delivered, some pending
                if rng.random() < 0.4:
                    svc.release(app, cid)  # some exited containers released
                del live[app]
            else:
                app, cid = rng.choice(sorted(live.items()))
                svc.release_all(app)
                del live[app]
        return live

    @staticmethod
    def _state(svc):
        apps = {
            a.app_id: (a.queue, a.priority, a.seq, a.admitted, a.preempted,
                       a.demand_memory, a.demand_vcores, a.demand_chips,
                       round(a.wait_unix, 3), round(a.admitted_unix, 3))
            for a in svc._apps.values()
        }
        conts = {
            cid: {k: v for k, v in rec.items() if k != "seen_live"}
            for cid, rec in svc._containers.items()
        }
        return apps, conts, {k: dict(v) for k, v in svc._app_exits.items()}

    @pytest.mark.parametrize("compact_every", [0, 20])
    def test_replay_fidelity_with_and_without_compaction(self, tmp_path, compact_every):
        path = str(tmp_path / "pool.jsonl")
        svc = PoolService(journal_path=path,
                          journal_compact_every=compact_every, port=0)
        live = self._drive(svc, seed=11)
        assert live  # the scenario must actually cover live containers
        before = self._state(svc)
        svc.stop()
        restarted = PoolService(journal_path=path, port=0)
        try:
            assert self._state(restarted) == before
        finally:
            restarted.stop()

    def test_compaction_bounds_the_file(self, tmp_path):
        plain, compacted = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")

        def lines(p):
            with open(p) as f:
                return sum(1 for line in f if line.strip())

        s1 = PoolService(journal_path=plain, port=0)
        self._drive(s1, seed=3, ops=200)
        s1.stop()
        s2 = PoolService(journal_path=compacted, journal_compact_every=25, port=0)
        self._drive(s2, seed=3, ops=200)
        s2.stop()
        assert lines(compacted) < lines(plain) / 3

    def test_drain_episode_survives_compaction(self, tmp_path):
        """In-flight drain/shrink state is part of the snapshot: a pool that
        compacts mid-drain and then restarts must still escalate the
        episode (deadline rebased onto the new process's clock)."""
        path = str(tmp_path / "pool.jsonl")
        svc = PoolService(journal_path=path, journal_compact_every=1, port=0)
        with svc._lock:
            svc._drains["victim"] = {
                "req_id": "pre-test1", "mode": "drain", "workers": 0,
                "target_primary": 0,
                "deadline": time.monotonic() + 30.0,
                "t0": time.monotonic() - 2.0, "escalated": False,
            }
            # stage a journaled transition; the sync below (what every RPC
            # entry point runs after releasing the lock) must trigger a
            # compaction that folds the drain into the snapshot
            svc._jlog_locked("app_removed", app_id="nobody")
        svc._journal_sync()
        svc.stop()
        restarted = PoolService(journal_path=path, port=0)
        try:
            entry = restarted._drains["victim"]
            assert entry["req_id"] == "pre-test1"
            remaining = entry["deadline"] - time.monotonic()
            assert 20.0 < remaining < 31.0  # rebased, not reset
        finally:
            restarted.stop()

    def test_capacity_market_survives_restart_and_compaction(self, tmp_path):
        """A pool restart mid-spike preserves the capacity market: the
        published serve deficit (its TTL counted from the ORIGINAL publish
        instant, not restart time), the grow-back debt ledger, and an
        in-flight grow offer with its deadline rebased onto the new
        process's clock (docs/scheduling.md "Capacity market")."""
        path = str(tmp_path / "pool.jsonl")
        svc = PoolService(journal_path=path, journal_compact_every=1, port=0)
        now_unix, now_mono = time.time(), time.monotonic()
        with svc._lock:
            svc._demand["serve_head"] = {
                "workers": 2, "unit": (1 << 30, 1, 0),
                "unix": now_unix - 10.0, "mono": now_mono - 10.0,
            }
            svc._journal_demand_locked("serve_head")
            svc._shrunk["train_gang"] = {
                "workers": 2, "unit": (1 << 30, 1, 0), "queue": "train",
                "since_unix": now_unix - 8.0,
            }
            svc._grows["train_gang"] = {
                "req_id": "grow-pre-1", "workers": 1,
                "expected_primary": 4, "deadline": now_mono + 30.0,
            }
            svc._journal_growback_locked("train_gang")
            # stage a journaled transition; the sync below must fold the
            # market rows into the compaction snapshot
            svc._jlog_locked("app_removed", app_id="nobody")
        svc._journal_sync()
        svc.stop()
        restarted = PoolService(journal_path=path, port=0)
        try:
            d = restarted._demand["serve_head"]
            assert d["workers"] == 2 and d["unit"] == (1 << 30, 1, 0)
            # TTL clock rebased: ~10s of publish age already elapsed
            assert 8.0 < time.monotonic() - d["mono"] < 13.0
            s = restarted._shrunk["train_gang"]
            assert s["workers"] == 2 and s["queue"] == "train"
            assert s["unit"] == (1 << 30, 1, 0)
            assert abs(s["since_unix"] - (now_unix - 8.0)) < 2.0
            g = restarted._grows["train_gang"]
            assert g["req_id"] == "grow-pre-1" and g["workers"] == 1
            assert g["expected_primary"] == 4
            remaining = g["deadline"] - time.monotonic()
            assert 20.0 < remaining < 31.0  # rebased, not reset

            # clearing records replay too: workers=0 retracts the deficit
            # and settles the debt (dropping the offer with it)
            with restarted._lock:
                restarted._demand.pop("serve_head")
                restarted._journal_demand_locked("serve_head")
                restarted._shrunk.pop("train_gang")
                restarted._grows.pop("train_gang")
                restarted._journal_growback_locked("train_gang")
            restarted._journal_sync()
        finally:
            restarted.stop()
        final = PoolService(journal_path=path, port=0)
        try:
            assert not final._demand and not final._shrunk and not final._grows
        finally:
            final.stop()


# ---------------------------------------------------------------------------
# E2E: pool service + ≥2 agent PROCESSES on loopback, full submit spine
# ---------------------------------------------------------------------------
def spawn_agent(rm_addr, name, tmp, memory="4g", extra=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    log = open(os.path.join(tmp, f"agent_{name}.log"), "ab")
    return subprocess.Popen(
        [
            sys.executable, "-u", "-m", "tony_tpu.cluster.agent",
            "--rm", f"{rm_addr[0]}:{rm_addr[1]}", "--name", name,
            "--secret", SECRET, "--memory", memory, "--vcores", "8",
            "--heartbeat-ms", "100", *extra,
        ],
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )


@pytest.fixture()
def pool_with_agents(tmp_tony_root, tmp_path):
    svc = PoolService(heartbeat_interval_ms=100, max_missed_heartbeats=4, secret=SECRET)
    svc.start()
    agents = [
        spawn_agent(svc.address, "nodeA", str(tmp_path)),
        spawn_agent(svc.address, "nodeB", str(tmp_path)),
    ]
    deadline = time.time() + 15
    while time.time() < deadline:
        if sum(1 for n in svc._nodes.values() if n.alive) >= 2:
            break
        time.sleep(0.05)
    else:
        raise RuntimeError("agents failed to register")
    yield svc, agents
    for a in agents:
        if a.poll() is None:
            a.terminate()
    for a in agents:
        try:
            a.wait(timeout=5)
        except subprocess.TimeoutExpired:
            a.kill()
    svc.stop()


def pool_conf(svc, extra):
    host, port = svc.address
    return {
        **FAST,
        keys.TPU_POOL_SPEC: f"rm:{host}:{port}",
        keys.TPU_POOL_SECRET: SECRET,
        **extra,
    }


def run_job(tmp_tony_root, conf) -> tuple[JobStatus, object]:
    cfg = TonyConfig({keys.STAGING_ROOT: str(tmp_tony_root), **conf})
    client = Client(cfg)
    handle = client.submit()
    final = client.monitor_application(handle, quiet=True)
    return final, handle


@pytest.mark.e2e
class TestPoolE2E:
    def test_executors_launch_via_agents_on_two_nodes(self, tmp_tony_root, pool_with_agents):
        svc, _ = pool_with_agents
        final, handle = run_job(
            tmp_tony_root,
            pool_conf(svc, {
                "tony.worker.instances": "2",
                "tony.worker.memory": "3g",   # 3g+3g > one 4g node → must spread
                keys.EXECUTES: fixture_cmd("record_node.py"),
            }),
        )
        assert final == JobStatus.SUCCEEDED, handle.final_status()
        nodes = set()
        for i in (0, 1):
            with open(os.path.join(handle.staging_dir, f"node_of_worker_{i}.txt")) as f:
                nodes.add(f.read())
        assert nodes == {"nodeA", "nodeB"}  # launched BY the agents, one each

    def test_node_death_fails_job(self, tmp_tony_root, pool_with_agents):
        svc, agents = pool_with_agents
        cfg = TonyConfig({
            keys.STAGING_ROOT: str(tmp_tony_root),
            **pool_conf(svc, {
                "tony.worker.instances": "2",
                "tony.worker.memory": "3g",
                keys.EXECUTES: fixture_cmd("forever.py"),
            }),
        })
        client = Client(cfg)
        handle = client.submit()
        # wait for both workers to be running, then SIGKILL one agent (the
        # whole "machine" dies: its heartbeats stop, its container orphans)
        rpc = handle.rpc(timeout_s=30)
        assert rpc is not None
        deadline = time.time() + 30
        while time.time() < deadline:
            infos = rpc.call("get_task_infos")
            if len(infos) == 2 and all(i["status"] == "RUNNING" for i in infos):
                break
            time.sleep(0.1)
        os.kill(agents[0].pid, signal.SIGKILL)
        final = client.monitor_application(handle, quiet=True)
        assert final == JobStatus.FAILED
        status = handle.final_status()
        codes = {t["exit_code"] for t in status["tasks"]}
        assert constants.EXIT_NODE_LOST in codes, status

    def test_node_death_gang_restart_recovers(self, tmp_tony_root, pool_with_agents):
        svc, agents = pool_with_agents
        cfg = TonyConfig({
            keys.STAGING_ROOT: str(tmp_tony_root),
            **pool_conf(svc, {
                "tony.worker.instances": "2",
                "tony.worker.memory": "1g",   # after the node dies, BOTH fit on the survivor
                keys.TASK_RESTART_ON_FAILURE: "true",
                keys.EXECUTES: fixture_cmd("lost_then_ok.py"),
            }),
        })
        client = Client(cfg)
        handle = client.submit()
        rpc = handle.rpc(timeout_s=30)
        assert rpc is not None
        deadline = time.time() + 30
        while time.time() < deadline:
            infos = rpc.call("get_task_infos")
            if len(infos) == 2 and all(i["status"] == "RUNNING" for i in infos):
                break
            time.sleep(0.1)
        os.kill(agents[0].pid, signal.SIGKILL)
        final = client.monitor_application(handle, quiet=True)
        assert final == JobStatus.SUCCEEDED, handle.final_status()
        # the restarted gang ran entirely on the surviving node
        for i in (0, 1):
            with open(os.path.join(handle.staging_dir, f"node_of_worker_{i}.txt")) as f:
                assert f.read() == "nodeB"

    @pytest.mark.slow  # ~3 min multi-process e2e: node kill + downsize-grace waits
    def test_node_death_gang_downsizes_and_resumes(self, tmp_tony_root, pool_with_agents, tmp_path):
        """The full elastic loop (VERDICT r4 #1): a 2-worker training gang
        loses one node FOR GOOD; the configured gang (2×3g) no longer fits
        the surviving 4g node, so the AM re-plans to 1 worker
        (tony.worker.min-instances=1), the pool admits the shrunken demand,
        and the restarted single process restores the checkpoint onto the
        smaller mesh and trains to completion. The global-order loader
        replays the exact sample stream across the shard-count change, so
        the final loss matches an uninterrupted fixed-shape reference."""
        import numpy as np

        from tony_tpu.data import write_token_shard
        from tony_tpu.models import llama
        from tony_tpu.train.loop import LoopConfig, run_lm_training

        rng = np.random.default_rng(0)
        data = tmp_path / "data"
        data.mkdir()
        write_token_shard(data / "s0.tonytok", rng.integers(0, 256, 40_000, dtype=np.int32))
        ckpt = tmp_path / "ckpt"

        svc, agents = pool_with_agents
        cfg = TonyConfig({
            keys.STAGING_ROOT: str(tmp_tony_root),
            **pool_conf(svc, {
                "tony.worker.instances": "2",
                "tony.worker.min-instances": "1",
                "tony.worker.memory": "3g",  # 2×3g > the surviving 4g node
                # short hysteresis so the test shrinks promptly (the default
                # 10s guards real pools against heartbeat blips)
                keys.APPLICATION_DOWNSIZE_GRACE_MS: "500",
                keys.TASK_RESTART_ON_FAILURE: "true",
                keys.TASK_MAX_TOTAL_INSTANCE_FAILURES: "2",
                keys.EXECUTES: f"{fixture_cmd('elastic_train.py')} {data} {ckpt}",
            }),
        })
        client = Client(cfg)
        handle = client.submit()
        rpc = handle.rpc(timeout_s=30)
        assert rpc is not None
        # wait for attempt 0 (2 procs) to finish its 4 steps + checkpoint,
        # then kill nodeA permanently
        deadline = time.time() + 180
        while time.time() < deadline:
            if (ckpt / "4").exists():
                break
            time.sleep(0.25)
        else:
            raise AssertionError("attempt 0 never checkpointed step 4")
        os.kill(agents[0].pid, signal.SIGKILL)
        final = client.monitor_application(handle, quiet=True)
        assert final == JobStatus.SUCCEEDED, handle.final_status()

        # the gang RAN smaller: final status shows ONE worker (the portal's
        # job page renders this same task list)
        status = handle.final_status()
        workers = [t for t in status["tasks"] if t["name"] == "worker"]
        assert len(workers) == 1, status["tasks"]
        # the resize is in the history stream (portal event log)
        hist_dir = os.path.join(str(tmp_tony_root), "history")
        blob = ""
        for root, _, files in os.walk(hist_dir):
            for f in files:
                if handle.app_id in f or handle.app_id in root:
                    with open(os.path.join(root, f)) as fh:
                        blob += fh.read()
        assert "GANG_RESIZED" in blob
        # attempt 1 resumed from the checkpoint, single-process, to step 8
        log = os.path.join(handle.staging_dir, "logs", "worker_0_r1", "stdout.log")
        with open(log) as f:
            out = f.read()
        assert "resumed from checkpoint step" in out, out
        m = re.search(r"elastic attempt 1: step=8 loss=([0-9.]+) procs=1", out)
        assert m, out
        resumed_loss = float(m.group(1))

        # loss continuity: an uninterrupted fixed-shape run over the SAME
        # global stream ends at the same loss (reduction-order noise only)
        ref = run_lm_training(
            llama, llama.LLAMA_TINY,
            LoopConfig(steps=8, schedule_steps=8, batch_size=4, seq_len=64,
                       log_every=8, warmup_steps=0, data_dir=str(data),
                       checkpoint_dir=str(tmp_path / "ref_ckpt")),
        )
        np.testing.assert_allclose(resumed_loss, ref["loss"], rtol=1e-3)


class TestRemoteResourceManagerUnit:
    def test_allocation_error_surfaces_as_allocation_error(self, pool):
        pool.rpc.start()
        host, port = pool.address
        rm = RemoteResourceManager(host, port, secret=SECRET, app_id="app")
        with pytest.raises(AllocationError):
            rm.allocate("worker", 0, Resources(memory_bytes=1024))  # no nodes at all
        rm.shutdown()


class TestPoolCredential:
    """tony.keytab.* wiring: the keytab file is the pool credential source
    (Kerberos-keytab analog); keytab.user asserts the submitting identity."""

    def test_keytab_file_supplies_pool_secret(self, tmp_path):
        from tony_tpu.cluster.appmaster import _pool_credential

        kt = tmp_path / "pool.keytab"
        kt.write_text("s3cret-from-keytab\n")
        cfg = TonyConfig({keys.KEYTAB_LOCATION: str(kt)})
        assert _pool_credential(cfg) == "s3cret-from-keytab"

    def test_explicit_secret_wins_over_keytab(self, tmp_path):
        from tony_tpu.cluster.appmaster import _pool_credential

        kt = tmp_path / "pool.keytab"
        kt.write_text("from-file")
        cfg = TonyConfig({
            keys.KEYTAB_LOCATION: str(kt), keys.TPU_POOL_SECRET: "explicit",
        })
        assert _pool_credential(cfg) == "explicit"

    def test_missing_keytab_fails_fast(self):
        from tony_tpu.cluster.appmaster import _pool_credential

        cfg = TonyConfig({keys.KEYTAB_LOCATION: "/nonexistent/pool.keytab"})
        with pytest.raises(FileNotFoundError):
            _pool_credential(cfg)

    def test_wrong_keytab_user_rejected(self):
        from tony_tpu.cluster.appmaster import _pool_credential

        cfg = TonyConfig({keys.KEYTAB_USER: "definitely-not-this-user"})
        with pytest.raises(PermissionError, match="keytab.user"):
            _pool_credential(cfg)


class TestPortalLiveInPool:
    def test_portal_shows_live_job_mid_run(self, tmp_tony_root, pool_with_agents, monkeypatch):
        """The portal renders a RUNNING pool job mid-flight: running section
        from the intermediate .jhist, live task table over the AM RPC, and
        the pool page against the same pool service (r2 VERDICT #7
        done-when)."""
        import json as _json
        import threading
        import urllib.request

        from tony_tpu.portal.server import serve

        svc, _ = pool_with_agents
        cfg = TonyConfig({
            keys.STAGING_ROOT: str(tmp_tony_root),
            **pool_conf(svc, {
                "tony.worker.instances": "2",
                keys.EXECUTES: fixture_cmd("forever.py"),
            }),
        })
        client = Client(cfg)
        handle = client.submit()
        rpc = handle.rpc(timeout_s=30)
        assert rpc is not None
        deadline = time.time() + 30
        while time.time() < deadline:
            infos = rpc.call("get_task_infos")
            if len(infos) == 2 and all(i["status"] == "RUNNING" for i in infos):
                break
            time.sleep(0.1)

        history_root = os.path.join(str(tmp_tony_root), "history")
        host, port = svc.address
        monkeypatch.setenv(constants.ENV_POOL_SECRET, SECRET)
        server = serve(
            history_root, 0, staging_root=str(tmp_tony_root), pool=f"{host}:{port}"
        )
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            with urllib.request.urlopen(base + "/") as r:
                body = r.read().decode()
            assert handle.app_id in body and "running" in body
            with urllib.request.urlopen(f"{base}/job/{handle.app_id}") as r:
                detail = r.read().decode()
            assert "LIVE" in detail
            assert "AM state: RUNNING" in detail  # live table over the AM RPC
            assert "worker:0" in detail and "worker:1" in detail
            with urllib.request.urlopen(base + "/api/pool") as r:
                pool_state = _json.loads(r.read())
            assert pool_state["containers_running"] >= 2
        finally:
            server.shutdown()
            rpc.call("finish_application")
            client.monitor_application(handle, quiet=True)
