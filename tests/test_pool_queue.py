"""Pool multi-tenancy: queue admission, shares, priority, preemption.

The reference submits into YARN capacity queues (`tony.application.queue`,
SURVEY.md §2.1 config keys, §3.1 ApplicationSubmissionContext): jobs WAIT for
capacity instead of failing, FIFO within a queue, per-queue capacity shares,
priority ordering, optional preemption. This file tests the rebuild's analog
at both levels: the PoolService admission scheduler directly, and the full
client → AM → agent spine with two jobs racing one job's worth of capacity.
"""

import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from tony_tpu import constants
from tony_tpu.config import TonyConfig, keys
from tony_tpu.cluster.client import Client
from tony_tpu.cluster.pool import PoolService, RemoteResourceManager, parse_queue_spec
from tony_tpu.cluster.resources import AllocationError, AllocationPending, Resources
from tony_tpu.cluster.session import JobStatus

from tests.test_pool import (
    FAST,
    SECRET,
    pool_conf,
    register_cpu_node,
    spawn_agent,
)

GB = 1024**3


# ---------------------------------------------------------------------------
# Unit: queue-spec parsing
# ---------------------------------------------------------------------------
class TestParseQueueSpec:
    def test_basic(self):
        assert parse_queue_spec("prod=0.7,dev=0.3") == {"prod": 0.7, "dev": 0.3}

    def test_default(self):
        assert parse_queue_spec("") == {"default": 1.0}

    def test_bad_share(self):
        with pytest.raises(ValueError, match="share"):
            parse_queue_spec("prod=1.5")
        with pytest.raises(ValueError, match="share"):
            parse_queue_spec("prod=abc")

    def test_oversubscribed_shares_rejected(self):
        # guarantees cannot oversubscribe the pool (YARN rejects >100% too)
        with pytest.raises(ValueError, match="oversubscribe"):
            parse_queue_spec("prod=0.9,dev=0.9")
        with pytest.raises(ValueError, match="oversubscribe"):
            PoolService(secret=SECRET, queues={"a": 0.8, "b": 0.8})


# ---------------------------------------------------------------------------
# Unit: admission scheduler (direct PoolService calls, no RPC)
# ---------------------------------------------------------------------------
def make_pool(**kw):
    svc = PoolService(heartbeat_interval_ms=100, max_missed_heartbeats=3,
                      secret=SECRET, **kw)
    return svc


class TestQueueAdmission:
    def test_second_app_waits_then_admits(self):
        svc = make_pool()
        register_cpu_node(svc, "n0")  # 4 GB
        svc.register_app("app1", memory_bytes=3 * GB, vcores=1)
        got = svc.allocate("app1", "worker", 0, 3 * GB, 1, 0)
        assert got["node"] == "n0"
        # second tenant: feasible but the pool is busy → queued, NOT failed
        svc.register_app("app2", memory_bytes=3 * GB, vcores=1)
        wait = svc.allocate("app2", "worker", 0, 3 * GB, 1, 0)
        assert wait.get("wait") is True and wait["queue"] == "default"
        st = svc.pool_status()
        assert [w["app_id"] for w in st["queues"]["default"]["waiting"]] == ["app2"]
        # first app finishes → second admits and allocates
        svc.release_all("app1")
        got2 = svc.allocate("app2", "worker", 0, 3 * GB, 1, 0)
        assert got2["node"] == "n0"
        svc.stop()

    def test_fifo_within_queue(self):
        svc = make_pool()
        register_cpu_node(svc, "n0")
        svc.register_app("app1", memory_bytes=3 * GB, vcores=1)
        svc.allocate("app1", "worker", 0, 3 * GB, 1, 0)
        svc.register_app("app2", memory_bytes=3 * GB, vcores=1)
        svc.register_app("app3", memory_bytes=3 * GB, vcores=1)
        assert svc.allocate("app2", "worker", 0, 3 * GB, 1, 0)["position"] == 0
        assert svc.allocate("app3", "worker", 0, 3 * GB, 1, 0)["position"] == 1
        svc.release_all("app1")
        # FIFO: app2 (earlier) admits; app3 keeps waiting
        assert "node" in svc.allocate("app2", "worker", 0, 3 * GB, 1, 0)
        assert svc.allocate("app3", "worker", 0, 3 * GB, 1, 0).get("wait") is True
        svc.stop()

    def test_priority_beats_fifo(self):
        svc = make_pool()
        register_cpu_node(svc, "n0")
        svc.register_app("low", priority=0, memory_bytes=3 * GB, vcores=1)
        svc.allocate("low", "worker", 0, 3 * GB, 1, 0)
        svc.register_app("mid", priority=1, memory_bytes=3 * GB, vcores=1)
        svc.register_app("high", priority=9, memory_bytes=3 * GB, vcores=1)
        assert svc.allocate("mid", "worker", 0, 3 * GB, 1, 0).get("wait")
        assert svc.allocate("high", "worker", 0, 3 * GB, 1, 0)["position"] == 0
        svc.release_all("low")
        assert "node" in svc.allocate("high", "worker", 0, 3 * GB, 1, 0)
        assert svc.allocate("mid", "worker", 0, 3 * GB, 1, 0).get("wait") is True
        svc.stop()

    def test_queue_shares_cap_borrowing(self):
        """When capacity frees, a queue already OVER its share loses to
        another queue's waiter — even one that arrived later (the
        capacity-scheduler guarantee behind ``tony.pool.queues``)."""
        svc = make_pool(queues={"a": 0.5, "b": 0.5})
        register_cpu_node(svc, "n0")  # 4 GB total → 2 GB/queue share
        for app in ("a1", "a2"):  # queue a borrows the whole idle pool
            svc.register_app(app, queue="a", memory_bytes=2 * GB, vcores=1)
            svc.allocate(app, "worker", 0, 2 * GB, 1, 0)
        svc.register_app("a3", queue="a", memory_bytes=2 * GB, vcores=1)
        svc.register_app("b1", queue="b", memory_bytes=2 * GB, vcores=1)
        assert svc.allocate("a3", "worker", 0, 2 * GB, 1, 0).get("wait") is True
        assert svc.allocate("b1", "worker", 0, 2 * GB, 1, 0).get("wait") is True
        # capacity frees: a3 arrived first but queue a is at 2× share while
        # b waits at 0 — b1 is admitted, a3 keeps waiting
        svc.release_all("a1")
        assert "node" in svc.allocate("b1", "worker", 0, 2 * GB, 1, 0)
        assert svc.allocate("a3", "worker", 0, 2 * GB, 1, 0).get("wait") is True
        # once queue a drains under its share, a3 runs
        svc.release_all("a2")
        assert "node" in svc.allocate("a3", "worker", 0, 2 * GB, 1, 0)
        svc.stop()

    def test_elastic_borrow_when_pool_idle(self):
        """With no other queue waiting, a queue may exceed its share."""
        svc = make_pool(queues={"a": 0.25, "b": 0.75})
        register_cpu_node(svc, "n0")
        svc.register_app("a1", queue="a", memory_bytes=2 * GB, vcores=1)  # 2× share
        svc.allocate("a1", "worker", 0, 2 * GB, 1, 0)
        svc.register_app("a2", queue="a", memory_bytes=2 * GB, vcores=1)  # 4× share
        assert "node" in svc.allocate("a2", "worker", 0, 2 * GB, 1, 0)
        svc.stop()

    def test_unknown_queue_rejected(self):
        svc = make_pool(queues={"prod": 1.0})
        with pytest.raises(ValueError, match="unknown queue"):
            svc.register_app("x", queue="dev")
        svc.stop()

    def test_impossible_demand_is_allocation_error(self):
        svc = make_pool()
        register_cpu_node(svc, "n0")  # 4 GB
        svc.register_app("big", memory_bytes=64 * GB, vcores=1)
        with pytest.raises(AllocationError, match="never"):
            svc.allocate("big", "worker", 0, 2 * GB, 1, 0)
        svc.stop()

    def test_preemption_evicts_lower_priority(self):
        svc = make_pool(preemption=True)
        register_cpu_node(svc, "n0")
        svc.register_app("low", priority=0, memory_bytes=3 * GB, vcores=1)
        got = svc.allocate("low", "worker", 0, 3 * GB, 1, 0)
        # higher-priority arrival triggers eviction at registration time
        svc.register_app("high", priority=5, memory_bytes=3 * GB, vcores=1)
        node = svc._nodes["n0"]
        assert got["id"] in node.pending_kills  # kill order queued for agent
        st = svc.pool_status()
        q = st["queues"]["default"]
        assert [a["app_id"] for a in q["admitted"]] == ["high"]
        assert [w["app_id"] for w in q["waiting"]] == ["low"]
        assert q["waiting"][0]["preempted"] is True
        # the agent reports the kill → recorded as EXIT_PREEMPTED, capacity frees
        svc.node_heartbeat("n0", exited={got["id"]: 137})
        assert svc.poll_exited("low") == {got["id"]: constants.EXIT_PREEMPTED}
        assert "node" in svc.allocate("high", "worker", 0, 3 * GB, 1, 0)
        # low re-queues and returns once high releases
        assert svc.allocate("low", "worker", 0, 3 * GB, 1, 0).get("wait") is True
        svc.release_all("high")
        assert "node" in svc.allocate("low", "worker", 0, 3 * GB, 1, 0)
        svc.stop()

    def test_cross_queue_reclaim_restores_guarantee(self):
        """VERDICT r4 #2: a dev job that borrowed the whole idle pool is
        preempted back when a prod job arrives — the 70% guarantee is a
        guarantee at RECLAIM time, not only at admission time."""
        svc = make_pool(preemption=True, queues={"prod": 0.7, "dev": 0.3})
        register_cpu_node(svc, "n0")  # 4 GB → prod share 2.8 GB, dev 1.2 GB
        svc.register_app("dev1", queue="dev", memory_bytes=4 * GB, vcores=1)
        got = svc.allocate("dev1", "worker", 0, 4 * GB, 1, 0)  # idle borrow: whole pool
        assert "node" in got
        # prod arrives within its guarantee → dev1 is evicted for it
        svc.register_app("prod1", queue="prod", memory_bytes=2 * GB, vcores=1)
        assert got["id"] in svc._nodes["n0"].pending_kills
        st = svc.pool_status()
        assert [a["app_id"] for a in st["queues"]["prod"]["admitted"]] == ["prod1"]
        waiting = st["queues"]["dev"]["waiting"]
        assert [w["app_id"] for w in waiting] == ["dev1"]
        assert waiting[0]["preempted"] is True
        # the eviction is a preemption, not a failure (budget-exempt)
        svc.node_heartbeat("n0", exited={got["id"]: 137})
        assert svc.poll_exited("dev1") == {got["id"]: constants.EXIT_PREEMPTED}
        assert "node" in svc.allocate("prod1", "worker", 0, 2 * GB, 1, 0)
        # dev re-queues; once prod releases, dev borrows again
        assert svc.allocate("dev1", "worker", 0, 4 * GB, 1, 0).get("wait") is True
        svc.release_all("prod1")
        assert "node" in svc.allocate("dev1", "worker", 0, 4 * GB, 1, 0)
        svc.stop()

    def test_reclaim_never_digs_a_queue_below_its_share(self):
        """Eviction stops the moment the borrower queue is no longer over
        its share: an at-share queue is protected from reclaim."""
        svc = make_pool(preemption=True, queues={"a": 0.5, "b": 0.5})
        register_cpu_node(svc, "n0")  # 4 GB → 2 GB per queue share
        for app in ("b1", "b2"):  # b borrows the whole idle pool (2× share)
            svc.register_app(app, queue="b", memory_bytes=2 * GB, vcores=1)
            svc.allocate(app, "worker", 0, 2 * GB, 1, 0)
        b_cids = {rec["id"] for rec in svc._containers.values()}
        svc.register_app("a1", queue="a", memory_bytes=2 * GB, vcores=1)
        # exactly ONE b app (the newest, b2) is evicted — b lands AT share
        st = svc.pool_status()
        assert [a["app_id"] for a in st["queues"]["b"]["admitted"]] == ["b1"]
        assert [w["app_id"] for w in st["queues"]["b"]["waiting"]] == ["b2"]
        assert [a["app_id"] for a in st["queues"]["a"]["admitted"]] == ["a1"]
        assert len(svc._nodes["n0"].pending_kills) == 1
        # a second a-app cannot reclaim from b (b is AT its share now):
        # it waits for free capacity like anyone else
        svc.register_app("a2", queue="a", memory_bytes=2 * GB, vcores=1)
        st = svc.pool_status()
        assert [a["app_id"] for a in st["queues"]["b"]["admitted"]] == ["b1"]
        assert [w["app_id"] for w in st["queues"]["a"]["waiting"]] == ["a2"]
        assert len(svc._nodes["n0"].pending_kills) == 1  # no new kills
        assert b_cids  # silence unused warning; ids asserted via counts
        svc.stop()

    def test_reclaim_evicts_straddling_borrower_whole(self):
        """Whole-gang granularity: a borrower whose claim STRADDLES the
        share line (3 GB app, 2 GB share) evicts whole — the claimant's
        guarantee wins over the borrower's partial entitlement (the app
        only ever ran by borrowing; it re-queues with under-share
        priority)."""
        svc = make_pool(preemption=True, queues={"a": 0.5, "b": 0.5})
        register_cpu_node(svc, "n0")  # 4 GB → 2 GB per queue share
        svc.register_app("b1", queue="b", memory_bytes=3 * GB, vcores=1)
        svc.allocate("b1", "worker", 0, 3 * GB, 1, 0)  # 1 GB over share
        svc.register_app("a1", queue="a", memory_bytes=2 * GB, vcores=1)
        st = svc.pool_status()
        assert [a["app_id"] for a in st["queues"]["a"]["admitted"]] == ["a1"]
        assert [w["app_id"] for w in st["queues"]["b"]["waiting"]] == ["b1"]
        svc.stop()

    def test_reclaim_never_lifts_the_head_beyond_its_own_share(self):
        """Reclaim restores guarantees — it never funds borrowing: a head
        whose demand exceeds its own share cannot evict other queues."""
        svc = make_pool(preemption=True, queues={"a": 0.25, "b": 0.75})
        register_cpu_node(svc, "n0")  # 4 GB → a share 1 GB
        svc.register_app("b1", queue="b", memory_bytes=4 * GB, vcores=1)
        svc.allocate("b1", "worker", 0, 4 * GB, 1, 0)
        svc.register_app("a1", queue="a", memory_bytes=2 * GB, vcores=1)  # 2× share
        st = svc.pool_status()
        assert [a["app_id"] for a in st["queues"]["b"]["admitted"]] == ["b1"]
        assert [w["app_id"] for w in st["queues"]["a"]["waiting"]] == ["a1"]
        assert not svc._nodes["n0"].pending_kills
        svc.stop()

    def test_reclaim_grace_defers_cross_queue_eviction(self):
        """tony.pool.preemption.grace-ms: cross-queue kills fire only after
        the under-share head has waited out the grace window."""
        svc = make_pool(preemption=True, preemption_grace_ms=400,
                        queues={"prod": 0.7, "dev": 0.3})
        register_cpu_node(svc, "n0")
        svc.register_app("dev1", queue="dev", memory_bytes=4 * GB, vcores=1)
        svc.allocate("dev1", "worker", 0, 4 * GB, 1, 0)
        svc.register_app("prod1", queue="prod", memory_bytes=2 * GB, vcores=1)
        assert not svc._nodes["n0"].pending_kills  # inside the grace window
        assert svc.allocate("prod1", "worker", 0, 2 * GB, 1, 0).get("wait") is True
        time.sleep(0.5)
        # next scheduling pass (any allocate retry) fires the reclaim
        assert svc.allocate("prod1", "worker", 0, 2 * GB, 1, 0).get("wait") is True
        assert svc._nodes["n0"].pending_kills
        st = svc.pool_status()
        assert [a["app_id"] for a in st["queues"]["prod"]["admitted"]] == ["prod1"]
        svc.stop()

    def test_no_preemption_of_equal_priority(self):
        svc = make_pool(preemption=True)
        register_cpu_node(svc, "n0")
        svc.register_app("first", priority=3, memory_bytes=3 * GB, vcores=1)
        svc.allocate("first", "worker", 0, 3 * GB, 1, 0)
        svc.register_app("second", priority=3, memory_bytes=3 * GB, vcores=1)
        assert svc.allocate("second", "worker", 0, 3 * GB, 1, 0).get("wait") is True
        assert not svc._nodes["n0"].pending_kills  # strictly-lower only
        svc.stop()

    def test_admitted_chip_asks_keep_slice_packing(self):
        """Regression: the queue-wait restructuring must not reroute admitted
        chip allocations through the chipless memory-headroom ordering — a
        gang's second task must join its app's slice even when another
        slice's host has MORE free memory."""
        svc = make_pool()
        for s, mem in ((0, 8 * GB), (1, 64 * GB)):  # slice 1 = memory-rich
            for h in (0, 1):
                svc.register_node(
                    name=f"s{s}h{h}", host="h", port=1, memory_bytes=mem,
                    vcores=8, slice_id=s, slice_spec="v5e-8",
                    chips=[[r, 2 * h + c] for r in (0, 1) for c in (0, 1)],
                )
        svc.register_app("app", memory_bytes=2 * GB, vcores=2, chips=8)
        a = svc.allocate("app", "worker", 0, GB, 1, 4)
        b = svc.allocate("app", "worker", 1, GB, 1, 4)
        assert a["slice_id"] == b["slice_id"]  # ICI affinity, not memory headroom
        svc.stop()

    def test_unplaceable_rectangle_is_allocation_error(self):
        """An ask no host layout can form EVEN WHEN EMPTY must fail fast,
        not wait forever as 'fragmentation'."""
        svc = make_pool()
        svc.register_node(
            name="t0", host="h", port=1, memory_bytes=8 * GB, vcores=8,
            slice_id=0, slice_spec="v5e-8",
            chips=[[0, 0], [0, 1], [1, 2], [1, 3]],  # two disjoint dominoes
        )
        with pytest.raises(AllocationError, match="rectangle"):
            svc.allocate("app", "worker", 0, 1024, 1, 4)
        svc.stop()

    def test_remote_rm_raises_allocation_pending(self):
        svc = make_pool()
        svc.rpc.start()
        register_cpu_node(svc, "n0")
        host, port = svc.address
        rm1 = RemoteResourceManager(host, port, secret=SECRET, app_id="rm1")
        rm2 = RemoteResourceManager(host, port, secret=SECRET, app_id="rm2")
        rm1.register_app("default", 0, Resources(memory_bytes=3 * GB))
        rm2.register_app("default", 0, Resources(memory_bytes=3 * GB))
        rm1.allocate("worker", 0, Resources(memory_bytes=3 * GB))
        with pytest.raises(AllocationPending, match="queued"):
            rm2.allocate("worker", 0, Resources(memory_bytes=3 * GB))
        rm1.shutdown()  # release_all → rm2 admitted
        assert rm2.allocate("worker", 0, Resources(memory_bytes=3 * GB))
        rm2.shutdown()
        svc.stop()


# ---------------------------------------------------------------------------
# E2E: two jobs race one job's worth of capacity through the full spine
# ---------------------------------------------------------------------------
@pytest.fixture()
def small_pool(tmp_tony_root, tmp_path):
    """Pool service + ONE 4 GB agent: fits exactly one 3 GB job."""
    svc = PoolService(heartbeat_interval_ms=100, max_missed_heartbeats=4,
                      secret=SECRET, preemption=True)
    svc.start()
    agent = spawn_agent(svc.address, "solo", str(tmp_path))
    deadline = time.time() + 15
    while time.time() < deadline:
        if any(n.alive for n in svc._nodes.values()):
            break
        time.sleep(0.05)
    else:
        raise RuntimeError("agent failed to register")
    yield svc
    if agent.poll() is None:
        agent.terminate()
    try:
        agent.wait(timeout=5)
    except subprocess.TimeoutExpired:
        agent.kill()
    svc.stop()


def submit_async(tmp_tony_root, conf):
    cfg = TonyConfig({keys.STAGING_ROOT: str(tmp_tony_root), **conf})
    client = Client(cfg)
    handle = client.submit()
    result: dict = {}

    def monitor():
        result["final"] = client.monitor_application(handle, quiet=True)

    t = threading.Thread(target=monitor, daemon=True)
    t.start()
    return handle, t, result


def marker_script(tmp_path, name: str = "preemptee.py"):
    """Two-incarnation script: first run parks forever (gets preempted /
    killed), the restart (marker present) exits clean. Returns
    (script_path, marker_path)."""
    marker = tmp_path / f"{name}.ran_once"
    script = tmp_path / name
    script.write_text(
        "import os, sys, time\n"
        f"m = {str(marker)!r}\n"
        "if os.path.exists(m):\n"
        "    sys.exit(0)\n"
        "open(m, 'w').close()\n"
        "time.sleep(600)\n"
    )
    return script, marker


@pytest.mark.e2e
class TestQueueE2E:
    def test_second_job_waits_then_runs(self, tmp_tony_root, small_pool, tmp_path,
                                        monkeypatch):
        svc = small_pool
        sleeper = tmp_path / "sleeper.py"
        sleeper.write_text("import time; time.sleep(4)\n")
        h1, t1, r1 = submit_async(tmp_tony_root, pool_conf(svc, {
            "tony.worker.instances": "1", "tony.worker.memory": "3g",
            keys.EXECUTES: f"{sys.executable} {sleeper}",
        }))
        # job1 occupies the pool
        deadline = time.time() + 30
        while time.time() < deadline:
            if svc.pool_status()["containers_running"] >= 1:
                break
            time.sleep(0.05)
        else:
            pytest.fail("job1 never started")
        quick = tmp_path / "quick.py"
        quick.write_text("print('ran')\n")
        h2, t2, r2 = submit_async(tmp_tony_root, pool_conf(svc, {
            "tony.worker.instances": "1", "tony.worker.memory": "3g",
            keys.EXECUTES: f"{sys.executable} {quick}",
        }))
        # job2 must WAIT in the queue (not fail) while job1 runs
        deadline = time.time() + 20
        waiting = []
        while time.time() < deadline:
            waiting = svc.pool_status()["queues"]["default"]["waiting"]
            if waiting:
                break
            time.sleep(0.05)
        assert waiting and waiting[0]["app_id"] == h2.app_id
        assert r2.get("final") is None  # still pending, not failed

        # the portal /pool page renders the queue (VERDICT r3 done-when)
        from tony_tpu.portal.server import serve

        monkeypatch.setenv(constants.ENV_POOL_SECRET, SECRET)
        server = serve(
            os.path.join(str(tmp_tony_root), "history"), 0,
            staging_root=str(tmp_tony_root), pool="%s:%d" % svc.address,
        )
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            page = urllib.request.urlopen(
                f"http://127.0.0.1:{server.server_address[1]}/pool", timeout=10
            ).read().decode()
            assert h2.app_id in page and "waiting" in page
        finally:
            server.shutdown()

        # both jobs complete, in order
        t1.join(timeout=60)
        t2.join(timeout=60)
        assert r1.get("final") == JobStatus.SUCCEEDED, h1.final_status()
        assert r2.get("final") == JobStatus.SUCCEEDED, h2.final_status()

    @pytest.mark.slow
    def test_cross_queue_reclaim_evicts_borrower_end_to_end(
        self, tmp_tony_root, tmp_path
    ):
        """VERDICT r4 #2 done-when: prod=0.7,dev=0.3 — a dev job borrows the
        whole idle pool, a prod job arrives, dev is preempted back (and
        gang-restarts later), prod runs. Both jobs SUCCEED."""
        svc = PoolService(heartbeat_interval_ms=100, max_missed_heartbeats=4,
                          secret=SECRET, preemption=True,
                          queues={"prod": 0.7, "dev": 0.3})
        svc.start()
        agent = spawn_agent(svc.address, "solo", str(tmp_path))
        deadline = time.time() + 15
        while time.time() < deadline:
            if any(n.alive for n in svc._nodes.values()):
                break
            time.sleep(0.05)
        else:
            raise RuntimeError("agent failed to register")
        try:
            script, marker = marker_script(tmp_path, "dev_borrower.py")
            h1, t1, r1 = submit_async(tmp_tony_root, pool_conf(svc, {
                "tony.worker.instances": "1", "tony.worker.memory": "3g",
                keys.APPLICATION_QUEUE: "dev",
                keys.EXECUTES: f"{sys.executable} {script}",
            }))
            deadline = time.time() + 30
            while time.time() < deadline:
                if marker.exists():
                    break
                time.sleep(0.05)
            else:
                pytest.fail("dev job never started")
            quick = tmp_path / "prod_quick.py"
            quick.write_text("print('prod ran')\n")
            h2, t2, r2 = submit_async(tmp_tony_root, pool_conf(svc, {
                "tony.worker.instances": "1", "tony.worker.memory": "2g",
                keys.APPLICATION_QUEUE: "prod",
                keys.EXECUTES: f"{sys.executable} {quick}",
            }))
            # prod's guarantee reclaims the borrower: prod runs and finishes,
            # dev gang-restarts (marker present → exits clean) — both succeed
            t2.join(timeout=90)
            assert r2.get("final") == JobStatus.SUCCEEDED, h2.final_status()
            t1.join(timeout=90)
            assert r1.get("final") == JobStatus.SUCCEEDED, h1.final_status()
        finally:
            if agent.poll() is None:
                agent.terminate()
            try:
                agent.wait(timeout=5)
            except subprocess.TimeoutExpired:
                agent.kill()
            svc.stop()

    def test_preemption_evicts_and_restarts_lower_priority(
        self, tmp_tony_root, small_pool, tmp_path
    ):
        svc = small_pool
        # first incarnation parks forever; after preemption the gang restarts
        # and the second incarnation (marker present) exits clean
        script, marker = marker_script(tmp_path)
        h1, t1, r1 = submit_async(tmp_tony_root, pool_conf(svc, {
            "tony.worker.instances": "1", "tony.worker.memory": "3g",
            keys.APPLICATION_PRIORITY: "0",
            keys.EXECUTES: f"{sys.executable} {script}",
        }))
        deadline = time.time() + 30
        while time.time() < deadline:
            if marker.exists():
                break
            time.sleep(0.05)
        else:
            pytest.fail("low-priority job never started")
        quick = tmp_path / "quick.py"
        quick.write_text("print('prio')\n")
        h2, t2, r2 = submit_async(tmp_tony_root, pool_conf(svc, {
            "tony.worker.instances": "1", "tony.worker.memory": "3g",
            keys.APPLICATION_PRIORITY: "5",
            keys.EXECUTES: f"{sys.executable} {quick}",
        }))
        # high-priority job preempts, runs, finishes; low-priority job
        # restarts from the top and now exits clean — BOTH succeed
        t2.join(timeout=90)
        assert r2.get("final") == JobStatus.SUCCEEDED, h2.final_status()
        t1.join(timeout=90)
        assert r1.get("final") == JobStatus.SUCCEEDED, h1.final_status()
