"""Tier-1 gate: ``tony lint tony_tpu/`` must stay clean.

The suite's value is the CI ratchet — a PR that introduces an undeclared
config key, a side effect in traced code, donated-buffer reuse, an unlocked
cross-thread write, or a typo'd mesh axis fails here, with the same output
``tony lint`` prints locally. Deliberate exceptions carry an inline
``# lint: disable=<checker> — <why>`` comment, never a silent baseline entry
(the checked-in baseline stays empty; see docs/static-analysis.md).
"""

import json
import os

from tony_tpu.cli.lint import default_baseline_path, main as lint_main, repo_root


def test_tony_tpu_lints_clean(capsys):
    rc = lint_main([os.path.join(repo_root(), "tony_tpu"), "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 0, f"tony lint found regressions in tony_tpu/:\n{out}"


def test_repo_hot_loops_stay_sync_clean(capsys):
    """The host-sync ratchet over the step paths OUTSIDE the package too:
    bench.py's measurement loops (the repo's own MFU number) must never
    regrow an unconditional per-step host sync — the bug class that cost
    measurable step time through r5 (docs/performance.md)."""
    rc = lint_main([
        os.path.join(repo_root(), "bench.py"),
        os.path.join(repo_root(), "tony_tpu", "train"),
        "--checks", "host-sync", "--no-baseline",
    ])
    out = capsys.readouterr().out
    assert rc == 0, f"host-sync regressions on the hot loops:\n{out}"


def test_checked_in_baseline_is_empty():
    path = default_baseline_path()
    assert os.path.exists(path), "the .lint-baseline.json ratchet file is gone"
    with open(path) as f:
        data = json.load(f)
    assert data["findings"] == [], (
        "baseline grew — grandfathering real findings is reserved for "
        "generated/vendored code; fix the finding or suppress it inline "
        "with a justification"
    )


def test_cluster_lock_graph_is_cycle_free():
    """The deadlock ratchet: the cross-module lock-acquisition order graph
    over the whole package must stay acyclic. A new edge is fine (the graph
    documents order); a cycle is a potential deadlock and fails here with
    both acquisition paths in the lint output."""
    from tony_tpu.analysis.lock_order import build_lock_graph

    g = build_lock_graph([os.path.join(repo_root(), "tony_tpu")])
    assert g.cycles == [], f"lock-order cycle introduced:\n{g.render()}"
    # the two known benign orderings stay modeled — losing them means the
    # callgraph stopped resolving the journal/chip-grid acquires and the
    # witness test would be comparing against an empty model
    assert ("pool.PoolService._lock", "journal.Journal._lock") in g.edges
