"""Op-level tests. Flash-attention kernel parity runs on the real TPU only
(marked tpu); the CPU suite covers the reference path and the VJP wiring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.ops import attention as A
from tony_tpu.ops import layers as L


class TestLayers:
    def test_rms_norm_f32_accumulation(self):
        x = jnp.full((2, 8), 3.0, jnp.bfloat16)
        w = jnp.ones((8,), jnp.bfloat16)
        out = L.rms_norm(x, w)
        np.testing.assert_allclose(np.asarray(out, np.float32), 1.0, atol=1e-2)

    def test_rope_rotation_preserves_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 16, 32))
        cos, sin = L.rope_frequencies(32, 16)
        y = L.apply_rope(x, cos, sin)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(y), axis=-1),
            rtol=1e-5,
        )

    def test_rope_position_zero_is_identity(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 4, 8))
        cos, sin = L.rope_frequencies(8, 4)
        y = L.apply_rope(x, cos, sin, positions=jnp.zeros((4,), jnp.int32))
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)

    def test_cross_entropy_ignores_masked(self):
        logits = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 8))
        targets = jnp.array([[1, 2, -100, -100], [3, -100, -100, -100]])
        loss, n = L.cross_entropy_loss(logits, targets)
        assert int(n) == 3
        assert np.isfinite(float(loss))

    def test_cross_entropy_perfect_prediction(self):
        targets = jnp.array([[0, 1]])
        logits = jax.nn.one_hot(targets, 4) * 100.0
        loss, _ = L.cross_entropy_loss(logits, targets)
        assert float(loss) < 1e-3

    def test_chunked_cross_entropy_matches_plain(self):
        key = jax.random.PRNGKey(3)
        B, T, D, V = 2, 16, 8, 32
        x = jax.random.normal(key, (B, T, D), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(4), (D, V), jnp.float32) * 0.1
        targets = jax.random.randint(jax.random.PRNGKey(5), (B, T), 0, V)
        targets = targets.at[0, :3].set(-100)  # masked prefix

        plain_loss, plain_n = L.cross_entropy_loss(jnp.einsum("btd,dv->btv", x, w), targets)
        for chunk in (4, 16, 5):  # 5: non-divisible → padded with ignored targets
            loss, n = L.chunked_cross_entropy_loss(x, w, targets, chunk=chunk)
            np.testing.assert_allclose(float(loss), float(plain_loss), rtol=1e-5)
            assert int(n) == int(plain_n)

    def test_chunked_cross_entropy_grads_match(self):
        key = jax.random.PRNGKey(6)
        B, T, D, V = 2, 8, 4, 16
        x = jax.random.normal(key, (B, T, D), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(7), (D, V), jnp.float32) * 0.1
        targets = jax.random.randint(jax.random.PRNGKey(8), (B, T), 0, V)

        def plain(x, w):
            return L.cross_entropy_loss(jnp.einsum("btd,dv->btv", x, w), targets)[0]

        def chunked(x, w):
            return L.chunked_cross_entropy_loss(x, w, targets, chunk=4)[0]

        gx1, gw1 = jax.grad(plain, argnums=(0, 1))(x, w)
        gx2, gw2 = jax.grad(chunked, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2), rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2), rtol=2e-4, atol=1e-6)


class TestAttentionReference:
    def test_causal_masking(self):
        # changing a future token must not affect an earlier position's output
        q, k, v = (jax.random.normal(jax.random.fold_in(jax.random.PRNGKey(0), i), (1, 2, 8, 4))
                   for i in range(3))
        out1 = A.attention_reference(q, k, v, causal=True)
        k2 = k.at[:, :, -1].set(99.0)
        v2 = v.at[:, :, -1].set(99.0)
        out2 = A.attention_reference(q, k2, v2, causal=True)
        np.testing.assert_allclose(np.asarray(out1[:, :, :-1]), np.asarray(out2[:, :, :-1]), atol=1e-5)
        assert not np.allclose(np.asarray(out1[:, :, -1]), np.asarray(out2[:, :, -1]))

    def test_repeat_kv(self):
        k = jax.random.normal(jax.random.PRNGKey(0), (2, 2, 4, 8))
        r = A.repeat_kv(k, 3)
        assert r.shape == (2, 6, 4, 8)
        np.testing.assert_array_equal(np.asarray(r[:, 0]), np.asarray(r[:, 1]))

    def test_mha_dispatch_cpu_uses_reference(self):
        q, k, v = (jnp.ones((1, 1, 8, 4)),) * 3
        out = A.mha(q, k, v, causal=True, impl="auto")
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(A.attention_reference(q, k, v, causal=True)), atol=1e-6
        )

    def test_flash_vjp_wiring_grads_flow(self):
        # on CPU mha falls back to reference, but the custom-vjp path must
        # still be differentiable when called explicitly via interpret mode
        q, k, v = (jax.random.normal(jax.random.fold_in(jax.random.PRNGKey(1), i), (1, 2, 16, 4))
                   for i in range(3))

        def loss(q, k, v):
            return A.attention_reference(q, k, v, causal=True).sum()

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        assert all(np.isfinite(np.asarray(g)).all() for g in grads)


class TestFlashAttentionInterpret:
    """Kernel numerics on CPU via the Pallas interpreter (conftest sets
    TONY_PALLAS_INTERPRET=1): forward + the FlashAttention-2 backward."""

    def _qkv(self, B=1, H=2, T=512, D=64):
        ks = [jax.random.fold_in(jax.random.PRNGKey(7), i) for i in range(3)]
        return tuple(jax.random.normal(k, (B, H, T, D), jnp.float32) * 0.5 for k in ks)

    def test_forward_matches_reference(self):
        q, k, v = self._qkv()
        out = A._flash_fwd_impl(q, k, v, True, 256, 256)[0]
        want = A.attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)

    def test_lse_matches_reference(self):
        q, k, v = self._qkv(T=256)
        _, lse = A._flash_fwd_impl(q, k, v, True, 256, 256)
        D = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * D ** -0.5
        mask = jnp.tril(jnp.ones(s.shape[-2:], bool))
        want = jax.nn.logsumexp(jnp.where(mask, s, A.NEG_INF), axis=-1)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(want), atol=2e-4, rtol=2e-4)

    def test_backward_matches_reference(self):
        q, k, v = self._qkv()
        w = jnp.arange(q.shape[-1], dtype=jnp.float32)

        def loss_flash(q, k, v):
            return (A._flash_trainable(q, k, v, True) * w).sum()

        def loss_ref(q, k, v):
            return (A.attention_reference(q, k, v, causal=True) * w).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), gf, gr):
            scale = float(jnp.max(jnp.abs(b))) + 1e-9
            err = float(jnp.max(jnp.abs(a - b))) / scale
            assert err < 2e-4, f"{name} rel err {err}"

    def test_block_sizes_shrink_to_divide(self):
        # invariants hold under any TONY_FLASH_BQ/BK retuning
        for t in (768, 2048, 512, 640):
            bq, bk = A._block_sizes(t, t)
            assert t % bq == 0 and t % bk == 0
            assert bq <= min(A._BLOCK_Q, t) and bk <= min(A._BLOCK_K, t)
        if (A._BLOCK_Q, A._BLOCK_K) == (256, 512):  # stock defaults
            # a 768-long sequence divides 256 but not 512 — bk must halve
            assert A._block_sizes(768, 768) == (256, 256)
            assert A._block_sizes(512, 512) == (256, 512)
        # awkward lengths bottom out small — flash_attention must then take
        # the reference path, not launch a degenerate laneless grid
        bq, bk = A._block_sizes(257, 257)
        assert bq < 8  # degenerate → flash_attention takes the reference path

    def test_awkward_length_falls_back_to_reference(self):
        # T=257: _block_sizes degenerates; flash_attention must return the
        # reference result (and not crash or mis-tile)
        ks = [jax.random.fold_in(jax.random.PRNGKey(17), i) for i in range(3)]
        q, k, v = (jax.random.normal(kk, (1, 2, 257, 64), jnp.float32) * 0.5 for kk in ks)
        out = A.flash_attention(q, k, v, causal=True)
        want = A.attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)

    def test_bq_ne_bk_matches_reference(self):
        # asymmetric blocks (the production default) through fwd AND bwd
        q, k, v = self._qkv(T=512)
        w = jnp.arange(q.shape[-1], dtype=jnp.float32)

        def loss_flash(q, k, v):
            return (A._flash_trainable(q, k, v, True) * w).sum()

        def loss_ref(q, k, v):
            return (A.attention_reference(q, k, v, causal=True) * w).sum()

        if (A._BLOCK_Q, A._BLOCK_K) == (256, 512):  # stock defaults
            assert A._block_sizes(512, 512) == (256, 512)  # exercising bq != bk
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), gf, gr):
            scale = float(jnp.max(jnp.abs(b))) + 1e-9
            err = float(jnp.max(jnp.abs(a - b))) / scale
            assert err < 2e-4, f"{name} rel err {err}"

    def test_gqa_forward_matches_reference(self):
        B, H, Hkv, T, D = 1, 4, 2, 512, 64
        ks = [jax.random.fold_in(jax.random.PRNGKey(11), i) for i in range(3)]
        q = jax.random.normal(ks[0], (B, H, T, D), jnp.float32) * 0.5
        k = jax.random.normal(ks[1], (B, Hkv, T, D), jnp.float32) * 0.5
        v = jax.random.normal(ks[2], (B, Hkv, T, D), jnp.float32) * 0.5
        out = A._flash_fwd_impl(q, k, v, True, 256, 256)[0]
        want = A.attention_reference(q, A.repeat_kv(k, 2), A.repeat_kv(v, 2), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)

    def test_gqa_backward_matches_reference(self):
        B, H, Hkv, T, D = 1, 4, 2, 512, 64
        ks = [jax.random.fold_in(jax.random.PRNGKey(13), i) for i in range(3)]
        q = jax.random.normal(ks[0], (B, H, T, D), jnp.float32) * 0.5
        k = jax.random.normal(ks[1], (B, Hkv, T, D), jnp.float32) * 0.5
        v = jax.random.normal(ks[2], (B, Hkv, T, D), jnp.float32) * 0.5
        w = jnp.arange(D, dtype=jnp.float32)

        def loss_flash(q, k, v):
            return (A._flash_trainable(q, k, v, True) * w).sum()

        def loss_ref(q, k, v):
            # reference path: broadcast kv, let autodiff reduce back over group
            return (
                A.attention_reference(q, A.repeat_kv(k, 2), A.repeat_kv(v, 2), causal=True) * w
            ).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), gf, gr):
            assert a.shape == b.shape, f"{name}: {a.shape} vs {b.shape}"
            scale = float(jnp.max(jnp.abs(b))) + 1e-9
            err = float(jnp.max(jnp.abs(a - b))) / scale
            assert err < 2e-4, f"{name} rel err {err}"

    def test_gqa_backward_streaming_variant(self, monkeypatch):
        # force the pair-enumeration (long-sequence) dkv kernel and check parity
        monkeypatch.setattr(A, "_DKV_RESIDENT_MAX_QROWS", 0)
        self.test_gqa_backward_matches_reference()
        self.test_backward_matches_reference()

    def test_streaming_dkv_causal_tk_gt_tq(self, monkeypatch):
        # Tk > Tq + causal: k blocks wholly past the causal horizon must come
        # back as exact ZERO dk/dv (the sparse pair walk still has to visit
        # them once to zero-init the output block)
        monkeypatch.setattr(A, "_DKV_RESIDENT_MAX_QROWS", 0)
        B, H, Tq, Tk, D = 1, 2, 256, 1024, 64
        ks = [jax.random.fold_in(jax.random.PRNGKey(17), i) for i in range(3)]
        q = jax.random.normal(ks[0], (B, H, Tq, D), jnp.float32) * 0.5
        k = jax.random.normal(ks[1], (B, H, Tk, D), jnp.float32) * 0.5
        v = jax.random.normal(ks[2], (B, H, Tk, D), jnp.float32) * 0.5

        def loss_flash(q, k, v):
            return A._flash_trainable(q, k, v, True).sum()

        def loss_ref(q, k, v):
            # flash-kernel causal semantics: ABSOLUTE positions (query i sees
            # keys <= i), unlike attention_reference's bottom-aligned tril
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (D ** -0.5)
            mask = jnp.arange(Tq)[:, None] >= jnp.arange(Tk)[None, :]
            p = jax.nn.softmax(jnp.where(mask, s, A.NEG_INF), axis=-1)
            return jnp.einsum("bhqk,bhkd->bhqd", p, v).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), gf, gr):
            scale = float(jnp.max(jnp.abs(b))) + 1e-9
            err = float(jnp.max(jnp.abs(a - b))) / scale
            assert err < 2e-4, f"{name} rel err {err}"
        # keys at positions >= Tq are unreachable: gradients exactly zero
        np.testing.assert_array_equal(np.asarray(gf[1][:, :, Tq:, :]), 0.0)
        np.testing.assert_array_equal(np.asarray(gf[2][:, :, Tq:, :]), 0.0)

    def test_backward_noncausal(self):
        q, k, v = self._qkv(T=256)

        def loss_flash(q, k, v):
            return A._flash_trainable(q, k, v, False).sum()

        def loss_ref(q, k, v):
            return A.attention_reference(q, k, v, causal=False).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            scale = float(jnp.max(jnp.abs(b))) + 1e-9
            assert float(jnp.max(jnp.abs(a - b))) / scale < 2e-4


@pytest.mark.tpu
class TestFlashAttentionTPU:
    """Runs only on the real TPU backend (pytest -m tpu outside the CPU mesh)."""

    def test_matches_reference(self):
        if jax.default_backend() == "cpu":
            pytest.skip("needs TPU")
        q, k, v = (jax.random.normal(jax.random.fold_in(jax.random.PRNGKey(0), i),
                                     (2, 4, 512, 64), jnp.bfloat16) for i in range(3))
        out = A.flash_attention(q, k, v, causal=True)
        want = A.attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(want, np.float32), atol=2e-2, rtol=2e-2
        )


class TestSegmentIds:
    """Packed-sequence (segment-id) masking: reference semantics + the flash
    kernels (fwd, dq, resident dkv, streaming dkv) in interpret mode."""

    def _packed(self, B=1, H=2, T=512, D=64, n_seg=3, seed=23):
        ks = [jax.random.fold_in(jax.random.PRNGKey(seed), i) for i in range(4)]
        q, k, v = (jax.random.normal(kk, (B, H, T, D), jnp.float32) * 0.5 for kk in ks[:3])
        bounds = jnp.sort(jax.random.randint(ks[3], (n_seg - 1,), 1, T))
        seg = jnp.searchsorted(bounds, jnp.arange(T), side="right")
        seg = jnp.broadcast_to(seg[None, :], (B, T)).astype(jnp.int32)
        return q, k, v, seg

    def test_flash_fwd_matches_reference(self):
        q, k, v, seg = self._packed()
        out = A._flash_fwd_impl(q, k, v, True, 256, 256, seg)[0]
        want = A.attention_reference(q, k, v, causal=True, segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)

    def test_flash_fwd_equals_per_segment_slices(self):
        # ground truth from first principles: run each segment separately
        q, k, v, seg = self._packed(B=1)
        out = A._flash_fwd_impl(q, k, v, True, 256, 256, seg)[0]
        seg_np = np.asarray(seg[0])
        for s in np.unique(seg_np):
            idx = np.where(seg_np == s)[0]
            lo, hi = idx.min(), idx.max() + 1
            piece = A.attention_reference(
                q[:, :, lo:hi], k[:, :, lo:hi], v[:, :, lo:hi], causal=True
            )
            np.testing.assert_allclose(
                np.asarray(out[:, :, lo:hi]), np.asarray(piece), atol=2e-5, rtol=2e-5
            )

    def test_flash_bwd_matches_reference(self):
        q, k, v, seg = self._packed(H=4)
        kv = k[:, ::2], v[:, ::2]  # GQA: 2 kv heads for 4 q heads
        w = jnp.arange(q.shape[-1], dtype=jnp.float32)

        def loss_flash(q, k, v):
            return (A._flash_trainable_seg(q, k, v, seg, True) * w).sum()

        def loss_ref(q, k, v):
            return (
                A.attention_reference(
                    q, A.repeat_kv(k, 2), A.repeat_kv(v, 2), causal=True, segment_ids=seg
                ) * w
            ).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, *kv)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, *kv)
        for name, a, b in zip("dq dk dv".split(), gf, gr):
            scale = float(jnp.max(jnp.abs(b))) + 1e-9
            err = float(jnp.max(jnp.abs(a - b))) / scale
            assert err < 2e-4, f"{name} rel err {err}"

    def test_flash_bwd_streaming_variant(self, monkeypatch):
        monkeypatch.setattr(A, "_DKV_RESIDENT_MAX_QROWS", 0)
        self.test_flash_bwd_matches_reference()


class TestSlidingWindow:
    """Mistral/Mixtral-style sliding-window attention: reference semantics +
    all four flash kernels (fwd, dq, resident dkv, streaming dkv)."""

    def _qkv(self, B=1, H=4, Hkv=2, T=768, D=64, seed=31):
        ks = [jax.random.fold_in(jax.random.PRNGKey(seed), i) for i in range(3)]
        q = jax.random.normal(ks[0], (B, H, T, D), jnp.float32) * 0.5
        k = jax.random.normal(ks[1], (B, Hkv, T, D), jnp.float32) * 0.5
        v = jax.random.normal(ks[2], (B, Hkv, T, D), jnp.float32) * 0.5
        return q, k, v

    def test_reference_window_band(self):
        # row i attends exactly (i-window, i]
        q, k, v = self._qkv(H=1, Hkv=1, T=16, D=8)
        out = A.attention_reference(q, k, v, causal=True, window=4)
        # compare against a hand-built mask softmax
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (8 ** -0.5)
        i = jnp.arange(16)[:, None]
        j = jnp.arange(16)[None, :]
        mask = (i >= j) & (i - j < 4)
        p = jax.nn.softmax(jnp.where(mask, s, A.NEG_INF), axis=-1)
        want = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-6)

    def test_flash_fwd_matches_reference(self):
        q, k, v = self._qkv()
        for window in (300, 256, 512):
            out = A._flash_fwd_impl(q, k, v, True, 256, 256, None, window)[0]
            want = A.attention_reference(
                q, A.repeat_kv(k, 2), A.repeat_kv(v, 2), causal=True, window=window
            )
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5,
                err_msg=f"window={window}",
            )

    def test_flash_bwd_matches_reference(self):
        q, k, v = self._qkv()
        w = jnp.arange(q.shape[-1], dtype=jnp.float32)
        for window in (300, 512):
            def loss_flash(q, k, v):
                return (A._flash_trainable(q, k, v, True, window) * w).sum()

            def loss_ref(q, k, v):
                return (
                    A.attention_reference(
                        q, A.repeat_kv(k, 2), A.repeat_kv(v, 2), causal=True, window=window
                    ) * w
                ).sum()

            gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
            gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
            for name, a, b in zip("dq dk dv".split(), gf, gr):
                scale = float(jnp.max(jnp.abs(b))) + 1e-9
                err = float(jnp.max(jnp.abs(a - b))) / scale
                assert err < 2e-4, f"window={window} {name} rel err {err}"

    def test_flash_bwd_streaming_variant(self, monkeypatch):
        monkeypatch.setattr(A, "_DKV_RESIDENT_MAX_QROWS", 0)
        self.test_flash_bwd_matches_reference()

    def test_window_with_segments(self):
        q, k, v = self._qkv(T=512)
        seg = jnp.where(jnp.arange(512) < 300, 1, 2)[None, :].astype(jnp.int32)
        out = A._flash_fwd_impl(q, k, v, True, 256, 256, seg, 128)[0]
        want = A.attention_reference(
            q, A.repeat_kv(k, 2), A.repeat_kv(v, 2),
            causal=True, segment_ids=seg, window=128,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)

    def test_model_level_swa(self):
        import dataclasses as dc

        from tony_tpu.models import llama

        base = dc.replace(llama.LLAMA_TINY, max_seq=256, remat=False)
        params = llama.init(jax.random.PRNGKey(0), base)
        batch = llama.synthetic_batch(jax.random.PRNGKey(1), 2, 256, base)
        for impl in ("reference", "flash"):
            l_full, _ = llama.loss_fn(params, batch, dc.replace(base, attn_impl=impl))
            l_swa, _ = llama.loss_fn(
                params, batch, dc.replace(base, attn_impl=impl, sliding_window=64)
            )
            assert float(l_full) != float(l_swa), impl  # the window must bite
        l_ref, _ = llama.loss_fn(
            params, batch, dc.replace(base, attn_impl="reference", sliding_window=64)
        )
        l_fl, _ = llama.loss_fn(
            params, batch, dc.replace(base, attn_impl="flash", sliding_window=64)
        )
        np.testing.assert_allclose(float(l_ref), float(l_fl), rtol=2e-3)
