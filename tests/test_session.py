"""Session state machine unit tests (TestTonySession analog, SURVEY.md §4)."""

import pytest

from tony_tpu.config import TonyConfig
from tony_tpu.cluster.session import JobStatus, Session, TaskStatus


def make_session(**types):
    cfg = TonyConfig({f"tony.{t}.instances": str(n) for t, n in types.items()})
    return Session(cfg)


class TestGangBarrier:
    def test_spec_incomplete_until_all_register(self):
        s = make_session(ps=1, worker=2)
        assert s.cluster_spec() is None
        s.register_worker_spec("ps", 0, "h1", 1000)
        s.register_worker_spec("worker", 0, "h2", 2000)
        assert not s.cluster_spec_complete()
        s.register_worker_spec("worker", 1, "h3", 3000)
        spec = s.cluster_spec()
        assert spec == {"ps": ["h1:1000"], "worker": ["h2:2000", "h3:3000"]}

    def test_spec_ordered_by_index(self):
        s = make_session(worker=2)
        s.register_worker_spec("worker", 1, "b", 2)
        s.register_worker_spec("worker", 0, "a", 1)
        assert s.cluster_spec() == {"worker": ["a:1", "b:2"]}

    def test_unknown_task_rejected(self):
        s = make_session(worker=1)
        with pytest.raises(KeyError):
            s.register_worker_spec("worker", 5, "h", 1)


class TestVerdict:
    def test_all_tracked_succeed(self):
        s = make_session(worker=2)
        s.on_task_completed("worker", 0, 0)
        s.on_task_completed("worker", 1, 0)
        assert s.tracked_all_terminal()
        assert s.reduce_final_status() == JobStatus.SUCCEEDED

    def test_any_tracked_failure_fails_job(self):
        s = make_session(worker=2)
        s.on_task_completed("worker", 0, 0)
        s.on_task_completed("worker", 1, 3)
        assert s.any_tracked_failed() is not None
        assert s.reduce_final_status() == JobStatus.FAILED

    def test_untracked_failure_ignored(self):
        # ps is untracked by default: its exit never gates the verdict
        s = make_session(ps=1, worker=1)
        s.on_task_completed("ps", 0, 1)
        s.on_task_completed("worker", 0, 0)
        assert s.any_tracked_failed() is None
        assert s.reduce_final_status() == JobStatus.SUCCEEDED

    def test_completion_is_idempotent(self):
        s = make_session(worker=1)
        s.on_task_completed("worker", 0, 0)
        s.on_task_completed("worker", 0, 7)  # late duplicate must not flip status
        t = s.get_task("worker", 0)
        assert t.status == TaskStatus.SUCCEEDED
        assert t.exit_code == 0

    def test_lost_task_fails_job(self):
        s = make_session(worker=1)
        s.register_worker_spec("worker", 0, "h", 1)
        s.mark_lost(s.get_task("worker", 0))
        assert s.reduce_final_status() == JobStatus.FAILED


class TestHeartbeats:
    def test_heartbeat_promotes_to_running(self):
        s = make_session(worker=1)
        s.register_worker_spec("worker", 0, "h", 1)
        assert s.get_task("worker", 0).status == TaskStatus.REGISTERED
        s.on_heartbeat("worker", 0)
        assert s.get_task("worker", 0).status == TaskStatus.RUNNING

    def test_dead_task_detection(self):
        s = make_session(worker=1)
        s.register_worker_spec("worker", 0, "h", 1)
        t = s.get_task("worker", 0)
        t.last_heartbeat_ms -= 10_000  # simulate silence
        dead = s.find_dead_tasks(heartbeat_interval_ms=100, max_missed=5)
        assert dead == [t]

    def test_terminal_tasks_not_dead(self):
        s = make_session(worker=1)
        s.register_worker_spec("worker", 0, "h", 1)
        s.on_task_completed("worker", 0, 0)
        s.get_task("worker", 0).last_heartbeat_ms -= 10_000
        assert s.find_dead_tasks(100, 5) == []
