"""Event stream + history file tests (TestHistoryFileUtils analog, SURVEY.md §4)."""

import json
import os

import pytest

from tony_tpu.cluster.events import Event, EventHandler, EventType
from tony_tpu.cluster import history


class TestHistoryFilenameCodec:
    def test_roundtrip(self):
        h = history.HistoryFileName("application_123_abc", 100, 200, "alice", "SUCCEEDED")
        assert history.HistoryFileName.parse(h.render()) == h

    def test_app_id_with_dashes(self):
        h = history.HistoryFileName("app-with-dashes", 1, 2, "bob", "FAILED")
        assert history.HistoryFileName.parse(h.render()).app_id == "app-with-dashes"


class TestEventHandler:
    def test_events_drained_to_jsonl(self, tmp_path):
        eh = EventHandler(str(tmp_path), "app1")
        eh.start()
        eh.emit(EventType.APPLICATION_INITED, app_id="app1")
        eh.emit(EventType.TASK_STARTED, task="worker:0")
        eh.stop()
        lines = open(eh.intermediate_path).read().splitlines()
        assert len(lines) == 2
        evs = [Event.from_json(line) for line in lines]
        assert evs[0].type == EventType.APPLICATION_INITED
        assert evs[1].payload == {"task": "worker:0"}

    def test_finalize_moves_and_snapshots_config(self, tmp_path):
        eh = EventHandler(str(tmp_path), "app2")
        eh.start()
        eh.emit(EventType.APPLICATION_FINISHED, status="SUCCEEDED")
        eh.stop()
        dest = history.finalize_history(
            str(tmp_path), "app2", eh.intermediate_path, 100, 200, "SUCCEEDED",
            config_snapshot={"tony.worker.instances": "1"}, user="tester",
        )
        assert os.path.exists(dest)
        assert not os.path.exists(eh.intermediate_path)
        cfg = json.load(open(os.path.join(os.path.dirname(dest), "config.json")))
        assert cfg["tony.worker.instances"] == "1"

        jobs = history.list_finished_jobs(str(tmp_path))
        assert [j.app_id for j in jobs] == ["app2"]
        evs = history.read_events(str(tmp_path), "app2")
        assert evs[-1].type == EventType.APPLICATION_FINISHED

    def test_read_events_intermediate(self, tmp_path):
        eh = EventHandler(str(tmp_path), "app3")
        eh.start()
        eh.emit(EventType.TASK_STARTED, task="w:0")
        eh.stop()
        assert history.read_events(str(tmp_path), "app3")[0].type == EventType.TASK_STARTED

    def test_missing_app_gives_empty(self, tmp_path):
        assert history.read_events(str(tmp_path), "ghost") == []
