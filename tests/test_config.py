"""Config-system unit tests.

Mirrors the reference's TestTonyConfigurationFields (registry ↔ defaults
completeness) and TestUtils (memory/time parsing) — SURVEY.md §4.
"""

import json

import pytest

from tony_tpu import constants
from tony_tpu.config import TonyConfig, keys, parse_memory_string, parse_time_ms


class TestKeyRegistry:
    def test_every_known_key_has_a_default(self):
        # the TestTonyConfigurationFields analog: registry and defaults artifact
        # must never drift apart.
        missing = keys.all_known_keys() - set(keys.DEFAULTS)
        assert not missing, f"keys missing defaults: {sorted(missing)}"

    def test_every_default_is_a_known_key(self):
        unknown = set(keys.DEFAULTS) - keys.all_known_keys()
        assert not unknown, f"defaults for undeclared keys: {sorted(unknown)}"

    def test_defaults_are_strings(self):
        assert all(isinstance(v, str) for v in keys.DEFAULTS.values())


class TestParsing:
    @pytest.mark.parametrize(
        "s,expected",
        [("2g", 2 * 1024**3), ("512m", 512 * 1024**2), ("1024", 1024), ("3K", 3 * 1024), ("1gb", 1024**3)],
    )
    def test_memory(self, s, expected):
        assert parse_memory_string(s) == expected

    def test_memory_bad(self):
        with pytest.raises(ValueError):
            parse_memory_string("two gigs")

    @pytest.mark.parametrize(
        "s,expected", [("500", 500), ("500ms", 500), ("5s", 5000), ("2m", 120000), ("1h", 3600000)]
    )
    def test_time(self, s, expected):
        assert parse_time_ms(s) == expected


class TestLayering:
    def test_defaults_present(self):
        cfg = TonyConfig()
        assert cfg.get(keys.APPLICATION_FRAMEWORK) == "jax"
        assert cfg.get_int(keys.TASK_MAX_MISSED_HEARTBEATS) == 25

    def test_scheduler_indexed_key_defaults_true(self):
        """The r14 kill switch (docs/performance.md "Scheduler pass"):
        indexed scheduling is the default — parity-proven identical
        semantics — and false restores the reference policy verbatim."""
        cfg = TonyConfig()
        assert cfg.get_bool(keys.POOL_SCHEDULER_INDEXED) is True
        flipped = TonyConfig({keys.POOL_SCHEDULER_INDEXED: "false"})
        assert flipped.get_bool(keys.POOL_SCHEDULER_INDEXED) is False

    def test_train_and_tune_keys_registered_with_defaults(self):
        """The r11 step-path knobs (docs/performance.md): registered,
        defaulted, and typed the way the executor reads them."""
        cfg = TonyConfig()
        assert cfg.get_int(keys.TRAIN_PREFETCH_DEPTH) == 2
        assert cfg.get_time_ms(keys.TRAIN_INPUT_WAIT_SPAN_MS) == 25
        assert cfg.get(keys.TUNE_CACHE_FILE) == ""     # → env/per-user default
        assert cfg.get_bool(keys.TUNE_ENABLED) is True
        for k in (keys.TRAIN_PREFETCH_DEPTH, keys.TRAIN_INPUT_WAIT_SPAN_MS,
                  keys.TUNE_CACHE_FILE, keys.TUNE_ENABLED):
            assert k in keys.DEFAULTS

    def test_layer_order_later_wins(self, tmp_path):
        site = tmp_path / "site.json"
        site.write_text(json.dumps({keys.APPLICATION_QUEUE: "prod", keys.AM_RETRY_COUNT: "2"}))
        job = tmp_path / "job.json"
        job.write_text(json.dumps({keys.AM_RETRY_COUNT: "3"}))
        cfg = TonyConfig.from_layers(str(site), str(job), [f"{keys.AM_RETRY_COUNT}=5"])
        assert cfg.get(keys.APPLICATION_QUEUE) == "prod"   # from site
        assert cfg.get_int(keys.AM_RETRY_COUNT) == 5       # --conf wins

    def test_nested_json_flattens(self, tmp_path):
        f = tmp_path / "job.json"
        f.write_text(json.dumps({"tony": {"worker": {"instances": 4, "memory": "2g"}}}))
        cfg = TonyConfig().load_file(str(f))
        assert cfg.instances("worker") == 4
        assert cfg.get_memory_bytes(keys.jobtype_key("worker", keys.MEMORY_SUFFIX)) == 2 * 1024**3

    def test_hadoop_xml_parity(self, tmp_path):
        # the reference's job files are Hadoop-style XML (e.g. tony-examples/
        # mnist-tensorflow/tony.xml); we accept the same shape.
        f = tmp_path / "tony.xml"
        f.write_text(
            """<?xml version="1.0"?>
            <configuration>
              <property><name>tony.worker.instances</name><value>2</value></property>
              <property><name>tony.application.name</name><value>mnist</value></property>
            </configuration>"""
        )
        cfg = TonyConfig().load_file(str(f))
        assert cfg.instances("worker") == 2
        assert cfg.get(keys.APPLICATION_NAME) == "mnist"

    def test_toml(self, tmp_path):
        f = tmp_path / "job.toml"
        f.write_text('[tony.worker]\ninstances = 2\n[tony.application]\nname = "t"\n')
        cfg = TonyConfig().load_file(str(f))
        assert cfg.instances("worker") == 2


class TestJobTypes:
    def _cfg(self):
        return TonyConfig(
            {
                "tony.ps.instances": "2",
                "tony.worker.instances": "4",
                "tony.tensorboard.instances": "1",
                "tony.evaluator.instances": "0",
            }
        )

    def test_job_types_discovered(self):
        assert self._cfg().job_types() == ("ps", "tensorboard", "worker")

    def test_zero_instance_types_excluded(self):
        assert "evaluator" not in self._cfg().job_types()

    def test_tracked_untracked_split(self):
        cfg = self._cfg()
        assert cfg.untracked_types() >= {"ps", "tensorboard"}
        assert cfg.tracked_types() == ("worker",)

    def test_dependency_keys(self):
        cfg = self._cfg().set(keys.dependency_key("worker", "ps"), "5s")
        assert cfg.dependencies() == {"worker": {"ps": 5000}}


class TestFreeze:
    def test_freeze_blocks_mutation(self):
        cfg = TonyConfig().freeze()
        with pytest.raises(RuntimeError):
            cfg.set("tony.application.name", "x")

    def test_roundtrip_artifact(self, tmp_path):
        cfg = TonyConfig({"tony.worker.instances": "4"})
        cfg.freeze()
        path = cfg.write_final(tmp_path)
        assert path.endswith(constants.TONY_FINAL_CONF)
        loaded = TonyConfig.load_final(path)
        assert loaded.frozen
        assert loaded.instances("worker") == 4
        # frozen artifact is the WHOLE truth: defaults were baked in at freeze
        assert loaded.get(keys.TASK_HEARTBEAT_INTERVAL_MS) == "1000"
