"""TPU-slice resource model tests: topology parsing, ICI-contiguous rectangle
allocation (the GPU-scheduling analog of TestTaskScheduler, SURVEY.md §4)."""

import os
import time

import pytest

from tony_tpu.cluster.resources import (
    AllocationError,
    ChipGrid,
    LocalResourceManager,
    Resources,
    SliceSpec,
    squarish_topology,
)


class TestSliceSpec:
    @pytest.mark.parametrize(
        "spec,accel,topo",
        [
            ("v5e-64", "v5e", (8, 8)),
            ("v5e-8", "v5e", (2, 4)),
            ("v5e-256", "v5e", (16, 16)),
            ("v5e,4x8", "v5e", (4, 8)),
            ("cpu", "cpu", (0, 0)),
        ],
    )
    def test_parse(self, spec, accel, topo):
        s = SliceSpec.parse(spec)
        assert (s.accelerator, s.topology) == (accel, topo)

    def test_chips(self):
        assert SliceSpec.parse("v5e-64").chips == 64
        assert SliceSpec.parse("cpu").chips == 0

    def test_squarish(self):
        assert squarish_topology(12) == (3, 4)
        assert squarish_topology(7) == (1, 7)


class TestChipGrid:
    def test_rect_allocation_contiguous(self):
        g = ChipGrid((4, 4))
        coords = g.allocate_rect((2, 2))
        rows = {r for r, _ in coords}
        cols = {c for _, c in coords}
        assert len(coords) == 4
        # contiguity: the rectangle spans consecutive rows/cols (ICI affinity)
        assert rows == set(range(min(rows), max(rows) + 1))
        assert cols == set(range(min(cols), max(cols) + 1))

    def test_exhaustion(self):
        g = ChipGrid((2, 2))
        assert g.allocate_rect((2, 2)) is not None
        assert g.allocate_rect((1, 1)) is None

    def test_release_reuses(self):
        g = ChipGrid((2, 2))
        coords = g.allocate_rect((2, 2))
        g.release(coords)
        assert g.allocate_rect((2, 2)) is not None

    def test_orientation_fallback(self):
        g = ChipGrid((2, 4))
        assert g.allocate_rect((4, 2)) is not None  # rotated to fit

    def test_allocate_chips_prefers_square(self):
        g = ChipGrid((8, 8))
        coords = g.allocate_chips(16)
        rows = {r for r, _ in coords}
        cols = {c for _, c in coords}
        assert (len(rows), len(cols)) == (4, 4)

    def test_fragmentation_respected(self):
        g = ChipGrid((2, 4))
        g.allocate_rect((2, 2))
        assert g.allocate_chips(4) is not None   # 2x2 fits in the remainder
        assert g.allocate_chips(2) is None       # full now


class TestLocalResourceManager:
    def test_allocate_sets_device_env(self):
        rm = LocalResourceManager("local:v5e-8")
        c = rm.allocate("worker", 0, Resources(chips=4))
        env = c.device_env()
        assert env["TPU_CHIPS_PER_TASK"] == "4"
        assert env["TPU_SLICE_NAME"] == "v5e-8"
        assert len(env["TPU_CHIP_COORDS"].split(";")) == 4

    def test_chip_exhaustion_raises(self):
        rm = LocalResourceManager("local:v5e-4")
        rm.allocate("worker", 0, Resources(chips=4))
        with pytest.raises(AllocationError):
            rm.allocate("worker", 1, Resources(chips=1))

    def test_release_returns_chips(self):
        rm = LocalResourceManager("local:v5e-4")
        c = rm.allocate("worker", 0, Resources(chips=4))
        rm.release(c)
        rm.allocate("worker", 1, Resources(chips=4))

    def test_memory_accounting(self):
        rm = LocalResourceManager("local:cpu", host_memory="4g")
        rm.allocate("worker", 0, Resources(memory_bytes=3 * 1024**3))
        with pytest.raises(AllocationError):
            rm.allocate("worker", 1, Resources(memory_bytes=2 * 1024**3))

    def test_cpu_pool_rejects_chip_asks(self):
        rm = LocalResourceManager("local:cpu")
        with pytest.raises(AllocationError):
            rm.allocate("worker", 0, Resources(chips=4))


class TestMultiSlicePool:
    def _rm(self, spec="pool:v5e-8x2"):
        from tony_tpu.cluster.resources import MultiSliceResourceManager

        return MultiSliceResourceManager(spec)

    def test_spec_parse_and_env(self):
        rm = self._rm("pool:v5e-8x4")
        assert rm.num_slices == 4
        assert rm.slices[0].spec.chips == 8
        c = rm.allocate("worker", 0, Resources(chips=4))
        assert c.slice_name == "v5e-8"
        assert rm.slice_of(c) in range(4)

    def test_bad_specs_rejected(self):
        import pytest as _pytest

        for bad in ("pool:v5e-8", "pool:x", "pool:v5e-0x2"):
            with _pytest.raises(ValueError):
                self._rm(bad)

    def test_best_fit_packs_one_slice_first(self):
        rm = self._rm("pool:v5e-8x2")
        a = rm.allocate("worker", 0, Resources(chips=4))
        b = rm.allocate("worker", 1, Resources(chips=4))
        # both fit slice 0 exactly — best-fit must co-locate them
        assert rm.slice_of(a) == rm.slice_of(b)

    def test_spill_to_second_slice(self):
        rm = self._rm("pool:v5e-8x2")
        cs = [rm.allocate("worker", i, Resources(chips=4)) for i in range(4)]
        slices = {rm.slice_of(c) for c in cs}
        assert slices == {0, 1}  # 4x4 chips over two 8-chip slices

    def test_task_larger_than_slice_rejected(self):
        rm = self._rm("pool:v5e-8x2")
        with pytest.raises(AllocationError, match="span DCN"):
            rm.allocate("worker", 0, Resources(chips=16))

    def test_pool_exhaustion(self):
        rm = self._rm("pool:v5e-4x2")
        rm.allocate("w", 0, Resources(chips=4))
        rm.allocate("w", 1, Resources(chips=4))
        with pytest.raises(AllocationError, match="no slice"):
            rm.allocate("w", 2, Resources(chips=1))

    def test_release_refills_slice(self):
        rm = self._rm("pool:v5e-4x2")
        a = rm.allocate("w", 0, Resources(chips=4))
        rm.allocate("w", 1, Resources(chips=4))
        rm.release(a)
        c = rm.allocate("w", 2, Resources(chips=4))
        assert rm.slice_of(c) == 0 or rm.slice_of(c) == 1

    def test_slice_env_injected_at_start(self, tmp_path):
        import sys as _sys

        rm = self._rm("pool:v5e-4x2")
        c = rm.allocate("w", 0, Resources(chips=4))
        rm.allocate("w", 1, Resources(chips=4))  # spills → gang spans 2 slices
        out = tmp_path / "env.txt"
        rm.start_container(
            c,
            [_sys.executable, "-c",
             "import os;open(r'%s','w').write(os.environ['TPU_SLICE_ID']+' '+os.environ['TPU_NUM_SLICES'])" % out],
            {"PATH": os.environ.get("PATH", "")},
            str(tmp_path / "logs"),
        )
        for _ in range(100):
            if rm.poll_exited():
                break
            time.sleep(0.05)
        assert out.read_text() == "0 2"
        rm.shutdown()

    def test_hosts_per_slice(self):
        rm = self._rm("pool:v5e-8x2")
        assert len(rm.slices[0].hosts) == 2  # 8 chips / 4 per host
        c = rm.allocate("w", 0, Resources(chips=8))
        assert c.host.startswith("slice")

    def test_gang_span_not_pool_size(self, tmp_path):
        # a gang packed into ONE slice of a 4-slice pool is all-ICI: its env
        # must say num_slices=1 (pool size would force a bogus hybrid mesh)
        import sys as _sys

        rm = self._rm("pool:v5e-8x4")
        a = rm.allocate("w", 0, Resources(chips=4))
        b = rm.allocate("w", 1, Resources(chips=4))
        assert rm.gang_slice_span() == [rm.slice_of(a)]
        out = tmp_path / "env.txt"
        rm.start_container(
            b,
            [_sys.executable, "-c",
             "import os;open(r'%s','w').write(os.environ['TPU_SLICE_ID']+' '+os.environ['TPU_NUM_SLICES'])" % out],
            {"PATH": os.environ.get("PATH", "")},
            str(tmp_path / "logs"),
        )
        for _ in range(100):
            if rm.poll_exited():
                break
            time.sleep(0.05)
        assert out.read_text() == "0 1"
        rm.shutdown()

    def test_gang_span_appends_across_launch_waves(self):
        # dependency-gated type B allocated AFTER type A started may land on
        # a new slice: the span must grow (appending, so A's indices stay
        # valid) rather than crash on a frozen snapshot
        rm = self._rm("pool:v5e-4x2")
        a = [rm.allocate("a", i, Resources(chips=4)) for i in range(1)]
        assert rm.gang_slice_span() == [rm.slice_of(a[0])]
        # wave 2: slice of wave 1 is full → lands on the other slice
        b = rm.allocate("b", 0, Resources(chips=4))
        span = rm.gang_slice_span()
        assert span[0] == rm.slice_of(a[0]) and set(span) == {0, 1}
        # release everything → span resets for a restarted gang
        for c in a + [b]:
            rm.release(c)
        c2 = rm.allocate("a", 0, Resources(chips=4))
        assert rm.gang_slice_span() == [rm.slice_of(c2)]
        rm.shutdown()
