"""TPU-slice resource model tests: topology parsing, ICI-contiguous rectangle
allocation (the GPU-scheduling analog of TestTaskScheduler, SURVEY.md §4)."""

import pytest

from tony_tpu.cluster.resources import (
    AllocationError,
    ChipGrid,
    LocalResourceManager,
    Resources,
    SliceSpec,
    squarish_topology,
)


class TestSliceSpec:
    @pytest.mark.parametrize(
        "spec,accel,topo",
        [
            ("v5e-64", "v5e", (8, 8)),
            ("v5e-8", "v5e", (2, 4)),
            ("v5e-256", "v5e", (16, 16)),
            ("v5e,4x8", "v5e", (4, 8)),
            ("cpu", "cpu", (0, 0)),
        ],
    )
    def test_parse(self, spec, accel, topo):
        s = SliceSpec.parse(spec)
        assert (s.accelerator, s.topology) == (accel, topo)

    def test_chips(self):
        assert SliceSpec.parse("v5e-64").chips == 64
        assert SliceSpec.parse("cpu").chips == 0

    def test_squarish(self):
        assert squarish_topology(12) == (3, 4)
        assert squarish_topology(7) == (1, 7)


class TestChipGrid:
    def test_rect_allocation_contiguous(self):
        g = ChipGrid((4, 4))
        coords = g.allocate_rect((2, 2))
        rows = {r for r, _ in coords}
        cols = {c for _, c in coords}
        assert len(coords) == 4
        # contiguity: the rectangle spans consecutive rows/cols (ICI affinity)
        assert rows == set(range(min(rows), max(rows) + 1))
        assert cols == set(range(min(cols), max(cols) + 1))

    def test_exhaustion(self):
        g = ChipGrid((2, 2))
        assert g.allocate_rect((2, 2)) is not None
        assert g.allocate_rect((1, 1)) is None

    def test_release_reuses(self):
        g = ChipGrid((2, 2))
        coords = g.allocate_rect((2, 2))
        g.release(coords)
        assert g.allocate_rect((2, 2)) is not None

    def test_orientation_fallback(self):
        g = ChipGrid((2, 4))
        assert g.allocate_rect((4, 2)) is not None  # rotated to fit

    def test_allocate_chips_prefers_square(self):
        g = ChipGrid((8, 8))
        coords = g.allocate_chips(16)
        rows = {r for r, _ in coords}
        cols = {c for _, c in coords}
        assert (len(rows), len(cols)) == (4, 4)

    def test_fragmentation_respected(self):
        g = ChipGrid((2, 4))
        g.allocate_rect((2, 2))
        assert g.allocate_chips(4) is not None   # 2x2 fits in the remainder
        assert g.allocate_chips(2) is None       # full now


class TestLocalResourceManager:
    def test_allocate_sets_device_env(self):
        rm = LocalResourceManager("local:v5e-8")
        c = rm.allocate("worker", 0, Resources(chips=4))
        env = c.device_env()
        assert env["TPU_CHIPS_PER_TASK"] == "4"
        assert env["TPU_SLICE_NAME"] == "v5e-8"
        assert len(env["TPU_CHIP_COORDS"].split(";")) == 4

    def test_chip_exhaustion_raises(self):
        rm = LocalResourceManager("local:v5e-4")
        rm.allocate("worker", 0, Resources(chips=4))
        with pytest.raises(AllocationError):
            rm.allocate("worker", 1, Resources(chips=1))

    def test_release_returns_chips(self):
        rm = LocalResourceManager("local:v5e-4")
        c = rm.allocate("worker", 0, Resources(chips=4))
        rm.release(c)
        rm.allocate("worker", 1, Resources(chips=4))

    def test_memory_accounting(self):
        rm = LocalResourceManager("local:cpu", host_memory="4g")
        rm.allocate("worker", 0, Resources(memory_bytes=3 * 1024**3))
        with pytest.raises(AllocationError):
            rm.allocate("worker", 1, Resources(memory_bytes=2 * 1024**3))

    def test_cpu_pool_rejects_chip_asks(self):
        rm = LocalResourceManager("local:cpu")
        with pytest.raises(AllocationError):
            rm.allocate("worker", 0, Resources(chips=4))
