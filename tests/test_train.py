"""Trainer/checkpoint tests: Orbax roundtrip, resume path, MFU accounting."""

import functools

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models import mlp
from tony_tpu.train import OptimizerConfig, Throughput, TrainState, make_train_step
from tony_tpu.train.checkpoint import CheckpointManager, restore_or_init
from tony_tpu.train.metrics import detect_peak_flops, transformer_flops_per_token

KEY = jax.random.PRNGKey(0)
CFG = mlp.MLPConfig(input_dim=8, hidden_dim=16, num_classes=4)


def make_state():
    opt = OptimizerConfig(warmup_steps=0, total_steps=10).build()
    return TrainState.create(mlp.init(KEY, CFG), opt), opt


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        state, _ = make_state()
        mgr = CheckpointManager(str(tmp_path / "ckpt"), use_async=False)
        assert mgr.save(3, state)
        assert mgr.latest_step() == 3

        fresh, _ = make_state()
        restored = mgr.restore(fresh)
        np.testing.assert_array_equal(
            np.asarray(restored.params["layer_0"]["w"]), np.asarray(state.params["layer_0"]["w"])
        )
        assert int(restored.step) == int(state.step)
        mgr.close()

    def test_restore_after_training_steps(self, tmp_path):
        state, opt = make_state()
        step = make_train_step(functools.partial(mlp.loss_fn, cfg=CFG), opt)
        batch = mlp.synthetic_batch(KEY, 8, CFG)
        for _ in range(3):
            state, _m = step(state, batch)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), use_async=False)
        mgr.save(int(state.step), state)

        # gang-restart resume: fresh init, restore, continue
        def init_fn():
            s, _ = make_state()
            return s

        restored, mgr2, start = restore_or_init(str(tmp_path / "ckpt"), init_fn, use_async=False)
        assert start == 3
        restored, m = step(restored, batch)
        assert int(m["step"]) == 4
        mgr.close()
        mgr2.close()

    def test_restore_or_init_without_dir(self):
        state, mgr, start = restore_or_init(None, lambda: 42)
        assert (state, mgr, start) == (42, None, 0)

    def test_max_to_keep(self, tmp_path):
        state, _ = make_state()
        mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2, use_async=False)
        for s in (1, 2, 3):
            mgr.save(s, state)
        mgr.wait()
        assert mgr.latest_step() == 3
        steps = sorted(mgr._mgr.all_steps())
        assert steps == [2, 3]
        mgr.close()


class TestMetrics:
    def test_flops_formula_training_vs_inference(self):
        t = transformer_flops_per_token(1_000_000, 12, 768, 2048, training=True)
        i = transformer_flops_per_token(1_000_000, 12, 768, 2048, training=False)
        assert t > i
        assert t >= 6_000_000

    def test_detect_peak_flops_cpu(self):
        assert detect_peak_flops() > 0

    def test_throughput_meter(self):
        m = Throughput(tokens_per_step=1000, flops_per_token=1000, n_chips=2, peak_flops=1e6)
        m.start()
        m.step()
        m.step()
        r = m.report()
        assert r["tokens_per_sec"] > 0
        assert 0 <= r["mfu"]
        assert r["tokens_per_sec_per_chip"] * 2 == r["tokens_per_sec"]


class TestLoopWithData:
    def test_run_lm_training_on_tonytok_shards(self, tmp_path):
        """End-to-end: shard files on disk → loader → train steps → loss finite."""
        import numpy as np

        from tony_tpu.data import write_token_shard
        from tony_tpu.models import llama
        from tony_tpu.train.loop import LoopConfig, run_lm_training

        rng = np.random.default_rng(0)
        data = tmp_path / "data"
        data.mkdir()
        for i in range(2):
            write_token_shard(
                data / f"s{i}.tonytok", rng.integers(0, 256, 20_000, dtype=np.int32)
            )
        cfg = llama.LLAMA_TINY
        out = run_lm_training(
            llama, cfg,
            LoopConfig(steps=3, batch_size=2, seq_len=64, log_every=1,
                       warmup_steps=0, data_dir=str(data)),
        )
        assert np.isfinite(out["loss"])
        assert out["step"] == 3


class TestDataReplayOnResume:
    @pytest.mark.slow
    def test_interrupted_run_equals_uninterrupted(self, tmp_path):
        """VERDICT r3 #6a end-to-end: a run checkpointed at step 4 and
        resumed to step 8 sees the SAME data stream as a run that never
        stopped — identical final loss (bitwise: same params path, same
        batches, same op order on CPU)."""
        import numpy as np

        from tony_tpu.data import write_token_shard
        from tony_tpu.models import llama
        from tony_tpu.train.loop import LoopConfig, run_lm_training

        rng = np.random.default_rng(0)
        data = tmp_path / "data"
        data.mkdir()
        write_token_shard(data / "s0.tonytok", rng.integers(0, 256, 40_000, dtype=np.int32))
        cfg = llama.LLAMA_TINY
        # schedule_steps pins the LR schedule to the full 8-step plan in
        # every run — the interrupted 4-step run must not decay twice as fast
        base = dict(batch_size=2, seq_len=64, log_every=100, warmup_steps=0,
                    data_dir=str(data), checkpoint_every=4, schedule_steps=8)
        ref = run_lm_training(
            llama, cfg,
            LoopConfig(steps=8, checkpoint_dir=str(tmp_path / "A"), **base),
        )
        # interrupted: 4 steps, "crash", resume the same config to 8
        run_lm_training(
            llama, cfg, LoopConfig(steps=4, checkpoint_dir=str(tmp_path / "B"), **base)
        )
        got = run_lm_training(
            llama, cfg, LoopConfig(steps=8, checkpoint_dir=str(tmp_path / "B"), **base)
        )
        assert got["step"] == 8
        assert got["loss"] == ref["loss"], (got, ref)


class TestCrossShapeResume:
    @pytest.mark.slow
    def test_restore_onto_smaller_mesh_keeps_training(self, tmp_path):
        """VERDICT r3 #6b: a checkpoint written by an 8-device FSDP run
        restores onto a 4-device mesh (Orbax reshards into the target
        shardings) and training continues with the same loss as the
        8-device continuation — the node-lost → re-pack-smaller story."""
        import functools

        from tony_tpu.models import llama
        from tony_tpu.parallel import MeshSpec
        from tony_tpu.train.trainer import make_train_step, sharded_init

        cfg = llama.LLAMA_TINY
        opt = OptimizerConfig(warmup_steps=0, total_steps=10).build()
        rules = llama.sharding_rules(cfg)
        init_fn = lambda: llama.init(KEY, cfg)  # noqa: E731
        batch = llama.synthetic_batch(KEY, 8, 32, cfg)

        mesh8 = MeshSpec(fsdp=8).build()
        state8 = sharded_init(init_fn, rules, mesh8, opt)
        step8 = make_train_step(
            functools.partial(llama.loss_fn, cfg=cfg, mesh=mesh8), opt
        )
        for _ in range(2):
            state8, _ = step8(state8, batch)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), use_async=False)
        mgr.save(2, state8)
        mgr.wait()
        state8, m8 = step8(state8, batch)  # the 8-device continuation

        mesh4 = MeshSpec(fsdp=4).build(devices=jax.devices()[:4])
        state4 = sharded_init(init_fn, rules, mesh4, opt)
        restored = mgr.restore(state4)
        # restored arrays carry the 4-device shardings, not the saved ones
        p = jax.tree.leaves(restored.params)[0]
        assert len(p.sharding.device_set) == 4
        step4 = make_train_step(
            functools.partial(llama.loss_fn, cfg=cfg, mesh=mesh4), opt
        )
        _, m4 = step4(restored, batch)
        np.testing.assert_allclose(
            float(m4["loss"]), float(m8["loss"]), rtol=1e-5
        )
        mgr.close()


class TestOptimizerMemory:
    def test_mu_dtype_bf16_halves_first_moment(self):
        import jax.numpy as jnp

        from tony_tpu.models import mlp
        from tony_tpu.train import OptimizerConfig, TrainState, make_train_step

        params = mlp.init(jax.random.PRNGKey(0), mlp.MLPConfig())
        opt = OptimizerConfig(warmup_steps=0, total_steps=5, mu_dtype="bfloat16").build()
        state = TrainState.create(params, opt)
        mus = [l for l in jax.tree.leaves(state.opt_state)
               if hasattr(l, "dtype") and l.dtype == jnp.bfloat16]
        assert mus, "no bf16 first-moment leaves found"
        step = make_train_step(
            lambda p, b: mlp.loss_fn(p, b, mlp.MLPConfig()), opt
        )
        batch = mlp.synthetic_batch(jax.random.PRNGKey(1), 4, mlp.MLPConfig())
        state, metrics = step(state, batch)
        assert bool(jnp.isfinite(metrics["loss"]))


class TestLoopPipelineParallel:
    @pytest.mark.slow
    def test_run_lm_training_with_stage_axis(self):
        """tony-submit-path pipeline training: stage_axis=2 routes the loop
        through the 1F1B schedule (make_pp_train_step) on the virtual mesh."""
        import dataclasses as dc

        import numpy as np

        from tony_tpu.models import llama
        from tony_tpu.train.loop import LoopConfig, run_lm_training

        cfg = dc.replace(llama.LLAMA_TINY, max_seq=64)
        out = run_lm_training(
            llama, cfg,
            LoopConfig(steps=3, batch_size=8, seq_len=64, log_every=1,
                       warmup_steps=0, stage_axis=2, pp_microbatches=2),
        )
        assert np.isfinite(out["loss"])
        assert out["step"] == 3

    def test_stage_axis_rejects_models_without_pp(self):
        import pytest as _pytest

        from tony_tpu.models import bert
        from tony_tpu.train.loop import LoopConfig, run_lm_training

        with _pytest.raises(ValueError, match="pp_value_and_grad"):
            run_lm_training(
                bert, bert.BERT_TINY,
                LoopConfig(steps=1, batch_size=8, seq_len=64, stage_axis=2),
            )


class TestTrainMetricsDrop:
    def test_drop_and_executor_read(self, tmp_path, monkeypatch):
        """The loop's step report reaches the executor's metrics payload:
        loop._drop_train_metrics writes atomically to the advertised path;
        Executor._read_train_metrics picks it up; launch_child clears it
        (stale reports must not outlive an attempt)."""
        from tony_tpu import constants
        from tony_tpu.train import loop as loop_mod

        path = tmp_path / "m" / "worker_0.json"
        path.parent.mkdir()
        monkeypatch.setenv(constants.ENV_TRAIN_METRICS_FILE, str(path))
        line = {"step": 7, "loss": 1.25, "tokens_per_sec": 123.0, "mfu": 0.41}
        loop_mod._drop_train_metrics(line)
        import json as _json

        assert _json.loads(path.read_text()) == line

        # executor-side read + clear-on-launch, without standing up a gang
        from tony_tpu.cluster.executor import TaskExecutor as Executor

        ex = Executor.__new__(Executor)
        ex._train_metrics_path = str(path)
        assert Executor._read_train_metrics(ex) == line
        path.write_text("{not json")
        assert Executor._read_train_metrics(ex) is None  # malformed → ignored

        path.write_text(_json.dumps(line))

        class _Cfg:
            def get(self, *a, **k):
                return ""

        ex.config = _Cfg()
        ex.staging_dir = str(tmp_path)
        try:
            Executor.launch_child(ex, "true", {})
        except Exception:
            pass  # Popen details don't matter; the unlink happens first
        assert not path.exists()

    def test_drop_is_noop_outside_container(self, monkeypatch):
        from tony_tpu import constants
        from tony_tpu.train import loop as loop_mod

        monkeypatch.delenv(constants.ENV_TRAIN_METRICS_FILE, raising=False)
        loop_mod._drop_train_metrics({"step": 1})  # must not raise
