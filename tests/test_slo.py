"""SLO engine: error budgets, burn-rate alerts, request tracing, verdict.

Covers (tony_tpu/obs/slo.py; docs/observability.md "SLOs & error budgets"):

- objective parsing from ``tony.slo.*`` (market-threshold inheritance, loud
  misconfiguration);
- the BudgetLedger's EXACT accounting — unit cases plus the 300-seed
  randomized property test mirroring goodput's partition contract:
  everything ever ingested == expired out the window + still banked, for
  any interleaving of ingests, counter resets, and window boundaries;
- good/bad extraction from registry snapshots (TTFT histogram with the
  SLO-aligned bucket edge → exact counts; availability by outcome label;
  worst-offender exemplars);
- multi-window multi-burn-rate rule compilation + evaluation through the
  real AlertEngine (fast-burn fires, short-window confirmation resolves,
  no data holds state);
- the zero-allocation contract: with tracing disabled the per-request span
  chain and request-id plumbing allocate no Span objects;
- the router's X-Tony-Request-Id assignment/echo;
- slo.jsonl → history-store ``slo_series`` ingestion (REPLACE idempotence,
  torn tails, retention) and the merged-row dedupe the CLI verdict relies
  on;
- ``verdict_from_rows`` pass/fail/no-data semantics + the ``tony slo``
  CLI (status fallback + verdict exit codes);
- ``tony bench --gate``'s ``slo_verdict`` contract and
  ``budget_burned_pct`` direction;
- the diurnal arrival profile and the autoscaler's SLO-burn pressure;
- headline e2e: a diurnal loadtest over a live router/fleet with an
  injected mid-spike error burst — the fast-burn rule fires
  ``SLO_BURN_ALERT`` during the spike and resolves after, rows persist
  through the store sweep, and ``tony slo verdict`` reads PASS from
  history (exit 0), never from in-process state.
"""

import json
import random
import threading
import time
import types

import pytest

from tony_tpu.config import TonyConfig, keys
from tony_tpu.histserver import gate as bench_gate
from tony_tpu.histserver import ingest as hist_ingest
from tony_tpu.histserver.store import HistoryStore
from tony_tpu.obs import alerts as obs_alerts
from tony_tpu.obs import metrics as obs_metrics
from tony_tpu.obs import slo as obs_slo
from tony_tpu.obs import trace as obs_trace
from tony_tpu.serve.autoscaler import AutoscalePolicy, Autoscaler
from tony_tpu.serve.loadgen import LoadGenerator, LoadSpec, arrival_offsets

pytestmark = pytest.mark.slo


def cfg(**overrides):
    base = {"tony.worker.instances": "1"}
    base.update({k: str(v) for k, v in overrides.items()})
    c = TonyConfig(base)
    c.freeze()
    return c


def slo_cfg(**overrides):
    overrides.setdefault(keys.SLO_SERVE_TTFT_TARGET, "0.99")
    return cfg(**overrides)


# ---------------------------------------------------------------------------
# objective parsing
# ---------------------------------------------------------------------------
class TestObjectivesFromConfig:
    def test_disabled_by_default(self):
        assert obs_slo.objectives_from_config(cfg()) == []
        engine = obs_slo.SloEngine(cfg())
        assert not engine.enabled and engine.burn_rules() == []

    def test_ttft_threshold_inherits_market_key(self):
        objs = obs_slo.objectives_from_config(slo_cfg())
        assert [o.name for o in objs] == ["serve-ttft"]
        assert objs[0].threshold_ms == 2000.0  # market default
        objs = obs_slo.objectives_from_config(slo_cfg(**{
            keys.SERVE_MARKET_SLO_TTFT_MS: "750"}))
        assert objs[0].threshold_ms == 750.0
        objs = obs_slo.objectives_from_config(slo_cfg(**{
            keys.SERVE_MARKET_SLO_TTFT_MS: "750",
            keys.SLO_SERVE_TTFT_THRESHOLD_MS: "1500"}))
        assert objs[0].threshold_ms == 1500.0  # explicit beats inherited

    def test_all_three_objectives(self):
        c = cfg(**{keys.SLO_SERVE_TTFT_TARGET: "0.95",
                   keys.SLO_SERVE_AVAILABILITY_TARGET: "0.999",
                   keys.SLO_TRAIN_GOODPUT_TARGET: "0.8"})
        objs = {o.name: o for o in obs_slo.objectives_from_config(c)}
        assert set(objs) == {"serve-ttft", "serve-availability", "train-goodput"}
        assert objs["train-goodput"].unit == "ms"
        assert objs["serve-availability"].unit == "requests"

    def test_bad_target_is_loud(self):
        with pytest.raises(ValueError, match="not a number"):
            obs_slo.objectives_from_config(
                cfg(**{keys.SLO_SERVE_TTFT_TARGET: "ninety-nine"}))
        with pytest.raises(ValueError, match=r"fraction in \(0, 1\)"):
            obs_slo.objectives_from_config(
                cfg(**{keys.SLO_SERVE_AVAILABILITY_TARGET: "1.0"}))
        with pytest.raises(ValueError, match="must be > 0 ms"):
            obs_slo.objectives_from_config(slo_cfg(**{
                keys.SLO_SERVE_TTFT_THRESHOLD_MS: "-5"}))


# ---------------------------------------------------------------------------
# budget ledger units
# ---------------------------------------------------------------------------
def ledger(target=0.9, window_ms=60_000, bucket_ms=1_000, name="serve-ttft"):
    return obs_slo.BudgetLedger(
        obs_slo.Objective(name, target, "requests"), window_ms, bucket_ms)


class TestBudgetLedger:
    def test_cumulative_deltas(self):
        led = ledger()
        assert led.ingest("a", 10, 1, 1000) == (10, 1)
        assert led.ingest("a", 15, 1, 1500) == (5, 0)
        assert led.ingest("a", 15, 1, 2000) == (0, 0)  # no traffic: no-op
        assert (led.total_good, led.total_bad) == (15, 1)
        assert led.window_counts(2000) == (15, 1)

    def test_counter_reset_banks_fresh_totals(self):
        led = ledger()
        led.ingest("a", 100, 10, 1000)
        # the replica restarted: its counters start over — the fresh totals
        # ARE the delta, nothing lost and nothing double-counted
        assert led.ingest("a", 3, 1, 2000) == (3, 1)
        assert (led.total_good, led.total_bad) == (103, 11)

    def test_sources_are_independent(self):
        led = ledger()
        led.ingest("a", 10, 0, 1000)
        led.ingest("b", 20, 2, 1000)
        led.forget("a")
        led.ingest("a", 4, 0, 2000)  # re-appeared: fresh watermark
        assert led.total_good == 34

    def test_window_expiry_is_exact(self):
        led = ledger(window_ms=10_000, bucket_ms=1_000)
        led.ingest("a", 7, 3, 500)
        led.advance(5_000)
        assert led.window_counts(5_000) == (7, 3)
        led.advance(12_000)  # bucket [0,1000) wholly out of [2000, 12000]
        assert led.window_counts(12_000) == (0, 0)
        assert (led.expired_good, led.expired_bad) == (7, 3)
        assert led.total_good == led.expired_good == 7

    def test_burn_rate_semantics(self):
        led = ledger(target=0.9)  # 10% budget
        assert led.burn_rate(1000) is None  # no data ≠ zero burn
        led.ingest("a", 90, 10, 1000)
        assert led.burn_rate(1000) == pytest.approx(1.0)  # exactly sustainable
        led.ingest("a", 90, 30, 1000)  # cumulative: +20 bad
        # 30 bad / 120 total = 25% bad fraction over a 10% allowance
        assert led.burn_rate(1000) == pytest.approx((30 / 120) / 0.1)

    def test_budget_remaining(self):
        led = ledger(target=0.9)
        assert led.budget_remaining(1000) == 1.0  # untouched
        led.ingest("a", 95, 5, 1000)
        assert led.budget_remaining(1000) == pytest.approx(0.5)
        led.ingest("a", 95, 20, 1000)
        assert led.budget_remaining(1000) == 0.0  # clamped, over-spent

    def test_subwindow_counts_at_bucket_grain(self):
        led = ledger(window_ms=60_000, bucket_ms=1_000)
        led.ingest("a", 5, 0, 500)       # bucket [0, 1000)
        led.ingest("a", 9, 1, 10_500)    # bucket [10000, 11000)
        good, bad = led.window_counts(10_900, window_ms=2_000)
        assert (good, bad) == (4, 1)     # only the recent bucket
        good, bad = led.window_counts(10_900)
        assert (good, bad) == (9, 1)

    def test_bad_geometry_is_loud(self):
        with pytest.raises(ValueError, match="bucket-ms"):
            ledger(window_ms=1_000, bucket_ms=5_000)


# ---------------------------------------------------------------------------
# satellite: randomized property test — the accounting is EXACT
# ---------------------------------------------------------------------------
class TestBudgetPartitionProperty:
    """Mirror of goodput's exact-partition property: for ANY interleaving of
    cumulative samples (including counter resets), multiple sources, time
    jumps across bucket and window boundaries, and advances:

      ingested == expired + banked          (good and bad, to the count)
      consumed + remaining == window budget (when the budget is positive)
    """

    def _drive(self, rng):
        window_ms = rng.choice([5_000, 10_000, 60_000])
        bucket_ms = rng.choice([250, 1_000, window_ms])
        target = rng.choice([0.5, 0.9, 0.99])
        led = ledger(target=target, window_ms=window_ms, bucket_ms=bucket_ms)
        sources = [f"task:{i}" for i in range(rng.randint(1, 4))]
        watermark = {s: (0, 0) for s in sources}
        ingested_good = ingested_bad = 0
        now = rng.randint(0, 10_000)
        for _ in range(rng.randint(5, 60)):
            now += rng.choice([0, 1, bucket_ms // 2 or 1, bucket_ms,
                               window_ms // 3, window_ms * 2])
            op = rng.random()
            if op < 0.6:
                s = rng.choice(sources)
                g, b = watermark[s]
                if rng.random() < 0.15:
                    g, b = 0, 0  # process restart: counters start over
                ng, nb = g + rng.randint(0, 50), b + rng.randint(0, 10)
                dg, db = led.ingest(s, ng, nb, now)
                watermark[s] = (ng, nb)
                ingested_good += dg
                ingested_bad += db
            elif op < 0.8:
                led.advance(now)
            else:
                led.forget(rng.choice(sources))
            # THE invariant, checked after every single operation
            banked_g = sum(g for g, _ in led._buckets.values())
            banked_b = sum(b for _, b in led._buckets.values())
            assert led.total_good == ingested_good
            assert led.total_bad == ingested_bad
            assert led.expired_good + banked_g == ingested_good
            assert led.expired_bad + banked_b == ingested_bad
            # window budget partition: consumed + remaining == budget
            good, bad = led.window_counts(now)
            budget = led.objective.allowed_bad_fraction * (good + bad)
            if budget > 0:
                remaining = led.budget_remaining(now) * budget
                consumed = min(bad, budget)  # remaining clamps at 0
                assert consumed + remaining == pytest.approx(budget)

    def test_partition_is_exact_over_random_histories(self):
        for seed in range(300):
            try:
                self._drive(random.Random(seed))
            except AssertionError as e:
                raise AssertionError(f"seed {seed}: {e}") from e


# ---------------------------------------------------------------------------
# snapshot extraction: exact TTFT split, availability, exemplars
# ---------------------------------------------------------------------------
class TestExtraction:
    def _ttft_snapshot(self):
        reg = obs_metrics.MetricsRegistry()
        h = reg.histogram("tony_serve_ttft_seconds", "t",
                          buckets=(0.1, 1.0, 10.0))
        h.ensure_bucket(0.5)  # the SLO-aligned edge (500ms threshold)
        for v, rid in ((0.05, "r1"), (0.4, "r2"), (0.5, "r3"),
                       (0.9, "r4"), (4.0, "r5")):
            h.observe(v, exemplar=rid)
        return reg.snapshot()

    def test_ttft_good_bad_is_exact_at_the_aligned_edge(self):
        snap = self._ttft_snapshot()
        # good = cumulative count at the 0.5s edge: 0.05, 0.4, 0.5 land in
        good, bad = obs_slo.ttft_good_bad(snap, threshold_ms=500.0)
        assert (good, bad) == (3, 2)

    def test_ttft_missing_metric_is_none(self):
        assert obs_slo.ttft_good_bad([], 500.0) is None

    def test_exemplars_are_worst_first_and_capped(self):
        ex = obs_slo.ttft_exemplars(self._ttft_snapshot())
        assert ex[0] == (4.0, "r5")
        assert [rid for _, rid in ex[:2]] == ["r5", "r4"]
        assert len(ex) <= obs_metrics.EXEMPLAR_K

    def test_availability_by_outcome_label(self):
        snap = [{
            "name": "tony_serve_requests_total", "kind": "counter",
            "samples": [
                {"labels": {"outcome": "ok"}, "value": 90},
                {"labels": {"outcome": "forwarded"}, "value": 5},
                {"labels": {"outcome": "error"}, "value": 4},
                {"labels": {"outcome": "cancelled"}, "value": 7},
            ],
        }]
        # a client cancel spends no availability budget
        assert obs_slo.availability_good_bad(snap) == (102, 4)


# ---------------------------------------------------------------------------
# burn rules through the real AlertEngine
# ---------------------------------------------------------------------------
def engine_cfg(**overrides):
    base = {
        keys.SLO_SERVE_AVAILABILITY_TARGET: "0.9",
        keys.SLO_WINDOW_MS: "60000",
        keys.SLO_BUCKET_MS: "1000",
        keys.SLO_FAST_BURN: "8.0",
        keys.SLO_FAST_WINDOW_MS: "12000",
        keys.SLO_SLOW_BURN: "2.0",
        keys.SLO_SLOW_WINDOW_MS: "48000",
    }
    base.update({k: str(v) for k, v in overrides.items()})
    return cfg(**base)


def avail_snap(ok, err):
    return [{"name": "tony_serve_requests_total", "samples": [
        {"labels": {"outcome": "ok"}, "value": ok},
        {"labels": {"outcome": "error"}, "value": err},
    ]}]


class TestBurnRules:
    def test_rule_compilation(self):
        eng = obs_slo.SloEngine(engine_cfg(**{
            keys.SLO_SERVE_TTFT_TARGET: "0.99"}))
        rules = {r.name: r for r in eng.burn_rules()}
        assert set(rules) == {
            "slo-serve-ttft-fast-burn", "slo-serve-ttft-slow-burn",
            "slo-serve-availability-fast-burn",
            "slo-serve-availability-slow-burn"}
        fast = rules["slo-serve-ttft-fast-burn"]
        assert fast.threshold == 8.0 and fast.direction == "above"
        assert all(r.name.startswith(obs_slo.RULE_PREFIX) for r in rules.values())

    def test_fast_burn_fires_and_short_window_resolves(self):
        eng = obs_slo.SloEngine(engine_cfg())
        alerts = obs_alerts.AlertEngine(eng.burn_rules(), app_id="app")
        # sustained 50% errors over a 10% allowance: burn 5× → slow (2×)
        # fires, fast (8×) does not
        now = 0
        fired = set()
        for i in range(12):
            now = i * 1000
            eng.observe_serve("t", avail_snap(ok=(i + 1) * 5, err=(i + 1) * 5), now)
            for rec in alerts.evaluate(eng.tick(now)):
                fired.add((rec["rule"], rec["state"]))
        assert ("slo-serve-availability-slow-burn", "fired") in fired
        assert ("slo-serve-availability-fast-burn", "fired") not in fired
        # burst to ~90% errors across the fast window → burn past 8× → page
        for i in range(12, 18):
            now = i * 1000
            eng.observe_serve("t", avail_snap(ok=60, err=60 + (i - 11) * 40), now)
            for rec in alerts.evaluate(eng.tick(now)):
                fired.add((rec["rule"], rec["state"]))
        assert ("slo-serve-availability-fast-burn", "fired") in fired
        # the burn stops: fresh all-good buckets drain the SHORT confirm
        # window first, so the page resolves long before the fast window
        # itself is clean (the workbook's prompt-resolve property)
        for i in range(18, 24):
            now = i * 1000
            eng.observe_serve("t", avail_snap(ok=1000 + i * 200, err=300), now)
            for rec in alerts.evaluate(eng.tick(now)):
                fired.add((rec["rule"], rec["state"]))
        assert ("slo-serve-availability-fast-burn", "resolved") in fired

    def test_no_data_returns_none_and_holds_state(self):
        eng = obs_slo.SloEngine(engine_cfg())
        values = eng.tick(1000)
        assert values == {"slo-serve-availability-fast-burn": None,
                          "slo-serve-availability-slow-burn": None}
        alerts = obs_alerts.AlertEngine(eng.burn_rules(), app_id="app")
        assert alerts.evaluate(values) == []  # nothing fires, nothing resolves

    def test_gauges_track_the_ledger(self):
        eng = obs_slo.SloEngine(engine_cfg())
        eng.observe_serve("t", avail_snap(ok=50, err=50), 1000)
        eng.tick(1000)
        snap = obs_metrics.REGISTRY.snapshot()
        rem = burn = None
        for m in snap:
            if m["name"] == "tony_slo_budget_remaining":
                for s in m["samples"]:
                    if s["labels"].get("objective") == "serve-availability":
                        rem = s["value"]
            if m["name"] == "tony_slo_burn_rate":
                for s in m["samples"]:
                    if (s["labels"].get("objective") == "serve-availability"
                            and s["labels"].get("window") == "fast"):
                        burn = s["value"]
        assert rem == 0.0  # 50% errors vs a 10% budget: spent
        assert burn == pytest.approx(5.0)

    def test_observe_train_uses_the_ledger_partition(self):
        eng = obs_slo.SloEngine(engine_cfg(**{
            keys.SLO_SERVE_AVAILABILITY_TARGET: "",
            keys.SLO_TRAIN_GOODPUT_TARGET: "0.5"}))
        led = types.SimpleNamespace(
            wall_ms=10_000, phases_ms={"productive": 8_000, "compile": 2_000})
        eng.observe_train("app", led, 1000)
        doc = eng.status(1000)
        o = doc["objectives"]["train-goodput"]
        assert (o["good"], o["bad"]) == (8_000, 2_000)
        assert o["unit"] == "ms"


# ---------------------------------------------------------------------------
# status / window rows / jsonl sink
# ---------------------------------------------------------------------------
class TestEngineSurfaces:
    def test_status_document_shape(self):
        eng = obs_slo.SloEngine(engine_cfg(), app_id="app-1")
        eng.observe_serve("t", avail_snap(ok=99, err=1), 500)
        doc = eng.status(500)
        assert doc["app_id"] == "app-1" and doc["enabled"]
        o = doc["objectives"]["serve-availability"]
        assert (o["good"], o["bad"]) == (99, 1)
        assert 0.0 <= o["budget_remaining"] <= 1.0
        assert o["exemplars"] == []

    def test_window_rows_and_sink(self, tmp_path):
        sink = tmp_path / "slo.jsonl"
        eng = obs_slo.SloEngine(engine_cfg(), app_id="app-1",
                                sink_path=str(sink))
        eng.observe_serve("t", avail_snap(ok=10, err=2), 1500)
        eng.append_windows(1500)
        eng.observe_serve("t", avail_snap(ok=20, err=2), 1800)  # same bucket
        eng.append_windows(1800)
        rows = [json.loads(line) for line in sink.read_text().splitlines()]
        assert len(rows) == 2
        assert all(r["window_start_ms"] == 1000 for r in rows)
        # the bucket is re-emitted as it fills: the LAST write is the fullest
        assert (rows[0]["good"], rows[1]["good"]) == (10, 20)
        assert rows[1]["app_id"] == "app-1"
        assert rows[1]["objective"] == "serve-availability"
        assert rows[1]["window_end_ms"] == 2000

    def test_ttft_exemplars_merge_worst_across_snapshots(self):
        eng = obs_slo.SloEngine(engine_cfg(**{
            keys.SLO_SERVE_TTFT_TARGET: "0.99",
            keys.SLO_SERVE_AVAILABILITY_TARGET: ""}))
        snap = [{"name": "tony_serve_ttft_seconds", "buckets": [0.5, 2.0],
                 "samples": [{"counts": [1, 0], "count": 2,
                              "exemplars": [[3.0, "slow-1"], [0.2, "fast"]]}]}]
        eng.observe_serve("t", snap, 1000)
        snap2 = [{"name": "tony_serve_ttft_seconds", "buckets": [0.5, 2.0],
                  "samples": [{"counts": [1, 0], "count": 1,
                               "exemplars": [[7.0, "slow-2"]]}]}]
        eng.observe_serve("t", snap2, 2000)
        ex = eng.status(2000)["objectives"]["serve-ttft"]["exemplars"]
        assert [e["request_id"] for e in ex[:2]] == ["slow-2", "slow-1"]


# ---------------------------------------------------------------------------
# verdict
# ---------------------------------------------------------------------------
def row(objective, start, good, bad, target=0.9, source="app"):
    return {"app_id": source, "objective": objective, "target": target,
            "unit": "requests", "window_start_ms": start,
            "window_end_ms": start + 1000, "good": good, "bad": bad}


class TestVerdict:
    def test_pass_fail_no_data(self):
        rows = [row("serve-availability", 1000, 95, 5)]
        v = obs_slo.verdict_from_rows(rows, 60_000, 5_000)
        assert v["verdict"] == "PASS"
        o = v["objectives"]["serve-availability"]
        assert o["achieved"] == pytest.approx(0.95) and o["passed"]
        assert o["budget_burned_pct"] == pytest.approx(50.0)

        v = obs_slo.verdict_from_rows(
            [row("serve-availability", 1000, 80, 20)], 60_000, 5_000)
        assert v["verdict"] == "FAIL"
        assert v["objectives"]["serve-availability"]["budget_burned_pct"] == (
            pytest.approx(200.0))

        assert obs_slo.verdict_from_rows([], 60_000, 5_000)["verdict"] == "NO_DATA"

    def test_window_filter_sums_only_recent_rows(self):
        rows = [row("serve-availability", 0, 0, 100),        # ancient disaster
                row("serve-availability", 90_000, 99, 1)]
        v = obs_slo.verdict_from_rows(rows, 10_000, 95_000)
        o = v["objectives"]["serve-availability"]
        assert (o["good"], o["bad"]) == (99, 1) and v["verdict"] == "PASS"

    def test_one_failing_objective_fails_overall(self):
        rows = [row("serve-availability", 1000, 99, 1),
                row("serve-ttft", 1000, 50, 50, target=0.99)]
        v = obs_slo.verdict_from_rows(rows, 60_000, 5_000)
        assert v["verdict"] == "FAIL"
        assert v["objectives"]["serve-availability"]["passed"]
        assert not v["objectives"]["serve-ttft"]["passed"]

    def test_malformed_rows_are_skipped(self):
        rows = [{"objective": "x"}, {"window_start_ms": "?"}, None and {},
                row("serve-availability", 1000, 9, 1)]
        v = obs_slo.verdict_from_rows([r for r in rows if r], 60_000, 5_000)
        assert v["objectives"]["serve-availability"]["rows"] == 1


# ---------------------------------------------------------------------------
# history store: slo_series
# ---------------------------------------------------------------------------
class TestStoreSloSeries:
    def test_put_is_replace_idempotent(self, tmp_path):
        store = HistoryStore(str(tmp_path / "h.sqlite"))
        try:
            early = row("serve-availability", 1000, 10, 1)
            full = row("serve-availability", 1000, 30, 2)
            assert store.put_slo_windows("app", [early]) == 1
            assert store.put_slo_windows("app", [full, early]) == 2
            # re-sweeping converges: one row per (source, objective, bucket)
            got = store.slo_series(source="app")
            assert len(got) == 1
            assert (got[0]["good"], got[0]["bad"]) == (10, 1) or (
                got[0]["good"], got[0]["bad"]) == (30, 2)
            # the LAST write wins (REPLACE): early re-put after full
            assert (got[0]["good"], got[0]["bad"]) == (10, 1)
        finally:
            store.close()

    def test_filters_and_purge(self, tmp_path):
        store = HistoryStore(str(tmp_path / "h.sqlite"))
        try:
            store.put_slo_windows("a", [row("serve-ttft", 1000, 5, 0),
                                        row("serve-ttft", 2000, 5, 1)])
            store.put_slo_windows("b", [row("serve-availability", 1000, 9, 0)])
            assert len(store.slo_series()) == 3
            assert len(store.slo_series(objective="serve-ttft")) == 2
            assert len(store.slo_series(source="b")) == 1
            assert len(store.slo_series(since_ms=1500)) == 1
            assert store.purge_slo_older_than(2500) == 2
            assert len(store.slo_series()) == 1
        finally:
            store.close()

    def test_rows_without_keys_are_skipped(self, tmp_path):
        store = HistoryStore(str(tmp_path / "h.sqlite"))
        try:
            n = store.put_slo_windows("a", [{"good": 1}, {"objective": "x"},
                                            row("serve-ttft", 1000, 1, 0)])
            assert n == 1
        finally:
            store.close()


class TestSweepSloSeries:
    def _stage(self, tmp_path, app_id, rows, torn=False):
        d = tmp_path / app_id
        d.mkdir(parents=True, exist_ok=True)
        (d / "am_status.json").write_text("{}")  # staged_ids discovery marker
        with open(d / "slo.jsonl", "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
            if torn:
                f.write('{"objective": "serve-ttft", "window_')  # torn tail

    def test_sweep_ingests_and_tolerates_torn_tail(self, tmp_path):
        self._stage(tmp_path, "app-1",
                    [row("serve-ttft", 1000, 5, 1, source="app-1"),
                     row("serve-ttft", 1000, 9, 1, source="app-1")],  # re-emit
                    torn=True)
        store = HistoryStore(str(tmp_path / "h.sqlite"))
        try:
            counts = hist_ingest.sweep_slo_series(store, [str(tmp_path)])
            assert counts["files"] == 1 and counts["errors"] == 0
            got = store.slo_series(source="app-1")
            assert len(got) == 1
            assert (got[0]["good"], got[0]["bad"]) == (9, 1)  # last = fullest
            # idempotent: re-sweep converges to the same row
            hist_ingest.sweep_slo_series(store, [str(tmp_path)])
            assert len(store.slo_series(source="app-1")) == 1
        finally:
            store.close()

    def test_retention_purges_old_buckets(self, tmp_path):
        now_ms = 100 * 86_400_000
        old = row("serve-ttft", 1000, 5, 0, source="app-1")
        fresh = row("serve-ttft", now_ms - 1000, 5, 0, source="app-1")
        self._stage(tmp_path, "app-1", [old, fresh])
        store = HistoryStore(str(tmp_path / "h.sqlite"))
        try:
            counts = hist_ingest.sweep_slo_series(
                store, [str(tmp_path)], retention_days=7.0, now_ms=now_ms)
            assert counts["purged_rows"] == 1
            got = store.slo_series(source="app-1")
            assert len(got) == 1 and got[0]["window_start_ms"] == now_ms - 1000
        finally:
            store.close()


# ---------------------------------------------------------------------------
# CLI: merged rows dedupe + verdict exit codes + status fallback
# ---------------------------------------------------------------------------
class TestSloCli:
    def _stage(self, tmp_path, app_id, rows):
        d = tmp_path / app_id
        d.mkdir(parents=True, exist_ok=True)
        with open(d / "slo.jsonl", "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    def test_merged_rows_never_double_count(self, tmp_path):
        """The verdict sums rows — a bucket present in BOTH the store and
        the jsonl must be counted once (the jsonl copy, at least as fresh
        as the last sweep, wins)."""
        from tony_tpu.cli import slo as cli_slo

        jsonl = [row("serve-availability", 1000, 10, 1, source="app-1"),
                 row("serve-availability", 1000, 50, 2, source="app-1")]
        self._stage(tmp_path, "app-1", jsonl)
        store_path = str(tmp_path / "h.sqlite")
        store = HistoryStore(store_path)
        store.put_slo_windows("app-1", jsonl[:1])  # the sweep saw the early copy
        store.close()
        merged = cli_slo._merged_rows(str(tmp_path), "app-1", store_path)
        assert len(merged) == 1
        assert (merged[0]["good"], merged[0]["bad"]) == (50, 2)

    def test_verdict_exit_codes_from_persisted_rows(self, tmp_path, capsys):
        from tony_tpu.cli import slo as cli_slo

        now_ms = int(time.time() * 1000)
        self._stage(tmp_path, "app-1",
                    [row("serve-availability", now_ms - 5000, 99, 1)])
        rc = cli_slo.main(["verdict", "app-1", "--staging", str(tmp_path),
                           "--store", str(tmp_path / "h.sqlite")])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0 and doc["verdict"] == "PASS"
        assert doc["app_id"] == "app-1"

        self._stage(tmp_path, "app-2",
                    [row("serve-availability", now_ms - 5000, 50, 50)])
        assert cli_slo.main(["verdict", "app-2", "--staging", str(tmp_path),
                             "--store", str(tmp_path / "h.sqlite")]) == 1
        capsys.readouterr()
        assert cli_slo.main(["verdict", "absent", "--staging", str(tmp_path),
                             "--store", str(tmp_path / "h.sqlite")]) == 2

    def test_status_falls_back_to_persisted_rows(self, tmp_path, capsys):
        from tony_tpu.cli import slo as cli_slo

        self._stage(tmp_path, "app-1", [
            dict(row("serve-availability", 1000, 95, 5),
                 burn_fast=0.5, burn_slow=0.4, budget_remaining=0.5)])
        # bare `tony slo <app_id>` means status; no AM registered → replay
        rc = cli_slo.main(["app-1", "--staging", str(tmp_path),
                           "--store", str(tmp_path / "h.sqlite")])
        out = capsys.readouterr().out
        assert rc == 0
        assert "last persisted state" in out
        assert "serve-availability" in out and "good 95 bad 5" in out

    def test_status_missing_app_is_an_error(self, tmp_path, capsys):
        from tony_tpu.cli import slo as cli_slo

        rc = cli_slo.main(["nothing-here", "--staging", str(tmp_path),
                           "--store", str(tmp_path / "h.sqlite")])
        assert rc == 1
        assert "no SLO data" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# gate: slo_verdict contract + budget_burned_pct direction
# ---------------------------------------------------------------------------
def bench_record(n, **parsed):
    base = {"metric": "serve_tokens_per_sec", "value": 100.0, "unit": "tok/s",
            "vs_baseline": 1.0}
    base.update(parsed)
    return {"n": n, "rc": 0, "parsed": base}


class TestGateSloContract:
    def test_verdict_pass_is_a_passing_contract(self):
        res = bench_gate.evaluate(bench_record(2, slo_verdict="PASS"),
                                  [("r1.json", bench_record(1))])
        checks = {c.metric: c for c in res.checks}
        assert checks["slo_verdict"].passed
        assert checks["slo_verdict"].reference_from == "contract"

    def test_verdict_fail_and_no_data_fail_the_gate(self):
        for bad in ("FAIL", "NO_DATA"):
            res = bench_gate.evaluate(bench_record(2, slo_verdict=bad),
                                      [("r1.json", bench_record(1))])
            checks = {c.metric: c for c in res.checks}
            assert not checks["slo_verdict"].passed
            assert not res.passed

    def test_absent_verdict_is_not_checked(self):
        res = bench_gate.evaluate(bench_record(2),
                                  [("r1.json", bench_record(1))])
        assert "slo_verdict" not in {c.metric for c in res.checks}

    def test_budget_burned_gates_downward(self):
        res = bench_gate.evaluate(
            bench_record(2, budget_burned_pct=80.0),
            [("r1.json", bench_record(1, budget_burned_pct=10.0))])
        checks = {c.metric: c for c in res.checks}
        assert "budget_burned_pct" in checks
        assert not checks["budget_burned_pct"].passed

    def test_validate_record_rejects_unknown_verdicts(self):
        errs = bench_gate.validate_record(bench_record(1, slo_verdict="MAYBE"))
        assert any("slo_verdict" in e for e in errs)
        assert bench_gate.validate_record(bench_record(1, slo_verdict="PASS")) == []


# ---------------------------------------------------------------------------
# diurnal arrival profile
# ---------------------------------------------------------------------------
class TestArrivalOffsets:
    def test_uniform_is_fixed_spacing(self):
        assert arrival_offsets(4, 2.0) == [0.0, 0.5, 1.0, 1.5]

    def test_diurnal_keeps_total_duration_and_is_deterministic(self):
        a = arrival_offsets(40, 8.0, profile="diurnal")
        b = arrival_offsets(40, 8.0, profile="diurnal")
        assert a == b  # the spike's timing is part of the spec
        assert len(a) == 40
        assert a == sorted(a)
        assert a[-1] <= 40 / 8.0  # same total duration as uniform

    def test_diurnal_is_denser_mid_run(self):
        offs = arrival_offsets(60, 6.0, profile="diurnal", amp=3.0)
        total = 60 / 6.0
        head = sum(1 for t in offs if t < total / 3)
        mid = sum(1 for t in offs if total / 3 <= t <= 2 * total / 3)
        tail = sum(1 for t in offs if t > 2 * total / 3)
        # the spike: the middle third out-draws EACH shoulder by far
        assert mid > 1.5 * head and mid > 1.5 * tail

    def test_degenerate_inputs(self):
        assert arrival_offsets(0, 5.0, "diurnal") == []
        assert arrival_offsets(3, 0.0, "diurnal") == [0.0, 0.0, 0.0]


# ---------------------------------------------------------------------------
# autoscaler: SLO burn is up-pressure and a scale-down veto
# ---------------------------------------------------------------------------
class TestAutoscalerSloBurn:
    def _scaler(self, burn=None):
        from tests.test_serve_fleet import FakeAM, make_health

        am = FakeAM()
        h = make_health(am)
        p = AutoscalePolicy(min_replicas=1, max_replicas=4,
                            scale_up_ticks=2, scale_down_ticks=2)
        return Autoscaler(h, lambda job, n: am.call(
            "resize_jobtype", job_name=job, instances=n), p, burn=burn), am

    def _sig(self, healthy=2, queue=0, active=0, total=16):
        from tony_tpu.serve.health import FleetSignals

        return FleetSignals(replicas_known=healthy, replicas_healthy=healthy,
                            queue_depth=queue, slots_active=active,
                            slots_total=total)

    def test_burning_is_up_pressure_on_an_idle_fleet(self):
        sc, _ = self._scaler()
        sig = self._sig()  # zero queue, zero utilization
        assert sc.decide(2, sig, burning=True) == 2   # tick 1 of 2
        assert sc.decide(2, sig, burning=True) == 3   # sustained burn → +1

    def test_burning_vetoes_scale_down(self):
        sc, _ = self._scaler()
        sig = self._sig()
        assert sc.decide(2, sig, burning=True) == 2
        # without burn this second idle tick would shrink (down_ticks=2)
        assert sc.decide(2, sig, burning=True) == 3
        sc2, _ = self._scaler()
        assert sc2.decide(2, sig) == 2
        assert sc2.decide(2, sig) == 1  # the control: idle fleet shrinks

    def test_tick_consults_the_burn_supplier(self):
        from tests.test_serve_fleet import FakeReplica

        burns = iter([5.0, 5.0])
        sc, am = self._scaler(burn=lambda: next(burns))
        rep = FakeReplica()
        try:
            am.set_replica(0, rep.url)
            sc.health._resolve()
            sc.health.tick()
            sc.tick()
            sc.tick()  # burn ≥ 1 for scale_up_ticks samples → resize up
            assert am.resizes == [("serve", 2)]
        finally:
            rep.close()

    def test_burn_supplier_failure_never_breaks_the_tick(self):
        def boom():
            raise RuntimeError("AM mid-exit")

        from tests.test_serve_fleet import FakeReplica

        sc, am = self._scaler(burn=boom)
        rep = FakeReplica()
        try:
            am.set_replica(0, rep.url)
            sc.health._resolve()
            sc.health.tick()
            sc.tick()  # must not raise; load signals still decide
            assert am.resizes == []
        finally:
            rep.close()


# ---------------------------------------------------------------------------
# zero-allocation request-span path (tracing disabled)
# ---------------------------------------------------------------------------
class TestRequestSpanAllocationFree:
    def test_disabled_tracing_allocates_no_spans(self, monkeypatch):
        """The acceptance contract: with tracing off, the per-request span
        chain is a single attribute check — constructing a Span at all is a
        regression. Enforced by making the constructor explosive."""
        from tony_tpu.models.serving_http import RequestStream

        monkeypatch.setattr(obs_trace, "_tracer", None)

        def explode(*a, **k):
            raise AssertionError("Span allocated with tracing disabled")

        monkeypatch.setattr(obs_trace, "Span", explode)
        assert obs_trace.start_manual("serve.request", rid="r-1") is None
        stream = RequestStream(request_id="r-1")
        stream.open_trace()
        stream.begin_stage("serve.prefill")
        stream.begin_stage("serve.decode", ttft_s=0.1)
        stream.finish_trace("ok")
        assert stream.span is None and stream.stage is None

    def test_enabled_tracing_builds_the_chain(self, tmp_path, monkeypatch):
        from tony_tpu.models.serving_http import RequestStream

        tracer = obs_trace.Tracer("trace-1", "serve:0", str(tmp_path))
        monkeypatch.setattr(obs_trace, "_tracer", tracer)
        stream = RequestStream(request_id="req-42")
        stream.open_trace()
        root_id = stream.span.span_id
        assert stream.span.attrs["rid"] == "req-42"
        stream.begin_stage("serve.prefill")
        stream.begin_stage("serve.decode", ttft_s=0.05)
        stream.finish_trace("ok")
        tracer.close()
        spans = [json.loads(line)
                 for p in tmp_path.glob("*.jsonl")
                 for line in open(p).read().splitlines()]
        by_name = {s["name"]: s for s in spans}
        assert {"serve.request", "serve.queue", "serve.prefill",
                "serve.decode"} <= set(by_name)
        for stage in ("serve.queue", "serve.prefill", "serve.decode"):
            assert by_name[stage]["parent_id"] == root_id
        assert by_name["serve.decode"]["attrs"]["ttft_s"] == 0.05


# ---------------------------------------------------------------------------
# router request ids
# ---------------------------------------------------------------------------
class TestRouterRequestIds:
    def test_router_assigns_and_echoes_request_id(self):
        from tests.test_serve_fleet import (
            FakeAM, FakeReplica, inject, make_health, make_router, post_router)

        rep, am = FakeReplica(), FakeAM()
        h = make_health(am)
        router = make_router(h)
        try:
            inject(h, 0, rep.url)
            _, hdrs, _ = post_router(router.url, {"prompt_tokens": [1]})
            rid = hdrs.get("X-Tony-Request-Id")
            assert rid  # assigned at the front door
            _, hdrs2, _ = post_router(router.url, {"prompt_tokens": [1]})
            assert hdrs2["X-Tony-Request-Id"] != rid  # unique per request
        finally:
            router.stop()
            rep.close()

    def test_client_supplied_id_is_kept(self):
        import urllib.request

        from tests.test_serve_fleet import (
            FakeAM, FakeReplica, inject, make_health, make_router)

        rep, am = FakeReplica(), FakeAM()
        h = make_health(am)
        router = make_router(h)
        try:
            inject(h, 0, rep.url)
            req = urllib.request.Request(
                router.url + "/v1/completions",
                json.dumps({"prompt_tokens": [1]}).encode(),
                {"Content-Type": "application/json",
                 "X-Tony-Request-Id": "client-rid-7"})
            resp = urllib.request.urlopen(req, timeout=30)
            assert resp.headers["X-Tony-Request-Id"] == "client-rid-7"
        finally:
            router.stop()
            rep.close()


# ---------------------------------------------------------------------------
# headline e2e
# ---------------------------------------------------------------------------
@pytest.mark.e2e
class TestSloHeadlineE2E:
    """Diurnal load over a live router/fleet: a mid-spike error burst burns
    the availability budget fast enough to page, the page resolves once the
    burst ends, the budget rows persist through slo.jsonl → the history
    store, and `tony slo verdict` reads PASS from those PERSISTED rows.

    The replica fleet is the suite's fake (real HTTP, injectable failures)
    so the burst is deterministic — the capacity market's live spike e2e
    (tests/test_market.py) already drives real replicas; this headline
    pins down the SLO plane's seams end to end: router rids → loadtest
    worst-TTFT exemplars, live turns → ledgers → AlertEngine transitions →
    jsonl → store sweep → CLI verdict.
    """

    def test_diurnal_burn_fires_resolves_and_verdict_passes(
            self, tmp_path, capsys):
        from tony_tpu.cli import slo as cli_slo
        from tests.test_serve_fleet import (
            FakeAM, FakeReplica, make_health, make_router)

        app_id = "app-slo-e2e"
        staging = tmp_path / app_id
        staging.mkdir()
        (staging / "am_status.json").write_text("{}")  # staged_ids marker
        reps = [FakeReplica(), FakeReplica()]
        am = FakeAM()
        # a LIVE monitor (unlike the hand-ticked unit tests): the 500-burst
        # passively ejects both replicas from the router's rotation, and the
        # probe loop is what brings them back once the burst ends
        h = make_health(am, interval_s=0.1)
        router = make_router(h)
        c = cfg(**{
            keys.SLO_SERVE_AVAILABILITY_TARGET: "0.5",  # lenient: PASS overall
            keys.SLO_WINDOW_MS: "60000",
            keys.SLO_BUCKET_MS: "250",
            keys.SLO_FAST_BURN: "1.05",         # page on any unsustainable burn
            keys.SLO_FAST_WINDOW_MS: "750",
            keys.SLO_SLOW_BURN: "100.0",        # keep the slow rule quiet
            keys.SLO_SLOW_WINDOW_MS: "12000",
        })
        eng = obs_slo.SloEngine(c, app_id=app_id,
                                sink_path=str(staging / "slo.jsonl"))
        alert_engine = obs_alerts.AlertEngine(eng.burn_rules(), app_id=app_id)
        transitions = []
        try:
            for i, rep in enumerate(reps):
                am.set_replica(i, rep.url)
            h.tick()
            h.start()

            spec = LoadSpec(url=router.url, sessions=48, turns=1, rate=12.0,
                            profile="diurnal", stream=False, timeout_s=30.0)
            gen = LoadGenerator(spec)
            total_s = spec.sessions / spec.rate  # 4s

            stop = threading.Event()

            def flip_errors():
                # the burst sits inside the diurnal spike (dense middle):
                # 20% of wall time but ~1.6× the mean arrival density, so it
                # claims ~1/3 of the turns — enough to page, not to FAIL a
                # 0.5 availability target over the whole run
                time.sleep(total_s * 0.40)
                for rep in reps:
                    rep.cfg["status"] = 500
                time.sleep(total_s * 0.20)
                for rep in reps:
                    rep.cfg["status"] = 200

            def ticker():
                # the AM's goodput-tick analogue: live cumulative counters
                # from the real run's finished turns → ledger → alert engine
                while not stop.is_set():
                    with gen._lock:
                        turns = list(gen._results)
                    ok = sum(1 for t in turns if t.ok)
                    bad = len(turns) - ok
                    now_ms = int(time.time() * 1000)
                    if turns:
                        eng.observe_serve(
                            "serve:0",
                            avail_snap(ok=ok, err=bad), now_ms)
                    transitions.extend(
                        alert_engine.evaluate(eng.tick(now_ms)))
                    eng.append_windows(now_ms)
                    stop.wait(0.2)

            flipper = threading.Thread(target=flip_errors, daemon=True)
            tick_thread = threading.Thread(target=ticker, daemon=True)
            flipper.start()
            tick_thread.start()
            report = gen.run()
            flipper.join()
            stop.set()
            tick_thread.join(timeout=5)
            # keep ticking after the run: with the burst over, the SHORT
            # confirm window drains of error traffic and the page RESOLVES
            # long before the fast window itself is clean (the workbook's
            # prompt-resolve property) — no synthetic traffic needed
            with gen._lock:
                ok = sum(1 for t in gen._results if t.ok)
                bad = len(gen._results) - ok
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                now_ms = int(time.time() * 1000)
                eng.observe_serve("serve:0", avail_snap(ok=ok, err=bad), now_ms)
                transitions.extend(alert_engine.evaluate(eng.tick(now_ms)))
                eng.append_windows(now_ms)
                states = {(t["rule"], t["state"]) for t in transitions}
                if ("slo-serve-availability-fast-burn", "resolved") in states:
                    break
                time.sleep(0.2)

            states = {(t["rule"], t["state"]) for t in transitions}
            assert ("slo-serve-availability-fast-burn", "fired") in states, (
                f"fast burn never fired; transitions={transitions}, "
                f"errors={len(report.errors)}/{len(report.turns)}")
            assert ("slo-serve-availability-fast-burn", "resolved") in states

            # the run really was diurnal and really failed mid-spike
            d = report.to_dict()
            assert d["profile"] == "diurnal"
            assert report.errors, "the burst produced no failed turns"
            # worst-TTFT exemplars carry router-assigned request ids
            assert d.get("worst_ttft"), "no worst-TTFT exemplars in the report"
            assert all(w["request_id"] for w in d["worst_ttft"])

            # persisted rows survive the AM: sweep slo.jsonl into the store,
            # then judge the verdict from PERSISTED state only
            store_path = str(tmp_path / "history.sqlite")
            store = HistoryStore(store_path)
            try:
                counts = hist_ingest.sweep_slo_series(store, [str(tmp_path)])
                assert counts["rows"] > 0 and counts["errors"] == 0
            finally:
                store.close()
            rc = cli_slo.main([
                "verdict", app_id, "--staging", str(tmp_path),
                "--store", store_path, "--window", "3600"])
            verdict = json.loads(capsys.readouterr().out)
            assert rc == 0, f"verdict not PASS: {verdict}"
            assert verdict["verdict"] == "PASS"
            o = verdict["objectives"]["serve-availability"]
            assert o["bad"] > 0  # the burst is in the history
            assert 0.0 < o["budget_burned_pct"] < 100.0
        finally:
            stop.set()
            router.stop()
            h.stop()
            for rep in reps:
                rep.close()
