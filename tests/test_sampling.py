"""Per-request sampling: temperature / top-k / top-p (VERDICT r3 #7).

The dynamic ``sample_logits`` (one compiled variant, per-row device params)
against hand-computable distributions, and the engine's per-slot path: mixed
greedy + sampled requests decoding in the same batch.
"""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models.generate import sample_logits
from tony_tpu.models.llama import LLAMA_TINY, init
from tony_tpu.models.serving import ContinuousBatcher


def _counts(fn, n=300):
    out = {}
    for i in range(n):
        t = int(fn(jax.random.PRNGKey(i))[0])
        out[t] = out.get(t, 0) + 1
    return out


class TestSampleLogits:
    LOGITS = jnp.asarray([[3.0, 2.0, 1.0, 0.0, -10.0]])

    def _one(self, temp, k, p):
        return lambda key: sample_logits(
            self.LOGITS, key,
            jnp.asarray([temp], jnp.float32),
            jnp.asarray([k], jnp.int32),
            jnp.asarray([p], jnp.float32),
        )

    @pytest.mark.slow
    def test_greedy_row(self):
        assert _counts(self._one(0.0, 0, 0.0), n=5) == {0: 5}

    def test_top_k_restricts_support(self):
        got = _counts(self._one(1.0, 2, 0.0))
        assert set(got) <= {0, 1} and len(got) == 2  # only the top-2 tokens

    def test_top_p_restricts_support(self):
        # softmax([3,2,1,0,-10]) ≈ [.66,.24,.09,.03,~0]; p=.7 keeps {0,1}
        got = _counts(self._one(1.0, 0, 0.7))
        assert set(got) <= {0, 1} and len(got) == 2

    def test_top_p_one_keeps_all_support(self):
        got = _counts(self._one(2.0, 0, 1.0), n=600)
        assert set(got) >= {0, 1, 2, 3}  # p=1 → no nucleus cut

    def test_rows_are_independent(self):
        logits = jnp.tile(self.LOGITS, (3, 1))
        toks = sample_logits(
            logits, jax.random.PRNGKey(0),
            jnp.asarray([0.0, 1.0, 0.0], jnp.float32),
            jnp.asarray([0, 2, 0], jnp.int32),
            jnp.asarray([0.0, 0.0, 0.0], jnp.float32),
        )
        assert int(toks[0]) == 0 and int(toks[2]) == 0  # greedy rows
        assert int(toks[1]) in (0, 1)                   # top-2 sampled row

    def test_matches_static_sampler_distribution(self):
        # same key, same effective params → identical draw as _sample
        from tony_tpu.models.generate import _sample

        key = jax.random.PRNGKey(7)
        logits = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
        want = _sample(logits, key, 0.8, 3)
        got = sample_logits(
            logits, key,
            jnp.full((4,), 0.8, jnp.float32),
            jnp.full((4,), 3, jnp.int32),
            jnp.zeros((4,), jnp.float32),
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestEnginePerSlotSampling:
    def test_mixed_greedy_and_sampled_slots(self):
        params = init(jax.random.PRNGKey(0), LLAMA_TINY)
        eng = ContinuousBatcher(params, LLAMA_TINY, num_slots=3, max_len=64,
                                decode_chunk=4)
        # greedy reference from a pure-greedy engine
        ref_eng = ContinuousBatcher(params, LLAMA_TINY, num_slots=3, max_len=64,
                                    decode_chunk=4)
        ref = ref_eng.run() if False else None  # noqa: F841 — layout aid
        g_ref = ref_eng.submit([1, 2, 3], max_new_tokens=8)
        ref_out = ref_eng.run()

        g = eng.submit([1, 2, 3], max_new_tokens=8)                      # default greedy
        s1 = eng.submit([4, 5], max_new_tokens=8, temperature=1.0, top_k=5)
        s2 = eng.submit([6, 7], max_new_tokens=8, temperature=0.9, top_p=0.8)
        out = eng.run()
        # the greedy slot is EXACTLY the pure-greedy engine's output even
        # while sampled slots decode alongside it
        assert out[g] == ref_out[g_ref]
        assert len(out[s1]) == 8 and len(out[s2]) == 8
        vocab = LLAMA_TINY.vocab_size
        assert all(0 <= t < vocab for t in out[s1] + out[s2])

    def test_per_request_override_validation(self):
        params = init(jax.random.PRNGKey(0), LLAMA_TINY)
        eng = ContinuousBatcher(params, LLAMA_TINY, num_slots=1, max_len=32)
        import pytest

        with pytest.raises(ValueError, match="top_p"):
            eng.submit([1], max_new_tokens=1, top_p=1.5)
        # 0.0 is the internal "no nucleus cut" sentinel — a client sending
        # it would silently get the FULL distribution, so it is rejected
        with pytest.raises(ValueError, match="top_p"):
            eng.submit([1], max_new_tokens=1, top_p=0.0)
        with pytest.raises(ValueError, match="temperature"):
            eng.submit([1], max_new_tokens=1, temperature=-1)
