"""Indexed ↔ reference scheduler parity (docs/performance.md "Scheduler
pass").

PR 14 rebuilt `PreemptionPolicy.schedule` around indices; the hard contract
is DECISION-TRACE EQUALITY with the kept :class:`ReferencePolicy` oracle.
Three layers prove it:

- a property suite over thousands of seeded random worlds — mixed shares,
  priorities, budgets, grace, min-runtime protection, elastic contracts,
  shrink histories, unknown queues, held>demand claims — asserting the two
  implementations return equal :class:`Decision`\\s, mutate their views
  identically, and leave identical budget charge logs; plus adversarial
  orderings where queue heads tie on ``(used/share, sort_key)`` and where
  duplicate seqs force the stable-sort tiebreak;
- :class:`WorldIndex` consistency — after every simulator event the index's
  heaps/victim orders/counters/claim sums are audited against a brute-force
  recompute, and lazily-deleted entries can never resurface;
- the end-to-end half: ``run_parity`` (and the ``tony sim --parity`` CLI)
  replays every arrival mix through both policies and diffs decision traces
  event-by-event.

Plus the pool-level incrementality contract: an unchanged-world tick builds
zero views and skips the pass outright, and the
``tony.pool.scheduler.indexed=false`` kill switch restores the reference
implementation verbatim.
"""

import random
from dataclasses import replace

import pytest

from tony_tpu.cluster.policy import (
    AppView,
    PreemptionPolicy,
    ReferencePolicy,
    WorldIndex,
    make_policy,
)
from tony_tpu.cluster.sim import (
    GB,
    MIXES,
    PoolSimulator,
    diff_traces,
    generate_jobs,
    run_parity,
)

pytestmark = pytest.mark.sched

NOW = 1000.0  # the injected policy clock for every generated world


def make_world(seed: int):
    """One seeded random world: (queues, totals, views, policy kwargs).

    Deliberately hostile: unknown queues, held exceeding demand, protected
    and shrink-pending victims, elastic slack, tight budgets, zero-demand
    dimensions — every guard the pass consults gets exercised."""
    rng = random.Random(seed)
    nq = rng.randint(1, 4)
    shares = [rng.choice([0.1, 0.2, 0.25, 0.3, 0.5]) for _ in range(nq)]
    s = sum(shares)
    if s > 1.0:
        shares = [int(x / s * 1e6) / 1e6 for x in shares]  # truncate, never > 1
    queues = {f"q{i}": shares[i] for i in range(nq)}
    chips = rng.choice([0, rng.randint(4, 32)])
    totals = (rng.randint(4, 64) << 30, rng.randint(8, 128), chips)
    views = []
    for i in range(rng.randint(3, 40)):
        d = (rng.randint(0, 8) << 30, rng.randint(0, 8),
             rng.randint(0, 6) if chips else 0)
        admitted = rng.random() < 0.4
        held = tuple(
            x + ((rng.randint(0, 2) << 30) if j == 0 else rng.randint(0, 2))
            if rng.random() < 0.2 else (x if rng.random() < 0.7 else 0)
            for j, x in enumerate(d)
        )
        elastic = rng.random() < 0.4
        views.append(AppView(
            app_id=f"a{i}",
            queue=f"q{rng.randrange(nq)}" if rng.random() < 0.9 else "ghost",
            priority=rng.choice([0, 0, 1, 2, 5]),
            seq=i,
            demand=d,
            held=held if admitted else (held if rng.random() < 0.2 else (0, 0, 0)),
            admitted=admitted,
            preempted=rng.random() < 0.1,
            wait_since=NOW - rng.uniform(0, 20),
            admitted_at=NOW - rng.uniform(0, 30) if admitted else 0.0,
            elastic_unit=(1 << 30, 1, 1 if chips else 0) if elastic else (0, 0, 0),
            elastic_slack=rng.randint(0, 3) if elastic else 0,
            shrink_pending=rng.random() < 0.1,
        ))
    kwargs = dict(
        preemption=rng.random() < 0.8,
        grace_ms=rng.choice([0, 1_000, 5_000]),
        min_runtime_ms=rng.choice([0, 2_000, 10_000]),
        eviction_budget=rng.choice([0, 0, 1, 3]),
        budget_window_ms=60_000,
        clock=lambda: NOW,
    )
    return queues, totals, views, kwargs


def assert_parity(queues, totals, views, kwargs):
    va = [replace(v) for v in views]
    vb = [replace(v) for v in views]
    ref = ReferencePolicy(queues, **kwargs)
    idx = PreemptionPolicy(queues, **kwargs)
    da = ref.schedule(va, totals)
    db = idx.schedule(vb, totals)
    assert da == db, f"decisions diverge:\n ref: {da}\n idx: {db}"
    assert va == vb, "view mutations diverge: " + "; ".join(
        f"{x} != {y}" for x, y in zip(va, vb) if x != y)
    assert ref._charges == idx._charges, "budget charge logs diverge"
    return da


# ---------------------------------------------------------------------------
# decision-equality property suite
# ---------------------------------------------------------------------------
class TestDecisionEquality:
    def test_2000_seeded_worlds(self):
        """The headline contract: 2000+ random worlds, byte-identical
        decisions, identical view mutations, identical charge logs."""
        nonempty = 0
        for seed in range(2200):
            queues, totals, views, kwargs = make_world(seed)
            decision = assert_parity(queues, totals, views, kwargs)
            if not decision.empty():
                nonempty += 1
        # the suite must actually exercise decisions, not vacuous worlds
        assert nonempty > 500

    def test_heads_tying_on_ratio_break_by_sort_key(self):
        """Adversarial ordering: two queues with equal shares and equal
        (zero) usage — eligibility ratios tie exactly; (priority, seq) must
        decide, identically in both implementations."""
        queues = {"qa": 0.5, "qb": 0.5}
        totals = (8 << 30, 16, 0)
        views = [
            AppView(app_id="late-hi", queue="qa", priority=5, seq=10,
                    demand=(1 << 30, 1, 0), wait_since=NOW - 10),
            AppView(app_id="early-lo", queue="qb", priority=0, seq=1,
                    demand=(1 << 30, 1, 0), wait_since=NOW - 10),
        ]
        d = assert_parity(queues, totals, views,
                          dict(preemption=True, clock=lambda: NOW))
        # higher priority wins the tie despite the later seq
        assert d.admit[0] == "late-hi"

    def test_equal_nonzero_usage_ratio_tie(self):
        """Ratio ties with NONZERO usage: both queues at the same used/share
        — admit order must still be identical (and FIFO within priority)."""
        queues = {"qa": 0.5, "qb": 0.5}
        totals = (8 << 30, 64, 0)
        views = [
            AppView(app_id="run-a", queue="qa", seq=0, admitted=True,
                    demand=(2 << 30, 1, 0), held=(2 << 30, 1, 0),
                    admitted_at=NOW - 100),
            AppView(app_id="run-b", queue="qb", seq=1, admitted=True,
                    demand=(2 << 30, 1, 0), held=(2 << 30, 1, 0),
                    admitted_at=NOW - 100),
            AppView(app_id="wait-b", queue="qb", seq=2,
                    demand=(1 << 30, 1, 0), wait_since=NOW - 10),
            AppView(app_id="wait-a", queue="qa", seq=3,
                    demand=(1 << 30, 1, 0), wait_since=NOW - 10),
        ]
        d = assert_parity(queues, totals, views,
                          dict(preemption=True, clock=lambda: NOW))
        assert d.admit == ["wait-b", "wait-a"]  # equal ratios → FIFO by seq

    def test_duplicate_seq_stable_order(self):
        """Two same-queue waiters with IDENTICAL sort keys: the reference's
        stable sort admits them in list order — the index's insertion-order
        tiebreak must reproduce exactly that."""
        queues = {"q": 1.0}
        totals = (4 << 30, 8, 0)
        views = [
            AppView(app_id="first", queue="q", priority=1, seq=7,
                    demand=(1 << 30, 1, 0), wait_since=NOW - 5),
            AppView(app_id="second", queue="q", priority=1, seq=7,
                    demand=(1 << 30, 1, 0), wait_since=NOW - 5),
        ]
        d = assert_parity(queues, totals, views,
                          dict(preemption=True, clock=lambda: NOW))
        assert d.admit == ["first", "second"]

    def test_duplicate_seq_worlds(self):
        """400 worlds with seqs drawn from {0..3}: sort keys collide
        constantly, so every tie falls to the stable-order tiebreak —
        including apps admitted then evicted mid-pass, whose sticky
        insertion rank must restore their original stable position."""
        for seed in range(400):
            queues, totals, views, kwargs = make_world(seed + 10_000)
            rng = random.Random(seed)
            for v in views:
                v.seq = rng.randrange(4)
            assert_parity(queues, totals, views, kwargs)

    def test_budget_and_protection_worlds(self):
        """Focused re-run of the property over parameter corners the random
        mix visits rarely: budget=1 with many would-be victims, and
        min-runtime protecting every victim."""
        for seed in range(300):
            queues, totals, views, kwargs = make_world(seed)
            kwargs.update(preemption=True, eviction_budget=1)
            assert_parity(queues, totals, views, kwargs)
            kwargs.update(eviction_budget=0, min_runtime_ms=10_000_000)
            assert_parity(queues, totals, views, kwargs)


# ---------------------------------------------------------------------------
# WorldIndex consistency
# ---------------------------------------------------------------------------
class TestWorldIndex:
    def test_lazy_deleted_entries_never_resurface(self):
        w = WorldIndex()
        v = AppView(app_id="a", queue="q", seq=1, demand=(1, 1, 0))
        w.adopt(v)
        assert w.head("q") is v
        w.remove("a")
        assert w.head("q") is None
        # re-adopt the SAME object (the simulator's die→requeue path): the
        # stale first-life entry must not satisfy head() twice
        w.adopt(v)
        assert w.head("q") is v
        v.admitted = True
        w.note_admitted(v)
        assert w.head("q") is None
        assert [x.app_id for x in w.victims_iter("q")] == ["a"]
        v.admitted = False
        w.note_evicted(v)
        assert w.head("q") is v
        assert list(w.victims_iter("q")) == []
        assert w.audit([v]) == []

    def test_upsert_rebuckets_and_reaccounts(self):
        w = WorldIndex()
        fields = dict(queue="qa", priority=0, seq=1, demand=(4, 2, 0),
                      held=(0, 0, 0), admitted=False, preempted=False,
                      wait_since=0.0, admitted_at=0.0,
                      elastic_unit=(0, 0, 0), elastic_slack=0,
                      shrink_pending=False)
        w.upsert("a", **fields)
        assert w.waiting_count("qa") == 1 and w.claims == [0, 0, 0]
        ver = w.version
        w.upsert("a", **fields)  # no-op: version must not move
        assert w.version == ver
        w.upsert("a", **{**fields, "admitted": True, "held": (6, 1, 0)})
        assert w.waiting_count("qa") == 0
        assert w.claims == [6, 2, 0]  # elementwise max(demand, held)
        w.upsert("a", **{**fields, "admitted": True, "held": (6, 1, 0), "queue": "qb"})
        assert w.queue_claims["qa"] == [0, 0, 0]
        assert w.queue_claims["qb"] == [6, 2, 0]
        v = w.views["a"]
        assert w.audit([v]) == []
        w.remove("a")
        assert w.audit([]) == []
        assert w.claims == [0, 0, 0]

    def test_audit_catches_a_cooked_index(self):
        """Prove the auditor audits: silently flipping a view's admitted
        flag (bypassing the choke points) must be reported."""
        w = WorldIndex()
        v = AppView(app_id="a", queue="q", seq=1, demand=(1, 0, 0))
        w.adopt(v)
        v.admitted = True  # mutation NOT flowed through note_admitted
        assert w.audit([v]) != []

    @pytest.mark.parametrize("mix", MIXES)
    @pytest.mark.parametrize("chips", [0, 12])
    def test_index_consistent_after_every_sim_event(self, mix, chips):
        """The simulator feeds the WorldIndex through every event handler;
        audit() recomputes heaps/counters/claims brute-force after EACH
        event — thousands of arrival/admit/evict/die/shed transitions.
        Chip-bearing totals matter: chips flip the primary share dimension
        and make evict-AND-readmit-in-one-pass decisions common (an
        overshooting preemption refits its own victim), the path where a
        membership bug once hid."""
        queues = {"prod": 0.5, "dev": 0.3, "batch": 0.2}
        sim = PoolSimulator(
            queues, (8 * GB, 256, chips), preemption=True, grace_ms=2_000,
            drain_ms=5_000, min_runtime_ms=3_000, seed=18, verify_index=True,
        )
        report = sim.run(generate_jobs(mix, 250, queues, 18))
        assert report.ok(), report.violations[:5]


# ---------------------------------------------------------------------------
# end-to-end: all four mixes through both policies (the --parity contract)
# ---------------------------------------------------------------------------
class TestSimParity:
    @pytest.mark.parametrize("mix", MIXES)
    def test_mix_parity_1000_arrivals(self, mix):
        idx_rep, ref_rep, diff = run_parity(mix, 1000, seed=0)
        assert diff is None, diff
        assert idx_rep.ok(), idx_rep.violations[:5]
        assert ref_rep.ok(), ref_rep.violations[:5]

    @pytest.mark.parametrize("mix", MIXES)
    def test_mix_parity_chip_primary(self, mix):
        """Chips as the primary share dimension (and the
        evict-then-readmit-in-one-pass decisions it provokes) must hold
        trace parity too."""
        queues = {"prod": 0.5, "dev": 0.3, "batch": 0.2}
        idx_rep, ref_rep, diff = run_parity(
            mix, 400, seed=18, queues=queues, totals=(8 * GB, 256, 12))
        assert diff is None, diff

    def test_diff_traces_reports_first_divergence(self):
        a = [(3, "arrive", "x", 1.0, ("x",), (), ())]
        b = [(3, "arrive", "x", 1.0, ("y",), (), ())]
        msg = diff_traces(a, b)
        assert msg is not None and "event 3" in msg and "x" in msg and "y" in msg
        assert diff_traces(a, list(a)) is None
        msg = diff_traces(a, a + [(9, "tick", "", 2.0, ("z",), (), ())])
        assert "lengths differ" in msg and "event 9" in msg

    def test_parity_cli_all_mixes(self, capsys):
        from tony_tpu.cli.sim import main as sim_main

        rc = sim_main(["--parity", "--jobs", "150", "--seed", "5"])
        out = capsys.readouterr().out
        assert rc == 0
        assert out.count("parity OK") == len(MIXES)

    def test_sim_policy_flag_reference(self, capsys):
        from tony_tpu.cli.sim import main as sim_main

        rc = sim_main(["--mix", "batch", "--jobs", "120", "--seed", "2",
                       "--policy", "reference"])
        out = capsys.readouterr().out
        assert rc == 0 and "invariants: OK" in out


# ---------------------------------------------------------------------------
# pool-level incrementality + the kill switch
# ---------------------------------------------------------------------------
class TestPoolWorldIndex:
    def test_unchanged_world_tick_does_zero_view_rebuilds(self):
        from tony_tpu.cluster.pool import PoolService

        svc = PoolService(secret="s", queues={"default": 1.0})
        try:
            svc.register_node("n0", "127.0.0.1", 1,
                              memory_bytes=8 * GB, vcores=16)
            svc.register_app("app_a", queue="default",
                             memory_bytes=GB, vcores=1)
            svc.register_app("app_b", queue="default",
                             memory_bytes=GB, vcores=1)
            world = svc._world
            assert world is not None
            passes = []
            orig = svc._policy.schedule_world
            svc._policy.schedule_world = (
                lambda *a, **k: (passes.append(1), orig(*a, **k))[1])
            created, version = world.views_created, world.version
            with svc._lock:
                svc._schedule_locked()  # settles: empty decision recorded
            with svc._lock:
                svc._schedule_locked()  # unchanged world: skipped outright
                svc._schedule_locked()
            assert world.views_created == created  # zero view rebuilds
            assert world.version == version
            assert len(passes) == 1  # only the settling pass actually ran
        finally:
            svc.stop()

    def test_world_views_track_canonical_state(self):
        from tony_tpu.cluster.pool import PoolService

        svc = PoolService(secret="s", queues={"default": 1.0})
        try:
            svc.register_node("n0", "127.0.0.1", 1,
                              memory_bytes=4 * GB, vcores=8)
            svc.register_app("app_a", queue="default",
                             memory_bytes=GB, vcores=1)
            got = svc.allocate("app_a", "worker", 0, GB, 1)
            assert "id" in got
            v = svc._world.views["app_a"]
            assert v.admitted and v.held == (GB, 1, 0)
            svc.release("app_a", got["id"])
            assert svc._world.views["app_a"].held == (0, 0, 0)
            svc.release_all("app_a")
            assert "app_a" not in svc._world.views
        finally:
            svc.stop()

    def test_kill_switch_restores_reference_policy(self):
        from tony_tpu.cluster.pool import PoolService

        svc = PoolService(secret="s", queues={"default": 1.0},
                          scheduler_indexed=False)
        try:
            assert type(svc._policy) is ReferencePolicy
            assert svc._world is None
            svc.register_node("n0", "127.0.0.1", 1,
                              memory_bytes=4 * GB, vcores=8)
            got = svc.register_app("app_a", queue="default",
                                   memory_bytes=GB, vcores=1)
            assert got["admitted"]  # the reference path still schedules
        finally:
            svc.stop()

    def test_make_policy_rejects_unknown_impl(self):
        with pytest.raises(ValueError, match="unknown policy"):
            make_policy("quantum", {"default": 1.0})
