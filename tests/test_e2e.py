"""End-to-end lifecycle tests: submit → AM → executors → user processes.

The TestTonyE2E analog (SURVEY.md §4): no real cluster — the
LocalResourceManager realizes containers as local subprocesses, and the
"training" workloads are the tiny fixture scripts in tests/fixtures/
asserting on the env contract, exactly the reference's strategy.
"""

import os
import sys
import time

import pytest

from tony_tpu import compat, constants
from tony_tpu.config import TonyConfig, keys
from tony_tpu.cluster.client import Client
from tony_tpu.cluster.session import JobStatus
from tony_tpu.cluster import history

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")

FAST = {
    keys.AM_MONITOR_INTERVAL_MS: "50",
    keys.TASK_HEARTBEAT_INTERVAL_MS: "100",
    keys.AM_GANG_TIMEOUT_MS: "30000",
}


def fixture_cmd(name: str) -> str:
    return f"{sys.executable} {os.path.join(FIXTURES, name)}"


def run_job(tmp_tony_root, conf: dict) -> tuple[JobStatus, Client, object]:
    cfg = TonyConfig({**FAST, keys.STAGING_ROOT: str(tmp_tony_root), **conf})
    client = Client(cfg)
    handle = client.submit()
    final = client.monitor_application(handle, quiet=True)
    return final, client, handle


@pytest.mark.e2e
class TestLifecycle:
    def test_single_worker_success(self, tmp_tony_root):
        final, _, handle = run_job(
            tmp_tony_root,
            {"tony.worker.instances": "1", keys.EXECUTES: fixture_cmd("exit_0.py")},
        )
        assert final == JobStatus.SUCCEEDED
        status = handle.final_status()
        assert status["tasks"][0]["exit_code"] == 0

    def test_multi_worker_gang(self, tmp_tony_root):
        final, _, handle = run_job(
            tmp_tony_root,
            {"tony.worker.instances": "3", keys.EXECUTES: fixture_cmd("check_env.py"),
             keys.APPLICATION_FRAMEWORK: "tensorflow"},
        )
        assert final == JobStatus.SUCCEEDED, handle.final_status()

    def test_failure_fails_job(self, tmp_tony_root):
        final, _, handle = run_job(
            tmp_tony_root,
            {"tony.worker.instances": "1", keys.EXECUTES: fixture_cmd("exit_1.py")},
        )
        assert final == JobStatus.FAILED
        assert handle.final_status()["tasks"][0]["exit_code"] == 1

    def test_untracked_forever_task_killed_at_end(self, tmp_tony_root):
        # ps (untracked) sleeps forever; job ends when the tracked worker exits
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "1",
                "tony.ps.instances": "1",
                keys.EXECUTES: fixture_cmd("exit_0.py"),
                "tony.ps.command": fixture_cmd("forever.py"),
            },
        )
        assert final == JobStatus.SUCCEEDED
        statuses = {f"{t['name']}": t["status"] for t in handle.final_status()["tasks"]}
        assert statuses["worker"] == "SUCCEEDED"
        assert statuses["ps"] in ("KILLED", "FAILED")

    def test_history_written(self, tmp_tony_root):
        final, _, handle = run_job(
            tmp_tony_root,
            {"tony.worker.instances": "1", keys.EXECUTES: fixture_cmd("exit_0.py")},
        )
        assert final == JobStatus.SUCCEEDED
        history_root = os.path.join(str(tmp_tony_root), "history")
        jobs = history.list_finished_jobs(history_root)
        assert [j.app_id for j in jobs] == [handle.app_id]
        assert jobs[0].status == "SUCCEEDED"
        types = [e.type.value for e in history.read_events(history_root, handle.app_id)]
        assert types[0] == "APPLICATION_INITED"
        assert "GANG_COMPLETE" in types
        assert types[-1] == "APPLICATION_FINISHED"
        # frozen config snapshot alongside (config.json)
        dest = history.finished_dir(history_root, handle.app_id, jobs[0].completed_ms)
        assert os.path.exists(os.path.join(dest, constants.CONFIG_SNAPSHOT_FILE))

    def test_task_logs_captured(self, tmp_tony_root):
        final, _, handle = run_job(
            tmp_tony_root,
            {"tony.worker.instances": "1", keys.EXECUTES: fixture_cmd("exit_0.py")},
        )
        assert final == JobStatus.SUCCEEDED
        log = os.path.join(handle.staging_dir, constants.TASK_LOG_DIRNAME, "worker_0", "stdout.log")
        assert "fixture: ok" in open(log).read()


#: the two multi-process gangs below run REAL cross-process collectives on
#: the CPU backend — a jax without gloo CPU collectives aborts them with
#: "Multiprocess computations aren't implemented on the CPU backend", which
#: is an environment capability gap, not a tony regression (the
#: single-process SPMD and lifecycle e2es still cover the contract)
_needs_mp_cpu = pytest.mark.skipif(
    not compat.multiprocess_cpu_supported(),
    reason="this jax lacks cross-process CPU collectives "
           "(compat.multiprocess_cpu_supported)")


@pytest.mark.e2e
@_needs_mp_cpu
class TestDistributedDataPlane:
    def test_gang_forms_jax_process_group_and_reduces(self, tmp_tony_root):
        """The distributed-backend proof: a tony-launched 2-worker gang joins
        one jax.distributed group from the injected env and a cross-process
        collective produces the right value on every rank."""
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "2",
                keys.EXECUTES: fixture_cmd("jax_allreduce.py"),
                keys.APPLICATION_FRAMEWORK: "jax",
                # jax.distributed startup (gRPC coordination service) is slower
                # than the fixture scripts; give the gang room
                keys.AM_GANG_TIMEOUT_MS: "60000",
            },
        )
        assert final == JobStatus.SUCCEEDED, handle.final_status()


@pytest.mark.e2e
@_needs_mp_cpu
class TestMultiProcessSpmdTraining:
    def test_gang_trains_one_model_over_global_mesh(self, tmp_tony_root):
        """Full multi-host training proof: each of 2 workers owns 4 virtual
        devices; the sharded train step runs over the 8-device GLOBAL mesh
        with collectives crossing the process boundary."""
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "2",
                keys.EXECUTES: fixture_cmd("spmd_train.py"),
                keys.APPLICATION_FRAMEWORK: "jax",
                keys.AM_GANG_TIMEOUT_MS: "120000",
                # jax compile + distributed init is slower than fixtures;
                # generous heartbeat budget
                keys.TASK_MAX_MISSED_HEARTBEATS: "100",
            },
        )
        assert final == JobStatus.SUCCEEDED, handle.final_status()


@pytest.mark.e2e
class TestTorchRuntimeDataPlane:
    def test_gang_forms_torch_process_group_and_reduces(self, tmp_tony_root):
        """TorchRuntime parity proof: workers read only the injected DDP env
        (MASTER_ADDR/PORT, RANK, WORLD_SIZE, INIT_METHOD), form a real gloo
        process group, and all-reduce across the gang."""
        pytest.importorskip("torch")
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "2",
                keys.EXECUTES: fixture_cmd("torch_allreduce.py"),
                keys.APPLICATION_FRAMEWORK: "pytorch",
                keys.AM_GANG_TIMEOUT_MS: "60000",
            },
        )
        assert final == JobStatus.SUCCEEDED, handle.final_status()


@pytest.mark.e2e
class TestFailureDetection:
    def test_heartbeat_loss_marks_task_lost(self, tmp_tony_root):
        # chaos fault injection (tony.chaos.*): the hb-stall fault wedges the
        # executor — heartbeats stop while its process lives → AM declares LOST
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "1",
                keys.EXECUTES: fixture_cmd("forever.py"),
                keys.TASK_MAX_MISSED_HEARTBEATS: "3",
                keys.CHAOS_SPEC: "hb-stall:worker:0",
                keys.CHAOS_SEED: "7",
            },
        )
        assert final == JobStatus.FAILED
        assert handle.final_status()["tasks"][0]["status"] == "LOST"

    def test_gang_restart_resumes_training_from_checkpoint(self, tmp_tony_root):
        """Reliability spine (SURVEY.md §5.3/§5.4): a training task dies
        mid-run, the gang restarts, and the relaunched task RESUMES from its
        checkpoint instead of step 0 — verified by the verdict and the
        'resumed from checkpoint' line in the task's stdout."""
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "1",
                keys.EXECUTES: fixture_cmd("train_resume.py"),
                keys.TASK_RESTART_ON_FAILURE: "true",
            },
        )
        assert final == JobStatus.SUCCEEDED, handle.final_status()
        # the relaunched attempt logs under worker_0_r1 (restart suffix)
        log = os.path.join(
            str(tmp_tony_root), handle.app_id, "logs", "worker_0_r1", "stdout.log"
        )
        with open(log) as f:
            out = f.read()
        assert "resumed from checkpoint step" in out, out
        assert "resume run completed to step 8" in out, out

    def test_gang_restart_from_flaky_task(self, tmp_tony_root):
        # rebuild-only elasticity: whole-gang restart after a tracked failure
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "1",
                keys.EXECUTES: fixture_cmd("flaky.py"),
                keys.TASK_RESTART_ON_FAILURE: "true",
                keys.TASK_MAX_TOTAL_INSTANCE_FAILURES: "2",
            },
        )
        assert final == JobStatus.SUCCEEDED
        assert handle.final_status()["app_id"] == handle.app_id

    def test_kill_application(self, tmp_tony_root):
        cfg = TonyConfig(
            {
                **FAST,
                keys.STAGING_ROOT: str(tmp_tony_root),
                "tony.worker.instances": "1",
                keys.EXECUTES: fixture_cmd("forever.py"),
            }
        )
        client = Client(cfg)
        handle = client.submit()
        rpc = handle.rpc()
        assert rpc is not None
        # wait until the worker is running, then kill
        deadline = time.time() + 20
        while time.time() < deadline:
            infos = rpc.call("get_task_infos")
            if infos and infos[0]["status"] in ("REGISTERED", "RUNNING"):
                break
            time.sleep(0.1)
        assert Client.kill(handle)
        final = client.monitor_application(handle, quiet=True)
        assert final == JobStatus.KILLED


@pytest.mark.e2e
class TestSchedulingE2E:
    def test_dependency_ordering_ps_before_worker(self, tmp_tony_root):
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.ps.instances": "1",
                "tony.worker.instances": "1",
                "tony.ps.command": fixture_cmd("forever.py"),
                keys.EXECUTES: fixture_cmd("exit_0.py"),
                keys.dependency_key("worker", "ps"): "20s",
            },
        )
        assert final == JobStatus.SUCCEEDED
        # event order: ps TASK_STARTED strictly before worker TASK_STARTED
        history_root = os.path.join(str(tmp_tony_root), "history")
        evs = history.read_events(history_root, handle.app_id)
        started = [e.payload["task"] for e in evs if e.type.value == "TASK_STARTED"]
        assert started.index("ps:0") < started.index("worker:0")

    def test_allocation_failure_fails_job(self, tmp_tony_root):
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "2",
                "tony.worker.memory": "48g",   # 2x48g > 64g host
                keys.EXECUTES: fixture_cmd("exit_0.py"),
            },
        )
        assert final == JobStatus.FAILED
        assert "memory" in (handle.final_status().get("reason") or "")


@pytest.mark.e2e
class TestMultiSlicePool:
    def test_gang_spans_slices_with_placement_env(self, tmp_tony_root):
        # 4 workers x 4 chips on a pool of two v5e-8 slices: the gang MUST
        # spill onto the second slice, and every task sees the slice contract
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "4",
                "tony.worker.chips": "4",
                keys.TPU_POOL_SPEC: "pool:v5e-8x2",
                keys.EXECUTES: fixture_cmd("check_slice_env.py"),
            },
        )
        assert final == JobStatus.SUCCEEDED, handle.final_status()
        app_dir = os.path.join(str(tmp_tony_root), handle.app_id)
        placements = set()
        for root, _, files in os.walk(app_dir):
            for f in files:
                if f == "stdout.log":
                    with open(os.path.join(root, f)) as fh:
                        for line in fh:
                            if line.startswith("SLICE_PLACEMENT"):
                                placements.add(line.strip().split(" -> ")[1])
        assert placements == {"0", "1"}, placements

    def test_pool_too_small_fails_cleanly(self, tmp_tony_root):
        # a 16-chip task cannot fit an 8-chip slice: allocation must fail the
        # job (DCN-spanning single tasks are rejected), not hang the gang
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "1",
                "tony.worker.chips": "16",
                keys.TPU_POOL_SPEC: "pool:v5e-8x2",
                keys.EXECUTES: fixture_cmd("exit_0.py"),
            },
        )
        assert final == JobStatus.FAILED


@pytest.mark.e2e
class TestVenvArchive:
    def test_venv_zip_staged_and_activated(self, tmp_tony_root, tmp_path):
        # build a fake venv archive: bin/activate marker + bin/ on PATH
        import zipfile

        venv_src = tmp_path / "venv" / "bin"
        venv_src.mkdir(parents=True)
        probe = venv_src / "tony-venv-probe"
        probe.write_text("#!/bin/sh\necho venv-probe-ran\n")
        probe.chmod(0o755)
        archive = tmp_path / "venv.zip"
        with zipfile.ZipFile(archive, "w") as z:
            # z.write records each file's on-disk mode in external_attr
            # (the probe is 0755), which the unpacker must restore
            for p in (tmp_path / "venv").rglob("*"):
                z.write(p, p.relative_to(tmp_path))

        out_file = tmp_path / "which.txt"
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "1",
                keys.PYTHON_VENV: str(archive),
                keys.EXECUTES: (
                    # EXECUTE the probe (not just resolve it): catches zip
                    # extraction dropping the executable bit
                    f"bash -c 'tony-venv-probe > {out_file} && "
                    f"command -v tony-venv-probe >> {out_file} && "
                    f"echo VIRTUAL_ENV=$VIRTUAL_ENV >> {out_file}'"
                ),
            },
        )
        assert final == JobStatus.SUCCEEDED, handle.final_status()
        text = out_file.read_text()
        # the probe RAN from the unpacked archive inside staging, and
        # VIRTUAL_ENV points there too
        assert "venv-probe-ran" in text
        assert "/venv/worker_0" in text and "tony-venv-probe" in text
        assert "VIRTUAL_ENV=" in text and str(tmp_tony_root) in text
