"""Container-runtime (docker) passthrough: AM env injection + RM command wrap.

Mirrors the reference's tony.docker.* behavior (SURVEY.md §2.1 "Docker
support"): TonY sets YARN docker-runtime envs; here the AM sets the analog
envs and the ResourceManager (NM analog) rewrites the launch command.
"""

import json
import os
import sys

import pytest

from tony_tpu import constants
from tony_tpu.config import TonyConfig, keys
from tony_tpu.cluster.client import Client
from tony_tpu.cluster.resources import _docker_wrap
from tony_tpu.cluster.session import JobStatus

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


class TestDockerWrap:
    def _env(self, **extra):
        return {
            constants.ENV_CONTAINER_RUNTIME_TYPE: "docker",
            constants.ENV_CONTAINER_RUNTIME_IMAGE: "tf:latest",
            constants.ENV_STAGING_DIR: "/stage/app1",
            "TONY_APP_ID": "app1",
            "HOME": "/root",  # must NOT be forwarded
            **extra,
        }

    def test_wraps_command_with_image_and_mount(self):
        cmd = _docker_wrap(["python", "-m", "x"], self._env())
        assert cmd[0] == "docker" and cmd[1] == "run"
        assert "tf:latest" in cmd
        assert cmd[-3:] == ["python", "-m", "x"]
        assert "/stage/app1:/stage/app1" in cmd

    def test_forwards_contract_env_only(self):
        cmd = _docker_wrap(["x"], self._env())
        joined = " ".join(cmd)
        assert "TONY_APP_ID=app1" in joined
        assert "HOME=" not in joined

    def test_secret_never_on_command_line(self):
        cmd = _docker_wrap(["x"], self._env(TONY_AM_SECRET="hunter2"))
        assert "hunter2" not in " ".join(cmd)  # /proc/<pid>/cmdline is world-readable
        assert "TONY_AM_SECRET" in cmd  # bare -e KEY: inherited from client env

    def test_missing_image_raises(self):
        env = self._env()
        env[constants.ENV_CONTAINER_RUNTIME_IMAGE] = ""
        with pytest.raises(ValueError, match="no image"):
            _docker_wrap(["x"], env)


@pytest.mark.e2e
class TestDockerE2E:
    def test_job_runs_inside_fake_docker(self, tmp_tony_root, monkeypatch):
        log = os.path.join(str(tmp_tony_root), "docker_invocations.jsonl")
        monkeypatch.setenv("FAKE_DOCKER_LOG", log)
        cfg = TonyConfig({
            keys.AM_MONITOR_INTERVAL_MS: "50",
            keys.STAGING_ROOT: str(tmp_tony_root),
            "tony.worker.instances": "1",
            keys.EXECUTES: f"{sys.executable} {os.path.join(FIXTURES, 'exit_0.py')}",
            keys.DOCKER_ENABLED: "true",
            keys.DOCKER_IMAGE: "my-train-image:1.0",
            keys.DOCKER_BINARY: os.path.join(FIXTURES, "fake_docker.py"),
        })
        client = Client(cfg)
        handle = client.submit()
        final = client.monitor_application(handle, quiet=True)
        assert final == JobStatus.SUCCEEDED, handle.final_status()
        with open(log) as f:
            inv = json.loads(f.readline())
        assert inv["image"] == "my-train-image:1.0"
        # the image's python runs the executor (host interpreter path would
        # not exist inside the image); the repo is bind-mounted read-only
        assert inv["command"][0] == "python"
        assert any(m.endswith(":ro") for m in inv["mounts"])
