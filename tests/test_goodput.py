"""Goodput accounting, straggler detection, and the alert engine.

Covers the phase ledger's exact-partition contract (unit + randomized
property test), restart-rework and resize attribution, the straggler
detector's streak/median semantics, the alert engine's edge-triggered
transitions + sink, the history-store goodput columns and finalized-job
alert evaluation, the `tony goodput` CLI, and the headline e2e: a fixture
gang under chaos (one gang restart + one elastic resize) whose `tony
goodput` report partitions wall-time exactly, attributes the restart's lost
work to ``restart_rework``, flags the injected slow rank as a straggler,
and fires + resolves a configured goodput alert visible in portal
``/alerts``, the event stream, and the history store.
"""

import json
import os
import random
import threading
import time
import urllib.request

import pytest

from tony_tpu.cluster.events import Event, EventType
from tony_tpu.config import TonyConfig, keys
from tony_tpu.obs import alerts as obs_alerts
from tony_tpu.obs import goodput as obs_goodput

pytestmark = [pytest.mark.goodput]


def ev(t, ts, **payload):
    return Event(EventType(t), payload, ts)


def snap(ts, **task_steps):
    return ev("METRICS_SNAPSHOT", ts, tasks=[
        {"task": task, "metrics": {"train": {"step": step}}}
        for task, step in task_steps.items()
    ])


def assert_exact(ledger):
    """THE invariant: phases are non-overlapping and sum to wall-time."""
    assert sum(ledger.phases_ms.values()) == ledger.wall_ms
    covered = 0
    prev_end = ledger.t0_ms
    for phase, start, end in ledger.episodes:
        assert start == prev_end, "episodes must tile [t0, t1] with no gaps"
        assert end > start
        assert phase in obs_goodput.PHASE_ORDER
        covered += end - start
        prev_end = end
    if ledger.episodes:
        assert prev_end == ledger.t1_ms
    assert covered == ledger.wall_ms


# ---------------------------------------------------------------------------
# ledger units
# ---------------------------------------------------------------------------
class TestLedger:
    def test_simple_lifecycle(self):
        events = [
            ev("APPLICATION_INITED", 1000),
            ev("QUEUE_WAIT", 1000, state="waiting"),
            ev("QUEUE_WAIT", 3000, state="admitted"),
            ev("TASK_STARTED", 3100, task="worker:0"),
            ev("TASK_REGISTERED", 3500, task="worker:0"),
            ev("GANG_COMPLETE", 4000, tasks=1),
            snap(6000, **{"worker:0": 3}),
            ev("TASK_FINISHED", 9000, task="worker:0", exit_code=0),
            ev("APPLICATION_FINISHED", 9500, status="SUCCEEDED"),
        ]
        led = obs_goodput.build_ledger("a", events)
        assert_exact(led)
        assert not led.live
        assert led.phases_ms["queue_wait"] == 2000
        assert led.phases_ms["startup"] == 500       # 3000→3500 (reg takes over)
        assert led.phases_ms["registration"] == 500  # 3500→4000
        assert led.phases_ms["compile"] == 2000      # gang → first step evidence
        assert led.phases_ms["productive"] == 3000   # 6000→9000
        assert led.phases_ms["drain"] == 500
        assert 0 < led.goodput_fraction < 1

    def test_live_requires_now(self):
        events = [ev("APPLICATION_INITED", 1000), ev("GANG_COMPLETE", 2000)]
        with pytest.raises(ValueError, match="now_ms"):
            obs_goodput.build_ledger("a", events)
        led = obs_goodput.build_ledger("a", events, now_ms=5000)
        assert led.live and led.t1_ms == 5000
        assert_exact(led)
        # no step evidence: everything after the barrier counts productive
        assert led.phases_ms["productive"] == 3000

    def test_unterminated_queue_wait_runs_to_now(self):
        events = [ev("QUEUE_WAIT", 1000, state="waiting")]
        led = obs_goodput.build_ledger("a", events, now_ms=4000)
        assert_exact(led)
        assert led.phases_ms["queue_wait"] == 3000

    def test_restart_rework_attribution(self):
        events = [
            ev("APPLICATION_INITED", 100),  # ts 0 would be re-stamped to now
            ev("GANG_COMPLETE", 1000),
            snap(2000, **{"worker:0": 2}),
            snap(4000, **{"worker:0": 4}),   # last checkpoint was at step 3
            snap(6000, **{"worker:0": 6}),
            ev("HEARTBEAT_LOST", 7000, reason="gang restart: task worker:1 LOST"),
            ev("GANG_COMPLETE", 8000),
            snap(9000, **{"worker:0": 4}),   # resumed from ckpt step 3 → step 4
            ev("APPLICATION_FINISHED", 12000, status="SUCCEEDED"),
        ]
        led = obs_goodput.build_ledger("a", events)
        assert_exact(led)
        # steps (3, 6] were lost: first reached step>=4 at ts 4000, died 7000
        assert led.phases_ms["restart_rework"] == 3000
        assert led.restarts == 1

    def test_restart_without_step_evidence_has_no_rework(self):
        events = [
            ev("GANG_COMPLETE", 1000),
            ev("HEARTBEAT_LOST", 4000, reason="gang restart: worker:0 FAILED"),
            ev("GANG_COMPLETE", 5000),
            ev("APPLICATION_FINISHED", 8000, status="SUCCEEDED"),
        ]
        led = obs_goodput.build_ledger("a", events)
        assert_exact(led)
        assert "restart_rework" not in led.phases_ms

    def test_lost_task_heartbeat_is_not_a_restart_marker(self):
        events = [
            ev("GANG_COMPLETE", 1000),
            ev("HEARTBEAT_LOST", 3000, task="worker:1"),  # task lost, no restart
            ev("APPLICATION_FINISHED", 5000, status="FAILED"),
        ]
        led = obs_goodput.build_ledger("a", events)
        assert led.restarts == 0
        assert_exact(led)

    def test_resize_episode(self):
        events = [
            ev("GANG_COMPLETE", 1000),
            snap(2000, **{"worker:0": 5}),
            ev("GANG_RESIZED", 3000, resized={"worker": 4}, trigger="rpc"),
            ev("HEARTBEAT_LOST", 3000, reason="gang restart: resize worker: 2→4"),
            ev("GANG_COMPLETE", 5000),
            ev("APPLICATION_FINISHED", 9000, status="SUCCEEDED"),
        ]
        led = obs_goodput.build_ledger("a", events)
        assert_exact(led)
        assert led.phases_ms["resize"] == 2000
        assert led.resizes == 1

    def test_rejected_resize_claims_nothing(self):
        events = [
            ev("GANG_COMPLETE", 1000),
            ev("GANG_RESIZED", 2000, rejected=True, resized={"worker": 9}),
            ev("APPLICATION_FINISHED", 5000, status="SUCCEEDED"),
        ]
        led = obs_goodput.build_ledger("a", events)
        assert led.resizes == 0 and "resize" not in led.phases_ms

    def test_checkpoint_and_takeover_spans(self):
        events = [
            ev("GANG_COMPLETE", 1000),
            snap(1500, **{"worker:0": 1}),
            ev("AM_TAKEOVER", 6000, am_attempt=1),
            ev("APPLICATION_FINISHED", 10000, status="SUCCEEDED"),
        ]
        spans = [
            {"name": "ckpt.save", "start_ms": 3000.0, "end_ms": 3800.0},
            {"name": "am.takeover", "start_ms": 5500.0, "end_ms": 6000.0},
            {"name": "train.first_step", "start_ms": 1000.0, "end_ms": 1300.0},
        ]
        led = obs_goodput.build_ledger("a", events, spans)
        assert_exact(led)
        assert led.phases_ms["checkpoint"] == 800
        assert led.phases_ms["takeover"] == 500
        # the traced first-step span beats the snapshot estimate
        assert led.phases_ms["compile"] == 300
        assert led.takeovers == 1

    def test_window_fraction_recovers(self):
        events = [
            ev("GANG_COMPLETE", 100),
            snap(1000, **{"worker:0": 1}),
            ev("APPLICATION_FINISHED", 10_000, status="SUCCEEDED"),
        ]
        led = obs_goodput.build_ledger("a", events)
        # trailing 2s of a run whose tail is all productive
        assert led.window_fraction(2000) == 1.0
        assert led.window_fraction(100_000) == led.goodput_fraction

    def test_empty_events(self):
        led = obs_goodput.build_ledger("a", [], now_ms=123)
        assert led.wall_ms == 0 and led.goodput_fraction == 0.0

    def test_step_time_and_skew_by_task(self):
        events = [
            snap(0, **{"worker:0": 0, "worker:1": 0, "worker:2": 0}),
            snap(1000, **{"worker:0": 10, "worker:1": 10, "worker:2": 2}),
            snap(2000, **{"worker:0": 20, "worker:1": 20, "worker:2": 4}),
        ]
        times = obs_goodput.step_time_by_task(events)
        assert times["worker:0"] == pytest.approx(100.0)
        assert times["worker:2"] == pytest.approx(500.0)
        led = obs_goodput.build_ledger(
            "a", events + [ev("APPLICATION_FINISHED", 3000, status="SUCCEEDED")])
        skew = led.skew_by_task()
        assert skew["worker:2"] == pytest.approx(5.0)
        assert skew["worker:0"] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# satellite: randomized-history property test — the partition is EXACT
# ---------------------------------------------------------------------------
class TestPartitionProperty:
    def _random_history(self, rng):
        """A randomized event/span history with restarts, resizes,
        takeovers, queue waits, snapshots — including degenerate orderings
        a torn stream can produce."""
        t = rng.randrange(0, 10_000)
        events, spans = [], []
        step = 0
        for _ in range(rng.randrange(1, 40)):
            t += rng.randrange(0, 2000)
            kind = rng.randrange(10)
            if kind == 0:
                events.append(ev("QUEUE_WAIT", t,
                                 state=rng.choice(["waiting", "admitted"])))
            elif kind == 1:
                events.append(ev("GANG_COMPLETE", t))
            elif kind == 2:
                events.append(ev("HEARTBEAT_LOST", t,
                                 reason="gang restart: chaos"))
                step = max(step - rng.randrange(0, 5), 0)  # resumed earlier
            elif kind == 3:
                events.append(ev("GANG_RESIZED", t,
                                 resized={"worker": rng.randrange(1, 8)},
                                 rejected=rng.random() < 0.2))
            elif kind == 4:
                events.append(ev("AM_TAKEOVER", t, am_attempt=1))
            elif kind == 5:
                events.append(ev("TASK_REGISTERED", t, task="worker:0"))
            elif kind == 6:
                events.append(ev("TASK_FINISHED", t, task="worker:0"))
            elif kind == 7:
                s0 = t - rng.randrange(0, 3000)
                name = rng.choice(
                    ["ckpt.save", "am.takeover", "train.first_step", "other.span"])
                spans.append({"name": name, "start_ms": float(s0),
                              "end_ms": float(s0 + rng.randrange(0, 2500))})
            else:
                step += rng.randrange(0, 4)
                events.append(snap(t, **{
                    f"worker:{i}": max(step - rng.randrange(0, 3), 0)
                    for i in range(rng.randrange(1, 4))
                }))
        if rng.random() < 0.7:
            t += rng.randrange(0, 1500)
            events.append(ev("APPLICATION_FINISHED", t, status="SUCCEEDED"))
        return events, spans, t + rng.randrange(0, 5000)

    def test_partition_is_exact_over_random_histories(self):
        for seed in range(300):
            rng = random.Random(seed)
            events, spans, now = self._random_history(rng)
            led = obs_goodput.build_ledger("r", events, spans, now_ms=now)
            try:
                assert_exact(led)
                assert all(v >= 0 for v in led.phases_ms.values())
                assert 0.0 <= led.goodput_fraction <= 1.0
                for w in (1, 1000, 10_000_000):
                    assert 0.0 <= led.window_fraction(w) <= 1.0
            except AssertionError as e:  # pragma: no cover - diagnostics
                raise AssertionError(f"seed {seed}: {e}") from e

    def test_shuffled_span_order_is_irrelevant(self):
        rng = random.Random(42)
        events, spans, now = self._random_history(rng)
        led1 = obs_goodput.build_ledger("r", events, spans, now_ms=now)
        rng.shuffle(spans)
        led2 = obs_goodput.build_ledger("r", events, spans, now_ms=now)
        assert led1.phases_ms == led2.phases_ms


# ---------------------------------------------------------------------------
# straggler detector
# ---------------------------------------------------------------------------
class TestStragglerDetector:
    @staticmethod
    def feed(det, *ticks):
        out = []
        for stats in ticks:
            out.extend(det.observe(stats))
        return out

    def test_detects_after_streak_and_resolves(self):
        det = obs_goodput.StragglerDetector(factor=2.0, min_checks=2)
        base = {"worker:0": (0, 0.0), "worker:1": (0, 0.0), "worker:2": (0, 0.0)}
        t1 = {"worker:0": (10, 1.0), "worker:1": (10, 1.0), "worker:2": (10, 5.0)}
        t2 = {"worker:0": (20, 2.0), "worker:1": (20, 2.0), "worker:2": (20, 10.0)}
        t3 = {"worker:0": (30, 3.0), "worker:1": (30, 3.0), "worker:2": (30, 15.0)}
        out = self.feed(det, base, t1)
        assert out == []  # one evaluated tick over: streak 1 < min_checks
        out = det.observe(t2)
        assert [(a, t) for a, t, *_ in out] == [("detected", "worker:2")]
        assert det.observe(t3) == []  # already flagged: no re-detection
        assert det.flagged == {"worker:2"}
        assert det.skew["worker:2"] == pytest.approx(5.0)
        # back to normal step times → resolved
        t4 = {"worker:0": (40, 4.0), "worker:1": (40, 4.0), "worker:2": (40, 16.0)}
        out = det.observe(t4)
        assert [(a, t) for a, t, *_ in out] == [("resolved", "worker:2")]
        assert det.flagged == set()

    def test_needs_three_reporting_ranks(self):
        det = obs_goodput.StragglerDetector(factor=1.2, min_checks=1)
        a = {"worker:0": (0, 0.0), "worker:1": (0, 0.0)}
        b = {"worker:0": (10, 1.0), "worker:1": (10, 9.0)}
        assert self.feed(det, a, b) == []
        assert det.flagged == set()

    def test_vanished_flagged_task_resolves(self):
        det = obs_goodput.StragglerDetector(factor=1.5, min_checks=1)
        a = {f"worker:{i}": (0, 0.0) for i in range(3)}
        b = {"worker:0": (10, 1.0), "worker:1": (10, 1.0), "worker:2": (10, 9.0)}
        out = self.feed(det, a, b)
        assert [(x, t) for x, t, *_ in out] == [("detected", "worker:2")]
        # resized away: its row disappears → silent resolve
        c = {"worker:0": (20, 2.0), "worker:1": (20, 2.0)}
        out = det.observe(c)
        assert [(x, t) for x, t, *_ in out] == [("resolved", "worker:2")]

    def test_stalled_rank_lower_bound_detection(self):
        det = obs_goodput.StragglerDetector(factor=2.0, min_checks=1)
        a = {f"worker:{i}": (0, 0.0) for i in range(3)}
        det.observe(a, now_s=0.0)
        b = {"worker:0": (10, 1.0), "worker:1": (10, 1.0), "worker:2": (10, 1.0)}
        assert det.observe(b, now_s=1.0) == []
        # worker:2 stops advancing; 0.15s of silence is only 1.5x the 0.1s
        # median — could just be mid-step, so its state holds
        c = {"worker:0": (20, 2.0), "worker:1": (20, 2.0), "worker:2": (10, 1.0)}
        assert det.observe(c, now_s=1.15) == []
        # 0.85s of silence is a 8.5x lower bound on its step time → detected
        d = {"worker:0": (30, 3.0), "worker:1": (30, 3.0), "worker:2": (10, 1.0)}
        out = det.observe(d, now_s=2.0)
        assert [(x, t) for x, t, *_ in out] == [("detected", "worker:2")]
        # stepping again at normal speed → resolved
        e = {"worker:0": (40, 4.0), "worker:1": (40, 4.0), "worker:2": (20, 2.0)}
        out = det.observe(e, now_s=3.0)
        assert [(x, t) for x, t, *_ in out] == [("resolved", "worker:2")]

    def test_lone_advancer_is_never_evaluated(self):
        # only one rank still advancing (others finished/stalled): no median
        # quorum — the survivor must not be judged against itself
        det = obs_goodput.StragglerDetector(factor=1.5, min_checks=1)
        a = {f"worker:{i}": (0, 0.0) for i in range(3)}
        det.observe(a, now_s=0.0)
        b = {"worker:0": (10, 1.0), "worker:1": (0, 0.0), "worker:2": (0, 0.0)}
        assert det.observe(b, now_s=100.0) == []


class TestJhistFollower:
    def test_incremental_and_torn_tail(self, tmp_path):
        p = tmp_path / "x.jhist"
        f = obs_goodput.JhistFollower(str(p))
        assert f.poll() == []
        p.write_text(ev("GANG_COMPLETE", 1000).to_json() + "\n")
        assert [e.type.value for e in f.poll()] == ["GANG_COMPLETE"]
        # a torn tail (no newline yet) is not consumed...
        with open(p, "a") as fh:
            fh.write('{"type": "TASK_FIN')
        assert len(f.poll()) == 1
        # ...and is parsed whole once its newline lands
        with open(p, "a") as fh:
            fh.write('ISHED", "timestamp_ms": 2000, "payload": {}}\n')
        assert [e.type.value for e in f.poll()] == ["GANG_COMPLETE", "TASK_FINISHED"]


class TestHistogramPercentile:
    def test_merged_percentile(self):
        buckets = [0.1, 0.5, 1.0]
        snapa = [{"name": "tony_train_step_seconds", "type": "histogram",
                  "buckets": buckets,
                  "samples": [{"labels": {}, "counts": [90, 0, 0, 0],
                               "sum": 9.0, "count": 90}]}]
        snapb = [{"name": "tony_train_step_seconds", "type": "histogram",
                  "buckets": buckets,
                  "samples": [{"labels": {}, "counts": [0, 0, 10, 0],
                               "sum": 10.0, "count": 10}]}]
        p50 = obs_goodput.histogram_percentile([snapa, snapb], "tony_train_step_seconds", 0.5)
        p99 = obs_goodput.histogram_percentile([snapa, snapb], "tony_train_step_seconds", 0.99)
        assert p50 == pytest.approx(0.1)
        assert p99 == pytest.approx(1.0)

    def test_no_samples(self):
        assert obs_goodput.histogram_percentile([[]], "x", 0.99) is None

    def test_overflow_bucket(self):
        s = [{"name": "h", "type": "histogram", "buckets": [0.1],
              "samples": [{"labels": {}, "counts": [0, 5], "sum": 5.0, "count": 5}]}]
        assert obs_goodput.histogram_percentile([s], "h", 0.99) == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# alert engine
# ---------------------------------------------------------------------------
class TestAlertEngine:
    RULES = [obs_alerts.AlertRule("goodput-floor", 0.8, "below", "fraction"),
             obs_alerts.AlertRule("queue-depth", 5, "above", "requests")]

    def test_edge_triggered_transitions(self, tmp_path):
        sink = tmp_path / "alerts.jsonl"
        eng = obs_alerts.AlertEngine(
            self.RULES, sink=obs_alerts.AlertSink(str(sink)), app_id="app")
        out = eng.evaluate({"goodput-floor": 0.5, "queue-depth": 2}, now_ms=1000)
        assert [(r["rule"], r["state"]) for r in out] == [("goodput-floor", "fired")]
        # still firing: no new transition, value refreshed
        assert eng.evaluate({"goodput-floor": 0.4}, now_ms=2000) == []
        assert eng.active()[0]["value"] == 0.4
        out = eng.evaluate({"goodput-floor": 0.9}, now_ms=3000)
        assert [(r["rule"], r["state"]) for r in out] == [("goodput-floor", "resolved")]
        assert out[0]["active_ms"] == 2000
        assert eng.active() == []
        recs = [json.loads(line) for line in sink.read_text().splitlines()]
        assert [r["state"] for r in recs] == ["fired", "resolved"]

    def test_none_holds_state(self):
        eng = obs_alerts.AlertEngine(self.RULES, app_id="app")
        eng.evaluate({"goodput-floor": 0.1}, now_ms=0)
        # a scrape gap must neither fire nor resolve
        assert eng.evaluate({"goodput-floor": None}, now_ms=1) == []
        assert len(eng.active()) == 1

    def test_resolve_all(self, tmp_path):
        sink = tmp_path / "alerts.jsonl"
        eng = obs_alerts.AlertEngine(
            self.RULES, sink=obs_alerts.AlertSink(str(sink)), app_id="app")
        eng.evaluate({"goodput-floor": 0.1, "queue-depth": 9}, now_ms=0)
        out = eng.resolve_all("job finalized", now_ms=500)
        assert {r["rule"] for r in out} == {"goodput-floor", "queue-depth"}
        assert all(r["reason"] == "job finalized" for r in out)
        assert eng.active() == []

    def test_webhook_delivery(self, tmp_path):
        from http.server import BaseHTTPRequestHandler, HTTPServer

        got = []

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                got.append(json.loads(
                    self.rfile.read(int(self.headers["Content-Length"]))))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = HTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            sink = obs_alerts.AlertSink(
                None, f"http://127.0.0.1:{srv.server_address[1]}/hook")
            eng = obs_alerts.AlertEngine(self.RULES, sink=sink, app_id="app")
            eng.evaluate({"queue-depth": 50}, now_ms=0)
            assert got and got[0]["rule"] == "queue-depth"
        finally:
            srv.shutdown()

    def test_dead_webhook_is_not_an_outage(self):
        sink = obs_alerts.AlertSink(None, "http://127.0.0.1:1/hook", timeout_s=0.2)
        eng = obs_alerts.AlertEngine(self.RULES, sink=sink, app_id="app")
        out = eng.evaluate({"queue-depth": 50}, now_ms=0)  # must not raise
        assert out[0]["state"] == "fired"

    def test_rules_from_config(self):
        cfg = TonyConfig({
            keys.ALERTS_GOODPUT_FLOOR: "0.75",
            keys.ALERTS_QUEUE_DEPTH: "8",
        })
        rules = {r.name: r for r in obs_alerts.rules_from_config(cfg)}
        assert set(rules) == {"goodput-floor", "queue-depth"}
        assert rules["goodput-floor"].direction == "below"
        assert rules["goodput-floor"].threshold == 0.75
        assert rules["queue-depth"].breached(9) and not rules["queue-depth"].breached(5)

    def test_bad_threshold_is_loud(self):
        cfg = TonyConfig({keys.ALERTS_GOODPUT_FLOOR: "lots"})
        with pytest.raises(ValueError, match="not a number"):
            obs_alerts.rules_from_config(cfg)


# ---------------------------------------------------------------------------
# history-store integration: goodput columns, trend, finalized-alert evals
# ---------------------------------------------------------------------------
@pytest.mark.history
class TestHistoryIntegration:
    def test_ingest_distills_goodput_columns(self, tmp_path):
        from tests.test_history_server import make_job
        from tony_tpu.histserver import ingest as hist_ingest
        from tony_tpu.histserver.store import HistoryStore
        from tony_tpu.obs import artifacts as obs_artifacts

        make_job(tmp_path, "appg")
        store = HistoryStore(":memory:")
        art = obs_artifacts.index(str(tmp_path), "appg")
        assert hist_ingest.ingest_job(store, art) == "ingested"
        row = store.get_job("appg")
        assert row["goodput_s"] > 0
        assert row["badput_s"] > 0  # queue wait + startup are real time here
        assert 0 < row["goodput_fraction"] <= 1
        assert "phases_ms" in row["summary"]["goodput"]
        trend = store.trend("goodput_fraction")
        assert [p["app_id"] for p in trend] == ["appg"]
        store.close()

    def test_store_migration_adds_goodput_columns(self, tmp_path):
        import sqlite3

        path = str(tmp_path / "old.sqlite")
        db = sqlite3.connect(path)
        # a pre-goodput store: the PR-8 jobs schema, without the new columns
        db.execute("""CREATE TABLE jobs (
          app_id TEXT PRIMARY KEY, status TEXT NOT NULL, user TEXT DEFAULT '',
          started_ms INTEGER DEFAULT 0, completed_ms INTEGER DEFAULT 0,
          duration_ms INTEGER DEFAULT 0, incomplete INTEGER DEFAULT 0,
          tasks INTEGER DEFAULT 0, gang_epochs INTEGER DEFAULT 0,
          resizes INTEGER DEFAULT 0, takeovers INTEGER DEFAULT 0,
          queue_wait_s REAL DEFAULT 0.0, staging_dir TEXT DEFAULT '',
          source_path TEXT DEFAULT '', source_mtime_ns INTEGER DEFAULT 0,
          ingested_ms INTEGER DEFAULT 0, summary TEXT DEFAULT '{}',
          config TEXT DEFAULT '{}')""")
        db.execute("CREATE TABLE series (app_id TEXT, metric TEXT, seq INTEGER, "
                   "ts_ms INTEGER, value REAL, PRIMARY KEY (app_id, metric, seq))")
        db.commit()
        db.close()
        from tony_tpu.histserver.store import HistoryStore

        store = HistoryStore(path)  # must migrate, not explode
        store.put_job({"app_id": "x", "status": "SUCCEEDED",
                       "goodput_s": 1.5, "goodput_fraction": 0.5})
        assert store.get_job("x")["goodput_fraction"] == 0.5
        store.close()

    def test_finalized_alert_evaluation_counts(self, tmp_path):
        from tony_tpu.histserver.server import _ALERT_EVALS, HistoryServer

        srv = HistoryServer([str(tmp_path)], store_path=":memory:", port=0)
        srv.start()  # stop() joins the serve loop — it must actually run
        try:
            before = {o: _ALERT_EVALS.value(outcome=o)
                      for o in ("fired", "ok", "none", "error")}
            srv.store.put_job(
                {"app_id": "low", "status": "SUCCEEDED", "goodput_fraction": 0.2},
                config={keys.ALERTS_GOODPUT_FLOOR: "0.9"})
            srv._evaluate_final_alerts("low", None)
            srv.store.put_job(
                {"app_id": "hi", "status": "SUCCEEDED", "goodput_fraction": 0.95},
                config={keys.ALERTS_GOODPUT_FLOOR: "0.9"})
            srv._evaluate_final_alerts("hi", None)
            srv.store.put_job(
                {"app_id": "none", "status": "SUCCEEDED", "goodput_fraction": 0.1})
            srv._evaluate_final_alerts("none", None)
            assert _ALERT_EVALS.value(outcome="fired") - before["fired"] == 1
            assert _ALERT_EVALS.value(outcome="ok") - before["ok"] == 1
            assert _ALERT_EVALS.value(outcome="none") - before["none"] == 1
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# tony goodput CLI over fabricated artifacts
# ---------------------------------------------------------------------------
class TestGoodputCLI:
    def test_report_and_json(self, tmp_path, capsys):
        from tests.test_history_server import make_job
        from tony_tpu.cli.goodput import main as goodput_main

        make_job(tmp_path, "appc", extra=(
            (EventType.STRAGGLER_DETECTED, {"task": "worker:2", "ratio": 3.1}),
            (EventType.ALERT_FIRED,
             {"rule": "goodput-floor", "value": 0.2, "threshold": 0.8}),
            (EventType.ALERT_RESOLVED,
             {"rule": "goodput-floor", "value": 0.9, "threshold": 0.8}),
        ))
        assert goodput_main(["appc", "--staging", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "phase ledger" in out and "productive" in out
        assert "STRAGGLER" in out
        assert "goodput-floor" in out and "resolved" in out

        assert goodput_main(["appc", "--staging", str(tmp_path), "--json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert sum(data["phases_ms"].values()) == data["wall_ms"]
        assert data["alert_history"][0]["rule"] == "goodput-floor"
        assert data["straggler_history"][0]["task"] == "worker:2"

    def test_missing_app(self, tmp_path, capsys):
        from tony_tpu.cli.goodput import main as goodput_main

        assert goodput_main(["nope", "--staging", str(tmp_path)]) == 1
        assert "no history events" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# headline e2e: chaos restart + elastic resize + straggler + alert lifecycle
# ---------------------------------------------------------------------------
@pytest.mark.e2e
@pytest.mark.chaos
class TestGoodputHeadlineE2E:
    STEPS = 26

    def _wait(self, fn, timeout_s=90, interval=0.1):
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            got = fn()
            if got:
                return got
            time.sleep(interval)
        return None

    def _wait_observed(self, probe, stall_s=60.0, cap_s=420.0, interval=0.25):
        """Progress-derived deadline: ``probe()`` returns ``(result,
        signal)``; returns ``result`` once truthy. The wait only gives up
        after ``stall_s`` seconds with no change in ``signal`` (hard backstop
        ``cap_s``) — a slow-but-progressing run gets more time, a wedged one
        still fails fast."""
        t0 = last_t = time.time()
        last: object = object()
        while True:
            result, sig = probe()
            if result:
                return result
            now = time.time()
            if sig != last:
                last, last_t = sig, now
            if now - last_t >= stall_s or now - t0 >= cap_s:
                return None
            time.sleep(interval)

    @pytest.mark.skipif(
        (len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity")
         else (os.cpu_count() or 1)) < 2,
        reason="needs >= 2 CPUs: this e2e runs a full 3-worker gang + AM + "
               "pool + portal as real processes/threads on one box, and on a "
               "single CPU the gang's heartbeat/monitor/step loops serialize "
               "behind each other — the straggler-skew and restart timing "
               "assertions then flake on scheduler luck, not on product "
               "bugs (documented flake since PR 16; PR 17's "
               "progress-derived waits fixed the wedge case but cannot "
               "manufacture a second core). The test runs unchanged "
               "wherever nproc >= 2.")
    def test_restart_resize_straggler_and_alert_accounted(
            self, tmp_tony_root, tmp_path, capsys):
        from tests.test_e2e import FAST, fixture_cmd
        from tony_tpu.cli.goodput import main as goodput_main
        from tony_tpu.cluster.client import Client
        from tony_tpu.cluster.session import JobStatus
        from tony_tpu.histserver.store import HistoryStore
        from tony_tpu.histserver import ingest as hist_ingest
        from tony_tpu.obs import artifacts as obs_artifacts
        from tony_tpu.portal.server import serve

        shared = tmp_path / "shared"
        shared.mkdir()
        cfg = TonyConfig({
            **FAST,
            keys.STAGING_ROOT: str(tmp_tony_root),
            # rank 2 runs 3x slow (the injected straggler); ckpt every 4 steps
            keys.EXECUTES: f"{fixture_cmd('goodput_train.py')} {shared} "
                           f"{self.STEPS} 120 2 3.0 4",
            "tony.worker.instances": "3",
            keys.TASK_METRICS_INTERVAL_MS: "150",
            keys.TASK_RESTART_ON_FAILURE: "true",
            # one gang restart: a node dies once the AM has seen step 7
            keys.CHAOS_SPEC: "node-loss:worker:1@step+7",
            keys.CHAOS_SEED: "7",
            keys.GOODPUT_INTERVAL_MS: "250",
            keys.GOODPUT_WINDOW_MS: "2500",
            keys.GOODPUT_STRAGGLER_FACTOR: "2.0",
            keys.GOODPUT_STRAGGLER_CHECKS: "2",
            keys.ALERTS_GOODPUT_FLOOR: "0.5",
        })
        client = Client(cfg)
        handle = client.submit()
        app_id = handle.app_id

        # mid-run elastic resize: once the post-restart gang has made
        # PROGRESS (fresh step reports past the resume point — the ledger's
        # rework derivation needs the resumed epoch's snapshots on disk),
        # grow worker 3 → 4 over the same lever the autoscaler uses
        def restarted_and_progressing():
            rpc = handle.rpc(timeout_s=5)
            if rpc is None:
                return None, None
            sig = None
            try:
                st = rpc.call("get_application_status")
                infos = rpc.call("get_task_infos")
                steps = [
                    ((t.get("metrics") or {}).get("train") or {}).get("step") or 0
                    for t in infos
                ]
                running = sum(1 for t in infos if t["status"] == "RUNNING")
                sig = (st.get("restart_attempt", 0), running,
                       max(steps, default=0))
                if sig[0] >= 1 and running >= 3 and sig[2] >= 8:
                    return rpc, sig
            except Exception:  # noqa: BLE001 — AM mid-restart
                pass
            rpc.close()
            return None, sig

        # deadline derived from observed progress: restart attempts landing
        # and step reports advancing extend the wait; only a stall fails
        rpc = self._wait_observed(restarted_and_progressing,
                                  stall_s=60, cap_s=300)
        assert rpc is not None, "gang restart never landed (or never progressed)"
        try:
            # give the straggler detector a couple of ticks on the restarted
            # gang before the resize tears it down again
            time.sleep(1.0)
            assert rpc.call("resize_jobtype", job_name="worker", instances=4)["ack"]
        finally:
            rpc.close()

        final = client.monitor_application(handle, quiet=True)
        assert final == JobStatus.SUCCEEDED, handle.final_status()

        art = obs_artifacts.index(str(tmp_tony_root), app_id)
        events, complete = art.read_events()
        assert complete
        types = [e.type.value for e in events]

        # --- the event stream carries the whole story
        assert "STRAGGLER_DETECTED" in types
        straggled = {e.payload["task"] for e in events
                     if e.type.value == "STRAGGLER_DETECTED"}
        assert "worker:2" in straggled
        fired = [e for e in events if e.type.value == "ALERT_FIRED"]
        resolved = [e for e in events if e.type.value == "ALERT_RESOLVED"]
        assert fired and resolved
        assert fired[0].payload["rule"] == "goodput-floor"
        assert resolved[-1].timestamp_ms >= fired[0].timestamp_ms
        assert "GANG_RESIZED" in types

        # --- the sink received the same transitions
        sink = os.path.join(art.staging_dir, "alerts.jsonl")
        recs = [json.loads(line) for line in open(sink)]
        assert {r["state"] for r in recs} >= {"fired", "resolved"}

        # --- tony goodput: exact partition + attribution + straggler flag
        capsys.readouterr()
        assert goodput_main([app_id, "--staging", str(tmp_tony_root), "--json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert sum(data["phases_ms"].values()) == data["wall_ms"]
        assert data["phases_ms"].get("restart_rework", 0) > 0, data["phases_ms"]
        assert data["phases_ms"].get("resize", 0) > 0, data["phases_ms"]
        assert data["phases_ms"]["productive"] > 0
        assert data["restarts"] >= 2 and data["resizes"] == 1
        # ordinal, not a hard ratio: scheduling noise on a loaded CI box can
        # compress the margin, but the 3x-sleeping rank is always slowest
        skews = data["skew_by_task"]
        assert skews and max(skews, key=skews.get) == "worker:2", skews
        assert skews["worker:2"] > 1.0, skews

        assert goodput_main([app_id, "--staging", str(tmp_tony_root)]) == 0
        report = capsys.readouterr().out
        assert "restart_rework" in report and "resize" in report
        assert "worker:2" in report and "STRAGGLER" in report
        assert "goodput-floor" in report

        # --- history store: goodput columns + alert/straggler history
        store = HistoryStore(str(tmp_path / "store.sqlite"))
        counts = hist_ingest.sweep(store, [str(tmp_tony_root)])
        assert counts["ingested"] == 1
        row = store.get_job(app_id)
        assert 0 < row["goodput_fraction"] < 1
        assert row["goodput_s"] > 0
        assert any(h["rule"] == "goodput-floor" for h in row["summary"]["alerts"])
        assert "worker:2" in row["summary"]["stragglers"]
        store.close()

        # --- portal: /job/<id>/goodput and the fleet /alerts page
        server = serve(os.path.join(str(tmp_tony_root), "history"), 0,
                       str(tmp_tony_root),
                       history_db=str(tmp_path / "store.sqlite"))
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            body = urllib.request.urlopen(f"{base}/job/{app_id}/goodput").read().decode()
            assert "phase ledger" in body and "restart_rework" in body
            assert "STRAGGLER" in body
            alerts_page = urllib.request.urlopen(f"{base}/alerts").read().decode()
            assert app_id in alerts_page
            assert "goodput-floor" in alerts_page
            api = json.loads(
                urllib.request.urlopen(f"{base}/api/goodput/{app_id}").read())
            assert sum(api["phases_ms"].values()) == api["wall_ms"]
        finally:
            server.shutdown()

        # --- the optional bench goodput gate sees the same ledger
        from tony_tpu.cli.history import main_bench

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        capsys.readouterr()
        rc_hi = main_bench(["--gate", "--trajectory-dir", repo,
                            "--goodput-floor", "0.999", "--goodput-app", app_id,
                            "--staging", str(tmp_tony_root)])
        assert rc_hi == 1
        assert "GOODPUT REGRESSION" in capsys.readouterr().out
        rc_lo = main_bench(["--gate", "--trajectory-dir", repo,
                            "--goodput-floor", "0.0", "--goodput-app", app_id,
                            "--staging", str(tmp_tony_root)])
        assert rc_lo == 0
