"""Tier-1 wall-clock budget: the heavy-soak `slow` marks must not regress.

The tier-1 suite runs under a hard timeout (`-m 'not slow'`); the tests
below were measured as the dominant non-headline soaks and deliberately
moved behind the `slow` marker so the budget fits. A refactor that renames
or re-inlines one of them silently re-inflates the suite past its timeout —
so this meta-test pins the decision by NAME, via AST only (no imports, no
fixtures, milliseconds).

When one of these genuinely gets fast (or is deleted), update the list —
that's the point: the budget change becomes an explicit diff, not an
accident.
"""

import ast
import os

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))

# (file, test function name) — every entry must carry @pytest.mark.slow.
# Keep the per-subsystem HEADLINE e2es out of this list: they stay tier-1.
SLOW_SOAKS = [
    ("test_sampling.py", "test_greedy_row"),
    ("test_serve_dataplane.py",
     "test_loadtest_affinity_preemption_and_drained_scale_down"),
    ("test_serve_fleet.py", "test_replica_crash_is_not_client_visible"),
    ("test_recorder.py", "test_scaled_lane_reports_recorder_on"),
    ("test_pool_queue.py",
     "test_cross_queue_reclaim_evicts_borrower_end_to_end"),
    ("test_train.py", "test_interrupted_run_equals_uninterrupted"),
    ("test_train.py", "test_restore_onto_smaller_mesh_keeps_training"),
    ("test_serve.py", "test_high_priority_serve_preempts_training"),
    ("test_sched.py", "test_elastic_victim_sheds_workers_instead_of_dying"),
    ("test_input_pipeline.py",
     "test_synthetic_loss_trajectory_is_bit_identical"),
    ("test_input_pipeline.py", "test_loader_loss_trajectory_is_bit_identical"),
    ("test_elastic.py", "test_grow_promotes_a_parked_spare"),
    ("test_models.py", "test_train_step"),
    ("test_parallel.py", "test_fused_kernel_matches_xla_ragged"),
    ("test_train.py", "test_run_lm_training_with_stage_axis"),
    ("test_models.py", "test_grad_accumulation_matches_full_batch"),
    ("test_models.py", "test_loss_decreases"),
    ("test_models.py", "test_mlm_loss_and_convergence"),
    ("test_serving.py", "test_more_requests_than_slots"),
    ("test_input_pipeline.py", "test_tune_cli_dry_run_and_persist"),
    ("test_generate.py", "test_incremental_decode_matches_full_forward"),
    ("test_cbench.py", "test_probe_at_100k_apps_names_the_next_wall"),
]


def _has_slow_mark(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        # @pytest.mark.slow (possibly called: @pytest.mark.slow())
        node = dec.func if isinstance(dec, ast.Call) else dec
        if (isinstance(node, ast.Attribute) and node.attr == "slow"
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "mark"):
            return True
    return False


def _functions(tree: ast.Module):
    for top in tree.body:
        if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield top
        elif isinstance(top, ast.ClassDef):
            for item in top.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield item


def test_known_heavy_soaks_stay_behind_the_slow_marker():
    trees = {}
    missing, unmarked = [], []
    for fname, test in SLOW_SOAKS:
        if fname not in trees:
            with open(os.path.join(TESTS_DIR, fname)) as f:
                trees[fname] = ast.parse(f.read(), filename=fname)
        fns = [fn for fn in _functions(trees[fname]) if fn.name == test]
        if not fns:
            missing.append(f"{fname}::{test}")
        elif not any(_has_slow_mark(fn) for fn in fns):
            unmarked.append(f"{fname}::{test}")
    assert not missing, (
        f"budget list is stale — tests gone or renamed: {missing}; "
        "update SLOW_SOAKS to match (and keep the replacement marked slow)")
    assert not unmarked, (
        f"heavy soaks lost their @pytest.mark.slow: {unmarked}; "
        "tier-1 runs under a hard timeout — re-mark them (or, if one "
        "genuinely got fast, remove it from SLOW_SOAKS explicitly)")


def test_slow_marker_is_registered():
    # an unregistered marker dies under --strict-markers and silently
    # matches nothing under -m: pin its registration
    with open(os.path.join(TESTS_DIR, os.pardir, "pyproject.toml")) as f:
        doc = f.read()
    markers = doc.split("markers = [", 1)[1].split("]", 1)[0]
    assert '"slow:' in markers
