"""Asserts the executor env contract (reference check_env*.py analog)."""
import json, os, sys

def req(name):
    v = os.environ.get(name)
    assert v, f"missing env {name}"
    return v

job, idx = req("JOB_NAME"), int(req("TASK_INDEX"))
spec = json.loads(req("CLUSTER_SPEC"))
assert job in spec and len(spec[job]) > idx, f"{job}:{idx} not in spec {spec}"
tf = json.loads(req("TF_CONFIG"))
assert tf["task"] == {"type": job, "index": idx}, tf
assert set(tf["cluster"]) == set(spec), (tf, spec)
print("check_env: ok")
sys.exit(0)
