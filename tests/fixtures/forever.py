"""Sleep-forever workload for heartbeat/untracked-kill paths (forever.py analog)."""
import time
while True:
    time.sleep(0.5)
