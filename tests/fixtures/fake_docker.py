#!/usr/bin/env python3
"""Fixture: stands in for the docker CLI in container-runtime tests.

Parses ``run`` flags the way docker would (enough of them), records the
invocation to $FAKE_DOCKER_LOG, then execs the container command directly on
the host with the ``-e`` environment applied — the process tree behaves like
a real container launch from the RM's point of view.
"""

import json
import os
import sys


def main() -> int:
    args = sys.argv[1:]
    assert args and args[0] == "run", f"fake docker got {args[:1]}"
    args = args[1:]
    env = dict(os.environ)
    mounts, flags = [], []
    image = None
    i = 0
    while i < len(args):
        a = args[i]
        if a in ("-e", "--env"):
            spec = args[i + 1]
            if "=" in spec:  # bare `-e KEY` inherits from the client env
                k, _, v = spec.partition("=")
                env[k] = v
            i += 2
        elif a in ("-v", "--volume"):
            mounts.append(args[i + 1])
            i += 2
        elif a.startswith("-"):
            flags.append(a)
            i += 1
        else:
            image = a
            i += 1
            break
    command = args[i:]
    log = os.environ.get("FAKE_DOCKER_LOG")
    if log:
        with open(log, "a") as f:
            f.write(json.dumps({"image": image, "flags": flags, "mounts": mounts,
                                "command": command}) + "\n")
    assert image and command, f"fake docker: image={image!r} command={command!r}"
    os.execvpe(command[0], command, env)


if __name__ == "__main__":
    sys.exit(main())
