"""Sleeps forever on gang attempt 0 (so the test can kill its node), exits 0
on the restarted attempt after recording which node ran it — the node-death →
gang-restart E2E workload."""
import os
import sys
import time

if os.environ.get("TONY_RESTART_ATTEMPT", "0") == "0":
    time.sleep(600)
out = os.path.join(
    os.environ["TONY_STAGING_DIR"],
    f"node_of_{os.environ['JOB_NAME']}_{os.environ['TASK_INDEX']}.txt",
)
with open(out, "w") as f:
    f.write(os.environ.get("TONY_NODE_NAME", ""))
sys.exit(0)
