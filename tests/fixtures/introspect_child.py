"""Fake training child for the introspection e2e (tests/test_introspect.py).

Exercises every framework contract a real ``run_lm_training`` child does —
StepProfiler (incl. the on-demand control-file plane), structured logging,
the train-metrics + ``.obs`` registry drops, tracing — but with a plain
sleep loop instead of XLA work, so the gang is live within a second and the
mid-run ``tony profile`` / ``tony logs -f`` / ``tony top`` round trips are
fast and deterministic. Exits 0 when ``<staging>/stop`` appears.
"""

import json
import os
import sys
import time
import types

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


# This gang exercises the distributed relay plane — control file in, done
# file + artifacts out, RPC reports, merged logs — not XLA: a stub
# ``jax.profiler`` stands in for the real one (whose cold import would
# dominate the test clock), writing a real artifact file per capture. The
# genuine ``jax.profiler`` start/stop path and artifact readability are
# covered in-process by tests/test_profiling.py.
class _StubProfiler:
    _dir = ""

    def start_trace(self, out_dir):
        self._dir = out_dir

    def stop_trace(self):
        os.makedirs(self._dir, exist_ok=True)
        with open(os.path.join(self._dir, "trace.json"), "w") as f:
            json.dump({"stub": True}, f)

    def save_device_memory_profile(self, path):
        with open(path, "w") as f:
            f.write("stub")


_fake_jax = types.ModuleType("jax")
_fake_jax.profiler = _StubProfiler()
sys.modules.setdefault("jax", _fake_jax)


def _load_step_profiler():
    """StepProfiler straight from its file — ``tony_tpu.train``'s package
    init pulls the trainer (and with it the real jax) this gang exists to
    avoid paying for."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "tony_tpu", "train", "profiling.py",
    )
    spec = importlib.util.spec_from_file_location("_introspect_profiling", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.StepProfiler

from tony_tpu import constants  # noqa: E402
from tony_tpu.obs import logging as obs_log  # noqa: E402
from tony_tpu.obs import metrics as obs_metrics  # noqa: E402
from tony_tpu.obs import trace as obs_trace  # noqa: E402
StepProfiler = _load_step_profiler()  # noqa: E402

obs_log.init_from_env()
tracer = obs_trace.init_from_env()
root = token = None
if tracer is not None:
    root, token = tracer.start_span("train.run")
    tracer.root_parent = root.span_id

step_seconds = obs_metrics.histogram(
    "tony_train_step_seconds",
    "mean per-step wall time, sampled once per logging window")
metrics_path = os.environ.get(constants.ENV_TRAIN_METRICS_FILE, "")
stop_file = os.path.join(os.environ["TONY_STAGING_DIR"], "stop")
profiler = StepProfiler()


def drop(line):
    if not metrics_path:
        return
    tmp = metrics_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(line, f)
    os.replace(tmp, metrics_path)
    snap = [m for m in obs_metrics.REGISTRY.snapshot() if m["samples"]]
    if snap:
        tmp = metrics_path + ".obs.tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, metrics_path + ".obs")


t0 = time.perf_counter()
try:
    for step in range(2000):
        profiler.step(step)
        time.sleep(0.02)
        now = time.perf_counter()
        step_seconds.observe(now - t0)
        t0 = now
        if (step + 1) % 5 == 0:
            line = {"step": step + 1, "loss": round(2.5 - step * 1e-3, 4),
                    "tokens_per_sec": 123.4, "mfu": 0.1}
            obs_log.info(json.dumps(line), **line)
            drop(line)
        if os.path.exists(stop_file):
            break
finally:
    profiler.stop()
if tracer is not None:
    tracer.end_span(root, token)
    obs_trace.shutdown()
print("introspect child done", flush=True)
