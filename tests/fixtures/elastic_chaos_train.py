"""Fixture: the headline elastic-chaos workload (preempt → shrink → resume).

A data-parallel training gang WITHOUT cross-process XLA (the CPU backend in
the test image cannot compile multi-process computations): every rank draws
its own slice of the global stream through the real ``TokenLoader``
global-order contract and records a content hash per consumed local batch;
rank 0 additionally runs a real (single-device) train state with Orbax
checkpoints through ``restore_or_init`` and persists the consumption cursor.

A file-based handshake emulates the per-step collective of a real SPMD gang,
preserving its two elastic-critical invariants: (a) no rank runs more than
one step ahead of rank 0, so the AM's ``@step+N`` gate (fed from pushed
metrics) cannot open before the step-gated checkpoint is finalized, and
(b) rank 0 saves checkpoint ``s`` only after EVERY rank has published step
``s`` — a restored checkpoint therefore proves the whole gang consumed all
global batches below it, which is exactly what the test's exactly-once
accounting replays.

Attempt 0 gets an oversized step budget so the chaos
``preempt:worker:*@step+4`` faults always fire mid-run; after the AM's
shrink-on-preempt rebuild, the resumed attempt re-reads rank 0's published
resume step, validates the consumption cursor, and finishes at the SMALLER
world size.

Usage: elastic_chaos_train.py <data_dir> <shared_dir> <steps>
"""

import hashlib
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

from tony_tpu import constants  # noqa: E402
from tony_tpu.data import TokenLoader  # noqa: E402
from tony_tpu.data.dataset import ConsumptionCursor  # noqa: E402
from tony_tpu.train.checkpoint import restore_or_init  # noqa: E402

data_dir, shared_dir, total_steps = sys.argv[1], sys.argv[2], int(sys.argv[3])
attempt = int(os.environ.get("TONY_RESTART_ATTEMPT", "0"))
rank = int(os.environ[constants.ENV_JAX_PROCESS_ID])
world = int(os.environ[constants.ENV_JAX_NUM_PROCESSES])
GLOBAL_BATCH, SEQ, SEED = 4, 64, 0
local_rows = GLOBAL_BATCH // world
ckpt_dir = os.path.join(shared_dir, "ckpt")
os.makedirs(shared_dir, exist_ok=True)

# attempt 0 exists to BE preempted: a 10x budget guarantees the step-gated
# faults fire mid-run; resumed (post-shrink) attempts train to the target
steps = total_steps * 10 if attempt == 0 else total_steps


def _publish(path: str, step: int) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"step": step}, f)
    os.replace(tmp, path)


def _read_step(path: str, default: int) -> int:
    try:
        with open(path) as f:
            return int(json.load(f)["step"])
    except (OSError, ValueError, KeyError):
        return default


def _wait(cond, what: str) -> None:
    deadline = time.monotonic() + 120
    while not cond():
        if time.monotonic() > deadline:
            raise RuntimeError(f"timed out waiting for {what}")
        time.sleep(0.01)


def _progress(r: int) -> str:
    return os.path.join(shared_dir, f"progress-a{attempt}-r{r}.json")


# -- resume point: rank 0 restores (corruption-tolerant) and PUBLISHES the
# step; peers wait for it so every rank replays from the same global batch
resume_file = os.path.join(shared_dir, f"resume-{attempt}.json")
if rank == 0:
    state, mgr, start = restore_or_init(
        ckpt_dir, lambda: {"w": np.zeros(4, np.float64)}, use_async=False)
    if start:
        print(f"[train] resumed from checkpoint step {start}", flush=True)
        cursor = ConsumptionCursor.load(ckpt_dir, start)
        if cursor is not None:
            cursor.validate_resume(GLOBAL_BATCH, SEED, start)
            print(f"[train] data cursor validated: resuming the global stream "
                  f"at batch {start} (written at world size "
                  f"{cursor.world_size}, now {world})", flush=True)
    _publish(resume_file, start)
else:
    state, mgr = None, None
    _wait(lambda: os.path.exists(resume_file), "rank 0's resume step")
    start = _read_step(resume_file, 0)

loader = TokenLoader(
    sorted(Path(data_dir).glob("*.tonytok")), local_rows, SEQ,
    shard_id=rank, num_shards=world, seed=SEED, start_index=start,
)
record = open(os.path.join(shared_dir, f"consumed-a{attempt}-r{rank}.jsonl"), "a", buffering=1)
metrics_file = os.environ.get(constants.ENV_TRAIN_METRICS_FILE)

loss = float("nan")
try:
    for t in range(start, steps):
        if rank != 0:
            # the collective-lockstep bound: never run >1 step ahead of the
            # checkpointing rank, so a step the AM sees reported implies the
            # gated checkpoint below it is already finalized
            _wait(lambda: _read_step(_progress(0), start) >= t, f"rank 0 to reach step {t}")
        batch = loader.next()  # [local_rows, SEQ+1] rows of global batch t
        record.write(json.dumps({
            "attempt": attempt, "world": world, "rank": rank, "t": t,
            "sha1": hashlib.sha1(np.ascontiguousarray(batch).tobytes()).hexdigest(),
        }) + "\n")
        if rank == 0:
            # a real (single-device) optimizer step + periodic checkpoint,
            # so resume-from-the-smaller-gang restores genuine Orbax state
            state["w"] = state["w"] * 0.9 + float(batch.mean()) * 0.1
            loss = float(np.abs(state["w"]).mean())
            if (t + 1) % 2 == 0:
                # the collective invariant: a checkpoint at step s exists
                # only once EVERY rank has consumed the batches below s
                _wait(
                    lambda: all(_read_step(_progress(r), start) >= t + 1 for r in range(1, world)),
                    f"the gang to finish step {t + 1}",
                )
                mgr.save(t + 1, state, force=True)
                ConsumptionCursor(
                    global_batch_index=t + 1, global_batch_size=GLOBAL_BATCH,
                    seed=SEED, world_size=world,
                ).save(ckpt_dir)
        _publish(_progress(rank), t + 1)
        if metrics_file:
            # the executor piggybacks this on its heartbeat — the AM's chaos
            # context feeds @step+N gates from exactly this report
            tmp = metrics_file + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"step": t + 1, "loss": loss}, f)
            os.replace(tmp, metrics_file)
        time.sleep(0.05)  # paces the run so mid-run preemption lands mid-run
finally:
    loader.close()
    record.close()
    if mgr is not None:
        mgr.close()

print(f"elastic-chaos attempt {attempt}: rank={rank} step={steps} world={world}", flush=True)
sys.exit(0)
