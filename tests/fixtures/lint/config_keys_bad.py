"""config-keys fixture: two undeclared keys, one suppressed."""


def read(cfg):
    a = cfg.get("tony.app.name")                # declared: ok
    b = cfg.get("tony.app.nmae")                # undeclared (typo): finding
    c = cfg.get("tony.family.anything.goes")    # prefix family: ok
    d = cfg.get("tony.missing.key")             # undeclared: finding
    e = cfg.get("tony.app.nmae")  # lint: disable=config-keys — fixture for suppression
    msg = f"set tony.app.name={a} first"        # f-string part, not key-shaped: ok
    return a, b, c, d, e, msg
