"""print-discipline fixtures: bare prints in library code."""


def noisy(x):
    print("step", x)
    return x + 1


def contract(manifest):
    import json

    print(json.dumps(manifest))  # lint: disable=print-discipline — stdout contract
    return 0


def logged(msg):
    from tony_tpu.obs import logging as obs_logging

    obs_logging.info(msg)  # the blessed route — not a finding


print("module-level banner")
