"""guarded-fields fixture: clean patterns the checker must NOT flag."""

import threading


class SingleWriter:
    """All writes happen on ONE thread (the monitor): the incidental locked
    writes do not make the field guarded — bare snapshot reads from other
    threads are the GIL-atomic read pattern, not a race."""

    def __init__(self):
        self._lock = threading.Lock()
        self._phase = "init"
        self._t = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        with self._lock:
            self._phase = "running"
        self._step()

    def _step(self):
        with self._lock:
            self._phase = "stepping"

    def status(self):
        return self._phase                  # snapshot read: clean


class FullyGuarded:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._t = threading.Thread(target=self._drain, daemon=True)

    def _drain(self):
        with self._lock:
            self._items.clear()

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def size(self):
        with self._lock:
            return len(self._items)         # every access holds the lock
