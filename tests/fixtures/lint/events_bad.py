"""events-discipline fixture: documented, undocumented, and suppressed members."""

import enum


class EventType(enum.Enum):
    TASK_STARTED = "TASK_STARTED"  # documented in docs/observability.md: clean
    TOTALLY_UNDOCUMENTED_EVENT = "TOTALLY_UNDOCUMENTED_EVENT"  # finding
    ANOTHER_MISSING_EVENT = "ANOTHER_MISSING_EVENT"  # finding
    DELIBERATE_EXPERIMENT = "DELIBERATE_EXPERIMENT"  # lint: disable=events-discipline — fixture: flag-gated experiment
    _ORDINAL = 7  # non-string member value: not an event name


class NotEventType(enum.Enum):
    # a different enum: its members are not .jhist vocabulary
    SOME_STATE = "SOME_STATE"
