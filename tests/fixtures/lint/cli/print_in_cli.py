"""Under a cli/ path segment: stdout IS the product — exempt wholesale."""


def main():
    print("usage: whatever")
    return 0
