"""lock-ordering fixture: acquisition cycles the checker must report."""

import threading

_A = threading.Lock()
_B = threading.Lock()


def ab():
    with _A:
        with _B:        # edge A -> B
            pass


def ba():
    with _B:
        with _A:        # edge B -> A: cycle with ab()
            pass


def reenter():
    with _A:
        with _A:        # non-reentrant re-acquire: single-thread deadlock
            pass


class Pair:
    """Cycle formed THROUGH a call: nm() holds _n and calls a helper that
    takes _m, while mn() takes them in the opposite order."""

    def __init__(self):
        self._m = threading.Lock()
        self._n = threading.Lock()

    def mn(self):
        with self._m:
            self._grab_n()

    def _grab_n(self):
        with self._n:
            pass

    def nm(self):
        with self._n:
            with self._m:
                pass
