"""jit-purity fixture: pure jitted code, benign look-alikes, suppression."""

import functools
import time

import jax

_OPT = object()


def not_jitted(x):
    print(x)                    # plain function: ok
    return time.time() + x


@jax.jit
def pure(x):
    parts = []
    parts.append(x)             # local container: ok
    key = jax.random.PRNGKey(0)  # jax.random, not stdlib random: ok
    return parts[0] + jax.random.uniform(key)


@functools.partial(jax.jit, donate_argnums=(0,))
def api_method_named_update(state):
    updates, new_state = _OPT.update(state)   # result consumed: ok
    return updates, new_state


@jax.jit
def nested_helper_class(x):
    class _View:
        def __init__(self, ref):
            self.ref = ref      # the helper's own self: ok

    return _View(x).ref


@jax.jit
def suppressed(x):
    print("debug", x)  # lint: disable=jit-purity — trace-time debug fixture
    return x
