"""metrics-discipline fixtures: unprefixed + undocumented instruments."""

from tony_tpu.obs import metrics as obs_metrics

_OK = obs_metrics.counter(
    "tony_rpc_client_errors_total", "documented name — not a finding")

_BAD_PREFIX = obs_metrics.counter(
    "rpc_errors_total", "missing the tony_ prefix")

_UNDOCUMENTED = obs_metrics.gauge(
    "tony_fixture_only_gauge", "prefixed but absent from the docs table")

_SUPPRESSED = obs_metrics.histogram(  # lint: disable=metrics-discipline — fixture scratch
    "scratch_latency_seconds", "deliberately off-registry")


def dynamic(name):
    # dynamic names cannot be checked statically — not a finding
    return obs_metrics.counter(name, "runtime-chosen")
