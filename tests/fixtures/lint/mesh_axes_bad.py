"""mesh-axes fixture: undeclared literals; declared/threaded names pass.

The ``AXIS_*`` constants make this module its own declaration site.
"""

import jax
from jax import lax

AXIS_ROW = "row"
AXIS_COL = "col"


def bad_psum(x):
    return jax.lax.psum(x, "rows")              # line 14: finding (typo)


def bad_kw(x):
    return lax.all_gather(x, axis_name="diag")  # line 18: finding


def bad_wrapper(x, fn):
    return fn(x, axis_name="bogus")             # line 22: finding (any call)


def bad_default(x, axis_name="colz"):           # line 25: finding (param default)
    return lax.pmean(x, axis_name)


def good_declared(x):
    return lax.psum(x, AXIS_ROW) + jax.lax.pmean(x, "col")


def good_threaded(x, axis_name):
    return lax.all_gather(x, axis_name)


def good_tuple(x):
    return lax.pmean(x, ("row", "col"))


def suppressed(x):
    return lax.psum(x, "legacy")  # lint: disable=mesh-axes — external-mesh fixture


def bad_axis_index(x):
    return x[jax.lax.axis_index("rowz")]        # line 46: finding (slot 0)
