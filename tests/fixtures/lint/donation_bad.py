"""donation-safety fixture: donated buffers reused after the call."""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, x):
    return state + x


def _impl(cache, tok):
    return cache


cached_step = functools.partial(jax.jit, donate_argnums=(0,))(_impl)
jit_applied = jax.jit(_impl, donate_argnums=(0,))


def bad_reuse(state, x):
    new = step(state, x)
    return state + new                      # line 23: finding (state donated)


def bad_kw(state, x):
    new = step(x=x, state=state)            # donated position passed by kw
    return state                            # line 28: finding


def bad_applied(cache, tok):
    out = jit_applied(cache, tok)
    return cache.sum() + out                # line 33: finding


class Engine:
    def bad_attr(self, x):
        out = cached_step(self.cache, x)
        return self.cache.sum() + out       # line 39: finding
