"""Mini config-key registry for lint fixtures (a module named ``keys`` is a
declaration site for the config-keys checker)."""

APP_NAME = "tony.app.name"
TASK_TIMEOUT = "tony.task.timeout-ms"
FAMILY_PREFIX = "tony.family."
