"""blocking-under-lock fixture: clean patterns and justified suppressions."""

import os
import threading
import time

_lock = threading.Lock()


def stage_then_write(queue, f):
    with _lock:
        line = queue.pop()                  # fast: under the lock
    f.write(line)                           # slow: outside it
    os.fsync(f.fileno())


def sleep_outside():
    with _lock:
        n = 3
    time.sleep(n)                           # blocking op after release


class SerializedSink:
    """The lock IS the sink serializer — the deliberate, suppressed shape."""

    def __init__(self, f):
        self._lock = threading.Lock()
        self._f = f

    def emit(self, line):
        with self._lock:
            self._f.write(line)  # lint: disable=blocking-under-lock — leaf serializer fixture: the lock exists to order these writes
