"""blocking-under-lock fixture: blocking ops inside critical sections."""

import os
import sqlite3
import threading
import time

_lock = threading.Lock()


def sleepy():
    with _lock:
        time.sleep(0.5)                     # direct op under module lock


def _sync(f):
    os.fsync(f.fileno())                    # no lock of its own


def flush(f):
    with _lock:
        _sync(f)                            # transitive: callee fsyncs


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._db = sqlite3.connect(":memory:")

    def put(self, row):
        with self._lock:
            self._db.execute("INSERT INTO t VALUES (?)", row)  # typed receiver
