"""lock-discipline fixture: cross-thread writes missing the lock."""

import threading


class Daemon:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._status = ""
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while True:
            self._count += 1                # line 15: finding (thread side)
            with self._lock:
                self._status = "beat"       # locked: ok

    def bump(self):
        self._count = 0                     # line 20: finding (public side)

    def set_status(self, s):
        self._status = s                    # line 23: finding (thread also writes)


class NoLock:
    def __init__(self):
        self._n = 0
        self._thread = threading.Thread(target=self._tick)

    def _tick(self):
        self._n += 1                        # line 32: finding (no lock declared)

    def reset(self):
        self._n = 0                         # line 35: finding (no lock declared)
