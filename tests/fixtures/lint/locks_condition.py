"""lock-discipline fixture: Condition wait/notify discipline, multi-lock
``with a, b:`` acquires, and locktrace ``make_lock`` factory recognition."""

import threading

from tony_tpu.obs.locktrace import make_lock


class CondQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q = []

    def put(self, x):
        with self._cv:
            self._q.append(x)
            self._cv.notify()               # ok: cv held

    def put_via_owner(self, x):
        with self._lock:
            self._q.append(x)
            self._cv.notify()               # ok: the cv's OWNING lock held

    def take_bad(self):
        self._cv.wait()                     # line 26: finding (no lock)
        return self._q.pop()

    def poke_bad(self):
        self._cv.notify_all()               # line 30: finding (no lock)


class MultiAcquire:
    """``with self._a, self._b:`` counts both as held; ``make_lock`` is a
    lock factory exactly like ``threading.Lock``."""

    def __init__(self):
        self._a = make_lock("locks_condition.MultiAcquire._a")
        self._b = threading.RLock()
        self._x = 0
        self._y = 0
        self._t = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        with self._a, self._b:
            self._x += 1                    # both locks held: clean
            self._y += 1

    def reset(self):
        with self._a:
            self._x = 0                     # clean (make_lock recognized)
        with self._b:
            self._y = 0                     # clean (RLock recognized)
