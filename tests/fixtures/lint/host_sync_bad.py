"""host-sync true positives: unconditional syncs inside step loops."""


def bench_loop(step_fn, state, batch, steps):
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
        loss = float(metrics["loss"])
    return loss


def train_loop(step_fn, state, batches, jax):
    for step in range(10):
        state, metrics = step_fn(state, batches[step])
        metrics["loss"].item()
        jax.block_until_ready(metrics["grad_norm"])
    return state


def nested_syncs(step_fn, state, batch, steps, jax, log):
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
        log(float(jax.device_get(metrics["loss"])))
    return state


def sync_hiding_in_a_condition(step_fn, state, batch, steps):
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
        if float(metrics["loss"]) > 8.0:
            break
    return state
