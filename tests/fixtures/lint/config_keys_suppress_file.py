# lint: disable-file=config-keys — whole-file grandfather fixture


def read(cfg):
    return cfg.get("tony.totally.unknown")
