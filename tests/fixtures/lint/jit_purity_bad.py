"""jit-purity fixture: one finding per side-effect class."""

import functools
import time

import jax

_ACC = []


@jax.jit
def bad_print(x):
    print("tracing", x)                     # line 13: finding
    return x + 1


@functools.partial(jax.jit, static_argnames=("n",))
def bad_time(x, n):
    t = time.time()                         # line 19: finding
    return x * t


@jax.jit
def bad_mutate_closure(x):
    _ACC.append(x)                          # line 25: finding
    return x


@jax.jit
def bad_global(x):
    global _COUNT                           # line 31: finding
    _COUNT = 1
    return x


class Engine:
    @jax.jit
    def bad_self(self, x):
        self.cache = x                      # line 39: finding
        return x


def make_step():
    def inner(x):
        print(x)                            # line 45: finding (jitted below)
        return x

    return jax.jit(inner)
