"""donation-safety fixture: the approved rebind idioms and suppression."""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, x):
    return state + x


@functools.partial(jax.jit, donate_argnums=(0,))
def pair_step(cache, x):
    return x, cache


def good_rebind_inline(state, x):
    state = step(state, x)
    return state + 1


def good_rebind_later(state, x):
    new = step(state, x)
    state = new
    return state


def good_last_use(state, x):
    return step(state, x)


class Engine:
    def good_tuple_target(self, x):
        out, self.cache = pair_step(self.cache, x)
        return out

    def good_prefix_rebind(self, x):
        lengths = step(self.cache.lengths, x)
        self.cache = type(self.cache)(self.cache.k, lengths)
        return self.cache.lengths           # reads the REBOUND cache: ok


def suppressed(state, x):
    new = step(state, x)
    return state + new  # lint: disable=donation-safety — CPU-backend test fixture
