"""lock-discipline fixture: locked writes, *_locked trust, single-thread
state, RPC-handler resolution through a module-level method list."""

import threading

RPC_METHODS = ["handle_set"]


class GoodDaemon:
    def __init__(self, rpc):
        self._lock = threading.Lock()
        self._state = {}
        self._beats = 0
        rpc.register_object(self, RPC_METHODS)
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while True:
            with self._lock:
                self._state["beat"] = True      # locked: ok
            self._beats += 1                    # only _loop writes it: ok

    def handle_set(self, k, v):
        with self._lock:
            self._apply_locked(k, v)

    def _apply_locked(self, k, v):
        self._state[k] = v                      # *_locked contract: trusted

    def reset(self):
        self._state = {}  # lint: disable=lock-discipline — called pre-thread-start only


class SingleThread:
    """Helper + loop on the SAME thread must not be flagged."""

    def __init__(self):
        self._seen = 0
        self._thread = threading.Thread(target=self._loop)

    def _loop(self):
        while True:
            self._step()

    def _step(self):
        self._seen += 1                         # same thread as _loop: ok
