"""guarded-fields fixture: a field consistently written under a lock from
two concurrency contexts, then accessed lock-free."""

import threading


class Svc:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}
        self._t = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):                        # thread context writer
        with self._lock:
            self._state["tick"] = 1

    def update(self, k, v):                 # main context writer
        with self._lock:
            self._state[k] = v

    def peek(self):
        return self._state.get("tick")      # lock-free read: finding

    def wipe(self):
        self._state = {}                    # lock-free write: finding
