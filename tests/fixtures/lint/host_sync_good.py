"""host-sync clean patterns: throttled, suppressed, literal, non-step loops."""


def throttled(step_fn, state, batches, log_every):
    for step in range(10):
        state, metrics = step_fn(state, batches[step])
        if (step + 1) % log_every == 0:
            report = float(metrics["loss"])
    return report


def deliberate(step_fn, state, batch, steps):
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
        loss = float(metrics["loss"])  # lint: disable=host-sync — lockstep measurement control
    return loss


def literals_are_fine(step_fn, state, batch, steps):
    for _ in range(steps):
        state, _metrics = step_fn(state, batch)
        pad = int(8)
    return pad


def not_a_step_loop(values):
    total = 0.0
    for v in values:
        total += float(v)
    return total


def sync_after_the_loop(step_fn, state, batch, steps, jax):
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
    jax.block_until_ready(metrics["loss"])
    return float(metrics["loss"])
