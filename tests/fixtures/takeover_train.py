"""Takeover workload: count steps, publish train metrics, log every step.

A deliberately jax-free trainer for the control-plane chaos suite: each step
it atomically drops ``{"step": N, "loss": ...}`` at $TONY_TRAIN_METRICS_FILE
(the executor's metrics push feeds it to the AM, arming ``@step+N``-gated
faults like ``am-crash@step+3``) and appends the step to a per-task log in
<out_dir>. The log is the test's evidence: exactly one ``start`` line means
the child never restarted across the AM takeover, and the recorded step
sequence must be strictly 1..N — monotonic, no regression, no replay.

Usage: takeover_train.py <steps> <out_dir>
"""

import json
import os
import sys
import time

steps, out_dir = int(sys.argv[1]), sys.argv[2]
metrics_path = os.environ["TONY_TRAIN_METRICS_FILE"]
idx = os.environ["TASK_INDEX"]
attempt = os.environ.get("TONY_RESTART_ATTEMPT", "0")
os.makedirs(out_dir, exist_ok=True)

with open(os.path.join(out_dir, f"steps-{idx}.log"), "a", buffering=1) as log:
    log.write(f"start attempt={attempt} pid={os.getpid()}\n")
    for s in range(1, steps + 1):
        tmp = metrics_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": s, "loss": 1.0 / s}, f)
        os.replace(tmp, metrics_path)
        log.write(f"step {s}\n")
        time.sleep(0.15)

print(f"fixture: takeover worker {idx} completed {steps} steps")
