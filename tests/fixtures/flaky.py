"""Fails on gang attempt 0, succeeds after a gang restart (elasticity fixture)."""
import os, sys
attempt = int(os.environ.get("TONY_RESTART_ATTEMPT", "0"))
print(f"fixture: attempt {attempt}")
sys.exit(1 if attempt == 0 else 0)
