"""Trivial failure workload (reference test fixture exit_1.py analog)."""
import sys
print("fixture: failing")
sys.exit(1)
