"""Fixture: real torch.distributed all-reduce from the TorchRuntime env.

The PyTorchRuntime-analog parity proof (SURVEY.md §2.2): workers read only
the injected MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE contract — exactly what
a user's DDP script reads — form a gloo process group, and all-reduce their
ranks. CPU-only (gloo); on TPU hosts the same env drives torch-xla.
"""

import datetime
import os
import sys

import torch
import torch.distributed as dist

rank = int(os.environ["RANK"])
world = int(os.environ["WORLD_SIZE"])

dist.init_process_group(
    "gloo",
    init_method=os.environ["INIT_METHOD"],
    rank=rank,
    world_size=world,
    timeout=datetime.timedelta(seconds=60),
)
t = torch.tensor([float(rank + 1)])
dist.all_reduce(t, op=dist.ReduceOp.SUM)
want = world * (world + 1) / 2
assert float(t) == want, (float(t), want)
dist.destroy_process_group()
print(f"torch_allreduce ok: rank {rank}/{world}, sum={float(t)}")
sys.exit(0)
