"""Fixture: multi-process SPMD training step over a global mesh.

The full multi-host training proof (SURVEY.md §2.6, §5.8): two tony-launched
worker processes each own 4 virtual CPU devices; after `init_distributed`
the global mesh spans all 8 devices across both processes, parameters are
sharded over the global `fsdp` axis, and one train step runs with XLA
collectives crossing the process boundary (the ICI/DCN path on real slices).
Every rank must see the same finite loss — proof the gradient all-reduce
spanned processes.
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

# 4 virtual CPU devices per process (8 global across the 2-worker gang)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+", "", os.environ.get("XLA_FLAGS", "")
).strip()
os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=4").strip()

import dataclasses  # noqa: E402
import functools  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tony_tpu.models import llama  # noqa: E402
from tony_tpu.parallel import MeshSpec  # noqa: E402
from tony_tpu.runtime import init_distributed  # noqa: E402
from tony_tpu.train import OptimizerConfig, make_train_step, sharded_init  # noqa: E402

init_distributed()
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

cfg = dataclasses.replace(llama.LLAMA_TINY, max_seq=32)
mesh = MeshSpec(fsdp=8).build()
opt = OptimizerConfig(warmup_steps=0, total_steps=5).build()
state = sharded_init(lambda: llama.init(jax.random.PRNGKey(0), cfg), llama.sharding_rules(cfg), mesh, opt)
step = make_train_step(functools.partial(llama.loss_fn, cfg=cfg, mesh=mesh), opt)

# global batch sharded over fsdp: each process contributes its local half
B, T = 8, 32
local = np.asarray(
    llama.synthetic_batch(
        jax.random.fold_in(jax.random.PRNGKey(1), jax.process_index()), B // 2, T, cfg
    )["tokens"]
)
sharding = jax.NamedSharding(mesh, jax.sharding.PartitionSpec(("data", "fsdp")))
batch = {"tokens": jax.make_array_from_process_local_data(sharding, local)}

state, metrics = step(state, batch)
loss = float(metrics["loss"])
assert np.isfinite(loss), loss
print(f"spmd_train ok: rank {jax.process_index()}/2, loss={loss:.4f}")
