"""Fixture: stands in for a Jupyter server — binds $NOTEBOOK_PORT, serves HTTP.

(The reference tests fake training with tiny scripts; same idea for the
notebook path: assert the env contract, serve something proxyable.)
"""

import http.server
import os

PORT = int(os.environ["NOTEBOOK_PORT"])


class Handler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        body = b"notebook-fixture-ok"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


http.server.HTTPServer(("0.0.0.0", PORT), Handler).serve_forever()
