"""Multi-fault soak workload: train with checkpoints, crash, resume through a
chaos-torn checkpoint.

Attempt 0 trains 4 steps (checkpointing every 2) then exits nonzero. Under
the soak schedule (``ckpt-corrupt:latest`` + background ``rpc-drop``), the
restarted attempt's ``restore_or_init`` finds step 4 torn, quarantines it,
falls back to step 2 ("resumed from checkpoint step 2"), and completes the
full 8 steps. The soak test asserts the verdict, the fallback-resume line,
the exactly-once gang-complete invariant, and that no orphans survive.
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tony_tpu.cli.distributed_smoke import sanitize_env_for_cpu_group  # noqa: E402

sanitize_env_for_cpu_group()  # one CPU device: the tiny batch can't shard over 8

from tony_tpu.models import llama  # noqa: E402
from tony_tpu.train.checkpoint import CheckpointManager  # noqa: E402
from tony_tpu.train.loop import LoopConfig, run_lm_training  # noqa: E402

attempt = int(os.environ.get("TONY_RESTART_ATTEMPT", "0"))
ckpt_dir = os.path.join(os.environ["TONY_STAGING_DIR"], "ckpt")

cfg = dataclasses.replace(llama.LLAMA_TINY, max_seq=16)
loop = LoopConfig(
    steps=4 if attempt == 0 else 8,
    batch_size=2,
    seq_len=16,
    log_every=100,
    checkpoint_dir=ckpt_dir,
    checkpoint_every=2,
    warmup_steps=0,
)
run_lm_training(llama, cfg, loop)

if attempt == 0:
    print("fixture: attempt 0 crashing after checkpointed steps")
    sys.exit(1)

# the chaos ckpt-corrupt fault tore step 4 at restore time: the quarantine
# must have fallen back to step 2 and the corrupt dir must be out of the way
assert os.path.isdir(os.path.join(ckpt_dir, ".corrupt-4")), os.listdir(ckpt_dir)
final_mgr = CheckpointManager(ckpt_dir)
assert final_mgr.latest_step() == 8, final_mgr.latest_step()
print("fixture: soak resume run completed to step 8")
