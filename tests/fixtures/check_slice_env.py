"""Fixture: assert the multi-slice pool placement contract.

Every task must see TPU_SLICE_ID in [0, TPU_NUM_SLICES), chip coords, and
the per-slice topology; prints its slice id so the test can check the gang
actually spanned slices.
"""

import os
import sys

slice_id = int(os.environ["TPU_SLICE_ID"])
num_slices = int(os.environ["TPU_NUM_SLICES"])
assert 0 <= slice_id < num_slices, (slice_id, num_slices)
assert os.environ["TPU_CHIP_COORDS"], "chip coords missing"
assert "x" in os.environ["TPU_SLICE_TOPOLOGY"]
print(f"SLICE_PLACEMENT {os.environ['JOB_NAME']}:{os.environ['TASK_INDEX']} -> {slice_id}")
sys.exit(0)
