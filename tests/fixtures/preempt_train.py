"""Fixture: cooperative-preemption victim (checkpoint-then-yield headline).

A small gang that trains "forever" on attempt 0 (a 50x step budget — it
exists to BE preempted) and to the target step count once resumed. Rank 0
runs a real Orbax train state through ``restore_or_init`` with NO periodic
checkpoints — the only mid-run save is the urgent one the pool's drain
triggers through :class:`tony_tpu.train.checkpoint.UrgentSaveSignal` (the
exact class the production train loop polls), so the resumed step PROVES
whether the eviction was cooperative:

- drain path: resume step == the urgent checkpoint (> 0);
- kill path (drain-ms 0): resume step == 0, and the whole first attempt is
  the ``restart_rework`` the goodput ledger must meter.

Non-checkpointing ranks acknowledge the drain with their current step (their
state lives in rank 0's checkpoint), so the AM's all-ranks yield gate is
exercised at world > 1 too.

Every rank publishes its step to $TONY_TRAIN_METRICS_FILE each tick (the
piggyback the AM snapshots into the .jhist — the rework derivation reads
exactly these), and rank 0 publishes its resume step to
``<shared>/resume-<attempt>.json`` for the test's assertions.

Usage: preempt_train.py <shared_dir> <steps> <step_ms>
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

from tony_tpu import constants  # noqa: E402
from tony_tpu.train.checkpoint import UrgentSaveSignal, restore_or_init  # noqa: E402

shared, target_steps, step_ms = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
attempt = int(os.environ.get("TONY_RESTART_ATTEMPT", "0"))
rank = int(os.environ[constants.ENV_TASK_INDEX])
os.makedirs(shared, exist_ok=True)
ckpt_dir = os.path.join(shared, "ckpt")
metrics_file = os.environ.get(constants.ENV_TRAIN_METRICS_FILE)

# attempt 0 exists to be preempted; resumed attempts finish the job
steps = target_steps * 50 if attempt == 0 else target_steps


def publish(path, obj):
    tmp = f"{path}.tmp{rank}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


if rank == 0:
    state, mgr, start = restore_or_init(
        ckpt_dir, lambda: {"w": np.zeros(4, np.float64)}, use_async=False)
    if start:
        print(f"[train] resumed from checkpoint step {start}", flush=True)
    publish(os.path.join(shared, f"resume-{attempt}.json"), {"step": start})
else:
    state, mgr, start = None, None, 0

urgent = UrgentSaveSignal()
for t in range(start, steps):
    time.sleep(step_ms / 1000.0)
    if rank == 0:
        state["w"] = state["w"] * 0.9 + 0.1 * (t + 1)
    if metrics_file:
        publish(metrics_file, {"step": t + 1, "loss": round(1.0 / (t + 1), 4)})
    publish(os.path.join(shared, f"step-r{rank}.json"), {"step": t + 1})
    req = urgent.poll()
    if req is not None:
        if mgr is not None:
            # the urgent pre-preemption save: the ONLY mid-run checkpoint
            mgr.save(t + 1, state, force=True)
            mgr.wait()
            print(f"[train] urgent checkpoint at step {t + 1}", flush=True)
        urgent.acknowledge(req, t + 1)

if mgr is not None:
    mgr.close()
print(f"preempt_train attempt {attempt} rank {rank} finished at step {steps}", flush=True)
sys.exit(0)
