"""Trivial success workload (reference test fixture exit_0.py analog)."""
print("fixture: ok")
