"""Fixture: thin wrapper over the packaged distributed smoke workload.

Kept as a file fixture so e2e tests exercise the same `--executes "python
<script>"` path users take; the actual collective logic lives in
tony_tpu/cli/distributed_smoke.py (shipped with the package, also behind
``tony mini --distributed``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tony_tpu.cli.distributed_smoke import main  # noqa: E402

raise SystemExit(main())
