"""Fixture: a drain-aware long-runner for per-task drain tests.

Parks forever, polling ``$TONY_TRAIN_METRICS_FILE.drain`` (the control file
the executor's DrainCourier drops) exactly like serving_http's drain
watcher; on a notice it immediately publishes ``.drain.done`` with a fixed
step and keeps parking — the AM-side ``request_task_drain`` episode should
then read ``drained: true`` while the process stays alive (yielding is the
caller's move). A metrics heartbeat publishes a step so the courier
machinery has a metrics path to hang the control file on.

Usage: drain_echo.py [ack_step]
"""

import json
import os
import sys
import time

METRICS = os.environ.get("TONY_TRAIN_METRICS_FILE", "")
ACK_STEP = int(sys.argv[1]) if len(sys.argv) > 1 else 7


def write_atomic(path, obj):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


step = 0
acked = set()
while True:
    step += 1
    if METRICS:
        write_atomic(METRICS, {"step": step})
        try:
            with open(METRICS + ".drain") as f:
                req_id = json.load(f).get("req_id")
        except (OSError, ValueError):
            req_id = None
        if req_id and req_id not in acked:
            acked.add(req_id)
            write_atomic(METRICS + ".drain.done", {"req_id": req_id, "step": ACK_STEP})
    time.sleep(0.1)
