"""Elasticity fixture: train with checkpoints, die, resume after gang restart.

Attempt 0 trains 4 steps (checkpointing every 2) then exits nonzero —
simulating a mid-run crash. The AM's gang restart relaunches the task with
TONY_RESTART_ATTEMPT=1; this attempt must find the checkpoint, resume from
step >= 2 (run_lm_training prints "resumed from checkpoint step N"), and
finish the full 8 steps. The E2E test asserts on both the verdict and the
resume line in this task's stdout log.
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tony_tpu.cli.distributed_smoke import sanitize_env_for_cpu_group  # noqa: E402

sanitize_env_for_cpu_group()  # one CPU device: the tiny batch can't shard over 8

from tony_tpu.models import llama  # noqa: E402
from tony_tpu.train.checkpoint import CheckpointManager  # noqa: E402
from tony_tpu.train.loop import LoopConfig, run_lm_training  # noqa: E402

attempt = int(os.environ.get("TONY_RESTART_ATTEMPT", "0"))
ckpt_dir = os.path.join(os.environ["TONY_STAGING_DIR"], "ckpt")

if attempt > 0:
    mgr = CheckpointManager(ckpt_dir)
    latest = mgr.latest_step() or 0
    assert latest >= 2, f"gang restart found no checkpoint to resume from (latest={latest})"
    print(f"fixture: attempt {attempt} resuming, latest checkpoint step {latest}")

cfg = dataclasses.replace(llama.LLAMA_TINY, max_seq=16)
loop = LoopConfig(
    steps=4 if attempt == 0 else 8,
    batch_size=2,
    seq_len=16,
    log_every=100,
    checkpoint_dir=ckpt_dir,
    checkpoint_every=2,
    warmup_steps=0,
)
run_lm_training(llama, cfg, loop)

if attempt == 0:
    print("fixture: attempt 0 crashing after checkpointed steps")
    sys.exit(1)

final_mgr = CheckpointManager(ckpt_dir)
assert final_mgr.latest_step() == 8, final_mgr.latest_step()
print("fixture: resume run completed to step 8")
