"""Record which pool node launched this task (agent-launch E2E proof)."""
import os

out = os.path.join(
    os.environ["TONY_STAGING_DIR"],
    f"node_of_{os.environ['JOB_NAME']}_{os.environ['TASK_INDEX']}.txt",
)
with open(out, "w") as f:
    f.write(os.environ.get("TONY_NODE_NAME", ""))
