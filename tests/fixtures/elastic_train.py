"""Fixture: the elastic workload for the node-lost → run-smaller E2E.

Attempt 0: a 2-process gang trains 4 steps (checkpoints at 2 and 4), then
sleeps so the test can SIGKILL one node for good. The AM's capacity re-check
downsizes the gang (tony.worker.min-instances=1) and attempt 1 — ONE process
— resumes from the checkpoint onto the smaller mesh and trains to step 8.
The global-order loader replays the exact sample stream across the shard-
count change (data/native.py contract), so the final loss matches an
uninterrupted fixed-shape reference run up to reduction-order noise.

Usage: elastic_train.py <data_dir> <ckpt_dir>
"""

import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

# 2 virtual CPU devices per process: attempt 0 meshes over 4 global devices,
# the downsized attempt 1 over 2 — a REAL cross-shape restore
os.environ["JAX_PLATFORMS"] = "cpu"
flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+", "", os.environ.get("XLA_FLAGS", "")
).strip()
os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=2").strip()

from tony_tpu.models import llama  # noqa: E402
from tony_tpu.train.loop import LoopConfig, run_lm_training  # noqa: E402

data_dir, ckpt_dir = sys.argv[1], sys.argv[2]
attempt = int(os.environ.get("TONY_RESTART_ATTEMPT", "0"))
steps = 4 if attempt == 0 else 8
out = run_lm_training(
    llama, llama.LLAMA_TINY,
    LoopConfig(
        steps=steps, schedule_steps=8, batch_size=4, seq_len=64, log_every=1,
        warmup_steps=0, data_dir=data_dir, checkpoint_dir=ckpt_dir,
        checkpoint_every=2,
    ),
)
import jax  # noqa: E402

print(
    f"elastic attempt {attempt}: step={int(out['step'])} "
    f"loss={out['loss']:.6f} procs={jax.process_count()}",
    flush=True,
)
if attempt == 0:
    time.sleep(600)  # hold the gang so the test can kill a node mid-run
sys.exit(0)
