"""Goodput-headline workload: jax-free stepper with a deliberate straggler.

Every rank advances one step per tick, publishing the train step report at
$TONY_TRAIN_METRICS_FILE and a registry snapshot (with a cumulative
``tony_train_step_seconds`` histogram) at the ``.obs`` sibling — exactly the
piggyback contract the real train loop honors — so the AM's goodput tick
sees live per-rank step times. The rank named by ``slow_rank`` sleeps
``slow_mult``× the base step, making it a detectable straggler. A tiny
step-counter "checkpoint" is persisted to the shared dir every
``ckpt_every`` steps and resumed after a gang restart, so the restart loses
a provable amount of work (the rework the ledger must attribute).

Usage: goodput_train.py <shared_dir> <steps> <base_ms> <slow_rank> <slow_mult> <ckpt_every>
"""

import json
import os
import sys
import time

from tony_tpu.obs import metrics as obs_metrics

shared, steps, base_ms, slow_rank, slow_mult, ckpt_every = (
    sys.argv[1], int(sys.argv[2]), float(sys.argv[3]), int(sys.argv[4]),
    float(sys.argv[5]), int(sys.argv[6]))
rank = int(os.environ["TASK_INDEX"])
metrics_path = os.environ["TONY_TRAIN_METRICS_FILE"]
attempt = int(os.environ.get("TONY_RESTART_ATTEMPT", "0"))

step_s = base_ms / 1000.0 * (slow_mult if rank == slow_rank else 1.0)
hist = obs_metrics.histogram(
    "tony_train_step_seconds", "per-step wall time")

ckpt_path = os.path.join(shared, "ckpt.json")
start = 0
try:
    with open(ckpt_path) as f:
        start = int(json.load(f)["step"])
    print(f"fixture: rank {rank} resumed from checkpoint step {start}")
except (OSError, ValueError, KeyError):
    pass


def drop(path, obj):
    tmp = f"{path}.tmp{rank}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


for s in range(start + 1, steps + 1):
    time.sleep(step_s)
    hist.observe(step_s)
    drop(metrics_path, {
        "step": s,
        "loss": round(2.0 / s, 4),
        "mfu": round(0.4 + 0.001 * s, 4),
        "tokens_per_sec": 1000.0 + s,
    })
    drop(metrics_path + ".obs",
         [m for m in obs_metrics.REGISTRY.snapshot() if m["samples"]])
    if rank == 0 and s % ckpt_every == 0:
        drop(ckpt_path, {"step": s})

print(f"fixture: rank {rank} attempt {attempt} finished at step {steps}")
