"""History-server workload: quick jax-free steps publishing train metrics.

Each step it atomically drops ``{"step": N, "loss": ..., "mfu": ...,
"tokens_per_sec": ...}`` at $TONY_TRAIN_METRICS_FILE; the executor's metrics
push feeds the AM, whose METRICS_SNAPSHOT events become the series the
history server distills — so the e2e can assert a real MFU trend across two
ingested runs.

Usage: history_train.py <steps> <mfu_base>
"""

import json
import os
import sys
import time

steps, mfu_base = int(sys.argv[1]), float(sys.argv[2])
metrics_path = os.environ["TONY_TRAIN_METRICS_FILE"]

for s in range(1, steps + 1):
    tmp = metrics_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({
            "step": s,
            "loss": round(2.0 / s, 4),
            "mfu": round(mfu_base + 0.002 * s, 4),
            "tokens_per_sec": 1000.0 + 10 * s,
        }, f)
    os.replace(tmp, metrics_path)
    time.sleep(0.12)

print(f"fixture: history worker finished {steps} steps")
