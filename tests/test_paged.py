"""Paged KV cache + prefix caching (VERDICT r3 #4).

Block-paged page pool with per-slot page tables, refcounted shared-prefix
reuse, reservation-based admission. Properties under test:
- the paged Pallas kernel matches the dense ragged kernel bit-for-bit in
  math (interpret mode on CPU), including sliding windows and page-table
  indirection through a shuffled pool;
- the paged ENGINE matches the dense engine's greedy outputs exactly;
- N same-prefix requests cost ~1 prefill (prefix_hit_tokens accounting)
  and still match the dense engine;
- a pool smaller than slots × max_pages (the HBM win) still serves
  everything, waiting at admission instead of failing;
- allocator invariants: page 0 never allocated, LRU reuse-pool eviction,
  refcount sharing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models.llama import LLAMA_TINY, init
from tony_tpu.models.paged_cache import PageAllocator, prefix_keys
from tony_tpu.models.serving import ContinuousBatcher


def _params():
    return init(jax.random.PRNGKey(0), LLAMA_TINY)


# ---------------------------------------------------------------------------
# Kernel parity: paged vs dense ragged, shuffled pages, with/without SWA
# ---------------------------------------------------------------------------
class TestPagedKernel:
    def test_matches_dense_ragged_kernel(self):
        from tony_tpu.ops.decode_attention import (
            paged_decode_attention,
            ragged_decode_attention,
        )

        S, H, Hkv, maxT, Dh, PLEN = 3, 4, 2, 256, 128, 64
        max_pages = maxT // PLEN
        P = S * max_pages + 2
        ks = jax.random.split(jax.random.PRNGKey(3), 5)
        q = jax.random.normal(ks[0], (S, H, Dh), jnp.float32)
        ck = jax.random.normal(ks[1], (S, Hkv, maxT, Dh), jnp.float32)
        cv = jax.random.normal(ks[2], (S, Hkv, maxT, Dh), jnp.float32)
        cur_k = jax.random.normal(ks[3], (S, Hkv, Dh), jnp.float32)
        cur_v = jax.random.normal(ks[4], (S, Hkv, Dh), jnp.float32)
        lengths = jnp.array([0, 129, 250], jnp.int32)
        # scatter the dense caches into a SHUFFLED page pool: parity then
        # proves the page-table indirection, not just the math
        rng = np.random.default_rng(0)
        pt = rng.permutation(P)[: S * max_pages].reshape(S, max_pages).astype(np.int32)
        kp = np.zeros((P, Hkv, PLEN, Dh), np.float32)
        vp = np.zeros((P, Hkv, PLEN, Dh), np.float32)
        for s in range(S):
            for j in range(max_pages):
                kp[pt[s, j]] = np.asarray(ck)[s, :, j * PLEN:(j + 1) * PLEN]
                vp[pt[s, j]] = np.asarray(cv)[s, :, j * PLEN:(j + 1) * PLEN]
        for window in (0, 100):
            want = ragged_decode_attention(
                q, ck, cv, lengths, cur_k=cur_k, cur_v=cur_v,
                window=window, chunk=PLEN,
            )
            got = paged_decode_attention(
                q, jnp.asarray(kp), jnp.asarray(vp), lengths, jnp.asarray(pt),
                cur_k=cur_k, cur_v=cur_v, window=window,
            )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5,
                err_msg=f"window={window}",
            )

    def test_rejects_unaligned_page_len(self):
        """Direct kernel callers get the same sublane-alignment guard the
        engine enforces: page_len must be a multiple of 8 (ADVICE r4)."""
        from tony_tpu.ops.decode_attention import paged_decode_attention

        S, H, Hkv, Dh = 1, 2, 1, 128
        for plen in (4, 12):
            kp = jnp.zeros((2, Hkv, plen, Dh), jnp.float32)
            with pytest.raises(ValueError, match="multiple of 8"):
                paged_decode_attention(
                    jnp.zeros((S, H, Dh), jnp.float32), kp, kp,
                    jnp.zeros((S,), jnp.int32),
                    jnp.zeros((S, 1), jnp.int32),
                    cur_k=jnp.zeros((S, Hkv, Dh), jnp.float32),
                    cur_v=jnp.zeros((S, Hkv, Dh), jnp.float32),
                )


# ---------------------------------------------------------------------------
# Allocator invariants
# ---------------------------------------------------------------------------
class TestPageAllocator:
    def test_page_zero_never_allocated(self):
        a = PageAllocator(6)
        got = a.alloc(5)
        assert 0 not in got and sorted(got) == [1, 2, 3, 4, 5]
        with pytest.raises(RuntimeError, match="exhausted"):
            a.alloc(1)

    def test_release_unkeyed_returns_to_free(self):
        a = PageAllocator(4)
        p = a.alloc(1)[0]
        a.release(p)
        assert a.available() == 3 and p in a.alloc(3)

    def test_refcount_sharing(self):
        a = PageAllocator(4)
        keys = prefix_keys([1, 2, 3, 4], 2)  # two full pages
        pages = a.alloc(2)
        for p, k in zip(pages, keys):
            a.register(p, k)
        shared = a.match_prefix(keys)
        assert shared == pages  # both matched and pinned (ref 2)
        for p in pages:
            a.release(p)  # first holder retires
        assert a.available() == 1  # still live via the second holder
        for p in pages:
            a.release(p)  # second holder retires → reuse pool
        assert a.available() == 3
        assert a.match_prefix(keys) == pages  # resurrected from reuse pool
        for p in pages:
            a.release(p)

    def test_lru_eviction_of_reuse_pool(self):
        a = PageAllocator(4)  # 3 usable
        keys = prefix_keys([9, 9, 8, 8, 7, 7], 2)
        pages = a.alloc(3)
        for p, k in zip(pages, keys):
            a.register(p, k)
        for p in pages:
            a.release(p)  # all parked in the reuse pool
        fresh = a.alloc(2)  # evicts the two LRU pages
        assert set(fresh) == set(pages[:2])
        assert a.match_prefix(keys) == []  # chain broken at evicted page 0
        assert a.match_prefix(keys[1:2]) == []  # keys are cumulative chains


# ---------------------------------------------------------------------------
# Engine: parity, sharing, capacity
# ---------------------------------------------------------------------------
class TestPagedEngine:
    @pytest.mark.slow  # ~10 s full-engine decode parity sweep
    def test_greedy_parity_with_dense_engine(self):
        params = _params()
        dense = ContinuousBatcher(params, LLAMA_TINY, num_slots=3, max_len=128,
                                  decode_chunk=4)
        paged = ContinuousBatcher(params, LLAMA_TINY, num_slots=3, max_len=128,
                                  decode_chunk=4, kv="paged", page_len=32)
        prompts = [[1, 2, 3], [7, 8, 9, 10, 11], list(range(1, 40))]
        rd = [dense.submit(p, max_new_tokens=8) for p in prompts]
        rp = [paged.submit(p, max_new_tokens=8) for p in prompts]
        outd, outp = dense.run(), paged.run()
        for a, b in zip(rd, rp):
            assert outd[a] == outp[b]

    @pytest.mark.slow  # ~8 s full-engine prefix-cache burst
    def test_shared_prefix_burst_prefills_once(self):
        """VERDICT done-when (a): N same-prefix slots ~1 prefill cost."""
        params = _params()
        paged = ContinuousBatcher(params, LLAMA_TINY, num_slots=4, max_len=128,
                                  decode_chunk=4, kv="paged", page_len=32)
        prefix = list(range(3, 3 + 64))  # exactly 2 full pages
        reqs = [paged.submit(prefix + [100 + i], max_new_tokens=4) for i in range(4)]
        out = paged.run()
        # 3 of the 4 requests reuse both prefix pages: 3 × 64 skipped tokens
        assert paged.prefix_hit_tokens == 3 * 64
        dense = ContinuousBatcher(params, LLAMA_TINY, num_slots=4, max_len=128,
                                  decode_chunk=4)
        rd = [dense.submit(prefix + [100 + i], max_new_tokens=4) for i in range(4)]
        outd = dense.run()
        for a, b in zip(rd, reqs):
            assert outd[a] == out[b]

    def test_late_arrival_reuses_resident_prefix(self):
        params = _params()
        paged = ContinuousBatcher(params, LLAMA_TINY, num_slots=2, max_len=128,
                                  decode_chunk=4, kv="paged", page_len=32)
        prefix = list(range(5, 5 + 32))
        r1 = paged.submit(prefix + [70], max_new_tokens=3)
        out1 = paged.run()
        # first request retired; its full prompt page parks in the reuse pool
        r2 = paged.submit(prefix + [71], max_new_tokens=3)
        out2 = paged.run()
        assert paged.prefix_hit_tokens == 32
        assert len(out2[r2]) == 3 and len(out1[r1]) == 3

    def test_small_pool_overcommit_waits_and_serves(self):
        """VERDICT done-when (b): pool smaller than slots × max_pages —
        admission waits for pages, every request still completes."""
        params = _params()
        paged = ContinuousBatcher(params, LLAMA_TINY, num_slots=4, max_len=128,
                                  decode_chunk=4, kv="paged", page_len=32,
                                  num_pages=9)  # 8 usable vs 4 slots × 4 pages
        rids = [paged.submit([5 + i], max_new_tokens=30) for i in range(6)]
        out = paged.run()
        assert len(out) == 6 and all(len(v) == 30 for v in out.values())
        assert paged.allocator.live_pages() == 0  # everything reclaimed

    def test_oversized_request_rejected_at_submit(self):
        params = _params()
        paged = ContinuousBatcher(params, LLAMA_TINY, num_slots=2, max_len=128,
                                  decode_chunk=4, kv="paged", page_len=32,
                                  num_pages=3)  # 2 usable pages = 64 positions
        with pytest.raises(ValueError, match="pages"):
            paged.submit(list(range(1, 100)), max_new_tokens=20)

    @pytest.mark.slow  # ~10 s int8 engine parity sweep
    def test_int8_paged_matches_dense(self):
        """Composition: int8 weight-only trees decode through the paged
        cache identically to the dense engine (the cache stays bf16; only
        the _mm dispatch differs)."""
        from tony_tpu.ops import quant

        params = _params()
        qparams, _, _ = quant.quantize_tree(params, min_size=1 << 10)
        dense = ContinuousBatcher(qparams, LLAMA_TINY, num_slots=2, max_len=64,
                                  decode_chunk=4)
        paged = ContinuousBatcher(qparams, LLAMA_TINY, num_slots=2, max_len=64,
                                  decode_chunk=4, kv="paged", page_len=32)
        a = dense.submit([3, 4, 5], max_new_tokens=6)
        b = paged.submit([3, 4, 5], max_new_tokens=6)
        assert dense.run()[a] == paged.run()[b]

    @pytest.mark.slow  # ~10 s mixtral engine parity sweep
    def test_mixtral_paged_matches_dense(self):
        """Composition: the MoE decode FFN (all-expert + top-k combine)
        runs through the paged cache identically to dense."""
        import dataclasses

        from tony_tpu.models import mixtral

        # f32: in bf16 a 1-ulp cross-implementation difference gets amplified
        # by the MoE router into a greedy-token flip on knife-edge prompts
        # (same pin as test_serving.TestMixtralServing)
        mcfg = dataclasses.replace(mixtral.MIXTRAL_TINY, max_seq=64, dtype="float32")
        params = mixtral.init(jax.random.PRNGKey(2), mcfg)
        dense = ContinuousBatcher(params, mcfg, num_slots=2, max_len=64,
                                  decode_chunk=4)
        paged = ContinuousBatcher(params, mcfg, num_slots=2, max_len=64,
                                  decode_chunk=4, kv="paged", page_len=32)
        a = dense.submit([5, 6, 7, 8], max_new_tokens=6)
        b = paged.submit([5, 6, 7, 8], max_new_tokens=6)
        assert dense.run()[a] == paged.run()[b]

    @pytest.mark.slow  # ~9 s SWA engine parity sweep
    def test_swa_window_smaller_than_chunk_matches_dense(self):
        """The staged fold's out-of-window mask only fires when the sliding
        window is SMALLER than the decode chunk (staged positions can fall
        below the band) — lock that case in."""
        import dataclasses

        cfg = dataclasses.replace(LLAMA_TINY, sliding_window=3)
        params = init(jax.random.PRNGKey(4), cfg)
        dense = ContinuousBatcher(params, cfg, num_slots=2, max_len=128,
                                  decode_chunk=8)
        paged = ContinuousBatcher(params, cfg, num_slots=2, max_len=128,
                                  decode_chunk=8, kv="paged", page_len=32)
        prompt = list(range(2, 2 + 20))
        a = dense.submit(prompt, max_new_tokens=12)
        b = paged.submit(prompt, max_new_tokens=12)
        assert dense.run()[a] == paged.run()[b]

    @pytest.mark.slow  # ~8 s SWA engine parity sweep
    def test_swa_paged_matches_dense(self):
        import dataclasses

        cfg = dataclasses.replace(LLAMA_TINY, sliding_window=48)
        params = init(jax.random.PRNGKey(1), cfg)
        dense = ContinuousBatcher(params, cfg, num_slots=2, max_len=128,
                                  decode_chunk=4)
        paged = ContinuousBatcher(params, cfg, num_slots=2, max_len=128,
                                  decode_chunk=4, kv="paged", page_len=32)
        prompt = list(range(2, 2 + 60))  # longer than the window
        a = dense.submit(prompt, max_new_tokens=10)
        b = paged.submit(prompt, max_new_tokens=10)
        assert dense.run()[a] == paged.run()[b]
