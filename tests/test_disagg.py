"""Disaggregated prefill/decode serving + the sharded router tier
(tony_tpu/serve/disagg.py; docs/serving.md "Disaggregated serving").

Unit layer: the consistent-hash shard ring, the paged-KV export→ship→adopt
contract over real (tiny, CPU-interpret) paged engines, the coordinator's
prefill leg, and the shard front's exactly-once re-pin accounting.

E2E layer (the headline): a prefill-tier + decode-tier fleet behind TWO
router shards and one front, under the open-loop loadgen — a multi-turn
session workload completes with ZERO client-visible failures, KV pages are
adopted (not recomputed) on decode, and the run emits a SERVE_BENCH record
that satisfies the gate schema with the new handoff-latency field.
"""

import json
import sys
import threading
import urllib.request

import pytest

sys.path.insert(0, "tests")

from test_serve_fleet import (  # noqa: E402
    FakeAM,
    FakeReplica,
    dead_url,
    make_health,
    post_router,
)

from tony_tpu.obs import metrics as obs_metrics  # noqa: E402
from tony_tpu.serve import sessions as sessions_mod  # noqa: E402
from tony_tpu.serve.autoscaler import AutoscalePolicy, Autoscaler  # noqa: E402
from tony_tpu.serve.disagg import (  # noqa: E402
    DisaggCoordinator,
    RouterShardFront,
    ShardRing,
)
from tony_tpu.serve.health import FleetSignals  # noqa: E402
from tony_tpu.serve.loadgen import LoadGenerator, LoadSpec  # noqa: E402
from tony_tpu.serve.router import FleetRouter  # noqa: E402
from tony_tpu.serve.sessions import SessionTable  # noqa: E402

pytestmark = [pytest.mark.serve, pytest.mark.disagg]


class TieredAM(FakeAM):
    """FakeAM with two jobtypes (``serve`` + ``prefill``) — set_task keys on
    (name, index) so one application can carry both tiers."""

    def set_task(self, name, idx, url, status="RUNNING"):
        self.tasks[(name, idx)] = {
            "name": name, "index": idx, "url": url, "status": status}

    def drop_task(self, name, idx):
        self.tasks.pop((name, idx), None)


def _counter(name, **labels):
    # same name+shape re-registration hands back the existing instrument
    m = obs_metrics.REGISTRY.counter(name, labelnames=tuple(labels))
    return m.value(**labels)


def make_router(health, sessions=None, disagg=None, **kw):
    kw.setdefault("retries", 2)
    kw.setdefault("failover_deadline_s", 5.0)
    return FleetRouter(health, sessions=sessions or SessionTable(),
                       disagg=disagg, **kw).start()


# ---------------------------------------------------------------------------
# consistent-hash shard ring
# ---------------------------------------------------------------------------
class TestShardRing:
    def test_assignment_is_deterministic_and_covers_all_shards(self):
        r1, r2 = ShardRing(3), ShardRing(3)
        got = {f"s{i}": r1.assign(f"s{i}") for i in range(300)}
        assert got == {k: r2.assign(k) for k in got}  # pure function
        assert set(got.values()) == {0, 1, 2}          # no starving shard

    def test_only_the_dead_shards_sessions_move(self):
        ring = ShardRing(3)
        keys = [f"session-{i}" for i in range(300)]
        before = {k: ring.assign(k, {0, 1, 2}) for k in keys}
        after = {k: ring.assign(k, {0, 2}) for k in keys}
        moved = [k for k in keys if before[k] != after[k]]
        # minimal disruption: exactly the dead shard's arc re-resolves, and
        # it lands only on live shards
        assert moved == [k for k in keys if before[k] == 1]
        assert all(after[k] in (0, 2) for k in keys)

    def test_no_live_shard_resolves_none(self):
        ring = ShardRing(2)
        assert ring.assign("s", set()) is None
        assert ShardRing(0).assign("s") is None


# ---------------------------------------------------------------------------
# paged-KV handoff over real engines (CPU interpret via conftest)
# ---------------------------------------------------------------------------
class TestKvHandoff:
    def _paged_server(self, **kw):
        from test_serve import http_server, tiny_engine

        from tony_tpu.models.serving_http import EngineServer

        defaults = dict(kv="paged", page_len=8, num_slots=2, max_len=64)
        defaults.update(kw)
        role = defaults.pop("role", "serve")
        srv = EngineServer(tiny_engine(**defaults), role=role).start()
        httpd, url = http_server(srv)
        return srv, httpd, url

    def test_export_ship_adopt_then_decode_prefix_hits(self):
        from test_serve import post_raw, tiny_engine

        from tony_tpu.models.serving_http import EngineServer

        pre, ph, pre_url = self._paged_server(role="prefill")
        dec, dh, dec_url = self._paged_server()
        try:
            prompt = list(range(1, 25))  # 24 tokens = 3 full pages
            st, resp = post_raw(pre_url + "/v1/prefill",
                                {"prompt_tokens": prompt, "decode_url": dec_url})
            assert st == 200 and resp["pages"] == 3
            assert resp["adopted"] == 3 and resp["already_resident"] == 0
            assert resp["handoff_ms"] > 0
            # the decode replica now serves the prompt WITHOUT recomputing:
            # adopted pages satisfy the admission-time prefix match
            st2, out = post_raw(dec_url + "/v1/completions",
                                {"prompt_tokens": prompt, "max_tokens": 4})
            assert st2 == 200
            stats = json.loads(
                urllib.request.urlopen(dec_url + "/stats").read())
            assert stats["kv_handoff_adopted"] == 3
            assert stats["prefix_hit_tokens"] > 0
            assert stats["role"] == "serve"
            # parity: adopted KV must not change the sampled tokens
            ref = EngineServer(tiny_engine(
                kv="paged", page_len=8, num_slots=2, max_len=64)).start()
            try:
                r = ref.submit(prompt, 4, {})
                while True:
                    kind, payload = r.get()
                    if kind == "done":
                        break
                assert out["tokens"] == list(payload)
            finally:
                ref.stop()
            # re-ship is idempotent: everything already resident, nothing
            # double-registered
            st3, again = post_raw(pre_url + "/v1/prefill",
                                  {"prompt_tokens": prompt, "decode_url": dec_url})
            assert st3 == 200
            assert again["adopted"] == 0 and again["already_resident"] == 3
        finally:
            for httpd in (ph, dh):
                httpd.shutdown()
                httpd.server_close()
            pre.stop()
            dec.stop()

    def test_prefill_needs_a_paged_engine(self):
        from test_serve import http_server, post_raw, tiny_engine

        from tony_tpu.models.serving_http import EngineServer

        srv = EngineServer(tiny_engine(kv="dense")).start()
        httpd, url = http_server(srv)
        try:
            st, resp = post_raw(url + "/v1/prefill",
                                {"prompt_tokens": [1, 2, 3]})
            assert st == 409 and "paged" in resp["error"]
            st2, _ = post_raw(url + "/v1/kv/adopt", {"page_len": 8})
            assert st2 == 409
        finally:
            httpd.shutdown()
            httpd.server_close()
            srv.stop()

    def test_adopt_rejects_mismatched_geometry(self):
        from test_serve import post_raw

        pre, ph, pre_url = self._paged_server(role="prefill")
        dec, dh, dec_url = self._paged_server(page_len=16)
        try:
            prompt = list(range(1, 25))
            st, resp = post_raw(pre_url + "/v1/prefill",
                                {"prompt_tokens": prompt, "decode_url": dec_url})
            # the prefill leg still succeeds (degrade contract) but the ship
            # is refused by the decode side's 400 → surfaced as ship_error
            assert st == 200
            assert resp["adopted"] == 0 and "ship_error" in resp
            assert "page_len" in resp["ship_error"]
        finally:
            for httpd in (ph, dh):
                httpd.shutdown()
                httpd.server_close()
            pre.stop()
            dec.stop()

    def test_ship_failure_degrades_not_errors(self):
        from test_serve import post_raw

        pre, ph, pre_url = self._paged_server(role="prefill")
        try:
            st, resp = post_raw(pre_url + "/v1/prefill",
                                {"prompt_tokens": list(range(1, 25)),
                                 "decode_url": dead_url(), "timeout_s": 2.0})
            assert st == 200  # never client-visible
            assert resp["pages"] == 3 and resp["adopted"] == 0
            assert "ship_error" in resp
        finally:
            ph.shutdown()
            ph.server_close()
            pre.stop()


# ---------------------------------------------------------------------------
# coordinator: the prefill leg from the router's side
# ---------------------------------------------------------------------------
class TestDisaggCoordinator:
    def test_no_replica_returns_none(self):
        am = TieredAM()
        coord = DisaggCoordinator(make_health(am, job_name="prefill"))
        before = _counter("tony_router_prefill_legs_total", outcome="no_replica")
        assert coord.prefill([1, 2, 3], "http://x") is None
        assert _counter("tony_router_prefill_legs_total",
                        outcome="no_replica") == before + 1

    def test_leg_success_records_latency(self):
        rep, am = FakeReplica(), TieredAM()
        am.set_task("prefill", 0, rep.url)
        h = make_health(am, job_name="prefill")
        try:
            h.tick()
            coord = DisaggCoordinator(h, timeout_s=5.0)
            before = _counter("tony_router_prefill_legs_total", outcome="ok")
            got = coord.prefill([1, 2, 3], "http://decode")
            assert isinstance(got, dict)
            assert _counter("tony_router_prefill_legs_total",
                            outcome="ok") == before + 1
            s = coord.stats()
            assert s["handoff_p50_ms"] is not None and s["handoff_p50_ms"] > 0
            # balanced accounting: outstanding returned to zero
            assert all(r.outstanding == 0 for r in h.snapshot())
        finally:
            rep.close()

    def test_dead_prefill_replica_degrades(self):
        am = TieredAM()
        am.set_task("prefill", 0, dead_url())
        h = make_health(am, job_name="prefill")
        h._resolve()  # UNKNOWN is still eligible (optimistic first touch)
        coord = DisaggCoordinator(h, timeout_s=2.0)
        before = _counter("tony_router_prefill_legs_total", outcome="error")
        assert coord.prefill([1, 2, 3], "http://decode") is None
        assert _counter("tony_router_prefill_legs_total",
                        outcome="error") == before + 1

    def test_router_fires_one_leg_per_request(self):
        prefill, decode, am = FakeReplica(), FakeReplica(), TieredAM()
        am.set_task("prefill", 0, prefill.url)
        am.set_task("serve", 0, decode.url)
        ph = make_health(am, job_name="prefill")
        dh = make_health(am, job_name="serve")
        router = None
        try:
            ph.tick()
            dh.tick()
            coord = DisaggCoordinator(ph, timeout_s=5.0)
            router = make_router(dh, disagg=coord)
            st, headers, body = post_router(
                router.url, {"prompt_tokens": [1, 2, 3], "max_tokens": 2})
            assert st == 200 and body["tokens"]
            assert prefill.cfg["hits"] == 1  # exactly one leg
            assert decode.cfg["hits"] == 1
            assert "disagg" in router.stats()
        finally:
            if router is not None:
                router.stop()
            prefill.close()
            decode.close()


# ---------------------------------------------------------------------------
# sharded router tier: front, failover re-pin, gossip
# ---------------------------------------------------------------------------
class TestRouterShardFront:
    def _fleet(self, n_routers=2, coord=None):
        a, b, am = FakeReplica(), FakeReplica(), FakeAM()
        am.set_replica(0, a.url)
        am.set_replica(1, b.url)
        h = make_health(am)
        h.tick()
        routers = [make_router(h, disagg=coord) for _ in range(n_routers)]
        front = RouterShardFront(routers, gossip_interval_s=0).start()
        return a, b, h, routers, front

    def test_relay_and_shard_stamp(self):
        a, b, h, routers, front = self._fleet()
        try:
            st, headers, body = post_router(
                front.url, {"prompt_tokens": [1], "max_tokens": 2},
            )
            assert st == 200 and body["tokens"]
            assert headers.get("X-Tony-Shard") in ("0", "1")
            stats = front.stats()
            assert stats["front"]["shards"] == 2
            assert stats["front"]["shards_live"] == 2
            assert stats["fleet"]["slots_total"] == 16
        finally:
            front.stop()
            for r in routers:
                r.stop()
            a.close()
            b.close()

    def _post_session(self, url, sid, stream=False):
        req = urllib.request.Request(
            url + "/v1/completions",
            json.dumps({"prompt_tokens": [1, 2, 3], "max_tokens": 2,
                        "stream": stream}).encode(),
            {"Content-Type": "application/json", "X-Tony-Session": sid})
        resp = urllib.request.urlopen(req, timeout=30)
        shard = resp.headers.get("X-Tony-Shard")
        resp.read()
        return resp.status, shard

    def test_shard_failover_repins_exactly_once(self):
        """Satellite: a router worker dies; its sessions re-resolve to a
        surviving shard via the ring with EXACTLY ONE re-pin counted by
        tony_router_session_repins_total — and stay there."""
        a, b, h, routers, front = self._fleet()
        try:
            sid = "failover-session"
            st, shard = self._post_session(front.url, sid)
            assert st == 200 and shard is not None
            victim = int(shard)
            survivor = 1 - victim
            routers[victim].stop()
            before = sessions_mod.repins_total()
            st2, shard2 = self._post_session(front.url, sid)
            assert st2 == 200 and int(shard2) == survivor
            assert sessions_mod.repins_total() == before + 1
            # next turn: sticky on the survivor, NO further re-pin
            st3, shard3 = self._post_session(front.url, sid)
            assert st3 == 200 and int(shard3) == survivor
            assert sessions_mod.repins_total() == before + 1
            assert front.stats()["front"]["shards_live"] == 1
            routers[victim] = None  # already stopped
        finally:
            front.stop()
            for r in routers:
                if r is not None:
                    r.stop()
            a.close()
            b.close()

    def test_sessions_stick_to_their_shard(self):
        a, b, h, routers, front = self._fleet()
        try:
            for sid in ("s-one", "s-two", "s-three"):
                _, first = self._post_session(front.url, sid)
                _, second = self._post_session(front.url, sid)
                assert first == second
        finally:
            front.stop()
            for r in routers:
                r.stop()
            a.close()
            b.close()

    def test_gossip_replicates_prefix_hints(self):
        a, b, h, routers, front = self._fleet()
        try:
            prompt = list(range(1, 300))  # >= default prefix_span
            # pin a session with a fingerprinted prompt on shard 0's table
            routers[0].sessions.pin("gossip-s", 1, prompt)
            assert routers[1].sessions.hint(prompt) is None
            front.gossip_hints()
            assert routers[1].sessions.hint(prompt) == 1
            # local ownership survives future gossip; dropped replica purges
            routers[1].sessions.drop_replica(1)
            assert routers[1].sessions.hint(prompt) is None
        finally:
            front.stop()
            for r in routers:
                r.stop()
            a.close()
            b.close()

    def test_no_live_shard_is_503(self):
        a, b, h, routers, front = self._fleet()
        try:
            for r in routers:
                r.stop()
            st, _, body = post_router(front.url, {"prompt_tokens": [1]})
            assert st in (502, 503)
            assert "shard" in body["error"]
        finally:
            front.stop()
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# autoscaler: decode tier's KV-occupancy signal
# ---------------------------------------------------------------------------
class TestKvOccupancyScaling:
    def _scaler(self, **policy):
        p = AutoscalePolicy(min_replicas=1, max_replicas=4,
                            scale_up_ticks=2, scale_down_ticks=2, **policy)
        return Autoscaler(None, lambda j, n: None, p)

    def test_kv_occupancy_drives_scale_up(self):
        sc = self._scaler(scale_up_kv_occupancy=0.9)
        sig = FleetSignals(replicas_known=2, replicas_healthy=2,
                           slots_active=0, slots_total=16,
                           pages_live=95, pages_total=100)
        assert sig.kv_occupancy == 0.95
        assert sc.decide(2, sig) == 2      # tick 1: hysteresis holds
        assert sc.decide(2, sig) == 3      # tick 2: memory-bound scale-up

    def test_kv_occupancy_vetoes_scale_down(self):
        sc = self._scaler(scale_up_kv_occupancy=0.9)
        idle_but_full = FleetSignals(replicas_known=2, replicas_healthy=2,
                                     slots_active=0, slots_total=16,
                                     pages_live=95, pages_total=100)
        # idle slots + full pool: decode is memory-bound, not idle
        assert sc.decide(2, idle_but_full) in (2, 3)
        sc2 = self._scaler(scale_up_kv_occupancy=0.0)  # disabled
        idle = FleetSignals(replicas_known=2, replicas_healthy=2,
                            slots_active=0, slots_total=16)
        assert sc2.decide(2, idle) == 2
        assert sc2.decide(2, idle) == 1    # classic idle shrink still works

    def test_dense_fleet_reports_zero_occupancy(self):
        assert FleetSignals(replicas_healthy=1).kv_occupancy == 0.0


# ---------------------------------------------------------------------------
# loadgen: multi-router drive
# ---------------------------------------------------------------------------
class TestLoadgenSharding:
    def test_session_url_spread_is_sticky(self):
        spec = LoadSpec(url="http://a", urls=("http://b", "http://a/"))
        assert spec.all_urls() == ("http://a", "http://b")
        got = {spec.session_url(i) for i in range(8)}
        assert got == {"http://a", "http://b"}
        assert spec.session_url(3) == spec.session_url(3)

    def test_run_across_two_routers_directly(self):
        a, am = FakeReplica(), FakeAM()
        am.set_replica(0, a.url)
        h = make_health(am)
        h.tick()
        r1, r2 = make_router(h), make_router(h)
        try:
            spec = LoadSpec(url=r1.url, urls=(r2.url,), rate=50.0,
                            sessions=4, turns=2, prompt_mix=[(8, 1.0)],
                            max_tokens=4, stream=True, timeout_s=30.0)
            d = LoadGenerator(spec).run().to_dict()
            assert d["requests_failed"] == 0 and d["requests_ok"] == 8
            # both shard tables carry pins: the spread actually happened
            assert len(r1.sessions) > 0 and len(r2.sessions) > 0
        finally:
            r1.stop()
            r2.stop()
            a.close()


# ---------------------------------------------------------------------------
# headline e2e: disaggregated fleet across 2 router shards under loadtest
# ---------------------------------------------------------------------------
class TestDisaggHeadline:
    def test_disagg_fleet_across_two_shards_zero_failures(self):
        """Prefill tier + decode tier (real paged engines) behind TWO router
        shards and one front, under the open-loop loadgen: a multi-turn
        session workload completes with zero client-visible failures, KV
        pages are ADOPTED on decode (tony_serve_kv_handoff_total / prefix
        hits — not recomputed), and the run emits a gate-valid SERVE_BENCH
        record carrying the handoff-latency field."""
        from test_serve import http_server, tiny_engine

        from tony_tpu.histserver import gate as bench_gate
        from tony_tpu.models.serving_http import EngineServer

        def paged(role):
            srv = EngineServer(
                tiny_engine(kv="paged", page_len=8, num_slots=4, max_len=128),
                role=role).start()
            httpd, url = http_server(srv)
            return srv, httpd, url

        pre, pre_httpd, pre_url = paged("prefill")
        dec0, dec0_httpd, dec0_url = paged("serve")
        dec1, dec1_httpd, dec1_url = paged("serve")
        am = TieredAM()
        am.set_task("prefill", 0, pre_url)
        am.set_task("serve", 0, dec0_url)
        am.set_task("serve", 1, dec1_url)
        prefill_health = make_health(am, job_name="prefill", interval_s=0.2)
        decode_health = make_health(am, job_name="serve", interval_s=0.2)
        routers, front = [], None
        try:
            prefill_health.tick()
            decode_health.tick()
            # live ticking: the fleet agg (prefix hits, handoff counters)
            # the loadgen deltas is refreshed by the probe loop
            prefill_health.start()
            decode_health.start()
            coord = DisaggCoordinator(prefill_health, timeout_s=60.0)
            routers = [
                make_router(decode_health, disagg=coord,
                            failover_deadline_s=60.0)
                for _ in range(2)
            ]
            front = RouterShardFront(routers, gossip_interval_s=0.5).start()
            adopted_before = sum(
                s.kv_handoff_adopted for s in (dec0, dec1))
            spec = LoadSpec(url=front.url, rate=8.0, sessions=6, turns=3,
                            prompt_mix=[(16, 1.0)], max_tokens=4,
                            shared_prefix=8, stream=True, timeout_s=120.0,
                            seed=7)
            report = LoadGenerator(spec).run()
            d = report.to_dict()
            assert d["requests_failed"] == 0, d.get("first_errors")
            assert d["requests_ok"] == 18
            # KV pages moved through the handoff and were adopted — the
            # decode tier did NOT recompute every prompt
            adopted_after = sum(s.kv_handoff_adopted for s in (dec0, dec1))
            assert adopted_after > adopted_before
            assert pre.kv_handoff_exported > 0
            assert d.get("prefix_hit_tokens", 0) > 0
            assert d.get("kv_handoff_pages", 0) > 0
            assert d.get("handoff_p50_ms", 0) > 0
            # sessions sharded across BOTH router tables
            assert sum(len(r.sessions) for r in routers) == 6
            assert front.stats()["front"]["shards_live"] == 2
            # and the round is gate-grade: schema-valid, handoff field in
            # the record, hardware provenance stamped
            rec = report.to_bench_record(2, baseline_tokens_per_sec=100.59)
            assert bench_gate.validate_record(rec, wrapper=True) == []
            assert rec["parsed"]["handoff_p50_ms"] > 0
            assert rec["parsed"]["machine"]["cpus"] > 0
        finally:
            prefill_health.stop()
            decode_health.stop()
            if front is not None:
                front.stop()
            for r in routers:
                r.stop()
            for httpd in (pre_httpd, dec0_httpd, dec1_httpd):
                httpd.shutdown()
                httpd.server_close()
            for srv in (pre, dec0, dec1):
                srv.stop()
