"""HF-checkpoint import: logit-level parity against transformers' Llama.

The strongest interop proof for users arriving from the reference's
ecosystem with PyTorch checkpoints: a randomly-initialized HF
LlamaForCausalLM converted through models/convert.py must reproduce HF's
own forward logits (rope convention, GQA layout, norms, un-tied head).
"""

import jax
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_hf_model(tie=False):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10_000.0,
        tie_word_embeddings=tie,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    return transformers.LlamaForCausalLM(hf_cfg).eval()


class TestHfImport:
    def test_logit_parity_with_transformers(self):
        from tony_tpu.models import convert, llama

        model = _tiny_hf_model()
        params, cfg = convert.from_hf(model, dtype="float32")
        assert cfg.n_kv_heads == 2 and cfg.d_model == 64

        tokens = np.random.default_rng(1).integers(0, 256, (2, 16))
        with torch.no_grad():
            want = model(torch.tensor(tokens)).logits.numpy()
        got = np.asarray(
            llama.forward(params, jax.numpy.asarray(tokens, jax.numpy.int32), cfg),
            np.float32,
        )
        scale = np.abs(want).max() + 1e-6
        assert np.abs(got - want).max() / scale < 2e-3, (
            f"max logit divergence {np.abs(got - want).max() / scale:.2e}"
        )

    def test_tied_embeddings_fall_back_to_embed(self):
        from tony_tpu.models import convert, llama

        model = _tiny_hf_model(tie=True)
        sd = {k: v for k, v in model.state_dict().items() if k != "lm_head.weight"}
        cfg = convert.config_from_hf(model.config, dtype="float32")
        params = convert.params_from_hf_state_dict(sd, cfg)
        np.testing.assert_array_equal(
            np.asarray(params["lm_head"]), np.asarray(params["embed"]).T
        )

    def test_param_count_matches_config(self):
        from tony_tpu.models import convert

        model = _tiny_hf_model()
        params, cfg = convert.from_hf(model, dtype="float32")
        total = sum(p.size for p in jax.tree.leaves(params))
        assert total == cfg.num_params()

    def test_unconsumed_weights_rejected(self):
        from tony_tpu.models import convert

        model = _tiny_hf_model()
        cfg = convert.config_from_hf(model.config, dtype="float32")
        sd = dict(model.state_dict())
        sd["model.layers.0.self_attn.q_proj.bias"] = torch.zeros(64)
        with pytest.raises(ValueError, match="unconsumed"):
            convert.params_from_hf_state_dict(sd, cfg)

    def test_rope_scaling_llama3_imported(self):
        from tony_tpu.models import convert

        hf_cfg = _tiny_hf_model().config
        hf_cfg.rope_scaling = {
            "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0, "original_max_position_embeddings": 8192,
        }
        cfg = convert.config_from_hf(hf_cfg)
        assert cfg.rope_scaling == ("llama3", 8.0, 1.0, 4.0, 8192.0)

    def test_rope_scaling_unknown_type_rejected(self):
        from tony_tpu.models import convert

        hf_cfg = _tiny_hf_model().config
        hf_cfg.rope_scaling = {"rope_type": "yarn", "factor": 8.0}
        with pytest.raises(NotImplementedError, match="rope_scaling"):
            convert.config_from_hf(hf_cfg)

    def test_llama3_rope_matches_hf(self):
        # frequency-band scaling must reproduce transformers' llama3 rope
        import numpy as np

        from tony_tpu.ops import layers as L

        dim, theta = 64, 500_000.0
        factor, lo, hi, orig = 8.0, 1.0, 4.0, 8192
        cos, sin = L.rope_frequencies(
            dim, 64, theta, ("llama3", factor, lo, hi, orig)
        )
        # reference computation (transformers _compute_llama3_parameters),
        # in f64 so the band-boundary comparisons don't flip vs the jnp f32
        inv = 1.0 / (theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
        wavelen = 2 * np.pi / inv
        inv_llama = np.where(wavelen > orig / lo, inv / factor, inv)
        smooth = (orig / wavelen - lo) / (hi - lo)
        is_mid = (wavelen >= orig / hi) & (wavelen <= orig / lo)
        want_inv = np.where(is_mid, (1 - smooth) * inv / factor + smooth * inv, inv_llama)
        t = np.arange(64, dtype=np.float64)
        np.testing.assert_allclose(
            np.asarray(cos), np.cos(np.outer(t, want_inv)), rtol=1e-4, atol=1e-5
        )

    def test_generation_runs_on_imported_weights(self):
        from tony_tpu.models import convert, generate

        model = _tiny_hf_model()
        params, cfg = convert.from_hf(model, dtype="float32")
        prompt = jax.numpy.zeros((1, 4), jax.numpy.int32)
        out = generate.generate(params, prompt, cfg, max_new_tokens=4)
        assert out.shape == (1, 4)


class TestMixtralHfImport:
    def _tiny_hf_mixtral(self):
        hf_cfg = transformers.MixtralConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            num_local_experts=4, num_experts_per_tok=2,
            max_position_embeddings=32, rms_norm_eps=1e-5,
            attn_implementation="eager",
        )
        torch.manual_seed(1)
        return transformers.MixtralForCausalLM(hf_cfg).eval()

    def test_logit_parity_with_transformers(self):
        from tony_tpu.models import convert, mixtral

        model = self._tiny_hf_mixtral()
        params, cfg = convert.from_hf(model, dtype="float32")
        # lossless capacity: HF routing never drops tokens
        assert cfg.capacity_factor == pytest.approx(2.0)

        tokens = np.random.default_rng(3).integers(0, 128, (2, 16))
        with torch.no_grad():
            want = model(torch.tensor(tokens)).logits.numpy()
        got, aux = mixtral.forward(
            params, jax.numpy.asarray(tokens, jax.numpy.int32), cfg
        )
        got = np.asarray(got, np.float32)
        scale = np.abs(want).max() + 1e-6
        assert np.abs(got - want).max() / scale < 2e-3, (
            f"max logit divergence {np.abs(got - want).max() / scale:.2e}"
        )
        assert float(aux["moe_dropped_frac"]) == pytest.approx(0.0, abs=1e-6)
