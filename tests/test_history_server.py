"""Persistent history tier suite (docs/history.md).

Covers the three layers of the history subsystem: the shared artifact index
(obs/artifacts.py) and the discovery-parity contracts of the consumers it
replaced (portal scrape, ``tony trace``, ``tony logs``); ``.jhist``
torn-file tolerance (a byte-chopped history ingests its intact prefix as
``incomplete``); the SQLite store (idempotent re-ingest, compaction,
retention); the ingestion sweep and staging-dir GC; the ``tony history``
CLI; the ``tony history-server`` daemon; and the headline e2e — two real
fixture jobs ingested by a live daemon, compared, trend-rendered by the
portal, with ``tony bench --gate`` enforcing the perf trajectory.
"""

import json
import os
import threading
import time
import urllib.request

import pytest

from tony_tpu import constants
from tony_tpu.cluster.events import EventHandler, EventType
from tony_tpu.cluster.history import finalize_history
from tony_tpu.config import TonyConfig, keys
from tony_tpu.histserver import ingest as hist_ingest
from tony_tpu.histserver.gate import evaluate, parsed_of, validate_record
from tony_tpu.histserver.server import HistoryServer
from tony_tpu.histserver.store import HistoryStore, compact_series
from tony_tpu.obs import artifacts as obs_artifacts
from tony_tpu.obs import logging as obs_logging

pytestmark = [pytest.mark.history]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# fixture tree builders
# ---------------------------------------------------------------------------
def make_staging(root, app_id, conf=None, final=True):
    """A staging dir with the client/AM artifacts the index resolves."""
    d = os.path.join(str(root), app_id)
    os.makedirs(d, exist_ok=True)
    TonyConfig(dict(conf or {})).write_final(d)
    if final:
        with open(os.path.join(d, "am_status.json"), "w") as f:
            json.dump({"app_id": app_id, "status": "SUCCEEDED"}, f)
    return d


def emit_history(root, app_id, *, snapshots=3, finish="SUCCEEDED",
                 extra=(), finalize=True, started_ms=1_000, completed_ms=9_000,
                 user="tester"):
    """One job's .jhist (intermediate, optionally finalized) with a small
    metrics series and the counters the distiller reads."""
    hist = os.path.join(str(root), "history")
    eh = EventHandler(hist, app_id)
    eh.start()
    eh.emit(EventType.APPLICATION_INITED, app_id=app_id)
    eh.emit(EventType.QUEUE_WAIT, state="waiting", reason="test")
    eh.emit(EventType.QUEUE_WAIT, state="admitted")
    eh.emit(EventType.GANG_COMPLETE, tasks=1)
    for ev_type, payload in extra:
        eh.emit(ev_type, **payload)
    for s in range(1, snapshots + 1):
        eh.emit(EventType.METRICS_SNAPSHOT, tasks=[{
            "task": "worker:0",
            "metrics": {"train": {
                "step": s, "loss": 2.0 / s, "mfu": 0.4 + 0.01 * s,
                "tokens_per_sec": 1000.0 + s,
            }},
        }])
        time.sleep(0.012)  # distinct timestamps → derived step_time_ms
    if finish:
        eh.emit(EventType.APPLICATION_FINISHED, status=finish,
                tasks=[{"name": "worker", "index": 0, "status": finish}])
    eh.stop()
    if finalize:
        return finalize_history(
            hist, app_id, eh.intermediate_path, started_ms, completed_ms,
            finish or "FAILED", config_snapshot={"tony.worker.instances": "1"},
            user=user)
    return eh.intermediate_path


def make_job(root, app_id, **kw):
    make_staging(root, app_id)
    return emit_history(root, app_id, **kw)


# ---------------------------------------------------------------------------
# artifact index
# ---------------------------------------------------------------------------
class TestArtifactIndex:
    def test_default_layout(self, tmp_path):
        make_staging(tmp_path, "app1", final=False)
        art = obs_artifacts.index(str(tmp_path), "app1")
        assert art.staging_dir == os.path.join(str(tmp_path), "app1")
        assert art.history_root == os.path.join(str(tmp_path), "history")
        assert art.log_dir == os.path.join(art.staging_dir, "logs")
        assert art.trace_dir == os.path.join(art.staging_dir, "trace")
        assert art.profile_dir == os.path.join(art.staging_dir, "profile")
        assert not art.finalized and art.jhist_path is None
        assert art.am_status() is None

    def test_frozen_config_overrides(self, tmp_path):
        conf = {
            keys.LOG_DIR: str(tmp_path / "elsewhere-logs"),
            keys.TRACE_DIR: str(tmp_path / "elsewhere-trace"),
            keys.HISTORY_LOCATION: str(tmp_path / "elsewhere-history"),
        }
        make_staging(tmp_path, "app2", conf=conf)
        art = obs_artifacts.index(str(tmp_path), "app2")
        assert art.log_dir == conf[keys.LOG_DIR]
        assert art.trace_dir == conf[keys.TRACE_DIR]
        assert art.history_root == conf[keys.HISTORY_LOCATION]

    def test_running_then_finalized(self, tmp_path):
        make_staging(tmp_path, "app3", final=False)
        inter = emit_history(tmp_path, "app3", finalize=False)
        art = obs_artifacts.index(str(tmp_path), "app3")
        assert not art.finalized and art.jhist_path == inter
        assert obs_artifacts.running_ids(art.history_root) == ["app3"]
        dest = finalize_history(
            art.history_root, "app3", inter, 100, 200, "SUCCEEDED", user="u")
        art = obs_artifacts.index(str(tmp_path), "app3")
        assert art.finalized and art.jhist_path == dest
        assert art.history_file.status == "SUCCEEDED"
        assert art.history_file.user == "u"
        assert os.path.dirname(art.config_snapshot_path) == os.path.dirname(dest)
        assert obs_artifacts.running_ids(art.history_root) == []

    def test_staged_ids_recognizes_job_dirs(self, tmp_path):
        make_staging(tmp_path, "appA")
        make_staging(tmp_path, "appB", final=False)
        (tmp_path / "history").mkdir(exist_ok=True)
        (tmp_path / "random-dir").mkdir()
        assert obs_artifacts.staged_ids(str(tmp_path)) == ["appA", "appB"]

    # -- discovery parity: every producer contract the index replaced -------
    def test_logs_discovery_parity(self, tmp_path):
        """`tony logs` resolution == the writer-side resolve_log_dir, with
        and without the tony.log.dir override."""
        make_staging(tmp_path, "appL")
        assert (obs_artifacts.index(str(tmp_path), "appL").log_dir
                == obs_logging.resolve_log_dir(str(tmp_path), "appL"))
        make_staging(tmp_path, "appM", conf={keys.LOG_DIR: str(tmp_path / "ov")})
        assert (obs_artifacts.index(str(tmp_path), "appM").log_dir
                == obs_logging.resolve_log_dir(str(tmp_path), "appM")
                == str(tmp_path / "ov"))

    def test_trace_discovery_parity(self, tmp_path):
        """`tony trace` resolves the span dir (incl. tony.trace.dir) through
        the index, and the shared span reader tolerates torn files."""
        from tony_tpu.cli import trace as trace_cli

        assert trace_cli.load_spans is obs_artifacts.load_spans
        override = tmp_path / "spans-here"
        override.mkdir()
        (override / "am.spans.jsonl").write_text(
            json.dumps({"span_id": "s1", "start_ms": 1.0, "identity": "am"})
            + "\n{torn")
        make_staging(tmp_path, "appT", conf={keys.TRACE_DIR: str(override)})
        art = obs_artifacts.index(str(tmp_path), "appT")
        assert art.trace_dir == str(override)
        assert [s["span_id"] for s in obs_artifacts.load_spans(art.trace_dir)] == ["s1"]

    def test_portal_scrape_parity(self, tmp_path):
        """The portal's running/finished listing and per-job lookups all come
        from the index (same fixture tree, same answers)."""
        from tony_tpu.portal.server import PortalHandler

        make_staging(tmp_path, "appP", final=False)
        emit_history(tmp_path, "appP", finalize=False)
        make_job(tmp_path, "appQ")
        hist_root = os.path.join(str(tmp_path), "history")
        handler = type("H", (PortalHandler,), {
            "history_root": hist_root, "staging_root": str(tmp_path)})
        # class-level helpers only — no HTTP socket needed
        assert handler._running_ids(handler) == ["appP"]
        assert [j.app_id for j in obs_artifacts.finished_jobs(hist_root)] == ["appQ"]
        art = handler._art(handler, "appQ")
        assert art.finalized and art.history_root == hist_root

    def test_no_private_discovery_walks(self):
        """Grep-style contract: the three refactored consumers resolve every
        artifact through obs/artifacts.py — no direct path construction for
        AM advertisements, final status, intermediate history, frozen
        config, or directory walks."""
        forbidden = ("AM_INFO_FILE", "HISTORY_INTERMEDIATE_DIR",
                     "am_status" + ".json", "TONY_FINAL_CONF",
                     "resolve_log_dir", "os.walk(")
        for rel in ("tony_tpu/portal/server.py", "tony_tpu/cli/trace.py",
                    "tony_tpu/cli/introspect.py"):
            src = open(os.path.join(REPO_ROOT, rel)).read()
            assert "artifacts" in src, f"{rel} does not use the artifact index"
            for pat in forbidden:
                assert pat not in src, f"{rel} re-implements discovery: {pat}"


# ---------------------------------------------------------------------------
# torn/truncated .jhist hardening
# ---------------------------------------------------------------------------
class TestTornJhist:
    def test_byte_chopped_tail_keeps_prefix(self, tmp_path):
        path = make_job(tmp_path, "appX", snapshots=4)
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[:-17])  # SIGKILL mid final line
        events, complete = obs_artifacts.read_history_events(path)
        assert not complete
        assert events, "intact prefix must survive"
        assert events[0].type == EventType.APPLICATION_INITED
        # the torn final line is dropped, everything before it is kept
        assert len(events) == data.decode().strip().count("\n")

    def test_mid_file_garbage_keeps_intact_prefix(self, tmp_path):
        path = make_job(tmp_path, "appY")
        lines = open(path).read().splitlines()
        with open(path, "w") as f:
            f.write("\n".join(lines[:3]) + "\n}{garbage\n" + "\n".join(lines[3:]) + "\n")
        events, complete = obs_artifacts.read_history_events(path)
        assert not complete and len(events) == 3

    def test_missing_finish_event_is_incomplete(self, tmp_path):
        path = make_job(tmp_path, "appZ", finish=None, finalize=False)
        events, complete = obs_artifacts.read_history_events(path)
        assert events and not complete

    def test_chopped_job_ingests_as_incomplete(self, tmp_path):
        """The satellite contract: a job killed mid-write must ingest its
        intact prefix and be marked incomplete, never raise."""
        path = make_job(tmp_path, "appW", snapshots=5)
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[: int(len(data) * 0.7)])
        store = HistoryStore(":memory:")
        art = obs_artifacts.index(str(tmp_path), "appW")
        assert hist_ingest.ingest_job(store, art) == "ingested"
        row = store.get_job("appW")
        assert row["incomplete"] is True
        assert row["status"] == "SUCCEEDED"  # the filename encoding survives
        assert store.series("appW", "mfu")   # prefix series distilled
        store.close()


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------
class TestStore:
    def test_put_job_is_idempotent(self, tmp_path):
        store = HistoryStore(str(tmp_path / "h.sqlite"))
        job = {"app_id": "a1", "status": "SUCCEEDED", "completed_ms": 10}
        series = {"mfu": [(1, 0.4), (2, 0.5)]}
        store.put_job(job, series=series, summary={"mfu": {"p50": 0.4}})
        store.put_job(job, series=series, summary={"mfu": {"p50": 0.4}})
        assert store.count() == 1
        assert store.series("a1", "mfu") == [(1, 0.4), (2, 0.5)]
        store.close()

    def test_reingest_replaces_series(self, tmp_path):
        store = HistoryStore(str(tmp_path / "h.sqlite"))
        store.put_job({"app_id": "a1", "status": "FAILED"},
                      series={"mfu": [(1, 0.1)], "loss": [(1, 3.0)]})
        store.put_job({"app_id": "a1", "status": "SUCCEEDED"},
                      series={"mfu": [(1, 0.2)]})
        assert store.get_job("a1")["status"] == "SUCCEEDED"
        assert store.series("a1", "mfu") == [(1, 0.2)]
        assert store.series("a1", "loss") == []  # stale series dropped
        store.close()

    def test_compaction_bounds_series(self):
        points = [(i, float(i)) for i in range(1000)]
        out = compact_series(points, 50)
        assert len(out) <= 50
        assert out[0] == (0, 0.0) and out[-1] == (999, 999.0)
        assert out == sorted(out)
        store = HistoryStore(":memory:", max_series_points=50)
        store.put_job({"app_id": "a", "status": "SUCCEEDED"}, series={"mfu": points})
        assert len(store.series("a", "mfu")) <= 50
        store.close()

    def test_retention_purges_old_jobs(self):
        store = HistoryStore(":memory:")
        store.put_job({"app_id": "old", "status": "SUCCEEDED", "completed_ms": 100},
                      series={"mfu": [(1, 0.4)]})
        store.put_job({"app_id": "new", "status": "SUCCEEDED", "completed_ms": 10_000})
        assert store.purge_older_than(5_000) == ["old"]
        assert [j["app_id"] for j in store.list_jobs()] == ["new"]
        assert store.series("old", "mfu") == []
        store.close()

    def test_trend_orders_by_completion(self):
        store = HistoryStore(":memory:")
        for app, t, mfu in (("b", 200, 0.5), ("a", 100, 0.4), ("c", 300, 0.6)):
            store.put_job({"app_id": app, "status": "SUCCEEDED", "completed_ms": t},
                          summary={"mfu": {"p50": mfu}})
        assert [p["app_id"] for p in store.trend("mfu")] == ["a", "b", "c"]
        assert [p["value"] for p in store.trend("mfu")] == [0.4, 0.5, 0.6]
        # row-level counters trend straight off the jobs table
        assert len(store.trend("gang_epochs")) == 3
        store.close()


# ---------------------------------------------------------------------------
# ingestion
# ---------------------------------------------------------------------------
class TestIngest:
    def test_distill_counters_and_series(self, tmp_path):
        make_job(tmp_path, "appD", snapshots=4, extra=(
            (EventType.GANG_RESIZED, {"job_name": "worker", "to": 2}),
            (EventType.AM_TAKEOVER, {"attempt": 1}),
        ))
        art = obs_artifacts.index(str(tmp_path), "appD")
        job, series, summary = hist_ingest.distill(art)
        assert job["status"] == "SUCCEEDED" and not job["incomplete"]
        assert job["gang_epochs"] == 1 and job["resizes"] == 1 and job["takeovers"] == 1
        assert job["duration_ms"] == 8_000
        assert len(series["mfu"]) == 4 and len(series["loss"]) == 4
        assert "step_time_ms" in series  # derived from step/timestamp deltas
        assert summary["mfu"]["last"] == pytest.approx(0.44)
        assert summary["mfu"]["p50"] <= summary["mfu"]["max"]

    def test_sweep_is_idempotent_until_source_changes(self, tmp_path):
        path = make_job(tmp_path, "appS")
        store = HistoryStore(":memory:")
        assert hist_ingest.sweep(store, [str(tmp_path)])["ingested"] == 1
        counts = hist_ingest.sweep(store, [str(tmp_path)])
        assert counts["ingested"] == 0 and counts["unchanged"] >= 1
        os.utime(path, ns=(1, 1))  # source changed → re-ingest
        assert hist_ingest.sweep(store, [str(tmp_path)])["ingested"] == 1
        store.close()

    def test_sweep_skips_live_jobs_and_survives_garbage(self, tmp_path):
        make_staging(tmp_path, "appLive", final=False)
        emit_history(tmp_path, "appLive", finalize=False)
        make_job(tmp_path, "appDone")
        (tmp_path / "appGarbage").mkdir()
        (tmp_path / "appGarbage" / constants.TONY_FINAL_CONF).write_text("{not json")
        store = HistoryStore(":memory:")
        counts = hist_ingest.sweep(store, [str(tmp_path)])
        assert counts["ingested"] == 1
        assert store.get_job("appLive") is None
        store.close()

    def test_sweep_applies_retention(self, tmp_path):
        """Jobs past retention are never ingested in the first place (an
        ingest→purge cycle would otherwise repeat every sweep forever, since
        the finished .jhist deliberately outlives the store row), and rows
        that age past the cutoff in place get purged."""
        make_job(tmp_path, "appOld", completed_ms=1_000)
        make_job(tmp_path, "appFresh", completed_ms=9 * 86_400_000)
        store = HistoryStore(":memory:")
        now = 10 * 86_400_000
        counts = hist_ingest.sweep(store, [str(tmp_path)],
                                   retention_days=5, now_ms=now)
        assert counts["expired"] == 1 and counts["ingested"] == 1
        assert [j["app_id"] for j in store.list_jobs()] == ["appFresh"]
        # ...and the expired job stays out on the NEXT sweep too (no cycle)
        counts = hist_ingest.sweep(store, [str(tmp_path)],
                                   retention_days=5, now_ms=now)
        assert counts["expired"] == 1 and counts["ingested"] == 0
        # a row that ages past the cutoff in place is purged
        counts = hist_ingest.sweep(store, [str(tmp_path)], retention_days=5,
                                   now_ms=now + 10 * 86_400_000)
        assert counts["purged"] == 1 and store.count() == 0
        store.close()

    def test_reingests_after_staging_gc(self, tmp_path):
        """A job whose staging dir was GC'd is still discoverable through the
        finished history tree (fresh store rebuild)."""
        make_job(tmp_path, "appG")
        import shutil

        shutil.rmtree(tmp_path / "appG")
        store = HistoryStore(":memory:")
        assert hist_ingest.sweep(store, [str(tmp_path)])["ingested"] == 1
        assert store.get_job("appG")["status"] == "SUCCEEDED"
        store.close()


# ---------------------------------------------------------------------------
# staging-dir GC
# ---------------------------------------------------------------------------
class TestGC:
    def _prepare(self, tmp_path):
        store = HistoryStore(":memory:")
        make_job(tmp_path, "appOld", completed_ms=1_000)
        make_job(tmp_path, "appFresh", completed_ms=90 * 86_400_000)
        make_staging(tmp_path, "appLive", final=False)
        emit_history(tmp_path, "appLive", finalize=False)
        make_job(tmp_path, "appUningested", completed_ms=1_000)
        hist_ingest.ingest_job(store, obs_artifacts.index(str(tmp_path), "appOld"))
        hist_ingest.ingest_job(store, obs_artifacts.index(str(tmp_path), "appFresh"))
        return store, 100 * 86_400_000  # "now"

    def test_dry_run_lists_but_keeps(self, tmp_path):
        store, now = self._prepare(tmp_path)
        removed = hist_ingest.gc_staging(store, str(tmp_path), retention_days=30,
                                         dry_run=True, now_ms=now)
        assert [a for a, _ in removed] == ["appOld"]
        assert (tmp_path / "appOld").exists()
        store.close()

    def test_gc_removes_only_ingested_old_finalized(self, tmp_path):
        store, now = self._prepare(tmp_path)
        removed = hist_ingest.gc_staging(store, str(tmp_path), retention_days=30,
                                         now_ms=now)
        assert [a for a, _ in removed] == ["appOld"]
        assert not (tmp_path / "appOld").exists()
        # fresh, live, and un-ingested jobs are untouchable
        assert (tmp_path / "appFresh").exists()
        assert (tmp_path / "appLive").exists()
        assert (tmp_path / "appUningested").exists()
        # the finished .jhist (the forensic record) survives its staging dir
        assert obs_artifacts.index(str(tmp_path), "appOld").finalized
        store.close()

    def test_gc_requires_positive_retention(self, tmp_path):
        store, now = self._prepare(tmp_path)
        assert hist_ingest.gc_staging(store, str(tmp_path), retention_days=0,
                                      now_ms=now) == []
        store.close()


# ---------------------------------------------------------------------------
# tony history CLI
# ---------------------------------------------------------------------------
class TestHistoryCLI:
    def test_ingest_list_show_compare(self, tmp_path, capsys):
        from tony_tpu.cli.history import main as history_main

        make_job(tmp_path, "app_one")
        make_job(tmp_path, "app_two", snapshots=5)
        staging = ["--staging", str(tmp_path)]
        assert history_main(["ingest", *staging]) == 0
        capsys.readouterr()
        assert history_main(["list", *staging]) == 0
        out = capsys.readouterr().out
        assert "app_one" in out and "app_two" in out and "epochs=1" in out
        assert history_main(["show", "app_one", *staging]) == 0
        out = capsys.readouterr().out
        assert "mfu_p50" in out and "SUCCEEDED" in out
        assert history_main(["compare", "app_one", "app_two", *staging]) == 0
        out = capsys.readouterr().out
        assert "app_one" in out and "app_two" in out and "tokens_per_sec_p50" in out

    def test_show_falls_back_to_inline_distill(self, tmp_path, capsys):
        from tony_tpu.cli.history import main as history_main

        make_job(tmp_path, "app_ni")
        assert history_main(["show", "app_ni", "--staging", str(tmp_path)]) == 0
        assert "not ingested" in capsys.readouterr().out

    def test_legacy_spelling_dumps_events(self, tmp_path, capsys):
        from tony_tpu.cli.history import main as history_main

        make_job(tmp_path, "app_legacy")
        assert history_main(["app_legacy", "--staging", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "APPLICATION_INITED" in out and "APPLICATION_FINISHED" in out

    def test_legacy_flag_first_spelling(self, tmp_path, capsys):
        """Pre-store muscle memory: `tony history --root <history_dir>
        [app_id]` keeps listing/dumping."""
        from tony_tpu.cli.history import main as history_main

        make_job(tmp_path, "app_flags")
        hist_root = os.path.join(str(tmp_path), "history")
        assert history_main(["--root", hist_root]) == 0
        assert "app_flags" in capsys.readouterr().out
        assert history_main(["--root", hist_root, "app_flags"]) == 0
        assert "APPLICATION_FINISHED" in capsys.readouterr().out

    def test_gc_cli_dry_run(self, tmp_path, capsys):
        from tony_tpu.cli.history import main as history_main

        make_job(tmp_path, "app_gc", completed_ms=1_000)
        staging = ["--staging", str(tmp_path)]
        assert history_main(["ingest", *staging]) == 0
        assert history_main(["gc", "--retention-days", "30", "--dry-run",
                             *staging]) == 0
        out = capsys.readouterr().out
        assert "would remove" in out and "app_gc" in out
        assert (tmp_path / "app_gc").exists()

    def test_unknown_job_errors(self, tmp_path, capsys):
        from tony_tpu.cli.history import main as history_main

        assert history_main(["show", "ghost", "--staging", str(tmp_path)]) == 1


# ---------------------------------------------------------------------------
# daemon
# ---------------------------------------------------------------------------
class TestHistoryServerDaemon:
    def test_serves_health_metrics_and_queries(self, tmp_path):
        make_job(tmp_path, "app_d1")
        srv = HistoryServer([str(tmp_path)], store_path=str(tmp_path / "h.sqlite"),
                            port=0, scan_interval_s=0.2)
        srv.start()
        base = f"http://127.0.0.1:{srv.address[1]}"
        try:
            health = json.loads(urllib.request.urlopen(base + "/healthz").read())
            assert health["ok"] and health["jobs"] == 1
            jobs = json.loads(urllib.request.urlopen(base + "/api/jobs").read())
            assert [j["app_id"] for j in jobs] == ["app_d1"]
            one = json.loads(urllib.request.urlopen(base + "/api/job/app_d1").read())
            assert "mfu" in one["series"]
            series = json.loads(
                urllib.request.urlopen(base + "/api/series/app_d1/mfu").read())
            assert len(series) >= 2
            metrics = urllib.request.urlopen(base + "/metrics").read().decode()
            assert "tony_history_ingests_total" in metrics
            assert "tony_history_jobs 1" in metrics
            # a job finalized while the daemon runs is picked up by the sweep
            make_job(tmp_path, "app_d2")
            deadline = time.time() + 10
            while time.time() < deadline:
                jobs = json.loads(urllib.request.urlopen(base + "/api/jobs").read())
                if len(jobs) == 2:
                    break
                time.sleep(0.1)
            assert len(jobs) == 2
            trend = json.loads(
                urllib.request.urlopen(base + "/api/trend/mfu").read())
            assert len(trend) == 2
        finally:
            srv.stop()

    def test_404_and_root_page(self, tmp_path):
        srv = HistoryServer([str(tmp_path)], store_path=":memory:", port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.address[1]}"
        try:
            import urllib.error

            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(base + "/api/job/nope")
            body = urllib.request.urlopen(base + "/").read().decode()
            assert "tony history server" in body
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# portal /history pages
# ---------------------------------------------------------------------------
class TestPortalHistoryPages:
    def test_trend_dashboard_and_job_page(self, tmp_path):
        from tony_tpu.portal.server import serve

        for app, base_mfu in (("app_p1", 2), ("app_p2", 4)):
            make_job(tmp_path, app, snapshots=base_mfu)
        store_path = os.path.join(str(tmp_path), "history", "history.sqlite")
        store = HistoryStore(store_path)
        hist_ingest.sweep(store, [str(tmp_path)])
        store.close()
        server = serve(os.path.join(str(tmp_path), "history"), 0, str(tmp_path))
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            body = urllib.request.urlopen(base + "/history").read().decode()
            assert "app_p1" in body and "app_p2" in body
            assert "<svg" in body  # cross-job trend sparklines
            detail = urllib.request.urlopen(base + "/history/app_p1").read().decode()
            assert "summary" in detail and "mfu" in detail
            # finished job page links its history entry
            job = urllib.request.urlopen(base + "/job/app_p1").read().decode()
            assert "/history/app_p1" in job
            api = json.loads(
                urllib.request.urlopen(base + "/api/history/trend/mfu").read())
            assert len(api) == 2
        finally:
            server.shutdown()

    def test_history_page_without_store(self, tmp_path):
        from tony_tpu.portal.server import serve

        server = serve(str(tmp_path), 0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            body = urllib.request.urlopen(base + "/history").read().decode()
            assert "no history store" in body
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# finalized-job links (tony top / monitor)
# ---------------------------------------------------------------------------
class TestFinalizedLinks:
    def test_tony_top_points_at_history(self, tmp_path, capsys):
        from tony_tpu.cli.introspect import main_top

        make_job(tmp_path, "app_fin")
        assert main_top(["app_fin", "--staging", str(tmp_path), "--once"]) == 0
        out = capsys.readouterr().out
        assert "finished: SUCCEEDED" in out
        assert "tony history show app_fin" in out

    def test_monitor_final_print_mentions_history(self, tmp_path, capsys):
        from tony_tpu.cluster.client import ApplicationHandle, _print_final

        handle = ApplicationHandle("app_m", str(tmp_path / "app_m"), None)
        _print_final(handle, {"status": "SUCCEEDED", "tasks": []})
        assert "tony history show app_m" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# headline e2e: two real jobs → live daemon → compare/gate/portal
# ---------------------------------------------------------------------------
@pytest.mark.e2e
class TestHistoryE2E:
    def test_two_jobs_ingested_compared_gated_and_rendered(
            self, tmp_tony_root, tmp_path, capsys):
        from tests.test_e2e import FAST, fixture_cmd
        from tony_tpu.cli.history import main as history_main, main_bench
        from tony_tpu.cluster.client import Client
        from tony_tpu.cluster.session import JobStatus
        from tony_tpu.portal.server import serve

        app_ids = []
        for mfu_base in ("0.40", "0.44"):
            cfg = TonyConfig({
                **FAST,
                keys.STAGING_ROOT: str(tmp_tony_root),
                keys.TASK_METRICS_INTERVAL_MS: "100",
                "tony.worker.instances": "1",
                keys.EXECUTES: f"{fixture_cmd('history_train.py')} 8 {mfu_base}",
            })
            client = Client(cfg)
            handle = client.submit()
            final = client.monitor_application(handle, quiet=True)
            assert final == JobStatus.SUCCEEDED, handle.final_status()
            app_ids.append(handle.app_id)

        # a LIVE history server ingests both finalized jobs
        srv = HistoryServer([str(tmp_tony_root)],
                            store_path=str(tmp_path / "e2e.sqlite"),
                            port=0, scan_interval_s=0.2)
        srv.start()
        base = f"http://127.0.0.1:{srv.address[1]}"
        try:
            deadline = time.time() + 15
            jobs = []
            while time.time() < deadline:
                jobs = json.loads(urllib.request.urlopen(base + "/api/jobs").read())
                if len(jobs) >= 2:
                    break
                time.sleep(0.2)
            assert sorted(j["app_id"] for j in jobs) == sorted(app_ids)
            for j in jobs:
                assert j["status"] == "SUCCEEDED" and not j["incomplete"]
                assert j["gang_epochs"] == 1
            # the distilled MFU trend separates the two runs
            trend = json.loads(urllib.request.urlopen(base + "/api/trend/mfu").read())
            assert len(trend) == 2
            health = json.loads(urllib.request.urlopen(base + "/healthz").read())
            assert health["ok"] and health["jobs"] == 2
        finally:
            srv.stop()

        # tony history compare shows both runs side by side
        capsys.readouterr()
        assert history_main([
            "compare", *app_ids, "--staging", str(tmp_tony_root),
            "--store", str(tmp_path / "e2e.sqlite")]) == 0
        out = capsys.readouterr().out
        assert all(a in out for a in app_ids) and "mfu_p50" in out

        # tony bench --gate: PASS on the real checked-in trajectory...
        assert main_bench(["--gate", "--trajectory-dir", REPO_ROOT]) == 0
        # ...and nonzero on a synthetically regressed record
        regressed = json.load(
            open(os.path.join(REPO_ROOT, "BENCH_r05.json")))
        regressed["parsed"]["value"] *= 0.5
        regressed["parsed"]["vs_baseline"] *= 0.5
        reg_path = tmp_path / "regressed.json"
        reg_path.write_text(json.dumps(regressed))
        capsys.readouterr()
        assert main_bench(["--gate", "--trajectory-dir", REPO_ROOT,
                           "--record", str(reg_path)]) == 1
        assert "REGRESSION" in capsys.readouterr().out

        # portal /history renders the trend with both runs
        server = serve(os.path.join(str(tmp_tony_root), "history"), 0,
                       str(tmp_tony_root),
                       history_db=str(tmp_path / "e2e.sqlite"))
        threading.Thread(target=server.serve_forever, daemon=True).start()
        pbase = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            body = urllib.request.urlopen(pbase + "/history").read().decode()
            assert all(a in body for a in app_ids)
            assert "<svg" in body  # the cross-job trend chart rendered
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# gate units (the trajectory-wide tier-1 check lives in test_bench_gate.py)
# ---------------------------------------------------------------------------
class TestGateUnits:
    TRAJ = [
        ("BENCH_r01.json", {"n": 1, "rc": 0, "parsed": {
            "metric": "m", "value": 0.40, "unit": "mfu", "vs_baseline": 0.9,
            "step_time_ms": 1500.0}}),
        ("BENCH_r02.json", {"n": 2, "rc": 0, "parsed": {
            "metric": "m", "value": 0.45, "unit": "mfu", "vs_baseline": 1.0,
            "step_time_ms": 1400.0}}),
    ]

    def test_pass_when_at_best(self):
        cur = {"metric": "m", "value": 0.45, "unit": "mfu", "vs_baseline": 1.0}
        assert evaluate(cur, self.TRAJ).passed

    def test_fail_past_threshold(self):
        cur = {"metric": "m", "value": 0.40, "unit": "mfu", "vs_baseline": 0.88}
        res = evaluate(cur, self.TRAJ, tolerance_pct=5.0)
        assert not res.passed
        assert any(c.metric == "value" and not c.passed for c in res.checks)

    def test_lower_is_better_direction(self):
        cur = {"metric": "m", "value": 0.45, "unit": "mfu", "vs_baseline": 1.0,
               "step_time_ms": 1600.0}  # 14% slower than best 1400
        res = evaluate(cur, self.TRAJ)
        assert any(c.metric == "step_time_ms" and not c.passed for c in res.checks)

    def test_per_metric_threshold_override(self):
        cur = {"metric": "m", "value": 0.45, "unit": "mfu", "vs_baseline": 1.0,
               "step_time_ms": 1600.0}
        res = evaluate(cur, self.TRAJ, per_metric_pct={"step_time_ms": 20.0})
        assert all(c.passed for c in res.checks if c.metric == "step_time_ms")

    def test_kernel_smoke_failure_gates(self):
        cur = {"metric": "m", "value": 0.45, "unit": "mfu", "vs_baseline": 1.0,
               "kernel_smoke": "7/8"}
        res = evaluate(cur, self.TRAJ)
        assert not res.passed
        assert any(c.metric == "kernel_smoke" and not c.passed for c in res.checks)

    def test_fresh_trajectory_passes_with_note(self):
        """A preset change (renamed headline metric) or a first-ever record
        has nothing to regress against: pass-with-note, it BECOMES the
        trajectory to beat."""
        cur = {"metric": "other", "value": 0.1, "unit": "mfu", "vs_baseline": 0.2}
        res = evaluate(cur, self.TRAJ)
        assert res.passed
        assert "fresh trajectory" in res.checks[-1].note
        # ...but a kernel-smoke failure still gates a fresh trajectory
        cur["kernel_smoke"] = "6/8"
        assert not evaluate(cur, self.TRAJ).passed

    def test_single_record_trajectory_self_check_passes(self):
        only = self.TRAJ[:1]
        assert evaluate(only[0][1], only).passed

    def test_schema_validation(self):
        assert validate_record({"n": 1, "rc": 0, "parsed": {
            "metric": "m", "value": 0.4, "unit": "mfu", "vs_baseline": 1.0}}) == []
        errs = validate_record({"n": 1, "rc": 1, "parsed": {"metric": "m"}})
        assert any("rc" in e for e in errs)
        assert any("value" in e for e in errs)
        assert validate_record({"metric": "m", "value": float("nan"),
                                "unit": "u", "vs_baseline": 1.0}, wrapper=False)

    def test_parsed_of_unwraps(self):
        inner = {"metric": "m", "value": 1.0}
        assert parsed_of({"parsed": inner}) is inner
        assert parsed_of(inner) is inner
