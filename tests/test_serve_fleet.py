"""Replicated serving control plane (tony_tpu/serve; docs/serving.md).

Unit layer: fake replicas (configurable stdlib HTTP servers) + a fake AM
drive the health state machine, the router's balancing/failover/hedging,
and the autoscaler's decision core — no engine, no job spine.

E2E layer (the headline): a 2-replica ``tony serve`` fleet under continuous
client load; ``exec-crash`` kills one replica via ``tony.chaos.spec``; the
router retries/fails over so ZERO client requests fail, the gang restarts
the replica, the autoscaler's view reconverges, and the job trace +
portal ``/metrics`` carry the router spans and per-replica serving
instruments for the whole episode. Plus ``resize_jobtype`` driving the
AM's elastic rebuild on a plain fixture gang.
"""

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tony_tpu import constants
from tony_tpu.cli.notebook import TaskUrlUnavailable, wait_for_task_url
from tony_tpu.config import TonyConfig, keys
from tony_tpu.obs import metrics as obs_metrics
from tony_tpu.obs import trace as obs_trace
from tony_tpu.serve import (
    AutoscalePolicy,
    Autoscaler,
    FleetRouter,
    HealthMonitor,
    Replica,
    ReplicaState,
)
from tony_tpu.serve.health import FleetSignals

pytestmark = pytest.mark.serve


# ---------------------------------------------------------------------------
# fakes: replica HTTP server + AM RPC surface
# ---------------------------------------------------------------------------
class _FakeReplicaHandler(BaseHTTPRequestHandler):
    def log_message(self, *a) -> None:
        pass

    def _json(self, code, obj, headers=None):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        cfg = self.server.cfg
        if self.path == "/stats":
            self._json(200, {
                "healthy": cfg["healthy"], "draining": cfg["draining"],
                "queue_depth": cfg["queue_depth"],
                "slots_active": cfg["slots_active"], "slots_total": cfg["slots_total"],
                "requests_done": cfg["hits"], "tokens_out": 0, "tokens_delivered": 0,
            })
        else:
            self._json(404, {"error": "not found"})

    def do_POST(self):  # noqa: N802
        cfg = self.server.cfg
        n = int(self.headers.get("Content-Length") or 0)
        req = json.loads(self.rfile.read(n) or b"{}")
        cfg["hits"] += 1
        if cfg["delay_s"]:
            time.sleep(cfg["delay_s"])
        if cfg["status"] != 200:
            self._json(cfg["status"], {"error": cfg["error"]})
            return
        if req.get("stream"):
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.end_headers()
            for chunk in ([1, 2], [3, 4]):
                self.wfile.write(b"data: " + json.dumps({"tokens": chunk}).encode() + b"\n\n")
                self.wfile.flush()
            self.wfile.write(
                b"data: " + json.dumps({"finished": True, "tokens": [1, 2, 3, 4]}).encode() + b"\n\n")
            self.wfile.flush()
        else:
            self._json(200, {"tokens": cfg["tokens"], "finished": True})


class FakeReplica:
    def __init__(self, **cfg):
        self.cfg = dict(healthy=True, draining=False, queue_depth=0, slots_active=0,
                        slots_total=8, delay_s=0.0, status=200, error="injected",
                        tokens=[1, 2, 3], hits=0)
        self.cfg.update(cfg)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), _FakeReplicaHandler)
        self.httpd.cfg = self.cfg
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class FakeAM:
    """The two RPCs the health monitor uses + the autoscaler's lever."""

    def __init__(self):
        self.tasks = {}
        self.attempt = 0
        self.resizes = []

    def set_replica(self, idx, url, status="RUNNING"):
        self.tasks[idx] = {"name": "serve", "index": idx, "url": url, "status": status}

    def drop_replica(self, idx):
        self.tasks.pop(idx, None)

    def call(self, method, **kw):
        if method == "get_application_status":
            return {"restart_attempt": self.attempt}
        if method == "get_task_infos":
            return list(self.tasks.values())
        if method == "resize_jobtype":
            self.resizes.append((kw["job_name"], kw["instances"]))
            return {"ack": True, "current": kw["instances"]}
        raise AssertionError(f"unexpected AM call {method}")


def make_health(am, **kw):
    kw.setdefault("interval_s", 999)  # tests drive tick() by hand
    kw.setdefault("fail_threshold", 2)
    kw.setdefault("probe_timeout_s", 1.0)
    return HealthMonitor(am.call, **kw)


def dead_url():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


def post_router(url, obj, timeout=30):
    req = urllib.request.Request(
        url + "/v1/completions", json.dumps(obj).encode(),
        {"Content-Type": "application/json"})
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


# ---------------------------------------------------------------------------
# health: state machine + endpoint re-resolution
# ---------------------------------------------------------------------------
class TestHealthStateMachine:
    def test_probe_flips_unknown_to_healthy_and_reads_stats(self):
        rep, am = FakeReplica(queue_depth=3, slots_active=4), FakeAM()
        am.set_replica(0, rep.url)
        h = make_health(am)
        try:
            h._resolve()
            assert h.replicas[0].state == ReplicaState.UNKNOWN
            h.tick()
            r = h.replicas[0]
            assert r.state == ReplicaState.HEALTHY
            assert r.stats["queue_depth"] == 3
            sig = h.fleet_signals()
            assert sig.replicas_healthy == 1 and sig.queue_depth == 3
            assert sig.slots_active == 4 and sig.slots_total == 8
        finally:
            rep.close()

    def test_draining_and_fatal_states(self):
        rep, am = FakeReplica(), FakeAM()
        am.set_replica(0, rep.url)
        h = make_health(am)
        try:
            h.tick()
            assert h.replicas[0].state == ReplicaState.HEALTHY
            rep.cfg["draining"] = True
            h.tick()
            assert h.replicas[0].state == ReplicaState.DRAINING
            rep.cfg["draining"] = False
            rep.cfg["healthy"] = False  # fatal engine error
            h.tick()
            assert h.replicas[0].state == ReplicaState.DOWN  # immediate, no budget
        finally:
            rep.close()

    def test_probe_failures_down_after_threshold_then_recover(self):
        rep, am = FakeReplica(), FakeAM()
        am.set_replica(0, dead_url())
        h = make_health(am)  # fail_threshold=2
        h.tick()
        assert h.replicas[0].state == ReplicaState.UNKNOWN  # 1 miss: not yet
        h.tick()
        assert h.replicas[0].state == ReplicaState.DOWN
        # endpoint re-registers somewhere alive → next tick recovers
        am.set_replica(0, rep.url)
        try:
            h.tick()
            assert h.replicas[0].state == ReplicaState.HEALTHY
        finally:
            rep.close()

    def test_passive_hard_failure_is_immediate_down(self):
        rep, am = FakeReplica(), FakeAM()
        am.set_replica(0, rep.url)
        h = make_health(am)
        try:
            h.tick()
            r = h.replicas[0]
            h.report_failure(r, hard=True)
            assert r.state == ReplicaState.DOWN
            h.tick()  # active probe against the live server resurrects it
            assert h.replicas[0].state == ReplicaState.HEALTHY
        finally:
            rep.close()

    def test_gang_restart_invalidates_urls_and_reresolves(self):
        rep, am = FakeReplica(), FakeAM()
        am.set_replica(0, dead_url())  # pre-restart URL, process gone
        h = make_health(am)
        h._resolve()
        h.replicas[0].state = ReplicaState.HEALTHY  # pretend it was fine
        am.attempt = 1
        h._resolve()
        # attempt bump: the old URL is dead even if its port answers
        assert h.replicas[0].attempt == 1
        assert h.replicas[0].state == ReplicaState.UNKNOWN  # fresh entry for new epoch
        am.set_replica(0, rep.url)
        try:
            h.tick()
            assert h.replicas[0].url == rep.url
            assert h.replicas[0].state == ReplicaState.HEALTHY
        finally:
            rep.close()

    def test_report_success_never_resurrects_stale_epoch(self):
        """After a gang restart bumps the attempt, a completing in-flight
        request on the OLD endpoint must not flip it back to routable."""
        am = FakeAM()
        h = make_health(am)
        r = Replica(index=0, url=dead_url(), attempt=0, state=ReplicaState.DOWN)
        h.replicas[0] = r
        h.restart_attempt = 1  # new epoch: r's URL belongs to the dead gang
        h.report_success(r)
        assert r.state == ReplicaState.DOWN
        # current-epoch replicas DO resurrect
        r2 = Replica(index=1, url=dead_url(), attempt=1, state=ReplicaState.DOWN)
        h.replicas[1] = r2
        h.report_success(r2)
        assert r2.state == ReplicaState.HEALTHY

    def test_scaled_down_index_is_forgotten(self):
        am = FakeAM()
        am.set_replica(0, dead_url())
        am.set_replica(1, dead_url())
        h = make_health(am)
        h._resolve()
        assert set(h.replicas) == {0, 1}
        am.drop_replica(1)  # fleet resized 2 → 1
        h._resolve()
        assert set(h.replicas) == {0}


# ---------------------------------------------------------------------------
# router: balancing, failover, passthrough, streaming, hedging
# ---------------------------------------------------------------------------
def make_router(h, **kw):
    kw.setdefault("failover_deadline_s", 10.0)
    return FleetRouter(h, **kw).start()


def inject(h, idx, url, state=ReplicaState.HEALTHY, outstanding=0):
    r = Replica(index=idx, url=url, state=state)
    r.outstanding = outstanding
    h.replicas[idx] = r
    return r


class TestRouter:
    def test_least_outstanding_balancing(self):
        a, b, am = FakeReplica(tokens=[1]), FakeReplica(tokens=[2]), FakeAM()
        h = make_health(am)
        router = make_router(h)
        try:
            inject(h, 0, a.url, outstanding=5)
            inject(h, 1, b.url, outstanding=0)
            code, hdrs, body = post_router(router.url, {"prompt_tokens": [1]})
            assert code == 200 and body["tokens"] == [2]
            assert hdrs["X-Tony-Replica"] == "1"
        finally:
            router.stop()
            a.close()
            b.close()

    def test_failover_retries_on_live_replica_zero_client_failures(self):
        b, am = FakeReplica(tokens=[7, 8]), FakeAM()
        h = make_health(am)
        router = make_router(h)
        try:
            # replica 0 looks HEALTHY but its process is gone (crash window
            # between health ticks) — ties break toward index 0, so the
            # router tries it FIRST, hard-fails, and fails over to 1
            inject(h, 0, dead_url())
            inject(h, 1, b.url)
            retries0 = _counter_value("tony_router_retries_total")
            code, hdrs, body = post_router(router.url, {"prompt_tokens": [1]})
            assert code == 200 and body["tokens"] == [7, 8]
            assert hdrs["X-Tony-Replica"] == "1"
            assert h.replicas[0].state == ReplicaState.DOWN  # passive hard mark
            assert _counter_value("tony_router_retries_total") == retries0 + 1
        finally:
            router.stop()
            b.close()

    def test_client_errors_forwarded_not_retried(self):
        a, am = FakeReplica(status=400, error="empty prompt"), FakeAM()
        h = make_health(am)
        router = make_router(h)
        try:
            inject(h, 0, a.url)
            code, _, body = post_router(router.url, {"prompt_tokens": []})
            assert code == 400 and "empty prompt" in body["error"]
            assert a.cfg["hits"] == 1  # exactly one attempt
            assert h.replicas[0].state == ReplicaState.HEALTHY  # not a replica failure
        finally:
            router.stop()
            a.close()

    def test_504_deadline_forwarded_not_retried(self):
        """504 is the replica's verdict on the CLIENT's deadline — forward
        it verbatim; retrying would restart the deadline clock elsewhere
        and mark a healthy replica down."""
        a, am = FakeReplica(status=504, error="deadline exceeded"), FakeAM()
        h = make_health(am)
        router = make_router(h)
        try:
            inject(h, 0, a.url)
            code, _, body = post_router(router.url, {"prompt_tokens": [1]})
            assert code == 504 and "deadline" in body["error"]
            assert a.cfg["hits"] == 1
            assert h.replicas[0].state == ReplicaState.HEALTHY
        finally:
            router.stop()
            a.close()

    def test_5xx_soft_failures_exhaust_to_502(self):
        a, am = FakeReplica(status=500, error="boom"), FakeAM()
        h = make_health(am, fail_threshold=100)  # keep it HEALTHY: retries hit it
        router = make_router(h, retries=2)
        try:
            inject(h, 0, a.url)
            code, _, body = post_router(router.url, {"prompt_tokens": [1]})
            assert code == 502 and "replicas failing" in body["error"]
            assert a.cfg["hits"] == 3  # initial + 2 retries
        finally:
            router.stop()
            a.close()

    def test_streaming_relayed_verbatim(self):
        a, am = FakeReplica(), FakeAM()
        h = make_health(am)
        router = make_router(h)
        try:
            inject(h, 0, a.url)
            req = urllib.request.Request(
                router.url + "/v1/completions",
                json.dumps({"prompt_tokens": [1], "stream": True}).encode(),
                {"Content-Type": "application/json"})
            events = []
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.headers["Content-Type"].startswith("text/event-stream")
                assert resp.headers["X-Tony-Replica"] == "0"
                for line in resp:
                    line = line.decode().strip()
                    if line.startswith("data: "):
                        events.append(json.loads(line[6:]))
                        if events[-1].get("finished"):
                            break
            assert [e.get("tokens") for e in events] == [[1, 2], [3, 4], [1, 2, 3, 4]]
        finally:
            router.stop()
            a.close()

    def test_fleet_down_waits_for_recovery_instead_of_failing(self):
        am = FakeAM()
        h = HealthMonitor(am.call, interval_s=0.05, fail_threshold=1)
        h.start()
        router = make_router(h, failover_deadline_s=15.0)
        rep = FakeReplica(tokens=[5])
        try:
            result = {}

            def client():
                result["r"] = post_router(router.url, {"prompt_tokens": [1]}, timeout=30)

            t = threading.Thread(target=client, daemon=True)
            t.start()  # no replicas registered yet: the router must WAIT
            time.sleep(0.5)
            assert "r" not in result
            am.set_replica(0, rep.url)  # the gang came (back) up
            t.join(timeout=20)
            code, hdrs, body = result["r"]
            assert code == 200 and body["tokens"] == [5]
        finally:
            h.stop()
            router.stop()
            rep.close()

    def test_unavailable_after_deadline_is_503(self):
        am = FakeAM()
        h = make_health(am)
        router = make_router(h, failover_deadline_s=0.5)
        try:
            code, _, body = post_router(router.url, {"prompt_tokens": [1]}, timeout=10)
            assert code == 503 and "no healthy replica" in body["error"]
        finally:
            router.stop()

    def test_hedge_fires_and_second_replica_wins(self):
        slow = FakeReplica(delay_s=2.0, tokens=[1])
        fast = FakeReplica(tokens=[2])
        am = FakeAM()
        h = make_health(am)
        router = make_router(h, hedge_percentile=95.0, hedge_min_s=0.1)
        try:
            # seed the latency window so a percentile exists
            for _ in range(30):
                router._latencies.observe(0.01)
            inject(h, 0, slow.url)
            inject(h, 1, fast.url, outstanding=1)  # primary pick = 0 (slow)
            hedges0 = _counter_value("tony_router_hedges_total")
            wins0 = _counter_value("tony_router_hedge_wins_total")
            t0 = time.monotonic()
            code, hdrs, body = post_router(router.url, {"prompt_tokens": [1]})
            took = time.monotonic() - t0
            assert code == 200 and body["tokens"] == [2]
            assert hdrs["X-Tony-Replica"] == "1"
            assert took < 1.5, "hedge should beat the slow primary"
            assert _counter_value("tony_router_hedges_total") == hedges0 + 1
            assert _counter_value("tony_router_hedge_wins_total") == wins0 + 1
        finally:
            router.stop()
            time.sleep(0)  # let the losing leg settle before closing
            slow.close()
            fast.close()

    def test_fleet_and_stats_pages(self):
        a, am = FakeReplica(), FakeAM()
        h = make_health(am)
        router = make_router(h)
        try:
            am.set_replica(0, a.url)
            h.tick()
            with urllib.request.urlopen(router.url + "/fleet", timeout=10) as resp:
                fleet = json.loads(resp.read())
            assert fleet["replicas"][0]["state"] == "HEALTHY"
            with urllib.request.urlopen(router.url + "/stats", timeout=10) as resp:
                stats = json.loads(resp.read())
            assert stats["fleet"]["slots_total"] == 8
            assert "retries" in stats["router"]
            with urllib.request.urlopen(router.url + "/healthz", timeout=10) as resp:
                assert json.loads(resp.read())["ok"] is True
        finally:
            router.stop()
            a.close()

    def test_disabled_tracing_hot_path_is_allocation_free(self, monkeypatch):
        """Like obs's contract: with tracing off (the default), routing a
        request must never construct a Span."""
        assert obs_trace.get() is None

        def no_spans(*a, **kw):
            raise AssertionError("Span allocated on the disabled fast path")

        monkeypatch.setattr(obs_trace.Span, "__init__", no_spans)
        a, am = FakeReplica(tokens=[3]), FakeAM()
        h = make_health(am)
        router = make_router(h)
        try:
            inject(h, 0, a.url)
            code, _, body = post_router(router.url, {"prompt_tokens": [1]})
            assert code == 200 and body["tokens"] == [3]
        finally:
            router.stop()
            a.close()


def _counter_value(name, **labels):
    for m in obs_metrics.REGISTRY.snapshot():
        if m["name"] == name:
            for s in m["samples"]:
                if s["labels"] == {k: str(v) for k, v in labels.items()}:
                    return s["value"]
            return 0.0
    return 0.0


def _histogram_count(name, **labels):
    for m in obs_metrics.REGISTRY.snapshot():
        if m["name"] == name:
            return sum(s["count"] for s in m["samples"]
                       if all(s["labels"].get(k) == str(v) for k, v in labels.items()))
    return 0


# ---------------------------------------------------------------------------
# autoscaler: decision core + lever
# ---------------------------------------------------------------------------
def sig(healthy=2, queue=0, active=0, total=16, known=None):
    return FleetSignals(
        replicas_known=known if known is not None else healthy,
        replicas_healthy=healthy, queue_depth=queue,
        slots_active=active, slots_total=total)


class TestAutoscaler:
    def _scaler(self, am=None, **policy):
        p = AutoscalePolicy(**{**dict(min_replicas=1, max_replicas=4,
                                      scale_up_ticks=2, scale_down_ticks=3), **policy})
        am = am or FakeAM()
        h = make_health(am)
        return Autoscaler(h, lambda job, n: am.call(
            "resize_jobtype", job_name=job, instances=n), p), am

    def test_queue_pressure_scales_up_after_hysteresis(self):
        a, _ = self._scaler()
        assert a.decide(2, sig(queue=20)) == 2  # tick 1: hold
        assert a.decide(2, sig(queue=20)) == 3  # tick 2: fire

    def test_utilization_scales_up(self):
        a, _ = self._scaler()
        assert a.decide(2, sig(active=15, total=16)) == 2
        assert a.decide(2, sig(active=15, total=16)) == 3

    def test_ceiling_and_floor_clamp(self):
        a, _ = self._scaler(max_replicas=2)
        a.decide(2, sig(queue=50))
        assert a.decide(2, sig(queue=50)) == 2  # at ceiling: hold
        b, _ = self._scaler(min_replicas=2)
        for _ in range(10):
            target = b.decide(2, sig(queue=0, active=0))
        assert target == 2  # at floor: hold

    def test_scale_down_needs_longer_hysteresis_and_idle(self):
        a, _ = self._scaler()
        assert a.decide(3, sig(healthy=3, queue=0, active=0)) == 3
        assert a.decide(3, sig(healthy=3, queue=0, active=0)) == 3
        assert a.decide(3, sig(healthy=3, queue=0, active=0)) == 2  # tick 3

    def test_mixed_signals_reset_hysteresis(self):
        a, _ = self._scaler()
        a.decide(2, sig(queue=20))
        a.decide(2, sig(queue=0))  # pressure vanished
        assert a.decide(2, sig(queue=20)) == 2  # counter restarted

    def test_no_decision_while_fleet_down(self):
        a, _ = self._scaler()
        a.decide(2, sig(queue=50))  # up_ticks=1
        assert a.decide(2, sig(healthy=0, queue=0)) == 2
        assert a.decide(2, sig(queue=50)) == 2  # hysteresis was reset

    def test_tick_drives_the_am_lever(self):
        am = FakeAM()
        h = make_health(am)
        p = AutoscalePolicy(min_replicas=1, max_replicas=4, scale_up_ticks=1)
        a = Autoscaler(h, lambda job, n: am.call(
            "resize_jobtype", job_name=job, instances=n), p)
        inject(h, 0, dead_url()).stats = {"queue_depth": 50, "slots_active": 8,
                                          "slots_total": 8}
        a.tick()
        assert am.resizes == [("serve", 2)]
        assert a.target == 2


# ---------------------------------------------------------------------------
# wait_for_task_url: typed outcomes (was: None for both)
# ---------------------------------------------------------------------------
class _FakeHandle:
    def __init__(self, status=None):
        self._status = status

    def final_status(self):
        return self._status

    def rpc(self, timeout_s=0):
        return None


class TestWaitForTaskUrlTyped:
    def test_finished_job_raises_with_verdict(self):
        handle = _FakeHandle({"status": "FAILED", "reason": "allocation error"})
        with pytest.raises(TaskUrlUnavailable) as ei:
            wait_for_task_url(handle, "serve", timeout_s=5)
        assert ei.value.reason == "finished"
        assert "FAILED" in str(ei.value) and "allocation error" in str(ei.value)
        assert ei.value.final_status["status"] == "FAILED"

    def test_timeout_raises_distinctly(self):
        with pytest.raises(TaskUrlUnavailable) as ei:
            wait_for_task_url(_FakeHandle(None), "serve", timeout_s=0.3, poll_s=0.05)
        assert ei.value.reason == "timeout"
        assert "did not register" in str(ei.value)


# ---------------------------------------------------------------------------
# E2E: resize_jobtype rebuilds a live gang (fixture spine, no engine)
# ---------------------------------------------------------------------------
from tests.test_e2e import FAST, fixture_cmd  # noqa: E402

from tony_tpu.cluster.client import Client  # noqa: E402
from tony_tpu.cluster.session import JobStatus  # noqa: E402


def _wait(pred, timeout_s=60, poll_s=0.1):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll_s)
    return None


@pytest.mark.e2e
class TestResizeJobtypeE2E:
    def test_resize_grows_and_validates(self, tmp_tony_root):
        cfg = TonyConfig({
            **FAST,
            keys.STAGING_ROOT: str(tmp_tony_root),
            "tony.worker.instances": "1",
            keys.EXECUTES: fixture_cmd("forever.py"),
        })
        client = Client(cfg)
        handle = client.submit()
        try:
            rpc = handle.rpc()
            assert rpc is not None

            def running_workers():
                infos = rpc.call("get_task_infos")
                up = [t for t in infos if t["status"] == "RUNNING"]
                return up if len(up) == len(infos) else None

            assert _wait(running_workers), "initial worker never ran"

            from tony_tpu.cluster.rpc import RpcError

            # invalid requests are the TYPED InvalidResizeError through the
            # RPC error frame, not a generic {"ack": False} payload
            with pytest.raises(RpcError, match="InvalidResizeError.*unknown job type"):
                rpc.call("resize_jobtype", job_name="nope", instances=2)
            with pytest.raises(RpcError, match="InvalidResizeError.*>= 1"):
                rpc.call("resize_jobtype", job_name="worker", instances=0)
            r = rpc.call("resize_jobtype", job_name="worker", instances=1)
            assert r["ack"] and r.get("noop")

            r = rpc.call("resize_jobtype", job_name="worker", instances=2)
            assert r["ack"] and r["current"] == 1

            def two_running():
                infos = rpc.call("get_task_infos")
                return infos if (
                    len(infos) == 2 and all(t["status"] == "RUNNING" for t in infos)
                ) else None

            assert _wait(two_running, timeout_s=90), "resize to 2 never converged"
            status = rpc.call("get_application_status")
            assert status["restart_attempt"] == 1  # rebuild, not re-submission
        finally:
            Client.kill(handle)
        final = client.monitor_application(handle, quiet=True)
        assert final == JobStatus.KILLED


# ---------------------------------------------------------------------------
# E2E headline: 2-replica fleet + chaos exec-crash under continuous load
# ---------------------------------------------------------------------------
@pytest.mark.e2e
@pytest.mark.chaos
class TestFleetChaosE2E:
    @pytest.mark.slow
    def test_replica_crash_is_not_client_visible(self, tmp_tony_root):
        from tony_tpu.cli.serve import _fleet_am_client, build_serve_config
        from tony_tpu.portal import server as portal

        conf, _ = build_serve_config([
            "--replicas", "2", "--slots", "2", "--max_len", "64",
            "--decode_chunk", "4",
        ])
        conf.set(keys.STAGING_ROOT, str(tmp_tony_root))
        for k, v in FAST.items():
            conf.set(k, v)
        conf.set(keys.TASK_HEARTBEAT_INTERVAL_MS, "200")
        conf.set(keys.TASK_METRICS_INTERVAL_MS, "500")
        conf.set(keys.TRACE_ENABLED, "true")
        # the latch fires once: attempt 0's replica 0 crashes mid-load, the
        # restarted gang stays healthy
        conf.set(keys.CHAOS_SPEC, "exec-crash:serve:0@t+25s")
        conf.set(keys.CHAOS_SEED, "7")
        assert conf.get_bool(keys.TASK_RESTART_ON_FAILURE)  # serve default

        client = Client(conf)
        handle = client.submit()
        health = router = None
        failures: list = []
        try:
            wait_for_task_url(handle, constants.SERVE_JOB_NAME, timeout_s=180)
            fleet_rpc = _fleet_am_client(handle)
            assert fleet_rpc is not None
            health = HealthMonitor(fleet_rpc.call, interval_s=0.2, fail_threshold=2)
            health.tick()
            health.start()
            router = FleetRouter(health, failover_deadline_s=120.0).start()

            ok = [0]
            observed_down = threading.Event()
            stop_load = threading.Event()

            def load():
                i = 0
                while not stop_load.is_set():
                    i += 1
                    try:
                        code, _, body = post_router(
                            router.url,
                            {"prompt_tokens": [1 + (i % 5), 2, 3], "max_tokens": 4},
                            timeout=150)
                    except Exception as e:  # noqa: BLE001 — a failure IS the signal
                        failures.append(repr(e))
                        continue
                    if code == 200 and body.get("finished"):
                        ok[0] += 1
                    else:
                        failures.append((code, body))

            def watch():
                while not stop_load.is_set():
                    if any(r.state == ReplicaState.DOWN for r in health.snapshot()):
                        observed_down.set()
                    time.sleep(0.05)

            threads = [threading.Thread(target=load, daemon=True),
                       threading.Thread(target=watch, daemon=True)]
            for t in threads:
                t.start()

            # phase 1: the crash lands (gang restart bumps the attempt)
            assert _wait(
                lambda: (handle.rpc().call("get_application_status")
                         .get("restart_attempt", 0) >= 1) or None,
                timeout_s=120,
            ), "chaos exec-crash never triggered a gang restart"
            assert observed_down.wait(timeout=60), "health never observed the outage"

            # phase 2: the fleet reconverges — 2 replicas healthy again
            assert _wait(
                lambda: health.fleet_signals().replicas_healthy == 2 or None,
                timeout_s=150,
            ), f"fleet never recovered: {health.fleet_info()}"
            served_after = ok[0]
            assert _wait(lambda: ok[0] > served_after + 3 or None, timeout_s=60), \
                "no successful requests after recovery"
            stop_load.set()
            for t in threads:
                t.join(timeout=160)

            # ZERO client-visible failures across the whole episode
            assert not failures, failures[:5]
            assert ok[0] > 0

            # the autoscaler's view reconverges on the restarted fleet
            resizes: list = []
            scaler = Autoscaler(
                health, lambda job, n: resizes.append((job, n)),
                AutoscalePolicy(min_replicas=1, max_replicas=3,
                                scale_down_utilization=0.0),  # idle ≠ shrink here
            )
            scaler.tick()
            sig2 = health.fleet_signals()
            assert sig2.replicas_known == 2 and sig2.replicas_healthy == 2
            assert resizes == []  # steady state: no resize issued

            # /metrics (the portal's, scraped live) shows the router counters
            # pushed via push_client_metrics AND the replicas' serving
            # instruments (executor piggyback of the .obs drop)
            snap = [m for m in obs_metrics.REGISTRY.snapshot() if m["samples"]]
            fleet_rpc.call("push_client_metrics", identity="router", metrics=snap)
            history_root = os.path.join(str(tmp_tony_root), "history")
            psrv = portal.serve(history_root, port=0, staging_root=str(tmp_tony_root))
            threading.Thread(target=psrv.serve_forever, daemon=True).start()
            try:
                pport = psrv.server_address[1]

                def scrape():
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{pport}/metrics", timeout=10
                    ) as resp:
                        return resp.read().decode()

                text = _wait(
                    lambda: (lambda t: t if (
                        "tony_router_requests_total" in t
                        and "tony_serve_ttft_seconds" in t) else None)(scrape()),
                    timeout_s=30, poll_s=1.0,
                )
                assert text, "portal /metrics never showed router + serving instruments"
                assert f'app="{handle.app_id}"' in text
                assert 'task="router"' in text
            finally:
                psrv.shutdown()
                psrv.server_close()
        finally:
            if router is not None:
                router.stop()
            if health is not None:
                health.stop()
            Client.kill(handle)
            final = client.monitor_application(handle, quiet=True)
            obs_trace.shutdown()  # the submit() call installed a client tracer
        assert final == JobStatus.KILLED

        # the job trace carries the router→replica spans and the restart
        trace_dir = os.path.join(str(tmp_tony_root), handle.app_id, "trace")
        spans = []
        for fn in os.listdir(trace_dir):
            if fn.endswith(".spans.jsonl"):
                with open(os.path.join(trace_dir, fn)) as f:
                    spans += [json.loads(line) for line in f if line.strip()]
        names = {s["name"] for s in spans}
        assert "router.request" in names, sorted(names)
        assert "router.attempt" in names
        assert "am.gang_restart" in names
        # router spans join the ONE job trace (trace_id = app id)
        assert all(s["trace_id"] == handle.app_id for s in spans)

        # `tony trace` renders the episode end-to-end
        from tony_tpu.cli.trace import main as trace_main

        out_path = os.path.join(str(tmp_tony_root), "trace.json")
        rc = trace_main([handle.app_id, "--staging", str(tmp_tony_root),
                         "--out", out_path])
        assert rc == 0 and os.path.exists(out_path)
