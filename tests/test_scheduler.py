"""Gang/dependency scheduler tests (TestTaskScheduler analog, SURVEY.md §4)."""

import pytest

from tony_tpu.config import TonyConfig, keys
from tony_tpu.cluster.resources import AllocationError, LocalResourceManager
from tony_tpu.cluster.scheduler import DependencyTimeout, TaskScheduler
from tony_tpu.cluster.session import Session


def build(conf: dict, pool="local:cpu"):
    cfg = TonyConfig(conf)
    session = Session(cfg)
    rm = LocalResourceManager(pool)
    return TaskScheduler(cfg, session, rm), session, rm


class TestDependencyOrdering:
    CONF = {
        "tony.ps.instances": "1",
        "tony.worker.instances": "2",
        keys.dependency_key("worker", "ps"): "10s",
    }

    def test_worker_waits_for_ps(self):
        sched, session, _ = build(self.CONF)
        assert sched.ready_types() == ["ps"]
        sched.allocate_type("ps")
        assert sched.ready_types() == []  # ps allocated but not registered yet
        session.register_worker_spec("ps", 0, "h", 1)
        assert sched.ready_types() == ["worker"]

    def test_dependency_timeout_raises(self):
        conf = dict(self.CONF)
        conf[keys.dependency_key("worker", "ps")] = "0ms"
        sched, _, _ = build(conf)
        sched.allocate_type("ps")
        import time

        sched.ready_types()  # starts the wait clock
        time.sleep(0.01)
        with pytest.raises(DependencyTimeout):
            sched.ready_types()

    def test_undeclared_dependency_rejected(self):
        with pytest.raises(ValueError):
            build({
                "tony.worker.instances": "1",
                keys.dependency_key("worker", "ghost"): "1s",
            })


class TestGangAllocation:
    def test_all_or_nothing(self):
        # 4-chip pool, 2 workers x 4 chips: second alloc fails → first released
        sched, _, rm = build(
            {"tony.worker.instances": "2", "tony.worker.chips": "4"}, pool="local:v5e-4"
        )
        with pytest.raises(AllocationError):
            sched.allocate_type("worker")
        assert rm.grid.free == 4  # nothing leaked

    def test_no_dependencies_all_ready_in_priority_order(self):
        sched, _, _ = build({"tony.worker.instances": "1", "tony.evaluator.instances": "1"})
        assert sched.ready_types() == ["evaluator", "worker"]  # declared order
        sched.allocate_type("evaluator")
        sched.allocate_type("worker")
        assert sched.all_launched()
