"""Gang/dependency scheduler tests (TestTaskScheduler analog, SURVEY.md §4)."""

import pytest

from tony_tpu.config import TonyConfig, keys
from tony_tpu.cluster.resources import AllocationError, LocalResourceManager
from tony_tpu.cluster.scheduler import DependencyTimeout, TaskScheduler
from tony_tpu.cluster.session import Session


def build(conf: dict, pool="local:cpu"):
    cfg = TonyConfig(conf)
    session = Session(cfg)
    rm = LocalResourceManager(pool)
    return TaskScheduler(cfg, session, rm), session, rm


class TestDependencyOrdering:
    CONF = {
        "tony.ps.instances": "1",
        "tony.worker.instances": "2",
        keys.dependency_key("worker", "ps"): "10s",
    }

    def test_worker_waits_for_ps(self):
        sched, session, _ = build(self.CONF)
        assert sched.ready_types() == ["ps"]
        sched.allocate_type("ps")
        assert sched.ready_types() == []  # ps allocated but not registered yet
        session.register_worker_spec("ps", 0, "h", 1)
        assert sched.ready_types() == ["worker"]

    def test_dependency_timeout_raises(self):
        conf = dict(self.CONF)
        conf[keys.dependency_key("worker", "ps")] = "0ms"
        sched, _, _ = build(conf)
        sched.allocate_type("ps")
        import time

        sched.ready_types()  # starts the wait clock
        time.sleep(0.01)
        with pytest.raises(DependencyTimeout):
            sched.ready_types()

    def test_undeclared_dependency_rejected(self):
        with pytest.raises(ValueError):
            build({
                "tony.worker.instances": "1",
                keys.dependency_key("worker", "ghost"): "1s",
            })


class TestGangAllocation:
    def test_all_or_nothing(self):
        # 4-chip pool, 2 workers x 4 chips: second alloc fails → first released
        sched, _, rm = build(
            {"tony.worker.instances": "2", "tony.worker.chips": "4"}, pool="local:v5e-4"
        )
        with pytest.raises(AllocationError):
            sched.allocate_type("worker")
        assert rm.grid.free == 4  # nothing leaked

    def test_no_dependencies_all_ready_in_priority_order(self):
        sched, _, _ = build({"tony.worker.instances": "1", "tony.evaluator.instances": "1"})
        assert sched.ready_types() == ["evaluator", "worker"]  # declared order
        sched.allocate_type("evaluator")
        sched.allocate_type("worker")
        assert sched.all_launched()


class TestPlanDownsize:
    """The elastic-downsize decision (VERDICT r4 #1): pure-function tests of
    plan_downsize — the AM wires it to rm.total_capacity() + gang restart."""

    @staticmethod
    def _r(mem_gb=0, vcores=0, chips=0):
        from tony_tpu.cluster.resources import Resources

        return Resources(memory_bytes=mem_gb * 1024**3, vcores=vcores, chips=chips)

    def test_fits_returns_none(self):
        from tony_tpu.cluster.scheduler import plan_downsize

        got = plan_downsize(
            {"worker": 2}, {"worker": self._r(mem_gb=3)}, {"worker": 1},
            capacity=self._r(mem_gb=8),
        )
        assert got is None  # no shrink needed

    def test_shrinks_to_fit_after_node_loss(self):
        from tony_tpu.cluster.scheduler import plan_downsize

        # two 3g workers, pool lost a node: 4g left → one worker fits
        got = plan_downsize(
            {"worker": 2}, {"worker": self._r(mem_gb=3)}, {"worker": 1},
            capacity=self._r(mem_gb=4),
        )
        assert got == {"worker": 1}

    def test_respects_floor(self):
        from tony_tpu.cluster.scheduler import plan_downsize

        # floor 2 but only one instance fits: no legal shrink → keep queuing
        got = plan_downsize(
            {"worker": 4}, {"worker": self._r(mem_gb=3)}, {"worker": 2},
            capacity=self._r(mem_gb=4),
        )
        assert got is None

    def test_unshrinkable_type_never_shrinks(self):
        from tony_tpu.cluster.scheduler import plan_downsize

        # floor 0 = elasticity off for the type
        got = plan_downsize(
            {"worker": 2}, {"worker": self._r(mem_gb=3)}, {"worker": 0},
            capacity=self._r(mem_gb=4),
        )
        assert got is None

    def test_multi_type_shrinks_evenly_and_keeps_fixed_types(self):
        from tony_tpu.cluster.scheduler import plan_downsize

        got = plan_downsize(
            {"worker": 4, "ps": 1},
            {"worker": self._r(mem_gb=2), "ps": self._r(mem_gb=2)},
            {"worker": 1, "ps": 0},  # ps is not shrinkable
            capacity=self._r(mem_gb=6),
        )
        # ps keeps its 2g; workers shrink 4 → 2 (4g) to fit 6g total
        assert got == {"worker": 2}

    def test_chips_dimension_drives_shrink(self):
        from tony_tpu.cluster.scheduler import plan_downsize

        got = plan_downsize(
            {"worker": 8}, {"worker": self._r(chips=1)}, {"worker": 2},
            capacity=self._r(mem_gb=999, chips=4),
        )
        assert got == {"worker": 4}

    def test_shrinks_only_to_divisors_of_the_configured_count(self):
        """A batch-sized gang must shrink 4 -> 2, never 4 -> 3: non-divisor
        counts crash batch/mesh divisibility on relaunch, looping the
        restart budget away."""
        from tony_tpu.cluster.scheduler import plan_downsize

        # capacity fits 3 instances — but 3 does not divide 4, so 2 it is
        got = plan_downsize(
            {"worker": 4}, {"worker": self._r(mem_gb=3)}, {"worker": 1},
            capacity=self._r(mem_gb=10),
        )
        assert got == {"worker": 2}

    def test_placement_not_just_totals(self):
        """4x3g does NOT fit three 4g nodes (12g <= 12g is a lie): with
        per-node capacities, fits() demands a real placement."""
        from tony_tpu.cluster.scheduler import plan_downsize

        nodes = [self._r(mem_gb=4)] * 3
        got = plan_downsize(
            {"worker": 4}, {"worker": self._r(mem_gb=3)}, {"worker": 1},
            capacity=self._r(mem_gb=12), nodes=nodes,
        )
        assert got == {"worker": 2}
        # and with nodes that DO hold one instance each, no shrink happens
        got = plan_downsize(
            {"worker": 3}, {"worker": self._r(mem_gb=3)}, {"worker": 1},
            capacity=self._r(mem_gb=12), nodes=[self._r(mem_gb=4)] * 3,
        )
        assert got is None
