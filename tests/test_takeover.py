"""Work-preserving control-plane restart suite (docs/fault-tolerance.md
"Control-plane failures").

The AM and pool service die by SIGKILL (`am-crash` / `pool-crash`) and their
successors must ADOPT the live work: journal units, AM replay semantics,
resync fencing, container adoption (local + remote pools), pool queue
recovery through agent re-registration, and the headline e2e — a 2-worker
training gang whose AM is SIGKILLed mid-run finishes SUCCEEDED with zero
executor restarts and a strictly monotonic step counter, asserted under
``tony chaos --expect-takeover``. The corrupt-journal case degrades loudly
to a full gang restart.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time

import pytest

from tony_tpu import constants
from tony_tpu.chaos import FaultSchedule
from tony_tpu.cluster import history
from tony_tpu.cluster.appmaster import ApplicationMaster, _replay_am_journal
from tony_tpu.cluster.journal import Journal, JournalError, read_journal
from tony_tpu.cluster.pool import PoolService, RemoteResourceManager
from tony_tpu.cluster.resources import ContainerLauncher, LocalResourceManager, Resources
from tony_tpu.cluster.session import JobStatus
from tony_tpu.config import TonyConfig, keys

from tests.test_e2e import FAST, fixture_cmd

pytestmark = [pytest.mark.chaos, pytest.mark.elastic]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# journal substrate
# ---------------------------------------------------------------------------
class TestJournal:
    def test_roundtrip(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = Journal(p)
        j.append("epoch", attempt=0, resized={})
        j.append("registered", job="worker", index=1, host="h", port=9)
        j.close()
        recs = read_journal(p)
        assert [r["t"] for r in recs] == ["epoch", "registered"]
        assert recs[1]["index"] == 1

    def test_torn_tail_tolerated(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = Journal(p)
        j.append("epoch", attempt=0)
        j.append("gang_complete")
        j.close()
        with open(p, "a") as f:
            f.write('{"t": "registered", "job": "wor')  # SIGKILL mid-append
        recs = read_journal(p)
        assert [r["t"] for r in recs] == ["epoch", "gang_complete"]

    def test_mid_file_garbage_is_corrupt(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        with open(p, "w") as f:
            f.write('{"t": "epoch", "attempt": 0}\n')
            f.write("}{ definitely not json\n")
            f.write('{"t": "gang_complete"}\n')
        with pytest.raises(JournalError, match="corrupt journal record at line 2"):
            read_journal(p)

    def test_missing_and_empty(self, tmp_path):
        with pytest.raises(JournalError, match="missing"):
            read_journal(str(tmp_path / "nope.jsonl"))
        p = str(tmp_path / "empty.jsonl")
        open(p, "w").close()
        with pytest.raises(JournalError, match="empty"):
            read_journal(p)

    def test_append_survives_closed_file(self, tmp_path):
        # teardown race: a late append must never raise into the caller
        j = Journal(str(tmp_path / "j.jsonl"))
        j.close()
        j.append("epoch", attempt=0)  # no raise


# ---------------------------------------------------------------------------
# AM journal replay semantics
# ---------------------------------------------------------------------------
def _rec(t, **kw):
    return {"t": t, **kw}


class TestAmJournalReplay:
    def test_basic_state(self):
        st = _replay_am_journal([
            _rec("epoch", attempt=0, resized={}),
            _rec("registered", job="worker", index=0, host="h0", port=1),
            _rec("registered", job="worker", index=1, host="h1", port=2),
            _rec("gang_complete"),
            _rec("task_started", job="worker", index=0, cid="c0",
                 log_dir="/l", started_ms=5, container={"id": "c0"}),
            _rec("task_done", job="worker", index=1, exit_code=0),
            _rec("chaos_step", step=4),
            _rec("failures", n=2),
        ])
        assert st.attempt == 0 and st.gang_complete
        assert st.registered == {("worker", 0): ("h0", 1), ("worker", 1): ("h1", 2)}
        assert st.done == {("worker", 1): 0}
        assert list(st.containers) == ["c0"]
        assert st.chaos_step == 4 and st.failures == 2

    def test_epoch_supersedes_task_records(self):
        st = _replay_am_journal([
            _rec("epoch", attempt=0, resized={}),
            _rec("registered", job="worker", index=0, host="old", port=1),
            _rec("task_started", job="worker", index=0, cid="c0", container={}),
            _rec("gang_complete"),
            _rec("failures", n=1),
            _rec("epoch", attempt=1, resized={"worker": 2}),
            _rec("registered", job="worker", index=0, host="new", port=9),
        ])
        assert st.attempt == 1 and st.resized == {"worker": 2}
        assert st.registered == {("worker", 0): ("new", 9)}
        assert not st.containers and not st.gang_complete
        assert st.failures == 1  # cross-epoch: the budget survives restarts

    def test_pending_resize_last_wins(self):
        st = _replay_am_journal([
            _rec("epoch", attempt=0, resized={}),
            _rec("pending_resize", resizes={"serve": 4}),
            _rec("pending_resize", resizes={}),
        ])
        assert st.pending == {}

    def test_no_epoch_record_is_error(self):
        with pytest.raises(JournalError, match="no epoch record"):
            _replay_am_journal([_rec("gang_complete")])

    def test_unknown_record_type_is_error(self):
        with pytest.raises(JournalError, match="unknown journal record"):
            _replay_am_journal([_rec("epoch", attempt=0), _rec("from_the_future")])


# ---------------------------------------------------------------------------
# resync fencing: only an AM that actually adopted accepts re-attaches
# ---------------------------------------------------------------------------
class TestResyncFencing:
    @pytest.fixture()
    def am(self, tmp_path):
        cfg = TonyConfig({"tony.worker.instances": "1"})
        am = ApplicationMaster(cfg, "app_resync_test", str(tmp_path / "stage"))
        yield am
        am.rpc.stop()
        am.events.stop()
        am.rm.shutdown()

    def test_non_takeover_am_rejects_resync(self, am):
        am.register_worker_spec("worker", 0, "127.0.0.1", 1234, attempt=0)
        assert am.resync_task("worker", 0, "127.0.0.1", 1234, attempt=0)["stale"]

    def test_adopted_am_accepts_and_refreshes_heartbeat(self, am):
        am.register_worker_spec("worker", 0, "127.0.0.1", 1234, attempt=0)
        am._takeover_outcome = "adopted"
        task = am.session.get_task("worker", 0)
        task.last_heartbeat_ms = 1.0  # ancient: about to be declared dead
        resp = am.resync_task("worker", 0, "127.0.0.1", 4321, attempt=0)
        assert resp["ack"]
        assert task.port == 4321
        assert task.last_heartbeat_ms > 1.0
        assert not am.session.find_dead_tasks(100, 3)

    def test_adopted_am_fences_stale_epoch_and_unknown_task(self, am):
        am._takeover_outcome = "adopted"
        am._restart_attempt = 2
        assert am.resync_task("worker", 0, "h", 1, attempt=1)["stale"]
        assert am.resync_task("ghost", 7, "h", 1, attempt=2)["stale"]


# ---------------------------------------------------------------------------
# container adoption: launcher pid tracking + local RM re-accounting
# ---------------------------------------------------------------------------
def _spawn_detached() -> int:
    proc = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(120)"],
        start_new_session=True,
    )
    return proc.pid


class TestAdoptedContainers:
    def test_launcher_adopts_probes_and_kills(self):
        pid = _spawn_detached()
        launcher = ContainerLauncher()
        launcher.adopt("c_adopt", pid, grace_s=0.2)
        assert "c_adopt" in launcher.live_ids()
        assert launcher.poll_exited() == {}
        launcher.kill("c_adopt", force=True)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            exited = launcher.poll_exited()
            if exited:
                break
            time.sleep(0.05)
        assert exited == {"c_adopt": constants.EXIT_ADOPTED_UNKNOWN}
        assert "c_adopt" not in launcher.live_ids()

    def test_dead_pid_surfaces_on_first_poll(self):
        launcher = ContainerLauncher()
        launcher.adopt("c_gone", 2**22 + os.getpid())  # almost surely no such pid
        assert launcher.poll_exited() == {"c_gone": constants.EXIT_ADOPTED_UNKNOWN}

    def test_local_rm_adoption_reaccounts(self):
        rm1 = LocalResourceManager("local:cpu,2x2")
        res = Resources(memory_bytes=1 << 30, vcores=2, chips=2)
        c = rm1.allocate("worker", 0, res)
        pid = _spawn_detached()
        # stand in for a real start_container: the launcher only needs a pid
        rm1.launcher.adopt(c.id, pid)
        rec = rm1.journal_info(c)
        assert rec is not None and rec["pid"] == pid and rec["job_type"] == "worker"

        rm2 = LocalResourceManager("local:cpu,2x2")
        c2 = rm2.adopt_container(rec)
        assert c2 is not None and c2.id == c.id and c2.chip_coords == c.chip_coords
        assert rm2.host.used_memory == 1 << 30 and rm2.host.used_vcores == 2
        assert rm2.grid.free == 2
        # double adoption = the journal disagrees with the world → refused
        assert rm2.adopt_container(rec) is None
        # adopted containers die through the normal kill path
        rm2.kill_container(c2)
        rm2.release(c2)
        assert rm2.host.used_memory == 0 and rm2.grid.free == 4
        if _alive(pid):
            os.kill(pid, signal.SIGKILL)

    def test_remote_rm_adoption_against_live_pool(self):
        svc = PoolService()
        try:
            svc.register_node(name="n0", host="127.0.0.1", port=1,
                              memory_bytes=4 << 30, vcores=8, live=[])
            svc.rpc.start()
            host, port = svc.address
            rm1 = RemoteResourceManager(host, port, app_id="app_adopt")
            c = rm1.allocate("worker", 0, Resources(memory_bytes=1 << 30, vcores=1))
            rec = rm1.journal_info(c)
            assert rec is not None and rec["agent_host"] == "127.0.0.1"
            # the successor AM process: same app id, fresh client state
            rm2 = RemoteResourceManager(host, port, app_id="app_adopt")
            c2 = rm2.adopt_container(rec)
            assert c2 is not None and c2.id == c.id
            # the pool still holds the allocation; release flows through it
            rm2.release(c2)
            assert c.id not in svc._containers
            rm1.rm.close()
            rm2.shutdown()
        finally:
            svc.stop()


def _alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


# ---------------------------------------------------------------------------
# grammar: the control-plane fault kinds
# ---------------------------------------------------------------------------
class TestControlPlaneGrammar:
    def test_am_crash_and_pool_crash_parse(self):
        s = FaultSchedule.parse("am-crash@step+3;pool-crash@t+2s")
        assert [f.kind for f in s.faults] == ["am-crash", "pool-crash"]
        assert s.faults[0].step_gate == 3
        assert s.faults[1].delay_ms == 2000

    def test_step_gate_rejected_for_pool_crash(self):
        # pool-crash is decided in the pool service, which never sees steps
        with pytest.raises(ValueError, match="AM-decided"):
            FaultSchedule.parse("pool-crash@step+3")


# ---------------------------------------------------------------------------
# pool recovery: journal + agent re-registration (satellite: agent.py:154)
# ---------------------------------------------------------------------------
class TestPoolRecovery:
    def _pool(self, tmp_path, **kw):
        return PoolService(journal_path=str(tmp_path / "pool.jsonl"), **kw)

    def test_restart_preserves_queue_state_and_containers(self, tmp_path):
        svc = self._pool(tmp_path, queues={"prod": 0.5, "dev": 0.5})
        svc.register_node(name="n0", host="h", port=1,
                          memory_bytes=4 << 30, vcores=8, live=[])
        svc.register_app(app_id="running", queue="prod",
                         memory_bytes=3 << 30, vcores=2)
        got = svc.allocate("running", "worker", 0, 3 << 30, 2, 0)
        cid = got["id"]
        # a second app that cannot fit yet: admitted=False, waiting in dev
        svc.register_app(app_id="waiting", queue="dev",
                         memory_bytes=3 << 30, vcores=2)
        assert svc._apps["running"].admitted and not svc._apps["waiting"].admitted
        svc.stop()

        svc2 = self._pool(tmp_path, queues={"prod": 0.5, "dev": 0.5})
        try:
            # admitted app stays admitted (not re-admitted from scratch — its
            # claim survives), waiting app is still waiting, seq order kept
            assert svc2._apps["running"].admitted
            assert not svc2._apps["waiting"].admitted
            assert svc2._apps["running"].seq < svc2._apps["waiting"].seq
            assert svc2._containers[cid]["state"] == "RUNNING"
            # agent re-registration re-adopts the live container: accounting
            # restored, nothing in the kill list, and the running app is NOT
            # evicted by the post-registration scheduling pass
            resp = svc2.register_node(name="n0", host="h", port=1,
                                      memory_bytes=4 << 30, vcores=8, live=[cid])
            assert resp["kill"] == []
            assert svc2._nodes["n0"].used_memory == 3 << 30
            assert svc2._apps["running"].admitted and not svc2._apps["running"].preempted
            # a second allocate for the admitted app is NOT double-admitted —
            # it simply keeps allocating under its surviving claim
            assert svc2._held_locked("running") == (3 << 30, 2, 0)
        finally:
            svc2.stop()

    def test_journal_less_pool_kills_unknown_containers(self, tmp_path):
        svc = PoolService()  # no journal: recognizes nothing after "restart"
        try:
            resp = svc.register_node(name="n0", host="h", port=1,
                                     memory_bytes=4 << 30, vcores=8,
                                     live=["container_orphan"])
            assert resp["kill"] == ["container_orphan"]
        finally:
            svc.stop()

    def test_agent_restart_writes_off_its_dead_containers(self, tmp_path):
        svc = self._pool(tmp_path)
        try:
            svc.register_node(name="n0", host="h", port=1,
                              memory_bytes=4 << 30, vcores=8, live=[])
            got = svc.allocate("app", "worker", 0, 1 << 30, 1, 0)
            cid = got["id"]
            # agent process restarted: it re-registers with an EMPTY live
            # list — the container it was running died with it
            resp = svc.register_node(name="n0", host="h", port=1,
                                     memory_bytes=4 << 30, vcores=8, live=[])
            assert resp["kill"] == []
            assert svc.poll_exited("app") == {cid: constants.EXIT_NODE_LOST}
            assert svc._nodes["n0"].used_memory == 0
        finally:
            svc.stop()

    def test_unlaunched_container_survives_recovery_reconcile(self, tmp_path):
        # allocated-but-not-yet-launched (never seen live by any agent): a
        # post-recovery agent registration must NOT write it off — the AM may
        # still launch it
        svc = self._pool(tmp_path)
        svc.register_node(name="n0", host="h", port=1,
                          memory_bytes=4 << 30, vcores=8, live=[])
        got = svc.allocate("app", "worker", 0, 1 << 30, 1, 0)
        cid = got["id"]
        svc.stop()
        svc2 = self._pool(tmp_path)
        try:
            resp = svc2.register_node(name="n0", host="h", port=1,
                                      memory_bytes=4 << 30, vcores=8, live=[])
            assert resp["kill"] == []
            assert svc2._containers[cid]["state"] == "RUNNING"
            assert svc2.poll_exited("app") == {}
            # its claim is re-accounted even though no agent reported it live
            # yet — allocate() must not double-book the unlaunched container
            assert svc2._nodes["n0"].used_memory == 1 << 30
        finally:
            svc2.stop()

    def test_failed_recovery_starts_empty_not_half_replayed(self, tmp_path):
        jp = tmp_path / "pool.jsonl"
        # a valid app record followed by mid-file garbage: adopting the half-
        # replayed state would be fiction — the pool must start EMPTY (and
        # must start: no exception escapes __init__)
        jp.write_text(
            '{"t": "app", "app_id": "a", "queue": "default", "seq": 0, '
            '"admitted": true, "priority": 0, "preempted": false, '
            '"demand_memory": 1, "demand_vcores": 1, "demand_chips": 0}\n'
            "}{ garbage\n"
            '{"t": "app_removed", "app_id": "a"}\n'
        )
        svc = PoolService(journal_path=str(jp))
        try:
            assert svc._apps == {} and svc._containers == {}
        finally:
            svc.stop()
        # same for a structurally-broken record (KeyError, not JournalError)
        jp2 = tmp_path / "pool2.jsonl"
        jp2.write_text('{"t": "app", "app_id": "a"}\n')  # no queue/seq
        svc2 = PoolService(journal_path=str(jp2))
        try:
            assert svc2._apps == {}
        finally:
            svc2.stop()

    def test_backstop_kill_survives_pool_restart(self, tmp_path):
        svc = self._pool(tmp_path)
        svc.register_node(name="n0", host="h", port=1,
                          memory_bytes=4 << 30, vcores=8, live=[])
        got = svc.allocate("app", "worker", 0, 1 << 30, 1, 0)
        cid = got["id"]
        svc.node_heartbeat("n0", exited={}, live=[cid])  # seen live
        svc.stop()
        svc2 = self._pool(tmp_path)
        try:
            # the AM's backstop kill lands while the node is still away:
            # the order must not be dropped — with work-preserving
            # re-adoption nothing else would ever terminate this container
            svc2.request_kill(cid)
            resp = svc2.register_node(name="n0", host="h", port=1,
                                      memory_bytes=4 << 30, vcores=8, live=[cid])
            assert cid in resp["kill"]
            # the claim stays accounted until the agent reports the exit
            assert svc2._nodes["n0"].used_memory == 1 << 30
        finally:
            svc2.stop()

    def test_undelivered_exits_survive_restart_once(self, tmp_path):
        svc = self._pool(tmp_path)
        svc.register_node(name="n0", host="h", port=1,
                          memory_bytes=4 << 30, vcores=8, live=[])
        a = svc.allocate("app", "worker", 0, 1 << 30, 1, 0)["id"]
        b = svc.allocate("app", "worker", 1, 1 << 30, 1, 0)["id"]
        svc.node_heartbeat("n0", exited={a: 1}, live=[b])
        # exit recorded but NOT polled before the crash → re-delivered after
        svc.stop()
        svc2 = self._pool(tmp_path)
        assert svc2.poll_exited("app") == {a: 1}
        svc2.stop()
        # ... and the poll was journaled: a second restart re-delivers nothing
        svc3 = self._pool(tmp_path)
        try:
            assert svc3.poll_exited("app") == {}
        finally:
            svc3.stop()

    @pytest.mark.e2e
    def test_pool_crash_fault_sigkills_the_daemon(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        info = str(tmp_path / "pool_info.json")
        proc = subprocess.Popen(
            [sys.executable, "-m", "tony_tpu.cluster.pool",
             "--port", "0", "--info-file", info,
             "--journal-file", str(tmp_path / "pool.jsonl"),
             "--conf", "tony.chaos.spec=pool-crash",
             "--heartbeat-ms", "100"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        try:
            rc = proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert rc == -signal.SIGKILL  # fidelity: abrupt death, no drain


# ---------------------------------------------------------------------------
# satellite: no bare json.dump to a final path in cluster/ — a SIGKILL
# (am-crash / pool-crash) mid-write must never truncate a status/control
# file another process reads
# ---------------------------------------------------------------------------
class TestAtomicWriteDiscipline:
    def test_every_cluster_json_dump_goes_through_a_tmp_file(self):
        import tony_tpu.cluster as cluster_pkg

        root = os.path.dirname(os.path.abspath(cluster_pkg.__file__))
        offenders = []
        for fn in sorted(os.listdir(root)):
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn)) as f:
                lines = f.read().splitlines()
            for i, line in enumerate(lines):
                if not re.search(r"\bjson\.dump\(", line):
                    continue  # json.dumps (string form) is fine anywhere
                window = "\n".join(lines[max(0, i - 6): i + 1])
                if "tmp" not in window:
                    offenders.append(f"{fn}:{i + 1}")
        assert not offenders, (
            "bare json.dump to a final path (no write-tmp-then-os.replace "
            f"within 6 lines) in cluster/: {offenders}"
        )


# ---------------------------------------------------------------------------
# headline e2e: AM SIGKILLed mid-run → adopted, zero restarts, SUCCEEDED
# ---------------------------------------------------------------------------
TAKEOVER_CONF = {
    keys.AM_RETRY_COUNT: "2",
    keys.TASK_METRICS_INTERVAL_MS: "100",   # fast step feed for @step+N
    keys.TASK_MAX_MISSED_HEARTBEATS: "60",  # 6 s outage budget at 100 ms beats
}


@pytest.mark.e2e
class TestTakeoverE2E:
    def test_am_sigkill_mid_run_adopts_gang_zero_restarts(
        self, tmp_tony_root, tmp_path, capsys
    ):
        from tony_tpu.cli.chaos import main as chaos_main

        out_dir = tmp_path / "steps"
        steps = 20
        rc = chaos_main([
            "--spec", "am-crash@step+3",
            "--seed", "7",
            "--executes", f"{fixture_cmd('takeover_train.py')} {steps} {out_dir}",
            "--workers", "2",
            "--expect-takeover",
            "--conf", f"{keys.STAGING_ROOT}={tmp_tony_root}",
        ] + [f"--conf={k}={v}" for k, v in {**FAST, **TAKEOVER_CONF}.items()])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "invariants: OK" in out
        assert "AM takeovers: 1 adopted" in out
        assert "gang epochs: 1" in out  # ZERO executor restarts

        # per-worker: the child never restarted and the step counter is
        # strictly monotonic across the takeover (no regression, no replay)
        for idx in ("0", "1"):
            lines = (out_dir / f"steps-{idx}.log").read_text().splitlines()
            starts = [ln for ln in lines if ln.startswith("start ")]
            assert len(starts) == 1 and "attempt=0" in starts[0], lines
            seen = [int(ln.split()[1]) for ln in lines if ln.startswith("step ")]
            assert seen == list(range(1, steps + 1)), seen

        (app_dir,) = [d for d in os.listdir(tmp_tony_root)
                      if d.startswith("application_")]
        with open(os.path.join(tmp_tony_root, app_dir, "am_status.json")) as f:
            status = json.load(f)
        assert status["status"] == "SUCCEEDED"
        assert status["am_attempt"] == 1 and status["takeover"] == "adopted"
        assert status["restart_attempt"] == 0
        # the takeover is on the event stream exactly once, never degraded
        evs = [e.type.value for e in history.read_events(
            os.path.join(str(tmp_tony_root), "history"), app_dir)]
        assert evs.count("AM_TAKEOVER") == 1
        assert "AM_TAKEOVER_DEGRADED" not in evs
        assert "TASK_RESYNCED" in evs

    def test_corrupt_journal_degrades_to_full_gang_restart(
        self, tmp_tony_root, tmp_path
    ):
        from tony_tpu.cli.chaos import _find_orphans
        from tony_tpu.cluster.client import Client

        out_dir = tmp_path / "steps"
        cfg = TonyConfig({
            **FAST, **TAKEOVER_CONF,
            keys.STAGING_ROOT: str(tmp_tony_root),
            "tony.worker.instances": "1",
            keys.EXECUTES: f"{fixture_cmd('takeover_train.py')} 10 {out_dir}",
            keys.CHAOS_SPEC: "am-crash@step+2",
            keys.CHAOS_SEED: "3",
        })
        client = Client(cfg)
        handle = client.submit()
        assert handle.am_process.wait(timeout=90) == -signal.SIGKILL
        # corrupt the journal MID-FILE (not a torn tail): takeover must
        # refuse to adopt fiction and degrade loudly
        jpath = os.path.join(handle.staging_dir, constants.AM_JOURNAL_FILE)
        lines = open(jpath).read().splitlines()
        assert len(lines) >= 2, lines
        with open(jpath, "w") as f:
            f.write(lines[0] + "\n}{corrupt\n" + "\n".join(lines[1:]) + "\n")

        final = client.monitor_application(handle, quiet=True)
        assert final == JobStatus.SUCCEEDED, handle.final_status()
        evs = [e.type.value for e in history.read_events(
            os.path.join(str(tmp_tony_root), "history"), handle.app_id)]
        assert "AM_TAKEOVER_DEGRADED" in evs
        assert "AM_TAKEOVER" not in evs
        # full gang restart: the worker ran twice (and only twice)
        lines = (out_dir / "steps-0.log").read_text().splitlines()
        starts = [ln for ln in lines if ln.startswith("start ")]
        assert len(starts) == 2, lines
        # and the degraded path leaked no orphans
        assert not _find_orphans(handle.app_id)
        status = handle.final_status()
        assert status["am_attempt"] == 1 and status["takeover"] == "degraded"
