"""Control-plane RPC tests (framed JSON over TCP; rpc/ package analog)."""

import threading

import pytest

from tony_tpu.cluster.rpc import RpcClient, RpcError, RpcServer


@pytest.fixture()
def server():
    srv = RpcServer(secret="s3cret")
    srv.register("echo", lambda **kw: kw)
    srv.register("boom", lambda: 1 / 0)
    srv.start()
    yield srv
    srv.stop()


def client_for(server, secret="s3cret"):
    host, port = server.address
    return RpcClient(host, port, secret=secret)


class TestRpc:
    def test_echo_roundtrip(self, server):
        c = client_for(server)
        assert c.call("echo", a=1, b=[1, 2], c={"x": "y"}) == {"a": 1, "b": [1, 2], "c": {"x": "y"}}

    def test_remote_exception_surfaces(self, server):
        with pytest.raises(RpcError, match="ZeroDivisionError"):
            client_for(server).call("boom")

    def test_unknown_method(self, server):
        with pytest.raises(RpcError, match="unknown method"):
            client_for(server).call("nope")

    def test_bad_auth_rejected(self, server):
        with pytest.raises(RpcError, match="authentication"):
            client_for(server, secret="wrong").call("echo", a=1)

    def test_many_sequential_calls_one_connection(self, server):
        c = client_for(server)
        for i in range(100):
            assert c.call("echo", i=i) == {"i": i}

    def test_concurrent_clients(self, server):
        errors = []

        def worker(n):
            try:
                c = client_for(server)
                for i in range(20):
                    assert c.call("echo", n=n, i=i) == {"n": n, "i": i}
                c.close()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(n,)) for n in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors

    def test_reconnect_after_server_side_drop(self, server):
        c = client_for(server)
        assert c.call("echo", a=1) == {"a": 1}
        c._sock.close()  # simulate a dropped connection
        assert c.call("echo", a=2) == {"a": 2}  # transparent reconnect

    def test_call_with_retry_eventually_connects(self):
        srv = RpcServer(secret="")
        srv.register("ping", lambda: "pong")
        host, port = srv.address
        c = RpcClient(host, port)
        t = threading.Timer(0.3, srv.start)
        t.start()
        try:
            assert c.call_with_retry("ping", retries=30, delay_s=0.05) == "pong"
        finally:
            t.join()
            srv.stop()
