"""Tests for the opt-in traced-lock witness (tony_tpu/obs/locktrace.py).

Two layers: unit tests for the wrapper contract (off-mode zero-overhead
plain locks, edge/contention recording, no self-edges on reentrant
re-acquire, the hold-time histogram), and the tier-1 cross-check — drive
real PoolService / HistoryStore workloads under tracing and assert every
witnessed acquisition-order edge embeds into the static lock-order graph
the lint builds. A runtime inversion the static model missed fails here.
"""

import os
import threading
import time

import pytest

from tony_tpu.analysis.lock_order import build_lock_graph
from tony_tpu.obs import locktrace, metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def traced():
    """Tracing on for locks created inside the test, witness state clean."""
    locktrace.set_enabled(True)
    locktrace.reset_witness()
    yield
    locktrace.set_enabled(False)
    locktrace.reset_witness()


# ------------------------------------------------------------------ off mode
def test_off_mode_returns_plain_stdlib_locks():
    """The zero-overhead contract: tracing off, make_lock IS the stdlib
    primitive — no wrapper, no recording, byte-identical behavior."""
    locktrace.set_enabled(False)
    assert type(locktrace.make_lock("x")) is type(threading.Lock())
    assert type(locktrace.make_lock("x", reentrant=True)) is type(threading.RLock())
    with locktrace.make_lock("x"):
        pass
    assert locktrace.witness()["acquires"] == {}


# ------------------------------------------------------------------- on mode
def test_traced_lock_records_order_edges(traced):
    a = locktrace.make_lock("t.A")
    b = locktrace.make_lock("t.B")
    assert isinstance(a, locktrace._TracedLock)
    with a:
        with b:
            pass
    w = locktrace.witness()
    assert w["edges"] == {("t.A", "t.B"): 1}
    assert w["acquires"] == {"t.A": 1, "t.B": 1}
    assert w["contended"] == {}


def test_reentrant_reacquire_is_not_an_edge(traced):
    r = locktrace.make_lock("t.R", reentrant=True)
    with r:
        with r:  # same lock, same thread: RLock semantics, no self-edge
            pass
    w = locktrace.witness()
    assert w["edges"] == {}
    assert w["acquires"] == {"t.R": 2}


def test_contention_is_counted(traced):
    a = locktrace.make_lock("t.C")
    a.acquire()
    entered = threading.Event()

    def grab():
        entered.set()
        with a:
            pass

    t = threading.Thread(target=grab)
    t.start()
    entered.wait()
    time.sleep(0.05)  # let the thread hit the taken lock
    a.release()
    t.join()
    assert locktrace.witness()["contended"].get("t.C", 0) >= 1


def test_nonblocking_acquire_contract(traced):
    a = locktrace.make_lock("t.N")
    assert a.acquire() is True
    got: list[bool] = []
    t = threading.Thread(target=lambda: got.append(a.acquire(blocking=False)))
    t.start()
    t.join()
    assert got == [False]
    assert a.locked()
    a.release()
    assert not a.locked()
    # the failed non-blocking attempt must not have left a phantom acquire
    assert locktrace.witness()["acquires"] == {"t.N": 1}


def test_hold_time_histogram_observes(traced):
    h = locktrace.make_lock("t.H")
    with h:
        time.sleep(0.01)
    for entry in metrics.REGISTRY.snapshot():
        if entry["name"] == "tony_lock_hold_seconds":
            samples = [s for s in entry["samples"]
                       if s["labels"] == {"lock": "t.H"}]
            assert samples and samples[0]["count"] >= 1
            assert samples[0]["sum"] >= 0.01
            break
    else:
        pytest.fail("tony_lock_hold_seconds not registered")


# --------------------------------------------- tier-1 witness-vs-static check
def test_witnessed_order_embeds_into_static_graph(traced, tmp_path):
    """Drive representative pool + history-store workloads under tracing;
    every runtime (held -> acquired) edge must be ordered the same way by
    the static lock graph. A witnessed edge the lint's model cannot path
    is a modeling gap or a real inversion — either fails the build."""
    from tony_tpu.cluster.pool import PoolService
    from tony_tpu.histserver.store import HistoryStore

    svc = PoolService(
        heartbeat_interval_ms=50, max_missed_heartbeats=3,
        journal_path=str(tmp_path / "pool.journal"), journal_compact_every=4,
    )
    try:
        svc.register_node(name="n0", host="127.0.0.1", port=1,
                          memory_bytes=8 * 1024**3, vcores=8)
        svc.register_app("app", memory_bytes=1024**3, vcores=1)
        got = svc.allocate("app", "worker", 0, 1024**3, 1, 0)
        assert got.get("node") == "n0"
        svc.node_heartbeat(name="n0", exited={})
        svc.poll_exited("app")
        svc.release_all("app")
    finally:
        svc.stop()

    store = HistoryStore(str(tmp_path / "hist.sqlite"))
    store.put_job(
        {"app_id": "app", "status": "SUCCEEDED"},
        series={"goodput": [(1, 0.5), (2, 0.9)]},
    )
    store.close()

    w = locktrace.witness()
    assert w["acquires"], "workload acquired no traced locks — wiring broke"
    static = build_lock_graph([os.path.join(REPO, "tony_tpu")])
    assert static.cycles == []
    violations = [
        (held, acq) for (held, acq) in w["edges"]
        if not static.has_path(held, acq)
    ]
    assert violations == [], (
        f"witnessed lock edges outside the static order graph: {violations}\n"
        f"static:\n{static.render()}"
    )
