"""Workflow-jobtype integration (tony-azkaban analog, SURVEY.md §2.3)."""

import os
import sys

import pytest

from tony_tpu.config import keys
from tony_tpu.cluster.session import JobStatus
from tony_tpu.integrations import TonyWorkflowJob

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


class TestPropertyMerge:
    def test_shorthands_map_to_tony_keys(self):
        job = TonyWorkflowJob("step1", {
            "executes": "python train.py",
            "src_dir": "/src",
            "queue": "ml",
        })
        cfg = job.build_config()
        assert cfg.get(keys.EXECUTES) == "python train.py"
        assert cfg.get(keys.SRC_DIR) == "/src"
        assert cfg.get(keys.APPLICATION_QUEUE) == "ml"

    def test_explicit_tony_props_win_over_shorthands(self):
        job = TonyWorkflowJob("step1", {
            "executes": "shorthand-cmd",
            keys.EXECUTES: "explicit-cmd",
        })
        assert job.build_config().get(keys.EXECUTES) == "explicit-cmd"

    def test_passthrough_of_arbitrary_tony_keys(self):
        job = TonyWorkflowJob("s", {"tony.worker.instances": "4"})
        assert job.build_config().instances("worker") == 4

    def test_job_name_becomes_application_name(self):
        assert (
            TonyWorkflowJob("nightly-train", {}).build_config().get(keys.APPLICATION_NAME)
            == "nightly-train"
        )
        assert (
            TonyWorkflowJob("s", {keys.APPLICATION_NAME: "explicit"})
            .build_config()
            .get(keys.APPLICATION_NAME)
            == "explicit"
        )


@pytest.mark.e2e
class TestWorkflowE2E:
    def test_workflow_step_runs_job_and_reports_exit_code(self, tmp_tony_root):
        props = {
            "tony.worker.instances": "1",
            "executes": f"{sys.executable} {os.path.join(FIXTURES, 'exit_0.py')}",
            "staging_root": str(tmp_tony_root),
            keys.AM_MONITOR_INTERVAL_MS: "50",
        }
        assert TonyWorkflowJob("wf-ok", props).run() == 0
        props["executes"] = f"{sys.executable} {os.path.join(FIXTURES, 'exit_1.py')}"
        assert TonyWorkflowJob("wf-fail", props).run() != 0
