"""History portal tests (tony-portal analog, SURVEY.md §2.3)."""

import json
import threading
import urllib.request

import pytest

from tony_tpu.cluster.events import EventHandler, EventType
from tony_tpu.cluster.history import finalize_history
from tony_tpu.portal.server import serve


@pytest.fixture()
def portal(tmp_path):
    # one finished job in history
    eh = EventHandler(str(tmp_path), "app_x")
    eh.start()
    eh.emit(EventType.APPLICATION_INITED, app_id="app_x")
    eh.emit(
        EventType.APPLICATION_FINISHED,
        status="SUCCEEDED",
        tasks=[{"name": "worker", "index": 0, "status": "SUCCEEDED", "exit_code": 0, "host": "h"}],
    )
    eh.stop()
    finalize_history(
        str(tmp_path), "app_x", eh.intermediate_path, 100, 200, "SUCCEEDED",
        config_snapshot={"tony.worker.instances": "1"}, user="t",
    )
    server = serve(str(tmp_path), 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()


def get(url):
    with urllib.request.urlopen(url) as r:
        return r.status, r.read().decode()


class TestPortal:
    def test_job_list(self, portal):
        status, body = get(portal + "/")
        assert status == 200
        assert "app_x" in body and "SUCCEEDED" in body

    def test_job_detail(self, portal):
        _, body = get(portal + "/job/app_x")
        assert "APPLICATION_INITED" in body and "worker:0" in body

    def test_config_view(self, portal):
        _, body = get(portal + "/job/app_x/config")
        assert "tony.worker.instances" in body

    def test_api_jobs(self, portal):
        _, body = get(portal + "/api/jobs")
        jobs = json.loads(body)
        assert jobs[0]["app_id"] == "app_x"

    def test_404(self, portal):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError):
            get(portal + "/nope")


class TestLivePortal:
    """r3 live view: running jobs from intermediate .jhist, AM RPC task
    table, METRICS_SNAPSHOT sparklines, pool status page."""

    def _mk_running(self, tmp_path, app_id="app_live"):
        eh = EventHandler(str(tmp_path), app_id)
        eh.start()
        eh.emit(EventType.APPLICATION_INITED, app_id=app_id)
        for step in range(3):
            eh.emit(
                EventType.METRICS_SNAPSHOT,
                tasks=[{
                    "task": "worker:0",
                    "metrics": {"train": {
                        "loss": 3.0 - step, "tokens_per_sec": 1000.0 + step,
                        "mfu": 0.4 + 0.01 * step,
                    }},
                }],
            )
        eh.stop()  # file stays in intermediate/ (no finalize) → RUNNING

    def test_running_section_and_charts(self, tmp_path):
        self._mk_running(tmp_path)
        server = serve(str(tmp_path), 0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            _, body = get(base + "/")
            assert "running" in body and "app_live" in body
            _, detail = get(base + "/job/app_live")
            assert "LIVE" in detail
            assert "<svg" in detail and "tokens_per_sec" in detail  # sparklines
            _, api = get(base + "/api/jobs")
            assert any(j["app_id"] == "app_live" and j["status"] == "RUNNING"
                       for j in json.loads(api))
        finally:
            server.shutdown()

    def test_live_task_table_via_am_rpc(self, tmp_path):
        import os

        from tony_tpu import constants
        from tony_tpu.cluster.rpc import RpcServer

        self._mk_running(tmp_path, "app_rpc")

        class FakeAM:
            def get_application_status(self):
                return {"state": "RUNNING", "restart_attempt": 0}

            def get_task_infos(self):
                return [{
                    "name": "worker", "index": 0, "status": "RUNNING",
                    "host": "h1", "metrics": {"train": {"loss": 1.5}},
                }]

        rpc = RpcServer(port=0, secret="s3")
        rpc.register_object(FakeAM(), ["get_application_status", "get_task_infos"])
        rpc.start()
        host, port = rpc.address
        staging = tmp_path / "app_rpc"
        staging.mkdir()
        (staging / constants.AM_INFO_FILE).write_text(
            json.dumps({"host": host, "port": port, "secret": "s3"})
        )
        server = serve(str(tmp_path), 0, staging_root=str(tmp_path))
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            _, detail = get(base + "/job/app_rpc")
            assert "AM state: RUNNING" in detail
            assert "worker:0" in detail and "h1" in detail
        finally:
            server.shutdown()
            rpc.stop()

    def test_pool_page(self, tmp_path, monkeypatch):
        from tony_tpu import constants
        from tony_tpu.cluster.pool import PoolService

        svc = PoolService(port=0, secret="psec")
        svc.start()
        host, port = svc.address
        monkeypatch.setenv(constants.ENV_POOL_SECRET, "psec")
        server = serve(str(tmp_path), 0, pool=f"{host}:{port}")
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            _, body = get(base + "/pool")
            assert "containers running" in body
            _, api = get(base + "/api/pool")
            assert "nodes" in json.loads(api)
        finally:
            server.shutdown()
            svc.stop()
