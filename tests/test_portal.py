"""History portal tests (tony-portal analog, SURVEY.md §2.3)."""

import json
import threading
import urllib.request

import pytest

from tony_tpu.cluster.events import EventHandler, EventType
from tony_tpu.cluster.history import finalize_history
from tony_tpu.portal.server import serve


@pytest.fixture()
def portal(tmp_path):
    # one finished job in history
    eh = EventHandler(str(tmp_path), "app_x")
    eh.start()
    eh.emit(EventType.APPLICATION_INITED, app_id="app_x")
    eh.emit(
        EventType.APPLICATION_FINISHED,
        status="SUCCEEDED",
        tasks=[{"name": "worker", "index": 0, "status": "SUCCEEDED", "exit_code": 0, "host": "h"}],
    )
    eh.stop()
    finalize_history(
        str(tmp_path), "app_x", eh.intermediate_path, 100, 200, "SUCCEEDED",
        config_snapshot={"tony.worker.instances": "1"}, user="t",
    )
    server = serve(str(tmp_path), 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()


def get(url):
    with urllib.request.urlopen(url) as r:
        return r.status, r.read().decode()


class TestPortal:
    def test_job_list(self, portal):
        status, body = get(portal + "/")
        assert status == 200
        assert "app_x" in body and "SUCCEEDED" in body

    def test_job_detail(self, portal):
        _, body = get(portal + "/job/app_x")
        assert "APPLICATION_INITED" in body and "worker:0" in body

    def test_config_view(self, portal):
        _, body = get(portal + "/job/app_x/config")
        assert "tony.worker.instances" in body

    def test_api_jobs(self, portal):
        _, body = get(portal + "/api/jobs")
        jobs = json.loads(body)
        assert jobs[0]["app_id"] == "app_x"

    def test_404(self, portal):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError):
            get(portal + "/nope")
