"""Runtime adapter env-contract tests (SURVEY.md §2.2 parity)."""

import json

import pytest

from tony_tpu.config import TonyConfig, keys
from tony_tpu.runtime import Framework, get_runtime
from tony_tpu.runtime.jax_runtime import canonical_task_order, coordinator_address, global_rank

SPEC = {
    "ps": ["h1:10", "h2:20"],
    "worker": ["h3:30", "h3:31", "h4:40"],
}
CHIEF_SPEC = {"chief": ["c:1"], "worker": ["w:2"]}


def runtime_for(framework: str, extra: dict | None = None):
    cfg = TonyConfig({keys.APPLICATION_FRAMEWORK: framework, **(extra or {})})
    return get_runtime(cfg)


class TestFactory:
    @pytest.mark.parametrize("name", ["jax", "tensorflow", "pytorch", "horovod", "mxnet", "generic"])
    def test_selects(self, name):
        assert runtime_for(name) is not None

    def test_unknown_framework_raises(self):
        cfg = TonyConfig({keys.APPLICATION_FRAMEWORK: "caffe"})
        with pytest.raises(ValueError, match="unknown"):
            Framework.from_config(cfg)


class TestCanonicalOrder:
    def test_chief_first(self):
        assert canonical_task_order(CHIEF_SPEC)[0] == ("chief", 0)
        assert coordinator_address(CHIEF_SPEC) == "c:1"

    def test_rank_stable(self):
        order = canonical_task_order(SPEC)
        assert order == [("ps", 0), ("ps", 1), ("worker", 0), ("worker", 1), ("worker", 2)]
        assert global_rank(SPEC, "worker", 2) == 4


class TestBaseContract:
    def test_generic_env(self):
        env = runtime_for("generic").executor_env(SPEC, "worker", 1)
        assert env["JOB_NAME"] == "worker"
        assert env["TASK_INDEX"] == "1"
        assert env["TASK_NUM"] == "3"
        assert env["DISTRIBUTED_MODE"] == "GANG"
        assert json.loads(env["CLUSTER_SPEC"]) == SPEC

    def test_single_node_mode(self):
        env = runtime_for("generic").executor_env({"worker": ["h:1"]}, "worker", 0)
        assert env["DISTRIBUTED_MODE"] == "SINGLE_NODE"


class TestTFRuntime:
    def test_tf_config_shape(self):
        env = runtime_for("tensorflow").executor_env(SPEC, "worker", 1)
        tf = json.loads(env["TF_CONFIG"])
        assert tf["cluster"] == SPEC
        assert tf["task"] == {"type": "worker", "index": 1}

    def test_tensorboard_excluded_from_cluster(self):
        spec = dict(SPEC, tensorboard=["tb:99"])
        tf = json.loads(runtime_for("tensorflow").executor_env(spec, "worker", 0)["TF_CONFIG"])
        assert "tensorboard" not in tf["cluster"]


class TestTorchRuntime:
    def test_rendezvous_env(self):
        # ps is untracked by default → not a torch.distributed member
        env = runtime_for("pytorch").executor_env(SPEC, "worker", 1)
        assert env["MASTER_ADDR"] == "h3"
        assert env["MASTER_PORT"] == "30"
        assert env["RANK"] == "1"
        assert env["WORLD_SIZE"] == "3"
        assert env["INIT_METHOD"] == "tcp://h3:30"

    def test_ps_worker_topology_when_tracked(self):
        # a config that tracks ps (clears the untracked list) ranks ps first
        env = runtime_for(
            "pytorch", {keys.APPLICATION_UNTRACKED_TYPES: ""}
        ).executor_env(SPEC, "worker", 1)
        assert env["MASTER_ADDR"] == "h1"
        assert env["RANK"] == "3"
        assert env["WORLD_SIZE"] == "5"


class TestJaxRuntime:
    def test_coordinator_contract(self):
        # ps is untracked by default → excluded from the jax process group;
        # the first worker is the coordinator
        env = runtime_for("jax").executor_env(SPEC, "worker", 1)
        assert env["JAX_COORDINATOR_ADDRESS"] == "h3:30"
        assert env["JAX_PROCESS_ID"] == "1"
        assert env["JAX_NUM_PROCESSES"] == "3"

    def test_sidecar_gets_no_process_group(self):
        env = runtime_for("jax").executor_env(SPEC, "ps", 0)
        assert "JAX_COORDINATOR_ADDRESS" not in env
        assert "JAX_PROCESS_ID" not in env

    def test_tensorboard_never_coordinator(self):
        spec = {"tensorboard": ["a:1"], "worker": ["w:2", "w:3"]}
        env = runtime_for("jax").executor_env(spec, "worker", 0)
        assert env["JAX_COORDINATOR_ADDRESS"] == "w:2"
        assert env["JAX_NUM_PROCESSES"] == "2"


class TestHorovodRuntime:
    def test_slot_plan(self):
        from tony_tpu.cluster.session import Session

        cfg = TonyConfig(
            {
                keys.APPLICATION_FRAMEWORK: "horovod",
                "tony.worker.instances": "3",
            }
        )
        rt = get_runtime(cfg)
        session = Session(cfg)
        # two tasks share h3 → local ranks 0/1; h4 is cross-rank 1
        session.register_worker_spec("worker", 0, "h3", 30)
        session.register_worker_spec("worker", 1, "h3", 31)
        session.register_worker_spec("worker", 2, "h4", 40)
        rt.on_gang_complete(session)

        e0 = rt.am_extra_env(session, "worker", 0)
        e1 = rt.am_extra_env(session, "worker", 1)
        e2 = rt.am_extra_env(session, "worker", 2)
        assert (e0["HOROVOD_RANK"], e1["HOROVOD_RANK"], e2["HOROVOD_RANK"]) == ("0", "1", "2")
        assert (e0["HOROVOD_LOCAL_RANK"], e1["HOROVOD_LOCAL_RANK"]) == ("0", "1")
        assert e0["HOROVOD_LOCAL_SIZE"] == "2"
        assert e2["HOROVOD_CROSS_RANK"] == "1"
        assert e0["HOROVOD_SIZE"] == "3"
        assert e0["HOROVOD_GLOO_RENDEZVOUS_ADDR"] == "h3"


class TestMXNetRuntime:
    def test_dmlc_env(self):
        env = runtime_for("mxnet").executor_env(SPEC, "ps", 1)
        assert env["DMLC_ROLE"] == "server"
        assert env["DMLC_PS_ROOT_URI"] == "h1"
        assert env["DMLC_NUM_SERVER"] == "2"
        assert env["DMLC_NUM_WORKER"] == "3"
        assert runtime_for("mxnet").executor_env(SPEC, "worker", 0)["DMLC_ROLE"] == "worker"


class TestCheckpointEnvContract:
    def test_checkpoint_keys_reach_executor_env(self):
        rt = runtime_for("jax", {
            keys.CHECKPOINT_DIR: "/ckpt/run1",
            keys.CHECKPOINT_INTERVAL_STEPS: "50",
        })
        env = rt.executor_env({"worker": ["h:1"]}, "worker", 0)
        from tony_tpu import constants

        assert env[constants.ENV_CHECKPOINT_DIR] == "/ckpt/run1"
        assert env[constants.ENV_CHECKPOINT_INTERVAL] == "50"

    def test_absent_when_unconfigured(self):
        from tony_tpu import constants

        env = runtime_for("jax").executor_env({"worker": ["h:1"]}, "worker", 0)
        assert constants.ENV_CHECKPOINT_DIR not in env

    def test_loop_args_default_from_env(self, monkeypatch):
        from tony_tpu import constants
        from tony_tpu.train.loop import parse_loop_args

        monkeypatch.setenv(constants.ENV_CHECKPOINT_DIR, "/ckpt/fromenv")
        monkeypatch.setenv(constants.ENV_CHECKPOINT_INTERVAL, "25")
        loop, _ = parse_loop_args([])
        assert loop.checkpoint_dir == "/ckpt/fromenv"
        assert loop.checkpoint_every == 25
        # explicit CLI wins over env
        loop2, _ = parse_loop_args(["--checkpoint_dir", "/cli"])
        assert loop2.checkpoint_dir == "/cli"

    def test_interval_injected_without_dir(self):
        from tony_tpu import constants

        rt = runtime_for("jax", {keys.CHECKPOINT_INTERVAL_STEPS: "100"})
        env = rt.executor_env({"worker": ["h:1"]}, "worker", 0)
        assert env[constants.ENV_CHECKPOINT_INTERVAL] == "100"
        assert constants.ENV_CHECKPOINT_DIR not in env

    def test_malformed_interval_rejected_at_validate(self):
        rt = runtime_for("jax", {keys.CHECKPOINT_INTERVAL_STEPS: "1OO"})
        with pytest.raises(ValueError, match="interval-steps"):
            rt.validate()
