"""Cooperative preemption + the provable scheduler (docs/scheduling.md).

Four layers, innermost out:

- the pure :class:`PreemptionPolicy` (cluster/policy.py): shrink-first
  partial reclaim, minimum-runtime protection, per-queue eviction budgets;
- the discrete-event simulator (cluster/sim.py): invariant suites over
  >= 1000 seeded synthetic arrivals per mix, driving the SAME policy class
  the live pool runs (a parity guard greps for re-divergence);
- the live ``PoolService`` drain machinery: two-phase checkpoint-then-yield
  eviction, shrink notices over the ``poll_exited`` piggyback, deadline
  escalation, drain cancellation, and the journal's waiting-age persistence;
- the headline E2E: a prod arrival drains a running dev gang, which
  urgent-checkpoints through the real ``CheckpointManager`` and yields
  inside the deadline — with a kill-path control run proving the drain
  strictly reduced ``restart_rework`` — and an elastic victim sheds a
  worker via shrink instead of dying whole.
"""

import json
import os
import sys
import threading
import time

import pytest

from tony_tpu import constants
from tony_tpu.cluster import policy as pol
from tony_tpu.cluster import sim as simmod
from tony_tpu.cluster.events import Event, EventType
from tony_tpu.cluster.policy import AppView, PreemptionPolicy
from tony_tpu.cluster.pool import PoolService
from tony_tpu.cluster.sim import GB, PoolSimulator, SimJob, run_mix
from tony_tpu.config import keys
from tony_tpu.cluster.session import JobStatus
from tony_tpu.obs import goodput as obs_goodput
from tony_tpu.obs import metrics as obs_metrics

from tests.test_pool import (
    FAST,
    FIXTURES,
    SECRET,
    register_cpu_node,
    spawn_agent,
)
from tests.test_pool_queue import submit_async

pytestmark = pytest.mark.sched

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def counter_value(name: str, **labels) -> float:
    """Current value of one (labeled) counter child in the process registry."""
    for m in obs_metrics.REGISTRY.snapshot():
        if m.get("name") != name:
            continue
        for s in m.get("samples", []):
            if all(s.get("labels", {}).get(k) == v for k, v in labels.items()):
                return float(s.get("value", 0.0))
    return 0.0


# ---------------------------------------------------------------------------
# Pure policy units
# ---------------------------------------------------------------------------
class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _totals(mem_gb=8):
    return (mem_gb * GB, 256, 0)


def make_apps(*specs):
    return [AppView(**s) for s in specs]


class TestPolicyGuards:
    def test_min_runtime_protects_fresh_admittee_from_reclaim(self):
        clock = FakeClock()
        p = PreemptionPolicy({"a": 0.5, "b": 0.5}, preemption=True,
                             min_runtime_ms=5000, clock=clock)
        borrower = AppView("b1", "b", demand=(8 * GB, 1, 0), held=(8 * GB, 1, 0),
                           admitted=True, admitted_at=clock.t - 1.0)
        head = AppView("a1", "a", demand=(2 * GB, 1, 0), wait_since=clock.t - 60)
        d = p.schedule([borrower, head], _totals())
        assert d.empty()  # borrower admitted 1s ago: protected
        clock.t += 10.0
        d = p.schedule([borrower, head], _totals())
        assert d.admit == ["a1"] and [e.app_id for e in d.evict] == ["b1"]

    def test_min_runtime_protects_from_priority_preemption(self):
        clock = FakeClock()
        p = PreemptionPolicy({"q": 1.0}, preemption=True,
                             min_runtime_ms=5000, clock=clock)
        low = AppView("low", "q", priority=0, demand=(8 * GB, 1, 0),
                      held=(8 * GB, 1, 0), admitted=True, admitted_at=clock.t)
        high = AppView("high", "q", priority=9, demand=(8 * GB, 1, 0))
        assert p.schedule([low, high], _totals()).empty()
        clock.t += 6.0
        d = p.schedule([low, high], _totals())
        assert d.admit == ["high"] and [e.app_id for e in d.evict] == ["low"]

    def test_eviction_budget_caps_a_queue_and_refills(self):
        clock = FakeClock()
        p = PreemptionPolicy({"a": 0.5, "b": 0.5}, preemption=True,
                             eviction_budget=1, budget_window_ms=10_000,
                             clock=clock)

        def world():
            return [
                AppView("b1", "b", demand=(4 * GB, 1, 0), held=(4 * GB, 1, 0),
                        admitted=True, seq=0),
                AppView("b2", "b", demand=(4 * GB, 1, 0), held=(4 * GB, 1, 0),
                        admitted=True, seq=1),
                AppView("a1", "a", demand=(2 * GB, 1, 0), seq=2,
                        wait_since=clock.t - 60),
            ]

        d = p.schedule(world(), _totals())
        assert len(d.evict) == 1 and d.admit == ["a1"]  # 1 disruption: allowed
        # the SAME aggressor queue is out of budget now
        d2 = p.schedule(world(), _totals())
        assert d2.empty()
        clock.t += 11.0  # window rolls: budget refills
        d3 = p.schedule(world(), _totals())
        assert len(d3.evict) == 1 and d3.admit == ["a1"]

    def test_grace_defers_reclaim(self):
        clock = FakeClock()
        p = PreemptionPolicy({"a": 0.5, "b": 0.5}, preemption=True,
                             grace_ms=2000, clock=clock)
        borrower = AppView("b1", "b", demand=(8 * GB, 1, 0), held=(8 * GB, 1, 0),
                           admitted=True)
        head = AppView("a1", "a", demand=(2 * GB, 1, 0), wait_since=clock.t - 0.5)
        assert p.schedule([borrower, head], _totals()).empty()
        clock.t += 2.0
        assert not p.schedule([borrower, head], _totals()).empty()


class TestPolicyShrink:
    def world(self, clock, slack=7):
        borrower = AppView(
            "dev1", "dev", demand=(8 * GB, 8, 0), held=(8 * GB, 8, 0),
            admitted=True, elastic_unit=(GB, 1, 0), elastic_slack=slack)
        head = AppView("prod1", "prod", demand=(2 * GB, 1, 0),
                       wait_since=clock.t - 60)
        return [borrower, head]

    def test_shrink_preferred_over_whole_eviction(self):
        clock = FakeClock()
        p = PreemptionPolicy({"prod": 0.6, "dev": 0.4}, preemption=True, clock=clock)
        apps = self.world(clock)
        d = p.schedule(apps, _totals())
        assert d.admit == ["prod1"] and not d.evict
        assert [(s.app_id, s.workers) for s in d.shrink] == [("dev1", 2)]
        dev = apps[0]
        # the view reflects the applied shrink: demand reduced, settled flag
        assert dev.demand[0] == 6 * GB and dev.shrink_pending and dev.elastic_slack == 5

    def test_shrink_never_digs_victim_below_its_share(self):
        """The head needs 6 GB; dev's excess over share is only ~4.8 GB —
        shedding stops at dev's share, and the pure-evict fallback evicts
        whole instead (the app only ran by borrowing)."""
        clock = FakeClock()
        p = PreemptionPolicy({"prod": 0.6, "dev": 0.4}, preemption=True, clock=clock)
        apps = [
            AppView("dev1", "dev", demand=(8 * GB, 8, 0), held=(8 * GB, 8, 0),
                    admitted=True, elastic_unit=(GB, 1, 0), elastic_slack=7),
            AppView("prod1", "prod", demand=(4 * GB, 1, 0), wait_since=clock.t - 60),
        ]
        d = p.schedule(apps, _totals())
        assert d.admit == ["prod1"]
        if d.shrink:
            # shrink alone must not have pushed dev below its 3.2 GB share
            shed = sum(s.workers for s in d.shrink)
            assert 8 * GB - shed * GB >= 0.4 * 8 * GB
        else:
            assert [e.app_id for e in d.evict] == ["dev1"]

    def test_whole_eviction_when_slack_insufficient(self):
        clock = FakeClock()
        p = PreemptionPolicy({"prod": 0.6, "dev": 0.4}, preemption=True, clock=clock)
        apps = self.world(clock, slack=1)  # can shed 1 GB; head needs 2 GB
        d = p.schedule(apps, _totals())
        assert d.admit == ["prod1"]
        assert [e.app_id for e in d.evict] == ["dev1"] and not d.shrink

    def test_shrink_pending_app_is_not_revictimized(self):
        clock = FakeClock()
        p = PreemptionPolicy({"prod": 0.6, "dev": 0.4}, preemption=True, clock=clock)
        apps = self.world(clock)
        apps[0].shrink_pending = True
        d = p.schedule(apps, _totals())
        assert d.empty()  # in-flight shrink: wait for it, no piling on


# ---------------------------------------------------------------------------
# Simulator invariant suites (the tier-1 proof: >= 1000 arrivals per seed)
# ---------------------------------------------------------------------------
class TestSimulatorInvariants:
    @pytest.mark.parametrize("mix,seed", [
        ("batch", 0), ("bursty", 1), ("elastic", 2), ("priority", 3),
    ])
    def test_invariants_over_1000_arrivals(self, mix, seed):
        report = run_mix(mix, 1000, seed=seed)
        assert report.ok(), report.violations[:5]
        assert report.completed == report.jobs == 1000

    def test_budgeted_run_holds_budget_invariant(self):
        report = run_mix("priority", 1000, seed=5, eviction_budget=2,
                         budget_window_ms=30_000)
        assert report.ok(), report.violations[:5]

    def test_deterministic_per_seed(self):
        a = run_mix("bursty", 300, seed=9)
        b = run_mix("bursty", 300, seed=9)
        assert a.to_dict() == b.to_dict()

    def test_shrink_fires_in_a_crafted_pressure_scenario(self):
        """An elastic dev borrower holding the whole pool sheds workers for
        a prod arrival instead of dying whole."""
        queues = {"prod": 0.5, "dev": 0.5}
        sim = PoolSimulator(queues, (8 * GB, 256, 0), preemption=True,
                            grace_ms=0, drain_ms=5000, min_runtime_ms=0)
        jobs = [
            SimJob("dev-big", "dev", arrival_s=0.0, work_s=300.0,
                   demand=(8 * GB, 8, 0), elastic_unit=(GB, 1, 0),
                   elastic_slack=7, checkpoint_every_s=30.0),
            SimJob("prod-late", "prod", arrival_s=10.0, work_s=30.0,
                   demand=(2 * GB, 1, 0)),
        ]
        report = sim.run(jobs)
        assert report.ok(), report.violations
        assert report.shrinks >= 1 and report.evictions == 0

    def test_invariant_checker_catches_a_broken_policy(self, monkeypatch):
        """Prove the checker checks: a policy that admits everyone blindly
        must trip the no-oversubscription invariant."""
        def admit_everyone(self, world, totals):
            # schedule_world is the sim's entry point (the persistent-index
            # path); a blind admit must still trip the checker
            d = pol.Decision()
            for a in world.views.values():
                if not a.admitted:
                    a.admitted = True
                    d.admit.append(a.app_id)
            return d

        monkeypatch.setattr(PreemptionPolicy, "schedule_world", admit_everyone)
        report = run_mix("batch", 50, seed=0)
        assert any("oversubscription" in v for v in report.violations)

    def test_sim_cli_reports_and_exits_zero(self, capsys):
        from tony_tpu.cli.sim import main as sim_main

        rc = sim_main(["--mix", "batch", "--jobs", "200", "--seed", "4"])
        out = capsys.readouterr().out
        assert rc == 0 and "invariants: OK" in out
        rc = sim_main(["--queues", "prod=0.9,dev=0.9"])
        assert rc == 2  # oversubscribed guarantees rejected


# ---------------------------------------------------------------------------
# Live ↔ policy parity: the pool must IMPORT the policy, not re-implement it
# ---------------------------------------------------------------------------
class TestPolicyParity:
    def test_pool_and_sim_share_the_policy_class(self):
        svc = PoolService(secret=SECRET)
        try:
            sim = PoolSimulator({"default": 1.0}, (GB, 8, 0))
            assert type(svc._policy) is PreemptionPolicy
            assert type(sim.policy) is PreemptionPolicy
            assert simmod.PreemptionPolicy is pol.PreemptionPolicy
        finally:
            svc.stop()

    def test_no_scheduling_algorithm_left_in_pool_py(self):
        """Grep guard against re-divergence (same pattern as the
        artifact-index parity test): the admission/preemption ALGORITHM must
        live only in policy.py — pool.py applies decisions."""
        src = open(os.path.join(REPO_ROOT, "tony_tpu", "cluster", "pool.py")).read()
        for forbidden in (
            "def _preempt_for_locked",
            "def _reclaim_across_queues_locked",
            "blocked_heads",
            "over_share",
            "freed_primary",
            # r14 indexed-pass internals: the pool feeds the WorldIndex
            # deltas and applies decisions — it must never grow its own
            # head-selection, victim-walk, or eligibility logic
            "waiting_in",
            "others_waiting",
            "victims_iter",
            "deficit_dims",
            "slack_left",
            "note_admitted",
            "note_evicted",
        ):
            assert forbidden not in src, (
                f"{forbidden!r} found in pool.py — the scheduling algorithm "
                "belongs in cluster/policy.py (shared with tony sim)")
        assert "from tony_tpu.cluster.policy import" in src
        sim_src = open(os.path.join(REPO_ROOT, "tony_tpu", "cluster", "sim.py")).read()
        assert "PreemptionPolicy" in sim_src


# ---------------------------------------------------------------------------
# Live pool drain machinery (direct PoolService, no RPC)
# ---------------------------------------------------------------------------
def make_pool(**kw):
    return PoolService(heartbeat_interval_ms=100, max_missed_heartbeats=3,
                       secret=SECRET, **kw)


class TestPoolDrain:
    def test_two_phase_eviction_defers_kills_and_notifies(self):
        svc = make_pool(preemption=True, preemption_drain_ms=60_000)
        register_cpu_node(svc, "n0")  # 4 GB
        svc.register_app("victim", memory_bytes=3 * GB, vcores=1)
        got = svc.allocate("victim", "worker", 0, 3 * GB, 1, 0)
        svc.register_app("agg", priority=5, memory_bytes=3 * GB, vcores=1)
        # demoted, but NOT killed: the drain window is open
        st = svc.pool_status()
        assert [w["app_id"] for w in st["queues"]["default"]["waiting"]] == ["victim"]
        assert st["queues"]["default"]["waiting"][0]["draining"] is True
        assert st["drains_active"] == 1
        assert not svc._nodes["n0"].pending_kills
        # the notice rides the victim's poll
        resp = svc.poll_exited("victim", with_preempt=True)
        notice = resp["preempt"]
        assert notice["mode"] == "drain" and 0 < notice["deadline_ms"] <= 60_000
        # a cooperative yield (release) resolves the drain as mode=drain
        before = counter_value("tony_pool_preemptions_total", mode="drain")
        svc.release("victim", got["id"])
        assert counter_value("tony_pool_preemptions_total", mode="drain") == before + 1
        assert svc.pool_status()["drains_active"] == 0
        assert svc.poll_exited("victim", with_preempt=True)["preempt"] is None
        svc.stop()

    def test_drain_ms_zero_keeps_the_classic_kill_path(self):
        svc = make_pool(preemption=True)  # drain-ms 0
        register_cpu_node(svc, "n0")
        before = counter_value("tony_pool_preemptions_total", mode="kill")
        svc.register_app("victim", memory_bytes=3 * GB, vcores=1)
        got = svc.allocate("victim", "worker", 0, 3 * GB, 1, 0)
        svc.register_app("agg", priority=5, memory_bytes=3 * GB, vcores=1)
        assert got["id"] in svc._nodes["n0"].pending_kills  # immediate
        assert counter_value("tony_pool_preemptions_total", mode="kill") == before + 1
        svc.stop()

    def test_deadline_escalates_to_kill(self):
        svc = make_pool(preemption=True, preemption_drain_ms=150)
        register_cpu_node(svc, "n0")
        svc.register_app("victim", memory_bytes=3 * GB, vcores=1)
        got = svc.allocate("victim", "worker", 0, 3 * GB, 1, 0)
        svc.register_app("agg", priority=5, memory_bytes=3 * GB, vcores=1)
        assert not svc._nodes["n0"].pending_kills
        before = counter_value("tony_pool_preemptions_total", mode="kill")
        time.sleep(0.25)
        with svc._lock:
            svc._escalate_drains_locked()  # what the liveness loop runs
        assert got["id"] in svc._nodes["n0"].pending_kills
        assert counter_value("tony_pool_preemptions_total", mode="kill") == before + 1
        # the kill still reports as a preemption to the victim's poll
        svc.node_heartbeat("n0", exited={got["id"]: 137})
        assert svc.poll_exited("victim") == {got["id"]: constants.EXIT_PREEMPTED}
        svc.stop()

    def test_drain_cancelled_when_victim_readmitted(self):
        svc = make_pool(preemption=True, preemption_drain_ms=60_000)
        register_cpu_node(svc, "n0")
        svc.register_app("victim", memory_bytes=3 * GB, vcores=1)
        got = svc.allocate("victim", "worker", 0, 3 * GB, 1, 0)
        svc.register_app("agg", priority=5, memory_bytes=3 * GB, vcores=1)
        assert svc.pool_status()["drains_active"] == 1
        req_id = svc.poll_exited("victim", with_preempt=True)["preempt"]["req_id"]
        # the aggressor leaves before the victim yields → victim re-admits,
        # drain cancelled, nothing ever killed
        svc.release_all("agg")
        st = svc.pool_status()
        assert [a["app_id"] for a in st["queues"]["default"]["admitted"]] == ["victim"]
        assert st["drains_active"] == 0
        assert svc.poll_exited("victim", with_preempt=True)["preempt"] == {
            "cancelled": req_id}
        assert not svc._nodes["n0"].pending_kills
        assert got["id"] in svc._containers  # still running
        svc.stop()

    def test_shrink_notice_and_resolution(self):
        svc = make_pool(preemption=True, preemption_drain_ms=60_000,
                        queues={"prod": 0.5, "dev": 0.5})
        register_cpu_node(svc, "n0")  # 4 GB → 2 GB shares
        svc.register_app("dev1", queue="dev", memory_bytes=4 * GB, vcores=2,
                         elastic_unit=[2 * GB, 1, 0], elastic_slack=1)
        a = svc.allocate("dev1", "worker", 0, 2 * GB, 1, 0)
        svc.allocate("dev1", "worker", 1, 2 * GB, 1, 0)
        svc.register_app("prod1", queue="prod", memory_bytes=2 * GB, vcores=1)
        st = svc.pool_status()
        # partial reclaim: dev1 stays ADMITTED (draining), prod1 admitted too
        assert [x["app_id"] for x in st["queues"]["dev"]["admitted"]] == ["dev1"]
        assert st["queues"]["dev"]["admitted"][0]["draining"] is True
        assert [x["app_id"] for x in st["queues"]["prod"]["admitted"]] == ["prod1"]
        notice = svc.poll_exited("dev1", with_preempt=True)["preempt"]
        assert notice["mode"] == "shrink" and notice["shrink_workers"] == 1
        # the AM sheds: releases both containers (rebuild at size 1)
        before = counter_value("tony_pool_preemptions_total", mode="shrink")
        svc.release("dev1", a["id"])
        assert counter_value("tony_pool_preemptions_total", mode="shrink") == before + 1
        assert svc.pool_status()["drains_active"] == 0
        svc.stop()

    def test_shrink_escalates_to_whole_eviction(self):
        svc = make_pool(preemption=True, preemption_drain_ms=100,
                        queues={"prod": 0.5, "dev": 0.5})
        register_cpu_node(svc, "n0")
        svc.register_app("dev1", queue="dev", memory_bytes=4 * GB, vcores=2,
                         elastic_unit=[2 * GB, 1, 0], elastic_slack=1)
        c0 = svc.allocate("dev1", "worker", 0, 2 * GB, 1, 0)
        c1 = svc.allocate("dev1", "worker", 1, 2 * GB, 1, 0)
        svc.register_app("prod1", queue="prod", memory_bytes=2 * GB, vcores=1)
        assert svc.poll_exited("dev1", with_preempt=True)["preempt"]["mode"] == "shrink"
        # shrink deadlines floor at 10s (the shed is a rebuild); force-expire
        # instead of sleeping the test through it
        with svc._lock:
            svc._drains["dev1"]["deadline"] = 0.0
            svc._escalate_drains_locked()
        st = svc.pool_status()
        assert [w["app_id"] for w in st["queues"]["dev"]["waiting"]] == ["dev1"]
        kills = set(svc._nodes["n0"].pending_kills)
        assert {c0["id"], c1["id"]} <= kills
        svc.stop()

    def test_pool_status_share_utilization_fields(self):
        svc = make_pool(queues={"prod": 0.75, "dev": 0.25})
        register_cpu_node(svc, "n0")  # 4 GB
        svc.register_app("p1", queue="prod", memory_bytes=3 * GB, vcores=1)
        svc.allocate("p1", "worker", 0, 3 * GB, 1, 0)
        st = svc.pool_status()
        assert st["primary_dimension"] == "memory_bytes"
        q = st["queues"]["prod"]
        assert q["share_capacity"] == int(0.75 * 4 * GB)
        assert q["used"] == 3 * GB
        svc.stop()

    def test_waiting_age_survives_pool_restart(self, tmp_path):
        """Satellite: journal replay must not reset wait_since — a pool
        restart used to silently restart every waiter's reclaim grace."""
        journal = str(tmp_path / "pool.jsonl")
        svc = make_pool(journal_path=journal)
        register_cpu_node(svc, "n0")
        svc.register_app("busy", memory_bytes=3 * GB, vcores=1)
        svc.allocate("busy", "worker", 0, 3 * GB, 1, 0)
        svc.register_app("waiter", memory_bytes=3 * GB, vcores=1)
        svc.allocate("waiter", "worker", 0, 3 * GB, 1, 0)  # queued
        time.sleep(0.4)
        age_before = svc.pool_status()["queues"]["default"]["waiting"][0]["waiting_s"]
        assert age_before >= 0.4
        svc.stop()
        svc2 = make_pool(journal_path=journal)
        register_cpu_node(svc2, "n0")
        waiting = svc2.pool_status()["queues"]["default"]["waiting"]
        assert [w["app_id"] for w in waiting] == ["waiter"]
        # the age carried across the restart (>= what it was, not reset to 0)
        assert waiting[0]["waiting_s"] >= age_before
        svc2.stop()

    def test_drain_deadline_survives_pool_restart(self, tmp_path):
        journal = str(tmp_path / "pool.jsonl")
        svc = make_pool(preemption=True, preemption_drain_ms=60_000,
                        journal_path=journal)
        register_cpu_node(svc, "n0")
        svc.register_app("victim", memory_bytes=3 * GB, vcores=1)
        svc.allocate("victim", "worker", 0, 3 * GB, 1, 0)
        svc.register_app("agg", priority=5, memory_bytes=3 * GB, vcores=1)
        req = svc.poll_exited("victim", with_preempt=True)["preempt"]["req_id"]
        svc.stop()
        svc2 = make_pool(preemption=True, preemption_drain_ms=60_000,
                         journal_path=journal)
        assert svc2.pool_status()["drains_active"] == 1
        notice = svc2.poll_exited("victim", with_preempt=True)["preempt"]
        assert notice["req_id"] == req and notice["deadline_ms"] <= 60_000
        svc2.stop()


# ---------------------------------------------------------------------------
# Goodput: the drain window is classified, not lumped into `other`
# ---------------------------------------------------------------------------
def ev(t, ms, **payload):
    return Event(EventType(t), payload, ms)


class TestGoodputDrainPhase:
    def test_drain_window_classified(self):
        events = [
            ev("APPLICATION_INITED", 0),
            ev("TASK_REGISTERED", 100, task="w:0"),
            ev("GANG_COMPLETE", 200),
            ev("PREEMPTION_REQUESTED", 1000, req_id="p1", mode="drain"),
            ev("PREEMPTION_YIELDED", 2500, req_id="p1", cooperative=True),
            ev("HEARTBEAT_LOST", 2500, reason="gang restart: preempted"),
            ev("TASK_REGISTERED", 2600, task="w:0"),
            ev("GANG_COMPLETE", 2700),
            ev("TASK_FINISHED", 5000, task="w:0", exit_code=0),
            ev("APPLICATION_FINISHED", 5100, status="SUCCEEDED"),
        ]
        led = obs_goodput.build_ledger("a", events)
        assert led.phases_ms.get("preempt_drain", 0) == 1500
        assert sum(led.phases_ms.values()) == led.wall_ms  # exact partition

    def test_escalated_window_ends_at_escalation(self):
        events = [
            ev("APPLICATION_INITED", 0),
            ev("GANG_COMPLETE", 100),
            ev("PREEMPTION_REQUESTED", 1000, req_id="p1", mode="drain"),
            ev("PREEMPTION_ESCALATED", 4000, req_id="p1"),
            ev("HEARTBEAT_LOST", 4100, reason="gang restart: preempted"),
            ev("GANG_COMPLETE", 4200),
            ev("TASK_FINISHED", 6000, task="w:0", exit_code=0),
            ev("APPLICATION_FINISHED", 6100, status="SUCCEEDED"),
        ]
        led = obs_goodput.build_ledger("a", events)
        assert led.phases_ms.get("preempt_drain", 0) == 3000
        assert sum(led.phases_ms.values()) == led.wall_ms

    def test_cancelled_window_closes_at_cancellation(self):
        """A pool-cancelled drain must not classify the rest of the run as
        preempt_drain: PREEMPTION_CANCELLED terminates the window."""
        events = [
            ev("APPLICATION_INITED", 0),
            ev("GANG_COMPLETE", 100),
            ev("PREEMPTION_REQUESTED", 1000, req_id="p1", mode="drain"),
            ev("PREEMPTION_CANCELLED", 1800, req_id="p1"),
            ev("TASK_FINISHED", 60_000, task="w:0", exit_code=0),
            ev("APPLICATION_FINISHED", 60_100, status="SUCCEEDED"),
        ]
        led = obs_goodput.build_ledger("a", events)
        assert led.phases_ms.get("preempt_drain", 0) == 800
        assert led.phases_ms.get("productive", 0) > 50_000
        assert sum(led.phases_ms.values()) == led.wall_ms

    def test_no_drain_events_no_phase(self):
        events = [
            ev("APPLICATION_INITED", 0),
            ev("GANG_COMPLETE", 100),
            ev("TASK_FINISHED", 2000, task="w:0", exit_code=0),
            ev("APPLICATION_FINISHED", 2100, status="SUCCEEDED"),
        ]
        led = obs_goodput.build_ledger("a", events)
        assert led.phases_ms.get("preempt_drain", 0) == 0


class TestDrainSurfaces:
    def test_trace_summary_prints_drain_episodes(self):
        from tony_tpu.cli.trace import summarize

        spans = [
            {"name": "am.run", "identity": "am", "trace_id": "t",
             "start_ms": 0, "end_ms": 10_000},
            {"name": "am.preempt_drain", "identity": "am", "trace_id": "t",
             "start_ms": 2000, "end_ms": 3500,
             "attrs": {"mode": "drain", "cooperative": True}},
        ]
        out = summarize(spans)
        assert "preemption drains" in out and "1 episode(s)" in out
        assert "drain" in out

    def test_portal_share_bar_renders_over_guarantee_in_red(self):
        from tony_tpu.portal.server import _share_bar

        under = _share_bar({"share_capacity": 4 * GB, "used": 2 * GB})
        assert "50%" in under and "#e33" not in under
        over = _share_bar({"share_capacity": 2 * GB, "used": 4 * GB})
        assert "200%" in over and "#e33" in over
        assert _share_bar({"share_capacity": 0, "used": 0}) == "—"


# ---------------------------------------------------------------------------
# Courier + urgent-save signal over real files
# ---------------------------------------------------------------------------
class TestDrainRelay:
    def test_urgent_signal_roundtrip(self, tmp_path, monkeypatch):
        metrics = str(tmp_path / "m.json")
        monkeypatch.setenv("TONY_TRAIN_METRICS_FILE", metrics)
        monkeypatch.setenv("TONY_PROFILE_POLL_MS", "50")
        from tony_tpu.train.checkpoint import UrgentSaveSignal

        sig = UrgentSaveSignal()
        assert sig.poll() is None  # idle: nothing to do
        with open(metrics + ".drain", "w") as f:
            json.dump({"req_id": "r1"}, f)
        time.sleep(0.06)
        assert sig.poll() == "r1"
        time.sleep(0.06)
        assert sig.poll() is None  # dedup: handled once
        sig.acknowledge("r1", 7)
        done = json.load(open(metrics + ".drain.done"))
        assert done == {"req_id": "r1", "step": 7}

    def test_courier_writes_control_and_reports_done_once(self, tmp_path):
        from tony_tpu.obs.introspect import DrainCourier

        metrics = str(tmp_path / "m.json")
        reports = []
        courier = DrainCourier(lambda **kw: reports.append(kw))
        courier.handle({"req_id": "r9"}, metrics)
        ctl = json.load(open(metrics + ".drain"))
        assert ctl == {"req_id": "r9"}
        assert reports == []  # no done file yet
        with open(metrics + ".drain.done", "w") as f:
            json.dump({"req_id": "r9", "step": 12}, f)
        courier.handle(None, metrics)
        courier.handle({"req_id": "r9"}, metrics)  # redelivery: idempotent
        assert reports == [{"req_id": "r9", "step": 12}]

    def test_courier_retries_report_on_rpc_failure(self, tmp_path):
        from tony_tpu.obs.introspect import DrainCourier

        metrics = str(tmp_path / "m.json")
        calls = []

        def flaky(**kw):
            calls.append(kw)
            if len(calls) == 1:
                raise OSError("am unreachable")

        courier = DrainCourier(flaky)
        courier.handle({"req_id": "r2"}, metrics)
        with open(metrics + ".drain.done", "w") as f:
            json.dump({"req_id": "r2", "step": 3}, f)
        with pytest.raises(OSError):
            courier.handle(None, metrics)
        courier.handle(None, metrics)  # retried on the next beat
        assert len(calls) == 2


# ---------------------------------------------------------------------------
# Headline E2E: drain beats kill; shrink beats whole-gang eviction
# ---------------------------------------------------------------------------
def fixture_cmd(name, *args):
    return " ".join([sys.executable, os.path.join(FIXTURES, name), *map(str, args)])


PREEMPT_CONF = {
    keys.TASK_METRICS_INTERVAL_MS: "200",    # dense METRICS_SNAPSHOTs: the
    keys.PROFILE_POLL_INTERVAL_MS: "100",    # rework derivation reads them
    keys.GOODPUT_INTERVAL_MS: "60000",       # keep the tick out of the way
}


def wait_for(cond, what, timeout=45):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    pytest.fail(f"timed out waiting for {what}")


def read_step(path, default=-1):
    try:
        with open(path) as f:
            return int(json.load(f)["step"])
    except (OSError, ValueError, KeyError):
        return default


def finished_events(tmp_tony_root, app_id):
    from tony_tpu.cluster import history

    return history.read_events(os.path.join(str(tmp_tony_root), "history"), app_id)


def run_preemption_scenario(tmp_tony_root, tmp_path, drain_ms):
    """Two queues under pool pressure: a dev victim gang borrows the pool, a
    prod arrival reclaims it. Returns (victim_events, resume_step, verdicts)."""
    svc = PoolService(
        heartbeat_interval_ms=100, max_missed_heartbeats=4, secret=SECRET,
        preemption=True, preemption_drain_ms=drain_ms,
        queues={"prod": 0.5, "dev": 0.5},
    )
    svc.start()
    agent = spawn_agent(svc.address, "solo", str(tmp_path))
    try:
        wait_for(lambda: any(n.alive for n in svc._nodes.values()),
                 "agent registration", 15)
        shared = tmp_path / f"shared-{drain_ms}"
        h1, t1, r1 = submit_async(tmp_tony_root, {
            **FAST, **PREEMPT_CONF,
            keys.TPU_POOL_SPEC: "rm:%s:%d" % svc.address,
            keys.TPU_POOL_SECRET: SECRET,
            keys.APPLICATION_QUEUE: "dev",
            "tony.worker.instances": "1", "tony.worker.memory": "3g",
            keys.EXECUTES: fixture_cmd("preempt_train.py", shared, 12, 150),
        })
        # victim running and past step 3 before the aggressor arrives
        wait_for(lambda: read_step(shared / "step-r0.json") >= 3,
                 "victim to make progress")
        quick = tmp_path / f"prod-{drain_ms}.py"
        quick.write_text("import time; time.sleep(1)\n")
        h2, t2, r2 = submit_async(tmp_tony_root, {
            **FAST,
            keys.TPU_POOL_SPEC: "rm:%s:%d" % svc.address,
            keys.TPU_POOL_SECRET: SECRET,
            keys.APPLICATION_QUEUE: "prod",
            "tony.worker.instances": "1", "tony.worker.memory": "2g",
            keys.EXECUTES: f"{sys.executable} {quick}",
        })
        t2.join(timeout=90)
        t1.join(timeout=90)
        assert r2.get("final") == JobStatus.SUCCEEDED, h2.final_status()
        assert r1.get("final") == JobStatus.SUCCEEDED, h1.final_status()
        events = finished_events(tmp_tony_root, h1.app_id)
        resume = read_step(shared / "resume-1.json")
        return events, resume, h1.app_id
    finally:
        if agent.poll() is None:
            agent.terminate()
        try:
            agent.wait(timeout=5)
        except Exception:
            agent.kill()
        svc.stop()


@pytest.mark.e2e
class TestPreemptionE2E:
    def test_drain_checkpoints_then_yields_and_beats_the_kill_path(
        self, tmp_tony_root, tmp_path
    ):
        """The headline: with a generous drain window the victim
        urgent-checkpoints through the real CheckpointManager and yields —
        it resumes from that checkpoint and its measured restart_rework is
        strictly smaller than the kill-path control run's."""
        drain_before = counter_value("tony_pool_preemptions_total", mode="drain")
        events_d, resume_d, app_d = run_preemption_scenario(
            tmp_tony_root, tmp_path, drain_ms=15_000)
        # cooperative: the victim checkpointed BEFORE dying and resumed there
        types = [e.type.value for e in events_d]
        assert "PREEMPTION_REQUESTED" in types and "PREEMPTION_YIELDED" in types
        assert "PREEMPTION_ESCALATED" not in types
        yielded = next(e for e in events_d if e.type.value == "PREEMPTION_YIELDED")
        assert yielded.payload.get("cooperative") is True
        saved = yielded.payload.get("saved_steps") or {}
        assert resume_d > 0 and saved.get("worker:0") == resume_d
        assert counter_value(
            "tony_pool_preemptions_total", mode="drain") == drain_before + 1

        # control run: drain-ms 0 → classic kill, resume from nothing
        events_k, resume_k, app_k = run_preemption_scenario(
            tmp_tony_root, tmp_path, drain_ms=0)
        assert resume_k == 0
        assert "PREEMPTION_REQUESTED" not in [e.type.value for e in events_k]

        led_d = obs_goodput.build_ledger(app_d, events_d)
        led_k = obs_goodput.build_ledger(app_k, events_k)
        # the drain window is classified (not `other`) and the cooperative
        # run's rework is strictly below the kill run's
        assert led_d.phases_ms.get("preempt_drain", 0) > 0
        rework_d = led_d.phases_ms.get("restart_rework", 0)
        rework_k = led_k.phases_ms.get("restart_rework", 0)
        assert rework_k > rework_d, (rework_k, rework_d)
        # exact partition still holds with the new phase in play
        assert sum(led_d.phases_ms.values()) == led_d.wall_ms
        assert sum(led_k.phases_ms.values()) == led_k.wall_ms

    @pytest.mark.slow
    def test_elastic_victim_sheds_workers_instead_of_dying(
        self, tmp_tony_root, tmp_path
    ):
        """Partial reclaim: a 2-worker elastic dev gang sheds one worker
        (divisor rebuild, resumed from the urgent checkpoint) for a prod
        arrival — no whole-gang eviction, no re-queue."""
        svc = PoolService(
            heartbeat_interval_ms=100, max_missed_heartbeats=4, secret=SECRET,
            preemption=True, preemption_drain_ms=15_000,
            queues={"prod": 0.5, "dev": 0.5},
        )
        svc.start()
        agent = spawn_agent(svc.address, "solo", str(tmp_path))
        shrink_before = counter_value("tony_pool_preemptions_total", mode="shrink")
        try:
            wait_for(lambda: any(n.alive for n in svc._nodes.values()),
                     "agent registration", 15)
            shared = tmp_path / "shared-shrink"
            h1, t1, r1 = submit_async(tmp_tony_root, {
                **FAST, **PREEMPT_CONF,
                keys.TPU_POOL_SPEC: "rm:%s:%d" % svc.address,
                keys.TPU_POOL_SECRET: SECRET,
                keys.APPLICATION_QUEUE: "dev",
                "tony.worker.instances": "2", "tony.worker.memory": "2g",
                keys.ELASTIC_MIN_WORKERS: "1",
                keys.ELASTIC_SHRINK_ON_PREEMPT: "true",
                keys.EXECUTES: fixture_cmd("preempt_train.py", shared, 12, 150),
            })
            wait_for(lambda: read_step(shared / "step-r0.json") >= 3,
                     "victim to make progress")
            quick = tmp_path / "prod-shrink.py"
            quick.write_text("import time; time.sleep(1)\n")
            h2, t2, r2 = submit_async(tmp_tony_root, {
                **FAST,
                keys.TPU_POOL_SPEC: "rm:%s:%d" % svc.address,
                keys.TPU_POOL_SECRET: SECRET,
                keys.APPLICATION_QUEUE: "prod",
                "tony.worker.instances": "1", "tony.worker.memory": "2g",
                keys.EXECUTES: f"{sys.executable} {quick}",
            })
            t2.join(timeout=90)
            t1.join(timeout=90)
            assert r2.get("final") == JobStatus.SUCCEEDED, h2.final_status()
            assert r1.get("final") == JobStatus.SUCCEEDED, h1.final_status()
            events = finished_events(tmp_tony_root, h1.app_id)
            types = [e.type.value for e in events]
            req = next(e for e in events if e.type.value == "PREEMPTION_REQUESTED")
            assert req.payload.get("mode") == "shrink"
            assert req.payload.get("resize") == {"worker": 1}
            assert "PREEMPTION_YIELDED" in types
            assert "PREEMPTION_ESCALATED" not in types
            resized = [
                e for e in events
                if e.type.value == "GANG_RESIZED" and not e.payload.get("rejected")
            ]
            assert resized and resized[-1].payload["trigger"] == "preempt"
            assert resized[-1].payload["instances"].get("worker") == 1
            # resumed from the urgent checkpoint at the smaller world size
            assert read_step(shared / "resume-1.json") > 0
            assert counter_value(
                "tony_pool_preemptions_total", mode="shrink") == shrink_before + 1
        finally:
            if agent.poll() is None:
                agent.terminate()
            try:
                agent.wait(timeout=5)
            except Exception:
                agent.kill()
            svc.stop()


# ---------------------------------------------------------------------------
# Slow soak: pool-pressure scenario through `tony chaos --expect-preempt-drain`
# ---------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.e2e
class TestPoolPressureSoak:
    def test_chaos_expect_preempt_drain_under_pool_pressure(
        self, tmp_tony_root, tmp_path, monkeypatch, capsys
    ):
        """`tony chaos` drives the victim under a benign rpc-noise schedule
        while a prod job reclaims the pool: the run must show a cooperative
        drain (victim checkpointed before dying, nothing escalated)."""
        from tony_tpu.cli.chaos import main as chaos_main

        svc = PoolService(
            heartbeat_interval_ms=100, max_missed_heartbeats=4, secret=SECRET,
            preemption=True, preemption_drain_ms=20_000,
            queues={"prod": 0.5, "dev": 0.5},
        )
        svc.start()
        agent = spawn_agent(svc.address, "solo", str(tmp_path))
        try:
            wait_for(lambda: any(n.alive for n in svc._nodes.values()),
                     "agent registration", 15)
            shared = tmp_path / "soak-shared"

            def aggressor():
                wait_for(lambda: read_step(shared / "step-r0.json") >= 3,
                         "victim progress", 60)
                quick = tmp_path / "soak-prod.py"
                quick.write_text("import time; time.sleep(1)\n")
                h, t, r = submit_async(tmp_tony_root, {
                    **FAST,
                    keys.TPU_POOL_SPEC: "rm:%s:%d" % svc.address,
                    keys.TPU_POOL_SECRET: SECRET,
                    keys.APPLICATION_QUEUE: "prod",
                    "tony.worker.instances": "1", "tony.worker.memory": "2g",
                    keys.EXECUTES: f"{sys.executable} {quick}",
                })
                t.join(timeout=120)

            monkeypatch.setenv("TONY_ROOT", str(tmp_tony_root))
            th = threading.Thread(target=aggressor, daemon=True)
            th.start()
            rc = chaos_main([
                "--spec", "rpc-delay:p=0.05",
                "--seed", "3",
                "--executes", fixture_cmd("preempt_train.py", shared, 12, 150),
                "--conf", f"{keys.TPU_POOL_SPEC}=rm:%s:%d" % svc.address,
                "--conf", f"{keys.TPU_POOL_SECRET}={SECRET}",
                "--conf", f"{keys.APPLICATION_QUEUE}=dev",
                "--conf", "tony.worker.instances=1",
                "--conf", "tony.worker.memory=3g",
                "--conf", f"{keys.TASK_METRICS_INTERVAL_MS}=200",
                "--conf", f"{keys.PROFILE_POLL_INTERVAL_MS}=100",
                "--conf", f"{keys.AM_MONITOR_INTERVAL_MS}=50",
                "--expect-preempt-drain",
            ])
            th.join(timeout=120)
            out = capsys.readouterr().out
            assert rc == 0, out
            assert "pool preemptions: 1 requested, 1 yielded, 0 escalated" in out
        finally:
            if agent.poll() is None:
                agent.terminate()
            try:
                agent.wait(timeout=5)
            except Exception:
                agent.kill()
            svc.stop()
