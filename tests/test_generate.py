"""KV-cache generation: parity with the teacher-forced training forward."""

import dataclasses

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models import generate, llama

CFG = dataclasses.replace(llama.LLAMA_TINY, max_seq=64)
KEY = jax.random.PRNGKey(0)


def _params():
    return llama.init(KEY, CFG)


class TestCacheForwardParity:
    def test_prefill_logits_match_forward(self):
        params = _params()
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, CFG.vocab_size)
        cache = generate.init_cache(CFG, 2, 32)
        last, _ = generate.prefill(params, tokens, cache, CFG)
        full = llama.forward(params, tokens, CFG).astype(jnp.float32)
        np.testing.assert_allclose(
            np.asarray(last), np.asarray(full[:, -1]), rtol=2e-2, atol=2e-2
        )

    @pytest.mark.slow
    def test_incremental_decode_matches_full_forward(self):
        """Feeding tokens one at a time through the cache must give the same
        logits as one full causal forward — the cache-correctness proof."""
        params = _params()
        T = 10
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, T), 0, CFG.vocab_size)
        full = llama.forward(params, tokens, CFG).astype(jnp.float32)

        cache = generate.init_cache(CFG, 1, 16)
        step_logits = []
        for t in range(T):
            logits, cache = generate._forward_with_cache(
                params, tokens[:, t:t + 1], cache, CFG
            )
            step_logits.append(logits[:, -1])
        got = jnp.stack(step_logits, axis=1)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(full), rtol=3e-2, atol=3e-2
        )


class TestGenerate:
    def test_greedy_matches_teacher_forced_argmax(self):
        params = _params()
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0, CFG.vocab_size)
        out = generate.generate(params, prompt, CFG, max_new_tokens=5)
        assert out.shape == (2, 5)

        # replay: argmax of the full forward at each position must equal the
        # generated token (greedy decode == teacher forcing on its own output)
        seq = jnp.concatenate([prompt, out], axis=1)
        logits = llama.forward(params, seq, CFG).astype(jnp.float32)
        for i in range(5):
            want = jnp.argmax(logits[:, prompt.shape[1] - 1 + i], axis=-1)
            np.testing.assert_array_equal(np.asarray(out[:, i]), np.asarray(want))

    def test_sampled_generation_shape_and_vocab(self):
        params = _params()
        prompt = jnp.zeros((1, 4), jnp.int32)
        out = generate.generate(
            params, prompt, CFG, max_new_tokens=8, temperature=0.8, top_k=10,
            key=jax.random.PRNGKey(7),
        )
        assert out.shape == (1, 8)
        assert bool((out >= 0).all()) and bool((out < CFG.vocab_size).all())

    def test_single_token(self):
        params = _params()
        prompt = jnp.zeros((1, 4), jnp.int32)
        out = generate.generate(params, prompt, CFG, max_new_tokens=1)
        assert out.shape == (1, 1)


class TestQuantizedServing:
    def test_int8_weights_generate_end_to_end(self):
        from tony_tpu.ops import quant

        params = _params()
        qparams, before, after = quant.quantize_tree(params, min_size=1 << 10)
        assert after < before  # something actually quantized
        prompt = jax.random.randint(jax.random.PRNGKey(11), (1, 6), 0, CFG.vocab_size)

        cache = generate.init_cache(CFG, 1, 16)
        qlast, _ = generate.prefill(qparams, prompt, cache, CFG)
        flast, _ = generate.prefill(params, prompt, generate.init_cache(CFG, 1, 16), CFG)
        # int8 weight error is small relative to the logit scale
        scale = float(jnp.max(jnp.abs(flast))) + 1e-6
        assert float(jnp.max(jnp.abs(qlast - flast))) / scale < 0.15

        out = generate.generate(qparams, prompt, CFG, max_new_tokens=4)
        assert out.shape == (1, 4)
        assert bool((out >= 0).all()) and bool((out < CFG.vocab_size).all())
