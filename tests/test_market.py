"""The serve/train capacity market (docs/scheduling.md "Capacity market").

Four layers, innermost out:

- the pure policy passes (cluster/policy.py): ``fund_demand`` sheds elastic
  workers from over-share borrowers to cover a published deficit — never
  admitting, never whole-evicting — and ``plan_growback`` returns the debt
  once demand ebbs, both under the reclaim pass's own guards (share floor,
  min-runtime shield, eviction budget, plus the grow-back anti-thrash
  shield);
- the live ``PoolService`` market plumbing: the ``update_demand`` RPC
  (journal-durable, double-shed-proof while a demand drain is in flight),
  the liveness tick's TTL expiry / funding retry / quiet-window grow-back
  offers, and grow acceptance through re-registration;
- the seeded capacity-market simulator (``tony sim --mix serve-train``):
  deterministic by seed, market invariants asserted every virtual second —
  the fast tier-1 smoke the verify run-book registers;
- the headline E2E: a serve head whose fleet cannot place publishes its
  deficit to a real pool; an elastic train gang sheds workers through the
  drain/urgent-checkpoint contract (no whole-gang eviction), the serve
  fleet lands inside the spike, and after the ebb the pool grows the gang
  back (``GANG_RESIZED`` trigger=capacity) — every decision in the flight
  recorder under ``demand-spike`` / ``grow-back``.
"""

import json
import os
import sys
import threading
import time

import pytest

from tony_tpu.cluster.policy import AppView, PreemptionPolicy, WorldIndex
from tony_tpu.cluster.pool import PoolService, RemoteResourceManager
from tony_tpu.cluster.recorder import FlightRecorder
from tony_tpu.cluster.resources import Resources
from tony_tpu.cluster.rpc import RpcError
from tony_tpu.cluster.session import JobStatus
from tony_tpu.cluster.sim import GB, run_market_mix
from tony_tpu.config import keys
from tony_tpu.obs import goodput as obs_goodput

from tests.test_pool import FAST, SECRET, register_cpu_node, spawn_agent
from tests.test_pool_queue import submit_async
from tests.test_sched import (
    PREEMPT_CONF,
    FakeClock,
    counter_value,
    finished_events,
    fixture_cmd,
    read_step,
    wait_for,
)

pytestmark = pytest.mark.sched


def _totals(mem_gb=8):
    return (mem_gb * GB, 256, 0)


def _world(*views):
    w = WorldIndex()
    for v in views:
        w.adopt(v)
    return w


def _train(app_id="t1", workers=6, floor=2, seq=1, queue="train", **over):
    return AppView(
        app_id, queue, seq=seq, admitted=True,
        demand=(workers * GB, workers, 0), held=(workers * GB, workers, 0),
        elastic_unit=(GB, 1, 0), elastic_slack=workers - floor, **over)


def _serve_head(app_id="s1", gb=2, queue="serve", **over):
    return AppView(app_id, queue, priority=5, seq=99, admitted=True,
                   demand=(gb * GB, gb, 0), held=(gb * GB, gb, 0), **over)


# ---------------------------------------------------------------------------
# Pure policy units: fund_demand / plan_growback
# ---------------------------------------------------------------------------
class TestFundDemand:
    def test_sheds_from_overshare_elastic_borrower(self):
        clock = FakeClock()
        rec = FlightRecorder(clock=lambda: clock.t)
        p = PreemptionPolicy({"serve": 0.5, "train": 0.5}, preemption=True,
                             clock=clock, sink=rec)
        t1, s1 = _train(workers=6), _serve_head()
        world = _world(t1, s1)
        free = [0, 248, 0]  # 8 GiB pool fully held: 6 train + 2 serve
        d = p.fund_demand(world, _totals(), free, app_id="s1", queue="serve",
                          need=(2 * GB, 2, 0))
        assert [(sh.app_id, sh.workers, sh.for_app) for sh in d.shrink] == \
            [("t1", 2, "s1")]
        assert not d.admit and not d.evict  # the market never admits/evicts
        assert free[0] == 2 * GB and free[1] == 250
        # the victim's view mutated like the scheduling pass would
        assert t1.elastic_slack == 2 and t1.shrink_pending
        assert t1.demand == (4 * GB, 4, 0)
        chain = [r.rule for r in rec.explain("t1")]
        assert "demand-spike" in chain

    def test_headroom_already_covers_deficit(self):
        p = PreemptionPolicy({"serve": 0.5, "train": 0.5}, preemption=True,
                             clock=FakeClock())
        world = _world(_train(workers=4))
        d = p.fund_demand(world, _totals(), [4 * GB, 250, 0],
                          app_id="s1", queue="serve", need=(2 * GB, 2, 0))
        assert d.empty()

    def test_rigid_gang_never_whole_evicted(self):
        rec = FlightRecorder(clock=lambda: 0.0)
        p = PreemptionPolicy({"serve": 0.5, "train": 0.5}, preemption=True,
                             clock=FakeClock(), sink=rec)
        rigid = _train(workers=6, floor=6)  # slack 0: nothing to shed
        world = _world(rigid, _serve_head())
        d = p.fund_demand(world, _totals(), [0, 248, 0],
                          app_id="s1", queue="serve", need=(2 * GB, 2, 0))
        assert d.empty()  # no shrink AND no eviction fallback
        assert "demand-unfunded" in [r.rule for r in rec.explain("s1")]

    def test_share_floor_bounds_the_shed(self):
        # train share 0.5 of 8 GiB = 4 GiB: holding 6, only 2 GiB excess is
        # on the market even though slack would allow shedding deeper
        p = PreemptionPolicy({"serve": 0.5, "train": 0.5}, preemption=True,
                             clock=FakeClock())
        t1 = _train(workers=6, floor=0)
        world = _world(t1, _serve_head())
        d = p.fund_demand(world, _totals(), [0, 248, 0],
                          app_id="s1", queue="serve", need=(4 * GB, 4, 0))
        assert sum(sh.workers for sh in d.shrink) == 2

    def test_growback_shield_prevents_thrash(self):
        clock = FakeClock()
        rec = FlightRecorder(clock=lambda: clock.t)
        p = PreemptionPolicy({"serve": 0.5, "train": 0.5}, preemption=True,
                             min_runtime_ms=5000, clock=clock, sink=rec)
        t1 = _train(workers=6)
        world = _world(t1, _serve_head())
        grown_at = {"t1": clock.t - 1.0}  # re-grown 1s ago
        d = p.fund_demand(world, _totals(), [0, 248, 0], app_id="s1",
                          queue="serve", need=(2 * GB, 2, 0),
                          grown_at=grown_at)
        assert d.empty()  # freshly restored: shielded from the next spike
        assert "demand-unfunded" in [r.rule for r in rec.explain("s1")]
        clock.t += 10.0  # shield window over
        d = p.fund_demand(world, _totals(), [0, 248, 0], app_id="s1",
                          queue="serve", need=(2 * GB, 2, 0),
                          grown_at=grown_at)
        assert sum(sh.workers for sh in d.shrink) == 2

    def test_budget_bounds_disruptions_but_commits_partial(self):
        clock = FakeClock()
        rec = FlightRecorder(clock=lambda: clock.t)
        p = PreemptionPolicy({"serve": 0.5, "train": 0.5}, preemption=True,
                             eviction_budget=1, clock=clock, sink=rec)
        t1 = _train("t1", workers=3, floor=2, seq=1)
        t2 = _train("t2", workers=3, floor=2, seq=2)
        world = _world(t1, t2, _serve_head())
        d = p.fund_demand(world, _totals(), [0, 247, 0], app_id="s1",
                          queue="serve", need=(2 * GB, 2, 0))
        # one disruption allowed → one borrower sheds its single slack
        # worker; the partial funding is committed, not discarded
        assert len(d.shrink) == 1 and d.shrink[0].workers == 1
        assert "budget-exhausted" in [r.rule for r in rec.explain("s1")]


class TestPlanGrowback:
    def test_grants_oldest_first_bounded_by_free(self):
        rec = FlightRecorder(clock=lambda: 0.0)
        p = PreemptionPolicy({"serve": 0.5, "train": 0.5}, preemption=True,
                             clock=FakeClock(), sink=rec)
        t1, t2 = _train("t1", workers=2, floor=2), _train("t2", workers=2, floor=2)
        world = _world(t1, t2)
        free = [3 * GB, 3, 0]
        grants = p.plan_growback(
            world, free, [("t1", 2, (GB, 1, 0)), ("t2", 2, (GB, 1, 0))])
        assert grants == [("t1", 2), ("t2", 1)]  # oldest debt paid first
        assert free[0] == 0  # offers hold the capacity they promise
        assert {r.rule for r in rec.explain("t1")} == {"grow-back"}

    def test_step_caps_per_pass_and_gone_apps_skipped(self):
        p = PreemptionPolicy({"serve": 0.5, "train": 0.5}, preemption=True,
                             clock=FakeClock())
        world = _world(_train("t1", workers=2, floor=2))
        grants = p.plan_growback(
            world, [8 * GB, 8, 0],
            [("gone", 2, (GB, 1, 0)), ("t1", 3, (GB, 1, 0))], step=1)
        assert grants == [("t1", 1)]


# ---------------------------------------------------------------------------
# Live pool plumbing: update_demand RPC, liveness tick, grow acceptance
# ---------------------------------------------------------------------------
class TestPoolMarket:
    def _pool(self, tmp_path, **over):
        svc = PoolService(
            port=0, preemption=True, preemption_drain_ms=10_000,
            queues={"serve": 0.7, "train": 0.3},
            journal_path=str(tmp_path / "pool.jsonl"), **over)
        register_cpu_node(svc, "n0", memory=8 * GB, vcores=64)
        return svc

    def _admit_train(self, svc, workers=6, floor=2):
        svc.register_app("train1", queue="train", memory_bytes=workers * GB,
                         vcores=workers, elastic_unit=[GB, 1, 0],
                         elastic_slack=workers - floor)
        for i in range(workers):
            got = svc.allocate("train1", "worker", i, GB, 1)
            assert "id" in got, got

    def test_publish_funds_journals_and_is_double_shed_proof(self, tmp_path):
        funded_before = counter_value(
            "tony_pool_market_funded_workers_total", queue="train")
        svc = self._pool(tmp_path)
        try:
            self._admit_train(svc)
            svc.register_app("serve1", queue="serve",
                             memory_bytes=2 * GB, vcores=2)
            for i in range(2):
                assert "id" in svc.allocate("serve1", "serve", i, GB, 1)
            out = svc.update_demand("serve1", workers=2, unit=[GB, 1, 0],
                                    reason="pending serve x2")
            assert out == {"ack": True, "funded_workers": 2}
            assert svc._demand["serve1"]["workers"] == 2
            entry = svc._drains["train1"]
            assert entry["mode"] == "shrink" and entry["origin"] == "demand"
            assert entry["for_app"] == "serve1"
            assert counter_value("tony_pool_market_funded_workers_total",
                                 queue="train") == funded_before + 2
            # re-publish while the shed is in flight: the pending drain's
            # undo_demand covers the deficit — no double shed
            out2 = svc.update_demand("serve1", workers=2, unit=[GB, 1, 0])
            assert out2["funded_workers"] == 0

            st = svc.pool_status()
            assert st["market"]["demand"]["serve1"]["workers"] == 2
            assert svc.recorder is not None
            chain = [r.rule for r in svc.recorder.explain("train1")]
            assert "demand-spike" in chain

            # clearing retracts the published deficit and starts the quiet
            # clock the grow-back hysteresis counts from
            assert svc.update_demand("serve1", workers=0)["ack"]
            assert "serve1" not in svc._demand
            assert svc._demand_quiet_since is not None
        finally:
            svc.stop()

    def test_unknown_app_and_disabled_pool_refuse(self, tmp_path):
        svc = self._pool(tmp_path, demand_enabled=False)
        try:
            assert svc.update_demand("ghost", workers=1)["unknown_app"]
            svc.register_app("a1", queue="serve", memory_bytes=GB, vcores=1)
            assert svc.update_demand("a1", workers=1)["disabled"]
        finally:
            svc.stop()

    def test_tick_expires_ttl_offers_growback_and_acceptance_settles(
            self, tmp_path):
        growback_before = counter_value(
            "tony_pool_market_growback_workers_total", queue="train")
        svc = self._pool(tmp_path, demand_ttl_ms=5_000, growback_ebb_ms=1_000)
        try:
            self._admit_train(svc, workers=4, floor=2)
            svc.register_app("serve1", queue="serve",
                             memory_bytes=2 * GB, vcores=2)
            now = time.monotonic()
            with svc._lock:
                # a publisher that went quiet: TTL-expired by the tick
                svc._demand["serve1"] = {
                    "workers": 2, "unit": (GB, 1, 0),
                    "unix": time.time() - 10.0, "mono": now - 10.0,
                }
                svc._market_tick_locked(now)
                assert "serve1" not in svc._demand
                # grow-back: debt + quiet window elapsed + free capacity
                svc._shrunk["train1"] = {
                    "workers": 2, "unit": (GB, 1, 0), "queue": "train",
                    "since_unix": time.time() - 30.0,
                }
                svc._demand_quiet_since = now - 30.0
                svc._market_tick_locked(now)
                grow = svc._grows["train1"]
                assert grow["workers"] == 2
                assert grow["expected_primary"] == 6 * GB  # memory-primary pool
                notice = svc._preempt_notice_locked("train1")
                assert notice["mode"] == "grow"
                assert notice["grow_workers"] == 2
                assert notice["req_id"] == grow["req_id"]
            # acceptance: the AM resizes up and re-registers at the grown
            # demand — the debt settles and the anti-thrash shield arms
            svc.register_app("train1", queue="train", memory_bytes=6 * GB,
                             vcores=6, elastic_unit=[GB, 1, 0],
                             elastic_slack=4)
            assert "train1" not in svc._shrunk
            assert "train1" not in svc._grows
            assert "train1" in svc._grown_at
            assert counter_value("tony_pool_market_growback_workers_total",
                                 queue="train") == growback_before + 2
        finally:
            svc.stop()

    def test_client_degrades_on_pre_market_pool(self):
        rrm = object.__new__(RemoteResourceManager)
        rrm.app_id = "app_1"
        rrm._market_unsupported = False
        calls = []

        class _Cli:
            def call(self, method, **kw):
                calls.append(method)
                raise RpcError("unknown method 'update_demand'")

        rrm.rm = _Cli()
        assert rrm.update_demand(2, Resources(GB, 1, 0)) is False
        assert rrm._market_unsupported is True
        assert rrm.update_demand(2, Resources(GB, 1, 0)) is False
        assert calls == ["update_demand"]  # detected once, never re-sent


# ---------------------------------------------------------------------------
# Seeded capacity-market simulator (tier-1 smoke; verify run-book entry)
# ---------------------------------------------------------------------------
class TestMarketSim:
    def test_seeded_mix_ok_deterministic_with_provenance(self):
        r1, rec = run_market_mix("serve-train", seed=0, record_decisions=True)
        r2, _ = run_market_mix("serve-train", seed=0)
        assert r1.ok(), r1.violations
        assert r1.to_dict() == r2.to_dict()  # same seed, same market
        assert r1.evictions == 0 and r1.shed_workers > 0
        assert r1.restored_all and r1.growback_workers == r1.shed_workers
        assert r1.badput_fraction <= 0.25
        rules = {r.rule for r in rec.records}
        assert {"demand-spike", "grow-back"} <= rules

    def test_seeds_vary_the_spike_schedule(self):
        r0, _ = run_market_mix("serve-train", seed=0)
        r3, _ = run_market_mix("serve-train", seed=3)
        assert r3.ok(), r3.violations
        assert r0.to_dict() != r3.to_dict()  # a different seeded market

    def test_cli_routes_market_mix(self, capsys):
        from tony_tpu.cli.sim import main as sim_main

        assert sim_main(["--mix", "serve-train", "--seed", "3"]) == 0
        out = capsys.readouterr().out
        assert "market sim seed 3" in out
        assert sim_main(["--mix", "serve-train", "--seed", "1",
                         "--json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert data["evictions"] == 0 and data["shed_workers"] > 0

    def test_cli_rejects_infeasible_pool(self, capsys):
        from tony_tpu.cli.sim import main as sim_main

        assert sim_main(["--mix", "serve-train", "--memory", "6"]) == 2
        assert "too small" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# E2E headline: live spike funded by partial reclaim, grown back after ebb
# ---------------------------------------------------------------------------
@pytest.mark.e2e
class TestCapacityMarketE2E:
    def test_spike_sheds_train_workers_then_grows_back(
            self, tmp_tony_root, tmp_path):
        """A serve head that cannot place its fleet publishes the deficit;
        the pool funds it by shrinking the elastic train gang (urgent
        checkpoint, no whole-gang eviction); the fleet lands inside the
        spike; after the ebb the pool offers the shed workers back and the
        gang grows to full size (``GANG_RESIZED`` trigger=capacity)."""
        svc = PoolService(
            heartbeat_interval_ms=100, max_missed_heartbeats=4, secret=SECRET,
            preemption=True, preemption_drain_ms=15_000,
            queues={"serve": 0.8, "train": 0.2},
            growback_ebb_ms=1_500,
        )
        svc.start()
        agent = spawn_agent(svc.address, "solo", str(tmp_path), memory="8g",
                            extra=("--vcores", "16"))
        shrink_before = counter_value("tony_pool_preemptions_total", mode="shrink")
        kill_before = counter_value("tony_pool_preemptions_total", mode="kill")
        funded_before = counter_value(
            "tony_pool_market_funded_workers_total", queue="train")
        try:
            wait_for(lambda: any(n.alive for n in svc._nodes.values()),
                     "agent registration", 15)
            shared = tmp_path / "market-shared"
            # the borrower: 4×1g elastic train gang, floor 2
            h1, t1, r1 = submit_async(tmp_tony_root, {
                **FAST, **PREEMPT_CONF,
                keys.TPU_POOL_SPEC: "rm:%s:%d" % svc.address,
                keys.TPU_POOL_SECRET: SECRET,
                keys.APPLICATION_QUEUE: "train",
                "tony.worker.instances": "4", "tony.worker.memory": "1g",
                keys.ELASTIC_MIN_WORKERS: "2",
                keys.ELASTIC_SHRINK_ON_PREEMPT: "true",
                keys.EXECUTES: fixture_cmd("preempt_train.py", shared, 400, 150),
            })
            wait_for(lambda: read_step(shared / "step-r0.json") >= 3,
                     "train gang progress")
            # the serve head: ADMITTED at 2×1g (claims fit), market bridge
            # on. The spike lands as a mid-flight scale-up to 6 replicas —
            # 2 more than physically free — so its AM sits in
            # AllocationPending and publishes the unmet deficit instead of
            # waiting the spike out.
            quick = tmp_path / "serve-replica.py"
            quick.write_text("import time; time.sleep(8)\n")
            h2, t2, r2 = submit_async(tmp_tony_root, {
                **FAST,
                keys.TPU_POOL_SPEC: "rm:%s:%d" % svc.address,
                keys.TPU_POOL_SECRET: SECRET,
                keys.APPLICATION_QUEUE: "serve",
                "tony.worker.instances": "2", "tony.worker.memory": "1g",
                keys.SERVE_MARKET_ENABLED: "true",
                keys.TASK_RESTART_ON_FAILURE: "true",
                keys.EXECUTES: f"{sys.executable} {quick}",
            })

            def serve_fleet_up():
                rpc = h2.rpc(timeout_s=5)
                if rpc is None:
                    return None
                try:
                    infos = rpc.call("get_task_infos")
                    if sum(1 for t in infos if t["status"] == "RUNNING") >= 2:
                        return rpc
                except Exception:  # noqa: BLE001 — AM still starting
                    pass
                rpc.close()
                return None

            rpc = wait_for_value(serve_fleet_up, "serve fleet up", 90)
            try:
                assert rpc.call("resize_jobtype", job_name="worker",
                                instances=6)["ack"]
            finally:
                rpc.close()
            wait_for(lambda: svc._demand or svc._shrunk,
                     "published deficit reaching the pool", 60)
            # the grown serve fleet places INSIDE the spike (funded by the
            # shed) and runs to completion
            t2.join(timeout=150)
            assert r2.get("final") == JobStatus.SUCCEEDED, h2.final_status()
            assert counter_value("tony_pool_market_funded_workers_total",
                                 queue="train") >= funded_before + 2
            assert counter_value("tony_pool_preemptions_total",
                                 mode="shrink") >= shrink_before + 1

            # ebb → quiet window → grow offer → the gang accepts and grows
            # back to 4 workers under trigger=capacity
            def grown_back():
                evs = finished_events(tmp_tony_root, h1.app_id)
                return [
                    e for e in evs
                    if e.type.value == "GANG_RESIZED"
                    and not e.payload.get("rejected")
                    and e.payload.get("trigger") == "capacity"
                ] or None

            resized = wait_for_value(grown_back, "grow-back resize", 90)
            assert resized[-1].payload["instances"].get("worker") == 4
            wait_for(lambda: not svc._shrunk, "grow-back debt settled", 60)

            events = finished_events(tmp_tony_root, h1.app_id)
            types = [e.type.value for e in events]
            # the shed was cooperative partial reclaim, never an eviction
            req = next(e for e in events
                       if e.type.value == "PREEMPTION_REQUESTED")
            assert req.payload.get("mode") == "shrink"
            assert "PREEMPTION_ESCALATED" not in types
            assert counter_value("tony_pool_preemptions_total",
                                 mode="kill") == kill_before
            # provenance: the flight recorder chains name the market rules
            chain = [r.rule for r in svc.recorder.explain(h1.app_id)]
            assert "demand-spike" in chain and "grow-back" in chain
            # disruption stays bounded: the goodput ledger charges the shed
            # and the grow-back rebuilds, and they are a fraction of the run
            led = obs_goodput.build_ledger(
                h1.app_id, events, now_ms=int(time.time() * 1000))
            assert led.disruption_fraction() < 0.75, led.phases_ms
        finally:
            from tony_tpu.cluster.client import Client

            Client.kill(h1)
            t1.join(timeout=60)
            if agent.poll() is None:
                agent.terminate()
            try:
                agent.wait(timeout=5)
            except Exception:  # noqa: BLE001
                agent.kill()
            svc.stop()


def wait_for_value(cond, what, timeout=45):
    deadline = time.time() + timeout
    while time.time() < deadline:
        got = cond()
        if got:
            return got
        time.sleep(0.1)
    pytest.fail(f"timed out waiting for {what}")
