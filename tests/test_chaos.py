"""Deterministic fault-injection suite (tony_tpu/chaos; docs/fault-tolerance.md).

Fast tier-1 coverage of the recovery matrix — one fault per recovery path:
rpc retry/backoff/deadline, heartbeat-lost → LOST / → gang restart,
stale-epoch spec fencing, execution-timeout exit code, corrupt-checkpoint
fallback — plus the seeded multi-fault soak (marked slow).
"""

import os
import socket
import time

import pytest

from tony_tpu import constants
from tony_tpu.chaos import ChaosContext, FaultSchedule, corrupt_latest_checkpoint
from tony_tpu.cluster import history
from tony_tpu.cluster.rpc import RpcClient, RpcError, RpcServer
from tony_tpu.cluster.session import JobStatus
from tony_tpu.config import TonyConfig, keys

from tests.test_e2e import FAST, fixture_cmd, run_job

pytestmark = pytest.mark.chaos


def ctx_for(spec: str, seed: int = 0, identity: str = "worker:0", staging=None) -> ChaosContext:
    return ChaosContext(FaultSchedule.parse(spec, seed), identity, staging_dir=staging)


# ---------------------------------------------------------------------------
# grammar
# ---------------------------------------------------------------------------
class TestFaultGrammar:
    def test_full_exemplar_schedule(self):
        s = FaultSchedule.parse(
            "rpc-drop:p=0.05;exec-crash:worker:1@gang_complete;"
            "hb-stall:worker:0@t+5s;ckpt-corrupt:latest",
            seed=42,
        )
        assert [f.kind for f in s.faults] == ["rpc-drop", "exec-crash", "hb-stall", "ckpt-corrupt"]
        drop, crash, stall, corrupt = s.faults
        assert drop.params == {"p": 0.05} and drop.target is None
        assert crash.target == ("worker", 1) and crash.trigger == "gang_complete"
        assert stall.target == ("worker", 0) and stall.delay_ms == 5000 and stall.trigger is None
        assert corrupt.args == ("latest",)
        assert s.seed == 42

    def test_params_and_args_mix(self):
        (f,) = FaultSchedule.parse("rpc-delay:worker:2:p=0.5:ms=250").faults
        assert f.target == ("worker", 2)
        assert f.params == {"p": 0.5, "ms": 250.0}
        assert f.ms(default=1) == 250

    def test_empty_spec_and_whitespace(self):
        assert FaultSchedule.parse("").faults == ()
        assert FaultSchedule.parse(" ; ;").faults == ()

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultSchedule.parse("rpc-frobnicate:p=1")

    def test_bad_probability_rejected(self):
        with pytest.raises(ValueError, match="out of"):
            FaultSchedule.parse("rpc-drop:p=1.5")

    def test_non_numeric_param_rejected(self):
        with pytest.raises(ValueError, match="non-numeric"):
            FaultSchedule.parse("rpc-drop:p=often")


# ---------------------------------------------------------------------------
# determinism: the acceptance criterion — same seed + schedule, same sequence
# ---------------------------------------------------------------------------
class TestDeterminism:
    SPEC = "rpc-drop:p=0.3;rpc-delay:p=0.2:ms=1"

    @staticmethod
    def _sequence(ctx, n=300):
        return [
            (ctx.take("rpc-drop") is not None, ctx.take("rpc-delay") is not None)
            for _ in range(n)
        ]

    def test_same_seed_same_injected_sequence(self):
        a = self._sequence(ctx_for(self.SPEC, seed=42))
        b = self._sequence(ctx_for(self.SPEC, seed=42))
        assert a == b

    def test_different_seed_differs(self):
        a = self._sequence(ctx_for(self.SPEC, seed=42))
        c = self._sequence(ctx_for(self.SPEC, seed=43))
        assert a != c

    def test_streams_are_per_identity(self):
        a = self._sequence(ctx_for(self.SPEC, seed=42, identity="worker:0"))
        b = self._sequence(ctx_for(self.SPEC, seed=42, identity="worker:1"))
        assert a != b

    def test_targeted_fault_ignores_other_tasks(self):
        ctx = ctx_for("hb-stall:worker:1", identity="worker:0")
        assert ctx.take("hb-stall") is None
        ctx = ctx_for("hb-stall:worker:1", identity="worker:1")
        assert ctx.take("hb-stall") is not None

    def test_once_per_job_latch_survives_process_restart(self, tmp_path):
        staging = str(tmp_path)
        assert ctx_for("hb-stall:worker:0", staging=staging).take("hb-stall") is not None
        # a NEW context (a restarted attempt) sees the shared latch
        assert ctx_for("hb-stall:worker:0", staging=staging).take("hb-stall") is None

    def test_time_armed_fault_waits(self):
        ctx = ctx_for("exec-crash:worker:0@t+1h")
        assert ctx.take("exec-crash") is None  # not armed yet

    def test_take_spec_enforces_target(self):
        # the executor's timed-fault threads go through take_spec directly:
        # a fault targeted at another task must not fire here
        ctx = ctx_for("exec-crash:worker:1", identity="worker:0")
        (f,) = ctx.schedule.faults
        assert ctx.take_spec(f) is None
        ctx1 = ctx_for("exec-crash:worker:1", identity="worker:1")
        assert ctx1.take_spec(ctx1.schedule.faults[0]) is not None

    def test_injections_are_logged(self, tmp_path):
        ctx = ctx_for("hb-stall:worker:0", staging=str(tmp_path))
        ctx.take("hb-stall")
        (log,) = [f for f in os.listdir(tmp_path / "chaos") if f.endswith(".jsonl")]
        assert "worker_0" in log
        assert ctx.injected[0]["kind"] == "hb-stall"


# ---------------------------------------------------------------------------
# rpc hardening: exponential backoff + full jitter + overall deadline
# ---------------------------------------------------------------------------
def _dead_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]  # closed on exit → connection refused


class TestRetryBackoff:
    def test_exponential_backoff_with_jitter_and_cap(self, monkeypatch):
        sleeps = []
        import tony_tpu.cluster.rpc as rpc_mod

        monkeypatch.setattr(rpc_mod.time, "sleep", sleeps.append)
        c = RpcClient("127.0.0.1", _dead_port())
        with pytest.raises(RpcError, match="failed after 8 retries"):
            c.call_with_retry("ping", retries=8, delay_s=0.05, max_delay_s=0.4)
        assert len(sleeps) == 7  # no sleep after the final attempt
        for i, s in enumerate(sleeps):
            assert 0 <= s <= min(0.4, 0.05 * 2**i) + 1e-9

    def test_overall_deadline_bounds_wall_time(self):
        c = RpcClient("127.0.0.1", _dead_port())
        t0 = time.monotonic()
        with pytest.raises(RpcError, match="deadline"):
            c.call_with_retry("ping", retries=10_000, delay_s=0.01, deadline_s=0.3)
        assert time.monotonic() - t0 < 5

    def test_success_path_unchanged(self):
        srv = RpcServer()
        srv.register("ping", lambda: "pong")
        srv.start()
        try:
            host, port = srv.address
            assert RpcClient(host, port).call_with_retry("ping", retries=3) == "pong"
        finally:
            srv.stop()


class TestRpcChaos:
    @pytest.fixture()
    def server(self):
        srv = RpcServer()
        srv.register("echo", lambda **kw: kw)
        srv.start()
        yield srv
        srv.stop()

    def _client(self, server, spec, seed=0):
        host, port = server.address
        return RpcClient(host, port, chaos=ctx_for(spec, seed))

    def test_drop_fails_the_call(self, server):
        c = self._client(server, "rpc-drop:p=1")
        with pytest.raises(ConnectionError, match="chaos rpc-drop"):
            c.call("echo", a=1)

    def test_delay_is_injected_but_call_succeeds(self, server):
        c = self._client(server, "rpc-delay:p=1:ms=10")
        assert c.call("echo", a=1) == {"a": 1}
        assert [r["kind"] for r in c.chaos.injected] == ["rpc-delay"]

    def test_sever_loses_response_and_reconnect_recovers(self, server):
        # p=0.5: some calls get severed mid-call; retry must always recover
        c = self._client(server, "rpc-sever:p=0.5", seed=3)
        for i in range(20):
            assert c.call_with_retry("echo", retries=10, delay_s=0.01, i=i) == {"i": i}
        assert any(r["kind"] == "rpc-sever" for r in c.chaos.injected)

    def test_retry_rides_through_seeded_drops(self, server):
        c = self._client(server, "rpc-drop:p=0.5", seed=11)
        for i in range(10):
            assert c.call_with_retry("echo", retries=30, delay_s=0.01, i=i) == {"i": i}
        assert any(r["kind"] == "rpc-drop" for r in c.chaos.injected)


# ---------------------------------------------------------------------------
# container faults at the RM poll_exited seam
# ---------------------------------------------------------------------------
class _FakeRM:
    def __init__(self, containers):
        self.live = containers
        self.killed = []

    def _live_containers(self):
        return self.live

    def kill_container(self, c):
        self.killed.append(c.id)


def _container(cid, job, idx):
    from tony_tpu.cluster.resources import Container, Resources

    return Container(id=cid, host="h", resources=Resources(), job_type=job, task_index=idx)


class TestContainerFaults:
    def test_node_loss_respects_target(self):
        rm = _FakeRM([_container("c0", "worker", 0), _container("c1", "worker", 1)])
        exits = ctx_for("node-loss:worker:1").perturb_container_exits(rm, {})
        assert exits == {"c1": constants.EXIT_NODE_LOST}
        assert rm.killed == ["c1"]

    def test_untargeted_node_loss_kills_all(self):
        rm = _FakeRM([_container("c0", "worker", 0), _container("c1", "ps", 0)])
        exits = ctx_for("node-loss").perturb_container_exits(rm, {})
        assert exits == {"c0": constants.EXIT_NODE_LOST, "c1": constants.EXIT_NODE_LOST}

    def test_preempt_targets_and_is_budget_exempt_code(self):
        rm = _FakeRM([_container("c0", "worker", 0), _container("c1", "worker", 1)])
        exits = ctx_for("preempt:worker:0").perturb_container_exits(rm, {})
        assert exits == {"c0": constants.EXIT_PREEMPTED}


# ---------------------------------------------------------------------------
# stale-epoch fencing: get_cluster_spec now fenced like every executor RPC
# ---------------------------------------------------------------------------
class TestStaleEpochFencing:
    def test_spec_fenced_by_gang_epoch(self, tmp_path):
        from tony_tpu.cluster.appmaster import ApplicationMaster

        cfg = TonyConfig({"tony.worker.instances": "1"})
        am = ApplicationMaster(cfg, "app_fence_test", str(tmp_path / "stage"))
        try:
            am.register_worker_spec("worker", 0, "127.0.0.1", 1234, attempt=0)
            resp = am.get_cluster_spec("worker", 0, attempt=0)
            assert resp["spec"] == {"worker": ["127.0.0.1:1234"]}
            # a gang restart bumps the epoch; the old executor's identity recurs
            am._restart_attempt = 1
            resp = am.get_cluster_spec("worker", 0, attempt=0)
            assert resp == {"spec": None, "stale": True}
            assert am.register_execution_result("worker", 0, exit_code=0, attempt=0)["stale"]
            assert am.task_executor_heartbeat("worker", 0, attempt=0)["stale"]
        finally:
            am.rpc.stop()
            am.events.stop()
            am.rm.shutdown()


# ---------------------------------------------------------------------------
# corrupt-checkpoint fallback (restore_or_init hardening)
# ---------------------------------------------------------------------------
class TestCheckpointFallback:
    @staticmethod
    def _save_steps(d, steps):
        import jax.numpy as jnp

        from tony_tpu.train.checkpoint import CheckpointManager

        mgr = CheckpointManager(d, use_async=False)
        for s in steps:
            mgr.save(s, {"w": jnp.full((4,), float(s))}, force=True)
        mgr.wait()
        mgr.close()

    def test_falls_back_to_newest_intact_step(self, tmp_path):
        import jax.numpy as jnp

        from tony_tpu.train.checkpoint import restore_or_init

        d = str(tmp_path / "ckpt")
        self._save_steps(d, [1, 2])
        assert corrupt_latest_checkpoint(d) == 2
        state, mgr, step = restore_or_init(d, lambda: {"w": jnp.zeros((4,))})
        try:
            assert step == 1
            assert float(state["w"][0]) == 1.0
            # the torn step is quarantined: latest_step no longer reports it
            assert mgr.latest_step() == 1
            assert os.path.isdir(os.path.join(d, ".corrupt-2"))
        finally:
            mgr.close()

    def test_quarantine_race_with_peer_worker_is_benign(self, tmp_path):
        # gang workers share the ckpt dir and quarantine concurrently: losing
        # the rename race (src already gone) must not crash the worker
        from tony_tpu.train.checkpoint import _quarantine_step

        d = tmp_path / "ckpt"
        (d / "4").mkdir(parents=True)
        _quarantine_step(str(d), 4)
        assert (d / ".corrupt-4").is_dir()
        _quarantine_step(str(d), 4)  # peer already moved it: no-op, no raise

    def test_quarantine_replaces_stale_quarantine_dir(self, tmp_path):
        from tony_tpu.train.checkpoint import _quarantine_step

        d = tmp_path / "ckpt"
        (d / ".corrupt-4" / "old").mkdir(parents=True)  # leftover, non-empty
        (d / "4").mkdir()
        _quarantine_step(str(d), 4)
        assert not (d / "4").exists()
        assert not (d / ".corrupt-4" / "old").exists()

    def test_all_corrupt_initializes_fresh(self, tmp_path):
        import jax.numpy as jnp

        from tony_tpu.train.checkpoint import restore_or_init

        d = str(tmp_path / "ckpt")
        self._save_steps(d, [1])
        corrupt_latest_checkpoint(d, mode="garbage")
        state, mgr, step = restore_or_init(d, lambda: {"w": jnp.zeros((4,))})
        try:
            assert step == 0
            assert float(state["w"][0]) == 0.0
        finally:
            mgr.close()

    def test_env_gated_injection_tears_latest(self, tmp_path, monkeypatch):
        from tony_tpu.chaos import maybe_corrupt_checkpoint

        d = str(tmp_path / "ckpt")
        self._save_steps(d, [3])
        # no schedule in env → strict no-op
        assert maybe_corrupt_checkpoint(d) is None
        monkeypatch.setenv(constants.ENV_CHAOS_SPEC, "ckpt-corrupt:latest")
        monkeypatch.setenv(constants.ENV_CHAOS_SEED, "5")
        monkeypatch.setenv(constants.ENV_STAGING_DIR, str(tmp_path))
        assert maybe_corrupt_checkpoint(d) == 3
        # once per job: the latch is spent
        assert maybe_corrupt_checkpoint(d) is None


# ---------------------------------------------------------------------------
# recovery-path E2E: one fault per path (fast, tier-1)
# ---------------------------------------------------------------------------
@pytest.mark.e2e
class TestChaosRecoveryE2E:
    def test_heartbeat_stall_triggers_gang_restart(self, tmp_tony_root):
        # attempt 0 is wedged by hb-stall → LOST → whole-gang restart; the
        # once-per-job latch keeps attempt 1 healthy and it exits 0
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "1",
                keys.EXECUTES: fixture_cmd("lost_then_ok.py"),
                keys.TASK_MAX_MISSED_HEARTBEATS: "3",
                keys.TASK_RESTART_ON_FAILURE: "true",
                keys.CHAOS_SPEC: "hb-stall:worker:0",
                keys.CHAOS_SEED: "13",
            },
        )
        assert final == JobStatus.SUCCEEDED, handle.final_status()
        assert handle.final_status()["restart_attempt"] == 1

    def test_execution_timeout_gets_own_exit_code(self, tmp_tony_root):
        final, _, handle = run_job(
            tmp_tony_root,
            {
                "tony.worker.instances": "1",
                keys.EXECUTES: fixture_cmd("forever.py"),
                keys.TASK_EXECUTOR_EXECUTION_TIMEOUT_MS: "1000",
            },
        )
        assert final == JobStatus.FAILED
        task = handle.final_status()["tasks"][0]
        assert task["exit_code"] == constants.EXIT_EXECUTION_TIMEOUT
        history_root = os.path.join(str(tmp_tony_root), "history")
        finished = [
            e for e in history.read_events(history_root, handle.app_id)
            if e.type.value == "TASK_FINISHED"
            and e.payload.get("exit_code") == constants.EXIT_EXECUTION_TIMEOUT
        ]
        assert finished and "execution timeout" in finished[0].payload["reason"]

    def test_chaos_cli_asserts_invariants(self, tmp_tony_root, capsys):
        from tony_tpu.cli.chaos import main as chaos_main

        rc = chaos_main([
            "--spec", "rpc-delay:p=0.3:ms=5",
            "--seed", "11",
            "--executes", fixture_cmd("exit_0.py"),
            "--workers", "1",
            "--conf", f"{keys.STAGING_ROOT}={tmp_tony_root}",
        ] + [f"--conf={k}={v}" for k, v in FAST.items()])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "invariants: OK" in out
        assert "gang epochs: 1" in out


# ---------------------------------------------------------------------------
# seeded multi-fault soak (slow): AM SIGKILL + crash + torn ckpt + rpc noise
# ---------------------------------------------------------------------------
@pytest.mark.e2e
@pytest.mark.slow
class TestMultiFaultSoak:
    def test_soak_resumes_through_torn_checkpoint_and_am_crash(self, tmp_tony_root):
        from tony_tpu.cli.chaos import _find_orphans, verify_chaos_run

        # am-crash rides along with the executor/rpc faults: the control
        # plane dies mid-run (work-preserving takeover adopts the gang) AND
        # the data plane still crashes + tears its checkpoint afterwards
        spec = "rpc-drop:p=0.02;ckpt-corrupt:latest;am-crash@t+2s"
        cfg = TonyConfig({
            **FAST,
            keys.STAGING_ROOT: str(tmp_tony_root),
            "tony.worker.instances": "1",
            keys.EXECUTES: fixture_cmd("chaos_train.py"),
            keys.TASK_RESTART_ON_FAILURE: "true",
            keys.TASK_MAX_MISSED_HEARTBEATS: "100",  # jax compile outlasts the fast hb budget
            keys.AM_RETRY_COUNT: "1",
            keys.CHAOS_SPEC: spec,
            keys.CHAOS_SEED: "20260803",
        })
        from tony_tpu.cluster.client import Client

        client = Client(cfg)
        handle = client.submit()
        final = client.monitor_application(handle, quiet=True)
        assert final == JobStatus.SUCCEEDED, handle.final_status()

        # the relaunched attempt fell back past the torn step 4 to step 2
        log = os.path.join(str(tmp_tony_root), handle.app_id, "logs", "worker_0_r1", "stdout.log")
        with open(log) as f:
            out = f.read()
        assert "resumed from checkpoint step 2" in out, out
        assert "soak resume run completed to step 8" in out, out

        failures, info = verify_chaos_run(handle, cfg)
        assert not failures, failures
        assert info["gang_epochs"] == 2  # the takeover consumed NO gang epoch
        assert info["takeovers"] == 1 and not info["takeovers_degraded"]
        assert handle.final_status()["am_attempt"] == 1
        assert not _find_orphans(handle.app_id)
