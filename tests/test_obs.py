"""Observability suite: span model, RPC trace propagation + overhead,
metrics registry / Prometheus exposition, forward-compat event reads,
sparkline hardening, `tony trace` reconstruction, and the full e2e
acceptance path (traced chaos job → merged Chrome timeline → /metrics).
"""

import http.client
import json
import math
import os
import socket
import sys
import threading

import pytest

from tony_tpu.cluster.events import Event, EventType, UnknownEventType
from tony_tpu.cluster.rpc import RpcClient, RpcServer, _recv_frame, _send_frame
from tony_tpu.obs import metrics as obs_metrics
from tony_tpu.obs import trace as obs_trace
from tony_tpu.obs.metrics import MetricsRegistry, render_merged
from tony_tpu.portal.server import _sparkline

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


@pytest.fixture()
def tracer(tmp_path):
    tr = obs_trace.init_tracing("app-test", "tester", str(tmp_path))
    yield tr
    obs_trace.shutdown()


def read_spans(tmp_path, identity="tester"):
    path = os.path.join(str(tmp_path), f"{identity}.spans.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.mark.obs
class TestSpanModel:
    def test_nested_spans_parent_links_and_sink(self, tracer, tmp_path):
        with tracer.span("outer", kind="internal", answer=42) as outer:
            with tracer.span("inner") as inner:
                inner.add_event("tick", n=1)
        spans = read_spans(tmp_path)
        # inner finished (and was written) first
        assert [s["name"] for s in spans] == ["inner", "outer"]
        by_name = {s["name"]: s for s in spans}
        assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
        assert by_name["outer"]["parent_id"] is None
        assert by_name["outer"]["trace_id"] == by_name["inner"]["trace_id"] == "app-test"
        assert by_name["outer"]["attrs"] == {"answer": 42}
        assert by_name["inner"]["events"][0]["name"] == "tick"
        assert by_name["inner"]["end_ms"] >= by_name["inner"]["start_ms"]

    def test_root_parent_fallback_for_bare_threads(self, tracer, tmp_path):
        tracer.root_parent = "feedfacefeedface"
        with tracer.span("orphan"):
            pass
        assert read_spans(tmp_path)[0]["parent_id"] == "feedfacefeedface"

    def test_error_status_on_exception(self, tracer, tmp_path):
        with pytest.raises(RuntimeError):
            with tracer.span("boom"):
                raise RuntimeError("x")
        assert read_spans(tmp_path)[0]["status"] == "error"

    def test_add_event_is_noop_when_disabled(self):
        assert obs_trace.get() is None
        obs_trace.add_event("nobody-home", x=1)  # must not raise
        assert obs_trace.current_span() is None

    def test_maybe_span_disabled_is_shared_noop(self):
        assert obs_trace.get() is None
        ctx1 = obs_trace.maybe_span("a")
        ctx2 = obs_trace.maybe_span("b", kind="server", attr=1)
        assert ctx1 is ctx2  # one shared object: zero allocation per hook
        with ctx1:
            pass


@pytest.fixture()
def echo_server():
    srv = RpcServer(secret="s3cret")
    srv.register("echo", lambda **kw: kw)
    srv.start()
    yield srv
    srv.stop()


@pytest.mark.obs
class TestRpcTracing:
    def test_disabled_round_trip_allocates_no_spans(self, echo_server, monkeypatch):
        """Tracing off (the default): the RPC round trip must construct zero
        Span objects and put no trace field on the wire."""
        assert obs_trace.get() is None
        sent = []
        real_send = _send_frame

        def spy_send(sock, obj):
            sent.append(obj)
            real_send(sock, obj)

        def no_spans(*a, **kw):
            raise AssertionError("Span allocated on the disabled fast path")

        monkeypatch.setattr("tony_tpu.cluster.rpc._send_frame", spy_send)
        monkeypatch.setattr(obs_trace.Span, "__init__", no_spans)
        host, port = echo_server.address
        cli = RpcClient(host, port, secret="s3cret")
        assert cli.call("echo", a=1) == {"a": 1}
        cli.close()
        req = next(o for o in sent if isinstance(o, dict) and o.get("method") == "echo")
        assert "trace" not in req

    def test_enabled_spans_survive_frame_codec_and_link(self, echo_server, tmp_path):
        """Client + server share this process: both spans land in the sink,
        the server span's parent is the client span carried IN the frame."""
        tr = obs_trace.init_tracing("app-rpc", "both", str(tmp_path))
        try:
            host, port = echo_server.address
            cli = RpcClient(host, port, secret="s3cret")
            with tr.span("root"):
                assert cli.call("echo", x="y") == {"x": "y"}
            cli.close()
        finally:
            obs_trace.shutdown()
        by_name = {s["name"]: s for s in read_spans(tmp_path, "both")}
        client_span = by_name["rpc.client:echo"]
        server_span = by_name["rpc.server:echo"]
        root = by_name["root"]
        assert client_span["parent_id"] == root["span_id"]
        assert server_span["parent_id"] == client_span["span_id"]  # crossed the wire
        assert server_span["kind"] == "server" and client_span["kind"] == "client"
        assert server_span["trace_id"] == "app-rpc"

    def test_server_ignores_trace_field_when_disabled(self, echo_server):
        """Forward compat: a frame carrying trace context is served normally
        by a server whose tracing is off."""
        assert obs_trace.get() is None
        host, port = echo_server.address
        with socket.create_connection((host, port), timeout=5) as sock:
            _send_frame(sock, {
                "method": "echo", "params": {"k": 1}, "auth": "s3cret",
                "trace": {"t": "someone-elses-trace", "s": "abcd" * 4},
            })
            resp = _recv_frame(sock)
        assert resp == {"ok": True, "result": {"k": 1}}

    def test_rpc_latency_metrics_recorded(self, echo_server):
        from tony_tpu.cluster.rpc import _CLIENT_LATENCY
        key = ("echo",)
        before = _CLIENT_LATENCY._children.get(key, {}).get("count", 0)
        host, port = echo_server.address
        cli = RpcClient(host, port, secret="s3cret")
        cli.call("echo", a=1)
        cli.close()
        assert _CLIENT_LATENCY._children[key]["count"] == before + 1


@pytest.mark.obs
class TestMetricsRegistry:
    def test_counter_gauge_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total", "help", labelnames=("method",))
        c.inc(method="a")
        c.inc(2, method="a")
        c.inc(method="b")
        g = reg.gauge("t_gauge")
        g.set(1.5)
        assert c.value(method="a") == 3
        assert g.value() == 1.5
        with pytest.raises(ValueError):
            c.inc(wrong="label")

    def test_reregistration_returns_same_metric(self):
        reg = MetricsRegistry()
        assert reg.counter("x_total") is reg.counter("x_total")
        with pytest.raises(ValueError):
            reg.gauge("x_total")

    def test_histogram_buckets_monotone_and_consistent(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "x", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.005, 0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        text = render_merged([(reg.snapshot(), {})])
        counts = []
        for line in text.splitlines():
            if line.startswith("lat_seconds_bucket"):
                counts.append(int(line.rsplit(" ", 1)[1]))
        assert counts == sorted(counts), f"bucket counts must be cumulative-monotone: {counts}"
        assert len(counts) == 4  # 3 finite buckets + +Inf
        assert counts[-1] == 6  # +Inf == total count
        assert 'le="+Inf"' in text
        assert "lat_seconds_count 6" in text
        assert "lat_seconds_sum" in text

    def test_render_merged_applies_extra_labels(self):
        reg = MetricsRegistry()
        reg.counter("y_total", "h", labelnames=("method",)).inc(method="m")
        text = render_merged([(reg.snapshot(), {"app": "application_1_ab"})])
        assert 'y_total{method="m",app="application_1_ab"} 1' in text
        # one TYPE header even when two groups carry the same metric
        two = render_merged([
            (reg.snapshot(), {"app": "a1"}), (reg.snapshot(), {"app": "a2"}),
        ])
        assert two.count("# TYPE y_total counter") == 1
        assert 'app="a1"' in two and 'app="a2"' in two

    def test_set_enabled_false_noops(self):
        reg = MetricsRegistry()
        c = reg.counter("z_total")
        obs_metrics.set_enabled(False)
        try:
            c.inc()
            assert c.value() == 0
        finally:
            obs_metrics.set_enabled(True)
        c.inc()
        assert c.value() == 1

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter("esc_total", labelnames=("m",)).inc(m='ba"ck\\slash\nnl')
        text = render_merged([(reg.snapshot(), {})])
        assert r'm="ba\"ck\\slash\nnl"' in text


@pytest.mark.obs
class TestEventForwardCompat:
    def test_unknown_event_type_tolerated(self):
        line = json.dumps({
            "type": "TRACE_SNAPSHOT_FROM_THE_FUTURE",
            "timestamp_ms": 123,
            "payload": {"spans": 7},
        })
        ev = Event.from_json(line)
        assert isinstance(ev.type, UnknownEventType)
        assert ev.type.value == "TRACE_SNAPSHOT_FROM_THE_FUTURE"
        assert ev.type.name == "TRACE_SNAPSHOT_FROM_THE_FUTURE"
        assert ev.payload == {"spans": 7}
        # and it round-trips byte-compatibly
        assert json.loads(ev.to_json())["type"] == "TRACE_SNAPSHOT_FROM_THE_FUTURE"

    def test_known_event_type_still_enum(self):
        ev = Event.from_json(Event(EventType.GANG_COMPLETE, {"tasks": 2}).to_json())
        assert ev.type is EventType.GANG_COMPLETE

    def test_unknown_type_equality_and_hash(self):
        a, b = UnknownEventType("X_EVENT"), UnknownEventType("X_EVENT")
        assert a == b and hash(a) == hash(b)
        assert a != UnknownEventType("Y_EVENT")


@pytest.mark.obs
class TestSparkline:
    def test_non_finite_values_filtered(self):
        svg = _sparkline([1.0, float("nan"), 2.0, float("inf"), 3.0], "loss")
        assert "<svg" in svg
        assert "nan" not in svg.lower() and "inf" not in svg.lower()

    def test_fewer_than_two_finite_points_skips_chart(self):
        assert _sparkline([float("nan"), float("inf")], "loss") == ""
        assert _sparkline([1.0, float("nan")], "loss") == ""
        assert _sparkline([], "loss") == ""

    def test_all_finite_unchanged(self):
        svg = _sparkline([1.0, 2.0, 0.5], "loss")
        assert "<polyline" in svg and "0.5" in svg


def _make_span(name, identity, span_id, parent_id, start_ms, end_ms, **kw):
    return {
        "name": name, "trace_id": "app-cli", "span_id": span_id,
        "parent_id": parent_id, "kind": kw.pop("kind", "internal"),
        "identity": identity, "thread": kw.pop("thread", 1),
        "start_ms": start_ms, "end_ms": end_ms, "status": "ok", **kw,
    }


@pytest.mark.obs
class TestTraceCli:
    def _write_fixture_trace(self, trace_dir):
        os.makedirs(trace_dir, exist_ok=True)
        client = [_make_span("client.submit", "client", "c1", None, 1000.0, 1500.0)]
        am = [
            _make_span("am.run", "am", "a1", "c1", 1200.0, 9000.0),
            _make_span("am.queue_wait", "am", "a2", "a1", 1300.0, 2300.0),
        ]
        worker = [
            _make_span("executor.run", "worker:0", "w1", "a1", 2500.0, 8000.0),
            _make_span(
                "executor.register", "worker:0", "w2", "w1", 2600.0, 2700.0,
                events=[{"name": "chaos.rpc-delay", "ts_ms": 2650.0,
                         "attrs": {"fault": "rpc-delay:worker:0"}}],
            ),
        ]
        for ident, spans in [("client", client), ("am", am), ("worker_0", worker)]:
            with open(os.path.join(trace_dir, f"{ident}.spans.jsonl"), "w") as f:
                for s in spans:
                    f.write(json.dumps(s) + "\n")
                f.write("{corrupt json\n")  # torn tail line must be skipped

    def test_merge_summary_and_chrome_json(self, tmp_path, capsys):
        from tony_tpu.cli.trace import load_spans, main as trace_main, summarize, to_chrome

        trace_dir = os.path.join(str(tmp_path), "app1", "trace")
        self._write_fixture_trace(trace_dir)
        spans = load_spans(trace_dir)
        assert len(spans) == 5
        chrome = to_chrome(spans)
        json.dumps(chrome)  # must be valid JSON
        events = chrome["traceEvents"]
        names = {e["args"]["name"] for e in events if e["ph"] == "M"}
        assert names == {"client", "am", "worker:0"}
        # X events with µs timestamps, instant event for the chaos annotation
        xs = {e["name"]: e for e in events if e["ph"] == "X"}
        assert xs["client.submit"]["ts"] == 1000.0 * 1000
        assert any(e["ph"] == "i" and e["name"] == "chaos.rpc-delay" for e in events)
        # cross-process parent links become flow arrows
        assert any(e["ph"] == "s" for e in events) and any(e["ph"] == "f" for e in events)

        text = summarize(spans)
        assert "queue wait" in text and "1.00s" in text
        assert "chaos.rpc-delay" in text
        assert "registration barrier" in text

        # CLI end-to-end: writes trace.json next to the spans
        rc = trace_main(["app1", "--staging", str(tmp_path)])
        assert rc == 0
        out_path = os.path.join(trace_dir, "trace.json")
        assert os.path.exists(out_path)
        assert json.load(open(out_path))["traceEvents"]
        assert "critical path" in capsys.readouterr().out

    def test_no_spans_returns_error(self, tmp_path, capsys):
        from tony_tpu.cli.trace import main as trace_main

        assert trace_main(["missing-app", "--staging", str(tmp_path)]) == 1


FAST = {
    "tony.am.monitor-interval-ms": "50",
    "tony.task.heartbeat-interval-ms": "100",
    "tony.am.gang-timeout-ms": "30000",
}


@pytest.mark.obs
@pytest.mark.e2e
class TestTracedJobEndToEnd:
    """The acceptance path: a real traced job under a chaos fault yields a
    causally-linked client→AM→executor chain, a chaos-annotated span, a
    `tony trace` merge, and a /metrics exposition with non-zero RPC latency
    histogram counts."""

    def test_traced_chaos_job_timeline_and_metrics(self, tmp_path, tmp_tony_root):
        from tony_tpu.cli.trace import load_spans, summarize, to_chrome
        from tony_tpu.cluster.client import Client
        from tony_tpu.cluster.session import JobStatus
        from tony_tpu.config import TonyConfig, keys
        from tony_tpu.portal.server import serve

        cfg = TonyConfig({
            **FAST,
            keys.STAGING_ROOT: str(tmp_tony_root),
            "tony.worker.instances": "1",
            keys.EXECUTES: f"{sys.executable} {os.path.join(FIXTURES, 'exit_0.py')}",
            keys.TRACE_ENABLED: "true",
            # deterministic once-latched fault: the executor's first RPC is
            # delayed 50ms inside its open rpc.client span
            keys.CHAOS_SPEC: "rpc-delay:worker:0:ms=50",
            keys.CHAOS_SEED: "11",
        })
        client = Client(cfg)
        handle = client.submit()
        try:
            final = client.monitor_application(handle, quiet=True)
            assert final == JobStatus.SUCCEEDED, handle.final_status()

            trace_dir = os.path.join(handle.staging_dir, "trace")
            spans = load_spans(trace_dir)
            idents = {s["identity"] for s in spans}
            assert {"client", "am", "worker:0"} <= idents, idents

            # causal chain client.submit → am.run → executor.run
            by_id = {s["span_id"]: s for s in spans}
            submit = next(s for s in spans if s["name"] == "client.submit")
            am_run = next(s for s in spans if s["name"] == "am.run")
            ex_run = next(s for s in spans if s["name"] == "executor.run")
            assert am_run["parent_id"] == submit["span_id"]
            assert ex_run["parent_id"] == am_run["span_id"]
            assert submit["trace_id"] == am_run["trace_id"] == ex_run["trace_id"]

            # RPC boundary link: some server span's parent is a client span
            # recorded by ANOTHER process (in-band context propagation)
            crossed = [
                s for s in spans
                if s["name"].startswith("rpc.server:")
                and by_id.get(s["parent_id"], {}).get("identity") not in (None, s["identity"])
            ]
            assert crossed, "no cross-process rpc parent links resolved"

            # the chaos injection rides as an event on the span it perturbed
            chaos_spans = [
                s for s in spans
                if any(str(e.get("name", "")).startswith("chaos.") for e in s.get("events") or [])
            ]
            assert chaos_spans, "chaos fault not annotated on any span"
            assert chaos_spans[0]["identity"] == "worker:0"

            # merged Chrome trace is valid and carries the chain + annotation
            chrome = to_chrome(spans)
            blob = json.dumps(chrome)
            assert json.loads(blob)["traceEvents"]
            assert any(
                e.get("ph") == "i" and str(e.get("name", "")).startswith("chaos.")
                for e in chrome["traceEvents"]
            )
            assert "chaos" in summarize(spans)

            # portal /metrics: parseable Prometheus text with a non-zero RPC
            # latency histogram (this process ran the submit/monitor client)
            server = serve(
                os.path.join(str(tmp_tony_root), "history"), port=0,
                staging_root=str(tmp_tony_root),
            )
            t = threading.Thread(target=server.serve_forever, daemon=True)
            t.start()
            try:
                conn = http.client.HTTPConnection("127.0.0.1", server.server_address[1], timeout=10)
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                assert resp.status == 200
                assert resp.getheader("Content-Type", "").startswith("text/plain")
                text = resp.read().decode()
            finally:
                server.shutdown()
                server.server_close()
            for line in text.splitlines():  # exposition-format sanity
                assert line.startswith("#") or " " in line
            assert "# TYPE tony_rpc_client_latency_seconds histogram" in text
            counts = [
                int(line.rsplit(" ", 1)[1])
                for line in text.splitlines()
                if line.startswith("tony_rpc_client_latency_seconds_count")
            ]
            assert counts and sum(counts) > 0, "rpc latency histogram has zero counts"
        finally:
            obs_trace.shutdown()  # the in-process client installed a tracer


@pytest.mark.obs
class TestOverheadContract:
    def test_disabled_costs_one_none_check(self):
        """The documented contract: with tracing off, maybe_span/add_event
        perform no allocation and Span construction is never reached."""
        assert obs_trace.get() is None
        ctx = obs_trace.maybe_span("hot-path")
        for _ in range(3):
            with ctx:
                obs_trace.add_event("nope")
        assert obs_trace.current_span() is None

    def test_math_isfinite_guard(self):
        # regression guard for the sparkline fix's helper usage
        assert math.isfinite(1.0) and not math.isfinite(float("nan"))
