"""Parallelism library tests on the 8-virtual-device CPU mesh (SURVEY.md §4
strategy: multi-chip behavior without multi-chip hardware)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tony_tpu.compat import shard_map, tree_leaves_with_path
from tony_tpu.ops.attention import attention_reference
from tony_tpu.parallel import MeshSpec, ShardingRules, fsdp_spec_tree
from tony_tpu.parallel.context import ring_attention, ulysses_attention
from tony_tpu.parallel.expert import MoEConfig, capacity, moe_ffn, route
from tony_tpu.parallel.pipeline import spmd_pipeline, split_layers_into_stages, stack_stages


class TestMeshSpec:
    def test_build_all_axes(self):
        mesh = MeshSpec(data=2, fsdp=2, model=2).build()
        assert mesh.shape == {"stage": 1, "data": 2, "fsdp": 2, "expert": 1, "context": 1, "model": 2}

    def test_wrong_device_count_raises(self):
        with pytest.raises(ValueError, match="devices"):
            MeshSpec(data=3).build()

    def test_auto_fills_fsdp(self):
        spec = MeshSpec.auto(8, model=2)
        assert spec.fsdp == 4 and spec.model == 2 and spec.num_devices == 8

    def test_auto_indivisible_raises(self):
        with pytest.raises(ValueError):
            MeshSpec.auto(8, model=3)

    def test_dcn_discipline_rejects_ici_axis_spanning_slices(self):
        spec = MeshSpec(model=8)
        with pytest.raises(ValueError, match="ICI|DCN|slice"):
            spec._check_dcn_discipline(num_slices=2)


class TestShardingRules:
    def test_first_match_wins_and_default_replicates(self):
        rules = ShardingRules([(r"w$", P("fsdp", "model")), (r"w", P("model"))])
        assert rules.spec_for("layers/w") == P("fsdp", "model")
        assert rules.spec_for("layers/wx") == P("model")
        assert rules.spec_for("bias") == P()

    def test_spec_tree_paths(self):
        params = {"a": {"w": jnp.zeros((4, 4)), "b": jnp.zeros((4,))}}
        tree = ShardingRules([(r"a/w", P("fsdp", None))]).spec_tree(params)
        assert tree["a"]["w"] == P("fsdp", None)
        assert tree["a"]["b"] == P()

    def test_fsdp_spec_tree_shards_largest_dim(self):
        params = {"big": jnp.zeros((128, 64)), "small": jnp.zeros((4,))}
        tree = fsdp_spec_tree(params, min_size=128)
        assert tree["big"] == P("fsdp", None)
        assert tree["small"] == P()


def _qkv(key, B=2, H=4, T=64, D=16, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (B, H, T, D), dtype) for k in ks)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        mesh = MeshSpec(context=8).build()
        spec = P(None, None, "context", None)
        ring = shard_map(
            functools.partial(ring_attention, axis_name="context", causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={"context"}, check_vma=False,
        )
        got = jax.jit(ring)(q, k, v)
        want = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)

    def test_context_4_with_other_axes_active(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), H=4, T=32)
        mesh = MeshSpec(data=2, context=4).build()
        spec = P(None, None, "context", None)
        ring = shard_map(
            functools.partial(ring_attention, axis_name="context", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={"context"}, check_vma=False,
        )
        got = jax.jit(ring)(q, k, v)
        want = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


class TestUlyssesAttention:
    def test_matches_reference(self):
        q, k, v = _qkv(jax.random.PRNGKey(2), H=8)
        mesh = MeshSpec(context=8).build()
        spec = P(None, None, "context", None)
        uly = shard_map(
            functools.partial(ulysses_attention, axis_name="context", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={"context"}, check_vma=False,
        )
        got = jax.jit(uly)(q, k, v)
        want = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


class TestPipeline:
    def test_matches_sequential(self):
        S, B, D, M = 4, 8, 16, 4
        key = jax.random.PRNGKey(3)
        stages = [
            {"w": jax.random.normal(jax.random.fold_in(key, s), (D, D)) / D**0.5, "b": jnp.zeros((D,))}
            for s in range(S)
        ]
        stacked = stack_stages(stages)
        x = jax.random.normal(jax.random.fold_in(key, 99), (B, D))

        def stage_fn(p, h):
            return jax.nn.relu(h @ p["w"] + p["b"])

        mesh = MeshSpec(stage=4, data=2).build()
        got = jax.jit(
            functools.partial(spmd_pipeline, stage_fn, mesh=mesh, num_microbatches=M)
        )(stacked, x)

        want = x
        for s in range(S):
            want = stage_fn(stages[s], want)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)

    def test_backward_matches_sequential(self):
        # PP is trainable: grads THROUGH the microbatch schedule (scan +
        # ppermute + masked psum) must equal sequential-execution grads
        S, B, D, M = 4, 8, 16, 4
        key = jax.random.PRNGKey(5)
        stages = [
            {"w": jax.random.normal(jax.random.fold_in(key, s), (D, D)) / D**0.5,
             "b": jnp.zeros((D,))}
            for s in range(S)
        ]
        stacked = stack_stages(stages)
        x = jax.random.normal(jax.random.fold_in(key, 99), (B, D))
        tgt = jax.random.normal(jax.random.fold_in(key, 100), (B, D))

        def stage_fn(p, h):
            return jax.nn.gelu(h @ p["w"] + p["b"])

        mesh = MeshSpec(stage=4, data=2).build()

        def loss_pp(params):
            out = spmd_pipeline(stage_fn, params, x, mesh=mesh, num_microbatches=M)
            return ((out - tgt) ** 2).mean()

        def loss_seq(params):
            h = x
            for s in range(S):
                h = stage_fn(jax.tree.map(lambda p: p[s], params), h)
            return ((h - tgt) ** 2).mean()

        v_pp, g_pp = jax.jit(jax.value_and_grad(loss_pp))(stacked)
        v_seq, g_seq = jax.value_and_grad(loss_seq)(stacked)
        assert abs(float(v_pp) - float(v_seq)) < 1e-6
        for name, a, b in zip(("b", "w"), jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5,
                err_msg=f"pipeline grad {name} diverges from sequential",
            )

    def test_split_layers_into_stages(self):
        layers = {"w": jnp.zeros((8, 3, 3))}
        split = split_layers_into_stages(layers, 4)
        assert split["w"].shape == (4, 2, 3, 3)
        with pytest.raises(ValueError):
            split_layers_into_stages({"w": jnp.zeros((7, 3))}, 4)

    def test_bad_microbatch_count(self):
        mesh = MeshSpec(stage=4, data=2).build()
        with pytest.raises(ValueError, match="divisible"):
            spmd_pipeline(lambda p, x: x, {"w": jnp.zeros((4, 1))}, jnp.zeros((6, 2)),
                          mesh=mesh, num_microbatches=4)


class TestMoE:
    CFG = MoEConfig(num_experts=4, top_k=2, capacity_factor=2.0)

    def test_capacity(self):
        assert capacity(64, self.CFG) == 64  # 2*64*2/4
        assert capacity(1, MoEConfig(num_experts=8, top_k=2)) == 2  # floor >= top_k

    def test_route_shapes_and_mass(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 8))
        router = jax.random.normal(jax.random.PRNGKey(1), (8, 4))
        dispatch, combine, aux = route(x, router, self.CFG)
        C = capacity(16, self.CFG)
        assert dispatch.shape == (2, 16, 4, C)
        # every token dispatched to exactly top_k slots (ample capacity)
        np.testing.assert_allclose(np.asarray(dispatch.sum(axis=(2, 3))), 2.0)
        # combine weights sum to 1 per token (renormalized top-k)
        np.testing.assert_allclose(np.asarray(combine.sum(axis=(2, 3))), 1.0, atol=1e-5)
        assert float(aux["moe_dropped_frac"]) == pytest.approx(0.0, abs=1e-6)

    def test_capacity_drops_tokens(self):
        cfg = MoEConfig(num_experts=4, top_k=1, capacity_factor=0.25)
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 32, 8))
        # router that sends everything to expert 0 → capacity clamps
        router = jnp.zeros((8, 4)).at[:, 0].set(10.0)
        dispatch, _, aux = route(x, router, cfg)
        assert float(aux["moe_dropped_frac"]) > 0.5

    def test_moe_ffn_sharded_matches_unsharded(self):
        E, D, F = 4, 16, 32
        key = jax.random.PRNGKey(4)
        ks = jax.random.split(key, 5)
        x = jax.random.normal(ks[0], (2, 8, D))
        router = jax.random.normal(ks[1], (D, E))
        wg = jax.random.normal(ks[2], (E, D, F)) / D**0.5
        wu = jax.random.normal(ks[3], (E, D, F)) / D**0.5
        wd = jax.random.normal(ks[4], (E, F, D)) / F**0.5
        y_ref, _ = moe_ffn(x, router, wg, wu, wd, self.CFG, mesh=None)

        mesh = MeshSpec(data=2, expert=4).build()
        y_sharded, _ = jax.jit(
            functools.partial(moe_ffn, cfg=self.CFG, mesh=mesh)
        )(x, router, wg, wu, wd)
        np.testing.assert_allclose(np.asarray(y_sharded), np.asarray(y_ref), atol=1e-5, rtol=1e-5)

    def test_gather_dispatch_matches_dense(self):
        # the indexed dispatch must be numerically identical to the GShard
        # one-hot einsum — outputs, aux losses, and gradients
        import dataclasses

        E, D, F = 4, 16, 32
        ks = jax.random.split(jax.random.PRNGKey(9), 5)
        x = jax.random.normal(ks[0], (2, 8, D))
        router = jax.random.normal(ks[1], (D, E))
        wg = jax.random.normal(ks[2], (E, D, F)) / D**0.5
        wu = jax.random.normal(ks[3], (E, D, F)) / D**0.5
        wd = jax.random.normal(ks[4], (E, F, D)) / F**0.5
        dense_cfg = dataclasses.replace(self.CFG, dispatch="dense")
        gather_cfg = dataclasses.replace(self.CFG, dispatch="gather")

        yd, auxd = moe_ffn(x, router, wg, wu, wd, dense_cfg)
        yg, auxg = moe_ffn(x, router, wg, wu, wd, gather_cfg)
        np.testing.assert_allclose(np.asarray(yg), np.asarray(yd), atol=1e-5, rtol=1e-5)
        for k in auxd:
            np.testing.assert_allclose(float(auxg[k]), float(auxd[k]), atol=1e-6)

        def loss(cfg):
            def f(x, router, wg, wu, wd):
                y, aux = moe_ffn(x, router, wg, wu, wd, cfg)
                return (y * y).sum() + aux["moe_balance_loss"]
            return jax.grad(f, argnums=(0, 1, 2, 3, 4))

        gd = loss(dense_cfg)(x, router, wg, wu, wd)
        gg = loss(gather_cfg)(x, router, wg, wu, wd)
        for name, a, b in zip("dx drouter dwg dwu dwd".split(), gg, gd):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=f"{name} mismatch between dispatch impls",
            )

    def test_ragged_dispatch_matches_dense(self):
        # the grouped-GEMM (ragged_dot) dispatch must match the GShard
        # einsum when capacity is ample (cf = E/K → zero drops): outputs,
        # aux losses, gradients — including with a pad mask
        import dataclasses

        E, D, F = 4, 16, 32
        ks = jax.random.split(jax.random.PRNGKey(11), 5)
        x = jax.random.normal(ks[0], (2, 8, D))
        router = jax.random.normal(ks[1], (D, E))
        wg = jax.random.normal(ks[2], (E, D, F)) / D**0.5
        wu = jax.random.normal(ks[3], (E, D, F)) / D**0.5
        wd = jax.random.normal(ks[4], (E, F, D)) / F**0.5
        dense_cfg = dataclasses.replace(self.CFG, dispatch="dense")
        ragged_cfg = dataclasses.replace(self.CFG, dispatch="ragged")
        mask = jnp.ones((2, 8), bool).at[0, 5:].set(False)  # packed-batch pads

        for tm in (None, mask):
            yd, auxd = moe_ffn(x, router, wg, wu, wd, dense_cfg, token_mask=tm)
            yr, auxr = moe_ffn(x, router, wg, wu, wd, ragged_cfg, token_mask=tm)
            if tm is not None:  # pad rows: dense gives 0 via dispatch mask, ragged via 0 gates
                yd = yd * tm[..., None]
                yr = yr * tm[..., None]
            np.testing.assert_allclose(np.asarray(yr), np.asarray(yd), atol=1e-5, rtol=1e-5)
            for k in auxd:
                np.testing.assert_allclose(float(auxr[k]), float(auxd[k]), atol=1e-6)

            def loss(cfg, tm=tm):
                def f(x, router, wg, wu, wd):
                    y, aux = moe_ffn(x, router, wg, wu, wd, cfg, token_mask=tm)
                    if tm is not None:
                        y = y * tm[..., None]
                    return (y * y).sum() + aux["moe_balance_loss"]
                return jax.grad(f, argnums=(0, 1, 2, 3, 4))

            gd = loss(dense_cfg)(x, router, wg, wu, wd)
            gr = loss(ragged_cfg)(x, router, wg, wu, wd)
            for name, a, b in zip("dx drouter dwg dwu dwd".split(), gr, gd):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                    err_msg=f"{name} mismatch ragged vs dense (mask={tm is not None})",
                )

    def test_ragged_ep_grads_match_unsharded(self):
        """Expert-SHARDED ragged dispatch (contiguous-span shard_map path):
        gradients through the psum'd partial combine must match the
        unsharded ragged path (balance loss off — per-shard statistic is a
        documented approximation; z-loss is linear and stays on)."""
        import dataclasses

        E, D, F = 4, 16, 32
        ks = jax.random.split(jax.random.PRNGKey(41), 5)
        x = jax.random.normal(ks[0], (4, 8, D))
        router = jax.random.normal(ks[1], (D, E))
        wg = jax.random.normal(ks[2], (E, D, F)) / D**0.5
        wu = jax.random.normal(ks[3], (E, D, F)) / D**0.5
        wd = jax.random.normal(ks[4], (E, F, D)) / F**0.5
        cfg = dataclasses.replace(self.CFG, dispatch="ragged", aux_loss_coef=0.0)
        mesh = MeshSpec(data=2, expert=4).build()

        def loss(mesh_arg):
            def f(x, router, wg, wu, wd):
                y, aux = moe_ffn(x, router, wg, wu, wd, cfg, mesh=mesh_arg)
                return (y * y).sum() + aux["moe_z_loss"]
            return jax.jit(jax.grad(f, argnums=(0, 1, 2, 3, 4)))

        g_ref = loss(None)(x, router, wg, wu, wd)
        g_ep = loss(mesh)(x, router, wg, wu, wd)
        for name, a, b in zip("dx drouter dwg dwu dwd".split(), g_ep, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=f"{name} mismatch EP-ragged vs unsharded",
            )

    def test_ragged_ep_kernel_branch_matches_unsharded(self):
        """EP span path through the FUSED KERNEL branch (aligned bf16
        geometry, interpret mode): the padded-group offsets / dynamic-slice
        / local tile_group arithmetic must reproduce the unsharded kernel
        path — fwd and grads."""
        import dataclasses

        from tony_tpu.ops import moe_gemm

        assert moe_gemm._INTERPRET
        E, D, F = 4, 128, 256
        ks = jax.random.split(jax.random.PRNGKey(47), 5)
        x = (jax.random.normal(ks[0], (2, 16, D)) * 0.5).astype(jnp.bfloat16)
        router = jax.random.normal(ks[1], (D, E))
        wg = (jax.random.normal(ks[2], (E, D, F)) / D**0.5).astype(jnp.bfloat16)
        wu = (jax.random.normal(ks[3], (E, D, F)) / D**0.5).astype(jnp.bfloat16)
        wd = (jax.random.normal(ks[4], (E, F, D)) / F**0.5).astype(jnp.bfloat16)
        cfg = dataclasses.replace(self.CFG, dispatch="ragged", aux_loss_coef=0.0)
        mesh = MeshSpec(data=2, expert=4).build()

        def loss(mesh_arg):
            def f(x, wg, wu, wd):
                y, aux = moe_ffn(x, router, wg, wu, wd, cfg, mesh=mesh_arg)
                return (y.astype(jnp.float32) ** 2).sum() + aux["moe_z_loss"]
            return jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2, 3)))

        l_ref, g_ref = loss(None)(x, wg, wu, wd)
        l_ep, g_ep = loss(mesh)(x, wg, wu, wd)
        np.testing.assert_allclose(float(l_ep), float(l_ref), rtol=2e-2)
        for name, a, b in zip("dx dwg dwu dwd".split(), g_ep, g_ref):
            a = np.asarray(a, jnp.float32)
            b = np.asarray(b, jnp.float32)
            scale = np.abs(b).max() + 1e-9
            assert np.abs(a - b).max() / scale < 5e-2, f"{name} mismatch (EP kernel)"

    def test_ragged_no_drops_under_imbalance(self):
        # capacity-free: the all-to-one router that drops >50% under
        # capacity schemes drops NOTHING here, and the output still equals
        # a dense-dispatch run with unbounded capacity
        import dataclasses

        cfg = dataclasses.replace(
            MoEConfig(num_experts=4, top_k=1, capacity_factor=0.25), dispatch="ragged"
        )
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 32, 8))
        router = jnp.zeros((8, 4)).at[:, 0].set(10.0)
        wg = jnp.ones((4, 8, 16)) * 0.1
        wu = jnp.ones((4, 8, 16)) * 0.1
        wd = jnp.ones((4, 16, 8)) * 0.1
        y, aux = moe_ffn(x, router, wg, wu, wd, cfg)
        assert float(aux["moe_dropped_frac"]) == 0.0
        big = dataclasses.replace(cfg, dispatch="dense", capacity_factor=4.0)
        y_ref, _ = moe_ffn(x, router, wg, wu, wd, big)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5, rtol=1e-5)

    @pytest.mark.slow
    def test_fused_kernel_matches_xla_ragged(self):
        """The Pallas fused grouped-GEMM SwiGLU (interpret mode here) must
        match the jax.lax.ragged_dot path bit-for-tolerance: outputs, aux,
        and grads — including with a pad mask and an MXU-aligned geometry
        that actually triggers the kernel (D,F % 128 == 0, bf16)."""
        import dataclasses

        from tony_tpu.ops import moe_gemm

        assert moe_gemm._INTERPRET, "conftest must set TONY_PALLAS_INTERPRET"
        E, D, F = 4, 128, 256
        ks = jax.random.split(jax.random.PRNGKey(21), 5)
        x = (jax.random.normal(ks[0], (2, 16, D)) * 0.5).astype(jnp.bfloat16)
        router = jax.random.normal(ks[1], (D, E))
        wg = (jax.random.normal(ks[2], (E, D, F)) / D**0.5).astype(jnp.bfloat16)
        wu = (jax.random.normal(ks[3], (E, D, F)) / D**0.5).astype(jnp.bfloat16)
        wd = (jax.random.normal(ks[4], (E, F, D)) / F**0.5).astype(jnp.bfloat16)
        kcfg = dataclasses.replace(self.CFG, dispatch="ragged")
        xcfg = dataclasses.replace(self.CFG, dispatch="ragged_xla")
        mask = jnp.ones((2, 16), bool).at[1, 10:].set(False)

        for tm in (None, mask):
            yk, auxk = moe_ffn(x, router, wg, wu, wd, kcfg, token_mask=tm)
            yx, auxx = moe_ffn(x, router, wg, wu, wd, xcfg, token_mask=tm)
            np.testing.assert_allclose(
                np.asarray(yk, jnp.float32), np.asarray(yx, jnp.float32),
                atol=3e-2, rtol=3e-2,
            )
            for k in auxx:
                np.testing.assert_allclose(float(auxk[k]), float(auxx[k]), atol=1e-6)

            def loss(cfg, tm=tm):
                def f(x, wg, wu, wd):
                    y, aux = moe_ffn(x, router, wg, wu, wd, cfg, token_mask=tm)
                    return (y.astype(jnp.float32) ** 2).sum() + aux["moe_balance_loss"]
                return jax.grad(f, argnums=(0, 1, 2, 3))

            gk = loss(kcfg)(x, wg, wu, wd)
            gx = loss(xcfg)(x, wg, wu, wd)
            for name, a, b in zip("dx dwg dwu dwd".split(), gk, gx):
                a = np.asarray(a, jnp.float32)
                b = np.asarray(b, jnp.float32)
                scale = np.abs(b).max() + 1e-9
                assert np.abs(a - b).max() / scale < 5e-2, (
                    f"{name} mismatch kernel vs xla (mask={tm is not None})"
                )

    def test_fused_kernel_empty_experts(self):
        """All tokens routed to ONE expert through the KERNEL path (aligned
        dims): empty experts still get zero-initialized dW blocks (each
        padded group keeps >= one tile) and outputs/grads match the dense
        reference with unbounded capacity."""
        import dataclasses

        E, D, F = 4, 128, 256
        ks = jax.random.split(jax.random.PRNGKey(31), 4)
        x = (jax.random.normal(ks[0], (2, 16, D)) * 0.5).astype(jnp.bfloat16)
        x = x.at[:, :, 0].set(5.0)                     # fixed positive feature
        router = jnp.zeros((D, E)).at[0, 1].set(10.0)  # everything → expert 1
        wg = (jax.random.normal(ks[1], (E, D, F)) / D**0.5).astype(jnp.bfloat16)
        wu = (jax.random.normal(ks[2], (E, D, F)) / D**0.5).astype(jnp.bfloat16)
        wd = (jax.random.normal(ks[3], (E, F, D)) / F**0.5).astype(jnp.bfloat16)
        base = MoEConfig(num_experts=E, top_k=1)  # top_k=1: experts 0/2/3 truly empty
        kcfg = dataclasses.replace(base, dispatch="ragged")
        big = dataclasses.replace(base, dispatch="dense", capacity_factor=4.0)

        def loss(cfg):
            def f(x, wg, wu, wd):
                y, aux = moe_ffn(x, router, wg, wu, wd, cfg)
                return (y.astype(jnp.float32) ** 2).sum()
            return jax.value_and_grad(f, argnums=(1, 2, 3))

        lk, gk = loss(kcfg)(x, wg, wu, wd)
        ld, gd = loss(big)(x, wg, wu, wd)
        np.testing.assert_allclose(float(lk), float(ld), rtol=3e-2)
        for name, a, b in zip("dwg dwu dwd".split(), gk, gd):
            a = np.asarray(a, jnp.float32)
            b = np.asarray(b, jnp.float32)
            # empty experts (0, 2, 3) must have exactly ZERO grads, not junk
            for e in (0, 2, 3):
                assert np.all(a[e] == 0.0), f"{name}[{e}] nonzero for empty expert"
            scale = np.abs(b).max() + 1e-9
            assert np.abs(a - b).max() / scale < 5e-2, f"{name} mismatch"

    def test_gather_dispatch_capacity_drops(self):
        import dataclasses

        cfg = dataclasses.replace(
            MoEConfig(num_experts=4, top_k=1, capacity_factor=0.25), dispatch="gather"
        )
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 32, 8))
        router = jnp.zeros((8, 4)).at[:, 0].set(10.0)
        wg = jnp.ones((4, 8, 16)) * 0.1
        wu = jnp.ones((4, 8, 16)) * 0.1
        wd = jnp.ones((4, 16, 8)) * 0.1
        _, aux = moe_ffn(x, router, wg, wu, wd, cfg)
        assert float(aux["moe_dropped_frac"]) > 0.5


@pytest.mark.slow  # ~6 min of multi-device XLA compiles on the CPU mesh:
# each 1F1B case builds a full shard_map pipeline fwd+bwd; tier-1 budgets
# its 870 s for breadth, so this class runs in the unfiltered suite only
class TestPipeline1F1B:
    """1F1B schedule: hand-scheduled interleaved backward must reproduce the
    flat (non-pipelined) model's loss and gradients exactly — including with
    a data axis sharding the microbatch batch dim, and with the bf16 wire
    (no autodiff through collectives, so narrow wire works on any backend)."""

    def _setup(self, S=4, M=4, B=8, T=32):
        import dataclasses as dc

        from tony_tpu.models import llama

        cfg = dc.replace(
            llama.LLAMA_TINY, n_layers=S, max_seq=T, remat=False,
            dtype="float32", ce_chunk=16,
        )
        params = llama.init(jax.random.PRNGKey(0), cfg)
        batch = llama.synthetic_batch(jax.random.PRNGKey(1), B, T, cfg)
        return llama, cfg, params, batch

    def _check(self, mesh_spec, S=4, M=4, wire=jnp.bfloat16, devices=None):
        llama, cfg, params, batch = self._setup(S=S)
        mesh = mesh_spec.build(devices)
        loss_pp, metrics, grads = jax.jit(
            functools.partial(
                llama.pp_value_and_grad, cfg=cfg, mesh=mesh,
                num_microbatches=M, wire_dtype=wire,
            )
        )(params, batch)
        (loss_flat, m_flat), grads_flat = jax.value_and_grad(
            lambda p: llama.loss_fn(p, batch, cfg), has_aux=True
        )(params)
        np.testing.assert_allclose(float(loss_pp), float(loss_flat), rtol=3e-3)
        assert int(metrics["tokens"]) == int(m_flat["tokens"])
        flat_g = tree_leaves_with_path(grads_flat)
        pp_g = dict(tree_leaves_with_path(grads))
        for path, g in flat_g:
            got = pp_g[path]
            scale = float(jnp.max(jnp.abs(g))) + 1e-9
            err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - g.astype(jnp.float32)))) / scale
            assert err < 2e-2, f"{path} rel err {err}"

    def test_grads_match_flat_scan(self):
        from tony_tpu.parallel import MeshSpec

        self._check(MeshSpec(stage=4), S=4, M=4, devices=jax.devices()[:4])

    def test_composes_with_data_axis(self):
        from tony_tpu.parallel import MeshSpec

        self._check(MeshSpec(stage=4, data=2), S=4, M=4)

    def test_more_microbatches_than_stages(self):
        from tony_tpu.parallel import MeshSpec

        self._check(MeshSpec(stage=2), S=2, M=8, devices=jax.devices()[:2])

    def test_f32_wire_also_works(self):
        from tony_tpu.parallel import MeshSpec

        self._check(MeshSpec(stage=4), S=4, M=4, wire=jnp.float32,
                    devices=jax.devices()[:4])

    def test_interleaved_grads_match_flat_scan(self):
        """Interleaved 1F1B (virtual pipeline chunks, V=2): each device
        owns two model chunks, microbatches visit it twice, the wrap hop
        advances the chunk — loss and grads must equal the flat scan."""
        llama, cfg, params, batch = self._setup(S=4)  # 4 layers → S2 × V2
        from tony_tpu.parallel import MeshSpec

        mesh = MeshSpec(stage=2).build(jax.devices()[:2])
        loss_pp, metrics, grads = jax.jit(
            functools.partial(
                llama.pp_value_and_grad, cfg=cfg, mesh=mesh,
                num_microbatches=4, num_chunks=2, wire_dtype=jnp.float32,
            )
        )(params, batch)
        (loss_flat, m_flat), grads_flat = jax.value_and_grad(
            lambda p: llama.loss_fn(p, batch, cfg), has_aux=True
        )(params)
        np.testing.assert_allclose(float(loss_pp), float(loss_flat), rtol=1e-4)
        assert int(metrics["tokens"]) == int(m_flat["tokens"])
        pp_g = dict(tree_leaves_with_path(grads))
        for path, g in tree_leaves_with_path(grads_flat):
            scale = float(jnp.max(jnp.abs(g))) + 1e-9
            err = float(jnp.max(jnp.abs(pp_g[path].astype(jnp.float32) - g.astype(jnp.float32)))) / scale
            assert err < 1e-3, f"{path} rel err {err}"

    def test_interleaved_composes_with_data_axis(self):
        """V=2 chunks × stage=2 × data=4, bf16 wire: the production shape."""
        import dataclasses as dc

        from tony_tpu.models import llama as llama_mod
        from tony_tpu.parallel import MeshSpec

        cfg = dc.replace(
            llama_mod.LLAMA_TINY, n_layers=8, max_seq=32, remat=False,
            dtype="float32", ce_chunk=16,
        )
        params = llama_mod.init(jax.random.PRNGKey(0), cfg)
        batch = llama_mod.synthetic_batch(jax.random.PRNGKey(1), 16, 32, cfg)
        mesh = MeshSpec(stage=2, data=4).build()
        loss_pp, metrics, grads = jax.jit(
            functools.partial(
                llama_mod.pp_value_and_grad, cfg=cfg, mesh=mesh,
                num_microbatches=4, num_chunks=2,
            )
        )(params, batch)
        (loss_flat, m_flat), grads_flat = jax.value_and_grad(
            lambda p: llama_mod.loss_fn(p, batch, cfg), has_aux=True
        )(params)
        np.testing.assert_allclose(float(loss_pp), float(loss_flat), rtol=3e-3)
        pp_g = dict(tree_leaves_with_path(grads))
        for path, g in tree_leaves_with_path(grads_flat):
            scale = float(jnp.max(jnp.abs(g))) + 1e-9
            err = float(jnp.max(jnp.abs(pp_g[path].astype(jnp.float32) - g.astype(jnp.float32)))) / scale
            assert err < 2e-2, f"{path} rel err {err}"

    def test_interleaved_rejects_bad_microbatches(self):
        llama, cfg, params, batch = self._setup(S=4)
        from tony_tpu.parallel import MeshSpec

        mesh = MeshSpec(stage=2).build(jax.devices()[:2])
        with pytest.raises(ValueError, match="microbatches"):
            jax.jit(
                functools.partial(
                    llama.pp_value_and_grad, cfg=cfg, mesh=mesh,
                    num_microbatches=3, num_chunks=2,  # 3 % S(2) != 0
                )
            )(params, batch)

    def test_packed_batch_matches_flat(self):
        """Packed batches (segment_ids) through the 1F1B schedule: loss and
        grads must match the flat scan on the same packed batch."""
        from tony_tpu.parallel import MeshSpec

        llama, cfg, params, batch = self._setup(S=2)
        B, Tp1 = batch["tokens"].shape
        # two segments per row + trailing pad (segment 0)
        seg = jnp.ones((B, Tp1), jnp.int32)
        seg = seg.at[:, Tp1 // 2:].set(2).at[:, -4:].set(0)
        batch = {**batch, "segment_ids": seg}
        mesh = MeshSpec(stage=2).build(jax.devices()[:2])
        loss_pp, metrics, grads = jax.jit(
            functools.partial(
                llama.pp_value_and_grad, cfg=cfg, mesh=mesh, num_microbatches=4,
            )
        )(params, batch)
        (loss_flat, m_flat), grads_flat = jax.value_and_grad(
            lambda p: llama.loss_fn(p, batch, cfg), has_aux=True
        )(params)
        np.testing.assert_allclose(float(loss_pp), float(loss_flat), rtol=3e-3)
        assert int(metrics["tokens"]) == int(m_flat["tokens"])
        flat_g = tree_leaves_with_path(grads_flat)
        pp_g = dict(tree_leaves_with_path(grads))
        for path, g in flat_g:
            scale = float(jnp.max(jnp.abs(g))) + 1e-9
            err = float(jnp.max(jnp.abs(pp_g[path].astype(jnp.float32) - g.astype(jnp.float32)))) / scale
            assert err < 2e-2, f"{path} rel err {err}"

    def test_mixtral_pp_matches_flat(self):
        """MoE 1F1B: aux losses thread through the hand-scheduled backward.
        Balance loss is a per-microbatch mean (nonlinear in tokens), so grad
        parity vs the flat scan is exact only with aux_loss_coef=0; a second
        check asserts the aux path actually reaches router grads."""
        import dataclasses as dc

        from tony_tpu.models import mixtral
        from tony_tpu.parallel import MeshSpec

        # balance loss OFF for exact parity: it is a product of token-means,
        # so the per-microbatch statistic differs from the full-batch one by
        # construction (documented approximation). z-loss is a plain token
        # mean — linear — and stays on, proving the aux cotangent path.
        cfg = dc.replace(
            mixtral.MIXTRAL_TINY, n_layers=4, max_seq=32, remat=False,
            dtype="float32", ce_chunk=16, aux_loss_coef=0.0,
        )
        params = mixtral.init(jax.random.PRNGKey(0), cfg)
        batch = mixtral.synthetic_batch(jax.random.PRNGKey(1), 8, 32, cfg)
        mesh = MeshSpec(stage=2).build(jax.devices()[:2])

        # f32 wire: a bf16 wire quantizes each stage's input, which can FLIP
        # near-tie top-k routing decisions vs the flat model — harmless
        # routing jitter in training, but fatal to exact parity checking
        loss_pp, metrics, grads = jax.jit(
            functools.partial(
                mixtral.pp_value_and_grad, cfg=cfg, mesh=mesh, num_microbatches=4,
                wire_dtype=jnp.float32,
            )
        )(params, batch)
        (loss_flat, m_flat), grads_flat = jax.value_and_grad(
            lambda p: mixtral.loss_fn(p, batch, cfg), has_aux=True
        )(params)
        # losses close (balance term differs per-microbatch vs full batch —
        # tolerance covers the statistic shift at tiny scale)
        np.testing.assert_allclose(float(loss_pp), float(loss_flat), rtol=1e-4)
        assert int(metrics["tokens"]) == int(m_flat["tokens"])
        flat_g = tree_leaves_with_path(grads_flat)
        pp_g = dict(tree_leaves_with_path(grads))
        for path, g in flat_g:
            scale = float(jnp.max(jnp.abs(g))) + 1e-9
            err = float(jnp.max(jnp.abs(pp_g[path].astype(jnp.float32) - g.astype(jnp.float32)))) / scale
            assert err < 1e-3, f"{path} rel err {err}"
        # the aux cotangent must reach the router at all
        assert float(jnp.max(jnp.abs(grads["layers"]["router"]))) > 0.0

    def test_mixtral_pp_packed_runs(self):
        """Packed Mixtral 1F1B: segment confinement + pad-aware routing +
        boundary masking compose with the pipeline (smoke + token count)."""
        import dataclasses as dc

        from tony_tpu.models import mixtral
        from tony_tpu.parallel import MeshSpec

        cfg = dc.replace(
            mixtral.MIXTRAL_TINY, n_layers=2, max_seq=32, remat=False,
            dtype="float32", ce_chunk=16,
        )
        params = mixtral.init(jax.random.PRNGKey(0), cfg)
        batch = mixtral.synthetic_batch(jax.random.PRNGKey(1), 8, 32, cfg)
        B, Tp1 = batch["tokens"].shape
        seg = jnp.ones((B, Tp1), jnp.int32)
        seg = seg.at[:, Tp1 // 2:].set(2).at[:, -4:].set(0)
        batch = {**batch, "segment_ids": seg}
        mesh = MeshSpec(stage=2).build(jax.devices()[:2])
        loss, metrics, grads = jax.jit(
            functools.partial(
                mixtral.pp_value_and_grad, cfg=cfg, mesh=mesh, num_microbatches=2,
            )
        )(params, batch)
        assert jnp.isfinite(loss)
        (loss_flat, m_flat), _ = jax.value_and_grad(
            lambda p: mixtral.loss_fn(p, batch, cfg), has_aux=True
        )(params)
        assert int(metrics["tokens"]) == int(m_flat["tokens"])
        np.testing.assert_allclose(float(loss), float(loss_flat), rtol=5e-2)

    def test_train_step_decreases_loss(self):
        import dataclasses as dc
        import functools as ft

        from tony_tpu.models import llama
        from tony_tpu.parallel import MeshSpec
        from tony_tpu.train import OptimizerConfig, make_pp_train_step, sharded_init

        llama_mod, cfg, params, batch = self._setup(S=2)
        mesh = MeshSpec(stage=2, data=2).build(jax.devices()[:4])
        opt = OptimizerConfig(learning_rate=1e-2, warmup_steps=2, total_steps=50).build()
        state = sharded_init(
            lambda: llama_mod.init(jax.random.PRNGKey(0), cfg),
            llama_mod.sharding_rules(cfg), mesh, opt,
        )
        step = make_pp_train_step(
            ft.partial(llama_mod.pp_value_and_grad, cfg=cfg, mesh=mesh, num_microbatches=4),
            opt,
        )
        losses = []
        for _ in range(8):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses
