"""Checker-level tests for the static-analysis suite (tony_tpu/analysis/).

Each checker gets fixture-backed true-positive assertions (exact finding
counts + line numbers) and false-positive/suppression coverage, plus the
``tony lint`` CLI exit-code and JSON contract external CI relies on.
"""

import json
import os

import pytest

from tony_tpu.analysis.analyzer import Analyzer, all_checkers
from tony_tpu.cli import lint as lint_cli

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures", "lint")


def run_lint(*files, checks=None):
    checkers = all_checkers()
    if checks:
        checkers = [c for c in checkers if c.name in checks]
    analyzer = Analyzer(checkers, root=FIXTURES)
    return analyzer.run([os.path.join(FIXTURES, f) for f in files])


def lines_of(findings, checker):
    return [f.line for f in findings if f.checker == checker]


# ---------------------------------------------------------------- config-keys
def test_config_keys_flags_undeclared_literals():
    findings = run_lint("keys.py", "config_keys_bad.py", checks={"config-keys"})
    assert lines_of(findings, "config-keys") == [6, 8]
    typo = findings[0]
    assert "tony.app.nmae" in typo.message
    assert "did you mean 'tony.app.name'" in typo.message  # typo hint


def test_config_keys_prefix_families_and_suppression():
    findings = run_lint("keys.py", "config_keys_bad.py", checks={"config-keys"})
    # line 7 (declared prefix family) and line 9 (suppressed) are absent
    assert 7 not in lines_of(findings, "config-keys")
    assert 9 not in lines_of(findings, "config-keys")


def test_config_keys_file_level_suppression():
    findings = run_lint(
        "keys.py", "config_keys_suppress_file.py", checks={"config-keys"}
    )
    assert findings == []


def test_config_keys_skips_without_registry():
    # no keys.py in scope → nothing to validate against, no noise
    findings = run_lint("config_keys_bad.py", checks={"config-keys"})
    assert findings == []


# ----------------------------------------------------------------- jit-purity
def test_jit_purity_true_positives():
    findings = run_lint("jit_purity_bad.py", checks={"jit-purity"})
    assert lines_of(findings, "jit-purity") == [13, 19, 25, 31, 39, 45]
    messages = " | ".join(f.message for f in findings)
    for needle in ("print()", "time.time()", ".append()", "global", "self.*"):
        assert needle in messages


def test_jit_purity_clean_and_suppressed():
    findings = run_lint("jit_purity_good.py", checks={"jit-purity"})
    assert findings == []


# ------------------------------------------------------------ donation-safety
def test_donation_true_positives():
    findings = run_lint("donation_bad.py", checks={"donation-safety"})
    assert lines_of(findings, "donation-safety") == [23, 28, 33, 39]
    # keyword-passed donated arg and self-attribute donors are both tracked
    assert "'state'" in findings[1].message
    assert "'self.cache'" in findings[3].message


def test_donation_rebind_idioms_are_clean():
    findings = run_lint("donation_good.py", checks={"donation-safety"})
    assert findings == []


# ------------------------------------------------------------ lock-discipline
def test_lock_discipline_true_positives():
    findings = run_lint("locks_bad.py", checks={"lock-discipline"})
    assert lines_of(findings, "lock-discipline") == [15, 20, 23, 32, 35]
    assert "hold one of: self._lock" in findings[0].message
    assert "declare a threading.Lock" in findings[3].message


def test_lock_discipline_clean_patterns():
    # locked writes, *_locked helper trust, single-thread helper chains,
    # RPC method-list resolution, per-line suppression
    findings = run_lint("locks_good.py", checks={"lock-discipline"})
    assert findings == []


# ---------------------------------------------------------------- mesh-axes
def test_mesh_axes_true_positives():
    findings = run_lint("mesh_axes_bad.py", checks={"mesh-axes"})
    # axis_index takes its axis at positional slot 0, the rest at slot 1
    assert lines_of(findings, "mesh-axes") == [14, 18, 22, 25, 46]
    assert "'rows'" in findings[0].message
    assert "declared: col, row" in findings[0].message
    assert "'rowz'" in findings[-1].message


def test_mesh_axes_declared_and_threaded_are_clean():
    findings = run_lint("mesh_axes_bad.py", checks={"mesh-axes"})
    flagged = {f.line for f in findings}
    # good_declared / good_threaded / good_tuple / suppressed bodies
    assert not flagged & {30, 34, 38, 42}


def test_mesh_axes_real_registry_covers_canonical_axes():
    from tony_tpu.analysis.mesh_axes import MeshAxisChecker
    from tony_tpu.analysis.analyzer import load_module

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    checker = MeshAxisChecker()
    checker.collect(load_module(os.path.join(repo, "tony_tpu", "parallel", "mesh.py")))
    assert checker.declared == {"data", "fsdp", "model", "context", "expert", "stage"}


# --------------------------------------------------------- print-discipline
def test_print_discipline_true_positives():
    findings = run_lint("print_bad.py", checks={"print-discipline"})
    # the inline-suppressed stdout contract (line 12) and the obs_logging
    # route are absent; the bare calls are flagged
    assert lines_of(findings, "print-discipline") == [5, 22]
    assert "tony_tpu.obs.logging" in findings[0].message


def test_print_discipline_exempts_cli_paths():
    findings = run_lint(
        os.path.join("cli", "print_in_cli.py"), checks={"print-discipline"}
    )
    assert findings == []


def test_print_discipline_library_is_clean():
    """The ratchet this checker enforces: every bare print left in tony_tpu/
    (outside cli/) is either converted to obs_logging or carries an inline
    justification — also covered by tests/test_lint_clean.py over the whole
    package."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    analyzer = Analyzer(
        [c for c in all_checkers() if c.name == "print-discipline"], root=repo
    )
    findings = analyzer.run([os.path.join(repo, "tony_tpu")])
    assert findings == [], [f"{f.path}:{f.line}" for f in findings]


# --------------------------------------------------------- metrics-discipline
def test_metrics_discipline_flags_prefix_and_docs_drift():
    findings = run_lint("metrics_bad.py", checks={"metrics-discipline"})
    # documented name (5), suppressed (14), and dynamic-name (19) are absent
    assert lines_of(findings, "metrics-discipline") == [8, 11]
    assert "tony_ prefix" in findings[0].message
    assert "docs/observability.md" in findings[1].message


def test_metrics_discipline_library_is_clean():
    """The ratchet: every instrument registered in tony_tpu/ is prefixed
    AND has a row in docs/observability.md's table — new metrics cannot
    land undocumented (the drift that made the trace summary stale)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    analyzer = Analyzer(
        [c for c in all_checkers() if c.name == "metrics-discipline"], root=repo
    )
    findings = analyzer.run([os.path.join(repo, "tony_tpu")])
    assert findings == [], [f"{f.path}:{f.line}: {f.message}" for f in findings]


# ---------------------------------------------------------- events-discipline
def test_events_discipline_flags_undocumented_members():
    findings = run_lint("events_bad.py", checks={"events-discipline"})
    # documented (7), suppressed (10), non-string member (11), and the
    # differently-named enum (16) are all absent
    assert lines_of(findings, "events-discipline") == [8, 9]
    assert "TOTALLY_UNDOCUMENTED_EVENT" in findings[0].message
    assert "docs/observability.md" in findings[0].message


def test_events_discipline_library_is_clean():
    """The ratchet: every EventType member declared in tony_tpu/ has a row
    in docs/observability.md's event catalog — a new .jhist event type
    cannot land undocumented (the drift PRs 9-14 accumulated and this PR
    backfilled)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    analyzer = Analyzer(
        [c for c in all_checkers() if c.name == "events-discipline"], root=repo
    )
    findings = analyzer.run([os.path.join(repo, "tony_tpu")])
    assert findings == [], [f"{f.path}:{f.line}: {f.message}" for f in findings]


# ------------------------------------------------------------------ host-sync
def test_host_sync_true_positives():
    findings = run_lint("host_sync_bad.py", checks={"host-sync"})
    # line 22 twice: float(jax.device_get(...)) is TWO syncs — a flagged
    # call's arguments are still walked, so fixing only the outer one
    # cannot re-lint clean. Line 29: an `if` BODY is conditional but its
    # TEST evaluates every iteration — `if float(loss) > 8.0` still syncs.
    assert lines_of(findings, "host-sync") == [7, 14, 15, 22, 22, 29]
    assert "unconditional device sync in a step loop" in findings[0].message


def test_host_sync_clean_patterns():
    """Throttled (window `if`), suppressed, literal-arg, non-step loops, and
    sync-after-the-loop are all out of scope — the checker targets exactly
    the per-step-sync bug class, nothing broader."""
    findings = run_lint("host_sync_good.py", checks={"host-sync"})
    assert findings == []


# -------------------------------------------------------------- CLI contract
def test_cli_exit_0_clean_json(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    rc = lint_cli.main([str(clean), "--format", "json", "--no-baseline"])
    out = json.loads(capsys.readouterr().out)
    assert rc == lint_cli.EXIT_CLEAN == 0
    assert out["findings"] == []
    assert out["summary"]["total"] == 0


def test_cli_exit_1_findings_json(capsys):
    rc = lint_cli.main([
        os.path.join(FIXTURES, "mesh_axes_bad.py"),
        "--format", "json", "--no-baseline", "--checks", "mesh-axes",
    ])
    out = json.loads(capsys.readouterr().out)
    assert rc == lint_cli.EXIT_FINDINGS == 1
    assert out["summary"]["total"] == 5
    assert out["summary"]["by_checker"] == {"mesh-axes": 5}
    f = out["findings"][0]
    assert set(f) >= {"checker", "path", "line", "col", "message", "severity", "fingerprint"}


def test_cli_exit_2_internal_error(capsys):
    rc = lint_cli.main(["/nonexistent/path/nowhere", "--format", "json"])
    assert rc == lint_cli.EXIT_INTERNAL_ERROR == 2
    assert json.loads(capsys.readouterr().out or "{}") == {}  # nothing on stdout


def test_cli_unknown_checker_is_internal_error(capsys):
    rc = lint_cli.main([FIXTURES, "--checks", "no-such-checker"])
    assert rc == 2


def test_cli_baseline_workflow(tmp_path, capsys):
    """--update-baseline grandfathers findings; new findings still fail."""
    baseline = tmp_path / "baseline.json"
    target = os.path.join(FIXTURES, "mesh_axes_bad.py")
    args = [target, "--baseline", str(baseline)]
    assert lint_cli.main(args + ["--update-baseline"]) == 0
    capsys.readouterr()
    # everything grandfathered → clean (also through a checker-subset run)
    rc = lint_cli.main(args + ["--checks", "mesh-axes", "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["summary"] == {"total": 0, "grandfathered": 5, "by_checker": {}}
    # --no-baseline resurfaces them
    assert lint_cli.main(args + ["--no-baseline"]) == 1
    capsys.readouterr()
    # a checker-subset run must not rewrite the baseline (it would drop
    # the other checkers' grandfathered entries)
    assert lint_cli.main(args + ["--checks", "mesh-axes", "--update-baseline"]) == 2
    capsys.readouterr()


def test_cli_registered_in_tony_main(capsys):
    from tony_tpu.cli.main import main as tony_main

    rc = tony_main(["lint", "--list-checks"])
    out = capsys.readouterr().out
    assert rc == 0
    for name in (
        "config-keys", "jit-purity", "donation-safety",
        "lock-discipline", "mesh-axes", "print-discipline",
    ):
        assert name in out


def test_parse_error_is_a_finding(tmp_path):
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    analyzer = Analyzer(all_checkers(), root=str(tmp_path))
    findings = analyzer.run([str(broken)])
    assert len(findings) == 1 and findings[0].checker == "parse"


def test_undecodable_file_is_a_finding_not_an_abort(tmp_path):
    """One broken file must not swallow the other files' findings."""
    (tmp_path / "bad_bytes.py").write_bytes(b"x = '\xff\xfe'\n")
    (tmp_path / "keys.py").write_text('K = "tony.app.name"\n')
    (tmp_path / "mod.py").write_text('V = "tony.nope.key"\n')
    findings = Analyzer(all_checkers(), root=str(tmp_path)).run([str(tmp_path)])
    assert {f.checker for f in findings} == {"parse", "config-keys"}


def test_coding_cookie_files_are_readable(tmp_path):
    src = "# -*- coding: latin-1 -*-\n# caf\xe9\nV = 1\n"
    (tmp_path / "latin.py").write_bytes(src.encode("latin-1"))
    findings = Analyzer(all_checkers(), root=str(tmp_path)).run([str(tmp_path)])
    assert findings == []


def test_donation_local_plain_def_shadows_foreign_donor(tmp_path):
    """A module's own non-donating `update` must not be treated as the
    donor another module registered under the same name."""
    (tmp_path / "a.py").write_text(
        "import functools, jax\n"
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def update(s, x):\n    return s + x\n"
    )
    (tmp_path / "b.py").write_text(
        "def update(s, x):\n    return s\n"
        "def caller(s, x):\n"
        "    out = update(s, x)\n"
        "    return s + out\n"
    )
    findings = Analyzer(all_checkers(), root=str(tmp_path)).run([str(tmp_path)])
    assert [f for f in findings if f.checker == "donation-safety"] == []


def test_fingerprints_are_line_stable(tmp_path):
    """Shifting a finding down the file must not change its fingerprint
    (the property the baseline workflow depends on)."""
    src = (
        "import functools, jax\n"
        'K = "tony.nope.key"\n'  # config-keys finding
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def step(s, x):\n"
        "    return s + x\n"
        "def reuse(s, x):\n"
        "    out = step(s, x)\n"
        "    return s + out\n"  # donation finding
    )
    keys = 'K = "tony.app.name"\n'
    (tmp_path / "keys.py").write_text(keys)
    a = tmp_path / "mod.py"
    a.write_text(src)
    f1 = Analyzer(all_checkers(), root=str(tmp_path)).run([str(tmp_path)])
    a.write_text("# a new leading comment\n\n" + src)
    f2 = Analyzer(all_checkers(), root=str(tmp_path)).run([str(tmp_path)])
    assert {x.checker for x in f1} == {"config-keys", "donation-safety"}
    assert [x.fingerprint() for x in f1] == [x.fingerprint() for x in f2]
    assert [x.line for x in f1] != [x.line for x in f2]


# -------------------------------------------------------------- lock-ordering
def test_lock_ordering_cycles_and_reacquire():
    findings = run_lint("lockorder_bad.py", checks={"lock-ordering"})
    assert lines_of(findings, "lock-ordering") == [11, 23, 37]
    by_line = {f.line: f.message for f in findings}
    # module-level A -> B / B -> A inversion, with both witnesses named
    assert "potential deadlock: lock acquisition cycle" in by_line[11]
    assert "lockorder_bad._A -> lockorder_bad._B" in by_line[11]
    assert "lockorder_bad._B -> lockorder_bad._A" in by_line[11]
    # single-thread re-acquire of a non-reentrant Lock is a self-cycle
    assert "non-reentrant lock lockorder_bad._A is re-acquired" in by_line[23]
    # the class-attr cycle goes through a resolved `self._grab_n()` call
    assert "potential deadlock: lock acquisition cycle" in by_line[37]
    assert "lockorder_bad.Pair._m" in by_line[37]


def test_lock_graph_of_clean_tree_is_acyclic(tmp_path):
    (tmp_path / "mod.py").write_text(
        "import threading\n"
        "_a = threading.Lock()\n"
        "_b = threading.Lock()\n"
        "def ordered():\n"
        "    with _a:\n"
        "        with _b:\n"
        "            pass\n"
    )
    from tony_tpu.analysis.lock_order import build_lock_graph

    g = build_lock_graph([str(tmp_path)])
    assert g.cycles == []
    assert ("mod._a", "mod._b") in g.edges
    assert g.has_path("mod._a", "mod._b")
    assert not g.has_path("mod._b", "mod._a")
    assert "mod._a -> mod._b" in g.render()


# -------------------------------------------------------- blocking-under-lock
def test_blocking_under_lock_true_positives():
    findings = run_lint("blocking_bad.py", checks={"blocking-under-lock"})
    assert lines_of(findings, "blocking-under-lock") == [13, 17, 32]
    by_line = {f.line: f.message for f in findings}
    assert "time.sleep" in by_line[13]
    # the fsync lives in a private helper whose every caller holds the
    # lock — the finding lands AT the op, via inferred entry-holds
    assert "fsync" in by_line[17]
    assert "blocking_bad._lock" in by_line[17]
    assert "sqlite" in by_line[32]


def test_blocking_under_lock_clean_patterns():
    """Stage-under-lock/write-outside, sleep after release, and the
    suppressed leaf-serializer shape are all clean."""
    findings = run_lint("blocking_good.py", checks={"blocking-under-lock"})
    assert findings == []


# ------------------------------------------------------------- guarded-fields
def test_guarded_fields_true_positives():
    findings = run_lint("guarded_bad.py", checks={"guarded-fields"})
    assert lines_of(findings, "guarded-fields") == [22, 25]
    by_line = {f.line: f.message for f in findings}
    assert "_state" in by_line[22]
    assert "_lock" in by_line[22]


def test_guarded_fields_clean_patterns():
    """Single-writer snapshot reads and fully-guarded classes are clean."""
    findings = run_lint("guarded_good.py", checks={"guarded-fields"})
    assert findings == []


# --------------------------------------------- lock-discipline (round 16 deep)
def test_condition_wait_notify_requires_owning_lock():
    findings = run_lint("locks_condition.py", checks={"lock-discipline"})
    assert lines_of(findings, "lock-discipline") == [26, 30]
    assert "Condition wait/notify requires the owning lock" in findings[0].message


def test_multi_with_and_make_lock_recognized():
    """`with self._a, self._b:` holds both; locktrace.make_lock and RLock
    are lock factories — MultiAcquire must produce zero findings (the only
    findings on the file are CondQueue's two)."""
    findings = run_lint("locks_condition.py")
    assert [(f.checker, f.line) for f in findings] == [
        ("lock-discipline", 26), ("lock-discipline", 30),
    ]


# --------------------------------------------------- CLI round-16 extensions
def test_cli_lock_graph_dump(capsys):
    rc = lint_cli.main([
        os.path.join(FIXTURES, "lockorder_bad.py"), "--lock-graph",
    ])
    out = capsys.readouterr().out
    assert rc == lint_cli.EXIT_FINDINGS  # fixture graph has cycles
    assert "lock-order graph:" in out
    assert "CYCLE:" in out


def test_cli_lock_graph_clean_exit_0(tmp_path, capsys):
    f = tmp_path / "clean.py"
    f.write_text("import threading\n_l = threading.Lock()\n")
    rc = lint_cli.main([str(f), "--lock-graph"])
    out = capsys.readouterr().out
    assert rc == lint_cli.EXIT_CLEAN
    assert "0 cycles" in out


def test_cli_json_timings_block(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    rc = lint_cli.main([str(clean), "--format", "json", "--no-baseline"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    t = out["timings"]
    assert set(t) == {"per_checker_s", "budget_s", "over_budget"}
    assert "lock-ordering" in t["per_checker_s"]
    assert t["over_budget"] == []  # advisory: nothing is that slow here


def test_changed_files_outside_git_degrades_to_full_run(tmp_path):
    assert lint_cli.changed_files(str(tmp_path)) is None


def test_analyzer_check_paths_keeps_cross_module_context(tmp_path):
    """--changed soundness: collect over the whole tree, check only the
    changed files — a finding in an unchanged file is filtered, but the
    registry (keys.py) is still seen."""
    (tmp_path / "keys.py").write_text('K = "tony.app.name"\n')
    (tmp_path / "mod.py").write_text('V = "tony.nope.key"\n')
    (tmp_path / "other.py").write_text('W = "tony.also.nope"\n')
    analyzer = Analyzer(all_checkers(), root=str(tmp_path))
    findings = analyzer.run(
        [str(tmp_path)], check_paths=[str(tmp_path / "mod.py")]
    )
    assert [(f.checker, os.path.basename(f.path)) for f in findings] == [
        ("config-keys", "mod.py")
    ]
